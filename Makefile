# Build/push/deploy targets — the reference operator's `make docker-build
# docker-push deploy` flow (README.md:298-302), retargeted at this
# platform's three images and Helm-role release.
#
#   make docker-build               # build all images
#   make docker-push                # push to $(REGISTRY)
#   make deploy                     # install/upgrade the platform chart
#   make undeploy
#
# Overridables: REGISTRY, TAG, NAMESPACE.

REGISTRY ?= registry.example.com/k8sgpu
TAG      ?= 0.1.0
NAMESPACE ?= gohai-system

IMAGES = operator trainer devenv

.PHONY: verify docker-build docker-push deploy undeploy test check trace-demo chaos-demo alerts-demo prefix-demo fleet-demo router-demo analysis-demo profile-demo kernel-demo flash-v2-parity goodput-demo canary-demo frontend-demo waterfall-demo migrate-demo gateway-demo replay-demo disagg-demo

# The default verify path (bare `make`): graftcheck invariants + the
# attribution-plane smoke + the flash-v2 parity suite (ISSUE 12 — every
# knob's fwd/bwd parity, the fallback mint chain, and the zero-recompile
# train-step guard, all CPU-safe through the Pallas interpreter).  The
# full suite stays `make test` (it takes minutes); image builds stay
# `make docker-build`.
verify: check profile-demo goodput-demo canary-demo frontend-demo waterfall-demo migrate-demo gateway-demo replay-demo disagg-demo flash-v2-parity

flash-v2-parity:
	python -m pytest tests/test_flash_v2.py -q -p no:cacheprovider

docker-build:
	@for img in $(IMAGES); do \
	  docker build -t $(REGISTRY)/$$img:$(TAG) -f images/$$img/Dockerfile .; \
	done

docker-push:
	@for img in $(IMAGES); do \
	  docker push $(REGISTRY)/$$img:$(TAG); \
	done

# The in-repo release path: the CLI's helm-role verbs render
# platform/release.py:gohai_platform_chart onto the cluster state the
# controllers reconcile (docs/platform/deploy.md for the full flow).
deploy:
	python -m k8s_gpu_tpu.cli ci install gohai \
	  --image $(REGISTRY)/operator:$(TAG) --namespace $(NAMESPACE)

undeploy:
	python -m k8s_gpu_tpu.cli ci uninstall gohai --namespace $(NAMESPACE)

# Full suite, reliably: bounded per-chunk pytest subprocesses with merged
# reporting (this environment's jaxlib segfaults after several hundred
# accumulated compiles in one process — docs/testing.md).  One command,
# deterministic completion, non-zero exit iff any test fails.
test:
	python tools/run_tests.py

# Single-process run (what the driver smoke-checks); per-module cache
# clearing in tests/conftest.py keeps this under the compiler's
# accumulation threshold, but `make test` is the canonical full run.
test-single:
	python -m pytest tests/ -x -q

# graftcheck: the AST invariant linter (k8s_gpu_tpu/analysis) — the
# determinism planes carry no ambient time/randomness/set-order, every
# metric mint site honors the registry contract and observability.md,
# and lock-guarded fields are touched under their lock.  Findings are
# compared against config/analysis_baseline.json (pinned debt only
# shrinks); non-zero exit on any new finding or stale baseline entry.
# docs/platform/invariants.md documents every rule.
check:
	python -m k8s_gpu_tpu.analysis

# graftcheck demo: seeds one violation of each rule into a scratch tree,
# shows the linter catching all of them, then shows the runtime
# instrumented lock catching an unguarded write a static pass can't see.
analysis-demo:
	python tools/analysis_demo.py

# End-to-end tracing smoke: apiserver create (traceparent in) → workqueue
# → reconcile → fake cloud call → /debug/traces shows one linked trace.
# Prints the rendered flame tree; non-zero exit if any link is missing.
trace-demo:
	python tools/trace_demo.py

# Chaos smoke: seeded 30% fault schedule against the fake Cloud TPU API,
# reconcile-to-convergence behind retries + circuit breakers, then print
# the retry/breaker/shed counters.  Non-zero exit if convergence or any
# invariant (zero leaked resources, faults actually fired) fails.
chaos-demo:
	python tools/chaos_demo.py

# Alerts smoke: chaos-driven breaker/pool alerts traverse
# pending→firing→resolved deterministically under FakeClock (two runs,
# identical timelines), Warning Events land on the affected objects, and
# `obs top` renders the fleet-utilization snapshot from one /metrics
# scrape.  Non-zero exit if any invariant fails.
alerts-demo:
	python tools/alerts_demo.py

# Prefix-cache smoke: 8 requests sharing a 1k-token system prompt on
# the paged KV pool — prints the hit rate, physical blocks shared, and
# warm-vs-cold TTFT.  Non-zero exit if sharing, the >= 2x TTFT win, or
# the refcount leak check fails.
prefix-demo:
	python tools/prefix_demo.py

# Fleet telemetry smoke: 3 in-process batcher replicas with per-replica
# registries serve skewed per-tenant traffic; the federation collector
# scrapes/relabels/aggregates them, the fleet view identifies the hot
# replica and hot tenant, killing a replica fires FleetReplicaDown
# (and reviving resolves it), and every request's journal record
# cross-links to a resolvable trace.  Non-zero exit on any failure.
fleet-demo:
	python tools/fleet_demo.py

# Performance-attribution smoke: a live batcher under mixed traffic
# (the phase table identifies the dominant phase), a seeded shape-churn
# burst walks CompileStorm pending→firing→resolved under FakeClock, and
# the Chrome/Perfetto trace export is written and schema-validated.
profile-demo:
	python tools/profile_demo.py

# Training-goodput smoke: a tiny training run's wall-clock partition is
# exhaustive and exact, a seeded preemption walks GoodputDegraded
# pending→firing→resolved across checkpoint restore, heartbeats name
# the seeded straggler host, and two scripted runs serve byte-identical
# /debug/goodput bodies.
goodput-demo:
	python tools/goodput_demo.py

# Black-box probing end to end on CPU (ISSUE 14): the chaos drill (a
# 3-replica fleet, seeded faults + one corrupting replica — FSM walk,
# ReplicaUnhealthy fire/resolve, router quarantine, budget spend stays
# visible), the /healthz + /readyz contract over real HTTP, the canary
# self-pollution guard, and two-run byte-identical /debug/probes.
canary-demo:
	python tools/canary_demo.py

# Kernel A/Bs, end to end on CPU interpret mode: fused paged-attention
# op-level kernel-vs-oracle parity (f32 + int8 KV + trash-block poison),
# batcher streams gather-vs-kernel byte-identical — greedy and with an
# int8-compute speculative draft — and the train-side flash-v2 act
# (rope in-kernel + GQA streaming + q pipeline: fwd/grad parity and the
# fallback mint chain).  The perf ratios (cb_paged_kernel_vs_gather_x,
# train_flash_v2_vs_v1_x) are bench.py's job on a TPU host.
kernel-demo:
	python tools/kernel_demo.py

# Fleet front-end smoke (ISSUE 15): 3 real LmServers on real sockets
# behind the FleetFrontend gateway — admin-plane registration gated on
# /readyz, skewed tenants routing by affinity (x-route-* headers), a
# dead-kill rehash with zero lost requests, and an in-flight-aware
# drain that retires gracefully while its work finishes.
frontend-demo:
	python tools/frontend_demo.py

# Fleet waterfall smoke (ISSUE 16): 3 replicas behind the gateway,
# skewed traffic with one replica killed mid-burst — the cross-process
# stitcher shows the rehashed request's dead attempt AND the
# survivor's completion in ONE trace, retry_hop attributed, segments
# summing exactly to E2E, byte-identical across two stitching runs.
waterfall-demo:
	python tools/waterfall_demo.py

# KV migration chaos drill (ISSUE 17): 2 replicas behind the gateway,
# one drained while a long stream is mid-flight — wire-level block
# export/import + router re-home, the cut stream resumes on the
# survivor (full token budget, zero lost/duplicated, one trace id),
# and the migrated prefix beats a cold re-prefill by >= 2x TTFT.
migrate-demo:
	python tools/migration_demo.py

# Replicated-gateway smoke (ISSUE 18): 3 gateways over 3 replicas —
# byte-identical owner-map reconstruction from scrapes alone, a cruel
# mid-burst gateway kill with client failover losing zero tokens, and a
# 10:1 hot-tenant flood throttled at the weighted-fair admission door.
gateway-demo:
	python tools/gateway_demo.py

# Workload flight-recorder drill (ISSUE 19): capture mixed paged+spec
# multi-tenant traffic (two byte-identical captures), replay it
# byte-exact on a fresh replica (mid-burst replica-kill capture
# included), then catch a seeded prefix-cache-off regression with the
# diff attributing the delta to prefill and ReplayRegression firing.
replay-demo:
	python tools/replay_demo.py

# Disaggregated prefill/decode drill: long prompts prefill on a
# dedicated worker and ship KV over the migration wire while 8 short
# decode streams deliver in full (byte-identical to fused references),
# seeded disagg.handover faults degrade to fused with zero lost, and a
# traffic-mix flip makes the ratio controller reassign a live worker
# (role flip observed on the worker AND the gateway).
disagg-demo:
	python tools/disagg_demo.py

# Fleet router smoke: 4 paged replicas behind the prefix-affinity
# router serve skewed multi-tenant traffic (each tenant's shared prompt
# lands on ONE replica — per-replica hit rates from the federated
# counters prove it), a backlog fires FleetQueueBacklog and the
# autoscaler adds a replica, and the prefix-aware scale-down drains the
# fewest-chains replica with zero dropped requests.
router-demo:
	python tools/router_demo.py
