"""Fleet waterfall (utils/waterfall.py): cross-process trace stitching
and per-request critical-path attribution.

Two halves.  The synthetic half pins the math: a hand-built
gateway+replica trace with a known 5000s clock skew must stitch into
the exact segment partition (segments + unattributed summing to the
client-observed elapsed), report the skew rather than hide it, and
produce byte-identical sort_keys JSON across two fresh assembler runs
— the /debug/waterfall contract.  The live half drives the real thing:
traceparent through the gateway's ndjson streaming path with
``x-trace-id`` echoed on every outcome including sheds, then the chaos
drill — a gateway over two live LmServers, one killed mid-burst, and
the rehashed request's SINGLE stitched trace showing the dead
replica's failed attempt AND the survivor's completion, with
``retry_hop`` attributed and the partition still exact.
"""

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from k8s_gpu_tpu.data import BpeTokenizer
from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import FleetFrontend, LmServer
from k8s_gpu_tpu.utils import (
    FakeClock,
    FleetTraceAssembler,
    MetricsRegistry,
    split_by_process,
)
from k8s_gpu_tpu.utils.obs import MetricsServer
from k8s_gpu_tpu.utils.tracing import SpanContext, Tracer, global_tracer

PAGE = 8

TENANT_PROMPTS = {
    "acme": ("the cat sat on the log. the dog sat on the mat. "
             "the mat sat on the cat."),
    "blue": ("the dog sat on the mat. the cat sat on the log. "
             "the log sat on the dog."),
}


# -- synthetic fixtures ---------------------------------------------------

TID = "ab" * 16
GW_ROOT = "aa" * 8
D1 = "d1" * 8
D2 = "d2" * 8
SRV = "e5" * 8
# rep-b's monotonic origin sits 5000s behind the gateway's: every
# rep-b-local timestamp below is true_time + 5000.
SKEW = 5000.0


def _span(name, sid, parent, start, dur_ms, status="ok", **attrs):
    return {
        "name": name, "trace_id": TID, "span_id": sid,
        "parent_id": parent, "start": start, "duration_ms": dur_ms,
        "ts": 0.0, "attributes": attrs, "status": status,
    }


def _frag(spans):
    return {"trace_id": TID, "span_count": len(spans), "tree": spans}


def _synthetic_targets():
    """A gateway fragment and a skewed replica fragment whose stitched
    partition is known exactly: e2e 1.0s = gateway_route 0.10 +
    retry_hop 0.20 + network_gap 0.10 (0.05 each leg) + queue_wait 0.04
    + prefill 0.15 + decode 0.29 + unattributed 0.12."""
    gw = _frag([
        _span("http POST /generate", GW_ROOT, "cd" * 8, 10.0, 1000.0,
              server="fleet-frontend"),
        _span("gateway.dispatch", D1, GW_ROOT, 10.1, 200.0,
              status="error", replica="rep-a", attempt=1,
              outcome="fail"),
        _span("gateway.dispatch", D2, GW_ROOT, 10.3, 650.0,
              replica="rep-b", attempt=2, outcome="ok"),
    ])
    rb = _frag([
        _span("http POST /generate", SRV, D2, SKEW + 10.35, 550.0,
              server="lm-server"),
        _span("serve.queue_wait", "b1" * 8, D2, SKEW + 10.35, 40.0),
        _span("serve.prefill", "b2" * 8, D2, SKEW + 10.39, 150.0,
              fused=True),
        _span("serve.round", "b3" * 8, D2, SKEW + 10.54, 290.0),
    ])
    return {
        "gateway": lambda: {"traces": [gw], "cursor": 1},
        "rep-b": lambda: {"traces": [rb], "cursor": 1},
    }


def _assembler(reg=None):
    a = FleetTraceAssembler(
        targets=_synthetic_targets(),
        registry=reg or MetricsRegistry(), clock=FakeClock(),
    )
    assert a.scrape_once() == {"gateway": True, "rep-b": True}
    return a


# -- the exact partition --------------------------------------------------


def test_synthetic_stitch_segments_exact():
    reg = MetricsRegistry()
    wf = _assembler(reg).waterfall(TID)
    assert wf["stitched"] and not wf["missing_spans"]
    assert wf["e2e_s"] == pytest.approx(1.0, abs=1e-9)
    secs = {s: wf["segments"][s]["seconds"] for s in wf["segments"]}
    assert secs["gateway_route"] == pytest.approx(0.10, abs=1e-9)
    assert secs["retry_hop"] == pytest.approx(0.20, abs=1e-9)
    assert secs["network_gap"] == pytest.approx(0.10, abs=1e-9)
    assert secs["queue_wait"] == pytest.approx(0.04, abs=1e-9)
    assert secs["prefill"] == pytest.approx(0.15, abs=1e-9)
    assert secs["decode"] == pytest.approx(0.29, abs=1e-9)
    assert secs["unattributed"] == pytest.approx(0.12, abs=1e-9)
    # The exhaustiveness contract: segments sum to the client-observed
    # elapsed — exactly, because unattributed is the residual.
    assert abs(sum(secs.values()) - wf["e2e_s"]) < 1e-8
    assert wf["critical"] == "decode"
    # Symmetric-legs network split, both sides reported.
    assert wf["network"]["request_s"] == pytest.approx(0.05, abs=1e-9)
    assert wf["network"]["response_s"] == pytest.approx(0.05, abs=1e-9)
    # TTFT clips the same sweep at first prefill end: 0.54s, with the
    # response network leg and decode excluded.
    assert wf["ttft_s"] == pytest.approx(0.54, abs=1e-9)
    assert wf["ttft_segments"]["decode"] == pytest.approx(0.0, abs=1e-9)
    assert wf["ttft_segments"]["network_gap"] == pytest.approx(
        0.05, abs=1e-9
    )
    # Skew is REPORTED, never hidden: the replica pinned 5000s off.
    assert wf["processes"]["gateway"]["offset_s"] == 0.0
    assert wf["processes"]["rep-b"]["aligned"]
    assert wf["processes"]["rep-b"]["pairs"] == 1
    assert wf["processes"]["rep-b"]["offset_s"] == pytest.approx(
        -SKEW, abs=1e-6
    )
    # Both attempts live in the one stitched trace.
    assert [a["outcome"] for a in wf["attempts"]] == ["fail", "ok"]
    assert [a["replica"] for a in wf["attempts"]] == ["rep-a", "rep-b"]
    assert wf["attempts"][0]["status"] == "error"
    # Metric export: one stitched trace, skew gauge per process.
    assert reg.counter("e2e_traces_total") == 1
    assert reg.counter("e2e_missing_spans_total") == 0


def test_two_run_byte_identical():
    """Two fresh assemblers over identical scraped rings under FakeClock
    produce byte-identical sort_keys JSON — waterfall AND listing."""
    a1, a2 = _assembler(), _assembler()
    assert (
        json.dumps(a1.waterfall(TID), sort_keys=True)
        == json.dumps(a2.waterfall(TID), sort_keys=True)
    )
    assert (
        json.dumps(a1.snapshot(), sort_keys=True)
        == json.dumps(a2.snapshot(), sort_keys=True)
    )


def test_unaligned_process_flags_missing_spans():
    """A replica whose server span never completed (killed mid-request)
    leaves the dispatch pair-less: the process reads UNALIGNED and the
    trace is flagged, not silently absorbed."""
    targets = _synthetic_targets()
    # rep-b ships only batcher spans — no "http " server span, so no
    # (dispatch, server) pair to pin its clock with.
    rb = _frag([
        _span("serve.queue_wait", "b1" * 8, D2, SKEW + 10.35, 40.0),
    ])
    targets["rep-b"] = lambda: {"traces": [rb], "cursor": 1}
    reg = MetricsRegistry()
    a = FleetTraceAssembler(
        targets=targets, registry=reg, clock=FakeClock()
    )
    a.scrape_once()
    wf = a.waterfall(TID)
    assert wf["stitched"] and wf["missing_spans"]
    assert not wf["processes"]["rep-b"]["aligned"]
    assert reg.counter("e2e_missing_spans_total") == 1


def test_chrome_export_one_pid_per_process():
    ct = _assembler().chrome(TID)
    procs = {
        e["pid"]: e["args"]["name"]
        for e in ct["traceEvents"] if e["name"] == "process_name"
    }
    assert procs == {1: "gateway", 2: "rep-b"}
    slices = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in slices} == {1, 2}
    # Aligned shared timeline: the replica's server span starts after
    # the serving dispatch despite its 5000s-skewed source clock.
    d2 = next(s for s in slices if s["name"] == "gateway.dispatch"
              and s["args"].get("attempt") == "2")
    srv = next(s for s in slices if s["pid"] == 2
               and s["name"].startswith("http "))
    assert srv["ts"] >= d2["ts"]


def test_tracer_since_cursor():
    """The /debug/traces?since= contract: the completion index only
    ships traces that recorded a span after the cursor read."""
    tr = Tracer(clock=FakeClock())
    c0 = tr.cursor
    assert c0 == 0
    t1 = SpanContext("11" * 16, "aa" * 8)
    tr.add_span("one", parent=t1, start=0.0, end=1.0)
    c1 = tr.cursor
    assert c1 == 1
    assert [t["trace_id"] for t in tr.traces(since=c0)] == [t1.trace_id]
    assert tr.traces(since=c1) == []
    t2 = SpanContext("22" * 16, "bb" * 8)
    tr.add_span("two", parent=t2, start=1.0, end=2.0)
    assert [t["trace_id"] for t in tr.traces(since=c1)] == [t2.trace_id]
    # A new span in the OLD trace re-ships it (dedup is the scraper's
    # job — by span id, which is why overlap is safe and gaps are not).
    c2 = tr.cursor
    tr.add_span("one-more", parent=t1, start=2.0, end=3.0)
    assert {t["trace_id"] for t in tr.traces(since=c2)} == {t1.trace_id}


def test_debug_waterfall_endpoint():
    """MetricsServer serves the assembler: listing, one trace, chrome
    form — and two servers over identical assemblers answer with
    byte-identical bodies."""
    def fetch(port, path):
        url = f"http://127.0.0.1:{port}{path}"
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.read()

    srvs = [
        MetricsServer(
            registry=MetricsRegistry(), waterfall=_assembler()
        ).start()
        for _ in range(2)
    ]
    try:
        bodies = [fetch(s.port, "/debug/waterfall") for s in srvs]
        assert bodies[0] == bodies[1]
        listing = json.loads(bodies[0])
        assert [t["trace_id"] for t in listing["traces"]] == [TID]
        assert listing["traces"][0]["critical"] == "decode"
        one = [
            fetch(s.port, f"/debug/waterfall?trace_id={TID}")
            for s in srvs
        ]
        assert one[0] == one[1]
        wf = json.loads(one[0])
        assert wf["stitched"] and wf["e2e_s"] == pytest.approx(1.0)
        ct = json.loads(
            fetch(srvs[0].port,
                  f"/debug/waterfall?trace_id={TID}&chrome=1")
        )
        assert any(
            e["name"] == "process_name"
            for e in ct["traceEvents"]
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            fetch(srvs[0].port, "/debug/waterfall?trace_id=" + "f" * 32)
        assert ei.value.code == 404
    finally:
        for s in srvs:
            s.stop()


# -- live half: the gateway over real replicas ----------------------------


@pytest.fixture(scope="module")
def stack():
    corpus = "the cat sat on the mat. the dog sat on the log. " * 40
    tok = BpeTokenizer.train(corpus, vocab_size=300)
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, use_flash=False,
    )
    model = TransformerLM(cfg)
    model.init(jax.random.PRNGKey(0))
    return tok, model


def _mk_server(stack, name):
    tok, model = stack
    params = model.init(jax.random.PRNGKey(0))
    return LmServer(
        model, params, tok, slots=4, paged_blocks=64, page_size=PAGE,
        metrics=MetricsRegistry(), name=name,
    ).start()


def _post(base, path, payload, headers=None, timeout=60.0):
    req = urllib.request.Request(
        base.rstrip("/") + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload, dict(e.headers)


def _gen(tenant, i, extra=None):
    body = {
        "prompt": TENANT_PROMPTS[tenant] + f" q{i}",
        "max_new_tokens": 4, "temperature": 0.0, "tenant": tenant,
    }
    body.update(extra or {})
    return body


def _tid(i):
    return f"{0x57A7ED00 + i:032x}"


def test_traceparent_through_gateway_stream(stack):
    """The ndjson streaming path: the client's traceparent survives the
    gateway hop into the replica's summary event, and x-trace-id is
    echoed on the stream headers AND on shed outcomes."""
    servers = {"ws-0": _mk_server(stack, "ws-0")}
    tok, _ = stack
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry()
    ).start()
    try:
        fe.register_replica(
            "ws-0", f"http://127.0.0.1:{servers['ws-0'].port}"
        )
        trace_id = "ab" * 16
        conn = http.client.HTTPConnection(
            "127.0.0.1", fe.port, timeout=60
        )
        conn.request(
            "POST", "/generate",
            json.dumps(_gen("blue", 1, {"stream": True,
                                        "max_new_tokens": 6})),
            {"Content-Type": "application/json",
             "traceparent": f"00-{trace_id}-{'cd' * 8}-01"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("x-trace-id") == trace_id
        lines = [ln for ln in resp.read().splitlines() if ln.strip()]
        conn.close()
        summary = json.loads(lines[-1])
        assert summary["done"] is True
        # The replica continued OUR trace across both hops.
        assert summary["trace_id"] == trace_id
        # Shed outcomes are findable too: a dead deadline never reaches
        # a replica, yet the 504 still carries the trace id.
        shed_tid = "ef" * 16
        code, body, hdrs = _post(
            fe.url, "/generate", _gen("acme", 2),
            headers={"traceparent": f"00-{shed_tid}-{'cd' * 8}-01",
                     "x-request-deadline-ms": "0"},
        )
        assert code == 504 and "deadline" in body["error"]
        assert hdrs["x-trace-id"] == shed_tid
    finally:
        fe.stop()
        for srv in servers.values():
            srv.stop()


def test_kill_mid_burst_single_stitched_trace(stack):
    """The chaos drill: kill a replica mid-burst; the rehashed request
    yields ONE stitched waterfall holding the dead replica's failed
    attempt and the survivor's completion, retry_hop attributed, the
    partition exact — and the stitch byte-identical across two fresh
    assembler runs over the same captured rings."""
    tok, _ = stack
    servers = {f"wf-{i}": _mk_server(stack, f"wf-{i}") for i in range(2)}
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry()
    ).start()
    try:
        for name, srv in servers.items():
            fe.register_replica(name, f"http://127.0.0.1:{srv.port}")
        _, _, hdrs = _post(fe.url, "/generate", _gen("acme", 0))
        victim = hdrs["x-route-replica"]
        n_burst = 10
        codes = []

        def fire(i):
            tenant = "acme" if i % 2 else "blue"
            code, _, _ = _post(
                fe.url, "/generate",
                _gen(tenant, 100 + i, {"max_new_tokens": 12}),
                headers={"traceparent": f"00-{_tid(i)}-{'cd' * 8}-01"},
            )
            codes.append(code)

        def killer():
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if servers[victim].batcher.inflight_requests > 0:
                    break
                time.sleep(0.005)
            servers[victim].stop()

        threads = [threading.Thread(target=killer)]
        threads += [
            threading.Thread(target=fire, args=(i,))
            for i in range(n_burst)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert codes == [200] * n_burst, f"lost requests: {codes}"

        # The http spans close just after the response bytes — wait for
        # a rehashed trace (>= 2 dispatch attempts) to fully land.
        def rehashed_tids():
            out = []
            for i in range(n_burst):
                tr = global_tracer.traces(trace_id=_tid(i), limit=1)
                if not tr:
                    continue
                flat = json.dumps(tr[0])
                if flat.count('"gateway.dispatch"') >= 2:
                    out.append(_tid(i))
            return out

        deadline = time.time() + 10.0
        tids = rehashed_tids()
        while not tids and time.time() < deadline:
            time.sleep(0.05)
            tids = rehashed_tids()
        assert tids, "no request rehashed — kill landed too late"
        tid = tids[0]

        # Capture the shared ring ONCE, split into the per-process
        # fragments real /debug/traces scrapes would ship, then stitch
        # twice from scratch: the byte-identical contract.
        captured = global_tracer.traces(trace_id=tid, limit=1)
        frags = split_by_process(captured)
        assert "gateway" in frags
        targets = {
            p: (lambda p=p: {"traces": frags[p]}) for p in frags
        }
        wfs = []
        for _ in range(2):
            a = FleetTraceAssembler(
                targets=targets, registry=MetricsRegistry(),
                clock=FakeClock(),
            )
            a.scrape_once()
            wfs.append(a.waterfall(tid))
        assert (
            json.dumps(wfs[0], sort_keys=True)
            == json.dumps(wfs[1], sort_keys=True)
        )
        wf = wfs[0]
        assert wf["stitched"]
        # Both attempts in ONE trace: the dead replica's failed hop and
        # the survivor's completion.
        outcomes = [a["outcome"] for a in wf["attempts"]]
        assert len(wf["attempts"]) >= 2
        assert "fail" in outcomes and outcomes[-1] == "ok"
        replicas = [a["replica"] for a in wf["attempts"]]
        assert victim in replicas
        assert replicas[-1] != victim
        # The rehash cost is attributed, not absorbed.
        secs = {s: wf["segments"][s]["seconds"] for s in wf["segments"]}
        assert secs["retry_hop"] > 0.0
        assert secs["prefill"] > 0.0 or secs["decode"] > 0.0
        # And the partition stays exact even in chaos.
        assert abs(sum(secs.values()) - wf["e2e_s"]) < 1e-8
        # The survivor's clock got pinned through its server span.
        survivor = replicas[-1]
        assert wf["processes"][survivor]["aligned"]
    finally:
        fe.stop()
        for srv in servers.values():
            try:
                srv.stop()
            except Exception:
                pass
