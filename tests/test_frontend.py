"""Cross-process fleet front-end (serve/frontend.py): the HTTP gateway
over live LmServer replicas, end to end over real sockets.

The chaos drill the module exists for: affinity through the gateway's
chain hashing, a mid-burst replica kill rehashing with zero lost
requests, an in-flight-aware drain that retires its victim only after
the victim's stream finishes, 429 pass-through without a mark-down,
header propagation verified in BOTH journals, and two-run byte-identical
routing under FakeClock.  Plus the shared chain-hash helper's skew
regression: the gateway's routing key and the batcher's paged-admission
key must be the same bytes.
"""

import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import numpy as np
import pytest

from k8s_gpu_tpu.data import BpeTokenizer
from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import FleetFrontend, LmServer
from k8s_gpu_tpu.serve.kv_blocks import (
    chunk_hashes,
    shareable_chain,
    shareable_depth,
)
from k8s_gpu_tpu.utils import FakeClock, MetricsRegistry

# LmServer's batcher floors the paged page size at 8 (batcher.py) — the
# gateway MUST hash at the replicas' EFFECTIVE page or every chain skews,
# which is precisely what test_gateway_chain_equals_batcher_registration
# pins.
PAGE = 8

# Word-order permutations the corpus BPE cannot collapse: ~14 tokens of
# shared per-tenant prefix (plus the per-request suffix) — at least two
# full shareable pages at PAGE=8, so routing is chain-affine.
TENANT_PROMPTS = {
    "acme": ("the cat sat on the log. the dog sat on the mat. "
             "the mat sat on the cat."),
    "blue": ("the dog sat on the mat. the cat sat on the log. "
             "the log sat on the dog."),
    "coral": ("the log sat on the cat. the mat sat on the dog. "
              "the cat sat on the log."),
}


@pytest.fixture(scope="module")
def stack():
    corpus = "the cat sat on the mat. the dog sat on the log. " * 40
    tok = BpeTokenizer.train(corpus, vocab_size=300)
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, use_flash=False,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return tok, model, params


def _mk_server(stack, name):
    tok, model, params = stack
    return LmServer(
        model, params, tok, slots=4, paged_blocks=64, page_size=PAGE,
        metrics=MetricsRegistry(), name=name,
    ).start()


@pytest.fixture(scope="module")
def fleet(stack):
    """3 live LmServers registered behind one gateway — shared by the
    non-destructive tests (nothing here kills or retires a replica)."""
    tok, _, _ = stack
    servers = {f"fr-{i}": _mk_server(stack, f"fr-{i}") for i in range(3)}
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry()
    ).start()
    for name, srv in servers.items():
        fe.register_replica(name, f"http://127.0.0.1:{srv.port}")
    yield fe, servers
    fe.stop()
    for srv in servers.values():
        srv.stop()


def _post(base, path, payload, headers=None, timeout=60.0):
    req = urllib.request.Request(
        base.rstrip("/") + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload, dict(e.headers)


def _gen(tenant, i, extra=None):
    body = {
        "prompt": TENANT_PROMPTS[tenant] + f" q{i}",
        "max_new_tokens": 4, "temperature": 0.0, "tenant": tenant,
    }
    body.update(extra or {})
    return body


# -- the shared chain definition (satellite 1) ---------------------------


def test_shareable_chain_matches_definition():
    ids = np.arange(2, 2 + 23, dtype=np.int32)
    # 23 tokens / page 4: (23-1)//4 = 5 full shareable pages.
    assert shareable_depth(23, 4) == 5
    assert shareable_chain(ids, 4) == chunk_hashes(ids, 4)[:5]
    # Exactly page-aligned: the LAST full page is NOT shareable — one
    # suffix token must remain to produce first-token logits.
    assert shareable_depth(24, 4) == 5
    assert len(shareable_chain(np.arange(24, dtype=np.int32), 4)) == 5
    # Shorter than a page: nothing shareable.
    assert shareable_chain(np.arange(4, dtype=np.int32), 4) == []


def test_gateway_chain_equals_batcher_registration(stack, fleet):
    """Skew regression: the hashes the gateway routes on are the very
    hashes the replica's block pool registers for the same prompt."""
    tok, _, _ = stack
    _, servers = fleet
    srv = servers["fr-0"]
    prompt = TENANT_PROMPTS["acme"] + " skew probe"
    code, _, _ = _post(
        f"http://127.0.0.1:{srv.port}", "/generate",
        {"prompt": prompt, "max_new_tokens": 2, "temperature": 0.0},
    )
    assert code == 200
    ids = tok.encode(prompt)
    chain = shareable_chain(ids, PAGE)
    assert len(chain) == shareable_depth(int(ids.size), PAGE) >= 2
    registered = srv.batcher._pool._blk_of
    for h in chain:
        assert h in registered, "gateway chain hash unknown to the pool"


# -- LmServer health contract through a live drain (satellite 2) ---------


def test_readyz_identity_and_inflight_through_drain(fleet):
    _, servers = fleet
    srv = servers["fr-1"]
    base = f"http://127.0.0.1:{srv.port}"

    def readyz():
        try:
            with urllib.request.urlopen(base + "/readyz", timeout=10) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    code, body = readyz()
    assert code == 200 and body["replica"] == "fr-1"
    assert body["inflight"] == 0
    # Hold a stream open so in-flight is observably non-zero.
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
    conn.request(
        "POST", "/generate",
        json.dumps({"prompt": TENANT_PROMPTS["blue"],
                    "max_new_tokens": 24, "temperature": 0.0,
                    "stream": True}),
        {"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    assert resp.status == 200
    deadline = time.time() + 10.0
    seen = 0
    while time.time() < deadline:
        seen = readyz()[1]["inflight"]
        if seen >= 1:
            break
    assert seen >= 1
    try:
        srv.drain()
        code, body = readyz()
        # Draining: NotReady verdict, but identity and the in-flight
        # count keep being served — the gateway's drain fast path.
        assert code == 503 and body["draining"] is True
        assert body["replica"] == "fr-1" and "inflight" in body
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["replica"] == "fr-1" and h["inflight"] >= 0
    finally:
        srv.undrain()
        while resp.readline():
            pass
        conn.close()
    assert readyz()[0] == 200


# -- affinity through the gateway ----------------------------------------


def test_affinity_across_live_fleet(fleet):
    fe, _ = fleet
    owners, reasons = {}, {}
    for tenant in TENANT_PROMPTS:
        for i in range(3):
            code, _, hdrs = _post(fe.url, "/generate", _gen(tenant, i))
            assert code == 200
            owners.setdefault(tenant, set()).add(hdrs["x-route-replica"])
            reasons.setdefault(tenant, []).append(hdrs["x-route-reason"])
    for tenant, reps in owners.items():
        assert len(reps) == 1, f"{tenant} scattered across {reps}"
    for tenant, rs in reasons.items():
        assert rs[-1] == "affinity", (tenant, rs)


def test_admin_views_and_gateway_health(fleet):
    fe, _ = fleet
    with urllib.request.urlopen(fe.url + "/healthz", timeout=10) as r:
        body = json.loads(r.read())
    assert body["ok"] is True and body["replicas"] == 3
    with urllib.request.urlopen(fe.url + "/readyz", timeout=10) as r:
        body = json.loads(r.read())
    assert body["ready"] is True and body["eligible"] == 3
    code, body, _ = _post(fe.url + "/admin/replicas", "", {"x": 1})
    assert code == 400  # name/url required
    with urllib.request.urlopen(fe.url + "/admin/replicas",
                                timeout=10) as r:
        states = json.loads(r.read())["replicas"]
    assert sorted(s["replica"] for s in states) == [
        "fr-0", "fr-1", "fr-2"
    ]
    assert all("url" in s and "inflight_gateway" in s for s in states)


# -- header propagation, journal-verified --------------------------------


def test_header_propagation_both_journals(fleet):
    fe, servers = fleet
    trace_id = "ab" * 16
    code, out, hdrs = _post(
        fe.url, "/generate", _gen("blue", 77),
        headers={
            "traceparent": f"00-{trace_id}-{'cd' * 8}-01",
            "x-request-deadline-ms": "20000",
        },
    )
    assert code == 200
    replica = hdrs["x-route-replica"]
    reason = hdrs["x-route-reason"]
    # Gateway journal: the client-facing record.
    rec = next(
        r for r in fe.journal.snapshot(limit=50)
        if r["trace_id"] == trace_id
    )
    assert rec["tenant"] == "blue" and rec["path"] == "gateway"
    assert rec["replica"] == replica and rec["route_reason"] == reason
    assert rec["extra"]["status"] == 200
    # Replica journal: the SAME trace id, tenant, and routing stamp
    # arrived downstream in headers.
    down = next(
        r for r in servers[replica].journal.snapshot(limit=50)
        if r["trace_id"] == trace_id
    )
    assert down["tenant"] == "blue"
    assert down["replica"] == replica
    assert down["route_reason"] == reason


def test_expired_deadline_sheds_at_gateway(fleet):
    fe, _ = fleet
    before = fe.metrics.counter("frontend_shed_total", reason="deadline")
    code, body, _ = _post(
        fe.url, "/generate", _gen("acme", 5),
        headers={"x-request-deadline-ms": "0"},
    )
    assert code == 504 and "deadline" in body["error"]
    after = fe.metrics.counter("frontend_shed_total", reason="deadline")
    assert after == before + 1


# -- kill mid-burst: rehash, zero lost -----------------------------------


def test_kill_mid_burst_zero_lost(stack):
    tok, _, _ = stack
    servers = {f"kb-{i}": _mk_server(stack, f"kb-{i}") for i in range(2)}
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry()
    ).start()
    try:
        for name, srv in servers.items():
            fe.register_replica(name, f"http://127.0.0.1:{srv.port}")
        # Learn acme's owner, then kill it once its work is in flight.
        _, _, hdrs = _post(fe.url, "/generate", _gen("acme", 0))
        victim = hdrs["x-route-replica"]
        n_burst = 12
        codes = []

        def fire(i):
            tenant = "acme" if i % 2 else "blue"
            code, _, _ = _post(
                fe.url, "/generate",
                _gen(tenant, 100 + i, {"max_new_tokens": 16}),
            )
            codes.append(code)

        def killer():
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if servers[victim].batcher.inflight_requests > 0:
                    break
                time.sleep(0.005)
            servers[victim].stop()

        kt = threading.Thread(target=killer)
        with ThreadPoolExecutor(max_workers=6) as ex:
            kt.start()
            futs = [ex.submit(fire, i) for i in range(n_burst)]
            for f in futs:
                f.result()
        kt.join()
        assert codes == [200] * n_burst, f"lost requests: {codes}"
        assert fe.metrics.counter("serve_router_rehash_total") >= 1
        # Journal audit: every burst request has exactly one terminal
        # gateway record, all ok — zero lost, zero duplicated.
        recs = [
            r for r in fe.journal.snapshot(limit=100)
            if r["tenant"] in ("acme", "blue")
        ]
        assert len(recs) == n_burst + 1  # burst + the owner probe
        assert all(r["reason"] == "ok" for r in recs)
        # Post-kill traffic re-homes off the victim.
        _, _, hdrs = _post(fe.url, "/generate", _gen("acme", 999))
        assert hdrs["x-route-replica"] != victim
    finally:
        fe.stop()
        for srv in servers.values():
            try:
                srv.stop()
            except Exception:
                pass


# -- in-flight-aware drain ------------------------------------------------


def test_drain_waits_for_inflight_stream(stack):
    tok, _, _ = stack
    servers = {f"dr-{i}": _mk_server(stack, f"dr-{i}") for i in range(2)}
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry()
    ).start()
    try:
        for name, srv in servers.items():
            fe.register_replica(
                name, f"http://127.0.0.1:{srv.port}",
                on_drain=srv.drain,
            )
        # Open a stream; the routing headers arrive before the body, so
        # the victim is known while its work is still in flight.
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=60)
        conn.request(
            "POST", "/generate",
            json.dumps(_gen("coral", 1, {"stream": True,
                                         "max_new_tokens": 24})),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        victim = resp.getheader("x-route-replica")
        code, st, _ = _post(
            fe.url, "/admin/drain", {"name": victim, "deadline_s": 30.0}
        )
        assert code == 202 and st["state"] == "draining"
        # The drain is announced: the victim's own /readyz flips NotReady
        # (on_drain hook) while the stream is still being served.
        events = [json.loads(line) for line in resp if line.strip()]
        conn.close()
        summary = events[-1]
        assert summary["done"] is True, summary
        assert summary["generated_tokens"] == 24
        deadline = time.time() + 15.0
        state = {}
        while time.time() < deadline:
            with urllib.request.urlopen(fe.url + "/admin/drain",
                                        timeout=10) as r:
                drains = json.loads(r.read())["drains"]
            state = next(
                (d for d in drains if d["replica"] == victim), {}
            )
            if state.get("state") == "retired":
                break
            time.sleep(0.05)
        assert state.get("state") == "retired", state
        assert state["forced"] is False  # graceful: the stream finished
        assert victim not in fe.replica_names()
        assert fe.metrics.counter(
            "frontend_drains_total", outcome="graceful"
        ) == 1
        # Traffic keeps flowing on the survivor.
        code, _, hdrs = _post(fe.url, "/generate", _gen("coral", 2))
        assert code == 200 and hdrs["x-route-replica"] != victim
    finally:
        fe.stop()
        for srv in servers.values():
            srv.stop()


# -- 429 pass-through without mark-down ----------------------------------


class _ShedReplica:
    """A replica that is alive, ready, and permanently full: /readyz
    says ready, every /generate sheds 429 with its own Retry-After."""

    def __init__(self, name):
        outer_name = name

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                body = json.dumps({
                    "ready": True, "scheduler_alive": True,
                    "draining": False, "replica": outer_name,
                    "inflight": 0,
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = json.dumps({"error": "queue full"}).encode()
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After", "7")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_429_passes_through_without_markdown(stack):
    tok, _, _ = stack
    shed = _ShedReplica("shed-0")
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry()
    ).start()
    try:
        fe.register_replica("shed-0", f"http://127.0.0.1:{shed.port}")
        code, body, hdrs = _post(fe.url, "/generate", _gen("acme", 1))
        assert code == 429
        assert body["error"] == "queue full"  # the replica's own body
        assert hdrs["Retry-After"] == "7"     # and its own backoff hint
        # Overload is load, not death: the replica stays routable.
        snap = {
            r["replica"]: r
            for r in fe.router.snapshot()["replicas"]
        }
        assert snap["shed-0"]["down"] is False
        assert fe.metrics.counter(
            "frontend_shed_total", reason="overloaded"
        ) == 1
        rec = fe.journal.snapshot(limit=5)[0]  # newest-first
        assert rec["reason"] == "overloaded"
    finally:
        fe.stop()
        shed.stop()


# -- two-run deterministic routing ---------------------------------------


def test_two_run_routing_is_byte_identical(stack, fleet):
    """Same replica set, same request sequence, FakeClock: the routing
    decisions AND the router snapshot must be byte-identical across two
    fresh gateways — routing is a pure function of its inputs."""
    tok, _, _ = stack
    _, servers = fleet

    def run():
        fe = FleetFrontend(
            tok, page_size=PAGE, clock=FakeClock(),
            metrics=MetricsRegistry(),
        ).start()
        try:
            for name in sorted(servers):
                fe.register_replica(
                    name, f"http://127.0.0.1:{servers[name].port}"
                )
            decisions = []
            for i in range(6):
                tenant = ["acme", "blue", "coral"][i % 3]
                code, _, hdrs = _post(
                    fe.url, "/generate", _gen(tenant, i)
                )
                assert code == 200
                decisions.append(
                    (hdrs["x-route-replica"], hdrs["x-route-reason"])
                )
            snap = dict(fe.router.snapshot())
            return json.dumps(
                {"decisions": decisions, "snapshot": snap},
                sort_keys=True,
            )
        finally:
            fe.stop()

    assert run() == run()


# -- forced-at-deadline drain ---------------------------------------------


class _StuckStreamReplica:
    """Alive, ready, and permanently mid-stream: /generate emits one
    token event and then parks on a release gate — an in-flight request
    a drain deadline must eventually abandon."""

    def __init__(self, name):
        outer_name = name
        self.release = threading.Event()
        release = self.release

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                body = json.dumps({
                    "ready": True, "scheduler_alive": True,
                    "draining": False, "replica": outer_name,
                    "inflight": 1,
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                self.send_response(200)
                self.send_header(
                    "Content-Type", "application/x-ndjson"
                )
                self.end_headers()
                self.wfile.write(b'{"id": 1}\n')
                self.wfile.flush()
                release.wait(30.0)
                self.wfile.write(
                    json.dumps({"done": False,
                                "error": "replica gave up"}).encode()
                    + b"\n"
                )

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()

    def stop(self):
        self.release.set()
        self.httpd.shutdown()
        self.httpd.server_close()


def test_drain_deadline_forces_and_journals_abandoned(stack):
    """A drain whose victim never goes idle is FORCED at the deadline —
    and the force is auditable: ``frontend_drains_total{forced}``, the
    drain state's abandoned count, and one ``path="gateway"`` journal
    record per in-flight request killed (``extra.drain_forced``).  With
    no surviving replica the cut stream's resume fails honestly: the
    client's last event says truncation, never a fake completion."""
    tok, _, _ = stack
    stuck = _StuckStreamReplica("stuck-0")
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry()
    ).start()
    try:
        fe.register_replica("stuck-0", f"http://127.0.0.1:{stuck.port}")
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=60)
        conn.request(
            "POST", "/generate",
            json.dumps(_gen("acme", 1, {"stream": True})),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        first = json.loads(resp.readline())
        assert first == {"id": 1}  # mid-stream: one token delivered
        code, st, _ = _post(
            fe.url, "/admin/drain",
            {"name": "stuck-0", "deadline_s": 0.4},
        )
        assert code == 202 and st["state"] == "draining"
        deadline = time.time() + 15.0
        state = {}
        while time.time() < deadline:
            with urllib.request.urlopen(fe.url + "/admin/drain",
                                        timeout=10) as r:
                drains = json.loads(r.read())["drains"]
            state = next(
                (d for d in drains if d["replica"] == "stuck-0"), {}
            )
            if state.get("state") == "retired":
                break
            time.sleep(0.05)
        assert state.get("state") == "retired", state
        assert state["forced"] is True
        assert state["abandoned"] == 1
        assert "stuck-0" not in fe.replica_names()
        assert fe.metrics.counter(
            "frontend_drains_total", outcome="forced"
        ) == 1
        # The abandoned request is in the gateway journal, marked as a
        # forced-drain casualty — not silently indistinguishable from a
        # graceful retirement.
        recs = [
            r for r in fe.journal.snapshot(limit=20)
            if r.get("extra", {}).get("drain_forced")
        ]
        assert len(recs) == 1
        assert recs[0]["reason"] == "aborted"
        assert recs[0]["path"] == "gateway"
        assert recs[0]["replica"] == "stuck-0"
        assert recs[0]["tenant"] == "acme"
        assert recs[0]["extra"]["abandoned"] == 1
        # Release the stuck stream: the relay sees a resumable
        # truncation, finds no surviving replica, and closes with an
        # honest failure summary.
        stuck.release.set()
        events = [json.loads(line) for line in resp if line.strip()]
        conn.close()
        assert events, "client never got a terminal event"
        last = events[-1]
        assert last["done"] is False
        assert "resume failed" in last["error"]
        assert fe.metrics.counter(
            "migrate_failures_total", stage="resume"
        ) >= 1
    finally:
        fe.stop()
        stuck.stop()
