"""TPU accelerator-type catalog + ICI topology math (SURVEY §7 hard part 5)."""

import pytest

from k8s_gpu_tpu.cloud.topology import default_topology, parse_accelerator_type


def test_v5p_64_is_4x4x4_16_hosts():
    t = parse_accelerator_type("v5p-64")
    assert t.chips == 64
    assert t.topology == (4, 4, 4)
    assert t.hosts == 16
    assert not t.is_single_host


def test_v4_8_single_host():
    t = parse_accelerator_type("v4-8")
    assert t.chips == 8
    assert t.topology == (2, 2, 2)
    assert t.hosts == 2  # 4 chips per v4 host


def test_v5e_256_is_16x16():
    t = parse_accelerator_type("v5e-256")
    assert t.topology == (16, 16)
    assert t.hosts == 32  # 8 chips per v5e host


@pytest.mark.parametrize(
    "accel,topo",
    [
        ("v4-16", (2, 2, 4)),
        ("v4-32", (2, 4, 4)),
        ("v5p-128", (4, 4, 8)),
        ("v5p-512", (8, 8, 8)),
        ("v5e-8", (2, 4)),
        ("v5e-64", (8, 8)),
        ("v6e-16", (4, 4)),
    ],
)
def test_known_topologies(accel, topo):
    assert parse_accelerator_type(accel).topology == topo


def test_topology_chip_product_invariant():
    for accel in ["v4-8", "v4-64", "v5p-64", "v5p-256", "v5e-128", "v6e-256"]:
        t = parse_accelerator_type(accel)
        prod = 1
        for d in t.topology:
            prod *= d
        assert prod == t.chips


def test_unknown_generation_rejected():
    with pytest.raises(ValueError):
        parse_accelerator_type("v3-8")
    with pytest.raises(ValueError):
        parse_accelerator_type("nonsense")
    with pytest.raises(ValueError):
        parse_accelerator_type("v4-0")


def test_factored_topology_for_unlisted_sizes():
    # Not in the known table → balanced factorization.
    assert default_topology(216, 3) == (6, 6, 6)


def test_host_bounds_cover_chips_per_host():
    t = parse_accelerator_type("v5p-64")
    b = t.host_bounds()
    prod = 1
    for d in b:
        prod *= d
    assert prod == t.generation.chips_per_host
