"""apimachinery semantics of the in-memory API server (SURVEY §4 item 2)."""

import pytest

from k8s_gpu_tpu.api import AzureVmPool, Secret
from k8s_gpu_tpu.controller import Conflict, FakeKube, NotFound


def pool(name="p", replicas=1):
    p = AzureVmPool()
    p.metadata.name = name
    p.spec.replicas = replicas
    return p


def test_create_get_roundtrip_deepcopies(kube: FakeKube):
    created = kube.create(pool())
    created.spec.replicas = 99  # mutate the returned copy
    got = kube.get("AzureVmPool", "p")
    assert got.spec.replicas == 1  # store unaffected
    assert got.metadata.uid and got.metadata.resource_version > 0


def test_update_requires_fresh_resource_version(kube: FakeKube):
    kube.create(pool())
    a = kube.get("AzureVmPool", "p")
    b = kube.get("AzureVmPool", "p")
    a.spec.replicas = 2
    kube.update(a)
    b.spec.replicas = 3
    with pytest.raises(Conflict):
        kube.update(b)


def test_generation_bumps_on_spec_change_only(kube: FakeKube):
    kube.create(pool())
    obj = kube.get("AzureVmPool", "p")
    assert obj.metadata.generation == 1
    obj.spec.replicas = 5
    obj = kube.update(obj)
    assert obj.metadata.generation == 2
    # Status update must NOT bump generation (subresource semantics,
    # reference README.md:130-131).
    obj.status.ready_replicas = 5
    obj = kube.update_status(obj)
    assert obj.metadata.generation == 2
    assert kube.get("AzureVmPool", "p").status.ready_replicas == 5


def test_plain_update_cannot_touch_status(kube: FakeKube):
    kube.create(pool())
    obj = kube.get("AzureVmPool", "p")
    obj.status.ready_replicas = 42
    kube.update(obj)
    assert kube.get("AzureVmPool", "p").status.ready_replicas == 0


def test_finalizer_blocks_deletion(kube: FakeKube):
    p = pool()
    p.metadata.finalizers = ["x/cleanup"]
    kube.create(p)
    kube.delete("AzureVmPool", "p")
    obj = kube.get("AzureVmPool", "p")  # still there
    assert obj.metadata.deletion_timestamp is not None
    obj.metadata.finalizers = []
    kube.update(obj)
    with pytest.raises(NotFound):
        kube.get("AzureVmPool", "p")


def test_watch_replays_existing_and_streams(kube: FakeKube):
    kube.create(pool("a"))
    events = []
    kube.watch("AzureVmPool", lambda ev: events.append((ev.type, ev.obj.metadata.name)))
    kube.create(pool("b"))
    kube.delete("AzureVmPool", "b")
    assert ("ADDED", "a") in events
    assert ("ADDED", "b") in events
    assert ("DELETED", "b") in events


def test_list_with_label_selector(kube: FakeKube):
    s = Secret()
    s.metadata.name = "s1"
    s.metadata.labels = {"team": "ml"}
    kube.create(s)
    s2 = Secret()
    s2.metadata.name = "s2"
    kube.create(s2)
    assert [o.metadata.name for o in kube.list("Secret", label_selector={"team": "ml"})] == ["s1"]
    assert len(kube.list("Secret")) == 2
