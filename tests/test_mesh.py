"""Mesh construction over the virtual 8-device CPU platform (conftest sets
xla_force_host_platform_device_count=8)."""

import jax
import pytest

from k8s_gpu_tpu.parallel import MeshConfig, build_mesh
from k8s_gpu_tpu.parallel.mesh import AXES, multislice_mesh


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_default_config_all_dp():
    mesh = build_mesh()
    assert mesh.shape["dp"] == 8
    assert mesh.axis_names == AXES


def test_mixed_axes():
    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2))
    assert mesh.shape["dp"] == 2
    assert mesh.shape["sp"] == 2
    assert mesh.shape["tp"] == 2
    assert mesh.size == 8


def test_dp_absorbs_remainder():
    mesh = build_mesh(MeshConfig(tp=4))
    assert mesh.shape["dp"] == 2


def test_indivisible_rejected():
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp=3))
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp=2, tp=3))


def test_n_devices_prefix():
    mesh = build_mesh(MeshConfig(tp=2), n_devices=4)
    assert mesh.size == 4
    assert mesh.shape["dp"] == 2


def test_multislice_dp_must_span_slices():
    # 2 slices of 4 devices: dp=2 (one per slice) * tp=4 → valid.
    mesh = multislice_mesh(MeshConfig(dp=2, tp=4), num_slices=2)
    assert mesh.shape["dp"] == 2
    # dp=1 cannot span 2 slices.
    with pytest.raises(ValueError):
        multislice_mesh(MeshConfig(dp=1, tp=8), num_slices=2)
