"""graftcheck self-enforcement: the repo passes its own invariant
checker inside tier-1, the declared lock contracts hold under a real
multi-threaded hammer, and the steady-state decode loop compiles
nothing new (ISSUE 8 tentpole + satellites).

No external CI: THIS file is the enforcement point.  A new wall-clock
call in a deterministic plane, an undocumented metric, a label-shape
drift, or an unlocked guarded-field access fails here, in the same
alphabetical tier-1 window as the rest of the early suite.
"""

import threading
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.analysis import format_report, run_report
from k8s_gpu_tpu.analysis.lockcheck import guarded_fields_for
from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher
from k8s_gpu_tpu.serve.journal import RequestJournal
from k8s_gpu_tpu.serve.router import FleetRouter
from k8s_gpu_tpu.utils.alerts import RuleEvaluator
from k8s_gpu_tpu.utils.clock import FakeClock
from k8s_gpu_tpu.utils.faults import FaultInjector, guard_declared
from k8s_gpu_tpu.utils.federation import FleetCollector
from k8s_gpu_tpu.utils.metrics import MetricsRegistry
from k8s_gpu_tpu.utils.tracing import Tracer

REPO_ROOT = Path(__file__).resolve().parents[1]

TINY = TransformerConfig(
    vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
    d_ff=96, max_seq=64, use_flash=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


# -- the self-check ------------------------------------------------------------

def test_repo_passes_graftcheck():
    """Every pass over the real tree: zero non-baselined findings, zero
    stale baseline entries.  The failure message IS the report."""
    report = run_report(REPO_ROOT)
    assert report["ok"], "\n" + format_report(report)


def test_baseline_is_small_and_scoped():
    """<= 10 pinned entries, none in serve/ or utils/ — the planes the
    fleet's determinism and race contracts live in carry NO debt."""
    report = run_report(REPO_ROOT)
    assert report["baseline_entries"] <= 10
    import json
    entries = json.loads(
        (REPO_ROOT / "config" / "analysis_baseline.json").read_text()
    )["entries"]
    for e in entries:
        assert not e["path"].startswith("k8s_gpu_tpu/serve/"), e
        assert not e["path"].startswith("k8s_gpu_tpu/utils/"), e


def test_report_output_byte_identical_across_runs():
    a = format_report(run_report(REPO_ROOT)).encode()
    b = format_report(run_report(REPO_ROOT)).encode()
    assert a == b


def test_contract_classes_declare_guards():
    """The classes where PRs 4-7 each fixed a real race carry explicit
    lock contracts — the single source the static pass verifies and the
    runtime guard enforces."""
    for cls, lock, field in (
        (ContinuousBatcher, "_lifecycle", "_dead"),
        (FleetRouter, "_lock", "_chains"),
        (FleetCollector, "_lock", "_fails"),
        (RequestJournal, "_lock", "_ring"),
        (MetricsRegistry, "_lock", "_counters"),
        (RuleEvaluator, "_lock", "_state"),
        (Tracer, "_lock", "_traces"),
        (FaultInjector, "_lock", "_sites"),
    ):
        guards = guarded_fields_for(cls)
        assert lock in guards, (cls.__name__, guards)
        assert field in guards[lock], (cls.__name__, guards)


# -- the runtime half: race stress over batcher/router/federation --------------

def _mk_replica(model, params, name, violations):
    """One guarded serving replica: batcher + journal + registry, all
    instrumented BEFORE the scheduler thread exists."""
    reg = MetricsRegistry()
    journal = RequestJournal(maxlen=64)
    b = ContinuousBatcher(
        model, params, slots=2, max_pending=64,
        metrics=reg, journal=journal,
    )
    guard_declared(b, violations)
    guard_declared(journal, violations)
    guard_declared(reg, violations)
    b.start()
    return b, reg, journal


def test_race_stress_submit_scrape_route_retire(setup):
    """Hammer submit/scrape/route/retire across threads with every
    guarded class instrumented: zero lock violations (the acceptance
    gate for the declared contracts under REAL concurrency, not just
    textual lock blocks)."""
    model, params = setup
    violations: list = []
    b0, reg0, j0 = _mk_replica(model, params, "r0", violations)
    b1, reg1, j1 = _mk_replica(model, params, "r1", violations)
    router = FleetRouter(
        page_size=16, metrics=MetricsRegistry(), clock=FakeClock()
    )
    guard_declared(router, violations)
    router.add_replica("r0", b0.submit)
    router.add_replica("r1", b1.submit)
    fc = FleetCollector(
        {"r0": reg0.render, "r1": reg1.render}, clock=FakeClock()
    )
    guard_declared(fc, violations)

    stop = threading.Event()
    errors: list = []

    def submitter(seed):
        try:
            for i in range(6):
                ids = [(seed * 13 + j) % 120 + 1 for j in range(3 + i % 3)]
                handle, _dec = router.dispatch(
                    ids, max_new_tokens=3, tenant=f"t{seed}"
                )
                toks = handle.result()
                assert isinstance(toks, list)
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    def scraper():
        while not stop.is_set():
            try:
                fc.scrape_once()
                fc.snapshot()
                reg0.percentile("serve_ttft_seconds", 0.95)
                j0.snapshot(limit=8)
                j1.snapshot(limit=8)
                router.snapshot()
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    threads = [
        threading.Thread(target=submitter, args=(s,), name=f"submit-{s}")
        for s in range(3)
    ] + [threading.Thread(target=scraper, name="scraper")]
    for t in threads:
        t.start()
    for t in threads[:3]:
        t.join(timeout=120)
        # A hung submitter must fail HERE (the cause), not as a
        # confusing journal-count miss downstream.
        assert not t.is_alive(), f"{t.name} hung past its join timeout"
    stop.set()
    threads[3].join(timeout=10)
    b0.stop()
    b1.stop()
    assert errors == [], errors
    assert violations == [], [str(v) for v in violations[:10]]
    # The hammer must have actually exercised the guarded paths.
    assert len(j0) + len(j1) >= 18
    assert router.metrics.counter(
        "serve_router_decisions_total", reason="affinity"
    ) + router.metrics.counter(
        "serve_router_decisions_total", reason="load"
    ) >= 18


def test_seeded_unguarded_write_is_detected(setup):
    """One deliberate unguarded write makes the stress contract fail —
    the detector detects (the acceptance criterion's negative half)."""
    model, params = setup
    violations: list = []
    router = FleetRouter(page_size=16, metrics=MetricsRegistry())
    guard_declared(router, violations)
    router.add_replica("r0")
    assert violations == []
    # The seeded race: touch the warm-chain table without the lock,
    # exactly what a future refactor might accidentally do.
    router._chains[b"h"] = "r0"
    assert violations, "unguarded write went undetected"
    assert violations[0].field == "_chains"
    assert violations[0].lock == "_lock"

    reg = MetricsRegistry()
    v2 = guard_declared(reg)
    reg.inc("ok_total")
    assert v2 == []
    reg._counters[("bad_total", ())] = 1.0  # bypasses the lock
    assert any(x.field == "_counters" for x in v2)


# -- satellite: the JAX recompile guard ----------------------------------------

def test_steady_state_decode_compiles_zero_executables(setup, xla_compiles):
    """After warmup, the continuous-batching decode loop must compile
    ZERO new XLA executables: admission buckets, decode dispatch, and
    retire/refill all reuse warm traces.  A silent static-shape
    regression (the exact hazard of ROADMAP item 3's kernel work) shows
    up here as a recompile, long before it shows up as a latency cliff."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        prompts = [[3, 7, 11], [2, 5, 9, 4]]

        def wave():
            handles = [
                b.submit(p, max_new_tokens=5) for p in prompts
            ]
            return [h.result() for h in handles]

        warm1 = wave()   # compiles: admission buckets + decode + retire
        wave()           # full admit→decode→retire→re-admit cycle, warm
        before = xla_compiles()
        steady1 = wave()
        steady2 = wave()
        after = xla_compiles()
        assert after == before, (
            f"steady-state decode compiled {after - before} new "
            "executable(s) — a static-shape regression"
        )
        # Determinism rides along: greedy decode, identical prompts.
        assert steady1 == warm1 and steady2 == warm1
    finally:
        b.stop()
