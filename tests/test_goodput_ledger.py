"""Goodput ledger & incident flight recorder (ISSUE 13): the wall-clock
partition is exhaustive and bit-exact under FakeClock, two scripted runs
serve byte-identical /debug/goodput bodies, a seeded chaos preemption
mid-fit walks GoodputDegraded through its full FSM with the incident
cross-linked to a trace id, straggler attribution names the seeded slow
host, and the checkpoint path mints its telemetry without perf_counter.
All advances are dyadic (2**-k) so float sums stay exact."""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from k8s_gpu_tpu.api.workload import WorkloadInterrupted
from k8s_gpu_tpu.utils.alerts import RuleEvaluator, default_rule_pack
from k8s_gpu_tpu.utils.clock import FakeClock, TickingFakeClock
from k8s_gpu_tpu.utils.faults import FaultPlan, global_faults
from k8s_gpu_tpu.utils.goodput import (
    SEGMENTS,
    GoodputLedger,
    attach_ledger,
    detach_ledger,
    goodput_snapshot,
    goodput_snapshot_from_exposition,
    record_incident,
)
from k8s_gpu_tpu.utils.metrics import MetricsRegistry
from k8s_gpu_tpu.utils.obs import MetricsServer, render_goodput
from k8s_gpu_tpu.utils.tracing import global_tracer


# -- the partition invariant -------------------------------------------------

def test_partition_exhaustive_and_exact():
    """sum(segments) + residual == elapsed EXACTLY — begins chain without
    gaps, end→begin leaves a residual, and the open segment's elapsed-
    so-far is folded into the snapshot."""
    clk = FakeClock()
    led = GoodputLedger(registry=MetricsRegistry(), clock=clk)
    led.begin("init")
    clk.advance(0.5)
    led.begin("compile")          # closes init at the same instant
    clk.advance(2.25)
    led.begin("step")
    clk.advance(0.125)
    led.end()                     # residual gap starts here
    clk.advance(0.0625)
    led.begin("step")
    clk.advance(0.25)             # left open: folded into snapshot
    snap = led.snapshot()
    total = sum(v["seconds"] for v in snap["segments"].values())
    assert total + snap["residual_s"] == snap["elapsed_s"]
    assert snap["elapsed_s"] == 3.1875
    assert snap["residual_s"] == 0.0625
    assert snap["open"] == "step"
    assert snap["segments"]["step"] == {
        "count": 2, "seconds": 0.375, "share": round(0.375 / 3.1875, 9),
    }
    assert snap["productive_s"] == 0.375


def test_unknown_segment_and_incident_kind_raise():
    led = GoodputLedger(registry=MetricsRegistry(), clock=FakeClock())
    with pytest.raises(ValueError, match="unknown goodput segment"):
        led.begin("lunch")
    with pytest.raises(ValueError, match="unknown incident kind"):
        led.incident("gremlins")
    assert "step" in SEGMENTS


def test_nonproductive_counter_feeds_per_segment():
    reg = MetricsRegistry()
    clk = FakeClock()
    led = GoodputLedger(registry=reg, clock=clk)
    led.begin("data_wait")
    clk.advance(1.5)
    led.begin("step")
    clk.advance(4.0)
    led.begin("checkpoint_save")
    clk.advance(0.5)
    led.end()
    assert reg.counter(
        "train_nonproductive_seconds_total", segment="data_wait"
    ) == 1.5
    assert reg.counter(
        "train_nonproductive_seconds_total", segment="checkpoint_save"
    ) == 0.5
    # productive time never lands in the nonproductive family
    assert not reg.counter(
        "train_nonproductive_seconds_total", segment="step"
    )
    assert reg.gauge("train_goodput_ratio") == pytest.approx(4.0 / 6.0)


# -- bit-identical /debug/goodput --------------------------------------------

def _scripted(clock):
    reg = MetricsRegistry()
    led = GoodputLedger(registry=reg, clock=clock, window_s=64.0)
    led.begin("init")
    clock.advance(0.5)
    led.begin("compile")
    clock.advance(2.0)
    led.begin("data_wait")
    clock.advance(0.0625)
    led.begin("step")
    clock.advance(0.25)
    led.end()
    clock.advance(0.125)
    led.incident(
        "preemption", detail="queued resource suspended",
        trace_id="feedfacefeedface", event="Warning/Restarting default/j",
    )
    led.begin("preempted")
    clock.advance(4.0)
    led.begin("checkpoint_restore")
    clock.advance(1.0)
    led.begin("step")
    clock.advance(0.25)
    led.end()
    led.heartbeat("host0", 2, 0.25)
    led.heartbeat("host1", 2, 0.5)
    return led, reg


def test_debug_goodput_endpoint_bit_identical_and_404():
    bodies = []
    for _ in range(2):
        led, reg = _scripted(FakeClock())
        srv = MetricsServer(registry=reg, goodput=led).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/goodput", timeout=5
            ) as r:
                bodies.append(r.read())
        finally:
            srv.stop()
    assert bodies[0] == bodies[1]
    snap = json.loads(bodies[0])
    total = sum(v["seconds"] for v in snap["segments"].values())
    assert total + snap["residual_s"] == snap["elapsed_s"]
    assert snap["incidents"][0]["trace_id"] == "feedfacefeedface"
    assert snap["straggler"]["host"] == "host1"
    assert "checkpoint" in snap
    # the renderer consumes the endpoint shape, identically each run
    views = [render_goodput(json.loads(b)) for b in bodies]
    assert views[0] == views[1]
    assert "TRAINING GOODPUT" in views[0]
    assert "preemption" in views[0]
    srv = MetricsServer(registry=MetricsRegistry()).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/goodput", timeout=5
            )
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_snapshot_from_exposition_reconstructs_offline_view():
    """The `obs goodput` offline path: nonproductive counters + incident
    counters survive the exposition round-trip; the ring itself does not
    (only counts), and the renderer says so."""
    led, reg = _scripted(FakeClock())
    snap = goodput_snapshot_from_exposition(reg.render())
    assert snap["segments"]["preempted"]["seconds"] == 4.0
    assert snap["segments"]["compile"]["seconds"] == 2.0
    assert snap["incident_counts"] == {"preemption": 1.0}
    assert snap["incidents"] == []
    assert snap["straggler"]["host"] == "host1"
    out = render_goodput(snap)
    assert "preemption" in out


# -- the operator cross-stamp hook -------------------------------------------

def test_record_incident_fans_out_to_attached_ledgers():
    led = GoodputLedger(registry=MetricsRegistry(), clock=FakeClock())
    try:
        record_incident("restart", detail="before attach")   # no-op
        attach_ledger(led)
        attach_ledger(led)                                   # idempotent
        record_incident(
            "eviction", detail="queued resource qr0 state=SUSPENDED",
            event="Warning/QueuedResourceDeleted default/pool",
        )
        incs = led.snapshot()["incidents"]
        assert [i["kind"] for i in incs] == ["eviction"]
        assert incs[0]["event"].startswith("Warning/QueuedResourceDeleted")
    finally:
        detach_ledger(None)
    record_incident("restart", detail="after detach")        # no-op again
    assert len(led.snapshot()["incidents"]) == 1


# -- straggler attribution ----------------------------------------------------

def test_straggler_attribution_names_seeded_slow_host():
    reg = MetricsRegistry()
    clk = FakeClock()
    led = GoodputLedger(registry=reg, clock=clk)
    led.heartbeat("host0", 1, 0.1)
    assert led.snapshot()["straggler"] is None      # needs a comparison set
    assert reg.gauge("train_step_skew_ratio") == 1.0
    for step in (2, 3, 4):
        led.heartbeat("host0", step, 0.1)
        led.heartbeat("host1", step, 0.5)
        led.heartbeat("host2", step, 0.125)
        clk.advance(0.5)
    snap = led.snapshot()
    assert snap["straggler"]["host"] == "host1"
    assert snap["straggler"]["skew_ratio"] > 1.5
    assert reg.gauge("train_step_skew_ratio") > 1.5
    assert reg.gauge("train_straggler_host", host="host1") > 0.0
    # the straggler heals: host1 speeds up, host0 degrades -> relabel
    for step in (5, 6, 7, 8, 9, 10):
        led.heartbeat("host0", step, 1.0)
        led.heartbeat("host1", step, 0.1)
        led.heartbeat("host2", step, 0.125)
    snap = led.snapshot()
    assert snap["straggler"]["host"] == "host0"
    assert reg.gauge("train_straggler_host", host="host1") is None
    assert reg.gauge("train_straggler_host", host="host0") > 0.0


# -- checkpoint telemetry -----------------------------------------------------

def _tiny_trainer(ledger=None):
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
    from k8s_gpu_tpu.parallel import MeshConfig
    from k8s_gpu_tpu.parallel.mesh import build_mesh
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    model = TransformerLM(TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, max_seq=16, use_flash=False))
    return Trainer(
        model, mesh=build_mesh(MeshConfig(dp=1), n_devices=1),
        train_config=TrainConfig(warmup_steps=1),
        peak_flops=1e12, ledger=ledger,
    )


def _batches(n=64):
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, (4, 17), dtype=np.int32)
    for _ in range(n):
        yield (toks[:, :-1], toks[:, 1:])


def test_checkpoint_save_restore_telemetry(tmp_path):
    from k8s_gpu_tpu.train.checkpoint import attach_to_trainer

    clk = TickingFakeClock()
    reg = MetricsRegistry()
    led = GoodputLedger(registry=reg, clock=clk)
    trainer = _tiny_trainer(led)
    trainer.init(jax.random.PRNGKey(0))
    ckpt, save, resume = attach_to_trainer(
        trainer, tmp_path / "ck", clock=clk, registry=reg
    )
    try:
        save(1)
        h = reg.histogram("train_checkpoint_seconds", op="save")
        assert h is not None and h.n == 1
        assert reg.gauge("train_checkpoint_bytes") > 0.0
        step = resume()
        assert step == 1
        h = reg.histogram("train_checkpoint_seconds", op="restore")
        assert h is not None and h.n == 1
        # the trainer's ledger recorded both as segments
        segs = led.snapshot()["segments"]
        assert segs["checkpoint_save"]["count"] == 1
        assert segs["checkpoint_save"]["seconds"] > 0.0
        assert segs["checkpoint_restore"]["count"] == 1
        # failure path: a raising save increments the counter and raises
        ckpt._mgr = _RaisingMgr()
        with pytest.raises(RuntimeError, match="disk full"):
            save(2)
        assert reg.counter(
            "train_checkpoint_failures_total", op="save"
        ) == 1.0
        # the /debug/goodput body assembles the checkpoint half
        snap = goodput_snapshot(led, reg)
        assert snap["checkpoint"]["ops"]["save"]["p95_s"] > 0.0
        assert snap["checkpoint"]["ops"]["save"]["failures"] == 1.0
        assert snap["checkpoint"]["last_bytes"] > 0.0
    finally:
        ckpt.close()


class _RaisingMgr:
    def save(self, *a, **k):
        raise RuntimeError("disk full")

    def wait_until_finished(self):
        pass

    def close(self):
        pass


# -- seeded chaos: preemption mid-fit walks the full FSM ----------------------

def test_preemption_chaos_goodput_fsm_and_recovery(tmp_path, xla_compiles):
    """The acceptance scenario end-to-end: a seeded `train.preempt` fault
    interrupts fit under a trace span; the ledger opens `preempted` and
    stamps the incident with the trace id; GoodputDegraded walks
    inactive→pending→firing; checkpoint restore + productive window
    recovers the ratio and resolves it; the partition stays exact and
    the resumed steps compile nothing new."""
    from k8s_gpu_tpu.train.checkpoint import attach_to_trainer

    clk = TickingFakeClock()
    reg = MetricsRegistry()
    led = GoodputLedger(registry=reg, clock=clk, window_s=8.0)
    trainer = _tiny_trainer(led)
    trainer.init(jax.random.PRNGKey(0))
    data = _batches()
    losses = trainer.fit(data, steps=2, log_every=1)
    assert len(losses) == 2
    snap = led.snapshot()
    assert snap["segments"]["compile"]["count"] >= 1
    assert snap["segments"]["step"]["count"] >= 1
    assert snap["segments"]["data_wait"]["count"] == 2
    compiles = xla_compiles()
    ckpt, save, resume = attach_to_trainer(
        trainer, tmp_path / "ck", clock=clk, registry=reg
    )
    try:
        save(2)
        # -- the incident: first fire of the armed site interrupts fit
        global_faults.arm("train.preempt", FaultPlan(flaky=1))
        try:
            with global_tracer.span("train.run", job="chaos"):
                with pytest.raises(WorkloadInterrupted):
                    trainer.fit(data, steps=2, log_every=1)
        finally:
            global_faults.disarm()
        snap = led.snapshot()
        assert snap["open"] == "preempted"
        inc = snap["incidents"][-1]
        assert inc["kind"] == "preemption"
        assert inc["trace_id"]                       # span cross-link
        assert reg.counter(
            "train_incidents_total", kind="preemption"
        ) == 1.0
        # -- the rule pack watches the decaying windowed ratio
        rules = [
            r for r in default_rule_pack(
                goodput_ratio=0.5, goodput_for_s=30.0
            )
            if getattr(r, "name", "") == "GoodputDegraded"
        ]
        assert len(rules) == 1
        ev = RuleEvaluator(rules, clock=clk, registry=reg, interval=10.0)
        ev.collectors.append(led.export_gauges)
        clk.advance(16.0)                            # outage in progress
        ev.evaluate_once()
        assert _states(ev) == {"GoodputDegraded": "pending"}
        assert led.goodput_ratio() < 1.0
        clk.advance(40.0)                            # held >= for_s
        ev.evaluate_once()
        assert _states(ev) == {"GoodputDegraded": "firing"}
        # -- recovery: restore closes `preempted`, steps refill the window
        step = resume()
        assert step == 2
        led.incident("resume", detail="restored step 2")
        losses = trainer.fit(data, steps=2, log_every=1)
        assert len(losses) == 2
        led.begin("step")
        clk.advance(6.0)                             # productive window
        led.end()
        ev.evaluate_once()
        assert _states(ev) == {}                     # resolved -> inactive
        assert [(t["from"], t["to"]) for t in ev.timeline] == [
            ("inactive", "pending"), ("pending", "firing"),
            ("firing", "resolved"),
        ]
        assert led.goodput_ratio() > 0.5
        # -- the partition never leaked a second
        snap = led.snapshot()
        total = sum(v["seconds"] for v in snap["segments"].values())
        assert total + snap["residual_s"] == snap["elapsed_s"]
        assert snap["segments"]["preempted"]["seconds"] >= 56.0
        # -- resumed steps reused the jitted step: zero new executables
        assert xla_compiles() == compiles
    finally:
        ckpt.close()


def _states(ev):
    return {a["alertname"]: a["state"] for a in ev.active_alerts()}
