"""Inference engine: KV-cache decode must agree with the full forward pass
(the classic prefill/decode parity check), plus sampling and EOS semantics.
The reference delegates inference to Ollama (智能风控解决方案.md:196); this
subsystem is its TPU-native replacement, so correctness is tested directly.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import InferenceEngine, SamplingConfig

TINY = TransformerConfig(
    vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
    d_ff=96, max_seq=48, use_flash=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def test_prefill_logits_match_forward(setup):
    model, params = setup
    eng = InferenceEngine(model)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 128)
    _, last = eng.prefill(params, toks)
    ref, _ = model.forward(params, toks)
    np.testing.assert_allclose(np.asarray(last), np.asarray(ref[:, -1]),
                               rtol=2e-4, atol=2e-4)


def test_decode_matches_teacher_forcing(setup):
    """Greedy generate with the cache must equal greedy re-running the full
    forward at every step (no cache)."""
    model, params = setup
    eng = InferenceEngine(model)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0, 128)
    out = eng.generate(params, prompt, max_new_tokens=6)
    # Reference: iterative full forward, argmax each step.
    seq = prompt
    ref_toks = []
    for _ in range(6):
        logits, _ = model.forward(params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        ref_toks.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    ref = jnp.stack(ref_toks, axis=1)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref))
    # eos_id=-1 never fires, so every row generates the full budget.
    assert out.lengths.tolist() == [6, 6]


def test_eos_masks_remaining_tokens(setup):
    model, params = setup
    eng = InferenceEngine(model)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 4), 0, 128)
    # Find what greedy emits first, then declare that token to be EOS: every
    # subsequent slot must be pad and length must be 0 (EOS itself unemitted).
    probe = eng.generate(params, prompt, max_new_tokens=4)
    eos = int(probe.tokens[0, 0])
    out = eng.generate(
        params, prompt, max_new_tokens=4,
        sampling=SamplingConfig(eos_id=eos, pad_id=0),
    )
    assert out.tokens[0].tolist() == [0, 0, 0, 0]
    assert int(out.lengths[0]) == 0


def test_temperature_sampling_is_seeded(setup):
    model, params = setup
    eng = InferenceEngine(model)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0, 128)
    s = SamplingConfig(temperature=1.0, top_k=8)
    a = eng.generate(params, prompt, max_new_tokens=5, sampling=s,
                     key=jax.random.PRNGKey(7))
    b = eng.generate(params, prompt, max_new_tokens=5, sampling=s,
                     key=jax.random.PRNGKey(7))
    c = eng.generate(params, prompt, max_new_tokens=5, sampling=s,
                     key=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a.tokens), np.asarray(b.tokens))
    assert a.tokens.shape == c.tokens.shape == (2, 5)


def test_moe_model_decodes(setup):
    cfg = dataclasses.replace(TINY, num_experts=4)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine(model)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0, 128)
    out = eng.generate(params, prompt, max_new_tokens=3)
    assert out.tokens.shape == (2, 3)
    assert bool(jnp.all((out.tokens >= 0) & (out.tokens < 128)))


def test_left_padded_bucket_matches_unpadded(setup):
    """Bucketed serving: left-padding + pad_left must not change greedy
    output (padding masked from attention, RoPE re-based)."""
    model, params = setup
    eng = InferenceEngine(model)
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 7), 0, 128)
    ref = eng.generate(params, prompt, max_new_tokens=5)
    padded = jnp.concatenate(
        [jnp.zeros((2, 9), jnp.int32), prompt], axis=1
    )
    out = eng.generate(params, padded, max_new_tokens=5, pad_left=9)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))


def test_left_padded_bucket_matches_unpadded_moe(setup):
    """MoE path: pads must not consume expert capacity or perturb routing.
    capacity_factor is set high so capping never binds (when it binds, drop
    patterns may differ between bucket sizes — documented in _moe_mlp)."""
    cfg = dataclasses.replace(TINY, num_experts=4, capacity_factor=8.0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine(model)
    prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 6), 0, 128)
    ref = eng.generate(params, prompt, max_new_tokens=4)
    padded = jnp.concatenate([jnp.zeros((2, 10), jnp.int32), prompt], axis=1)
    out = eng.generate(params, padded, max_new_tokens=4, pad_left=10)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))


def test_decode_step_accepts_python_int_pos(setup):
    model, params = setup
    eng = InferenceEngine(model)
    toks = jax.random.randint(jax.random.PRNGKey(7), (2, 4), 0, 128)
    cache, last = eng.prefill(params, toks)
    nxt = jnp.argmax(last, axis=-1)
    cache, logits = eng.decode_step(params, cache, 4, nxt)
    assert logits.shape == (2, 128)


def test_prompt_budget_enforced(setup):
    model, params = setup
    eng = InferenceEngine(model, max_seq=16)
    prompt = jnp.zeros((1, 10), jnp.int32)
    with pytest.raises(ValueError):
        eng.generate(params, prompt, max_new_tokens=10)


# -- nucleus (top-p) sampling ------------------------------------------------

def test_top_p_warp_keeps_exact_nucleus():
    import numpy as np

    from k8s_gpu_tpu.serve import InferenceEngine, SamplingConfig

    # probs ~ [0.5, 0.25, 0.15, 0.1] after temperature 1
    logits = jnp.log(jnp.asarray([0.5, 0.25, 0.15, 0.10]))
    warped = InferenceEngine.warp_logits(
        logits, SamplingConfig(temperature=1.0, top_p=0.7)
    )
    kept = np.asarray(jnp.isfinite(warped))
    # mass above token0 = 0 < .7 keep; above token1 = .5 < .7 keep;
    # above token2 = .75 >= .7 drop; token3 drop
    assert kept.tolist() == [True, True, False, False]
    # top_p=0.4: only the argmax survives (nucleus never empty)
    warped = InferenceEngine.warp_logits(
        logits, SamplingConfig(temperature=1.0, top_p=0.4)
    )
    assert np.asarray(jnp.isfinite(warped)).tolist() == [
        True, False, False, False
    ]
    # off values are no-ops
    for p in (0.0, 1.0):
        w = InferenceEngine.warp_logits(
            logits, SamplingConfig(temperature=1.0, top_p=p)
        )
        assert bool(jnp.isfinite(w).all())


def test_top_p_sampling_support_is_nucleus_only():
    import numpy as np

    from k8s_gpu_tpu.serve.engine import InferenceEngine, SamplingConfig

    logits = jnp.log(jnp.asarray([0.5, 0.25, 0.15, 0.10]))
    samp = SamplingConfig(temperature=1.0, top_p=0.7)
    draws = jax.vmap(
        lambda k: InferenceEngine._sample(logits, k, samp)
    )(jax.random.split(jax.random.PRNGKey(0), 2000))
    assert set(np.asarray(draws).tolist()) == {0, 1}


