"""Batcher split (ISSUE 20): scheduler/allocator/executor compose back
into the SAME batcher.

The ~3.4k-line serve/batcher.py monolith split into serve/scheduler.py
(admission, queueing, round policy), serve/allocator.py (BlockPool
interaction, page planning, migration payloads) and serve/executor.py
(prefill and decode device programs); ``ContinuousBatcher`` remains as
the thin composition owning all mutable state.  Contract: the split is
a pure relocation — greedy, sampled, speculative and paged-prefix
streams are byte-identical through the composed class — and the
prefill-only executor role never emits a decode token.
"""

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher
from k8s_gpu_tpu.serve.allocator import AllocatorMixin
from k8s_gpu_tpu.serve.executor import ExecutorMixin
from k8s_gpu_tpu.serve.scheduler import SchedulerMixin

CFG = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq=128, use_flash=False, dtype=jnp.float32,
)
MODEL = TransformerLM(CFG)
PARAMS = MODEL.init(jax.random.PRNGKey(0))

PROMPTS = [
    [3, 5, 7],
    list(range(2, 24)),        # crosses a 16-token page
    [11, 13],
    list(range(40, 75)),       # multi-page
]


def _run(batcher_kwargs, reqs):
    b = ContinuousBatcher(MODEL, PARAMS, slots=4, **batcher_kwargs).start()
    try:
        handles = [b.submit(ids, **kw) for ids, kw in reqs]
        return [h.result() for h in handles]
    finally:
        b.stop()


def _oracle(ids, n):
    seq = jnp.asarray(ids, jnp.int32)[None, :]
    out = []
    for _ in range(n):
        logits, _ = MODEL.forward(PARAMS, seq)
        nxt = int(jnp.argmax(logits[:, -1], axis=-1)[0])
        out.append(nxt)
        seq = jnp.concatenate(
            [seq, jnp.asarray([[nxt]], jnp.int32)], axis=1
        )
    return out


# -- composition is structural, not copied code ------------------------------

def test_compose_module_boundaries():
    """Each plane's methods actually LIVE in their module: the split is
    real (a regression that quietly reintroduces a monolith method on
    the composition class fails here)."""
    assert issubclass(ContinuousBatcher, SchedulerMixin)
    assert issubclass(ContinuousBatcher, AllocatorMixin)
    assert issubclass(ContinuousBatcher, ExecutorMixin)
    sched = "k8s_gpu_tpu.serve.scheduler"
    alloc = "k8s_gpu_tpu.serve.allocator"
    execu = "k8s_gpu_tpu.serve.executor"
    for name, mod in [
        ("submit", sched), ("_loop", sched), ("_dispatch_round", sched),
        ("run_quiesced", sched), ("_free_slot", sched),
        ("_paged_plan", alloc), ("migrate_export", alloc),
        ("migrate_import", alloc), ("_blocks_needed", alloc),
        ("_round_dev", execu), ("_admit_dev", execu),
        ("_guard_decode", execu), ("_spec_accept", execu),
    ]:
        assert getattr(ContinuousBatcher, name).__module__ == mod, name


# -- stream parity through the composed class --------------------------------

def test_greedy_streams_match_oracle():
    got = _run({}, [(p, dict(max_new_tokens=10)) for p in PROMPTS])
    for p, toks in zip(PROMPTS, got):
        assert toks == _oracle(p, 10), p


def test_sampled_streams_two_run_identical():
    reqs = [
        (p, dict(max_new_tokens=8, temperature=0.8, seed=41 + i))
        for i, p in enumerate(PROMPTS)
    ]
    assert _run({}, reqs) == _run({}, reqs)


def test_spec_ngram_matches_plain_greedy():
    reqs = [(p, dict(max_new_tokens=10)) for p in PROMPTS]
    plain = _run({}, reqs)
    spec = _run({"draft": "ngram", "spec_k": 3}, reqs)
    assert spec == plain


def test_paged_prefix_streams_match_dense():
    """Paged admission with a shared warm prefix (the second request
    acquires the first's registered chain) is still byte-identical to
    the dense batcher."""
    base = list(range(2, 36))
    reqs = [
        (base + [77], dict(max_new_tokens=8)),
        (base + [78], dict(max_new_tokens=8)),
    ]
    dense = _run({}, reqs)
    paged = _run({"paged_blocks": 64, "page_size": 16}, reqs)
    assert paged == dense


# -- prefill-only executor role ----------------------------------------------

def test_prefill_role_emits_no_decode_tokens():
    """A prefill-role batcher retires every request at admission: the
    stream is exactly the ONE admission-sampled token (greedy: the
    oracle's first token) regardless of the requested budget, and no
    decode round ever ran."""
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=2, paged_blocks=64, page_size=16,
        role="prefill",
    ).start()
    try:
        ids = list(range(2, 24))
        got = b.submit(ids, max_new_tokens=16).result()
        assert got == _oracle(ids, 1)
        assert b.steps_taken == 0, "a decode round ran on a prefill worker"
    finally:
        b.stop()


def test_prefill_role_guard_refuses_decode_dispatch():
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=2, paged_blocks=64, page_size=16,
        role="prefill",
    )
    with pytest.raises(RuntimeError, match="prefill-only"):
        b._guard_decode()
    # The decode/both roles never trip the guard.
    ContinuousBatcher(MODEL, PARAMS, slots=2)._guard_decode()


def test_unknown_role_rejected():
    with pytest.raises(ValueError, match="role"):
        ContinuousBatcher(MODEL, PARAMS, slots=2, role="verify")
