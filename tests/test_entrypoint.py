"""Operator image entrypoint (platform/entrypoint.py): the process the
gohai-api / gohai-controller / devenv-controller Deployments run
(reference README.md:298-302 deploy flow, GPU调度平台搭建.md:853-865)."""

import json
import urllib.request

import pytest

from k8s_gpu_tpu.controller.kubefake import FakeKube
from k8s_gpu_tpu.platform.entrypoint import build_operator


def test_api_role_serves_healthz(tmp_path, monkeypatch):
    monkeypatch.setenv("GOHAI_ASSET_DIR", str(tmp_path / "assets"))
    parts = build_operator("api", kube=FakeKube(), port=0)
    parts["start"]()
    try:
        port = parts["server"].port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ) as r:
            assert json.loads(r.read())["ok"] is True
    finally:
        parts["stop"]()


def test_controller_role_reconciles(tmp_path, monkeypatch):
    """The controller role must run the same reconciler set the CLI's
    local platform does — a TpuPodSlice applied to its kube goes Ready."""
    from k8s_gpu_tpu.api import TpuPodSlice

    monkeypatch.setenv("GOHAI_ASSET_DIR", str(tmp_path / "assets"))
    kube = FakeKube()
    parts = build_operator("controller", kube=kube)
    assert parts["mgr"] is not None
    parts["start"]()
    try:
        ps = TpuPodSlice()
        ps.metadata.name = "demo"
        ps.spec.accelerator_type = "v4-8"
        kube.create(ps)
        import time

        deadline = time.time() + 30
        while time.time() < deadline:
            cur = kube.get("TpuPodSlice", "demo")
            if cur.status.phase == "Ready":
                break
            time.sleep(0.2)
        assert kube.get("TpuPodSlice", "demo").status.phase == "Ready"
    finally:
        parts["stop"]()


def test_devenv_role_has_gateway(tmp_path, monkeypatch):
    monkeypatch.setenv("GOHAI_ASSET_DIR", str(tmp_path / "assets"))
    parts = build_operator("devenv-controller", kube=FakeKube(), port=0)
    parts["start"]()
    try:
        assert parts["gateway"].port > 0
        # The gateway carries an asset store: `devenv put` works in-cluster.
        assert parts["gateway"].assets is not None
    finally:
        parts["stop"]()


def test_state_dir_persists_across_restart(tmp_path, monkeypatch):
    """GOHAI_STATE_DIR: a controller pod restart resumes from pickled
    state instead of starting empty."""
    from k8s_gpu_tpu.api.core import Secret

    monkeypatch.setenv("GOHAI_ASSET_DIR", str(tmp_path / "assets"))
    sd = str(tmp_path / "state")
    parts = build_operator("controller", state_dir=sd)
    parts["start"]()
    sec = Secret()
    sec.metadata.name = "team-a-token"
    sec.data["k"] = "v"
    parts["kube"].create(sec)
    parts["stop"]()
    parts2 = build_operator("controller", state_dir=sd)
    assert parts2["kube"].try_get("Secret", "team-a-token") is not None


def test_unknown_role_rejected():
    with pytest.raises(ValueError, match="GOHAI_ROLE"):
        build_operator("apiserver")
