"""Observability (C32): /metrics endpoint, log aggregation, GC controller."""

import json
import logging
import time
import urllib.request

import pytest

from k8s_gpu_tpu.api import Event, PersistentVolumeClaim, Pod, TrainJob
from k8s_gpu_tpu.controller import FakeKube
from k8s_gpu_tpu.controller.manager import Request
from k8s_gpu_tpu.operators import ResourceGC
from k8s_gpu_tpu.operators.gc import GC_LABEL
from k8s_gpu_tpu.utils import (
    LogStore,
    LogStoreHandler,
    MetricsRegistry,
    MetricsServer,
)


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read().decode()


# -- metrics endpoint -------------------------------------------------------

def test_histogram_exact_percentiles():
    """percentile() is exact over the raw reservoir (latency evidence
    must not read as bucket upper bounds), and the reservoir rolls over
    instead of growing unbounded."""
    from k8s_gpu_tpu.utils.metrics import Histogram

    h = Histogram()
    for i in range(100):
        h.observe(i / 100.0)
    assert h.percentile(0.5) == pytest.approx(0.50)
    assert h.percentile(0.95) == pytest.approx(0.95)
    # rollover keeps the most recent window, bounded
    from collections import deque

    h2 = Histogram(raw=deque(maxlen=8))
    for v in range(100):
        h2.observe(float(v))
    assert len(h2.raw) == 8
    assert h2.percentile(0.0) >= 92.0  # only recent samples remain


def test_metrics_server_endpoints():
    reg = MetricsRegistry()
    reg.inc("reconcile_total", kind="TpuPodSlice", result="ok")
    reg.observe("reconcile_duration_seconds", 0.02, kind="TpuPodSlice")
    ready = {"ok": False}
    srv = MetricsServer(reg, ready_check=lambda: ready["ok"]).start()
    try:
        code, body = _get(srv.port, "/metrics")
        assert code == 200
        assert 'reconcile_total{kind="TpuPodSlice",result="ok"} 1.0' in body
        # Histogram exposition: cumulative buckets + count + sum.
        assert 'le="0.05"' in body
        assert 'le="+Inf"' in body
        assert "reconcile_duration_seconds_count" in body
        code, body = _get(srv.port, "/healthz")
        assert code == 200 and json.loads(body)["ok"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/readyz")
        assert ei.value.code == 503
        ready["ok"] = True
        code, body = _get(srv.port, "/readyz")
        assert code == 200
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.port, "/nope")
    finally:
        srv.stop()


# -- log store --------------------------------------------------------------

def test_logstore_selector_query():
    store = LogStore()
    store.push({"job": "j1", "pod": "w0"}, "step 1 loss 2.3", ts=1.0)
    store.push({"job": "j1", "pod": "w1"}, "step 1 loss 2.4", ts=2.0)
    store.push({"job": "j2", "pod": "w0"}, "other", ts=3.0)
    got = store.query({"job": "j1"})
    assert [e.line for e in got] == ["step 1 loss 2.3", "step 1 loss 2.4"]
    assert store.query({"job": "j1", "pod": "w1"})[0].line.endswith("2.4")
    assert store.query(contains="loss", since=1.5)[0].line.endswith("2.4")
    assert len(store.streams()) == 3


def test_logstore_bounded():
    store = LogStore(max_lines_per_stream=5, max_streams=2)
    for i in range(10):
        store.push({"s": "a"}, f"line {i}", ts=float(i))
    assert [e.line for e in store.query({"s": "a"})] == [
        f"line {i}" for i in range(5, 10)
    ]
    store.push({"s": "b"}, "b0", ts=20.0)
    store.push({"s": "c"}, "c0", ts=21.0)  # evicts quietest stream (a)
    assert store.dropped_streams == 1
    assert len(store.streams()) == 2
    assert store.query({"s": "c"})


def test_logging_handler_ships_records():
    store = LogStore()
    handler = LogStoreHandler(store, {"component": "controller"})
    lg = logging.getLogger("test.obs.ship")
    lg.addHandler(handler)
    lg.setLevel(logging.INFO)
    try:
        lg.info("reconciled %s", "demo")
        lg.warning("requeue")
    finally:
        lg.removeHandler(handler)
    assert [e.line for e in store.query({"level": "info"})] == [
        "reconciled demo"
    ]
    got = store.query({"logger": "test.obs.ship", "component": "controller"})
    assert len(got) == 2


# -- GC ---------------------------------------------------------------------

def _finished_job(kube, name, t, phase="Succeeded"):
    j = TrainJob()
    j.metadata.name = name
    created = kube.create(j)
    created.status.phase = phase
    created.status.completion_time = t
    kube.update_status(created)


def test_gc_keeps_last_n_jobs(kube: FakeKube):
    for i in range(8):
        _finished_job(kube, f"job-{i}", t=float(i))
    live = TrainJob()
    live.metadata.name = "running"
    kube.create(live)
    ResourceGC(kube, keep_finished=3).reconcile(Request("default", "job-0"))
    names = {j.metadata.name for j in kube.list("TrainJob")}
    # Newest 3 finished jobs + the unfinished one survive.
    assert names == {"job-5", "job-6", "job-7", "running"}


def test_gc_expires_old_events(kube: FakeKube):
    old = Event()
    old.metadata.name = "old-ev"
    kube.create(old)
    # Deterministic wall clock: "now" is 2h after the event was stamped.
    frozen_now = time.time() + 7200
    gc = ResourceGC(kube, event_ttl_s=3600, now_fn=lambda: frozen_now)
    fresh = Event()
    fresh.metadata.name = "fresh-ev"
    created = kube.create(fresh)
    # fresh-ev is "30 min old" at frozen_now: nudge its stamp forward.
    snap = kube.dump()
    for obj in snap["store"].values():
        if obj.metadata.name == "fresh-ev":
            obj.metadata.creation_timestamp = frozen_now - 1800
    kube.load(snap)
    gc.reconcile(Request("default", "x"))
    names = {e.metadata.name for e in kube.list("Event")}
    assert names == {"fresh-ev"}


def test_gc_pvc_opt_in_and_in_use(kube: FakeKube):
    keep = PersistentVolumeClaim()
    keep.metadata.name = "workspace-pvc"  # no GC label → never collected
    kube.create(keep)
    tagged = PersistentVolumeClaim()
    tagged.metadata.name = "scratch"
    tagged.metadata.labels[GC_LABEL] = "true"
    kube.create(tagged)
    used = PersistentVolumeClaim()
    used.metadata.name = "scratch-used"
    used.metadata.labels[GC_LABEL] = "true"
    kube.create(used)
    p = Pod()
    p.metadata.name = "p1"
    p.phase = "Running"
    p.mounts = {"/scratch": "pvc:scratch-used"}
    kube.create(p)
    ResourceGC(kube).reconcile(Request("default", "x"))
    names = {c.metadata.name for c in kube.list("PersistentVolumeClaim")}
    assert names == {"workspace-pvc", "scratch-used"}


def test_gc_debounce_collapses_event_storm(kube: FakeKube):
    """Startup watch replay delivers one event per object; only one global
    sweep should run per interval (review finding: N redundant sweeps)."""
    frozen = [1000.0]
    gc = ResourceGC(kube, keep_finished=0, now_fn=lambda: frozen[0])
    for i in range(4):
        _finished_job(kube, f"j{i}", t=float(i))
    gc.reconcile(Request("default", "j0"))
    assert kube.list("TrainJob") == []
    # Second trigger inside the debounce window: no sweep (new finished job
    # survives until the interval elapses).
    _finished_job(kube, "late", t=9.0)
    gc.reconcile(Request("default", "late"))
    assert {j.metadata.name for j in kube.list("TrainJob")} == {"late"}
    frozen[0] += gc.min_sweep_interval + 1
    gc.reconcile(Request("default", "late"))
    assert kube.list("TrainJob") == []


def test_gc_skips_already_deleting_jobs(kube: FakeKube):
    """Jobs held by a finalizer must not be re-deleted/re-counted."""
    from k8s_gpu_tpu.utils.metrics import MetricsRegistry

    _finished_job(kube, "old", t=1.0)
    held = kube.get("TrainJob", "old")
    held.metadata.finalizers.append("test/hold")
    kube.update(held)
    m = MetricsRegistry()
    gc = ResourceGC(kube, keep_finished=0, metrics=m, min_sweep_interval=0.0)
    gc.reconcile(Request("default", "old"))
    gc.reconcile(Request("default", "old"))
    # Deleted once; second sweep sees deletion_timestamp and skips.
    assert m.counter("gc_deleted_total", kind="TrainJob") == 1
