"""DevEnv lifecycle: SSH-key Secret, workspace PVC persistence, pod render,
key rotation, teardown (C21-C24; GPU调度平台搭建.md:314-419)."""

import pytest

from k8s_gpu_tpu.api import DevEnv
from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.operators import DevEnvReconciler
from k8s_gpu_tpu.operators.devenv import MAMBARC

PUBKEY = "ssh-ed25519 AAAAC3Nz alice@laptop"


@pytest.fixture
def harness(kube: FakeKube, clock):
    mgr = Manager(kube, clock=clock)
    mgr.register("DevEnv", DevEnvReconciler(kube))
    mgr.start()
    yield kube, mgr
    mgr.stop()


def make_env(kube, name="env-alice", user="alice", key=PUBKEY, ns="default"):
    env = DevEnv()
    env.metadata.name = name
    env.metadata.namespace = ns
    env.spec.username = user
    env.spec.ssh_public_key = key
    return kube.create(env)


def wait_ready(kube, mgr, name="env-alice", ns="default"):
    assert mgr.wait_idle(
        predicate=lambda: kube.get("DevEnv", name, ns).status.phase == "Ready"
    )
    return kube.get("DevEnv", name, ns)


def test_validation():
    from k8s_gpu_tpu.api import ValidationError

    env = DevEnv()
    env.metadata.name = "e"
    with pytest.raises(ValidationError, match="username"):
        env.validate()
    env.spec.username = "alice"
    with pytest.raises(ValidationError, match="sshPublicKey"):
        env.validate()


def test_devenv_materializes(harness):
    kube, mgr = harness
    make_env(kube)
    env = wait_ready(kube, mgr)
    # Secret carries the key and the micromamba persistence config (C23).
    s = kube.get("Secret", "user-ssh-alice")
    assert s.data["authorized_keys"] == PUBKEY
    assert "/workspace/.conda/envs" in s.data["mambarc"]
    assert s.data["mambarc"] == MAMBARC
    # Workspace PVC exists, RWX (C12 parity).
    pvc = kube.get("PersistentVolumeClaim", "workspace-pvc")
    assert pvc.access_modes == ["ReadWriteMany"]
    # Pod renders the reference template (C22): sshd PID 1 + both mounts.
    pod = kube.get("Pod", "devenv-alice")
    assert pod.command.startswith("/usr/sbin/sshd")
    assert pod.mounts["/workspace"] == "pvc:workspace-pvc"
    assert pod.mounts["/root/.ssh"] == "secret:user-ssh-alice"
    assert pod.phase == "Running"
    # Status surfaces the SSH endpoint (C24).
    assert env.status.ssh_endpoint.endswith(":2022")
    assert env.status.pod_name == "devenv-alice"


def test_key_rotation_updates_secret(harness):
    kube, mgr = harness
    make_env(kube)
    wait_ready(kube, mgr)
    env = kube.get("DevEnv", "env-alice")
    env.spec.ssh_public_key = "ssh-ed25519 NEWKEY alice@desktop"
    kube.update(env)
    assert mgr.wait_idle(
        predicate=lambda: kube.get("Secret", "user-ssh-alice").data[
            "authorized_keys"
        ].startswith("ssh-ed25519 NEWKEY")
    )
    events = [e for e in kube.list("Event") if e.reason == "SSHKeyRotated"]
    assert events


def test_teardown_keeps_pvc(harness):
    """Deleting the devenv removes pod + secret but the workspace PVC (and
    the conda envs inside it) survives for the next devenv (:374-383)."""
    kube, mgr = harness
    make_env(kube)
    wait_ready(kube, mgr)
    kube.delete("DevEnv", "env-alice")
    assert mgr.wait_idle(
        predicate=lambda: kube.try_get("DevEnv", "env-alice") is None
    )
    assert kube.try_get("Pod", "devenv-alice") is None
    assert kube.try_get("Secret", "user-ssh-alice") is None
    assert kube.get("PersistentVolumeClaim", "workspace-pvc") is not None
    # Recreation binds the same claim.
    make_env(kube, key="ssh-ed25519 BBBB alice@new")
    env = wait_ready(kube, mgr)
    assert env.status.phase == "Ready"


def test_two_users_share_workspace_pvc(harness):
    kube, mgr = harness
    make_env(kube, name="env-alice", user="alice")
    make_env(kube, name="env-bob", user="bob",
             key="ssh-ed25519 CCCC bob@box")
    wait_ready(kube, mgr, "env-alice")
    wait_ready(kube, mgr, "env-bob")
    assert len(kube.list("PersistentVolumeClaim")) == 1
    assert {p.metadata.name for p in kube.list("Pod")} == {
        "devenv-alice", "devenv-bob"
    }


def test_duplicate_username_rejected(harness):
    """A second DevEnv claiming an already-owned username must fail instead
    of overwriting the first user's key and sharing its pod."""
    kube, mgr = harness
    make_env(kube, name="env-a", user="ada")
    assert mgr.wait_idle(
        predicate=lambda: kube.get("DevEnv", "env-a").status.phase == "Ready"
    )
    make_env(kube, name="env-b", user="ada", key="ssh-ed25519 EVIL other")
    assert mgr.wait_idle(
        predicate=lambda: kube.get("DevEnv", "env-b").status.phase == "Failed"
    )
    b = kube.get("DevEnv", "env-b")
    assert "already claimed" in b.status.message
    # The original key was not clobbered.
    assert kube.get("Secret", "user-ssh-ada").data["authorized_keys"] == PUBKEY


def test_deleting_failed_duplicate_preserves_owner(harness):
    """Deleting the losing duplicate must not tear down the winner's
    pod/secret (teardown honors the ownership label)."""
    kube, mgr = harness
    make_env(kube, name="env-a", user="ada")
    assert mgr.wait_idle(
        predicate=lambda: kube.get("DevEnv", "env-a").status.phase == "Ready"
    )
    make_env(kube, name="env-b", user="ada", key="ssh-ed25519 EVIL other")
    assert mgr.wait_idle(
        predicate=lambda: kube.get("DevEnv", "env-b").status.phase == "Failed"
    )
    kube.delete("DevEnv", "env-b")
    assert mgr.wait_idle(
        predicate=lambda: kube.try_get("DevEnv", "env-b") is None
    )
    assert kube.get("Pod", "devenv-ada") is not None
    assert kube.get("Secret", "user-ssh-ada").data["authorized_keys"] == PUBKEY


def test_devenv_with_chips_requests_tpu(harness):
    """A chip-requesting devenv gets a real carve-out (scheduling/sharing.py)
    once a TPU host exists — and stays Pending without capacity."""
    from k8s_gpu_tpu.api.core import Node

    kube, mgr = harness
    env = DevEnv()
    env.metadata.name = "env-debug"
    env.spec.username = "alice"
    env.spec.ssh_public_key = PUBKEY
    env.spec.tpu_chips = 4
    kube.create(env)
    assert mgr.wait_idle(
        predicate=lambda: kube.get("DevEnv", "env-debug").status.phase
        == "Pending"
    )
    n = Node()
    n.metadata.name = "tpu-host"
    n.capacity = {"google.com/tpu": 4}
    n.allocatable = {"google.com/tpu": 4}
    n.ready = True
    kube.create(n)
    # Wake the controller (spec touch): the retry is requeue_after=15s on a
    # FakeClock, so advance past it instead of waiting wall-clock.
    mgr.clock.advance(16)
    wait_ready(kube, mgr, "env-debug")
    pod = kube.get("Pod", "devenv-alice")
    assert pod.requests["google.com/tpu"] == 4
    assert pod.env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
    assert pod.node_name == "tpu-host"
