"""psum smoke job — the BASELINE acceptance workload, CPU-simulated."""

from k8s_gpu_tpu.parallel import MeshConfig, build_mesh, psum_smoke


def test_psum_smoke_flat_mesh():
    out = psum_smoke()
    assert out["ok"], out
    assert out["n_devices"] == 8
    assert out["result"] == sum(range(8))


def test_psum_smoke_training_mesh():
    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2))
    out = psum_smoke(mesh)
    assert out["ok"], out
