"""sshwire.py against independently generated RFC wire vectors.

tests/fixtures/ssh2/vectors.json was produced by make_fixtures.py — a
second, from-scratch implementation of the SSH-2 encodings written
against the RFC text and importing nothing from this package.  Matching
byte-for-byte here means two independent RFC readings converge on the
same wire bytes: the interop evidence the r4 verdict asked for (the
self-against-self tests in test_ssh2.py cannot catch a shared
misreading; these can).
"""

import io
import json
from pathlib import Path

import pytest

# The wire-vector replay signs with real ed25519 keys; without the
# optional 'cryptography' package the whole module skips by name
# instead of failing collection.
pytest.importorskip(
    "cryptography",
    reason="ssh gateway tests need the optional 'cryptography' package",
)
from cryptography.hazmat.primitives.asymmetric.ed25519 import (  # noqa: E402
    Ed25519PrivateKey,
)

from k8s_gpu_tpu.platform import sshwire as w

VEC = json.loads(
    (Path(__file__).parent / "fixtures" / "ssh2" / "vectors.json").read_text()
)
INP = VEC["inputs"]
EXP = VEC["expected"]


def _key(seed_hex: str) -> Ed25519PrivateKey:
    return Ed25519PrivateKey.from_private_bytes(bytes.fromhex(seed_hex))


def test_ed25519_blob_matches_vector():
    blob = w.ed25519_blob(_key(INP["host_seed"]).public_key())
    assert blob.hex() == EXP["host_key_blob"]
    blob = w.ed25519_blob(_key(INP["user_seed"]).public_key())
    assert blob.hex() == EXP["user_key_blob"]


def test_authorized_keys_line_matches_vector():
    line = w.authorized_key_line(_key(INP["user_seed"]), "ada@fixture")
    assert line == EXP["authorized_keys_line"]
    assert w.parse_authorized_key(line).hex() == EXP["user_key_blob"]


def test_kexinit_payload_matches_vector():
    payload = w.kexinit_payload(bytes.fromhex(INP["cookie"]))
    assert payload.hex() == EXP["kexinit_payload"]
    w.check_kexinit(payload)  # and our own checker accepts it


def test_exchange_hash_matches_vector():
    i = w.kexinit_payload(bytes.fromhex(INP["cookie"]))
    H = w.exchange_hash(
        INP["v_c"].encode(), INP["v_s"].encode(), i, i,
        bytes.fromhex(EXP["host_key_blob"]),
        bytes.fromhex(INP["q_c"]), bytes.fromhex(INP["q_s"]), int(INP["K"]),
    )
    assert H.hex() == EXP["exchange_hash"]


def test_key_derivation_matches_vector():
    keys = w.derive_keys(
        int(INP["K"]), bytes.fromhex(EXP["exchange_hash"]),
        bytes.fromhex(INP["session_id"]),
    )
    for name in ("iv_c2s", "iv_s2c", "key_c2s", "key_s2c",
                 "mac_c2s", "mac_s2c"):
        assert keys[name].hex() == EXP[name], name


def test_userauth_sign_blob_matches_vector():
    blob = w.userauth_sign_blob(
        bytes.fromhex(INP["session_id"]), INP["username"],
        bytes.fromhex(EXP["user_key_blob"]),
    )
    assert blob.hex() == EXP["userauth_sign_blob"]


def _crypto_keys() -> dict:
    return {k: bytes.fromhex(EXP[k])
            for k in ("iv_c2s", "iv_s2c", "key_c2s", "key_s2c",
                      "mac_c2s", "mac_s2c")}


def test_encrypted_packet_bytes_match_vector(monkeypatch):
    """Client-side send of the fixture payload at the fixture seqno must
    produce the independently computed ciphertext+MAC byte-for-byte
    (padding pinned to the fixture's 0xAA fill)."""
    monkeypatch.setattr(
        w.os, "urandom", lambda n: bytes([INP["pad_byte"]]) * n
    )
    out = io.BytesIO()
    conn = w.PacketConn(io.BytesIO(), out, server=False)
    conn.enable_crypto(_crypto_keys())
    conn.seq_out = INP["seq"]
    conn.send(bytes.fromhex(INP["payload"]))
    assert out.getvalue().hex() == EXP["encrypted_packet_with_mac"]


def test_server_decrypts_and_verifies_fixture_packet():
    """The server side must decrypt + MAC-verify the independently
    encrypted packet and recover the exact payload — and reject it
    after one flipped ciphertext bit."""
    raw = bytes.fromhex(EXP["encrypted_packet_with_mac"])
    conn = w.PacketConn(io.BytesIO(raw), io.BytesIO(), server=True)
    conn.enable_crypto(_crypto_keys())
    conn.seq_in = INP["seq"]
    assert conn.recv().hex() == INP["payload"]

    # flip one bit mid-payload (byte 0 would corrupt the length field
    # and fail earlier, on the size guard — also fail-closed)
    tampered = raw[:8] + bytes([raw[8] ^ 0x01]) + raw[9:]
    conn = w.PacketConn(io.BytesIO(tampered), io.BytesIO(), server=True)
    conn.enable_crypto(_crypto_keys())
    conn.seq_in = INP["seq"]
    with pytest.raises(w.SshError, match="MAC"):
        conn.recv()


def test_wrong_sequence_number_fails_mac():
    """seq is MACed but not transmitted (RFC 4253 §6.4) — a desynced
    counter must fail verification, not silently pass."""
    raw = bytes.fromhex(EXP["encrypted_packet_with_mac"])
    conn = w.PacketConn(io.BytesIO(raw), io.BytesIO(), server=True)
    conn.enable_crypto(_crypto_keys())
    conn.seq_in = INP["seq"] + 1
    with pytest.raises(w.SshError, match="MAC"):
        conn.recv()
