"""Tenancy: Spaces, RBAC enforcement, ResourceQuota/LimitRange (C15)."""

import pytest

from k8s_gpu_tpu.api import (
    LimitRange,
    Pod,
    ResourceQuota,
    TrainJob,
    ValidationError,
)
from k8s_gpu_tpu.auth import (
    AuthorizedKube,
    Forbidden,
    Identity,
    QuotaEnforcer,
    QuotaReconciler,
    SpaceManager,
)
from k8s_gpu_tpu.controller.kubefake import FakeKube
from k8s_gpu_tpu.controller.manager import Request


@pytest.fixture
def kube():
    return FakeKube()


@pytest.fixture
def spaces(kube):
    return SpaceManager(kube)


def _pod(name, ns, chips=4, phase="Running"):
    p = Pod()
    p.metadata.name = name
    p.metadata.namespace = ns
    p.requests = {"google.com/tpu": chips}
    p.phase = phase
    return p


def _job(name, ns):
    j = TrainJob()
    j.metadata.name = name
    j.metadata.namespace = ns
    return j


# -- spaces + RBAC ----------------------------------------------------------

def test_create_space_materializes(kube, spaces):
    spaces.create_space("ml-team", owner="alice",
                        quota_hard={"google.com/tpu": 8})
    assert kube.get("Namespace", "ml-team", "").metadata.labels["space"] == "ml-team"
    assert kube.get("ResourceQuota", "space-quota", "ml-team").spec.hard == {
        "google.com/tpu": 8
    }
    ident = Identity("alice")
    assert spaces.allowed(ident, "create", "TrainJob", "ml-team")
    assert spaces.spaces_for(ident) == ["ml-team"]


def test_rbac_least_privilege(kube, spaces):
    spaces.create_space("ml-team", owner="alice")
    spaces.grant("ml-team", "bob", "space-user")
    spaces.grant("ml-team", "carol", "space-viewer")
    bob, carol = Identity("bob"), Identity("carol")
    # space-user: write TrainJob/DevEnv/Secret, read everything.
    assert spaces.allowed(bob, "create", "TrainJob", "ml-team")
    assert spaces.allowed(bob, "list", "Pod", "ml-team")
    assert not spaces.allowed(bob, "create", "TpuPodSlice", "ml-team")
    # space-viewer: read only.
    assert spaces.allowed(carol, "get", "TrainJob", "ml-team")
    assert not spaces.allowed(carol, "create", "TrainJob", "ml-team")
    # No bindings elsewhere.
    assert not spaces.allowed(bob, "get", "TrainJob", "other-ns")


def test_group_binding_and_cluster_admin(kube, spaces):
    spaces.create_space("ml-team", owner="alice")
    spaces.grant("ml-team", "researchers", "space-user", group=True)
    member = Identity("dave", frozenset({"researchers"}))
    assert spaces.allowed(member, "create", "TrainJob", "ml-team")
    root = Identity("root", frozenset({"platform-admins"}))
    assert spaces.allowed(root, "delete", "TpuPodSlice", "anywhere")


def test_authorized_kube_enforces(kube, spaces):
    spaces.create_space("ml-team", owner="alice")
    spaces.grant("ml-team", "carol", "space-viewer")
    viewer = AuthorizedKube(kube, spaces, Identity("carol"))
    with pytest.raises(Forbidden):
        viewer.create(_job("j1", "ml-team"))
    admin = AuthorizedKube(kube, spaces, Identity("alice"))
    admin.create(_job("j1", "ml-team"))
    assert viewer.get("TrainJob", "j1", "ml-team").metadata.name == "j1"
    with pytest.raises(Forbidden):
        viewer.delete("TrainJob", "j1", "ml-team")


def test_authorized_list_scopes_to_visible_namespaces(kube, spaces):
    spaces.create_space("team-a", owner="alice")
    spaces.create_space("team-b", owner="bob")
    kube.create(_job("ja", "team-a"))
    kube.create(_job("jb", "team-b"))
    mine = AuthorizedKube(kube, spaces, Identity("alice")).list("TrainJob")
    assert [j.metadata.namespace for j in mine] == ["team-a"]


# -- quota ------------------------------------------------------------------

def test_quota_blocks_over_chip_limit(kube, spaces):
    kube.admission.append(QuotaEnforcer(kube))
    spaces.create_space("ml-team", owner="alice",
                        quota_hard={"google.com/tpu": 8})
    kube.create(_pod("p1", "ml-team", chips=4))
    kube.create(_pod("p2", "ml-team", chips=4))
    with pytest.raises(ValidationError, match="exceeded quota"):
        kube.create(_pod("p3", "ml-team", chips=1))
    # Finished pods release chips.
    done = kube.get("Pod", "p1", "ml-team")
    done.phase = "Succeeded"
    kube.update(done)
    kube.create(_pod("p3", "ml-team", chips=4))


def test_quota_object_counts(kube, spaces):
    kube.admission.append(QuotaEnforcer(kube))
    spaces.create_space("ml-team", owner="alice",
                        quota_hard={"count/trainjobs": 2})
    kube.create(_job("j1", "ml-team"))
    kube.create(_job("j2", "ml-team"))
    with pytest.raises(ValidationError, match="count/trainjobs"):
        kube.create(_job("j3", "ml-team"))
    # Other namespaces unaffected.
    kube.create(_job("j3", "elsewhere"))


def test_untracked_kinds_unaffected_by_exceeded_quota(kube, spaces):
    """A namespace already over a (freshly lowered) hard limit must still
    accept writes that don't grow a tracked resource — Events especially,
    or the alerting that reports the overage could never be recorded."""
    from k8s_gpu_tpu.api import Event, ResourceQuota, Secret

    kube.admission.append(QuotaEnforcer(kube))
    kube.create(_pod("p1", "ml-team", chips=8))
    rq = ResourceQuota()
    rq.metadata.name = "space-quota"
    rq.metadata.namespace = "ml-team"
    rq.spec.hard = {"google.com/tpu": 4}  # already exceeded by p1
    kube.create(rq)
    ev = Event()
    ev.metadata.name = "ev1"
    ev.metadata.namespace = "ml-team"
    kube.create(ev)
    s = Secret()
    s.metadata.name = "s1"
    s.metadata.namespace = "ml-team"
    kube.create(s)
    # Counted kinds whose own limits aren't set are also unaffected.
    kube.create(_job("j1", "ml-team"))
    # Chip-less pods (devenv pods) don't gate on the exceeded chip limit.
    kube.create(_pod("p-noTPU", "ml-team", chips=0))
    # But growing the over-limit resource stays blocked.
    with pytest.raises(ValidationError, match="exceeded quota"):
        kube.create(_pod("p2", "ml-team", chips=1))


def test_limit_range_defaulting_and_ceiling(kube):
    kube.admission.append(QuotaEnforcer(kube))
    lr = LimitRange()
    lr.metadata.name = "limits"
    lr.metadata.namespace = "ml-team"
    lr.spec.default_tpu = 4
    lr.spec.max_tpu = 8
    kube.create(lr)
    p = _pod("p1", "ml-team", chips=0)
    p.requests = {}
    kube.create(p)
    assert kube.get("Pod", "p1", "ml-team").requests["google.com/tpu"] == 4
    with pytest.raises(ValidationError, match="LimitRange max"):
        kube.create(_pod("p2", "ml-team", chips=16))


def test_quota_enforced_on_pod_update(kube, spaces):
    kube.admission.append(QuotaEnforcer(kube))
    spaces.create_space("ml-team", owner="alice",
                        quota_hard={"google.com/tpu": 8})
    kube.create(_pod("p1", "ml-team", chips=4))
    grown = kube.get("Pod", "p1", "ml-team")
    grown.requests["google.com/tpu"] = 100
    with pytest.raises(ValidationError, match="exceeded quota"):
        kube.update(grown)
    # Shrinking or finishing is always allowed.
    shrunk = kube.get("Pod", "p1", "ml-team")
    shrunk.phase = "Succeeded"
    kube.update(shrunk)


def test_conflict_wins_over_quota(kube, spaces):
    from k8s_gpu_tpu.controller.kubefake import Conflict

    kube.admission.append(QuotaEnforcer(kube))
    spaces.create_space("ml-team", owner="alice",
                        quota_hard={"count/trainjobs": 1})
    kube.create(_job("j1", "ml-team"))
    # Re-creating an existing object at the quota ceiling must surface
    # Conflict (the operators' create-if-absent contract), not a quota error.
    with pytest.raises(Conflict):
        kube.create(_job("j1", "ml-team"))


def test_quota_reconciler_status_and_alert(kube, spaces):
    spaces.create_space("ml-team", owner="alice",
                        quota_hard={"google.com/tpu": 8})
    kube.create(_pod("p1", "ml-team", chips=8))
    rec = QuotaReconciler(kube)
    rec.reconcile(Request("ml-team", "space-quota"))
    rq = kube.get("ResourceQuota", "space-quota", "ml-team")
    assert rq.status.used["google.com/tpu"] == 8
    alert = [c for c in rq.status.conditions if c.type == "AlertActive"][0]
    assert alert.status == "True"
    events = [e for e in kube.list("Event", namespace="ml-team")
              if e.reason == "QuotaNearLimit"]
    assert events
    # Dropping below threshold clears the alert.
    kube.delete("Pod", "p1", "ml-team")
    rec.reconcile(Request("ml-team", "space-quota"))
    rq = kube.get("ResourceQuota", "space-quota", "ml-team")
    alert = [c for c in rq.status.conditions if c.type == "AlertActive"][0]
    assert alert.status == "False"
