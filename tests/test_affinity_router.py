"""Prefix-affinity fleet router + telemetry-driven autoscaling (ISSUE 7).

Named test_affinity_router so it sorts early inside the tier-1 870 s
window.  Covers: two-run routing determinism, affinity beating
round-robin on shared-prefix cache hits through REAL paged batchers,
replica-death rehash with zero lost requests (``utils/faults.py``
injection at the ``serve.submit`` site), the autoscaler FSM's
up/down/cooldown walk under ``FakeClock``, prefix-aware scale-down
victim choice + drain, and the journal's placement stamp.
"""

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher
from k8s_gpu_tpu.serve.router import (
    FleetAutoscaler,
    FleetRouter,
    router_rule_pack,
)
from k8s_gpu_tpu.utils.alerts import RuleEvaluator
from k8s_gpu_tpu.utils.clock import FakeClock
from k8s_gpu_tpu.utils.faults import FaultPlan, global_faults
from k8s_gpu_tpu.utils.metrics import MetricsRegistry

PAGE = 16
TINY = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8,
    d_ff=64, max_seq=64, use_flash=False, dtype=jnp.float32,
)

# Two tenants' shared system prompts: one full page each, so every
# request sharing one carries the same chain-root hash.
PREFIX_A = [(3 * j + 1) % 60 + 1 for j in range(PAGE)]
PREFIX_B = [(5 * j + 2) % 60 + 1 for j in range(PAGE)]


@pytest.fixture(scope="module")
def tiny_lm():
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _paged_batcher(model, params, reg=None):
    return ContinuousBatcher(
        model, params, slots=4, paged_blocks=24, page_size=PAGE,
        metrics=reg if reg is not None else MetricsRegistry(),
    ).start()


def _fresh_router(names, page=PAGE, **kw):
    r = FleetRouter(page_size=page, metrics=MetricsRegistry(), **kw)
    for n in names:
        r.add_replica(n)
    return r


# -- routing policy (no model, no device) -------------------------------------

def test_two_run_routing_deterministic():
    """Routing is a pure function of (request sequence, replica set):
    two fresh routers replay an identical decision list — replica AND
    reason — for the same traffic."""
    traffic = (
        [PREFIX_A + [40 + i] for i in range(3)]
        + [PREFIX_B + [40 + i] for i in range(3)]
        + [[7, 9]]                      # no shareable page -> load
        + [PREFIX_A + [50, 51], PREFIX_B + [50]]
    )

    def run():
        r = _fresh_router(["r0", "r1", "r2", "r3"])
        return [
            (d.replica, d.reason, d.chain_depth, d.warm_depth)
            for d in (r.route(ids) for ids in traffic)
        ]

    first, second = run(), run()
    assert first == second
    reasons = [x[1] for x in first]
    assert reasons[6] == "load"         # the prefix-less prompt
    assert set(reasons) <= {"affinity", "load"}


def test_shared_prefix_traffic_converges_on_one_replica():
    """Every request sharing a chain lands on the SAME replica, and the
    two tenants' chains are tracked in the ownership gauge."""
    r = _fresh_router(["r0", "r1", "r2"])
    a = {r.route(PREFIX_A + [40 + i]).replica for i in range(5)}
    b = {r.route(PREFIX_B + [40 + i]).replica for i in range(5)}
    assert len(a) == 1 and len(b) == 1
    owned = {n: r.chains_owned(n) for n in r.replica_names()}
    assert sum(owned.values()) >= 2  # both chain roots tracked
    # The second request onward is warm: decisions say so.
    d = r.route(PREFIX_A + [99])
    assert d.reason == "affinity" and d.warm_depth == 1


def test_hot_replica_sheds_new_prefixes_keeps_warm_chains():
    """Hysteresis: a hot replica stops receiving NEW chains (they
    re-route by rendezvous among the others) but keeps the chains
    already warm on it — load spills without thrashing the cache."""
    clock = FakeClock()

    class ScriptedCollector:
        """Collector stand-in: a registry the test scripts directly."""

        def __init__(self):
            self.registry = MetricsRegistry()

        def scrape_once(self):
            return {}

    col = ScriptedCollector()
    r = FleetRouter(
        page_size=PAGE, collector=col, metrics=MetricsRegistry(),
        clock=clock, staleness_s=0.0,
    )
    for n in ("r0", "r1"):
        r.add_replica(n)
        col.registry.set_gauge(
            "serve_slot_fill_ratio", 0.0, replica=n
        )
    owner = r.route(PREFIX_A + [40]).replica
    other = ({"r0", "r1"} - {owner}).pop()
    # Saturate the owner past hot_enter.
    col.registry.set_gauge("serve_slot_fill_ratio", 1.0, replica=owner)
    col.registry.set_gauge(
        "serve_kv_occupancy_ratio", 1.0, replica=owner
    )
    col.registry.set_gauge("serve_pending_requests", 99.0, replica=owner)
    d_new = r.route(PREFIX_B + [40])      # a NEW chain
    assert d_new.replica == other
    d_warm = r.route(PREFIX_A + [41])     # the chain warm on the hot one
    assert d_warm.replica == owner and d_warm.reason == "affinity"
    # Cool below hot_exit: the hot flag clears on the next load read.
    col.registry.set_gauge("serve_slot_fill_ratio", 0.1, replica=owner)
    col.registry.set_gauge(
        "serve_kv_occupancy_ratio", 0.0, replica=owner
    )
    col.registry.set_gauge("serve_pending_requests", 0.0, replica=owner)
    snap = r.snapshot()
    row = [x for x in snap["replicas"] if x["replica"] == owner][0]
    assert not row["hot"]


def test_drain_rehomes_chains_and_victim_choice():
    """``scale_down_victim`` picks the fewest-warm-chains replica;
    ``drain`` announces one, new traffic avoids it, and its warm
    chains re-home with reason=fallback (warm somewhere unusable)."""
    r = _fresh_router(["r0", "r1", "r2"])
    owner_a = r.route(PREFIX_A + [40]).replica
    for i in range(3):
        r.route(PREFIX_A + [41 + i])
    owner_b = r.route(PREFIX_B + [40]).replica
    # The victim owns the fewest warm chains of the eligible set.
    victim = r.scale_down_victim()
    assert r.chains_owned(victim) == min(
        r.chains_owned(n) for n in r.replica_names()
    )
    # Drain tenant B's owner: its chain must re-home off it.
    drained = r.drain(owner_b)
    assert drained == r.chains_owned(owner_b) >= 0
    d = r.route(PREFIX_B + [41])
    assert d.replica != owner_b
    assert d.reason == "fallback"
    # The re-homed chain now routes warm to its new owner.
    d2 = r.route(PREFIX_B + [42])
    assert d2.replica == d.replica and d2.reason == "affinity"
    assert owner_a is not None  # both tenants exercised the table


# -- affinity vs round-robin through real paged batchers ----------------------

def test_affinity_beats_round_robin_on_shared_prefix_hits(tiny_lm):
    """The tentpole claim at test scale: the same skewed two-tenant
    trace through 4 paged replicas scores at least 2x the block-cache
    hits under affinity routing vs round-robin."""
    model, params = tiny_lm
    trace = []
    for i in range(3):
        trace.append(PREFIX_A + [40 + i])
        trace.append(PREFIX_B + [40 + i])

    def run(route_fn):
        regs = {f"r{i}": MetricsRegistry() for i in range(4)}
        reps = {n: _paged_batcher(model, params, reg)
                for n, reg in regs.items()}
        try:
            handles = [
                reps[route_fn(i, ids)].submit(ids, max_new_tokens=4)
                for i, ids in enumerate(trace)
            ]
            assert all(len(h.result()) > 0 for h in handles)
            hits = sum(
                reg.counter("serve_prefix_cache_hits_total")
                for reg in regs.values()
            )
            return hits
        finally:
            for b in reps.values():
                b.stop()

    router = _fresh_router(["r0", "r1", "r2", "r3"])
    aff_hits = run(lambda i, ids: router.route(ids).replica)
    rr_hits = run(lambda i, ids: f"r{i % 4}")
    assert aff_hits == len(trace) - 2, (aff_hits, rr_hits)
    assert aff_hits >= 2 * rr_hits, (aff_hits, rr_hits)


def test_replica_death_rehash_zero_lost_requests(tiny_lm):
    """A replica whose submit fails (fault-injected, then a real
    stopped batcher) is marked down and its traffic re-routes — every
    request completes, nothing is lost."""
    model, params = tiny_lm
    reps = {n: _paged_batcher(model, params) for n in ("r0", "r1")}
    router = FleetRouter(page_size=PAGE, metrics=MetricsRegistry())
    for n, b in reps.items():
        router.add_replica(n, b.submit)
    try:
        # First submit call dies (injected RuntimeError through the
        # production serve.submit site); dispatch must absorb it.
        global_faults.arm(
            "serve.submit", FaultPlan(flaky=1, kinds=("error",))
        )
        try:
            handles = [
                router.dispatch(PREFIX_A + [40 + i], max_new_tokens=4)
                for i in range(4)
            ]
        finally:
            global_faults.disarm("serve.submit")
        assert all(len(h.result()) > 0 for h, _ in handles)
        assert router.metrics.counter("serve_router_rehash_total") == 1.0
        downed = [
            x["replica"] for x in router.snapshot()["replicas"]
            if x["down"]
        ]
        assert len(downed) == 1
        # Now a REAL death: revive the injected-down replica, stop the
        # other one's scheduler, and dispatch again — a dead batcher's
        # submit raises, the router rehashes, nothing is lost.
        alive = ({"r0", "r1"} - set(downed)).pop()
        router.mark_up(downed[0])
        reps[alive].stop()
        hs = [
            router.dispatch(PREFIX_B + [40 + i], max_new_tokens=4)
            for i in range(3)
        ]
        assert all(len(h.result()) > 0 for h, _ in hs)
        assert all(d.replica == downed[0] for _, d in hs)
    finally:
        global_faults.disarm("serve.submit")
        for b in reps.values():
            b.stop()


def test_journal_records_placement(tiny_lm):
    """A routed submit stamps (replica, reason) into the journal so
    ``obs requests`` explains placement."""
    from k8s_gpu_tpu.utils.obs import render_requests

    model, params = tiny_lm
    b = _paged_batcher(model, params)
    try:
        b.submit(
            PREFIX_A + [40], max_new_tokens=3,
            route=("replica-7", "affinity"),
        ).result()
        b.submit(PREFIX_A + [41], max_new_tokens=3).result()
    finally:
        b.stop()
    recs = b.journal.snapshot()
    assert len(recs) == 2
    routed = [r for r in recs if r["replica"]]
    assert len(routed) == 1
    assert routed[0]["replica"] == "replica-7"
    assert routed[0]["route_reason"] == "affinity"
    out = render_requests(recs)
    assert "replica-7" in out and "REPLICA" in out


# -- the autoscaler FSM -------------------------------------------------------

def _firing(ev):
    return {
        a["alertname"] for a in ev.active_alerts()
        if a["state"] == "firing"
    }


def test_autoscaler_fsm_up_down_cooldown_under_fakeclock():
    """The full walk: backlog alert scales up (sized, max-step
    clamped), cooldown holds the next action, sustained low fill
    scales down one step per cooldown window, floors at min."""
    clk = FakeClock()
    reg = MetricsRegistry()
    ev = RuleEvaluator(
        router_rule_pack(
            None, backlog_per_replica=4.0, backlog_for_s=10.0,
            low_fill=0.25, low_fill_for_s=20.0,
        ),
        clock=clk, registry=reg,
    )
    scaler = FleetAutoscaler(
        min_replicas=1, max_replicas=8, clock=clk, cooldown_s=30.0,
        max_step=2, target_pending_per_replica=4.0,
        metrics=MetricsRegistry(),
    )
    reg.set_gauge("serve_pending_requests", 40.0)
    reg.set_gauge("fleet_replicas_up", 2.0)
    reg.set_gauge("serve_slot_fill_ratio", 0.9)
    ev.evaluate_once()                      # backlog goes pending
    assert _firing(ev) == set()
    d = scaler.decide(replicas=2, pending=40.0, firing=_firing(ev))
    assert d.direction == 0                 # nothing firing yet
    clk.advance(10.0)
    ev.evaluate_once()                      # hold elapsed -> firing
    assert "FleetQueueBacklog" in _firing(ev)
    d = scaler.decide(replicas=2, pending=40.0, firing=_firing(ev))
    # need = ceil(40/4) = 10, clamped to max_step: 2 -> 4.
    assert (d.target, d.reason, d.direction) == (4, "backlog", 1)
    d = scaler.decide(replicas=4, pending=40.0, firing=_firing(ev))
    assert d.reason == "cooldown" and d.direction == 0
    clk.advance(30.0)
    ev.evaluate_once()
    d = scaler.decide(replicas=4, pending=40.0, firing=_firing(ev))
    assert (d.target, d.direction) == (6, 1)
    # Backlog clears, fill drops: scale-down after the sustained hold.
    reg.set_gauge("serve_pending_requests", 0.0)
    reg.set_gauge("serve_slot_fill_ratio", 0.05)
    clk.advance(30.0)
    ev.evaluate_once()                      # low fill goes pending
    clk.advance(20.0)
    ev.evaluate_once()                      # ...and fires
    assert "FleetLowFill" in _firing(ev)
    assert "FleetQueueBacklog" not in _firing(ev)
    d = scaler.decide(replicas=6, pending=0.0, firing=_firing(ev))
    assert (d.target, d.reason, d.direction) == (5, "low_fill", -1)
    d = scaler.decide(replicas=5, pending=0.0, firing=_firing(ev))
    assert d.reason == "cooldown"
    # One step per cooldown window, down to the floor, never below.
    reps = 5
    for _ in range(8):
        clk.advance(30.0)
        ev.evaluate_once()
        d = scaler.decide(
            replicas=reps, pending=0.0, firing=_firing(ev)
        )
        reps = d.target
    assert reps == 1
    d = scaler.decide(replicas=1, pending=0.0, firing=_firing(ev))
    assert d.direction == 0


def test_ttft_burn_scales_up():
    """Latency burn is a scale-up trigger even with an empty queue —
    the signal backlog depth alone misses when requests are long."""
    clk = FakeClock()
    reg = MetricsRegistry()
    ev = RuleEvaluator(
        router_rule_pack(None, ttft_slo_s=1.0, ttft_for_s=10.0),
        clock=clk, registry=reg,
    )
    for _ in range(20):
        reg.observe("serve_ttft_seconds", 3.0)
    ev.evaluate_once()
    clk.advance(10.0)
    ev.evaluate_once()
    assert "FleetTtftBurn" in _firing(ev)
    scaler = FleetAutoscaler(
        min_replicas=1, max_replicas=4, clock=clk,
        metrics=MetricsRegistry(),
    )
    d = scaler.decide(replicas=2, pending=0.0, firing=_firing(ev))
    assert (d.target, d.reason, d.direction) == (3, "ttft_burn", 1)
