"""Regex-constrained decoding: the compiled automaton is the contract —
every emitted string matches the pattern, dead ends stop cleanly, and
an all-permissive pattern reproduces unconstrained greedy exactly."""

import re

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import InferenceEngine, compile_constraint
from k8s_gpu_tpu.serve.constrain import RegexError

# A vocabulary of multi-character string tokens (what a BPE vocab looks
# like to the automaton).
# "s" included so every in-language prefix of "yes|no" can complete —
# the mask guarantees prefix-validity, not completion, so a vocabulary
# hole can strand greedy decoding in a dead end (accepted=False).
TOKENS = ["", "0", "1", "7", "12", "ab", "cd", "e", "a", "x", "yes", "no",
          "9", "y", "es", "o", "s"]
CFG = TransformerConfig(
    vocab_size=len(TOKENS), d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq=48, use_flash=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(CFG)
    return model, model.init(jax.random.PRNGKey(0)), InferenceEngine(model)


def _decode(ids, lengths, row=0):
    n = int(lengths[row])
    return "".join(TOKENS[int(t)] for t in ids[row][:n])


def test_table_semantics():
    c = compile_constraint("[0-9]+", TOKENS)
    import numpy as np
    allowed0 = np.asarray(c.allowed[c.start])
    for v, tok in enumerate(TOKENS):
        want = bool(tok) and all(ch.isdigit() for ch in tok)
        assert allowed0[v] == want, (tok, allowed0[v])
    # multi-char token walks: "12" from start lands in an accepting state
    s12 = int(c.next_state[c.start, TOKENS.index("12")])
    assert bool(c.accepting[s12])


def _is_language_prefix(pattern: str, s: str, alphabet: str) -> bool:
    """Independent prefix oracle: s extends to a full match within a few
    characters (all test languages complete within depth 3)."""
    from itertools import product

    for depth in range(4):
        for tail in product(alphabet, repeat=depth):
            if re.fullmatch(pattern, s + "".join(tail)):
                return True
    return False


@pytest.mark.parametrize("pattern", ["[0-9]+", "(ab|cd)+e", "yes|no"])
def test_generated_strings_match_pattern(setup, pattern):
    model, params, eng = setup
    c = compile_constraint(pattern, TOKENS)
    alphabet = "".join(sorted({ch for t in TOKENS for ch in t}))
    prompt = jax.random.randint(jax.random.PRNGKey(3), (4, 5), 1, 15)
    out = eng.generate_constrained(params, prompt, c, max_new_tokens=10)
    for b in range(4):
        s = _decode(out["tokens"], out["lengths"], b)
        if bool(out["accepted"][b]):
            assert re.fullmatch(pattern, s), (pattern, s)
        else:
            # Dead end / budget: the emission must still be a valid
            # prefix of the language (checked against Python's re, not
            # our own tables).
            assert _is_language_prefix(pattern, s, alphabet), (pattern, s)


def test_finite_language_stops_and_accepts(setup):
    model, params, eng = setup
    c = compile_constraint("yes|no", TOKENS)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (3, 4), 1, 15)
    out = eng.generate_constrained(params, prompt, c, max_new_tokens=8)
    for b in range(3):
        s = _decode(out["tokens"], out["lengths"], b)
        assert re.fullmatch("yes|no", s), s
        assert bool(out["accepted"][b])
        # dead end reached well before the budget
        assert int(out["lengths"][b]) <= 3


def test_permissive_pattern_matches_plain_greedy():
    """'.*' must reproduce unconstrained greedy bit-for-bit — on a
    vocabulary WITHOUT empty tokens.  Empty tokens are never allowed by
    the automaton (they would stall it), so the guarantee holds only
    when plain greedy can't pick one (the docs state this caveat)."""
    toks = ["0", "1", "ab", "cd", "e", "x", "y"]
    cfg = TransformerConfig(
        vocab_size=len(toks), d_model=32, n_layers=2, n_heads=2,
        d_head=16, d_ff=64, max_seq=48, use_flash=False,
        dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(2))
    eng = InferenceEngine(model)
    c = compile_constraint(".*", toks)
    assert bool(c.allowed.all())  # genuinely all-permissive
    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 6), 0, len(toks))
    ref = eng.generate(params, prompt, max_new_tokens=10)
    out = eng.generate_constrained(params, prompt, c, max_new_tokens=10)
    assert jnp.array_equal(out["tokens"], ref.tokens)


def test_sampled_constrained_stays_in_language(setup):
    from k8s_gpu_tpu.serve import SamplingConfig

    model, params, eng = setup
    c = compile_constraint("(ab|cd)+e", TOKENS)
    prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 4), 1, 15)
    out = eng.generate_constrained(
        params, prompt, c, max_new_tokens=9,
        sampling=SamplingConfig(temperature=1.0),
        key=jax.random.PRNGKey(11),
    )
    for b in range(2):
        if bool(out["accepted"][b]):
            s = _decode(out["tokens"], out["lengths"], b)
            assert re.fullmatch("(ab|cd)+e", s), s


def test_vocab_mismatch_rejected(setup):
    model, params, eng = setup
    c = compile_constraint("[0-9]", TOKENS + ["zz"])
    with pytest.raises(ValueError, match="vocab"):
        eng.generate_constrained(params, jnp.ones((1, 3), jnp.int32), c)


def test_regex_errors():
    with pytest.raises(RegexError):
        compile_constraint("(ab", TOKENS)
    with pytest.raises(RegexError):
        compile_constraint("[abc", TOKENS)
    with pytest.raises(RegexError):
        compile_constraint("*a", TOKENS)


def test_control_escapes_resolve_to_control_chars():
    """\\n / \\t match the control characters (standard semantics), not
    the literal letters — and unknown alphabetic escapes are an error
    rather than silently matching the letter."""
    toks = ["\n", "\t", "n", "t", "a"]
    c = compile_constraint(r"\n", toks)
    import numpy as np
    allowed = np.asarray(c.allowed[c.start])
    assert allowed[toks.index("\n")] and not allowed[toks.index("n")]
    c = compile_constraint(r"[\t]", toks)
    allowed = np.asarray(c.allowed[c.start])
    assert allowed[toks.index("\t")] and not allowed[toks.index("t")]
    with pytest.raises(RegexError, match="escape"):
        compile_constraint(r"\q", toks)
    with pytest.raises(RegexError, match="escape"):
        compile_constraint(r"[\q]", toks)
    # punctuation escapes still mean the literal character
    c = compile_constraint(r"\.", [".", "a"])
    assert bool(np.asarray(c.allowed[c.start])[0])


# -- banked constraints in the continuous batcher ---------------------------

def _bank(patterns):
    from k8s_gpu_tpu.serve.constrain import ConstraintBank

    return ConstraintBank(patterns, TOKENS)


def test_constraint_bank_shapes():
    bank = _bank({"digits": "[0-9]+", "yn": "yes|no"})
    assert bank.names == ["__free__", "digits", "yn"]
    C, S, V = bank.allowed.shape
    assert C == 3 and V == len(TOKENS)
    # index 0 is the all-permissive self-loop
    assert bool(bank.allowed[0, 0].all())
    assert int(bank.next_state[0, 0, 3]) == 0
    assert bank.index(None) == 0
    with pytest.raises(KeyError, match="unknown constraint"):
        bank.index("nope")


def test_batcher_constrained_matches_engine(setup):
    """The banked round loop and the engine's constrained scan are the
    same automaton: greedy streams agree token-for-token."""
    from k8s_gpu_tpu.serve import ContinuousBatcher

    model, params, eng = setup
    bank = _bank({"digits": "[0-9]+"})
    c = compile_constraint("[0-9]+", TOKENS)
    b = ContinuousBatcher(model, params, slots=2, eos_id=0,
                          constraints=bank).start()
    try:
        prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 1, 15)
        ref = eng.generate_constrained(params, prompt, c, max_new_tokens=8)
        got = b.submit(list(map(int, prompt[0])), max_new_tokens=8,
                       constraint="digits").result()
        n = int(ref["lengths"][0])
        assert got == [int(t) for t in ref["tokens"][0][:n]], (got, ref)
        # and the emission is digit-only
        assert all(TOKENS[t].isdigit() for t in got)
    finally:
        b.stop()


def test_batcher_mixed_constrained_and_free(setup):
    from k8s_gpu_tpu.serve import ContinuousBatcher

    model, params, eng = setup
    bank = _bank({"yn": "yes|no"})
    b = ContinuousBatcher(model, params, slots=3, eos_id=0,
                          constraints=bank).start()
    try:
        free_ids = [5, 9, 17]
        h1 = b.submit(free_ids, max_new_tokens=6)
        h2 = b.submit([7, 3], max_new_tokens=6, constraint="yn")
        free = h1.result()
        yn = h2.result()
        # the free row matches the plain engine (eos_id=0 semantics)
        ref = eng.generate(
            params, jnp.asarray([free_ids]), max_new_tokens=6,
            sampling=__import__(
                "k8s_gpu_tpu.serve", fromlist=["SamplingConfig"]
            ).SamplingConfig(eos_id=0),
        )
        n = int(ref.lengths[0])
        assert free == [int(t) for t in ref.tokens[0][:n]]
        # the constrained row produced a full yes/no
        s = "".join(TOKENS[t] for t in yn)
        assert re.fullmatch("yes|no", s), s
        with pytest.raises(KeyError, match="unknown constraint"):
            b.submit([1], constraint="nope")
    finally:
        b.stop()


def test_lm_server_constraint_param(setup):
    import json
    import urllib.error
    import urllib.request

    from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
    from k8s_gpu_tpu.serve import LmServer

    corpus = "0 1 7 9 12 ab cd e yes no " * 30
    tok = BpeTokenizer.train(corpus, vocab_size=260, backend="python")
    # the model's vocab must match the tokenizer's (byte-BPE floor: 256+)
    cfg_srv = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=48, use_flash=False,
        dtype=jnp.float32,
    )
    model_srv = TransformerLM(cfg_srv)
    params_srv = model_srv.init(jax.random.PRNGKey(4))
    srv = LmServer(model_srv, params_srv, tok,
                   constraints={"digits": "[0-9 ]+"}, eos_id=0).start()
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, out = post({"prompt": "ab cd", "max_new_tokens": 6,
                          "constraint": "digits"})
        assert code == 200
        assert re.fullmatch("[0-9 ]*", out["text"]), out["text"]
        code, err = post({"prompt": "x", "constraint": "nope"})
        assert code == 400 and "unknown constraint" in err["error"]
    finally:
        srv.stop()


def test_bank_vocab_mismatch_rejected_at_construction(setup):
    """A bank compiled over a different vocabulary must fail at batcher
    construction, not crash the scheduler mid-admit (which would strand
    the popped request's handle forever — regression for the admit
    crash path)."""
    from k8s_gpu_tpu.serve import ContinuousBatcher
    from k8s_gpu_tpu.serve.constrain import ConstraintBank

    model, params, _ = setup
    bank = ConstraintBank({"d": "[0-9]+"}, TOKENS + ["zz", "qq"])
    with pytest.raises(ValueError, match="vocab"):
        ContinuousBatcher(model, params, slots=2, constraints=bank)


def test_bank_without_eos_rejected(setup):
    """A ConstraintBank with eos_id unset is a construction error (a
    dead-ended row would pad token 0 as generated content until budget
    — previously only the CLI guarded this)."""
    from k8s_gpu_tpu.serve import ContinuousBatcher
    from k8s_gpu_tpu.serve.constrain import ConstraintBank

    model, params, _ = setup
    bank = ConstraintBank({"d": "[0-9]+"}, TOKENS)
    with pytest.raises(ValueError, match="eos_id"):
        ContinuousBatcher(model, params, slots=2, constraints=bank)


def test_one_shot_constrained_stops_on_eos(setup):
    """generate_constrained honors sampling.eos_id exactly like the
    batcher's constrained path: a row that samples EOS freezes, the EOS
    token is not emitted, and `accepted` reflects the pre-EOS state."""
    from k8s_gpu_tpu.serve.engine import SamplingConfig

    model, params, eng = setup
    # Pattern that allows every token (including whatever greedy picks):
    # then force EOS as token 0 by making it in-language too.
    c = compile_constraint(".*", TOKENS)
    prompt = jnp.ones((2, 3), jnp.int32)
    out_free = eng.generate_constrained(
        params, prompt, c, max_new_tokens=8,
        sampling=SamplingConfig(eos_id=-1))
    first = int(out_free["tokens"][0, 0])
    out_eos = eng.generate_constrained(
        params, prompt, c, max_new_tokens=8,
        sampling=SamplingConfig(eos_id=first))
    # Greedy deterministic: first sampled token is `first` → row 0 stops
    # immediately with zero emissions.
    assert int(out_eos["lengths"][0]) == 0
    assert int(out_eos["tokens"][0, 0]) == 0  # pad, not the EOS id


def test_admit_crash_aborts_popped_request(setup):
    """If dispatch itself raises, the popped request must be aborted —
    not left in neither queue with a caller blocked on result()."""
    from k8s_gpu_tpu.serve import ContinuousBatcher

    model, params, _ = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        # Force a TypeError inside dispatch, whichever admission program
        # the scheduler picks (fused cold-solo or the plain admit).
        b._admit_jit = None
        b._admit_round_jit = None
        h = b.submit([1, 2, 3], max_new_tokens=4)
        got = h.result()  # must return promptly
        assert h.aborted and got == []
    finally:
        b.stop()


def test_dead_end_logprobs_finite(setup):
    """A constrained row that dead-ends must record finite logprobs —
    NaN would serialize as invalid JSON (code-review r3)."""
    import math

    from k8s_gpu_tpu.serve import ContinuousBatcher

    model, params, _ = setup
    bank = _bank({"yn": "yes|no"})
    b = ContinuousBatcher(model, params, slots=2, eos_id=0,
                          constraints=bank, logprobs=True).start()
    try:
        h = b.submit([7, 3], max_new_tokens=6, constraint="yn")
        toks = h.result()
        assert toks  # produced a yes/no then dead-ended
        assert all(math.isfinite(lp) for lp in h.logprobs), h.logprobs
    finally:
        b.stop()
