"""Continuous performance attribution (ISSUE 9): phase profiler
determinism, /debug/profile, CompileStorm, Chrome-trace export, the
batcher's phase seams under a real paged+spec run, fleet aggregation of
the new gauges, per-axis collective bandwidth, and the profile_trainer
StopIteration regression.  (Named to sort early in the tier-1 window.)"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.utils.alerts import RuleEvaluator, default_rule_pack
from k8s_gpu_tpu.utils.clock import FakeClock
from k8s_gpu_tpu.utils.metrics import MetricsRegistry, global_metrics
from k8s_gpu_tpu.utils.obs import MetricsServer, render_profile
from k8s_gpu_tpu.utils.profiler import (
    PhaseProfiler, chrome_trace, profile_snapshot, snapshot_from_exposition,
)


def _scripted(clock: FakeClock):
    """One deterministic profiler run: nested phases, a direct record,
    idle gaps — the fixture both bit-identical tests replay."""
    reg = MetricsRegistry()
    prof = PhaseProfiler(plane="serve", registry=reg, clock=clock)
    with prof.phase("decode_dispatch"):
        clock.advance(0.05)
        with prof.phase("spec_draft"):
            clock.advance(0.02)
        clock.advance(0.01)
    prof.record("retire", 0.001)
    clock.advance(0.5)
    with prof.phase("decode_dispatch"):
        clock.advance(0.04)
    clock.advance(0.1)
    prof.export_shares()
    return prof, reg


# -- profiler core -----------------------------------------------------------

def test_nested_phases_record_self_time():
    prof, reg = _scripted(FakeClock())
    snap = prof.snapshot()
    # decode_dispatch self-time excludes the nested spec_draft segment:
    # 0.05 + 0.01 + 0.04; spec_draft carries its own 0.02.
    assert snap["phases"]["decode_dispatch"]["total_s"] == pytest.approx(0.10)
    assert snap["phases"]["spec_draft"]["total_s"] == pytest.approx(0.02)
    assert snap["phases"]["retire"]["total_s"] == pytest.approx(0.001)
    # The histogram family landed per-phase in the registry.
    h = reg.histogram("serve_phase_seconds", phase="decode_dispatch")
    assert h is not None and h.n == 2


def test_shares_sum_to_at_most_one_with_residual():
    prof, reg = _scripted(FakeClock())
    snap = prof.snapshot()
    shares = [st["share"] for st in snap["phases"].values()]
    assert sum(shares) <= 1.0 + 1e-9
    # Measured 0.121 s over a 0.721 s span; the rest is residual.
    assert snap["residual_share"] == pytest.approx(
        1.0 - sum(shares), abs=1e-9
    )
    assert snap["residual_share"] > 0.5  # mostly-idle script
    # Exported gauges mirror the snapshot, residual included.
    assert reg.gauge(
        "serve_phase_share", phase="decode_dispatch"
    ) == pytest.approx(snap["phases"]["decode_dispatch"]["share"], rel=1e-6)
    assert reg.gauge("serve_phase_share", phase="residual") is not None


def test_profile_snapshot_two_runs_bit_identical():
    a = json.dumps(profile_snapshot(*_scripted(FakeClock())), sort_keys=True)
    b = json.dumps(profile_snapshot(*_scripted(FakeClock())), sort_keys=True)
    assert a == b


def test_debug_profile_endpoint_bit_identical_and_404():
    import urllib.error
    import urllib.request

    bodies = []
    for _ in range(2):
        prof, reg = _scripted(FakeClock())
        srv = MetricsServer(registry=reg, profile=prof).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/profile", timeout=5
            ) as r:
                bodies.append(r.read())
        finally:
            srv.stop()
    assert bodies[0] == bodies[1]
    snap = json.loads(bodies[0])
    assert "decode_dispatch" in snap["phases"]
    assert "compile" in snap and "collectives" in snap
    # render_profile consumes the endpoint shape without error.
    assert "PHASE ATTRIBUTION" in render_profile(snap)
    srv = MetricsServer(registry=MetricsRegistry()).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/profile", timeout=5
            )
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_snapshot_from_exposition_reconstructs_phases():
    prof, reg = _scripted(FakeClock())
    snap = snapshot_from_exposition(reg.render())
    dd = snap["phases"]["decode_dispatch"]
    assert dd["count"] == 2 and dd["share"] > 0.0
    assert dd["p95_s"] > 0.0
    assert snap["residual_share"] is not None


# -- CompileStorm ------------------------------------------------------------

def _compile_storm_rule(reg, clock):
    rules = [
        r for r in default_rule_pack()
        if getattr(r, "name", "") == "CompileStorm"
    ]
    assert rules, "CompileStorm missing from the default pack"
    return RuleEvaluator(rules, clock=clock, registry=reg)


def test_compile_storm_fires_on_burst_and_resolves():
    reg = MetricsRegistry()
    clock = FakeClock()
    ev = _compile_storm_rule(reg, clock)
    ev.evaluate_once()  # t=0 seeds the rate watch
    for _ in range(5):  # recompile burst: 20 compiles / 10 s = 2/s
        reg.inc("xla_compiles_total", 20.0)
        clock.advance(10.0)
        ev.evaluate_once()
    timeline = [t["to"] for t in ev.timeline]
    assert "pending" in timeline and "firing" in timeline, timeline
    assert reg.gauge("alerts_firing", alertname="CompileStorm") == 1.0
    # Storm over: the rate window drains, the alert resolves.
    for _ in range(10):
        clock.advance(10.0)
        ev.evaluate_once()
    timeline = [t["to"] for t in ev.timeline]
    assert timeline[-1] == "resolved", timeline
    assert reg.gauge("alerts_firing", alertname="CompileStorm") == 0.0


def test_compile_storm_silent_at_zero_rate():
    reg = MetricsRegistry()
    clock = FakeClock()
    ev = _compile_storm_rule(reg, clock)
    reg.inc("xla_compiles_total", 5.0)  # warmup compiles, then steady
    for _ in range(8):
        clock.advance(10.0)
        ev.evaluate_once()
    assert not list(ev.timeline)
    assert reg.gauge("alerts_firing", alertname="CompileStorm") == 0.0


def test_runtime_compile_telemetry_counts_real_compiles(xla_compiles):
    n0 = xla_compiles()
    jax.jit(lambda x: x * 3 + 1)(jnp.ones((517,)))  # fresh shape
    assert xla_compiles() > n0
    assert global_metrics.histogram("xla_compile_seconds") is not None


# -- Chrome trace ------------------------------------------------------------

def test_chrome_trace_valid_json_monotonic_ts():
    from k8s_gpu_tpu.utils.tracing import Tracer

    clock = FakeClock()
    tracer = Tracer(registry=MetricsRegistry(), clock=clock)
    with tracer.span("http POST /generate") as sp:
        clock.advance(0.01)
        tracer.add_span(
            "serve.round", parent=sp.context,
            start=clock.now(), end=clock.now() + 0.005, round=1,
        )
        clock.advance(0.02)
    prof, _ = _scripted(FakeClock(100.0))
    data = chrome_trace(tracer.traces(), prof.snapshot())
    text = json.dumps(data)
    loaded = json.loads(text)
    xs = [e for e in loaded["traceEvents"] if e["ph"] == "X"]
    assert len(xs) >= 5  # 2 spans + 4 phase samples
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)
    for e in xs:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["dur"] >= 0.0
    # Both processes present with thread-name metadata.
    metas = [e for e in loaded["traceEvents"] if e["ph"] == "M"]
    assert any(e["pid"] == 1 and e["name"] == "thread_name" for e in metas)
    assert any(e["pid"] == 2 and e["name"] == "thread_name" for e in metas)


# -- the batcher's real seams ------------------------------------------------

def test_batcher_phase_histograms_paged_spec_run():
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
    from k8s_gpu_tpu.serve import ContinuousBatcher

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, max_seq=64,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    reg = MetricsRegistry()
    b = ContinuousBatcher(
        model, params, slots=4, paged_blocks=24, page_size=8,
        metrics=reg, draft="ngram", spec_k=4,
    ).start()
    try:
        shared = [(j * 5 + 2) % 60 + 2 for j in range(16)]
        hs = [
            b.submit(shared + [10 + i], max_new_tokens=24, seed=i)
            for i in range(3)
        ]
        total = sum(len(h.result()) for h in hs)
        assert total == 72
    finally:
        b.stop()
    for phase in ("admission", "paged_plan", "prefill_dispatch",
                  "decode_dispatch", "spec_draft", "spec_verify",
                  "retire"):
        h = reg.histogram("serve_phase_seconds", phase=phase)
        assert h is not None and h.n > 0, phase
    snap = b.profiler.snapshot()
    assert sum(s["share"] for s in snap["phases"].values()) <= 1.0 + 1e-9
    # Share gauges exported into the batcher's own registry.
    assert reg.gauge("serve_phase_share", phase="decode_dispatch") is not None
    assert reg.gauge("serve_phase_share", phase="residual") is not None
    # The full snapshot serializes (the /debug/profile body for this
    # replica) and names the deep-dive path.
    body = json.dumps(profile_snapshot(b.profiler, reg))
    assert "jax.profiler" in body


# -- fleet aggregation -------------------------------------------------------

def test_fleet_aggregates_phase_and_mfu_gauges():
    from k8s_gpu_tpu.utils.federation import FleetCollector

    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.set_gauge("serve_phase_share", 0.5, phase="decode_dispatch")
    r2.set_gauge("serve_phase_share", 0.3, phase="decode_dispatch")
    r1.set_gauge("train_mfu", 0.4)
    r2.set_gauge("train_mfu", 0.2)
    r1.set_gauge("collective_bytes_per_second", 1e9, axis="dp")
    r2.set_gauge("collective_bytes_per_second", 3e9, axis="dp")
    fc = FleetCollector(
        {"r1": r1.render, "r2": r2.render}, clock=FakeClock()
    )
    fc.scrape_once()
    reg = fc.registry
    # Relabeled per-replica detail...
    assert reg.gauge(
        "serve_phase_share", phase="decode_dispatch", replica="r1"
    ) == 0.5
    # ...and the stored aggregates per policy: avg for shares/MFU, max
    # (hottest member) for bandwidth.
    assert reg.gauge(
        "serve_phase_share", phase="decode_dispatch"
    ) == pytest.approx(0.4)
    assert reg.gauge("train_mfu") == pytest.approx(0.3)
    assert reg.gauge(
        "collective_bytes_per_second", axis="dp"
    ) == pytest.approx(3e9)


# -- per-axis collective bandwidth -------------------------------------------

def test_per_axis_bandwidth_probe_multislice():
    from k8s_gpu_tpu.parallel.collectives import per_axis_bandwidth_probe
    from k8s_gpu_tpu.parallel.mesh import MeshConfig, multislice_mesh

    mesh = multislice_mesh(MeshConfig(dp=4, tp=2), num_slices=2)
    reg = MetricsRegistry()
    out = per_axis_bandwidth_probe(mesh, mib=0.05, iters=1, registry=reg)
    assert set(out) == {"dp", "tp"}  # size-1 axes skipped
    for axis in ("dp", "tp"):
        assert out[axis]["bytes_per_second"] > 0.0
        assert reg.gauge(
            "collective_bytes_per_second", axis=axis
        ) == pytest.approx(out[axis]["bytes_per_second"])
        h = reg.histogram("collective_seconds", axis=axis, op="psum")
        assert h is not None and h.n == 1
    # The snapshot surfaces them per axis.
    snap = profile_snapshot(registry=reg)
    assert set(snap["collectives"]) == {"dp", "tp"}


# -- trainer plane -----------------------------------------------------------

def _tiny_trainer():
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
    from k8s_gpu_tpu.parallel import MeshConfig
    from k8s_gpu_tpu.parallel.mesh import build_mesh
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    model = TransformerLM(TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, max_seq=16, use_flash=False))
    return Trainer(
        model, mesh=build_mesh(MeshConfig(dp=1), n_devices=1),
        train_config=TrainConfig(warmup_steps=1),
        peak_flops=1e12,
    )


def test_train_step_exports_phase_split_and_rolling_mfu():
    trainer = _tiny_trainer()
    trainer.init(jax.random.PRNGKey(0))
    toks = np.random.default_rng(0).integers(0, 64, (4, 17), dtype=np.int32)
    for _ in range(2):
        trainer.step(toks[:, :-1], toks[:, 1:])
    snap = trainer.profiler.snapshot()
    for phase in ("shard_batch", "step_dispatch", "loss_sync"):
        assert snap["phases"][phase]["count"] == 2, phase
    mfu = global_metrics.gauge("train_mfu")
    assert mfu is not None and mfu > 0.0  # peak_flops override: nonzero
    assert global_metrics.gauge(
        "train_phase_share", phase="step_dispatch"
    ) is not None
    h = global_metrics.histogram("train_phase_seconds", phase="loss_sync")
    assert h is not None and h.n >= 2


def test_profile_trainer_guards_short_iterator(tmp_path):
    from k8s_gpu_tpu.utils.profiling import profile_trainer

    class NullTrainer:
        def step(self, *batch):
            return 0.0

    with pytest.raises(ValueError, match="exhausted after 0 batches"):
        profile_trainer(NullTrainer(), iter([]), steps=2,
                        log_dir=tmp_path / "p0")
    # Exhausting MID-window (warmup consumed the only batch) names the
    # steps+1 contract instead of leaking a bare StopIteration.
    with pytest.raises(ValueError, match=r"steps \+ 1"):
        profile_trainer(NullTrainer(), iter([(np.zeros(1),)]), steps=2,
                        log_dir=tmp_path / "p1")
