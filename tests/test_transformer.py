"""Flagship transformer: forward/loss correctness and sharded training on
the virtual 8-device mesh (dp/tp/sp; MoE for ep)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.parallel import MeshConfig, build_mesh
from k8s_gpu_tpu.train import TrainConfig, Trainer

FAST_TC = TrainConfig(learning_rate=1e-3, warmup_steps=1)

TINY = TransformerConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_head=16,
    d_ff=128, max_seq=64,
)


def batch(key, b=4, s=32, vocab=256):
    toks = jax.random.randint(key, (b, s + 1), 0, vocab)
    return toks[:, :-1], toks[:, 1:]


def test_forward_shapes_and_dtype():
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    tokens, _ = batch(jax.random.PRNGKey(1))
    logits, aux = model.forward(params, tokens)
    assert logits.shape == (4, 32, 256)
    assert logits.dtype == jnp.float32
    assert float(aux) == 0.0  # dense model has no aux loss


def test_logical_axes_tree_matches_params():
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    axes = model.logical_axes()
    flat_p = jax.tree.leaves(params)
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    for p, a in zip(flat_p, flat_a):
        assert p.ndim == len(a), (p.shape, a)


def test_loss_decreases_single_device():
    model = TransformerLM(TINY)
    trainer = Trainer(model, mesh=build_mesh(MeshConfig(dp=1), n_devices=1), train_config=FAST_TC)
    trainer.init(jax.random.PRNGKey(0))
    tokens, targets = batch(jax.random.PRNGKey(1))
    losses = [trainer.step(tokens, targets) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_training_dp_tp_sp_mesh():
    """The full sharded train step compiles and runs on dp=2,sp=2,tp=2 —
    ring attention active, heads/mlp sharded."""
    model = TransformerLM(TINY)
    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2))
    trainer = Trainer(model, mesh=mesh, train_config=FAST_TC)
    trainer.init(jax.random.PRNGKey(0))
    tokens, targets = batch(jax.random.PRNGKey(1))
    l0 = trainer.step(tokens, targets)
    l1 = trainer.step(tokens, targets)
    l2 = trainer.step(tokens, targets)
    assert np.isfinite([l0, l1, l2]).all()
    assert l2 < l0


def test_sharded_matches_single_device_loss():
    """pjit-sharded forward == single-device forward (numerics parity)."""
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    tokens, targets = batch(jax.random.PRNGKey(1))
    mesh = build_mesh(MeshConfig(dp=2, sp=1, tp=2), n_devices=4)
    single = float(model.loss(params, tokens, targets))
    from k8s_gpu_tpu.parallel.sharding import ParamRules
    from jax.sharding import NamedSharding, PartitionSpec as P

    rules = ParamRules()
    shardings = jax.tree.map(
        lambda ax: rules.sharding(mesh, ax), model.logical_axes(),
        is_leaf=lambda x: isinstance(x, tuple),
    )
    sp_params = jax.device_put(params, shardings)
    sp_tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
    sp_targets = jax.device_put(targets, NamedSharding(mesh, P("dp", None)))
    sharded = float(
        jax.jit(lambda p, t, g: model.loss(p, t, g))(sp_params, sp_tokens, sp_targets)
    )
    assert abs(single - sharded) < 1e-2, (single, sharded)


def test_moe_forward_and_training():
    cfg = TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_head=16,
        d_ff=128, num_experts=4,
    )
    model = TransformerLM(cfg)
    mesh = build_mesh(MeshConfig(dp=2, ep=2, tp=2))
    trainer = Trainer(model, mesh=mesh, train_config=FAST_TC)
    trainer.init(jax.random.PRNGKey(0))
    tokens, targets = batch(jax.random.PRNGKey(1))
    params = trainer.params
    _, aux = model.forward(params, tokens)
    assert float(aux) > 0.0  # MoE aux loss present
    losses = [trainer.step(tokens, targets) for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_remat_off_matches_on():
    m_on = TransformerLM(TINY)
    import dataclasses

    m_off = TransformerLM(dataclasses.replace(TINY, remat=False))
    params = m_on.init(jax.random.PRNGKey(0))
    tokens, targets = batch(jax.random.PRNGKey(1))
    assert abs(
        float(m_on.loss(params, tokens, targets))
        - float(m_off.loss(params, tokens, targets))
    ) < 1e-5
