"""Gradient accumulation + ZeRO-1 optimizer-state sharding.

Accumulation contract: accum=N over batch B is the SAME optimizer step
as accum=1 over batch B (equal microbatches → mean-of-means), so the
trained params must match to reduction-order tolerance.

ZeRO-1 contract: with zero1=True each dp replica materializes 1/dp of
adam mu/nu (checked via addressable shard sizes), and the loss curve is
unchanged — the sharding annotation is the whole feature (GSPMD inserts
the update-time all-gather).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.parallel.mesh import MeshConfig, build_mesh, mesh_from_devices
from k8s_gpu_tpu.train import TrainConfig, Trainer


def _mesh(cfg: MeshConfig):
    sizes = {"dp": cfg.dp, "pp": cfg.pp, "ep": cfg.ep, "sp": cfg.sp,
             "tp": cfg.tp}
    n = 1
    for s in sizes.values():
        n *= max(1, s)
    return mesh_from_devices(jax.devices()[:n], cfg)


def _cfg():
    return TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=32, dtype=jnp.float32, use_flash=False,
        remat=False,
    )


def _batch(key, b=8, s=16):
    toks = jax.random.randint(key, (b, s + 1), 0, 128)
    return toks[:, :-1], toks[:, 1:]


def _train(tc, steps=3, mesh_cfg=None):
    model = TransformerLM(_cfg())
    tr = Trainer(
        model, mesh=_mesh(mesh_cfg or MeshConfig(dp=1)),
        train_config=tc,
    )
    tr.init(jax.random.PRNGKey(0))
    losses = []
    for i in range(steps):
        losses.append(tr.step(*_batch(jax.random.PRNGKey(10 + i))))
    return tr, losses


def test_step_many_matches_step_loop():
    """N steps fused into one lax.scan program (step_many — the zero-
    dispatch-overhead window bench.py measures) must walk params through
    the SAME trajectory as N step() calls, and sync=False steps must
    chain identically to synced ones."""
    tc = TrainConfig(warmup_steps=1)
    batches = [_batch(jax.random.PRNGKey(10 + i)) for i in range(3)]

    def fresh():
        model = TransformerLM(_cfg())
        tr = Trainer(model, mesh=_mesh(MeshConfig(dp=1)), train_config=tc)
        tr.init(jax.random.PRNGKey(0))
        return tr

    tr_loop = fresh()
    for x, y in batches[:-1]:
        tr_loop.step(x, y, sync=False)  # pipelined regime
    last_loop = tr_loop.step(*batches[-1])

    tr_many = fresh()
    xs = jnp.stack([x for x, _ in batches])
    ys = jnp.stack([y for _, y in batches])
    last_many = tr_many.step_many(xs, ys)

    assert last_many == pytest.approx(last_loop, rel=1e-5)
    for a, b in zip(jax.tree.leaves(tr_loop.params),
                    jax.tree.leaves(tr_many.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_fit_returns_one_loss_per_step():
    """Regression (ADVICE): fit() must honor its one-loss-per-step return
    contract — len(losses) == steps, every entry a host float — while
    still syncing only at log boundaries inside the loop (the trailing
    conversion blocks once, after the last dispatch)."""
    model = TransformerLM(_cfg())
    tr = Trainer(
        model, mesh=_mesh(MeshConfig(dp=1)),
        train_config=TrainConfig(warmup_steps=1),
    )
    tr.init(jax.random.PRNGKey(0))
    batches = iter([_batch(jax.random.PRNGKey(10 + i)) for i in range(7)])
    losses = tr.fit(batches, steps=7, log_every=3)
    assert len(losses) == 7
    assert all(isinstance(x, float) and np.isfinite(x) for x in losses)
    # parity with an explicit step loop: same data, same trajectory
    tr2 = Trainer(
        TransformerLM(_cfg()), mesh=_mesh(MeshConfig(dp=1)),
        train_config=TrainConfig(warmup_steps=1),
    )
    tr2.init(jax.random.PRNGKey(0))
    want = [
        float(tr2.step(*_batch(jax.random.PRNGKey(10 + i))))
        for i in range(7)
    ]
    assert losses == pytest.approx(want)


def test_grad_accum_parity():
    tr1, l1 = _train(TrainConfig(warmup_steps=1))
    tr4, l4 = _train(TrainConfig(warmup_steps=1, grad_accum_steps=4))
    np.testing.assert_allclose(l1, l4, rtol=2e-5)
    for a, b in zip(jax.tree.leaves(tr1.params), jax.tree.leaves(tr4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_grad_accum_must_divide_batch():
    tr = Trainer(
        TransformerLM(_cfg()), mesh=_mesh(MeshConfig(dp=1)),
        train_config=TrainConfig(grad_accum_steps=3),
    )
    tr.init(jax.random.PRNGKey(0))
    with pytest.raises(Exception):  # 8 % 3 != 0 → reshape error
        tr.step(*_batch(jax.random.PRNGKey(1)))


def test_zero1_shards_optimizer_state():
    mesh_cfg = MeshConfig(dp=4, tp=2)
    tr, losses = _train(
        TrainConfig(warmup_steps=1, zero1=True), mesh_cfg=mesh_cfg,
    )
    dp = 4
    sharded = 0
    for leaf in jax.tree.leaves(tr.opt_state):
        if leaf.ndim == 0 or leaf.size < dp:
            continue
        spec_names = {
            n for part in leaf.sharding.spec if part
            for n in (part if isinstance(part, tuple) else (part,))
        }
        if "dp" in spec_names:
            sharded += 1
            local = leaf.addressable_shards[0].data.size
            assert local <= leaf.size // dp, (leaf.shape, local)
    assert sharded >= 10  # mu+nu for every major weight leaf

    # parity: the annotation must not change the math
    tr0, losses0 = _train(TrainConfig(warmup_steps=1), mesh_cfg=mesh_cfg)
    np.testing.assert_allclose(losses, losses0, rtol=2e-5)


def test_zero1_noop_without_dp():
    tr, _ = _train(
        TrainConfig(warmup_steps=1, zero1=True),
        mesh_cfg=MeshConfig(dp=1, tp=2),
    )
    for leaf in jax.tree.leaves(tr.opt_state):
        spec_names = {
            n for part in leaf.sharding.spec if part
            for n in (part if isinstance(part, tuple) else (part,))
        }
        assert "dp" not in spec_names


def test_accum_rejected_with_1f1b():
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=32, dtype=jnp.float32, use_flash=False,
        remat=False, pp_schedule="1f1b",
    )
    tr = Trainer(
        TransformerLM(cfg), mesh=_mesh(MeshConfig(dp=2, pp=2, tp=2)),
        train_config=TrainConfig(grad_accum_steps=2),
    )
    tr.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="pp_microbatches"):
        tr.step(*_batch(jax.random.PRNGKey(1)))


# -- schedule / EMA / eval ---------------------------------------------------

def test_cosine_schedule_decays():
    """Probes make_schedule — the exact object make_optimizer wires in."""
    from k8s_gpu_tpu.train.runner import make_optimizer, make_schedule

    sched = make_schedule(TrainConfig(
        warmup_steps=2, schedule="cosine", decay_steps=10,
        learning_rate=1e-2, min_lr_frac=0.1,
    ))
    assert float(sched(0)) == 0.0
    assert abs(float(sched(2)) - 1e-2) < 1e-9           # warmup peak
    assert float(sched(12)) < float(sched(4))           # decaying
    assert abs(float(sched(200)) - 1e-3) < 1e-8         # floor at 10%
    const = make_schedule(TrainConfig(warmup_steps=2, learning_rate=1e-2))
    assert abs(float(const(500)) - 1e-2) < 1e-9         # constant holds
    with pytest.raises(ValueError, match="unknown schedule"):
        make_optimizer(TrainConfig(schedule="bogus"))


def test_ema_tracks_params():
    tr, _ = _train(TrainConfig(warmup_steps=1, ema_decay=0.5), steps=4)
    assert tr.ema is not None
    # EMA lags but moves toward the params: closer to final params than
    # the init was, and not equal to them.
    p = jax.tree.leaves(tr.params)
    e = jax.tree.leaves(tr.ema)
    diff = sum(float(jnp.abs(a - b).sum()) for a, b in zip(p, e))
    assert diff > 0  # lagging
    # one more step shrinks the gap (decay 0.5 halves it each step)
    prev = diff
    tr.step(*_batch(jax.random.PRNGKey(99)))
    diff2 = sum(
        float(jnp.abs(a - b).sum())
        for a, b in zip(jax.tree.leaves(tr.params), jax.tree.leaves(tr.ema))
    )
    assert diff2 < prev * 1.5  # bounded; EMA follows


def test_evaluate_lm_perplexity():
    from k8s_gpu_tpu.train import evaluate_lm

    model = TransformerLM(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, 128)
    out = evaluate_lm(model, params, [toks, toks])
    assert out["tokens"] == 2 * 4 * 16
    import math

    assert abs(out["perplexity"] - math.exp(out["nll"])) < 1e-6
    # untrained model ~ uniform: ppl near vocab size
    assert 40 < out["perplexity"] < 400
    with pytest.raises(ValueError, match="no evaluation tokens"):
        evaluate_lm(model, params, [])


def test_ema_checkpoint_roundtrip(tmp_path):
    """EMA survives save/resume — a resumed run must not blend a shadow
    of the fresh init into the average (code-review r3)."""
    from k8s_gpu_tpu.train.checkpoint import attach_to_trainer

    tr, _ = _train(TrainConfig(warmup_steps=1, ema_decay=0.5), steps=3)
    ckpt, save, _ = attach_to_trainer(tr, tmp_path / "ck")
    save(3)
    ema_before = [np.asarray(x) for x in jax.tree.leaves(tr.ema)]
    ckpt.close()

    # fresh trainer, same config: resume must restore the SAVED ema
    tr2 = Trainer(
        TransformerLM(_cfg()), mesh=_mesh(MeshConfig(dp=1)),
        train_config=TrainConfig(warmup_steps=1, ema_decay=0.5),
    )
    tr2.init(jax.random.PRNGKey(123))  # different init than tr
    ckpt2, _, resume = attach_to_trainer(tr2, tmp_path / "ck")
    step = resume()
    assert step == 3
    for a, b in zip(ema_before, jax.tree.leaves(tr2.ema)):
        np.testing.assert_array_equal(a, np.asarray(b))
    ckpt2.close()


def test_pre_ema_checkpoint_reseeds_shadow(tmp_path):
    """A checkpoint written WITHOUT ema re-seeds the shadow from the
    restored params on resume, not from the fresh init."""
    from k8s_gpu_tpu.train.checkpoint import attach_to_trainer

    tr, _ = _train(TrainConfig(warmup_steps=1), steps=2)  # no EMA
    ckpt, save, _ = attach_to_trainer(tr, tmp_path / "ck")
    save(2)
    ckpt.close()

    tr2 = Trainer(
        TransformerLM(_cfg()), mesh=_mesh(MeshConfig(dp=1)),
        train_config=TrainConfig(warmup_steps=1, ema_decay=0.9),
    )
    tr2.init(jax.random.PRNGKey(123))
    ckpt2, _, resume = attach_to_trainer(tr2, tmp_path / "ck")
    resume()
    for p, e in zip(jax.tree.leaves(tr2.params), jax.tree.leaves(tr2.ema)):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(e))
    ckpt2.close()


def test_evaluate_lm_compile_cached_and_mesh():
    """Repeat evals reuse one compiled forward; mesh evaluates sharded."""
    from k8s_gpu_tpu.train import evaluate_lm
    from k8s_gpu_tpu.train.evaluate import _batch_nll_fn

    model = TransformerLM(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    f1 = _batch_nll_fn(model, None)
    f2 = _batch_nll_fn(model, None)
    assert f1 is f2  # same compiled fn across calls
    mesh = _mesh(MeshConfig(dp=2, tp=2))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, 128)
    out = evaluate_lm(model, params, [toks], mesh=mesh)
    ref = evaluate_lm(model, params, [toks])
    assert abs(out["nll"] - ref["nll"]) < 1e-5
