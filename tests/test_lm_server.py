"""LM serving HTTP surface: tokenize/generate/health endpoints over a
tiny trained model (the serving half of the platform's workload story)."""

import json
import urllib.request

import jax
import numpy as np
import pytest

from k8s_gpu_tpu.data import BpeTokenizer
from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import LmServer


@pytest.fixture(scope="module")
def server():
    corpus = "the cat sat on the mat. the dog sat on the log. " * 40
    tok = BpeTokenizer.train(corpus, vocab_size=300)
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, use_flash=False,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    srv = LmServer(model, params, tok).start()
    yield srv
    srv.stop()


def _post(srv, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health(server):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/healthz"
    ) as r:
        assert json.loads(r.read())["ok"] is True


def test_tokenize(server):
    code, out = _post(server, "/tokenize", {"text": "the cat sat"})
    assert code == 200 and out["count"] == len(out["ids"]) > 0


def test_generate(server):
    code, out = _post(server, "/generate",
                      {"prompt": "the cat", "max_new_tokens": 8})
    assert code == 200
    assert out["generated_tokens"] >= 1
    assert isinstance(out["text"], str)
    assert out["prompt_tokens"] > 0


def test_generate_deterministic_greedy(server):
    a = _post(server, "/generate", {"prompt": "the dog", "max_new_tokens": 6})[1]
    b = _post(server, "/generate", {"prompt": "the dog", "max_new_tokens": 6})[1]
    assert a["ids"] == b["ids"]  # temperature 0 = greedy


def test_generate_errors(server):
    code, out = _post(server, "/generate", {"prompt": ""})
    assert code == 400
    code, out = _post(server, "/generate", {"prompt": "x " * 400})
    assert code == 400 and "too long" in out["error"]


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="env: jaxlib CPU backend raises 'Multiprocess computations "
    "aren't implemented on the CPU backend' — the dist-psum workload "
    "launches real jax.distributed worker processes and needs a TPU/GPU "
    "host",
)
def test_dist_psum_workload():
    from k8s_gpu_tpu.train.registry import get_workload

    class Spec:
        workload_args = {"processes": 2, "devices_per_host": 2}

    out = get_workload("dist-psum-smoke")(Spec(), None)
    assert out["global_devices"] == 4
    assert out["psum"] == 1 * 2 + 2 * 2  # (proc+1) x devices


def test_bad_parameter_types_are_400(server):
    code, out = _post(server, "/generate",
                      {"prompt": "hi", "max_new_tokens": "lots"})
    assert code == 400 and "bad parameter" in out["error"]
    code, out = _post(server, "/tokenize", {"text": 42})
    assert code == 400
    code, out = _post(server, "/generate", [1, 2])
    assert code == 400 and "object" in out["error"]


def test_left_pad_bucketing_matches_unpadded(server):
    """The server's pow2 bucketing + pad_left must not change greedy
    output vs a direct unpadded engine call."""
    import jax.numpy as jnp

    code, out = _post(server, "/generate",
                      {"prompt": "the cat", "max_new_tokens": 6})
    assert code == 200
    ids = server.tokenizer.encode("the cat")
    direct = server.batcher.engine.generate(
        server.batcher.params, jnp.asarray(ids, jnp.int32)[None, :],
        max_new_tokens=8,
    )
    direct_ids = jax.device_get(direct.tokens[0])[:6].tolist()
    assert out["ids"] == direct_ids


def test_prompt_bucket_top_half_not_rejected():
    """ADVICE r1: prompts longer than max_seq/2 must still bucket (the old
    pow2-only scheme silently halved capacity)."""
    from k8s_gpu_tpu.serve.batcher import prompt_bucket

    assert prompt_bucket(10, 64) == 16
    assert prompt_bucket(33, 64) == 48       # top half: ¾ bucket
    assert prompt_bucket(50, 64) == 56       # near-full: max_seq-8 bucket
    assert prompt_bucket(56, 64) == 56
    assert prompt_bucket(57, 64) is None     # true limit is max_seq-8


def test_streaming_generate(server):
    """stream:true returns newline-delimited JSON: one {"id"} event per
    token, then a summary event — and the ids match the non-streaming
    greedy response."""
    code, plain = _post(server, "/generate",
                        {"prompt": "the cat", "max_new_tokens": 5})
    assert code == 200
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/generate",
        data=json.dumps({"prompt": "the cat", "max_new_tokens": 5,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        assert r.status == 200
        assert r.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(l) for l in r.read().splitlines() if l.strip()]
    *events, summary = lines
    assert summary["done"] is True
    assert [e["id"] for e in events] == plain["ids"]
    assert summary["generated_tokens"] == len(events)
    assert summary["text"] == plain["text"]


def test_concurrent_http_requests_interleave(server):
    """Two HTTP generates in flight at once: the batcher must interleave
    them (shared decode steps), not serialize."""
    import threading

    results = {}

    def go(name, prompt, n):
        results[name] = _post(server, "/generate",
                              {"prompt": prompt, "max_new_tokens": n})

    # Timing race under load: if thread B's HTTP post lags until A has
    # already drained, no shared round EXISTS to observe — retry a few
    # times and fail only if no attempt ever interleaves.
    for attempt in range(3):
        before = server.batcher.steps_taken
        ta = threading.Thread(target=go, args=("a", "the cat sat", 24))
        tb = threading.Thread(target=go, args=("b", "the dog", 24))
        ta.start(); tb.start(); ta.join(); tb.join()
        assert results["a"][0] == 200 and results["b"][0] == 200
        log = [e for e in server.batcher.interleave_log if e[0] >= before]
        slots = {s for _, s in log}
        if len(slots) >= 2:
            steps = {s: {st for st, sl in log if sl == s} for s in slots}
            vals = list(steps.values())
            if vals[0] & vals[1]:
                return
    raise AssertionError("requests were serialized in all 3 attempts")


def test_precache_endpoint(server):
    """/precache installs a prefix; a /generate whose prompt extends it
    returns the same stream as before caching (parity through HTTP)."""
    prompt = "the cat sat on the mat. the dog"
    code, cold = _post(server, "/generate",
                       {"prompt": prompt, "max_new_tokens": 6})
    assert code == 200
    code, out = _post(server, "/precache",
                      {"prompt": "the cat sat on the mat."})
    assert code == 200 and out["cached_tokens"] > 0
    code, warm = _post(server, "/generate",
                       {"prompt": prompt, "max_new_tokens": 6})
    assert code == 200
    assert warm["ids"] == cold["ids"]
    code, err = _post(server, "/precache", {"prompt": ""})
    assert code == 400


def test_generate_logprobs_field(server):
    code, out = _post(server, "/generate",
                      {"prompt": "the cat", "max_new_tokens": 4,
                       "logprobs": True})
    assert code == 200
    assert len(out["logprobs"]) == len(out["ids"])
    assert all(lp <= 0.0 for lp in out["logprobs"])
    code, out2 = _post(server, "/generate",
                       {"prompt": "the cat", "max_new_tokens": 4})
    assert "logprobs" not in out2
