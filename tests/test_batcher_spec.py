"""Batcher-level speculative decoding: draft+verify inside the
continuous batcher's shared rounds (VERDICT r3 ask #2).

The contract, in strength order:
1. greedy streams are BIT-exact vs the plain batcher/oracle for ANY
   draft — a random draft only slows rounds down, never changes tokens;
2. a good draft yields measured acceptance > 0 (spec_stats), and a
   distilled draft beats a random-init one;
3. interleaving still holds — co-tenants share verify rounds;
4. seeded sampled streams are co-tenant-independent (per-row keys).
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher, distill_draft

TINY = TransformerConfig(
    vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
    d_ff=96, max_seq=64, use_flash=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    draft_cfg = dataclasses.replace(TINY, n_layers=1, d_model=32, d_ff=64)
    draft_model = TransformerLM(draft_cfg)
    draft_params = draft_model.init(jax.random.PRNGKey(7))
    return model, params, draft_model, draft_params


def _reference_greedy(model, params, ids, n):
    seq = jnp.asarray(ids, jnp.int32)[None, :]
    out = []
    for _ in range(n):
        logits, _ = model.forward(params, seq)
        nxt = int(jnp.argmax(logits[:, -1], axis=-1)[0])
        out.append(nxt)
        seq = jnp.concatenate([seq, jnp.asarray([[nxt]], jnp.int32)], axis=1)
    return out


def test_greedy_exact_with_random_draft(setup):
    """A random-init draft accepts ~nothing — and the stream must STILL
    be bit-exact greedy: acceptance is performance, exactness is
    structural (accepted tokens ARE target argmaxes)."""
    model, params, draft_model, draft_params = setup
    b = ContinuousBatcher(
        model, params, slots=2, draft=(draft_model, draft_params),
        spec_k=3,
    ).start()
    try:
        for ids in ([5, 9, 17], [3, 1, 4, 1, 5]):
            got = b.submit(ids, max_new_tokens=7).result()
            assert got == _reference_greedy(model, params, ids, 7)
    finally:
        b.stop()


def test_greedy_exact_and_full_acceptance_with_perfect_draft(setup):
    """Target-as-draft: every proposal matches the target argmax, so
    acceptance is ~1.0 and the stream is still oracle-exact."""
    model, params, _, _ = setup
    b = ContinuousBatcher(
        model, params, slots=2, draft=(model, params), spec_k=3,
    ).start()
    try:
        ids = [5, 9, 17]
        got = b.submit(ids, max_new_tokens=9).result()
        assert got == _reference_greedy(model, params, ids, 9)
        st = b.spec_stats
        assert st["drafted"] > 0
        # The perfect draft's proposals all match; only budget-truncated
        # final windows can count below 1.0.
        assert st["acceptance"] > 0.8, st
    finally:
        b.stop()


def test_concurrent_spec_requests_interleave_and_match(setup):
    model, params, draft_model, draft_params = setup
    b = ContinuousBatcher(
        model, params, slots=4, draft=(draft_model, draft_params),
        spec_k=2,
    ).start()
    try:
        ids_a, ids_b = [5, 9, 17], [2, 4, 8]
        ref_a = _reference_greedy(model, params, ids_a, 8)
        ref_b = _reference_greedy(model, params, ids_b, 8)
        ha = b.submit(ids_a, max_new_tokens=8)
        hb = b.submit(ids_b, max_new_tokens=8)
        assert ha.result() == ref_a
        assert hb.result() == ref_b
        rounds = {}
        for rnd, slot in b.interleave_log:
            rounds.setdefault(rnd, set()).add(slot)
        assert any(len(s) > 1 for s in rounds.values()), (
            "no round carried tokens from both requests"
        )
    finally:
        b.stop()


def test_spec_eos_and_budget(setup):
    """EOS inside an accepted window retires the row mid-window; budget
    clips a window that runs past max_new."""
    model, params, _, _ = setup
    ids = [5, 9, 17]
    ref = _reference_greedy(model, params, ids, 12)
    eos = ref[4]
    want = ref[: ref.index(eos)]
    b = ContinuousBatcher(
        model, params, slots=2, eos_id=eos, draft=(model, params),
        spec_k=3,
    ).start()
    try:
        assert b.submit(ids, max_new_tokens=12).result() == want
        # budget shorter than one window's worth
        exp2 = want[:2]
        b2 = b.submit(ids, max_new_tokens=2).result()
        assert b2 == exp2
    finally:
        b.stop()


def test_spec_with_prefix_cache_zero_seated_draft(setup):
    """Prefix-cache admission seats a ZEROED draft row (no draft K/V for
    the prefix) — acceptance may suffer, the greedy stream must not."""
    model, params, draft_model, draft_params = setup
    b = ContinuousBatcher(
        model, params, slots=2, draft=(draft_model, draft_params),
        spec_k=2,
    ).start()
    try:
        prefix = [7, 3, 11, 2, 9, 1, 8, 4]
        b.precache_prefix(prefix)
        ids = prefix + [5, 6]
        got = b.submit(ids, max_new_tokens=6).result()
        assert got == _reference_greedy(model, params, ids, 6)
    finally:
        b.stop()


def test_seeded_sampled_stream_co_tenant_independent(setup):
    """A seeded temperature>0 request must produce the same stream alone
    and next to a greedy co-tenant: per-row keys, per-row warps."""
    model, params, draft_model, draft_params = setup

    def run(with_neighbor):
        b = ContinuousBatcher(
            model, params, slots=3, draft=(draft_model, draft_params),
            spec_k=2,
        ).start()
        try:
            h = b.submit([5, 9, 17], max_new_tokens=6, temperature=0.8,
                         seed=42)
            if with_neighbor:
                b.submit([2, 4, 8], max_new_tokens=6)
            return h.result()
        finally:
            b.stop()

    assert run(False) == run(True)


def test_distilled_draft_beats_random(setup):
    """distill_draft's measured acceptance must beat the random-init
    draft's on the same traffic — the number the bench reports.

    The traffic is a SEEDED fixed prompt set (in-distribution for the
    soft distillation, which samples the target's own continuations):
    on uniformly random prompts the tiny distilled draft's argmaxes
    matched the target's 0% of the time and the comparison degenerated
    to 0.0 > 0.0 — a coin-flip test, not evidence (failed at the
    PR 5/6 HEADs for exactly that)."""
    model, params, _, _ = setup

    def acceptance(dm, dp):
        b = ContinuousBatcher(
            model, params, slots=2, draft=(dm, dp), spec_k=3,
        ).start()
        try:
            for ids in ([5, 9, 17], [3, 1, 4, 1, 5], [2, 4, 8]):
                b.submit(ids, max_new_tokens=10).result()
            return b.spec_stats["acceptance"]
        finally:
            b.stop()

    dm, dp, kl = distill_draft(
        model, params, steps=120, batch=8, seq_len=48,
        key=jax.random.PRNGKey(1),
    )
    rand_params = dm.init(jax.random.PRNGKey(99))
    acc_rand = acceptance(dm, rand_params)
    acc_dist = acceptance(dm, dp)
    assert acc_dist > acc_rand, (acc_dist, acc_rand, kl)
    assert acc_dist > 0.0


def test_onpolicy_hard_label_distill_high_acceptance(setup):
    """Hard-label distillation on the serving prompts' own greedy
    trajectories (the production-traffic setup): measured acceptance on
    that workload must be high even for a weak target whose argmax
    function doesn't generalize — greedy spec accepts iff argmaxes
    agree, and on-policy hard labels train exactly that."""
    import jax.numpy as jnp

    model, params, _, _ = setup
    ids = [5, 9, 17]
    prompts = jnp.asarray(ids, jnp.int32)[None]  # greedy: 1 row suffices
    dm, dp, loss = distill_draft(
        model, params, steps=200, seq_len=32,
        key=jax.random.PRNGKey(2), data_temperature=0.0,
        hard_labels=True, prompts=prompts,
    )
    b = ContinuousBatcher(
        model, params, slots=2, draft=(dm, dp), spec_k=3,
    ).start()
    try:
        got = b.submit(ids, max_new_tokens=12).result()
        assert got == _reference_greedy(model, params, ids, 12)
        assert b.spec_stats["acceptance"] > 0.5, (b.spec_stats, loss)
    finally:
        b.stop()


def test_spec_with_moe_target(setup):
    """MoE target: the verify's full-capacity expert routing must match
    width-1 decode routing exactly (extend_multi's moe_full_capacity),
    or greedy exactness would break — this is the test that pins it
    inside the BATCHER's spec rounds."""
    cfg = dataclasses.replace(TINY, num_experts=4, d_ff=64)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(5))
    plain = ContinuousBatcher(model, params, slots=2).start()
    try:
        want = plain.submit([5, 9, 17], max_new_tokens=8).result()
    finally:
        plain.stop()
    spec = ContinuousBatcher(
        model, params, slots=2, draft=(model, params), spec_k=3,
    ).start()
    try:
        got = spec.submit([5, 9, 17], max_new_tokens=8).result()
        assert got == want, (got, want)
        assert spec.spec_stats["acceptance"] > 0.5
    finally:
        spec.stop()


def test_constraints_plus_draft_rejected(setup):
    model, params, draft_model, draft_params = setup
    from k8s_gpu_tpu.serve.constrain import ConstraintBank

    bank = ConstraintBank({"d": "[0-9]+"}, ["x"] * TINY.vocab_size)
    with pytest.raises(ValueError, match="speculative"):
        ContinuousBatcher(
            model, params, slots=2, eos_id=0, constraints=bank,
            draft=(draft_model, draft_params),
        )
