"""Fleet telemetry plane (ISSUE 6): metrics federation across replicas,
per-tenant SLO accounting, and the per-request journal.  Deterministic
under FakeClock — in-process fake scrape targets, no network except the
endpoint tests' loopback.  Named test_fleet_telemetry so it sorts early
inside the tier-1 870 s window."""

import json
import math
import time
import urllib.request

import pytest

from k8s_gpu_tpu.serve.journal import RequestJournal, RequestRecord
from k8s_gpu_tpu.utils.alerts import RuleEvaluator, default_rule_pack
from k8s_gpu_tpu.utils.clock import FakeClock
from k8s_gpu_tpu.utils.federation import FleetCollector, bucket_quantile
from k8s_gpu_tpu.utils.metrics import MetricsRegistry, parse_exposition
from k8s_gpu_tpu.utils.obs import (
    MetricsServer,
    render_fleet,
    render_requests,
    render_top_columns,
)


# -- exposition hardening (satellite 1) --------------------------------------

def test_exposition_escaped_labels_roundtrip():
    """Label values carrying quotes, backslashes, and newlines survive a
    render → parse round trip against the registry's OWN output — a
    tenant string is caller data, and before escaping any of these
    broke the line format."""
    reg = MetricsRegistry()
    nasty = ['he said "hi"', "back\\slash", "multi\nline", 'mix\\"\n"']
    for i, v in enumerate(nasty):
        reg.inc("c_total", float(i + 1), tenant=v)
        reg.set_gauge("g", float(i), tenant=v)
    fam = parse_exposition(reg.render())
    for i, v in enumerate(nasty):
        assert fam["c_total"][(("tenant", v),)] == float(i + 1)
        assert fam["g"][(("tenant", v),)] == float(i)


def test_exposition_nan_inf_values_parse():
    reg = MetricsRegistry()
    reg.set_gauge("a", float("nan"))
    reg.set_gauge("b", float("inf"), k="x")
    reg.set_gauge("c", float("-inf"))
    fam = parse_exposition(reg.render())
    assert math.isnan(fam["a"][()])
    assert fam["b"][(("k", "x"),)] == float("inf")
    assert fam["c"][()] == float("-inf")
    # Prometheus-style spellings parse too (other exporters emit them).
    fam = parse_exposition('x{le="+Inf"} 5\ny NaN\n')
    assert fam["x"][(("le", "+Inf"),)] == 5.0
    assert math.isnan(fam["y"][()])


def test_exposition_skips_malformed_lines():
    fam = parse_exposition(
        "# comment\n"
        "\n"
        "no_value\n"
        "bad value notanumber\n"
        "ok 1.5\n"
        'half{broken="x 2\n'
    )
    assert fam == {"ok": {(): 1.5}}


# -- the federation collector -------------------------------------------------

def _three_replicas():
    regs = {f"r{i}": MetricsRegistry() for i in range(3)}
    for i, reg in enumerate(regs.values()):
        reg.set_gauge("serve_slot_fill_ratio", 0.25 * (i + 1))
        reg.set_gauge("serve_kv_occupancy_ratio", 0.2 * (i + 1))
        reg.set_gauge("serve_pending_requests", float(i))
        reg.inc("http_requests_total", 10.0 * (i + 1), code="200")
        reg.inc("serve_tenant_tokens_total", 100.0 * (i + 1),
                tenant="acme")
    return regs


def test_fleet_relabels_and_applies_policies():
    regs = _three_replicas()
    fc = FleetCollector(
        {n: (lambda r=r: r.render()) for n, r in regs.items()},
        clock=FakeClock(),
    )
    assert fc.scrape_once() == {"r0": True, "r1": True, "r2": True}
    reg = fc.registry
    # Relabel: every source series exists with replica=.
    assert reg.gauge("serve_slot_fill_ratio", replica="r1") == 0.5
    assert reg.gauge("http_requests_total", code="200",
                     replica="r2") == 30.0
    # Gauge aggregates: stored under the same name, no replica label.
    assert reg.gauge("serve_slot_fill_ratio") == pytest.approx(0.5)  # avg
    assert reg.gauge("serve_kv_occupancy_ratio") == pytest.approx(0.6)  # max
    assert reg.gauge("serve_pending_requests") == 3.0                 # sum
    # Counters: NO stored aggregate — the fleet sum is read-time (a
    # stored sum would double every ctx.rate over the family).
    assert reg.gauge("http_requests_total", code="200") is None
    series = reg.series("http_requests_total")
    assert sum(series.values()) == 60.0 and len(series) == 3
    # Liveness gauges.
    assert reg.gauge("fleet_replicas") == 3.0
    assert reg.gauge("fleet_replicas_up") == 3.0
    assert reg.gauge("fleet_replica_up", replica="r0") == 1.0


def test_fleet_two_runs_bit_identical():
    """The acceptance bar: two scripted runs — scrapes, mutations, a
    replica death and revival, rule evaluation — produce a bit-identical
    fleet registry exposition AND alert timeline."""

    def run():
        regs = _three_replicas()
        clock = FakeClock()
        alive = {n: True for n in regs}

        def target(n):
            def t():
                if not alive[n]:
                    raise RuntimeError("dead")
                return regs[n].render()
            return t

        fc = FleetCollector({n: target(n) for n in regs}, clock=clock,
                            down_after=2)
        ev = RuleEvaluator(default_rule_pack(), clock=clock,
                           registry=fc.registry)
        fc.attach(ev)
        ev.evaluate_once()
        clock.advance(10.0)
        regs["r1"].set_gauge("serve_kv_occupancy_ratio", 0.97)
        ev.evaluate_once()
        alive["r2"] = False
        for _ in range(3):
            clock.advance(10.0)
            ev.evaluate_once()
        alive["r2"] = True
        clock.advance(10.0)
        ev.evaluate_once()
        timeline = [
            (t["t"], t["alert"], tuple(sorted(t["labels"].items())),
             t["from"], t["to"])
            for t in ev.timeline
        ]
        return fc.registry.render(), timeline

    (render_a, tl_a), (render_b, tl_b) = run(), run()
    assert render_a == render_b
    assert tl_a == tl_b
    # The scripted run includes a FleetReplicaDown fire/resolve cycle.
    down = [(frm, to) for _, alert, _, frm, to in tl_a
            if alert == "FleetReplicaDown"]
    assert down == [("inactive", "pending"), ("pending", "firing"),
                    ("firing", "resolved")]


def test_replica_death_purges_series_and_alert_resolves_on_revival():
    regs = _three_replicas()
    clock = FakeClock()
    alive = {n: True for n in regs}

    def target(n):
        def t():
            if not alive[n]:
                raise RuntimeError("dead")
            return regs[n].render()
        return t

    fc = FleetCollector({n: target(n) for n in regs}, clock=clock,
                        down_after=3)
    ev = RuleEvaluator(default_rule_pack(), clock=clock,
                       registry=fc.registry)
    fc.attach(ev)
    ev.evaluate_once()
    alive["r2"] = False
    # Two failures: still counted up (down_after=3), series intact.
    for _ in range(2):
        clock.advance(10.0)
        ev.evaluate_once()
    assert fc.registry.gauge("fleet_replica_up", replica="r2") == 1.0
    assert fc.registry.gauge(
        "serve_slot_fill_ratio", replica="r2") == 0.75
    assert not any(a["alertname"] == "FleetReplicaDown"
                   for a in ev.active_alerts())
    # Third consecutive failure: down, purged, firing.
    clock.advance(10.0)
    ev.evaluate_once()
    assert fc.registry.gauge("fleet_replica_up", replica="r2") == 0.0
    assert fc.registry.gauge(
        "serve_slot_fill_ratio", replica="r2") is None
    assert fc.registry.counter(
        "fleet_scrape_failures_total", replica="r2") == 3.0
    firing = [a for a in ev.active_alerts()
              if a["alertname"] == "FleetReplicaDown"]
    assert len(firing) == 1 and firing[0]["state"] == "firing"
    assert firing[0]["labels"] == {"replica": "r2"}
    # The aggregate dropped the dead member (max over r0/r1 only).
    assert fc.registry.gauge(
        "serve_kv_occupancy_ratio") == pytest.approx(0.4)
    # Revival: up again, series restored, alert resolves.
    alive["r2"] = True
    clock.advance(10.0)
    ev.evaluate_once()
    assert fc.registry.gauge("fleet_replica_up", replica="r2") == 1.0
    assert fc.registry.gauge(
        "serve_slot_fill_ratio", replica="r2") == 0.75
    assert not any(a["alertname"] == "FleetReplicaDown"
                   for a in ev.active_alerts())
    assert ev.timeline[-1]["to"] == "resolved"


def test_fleet_vanished_source_series_removed_on_next_scrape():
    """A gauge the replica stopped exporting (remove_gauge on the
    source) leaves the fleet registry too — scrapes replace, never
    accrete."""
    reg = MetricsRegistry()
    reg.set_gauge("pool_ready_ratio", 0.5, pool="p1")
    fc = FleetCollector({"r0": lambda: reg.render()}, clock=FakeClock())
    fc.scrape_once()
    assert fc.registry.gauge(
        "pool_ready_ratio", pool="p1", replica="r0") == 0.5
    reg.remove_gauge("pool_ready_ratio", pool="p1")
    fc.scrape_once()
    assert fc.registry.gauge(
        "pool_ready_ratio", pool="p1", replica="r0") is None
    assert fc.registry.gauge("pool_ready_ratio", pool="p1") is None


def test_fleet_percentile_merges_buckets_across_replicas():
    regs = {"a": MetricsRegistry(), "b": MetricsRegistry()}
    # Replica a: 9 fast (≤10 ms); replica b: 9 slow (≤500 ms) — the
    # fleet p95 must land in b's range, each replica's own in its own.
    for _ in range(9):
        regs["a"].observe("serve_ttft_seconds", 0.008)
        regs["b"].observe("serve_ttft_seconds", 0.4)
    fc = FleetCollector(
        {n: (lambda r=r: r.render()) for n, r in regs.items()},
        clock=FakeClock(),
    )
    fc.scrape_once()
    fleet = fc.percentile("serve_ttft_seconds", 0.95)
    fast = fc.percentile("serve_ttft_seconds", 0.95, replica="a")
    slow = fc.percentile("serve_ttft_seconds", 0.95, replica="b")
    assert fast is not None and fast <= 0.01 + 1e-9
    assert slow is not None and 0.1 <= slow <= 0.5
    assert fleet is not None and 0.1 <= fleet <= 0.5
    # Degenerate inputs answer None, never raise.
    assert bucket_quantile({}, 0.95) is None
    assert fc.percentile("no_such_metric", 0.5) is None


def test_fleet_snapshot_shape_and_tenant_table():
    regs = _three_replicas()
    fc = FleetCollector(
        {n: (lambda r=r: r.render()) for n, r in regs.items()},
        clock=FakeClock(),
    )
    fc.scrape_once()
    snap = fc.snapshot()
    assert [r["replica"] for r in snap["replicas"]] == ["r0", "r1", "r2"]
    assert all(r["up"] for r in snap["replicas"])
    assert snap["replicas"][1]["gauges"]["serve_slot_fill_ratio"] == 0.5
    assert snap["aggregates"]["serve_pending_requests"]["value"] == 3.0
    assert snap["aggregates"]["serve_pending_requests"]["agg"] == "sum"
    assert snap["tenants"]["acme"]["tokens"] == 600.0
    # JSON-serializable end to end (the /fleet contract).
    json.dumps(snap)
    # Renderers accept the same shape.
    out = render_fleet(snap)
    assert "r0" in out and "acme" in out
    cols = render_top_columns(snap)
    assert "FLEET" in cols and "r2" in cols and "(sum)" in cols


# -- request journal ----------------------------------------------------------

def test_journal_ring_bounds_and_filters():
    j = RequestJournal(maxlen=4)
    for i in range(10):
        j.append(RequestRecord(
            tenant="acme" if i % 2 else "blue",
            reason="eos" if i < 8 else "deadline",
            tokens=i, trace_id=f"t{i}",
        ))
    assert len(j) == 4 and j.dropped == 6
    recs = j.snapshot()
    # Newest first, only the last 4 survive the ring.
    assert [r["tokens"] for r in recs] == [9, 8, 7, 6]
    assert [r["tokens"] for r in j.snapshot(limit=2)] == [9, 8]
    assert j.snapshot(limit=0) == []
    assert [r["tokens"] for r in j.snapshot(tenant="acme")] == [9, 7]
    assert [r["tokens"] for r in j.snapshot(reason="deadline")] == [9, 8]
    assert [r["tokens"] for r in j.snapshot(trace_id="t7")] == [7]


def test_fleet_and_requests_endpoints():
    regs = _three_replicas()
    fc = FleetCollector(
        {n: (lambda r=r: r.render()) for n, r in regs.items()},
        clock=FakeClock(),
    )
    j = RequestJournal()
    j.append(RequestRecord(tenant="acme", reason="eos", tokens=3,
                           trace_id="abc123"))
    j.append(RequestRecord(tenant="blue", reason="deadline", tokens=0,
                           deadline_expired=True))
    srv = MetricsServer(MetricsRegistry(), fleet=fc, journal=j).start()
    try:
        # Never-scraped collector scrapes lazily on first /fleet read.
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/fleet"
        ) as r:
            snap = json.loads(r.read())
        assert snap["tenants"]["acme"]["tokens"] == 600.0
        assert len(snap["replicas"]) == 3
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/requests?tenant=acme"
        ) as r:
            body = json.loads(r.read())
        assert [x["trace_id"] for x in body["requests"]] == ["abc123"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/requests?reason=deadline"
        ) as r:
            body = json.loads(r.read())
        assert len(body["requests"]) == 1
        assert body["requests"][0]["deadline_expired"] is True
    finally:
        srv.stop()
    # Without a collector/journal the routes 404.
    srv = MetricsServer(MetricsRegistry()).start()
    try:
        for path in ("/fleet", "/debug/requests"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}"
                )
            assert ei.value.code == 404
    finally:
        srv.stop()


def test_render_requests_handles_empty_and_spec_columns():
    assert "no journal records" in render_requests([])
    out = render_requests([RequestRecord(
        tenant="acme", reason="budget", path="paged_shared", tokens=8,
        queue_wait_s=0.002, ttft_s=0.05, tpot_s=0.01, prefix_blocks=3,
        spec_drafted=16, spec_accepted=12, trace_id="deadbeef",
    ).to_dict()])
    assert "paged_shared" in out and "75%" in out and "deadbeef" in out


# -- tenant accounting through a real batcher --------------------------------

@pytest.fixture(scope="module")
def tiny_lm():
    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8,
        d_ff=64, max_seq=48, use_flash=False, dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def test_batcher_tenant_accounting_and_journal(tiny_lm):
    """One tiny batcher, its own registry: tenant-labeled latency and
    token counters land per tenant, a deadline shed counts in the total
    but not the goodput, and every retired request has a journal record
    whose trace id resolves in the tracer (/debug/traces cross-link)."""
    from k8s_gpu_tpu.serve import ContinuousBatcher
    from k8s_gpu_tpu.utils.tracing import global_tracer

    model, params = tiny_lm
    reg = MetricsRegistry()
    b = ContinuousBatcher(model, params, slots=2, metrics=reg).start()
    try:
        with global_tracer.span("test.acme"):
            h1 = b.submit([1, 2, 3], max_new_tokens=4, tenant="acme")
        h2 = b.submit([4, 5, 6], max_new_tokens=4, tenant="blue")
        h3 = b.submit([7, 8], max_new_tokens=4, tenant="acme",
                      deadline=time.monotonic() - 0.001)
        assert len(h1.result()) == 4 and len(h2.result()) == 4
        assert h3.result() == [] and h3.deadline_expired
        # Totals count every request's tokens; goodput only in-budget.
        assert reg.counter("serve_tenant_tokens_total",
                           tenant="acme") == 4.0
        assert reg.counter("serve_tenant_goodput_tokens_total",
                           tenant="acme") == 4.0
        assert reg.counter("serve_tenant_tokens_total",
                           tenant="blue") == 4.0
        assert reg.counter("serve_shed_total", reason="deadline",
                           tenant="acme") == 1.0
        # Latency series: unlabeled aggregate AND per-tenant.
        assert reg.histogram("serve_ttft_seconds").n >= 2
        assert reg.histogram("serve_ttft_seconds", tenant="acme").n == 1
        assert reg.histogram("serve_ttft_seconds", tenant="blue").n == 1
        # Journal: one record per request, reasons right.
        recs = b.journal.snapshot()
        assert len(recs) == 3
        reasons = sorted(r["reason"] for r in recs)
        assert reasons == ["budget", "budget", "deadline"]
        done = [r for r in recs
                if r["tenant"] == "acme" and r["reason"] == "budget"][0]
        assert done["tokens"] == 4 and done["ttft_s"] > 0.0
        assert done["queue_wait_s"] >= 0.0 and done["path"]
        # Trace cross-link: the traced submit's record resolves.
        assert done["trace_id"]
        assert global_tracer.get_trace(done["trace_id"]) is not None
        shed = [r for r in recs if r["reason"] == "deadline"][0]
        assert shed["tokens"] == 0 and shed["deadline_expired"]
    finally:
        b.stop()


def test_tenant_cardinality_bounded_through_batcher(tiny_lm):
    """A flood of distinct tenant strings cannot mint unbounded series:
    past the registry cap the batcher's tenant counters collapse into
    the {other="true"} overflow series."""
    from k8s_gpu_tpu.serve import ContinuousBatcher

    model, params = tiny_lm
    reg = MetricsRegistry(max_series_per_name=4)
    b = ContinuousBatcher(model, params, slots=2, metrics=reg).start()
    try:
        handles = [
            b.submit([1, 2], max_new_tokens=1, tenant=f"tenant-{i}")
            for i in range(8)
        ]
        for h in handles:
            assert len(h.result()) == 1
    finally:
        b.stop()
    series = reg.series("serve_tenant_tokens_total")
    # 4 real tenant series + the single collapsed overflow series.
    assert len(series) == 5
    assert reg.counter("serve_tenant_tokens_total", other="true") == 4.0
    assert reg.counter(
        "metrics_series_dropped_total",
        metric="serve_tenant_tokens_total",
    ) > 0.0


def test_lm_server_tenant_extraction_and_door_shed_journal(tiny_lm):
    """The HTTP tenant contract: body field first, x-tenant header as
    fallback, length-capped; the pre-submit 504 shed lands in the
    batcher's registry AND journal.  HTTP surface only — the batcher
    scheduler never starts, no device program compiles here."""
    from k8s_gpu_tpu.data import BpeTokenizer
    from k8s_gpu_tpu.serve import LmServer

    model, params = tiny_lm
    tok = BpeTokenizer.train("aa bb cc dd " * 30, vocab_size=80)
    reg = MetricsRegistry()
    srv = LmServer(model, params, tok, metrics=reg)
    srv._thread.start()
    try:
        seen = []

        class FakeHandle:
            aborted = False
            deadline_expired = False
            logprobs = []

            def result(self):
                return [1]

        def fake_submit(ids, **kw):
            seen.append(kw)
            return FakeHandle()

        srv.batcher.submit = fake_submit

        def post(payload, headers=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json",
                         **(headers or {})},
            )
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, _ = post({"prompt": "aa", "tenant": "body-tenant"},
                       headers={"x-tenant": "header-tenant"})
        assert code == 200 and seen[-1]["tenant"] == "body-tenant"
        code, _ = post({"prompt": "aa"},
                       headers={"x-tenant": "header-tenant"})
        assert code == 200 and seen[-1]["tenant"] == "header-tenant"
        code, _ = post({"prompt": "aa"})
        assert code == 200 and seen[-1]["tenant"] == "default"
        code, _ = post({"prompt": "aa", "tenant": "x" * 200})
        assert code == 200 and len(seen[-1]["tenant"]) == 64
        code, _ = post({"prompt": "aa", "tenant": 7})
        assert code == 400
        # Door shed: expired budget → 504 + counter + journal record.
        code, _ = post({"prompt": "aa", "tenant": "late"},
                       headers={"x-request-deadline-ms": "0"})
        assert code == 504
        assert reg.counter("serve_shed_total", reason="deadline",
                           tenant="late") == 1.0
        recs = srv.journal.snapshot(tenant="late")
        assert len(recs) == 1 and recs[0]["reason"] == "deadline"
    finally:
        srv._httpd.shutdown()
        srv._httpd.server_close()
