"""TrainJob gang scheduling + autoscaler: the end-to-end job path
(SURVEY §3.2 call-stack parity) and scale-from-zero (BASELINE config 5)."""

import pytest

from k8s_gpu_tpu.api import TpuPodSlice, TrainJob
from k8s_gpu_tpu.cloud import FakeCloudTpu, cloudtpu_client_factory
from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.operators import (
    SliceAutoscaler,
    TpuPodSliceReconciler,
    TrainJobReconciler,
)
from k8s_gpu_tpu.platform import expand_template, parse_template
from k8s_gpu_tpu.utils.clock import FakeClock


@pytest.fixture
def harness(kube: FakeKube, clock: FakeClock):
    cloud = FakeCloudTpu(clock=clock)
    mgr = Manager(kube, clock=clock)
    mgr.register(
        "TpuPodSlice", TpuPodSliceReconciler(kube, cloudtpu_client_factory(cloud))
    )
    mgr.register("TrainJob", TrainJobReconciler(kube), name="trainjob")
    mgr.register("TrainJob", SliceAutoscaler(kube), name="autoscaler")
    mgr.start()
    yield kube, clock, cloud, mgr
    mgr.stop()


def make_pool(kube, accel="v4-8", count=1, name="pool"):
    ps = TpuPodSlice()
    ps.metadata.name = name
    ps.spec.accelerator_type = accel
    ps.spec.slice_count = count
    kube.create(ps)


def make_job(accel="v4-8", name="job1", workload="psum-smoke", slices=1):
    job = TrainJob()
    job.metadata.name = name
    job.spec.accelerator_type = accel
    job.spec.workload = workload
    job.spec.slice_count = slices
    job.spec.mode = "single" if slices == 1 else "multislice"
    from k8s_gpu_tpu.cloud.topology import parse_accelerator_type

    job.spec.num_workers = parse_accelerator_type(accel).hosts * slices
    return job


def wait_phase(kube, mgr, clock, name, want, ticks=40):
    for _ in range(ticks):
        mgr.wait_idle()
        job = kube.try_get("TrainJob", name)
        if job is not None and job.status.phase == want:
            return job
        clock.advance(5.1)
    raise AssertionError(
        f"{name} never reached {want}; now "
        f"{kube.try_get('TrainJob', name).status.phase}: "
        f"{kube.try_get('TrainJob', name).status.message}"
    )


def test_job_runs_on_existing_pool(harness):
    """SURVEY §3.2: job submitted → gang placed on slice → workload runs →
    Succeeded with result."""
    kube, clock, cloud, mgr = harness
    make_pool(kube, "v4-8")
    kube.create(make_job("v4-8"))
    job = wait_phase(kube, mgr, clock, "job1", "Succeeded")
    assert job.status.result["ok"]
    assert len(job.status.placements) == 2  # one per v4-8 host
    assert len(set(job.status.placements.values())) == 2
    pods = [p for p in kube.list("Pod") if p.metadata.labels.get("job") == "job1"]
    assert all(p.phase == "Succeeded" for p in pods)


def test_job_from_template_end_to_end(harness):
    kube, clock, cloud, mgr = harness
    make_pool(kube, "v4-8")
    tpl = parse_template(
        "title: t\nworkload: cnn-train\nspec:\n  singleInstanceType: tpu-v4-8\n"
    )
    job = expand_template(tpl, "tjob")
    kube.create(job)
    done = wait_phase(kube, mgr, clock, "tjob", "Succeeded")
    assert done.status.result["last_loss"] < done.status.result["first_loss"]


def test_job_pending_without_capacity_reports_insufficient(kube, clock):
    """Without the autoscaler registered, a job with no pool must surface
    Pending + InsufficientCapacity, then place once a pool appears."""
    cloud = FakeCloudTpu(clock=clock)
    mgr = Manager(kube, clock=clock)
    mgr.register(
        "TpuPodSlice", TpuPodSliceReconciler(kube, cloudtpu_client_factory(cloud))
    )
    mgr.register("TrainJob", TrainJobReconciler(kube), name="trainjob")
    mgr.start()
    try:
        kube.create(make_job("v4-8"))
        assert mgr.wait_idle(
            predicate=lambda: (
                kube.get("TrainJob", "job1").status.phase == "Pending"
            )
        )
        cur = kube.get("TrainJob", "job1")
        assert "insufficient capacity" in cur.status.message
        conds = {c.type: (c.status, c.reason) for c in cur.status.conditions}
        assert conds["Schedulable"] == ("False", "InsufficientCapacity")
        make_pool(kube, "v4-8")
        job = wait_phase(kube, mgr, clock, "job1", "Succeeded")
        assert job.status.result["ok"]
    finally:
        mgr.stop()


def test_scale_from_zero_on_pending_job(harness):
    """BASELINE config 5: no capacity anywhere → pending job triggers pool
    creation from zero → job completes → pool scales back to zero."""
    kube, clock, cloud, mgr = harness
    kube.create(make_job("v4-8", name="cold-start"))
    job = wait_phase(kube, mgr, clock, "cold-start", "Succeeded")
    assert job.status.result["ok"]
    pool = kube.get("TpuPodSlice", "autoscale-v4-8")
    assert pool.metadata.labels["tpu.k8sgpu.dev/autoscale"] == "true"
    # After success, autoscaler returns the pool to zero.
    for _ in range(20):
        mgr.wait_idle()
        pool = kube.get("TpuPodSlice", "autoscale-v4-8")
        if pool.spec.slice_count == 0:
            break
        clock.advance(5.1)
    assert pool.spec.slice_count == 0


def test_two_jobs_share_pool_capacity_serially(harness):
    """Second gang must wait until the first releases the slice (capacity
    accounting via running pods)."""
    kube, clock, cloud, mgr = harness
    make_pool(kube, "v4-8", count=1)
    kube.create(make_job("v4-8", name="a"))
    kube.create(make_job("v4-8", name="b"))
    ja = wait_phase(kube, mgr, clock, "a", "Succeeded")
    jb = wait_phase(kube, mgr, clock, "b", "Succeeded")
    assert ja.status.placements and jb.status.placements


def test_multislice_job_lands_on_distinct_slices(harness):
    kube, clock, cloud, mgr = harness
    make_pool(kube, "v4-8", count=2)
    kube.create(make_job("v4-8", name="ms", slices=2))
    job = wait_phase(kube, mgr, clock, "ms", "Succeeded")
    nodes = {n.metadata.name: n for n in kube.list("Node")}
    slices_used = {
        nodes[nn].metadata.labels["tpu.k8sgpu.dev/slice"]
        for nn in job.status.placements.values()
    }
    assert len(slices_used) == 2


def test_workload_failure_marks_job_failed(harness):
    from k8s_gpu_tpu.train.registry import register_workload

    @register_workload("always-fails")
    def _fail(spec, placements):
        raise RuntimeError("boom")

    kube, clock, cloud, mgr = harness
    make_pool(kube, "v4-8")
    kube.create(make_job("v4-8", name="bad", workload="always-fails"))
    job = wait_phase(kube, mgr, clock, "bad", "Failed")
    assert "boom" in job.status.message
    pods = [p for p in kube.list("Pod") if p.metadata.labels.get("job") == "bad"]
    assert all(p.phase == "Failed" for p in pods)


def test_unexpanded_job_fails_cleanly(harness):
    kube, clock, cloud, mgr = harness
    job = TrainJob()
    job.metadata.name = "raw"
    kube.create(job)
    j = wait_phase(kube, mgr, clock, "raw", "Failed", ticks=5)
    assert "not expanded" in j.status.message


def test_same_name_jobs_in_two_namespaces_account_capacity(harness):
    """Regression (code review): ns-A job 'train' running must block ns-B
    job 'train' from double-booking the same slice."""
    kube, clock, cloud, mgr = harness
    make_pool(kube, "v4-8", count=1)
    ja = make_job("v4-8", name="train", workload="psum-smoke")
    ja.metadata.namespace = "ns-a"
    jb = make_job("v4-8", name="train", workload="psum-smoke")
    jb.metadata.namespace = "ns-b"
    kube.create(ja)
    kube.create(jb)
    for _ in range(40):
        mgr.wait_idle()
        a = kube.get("TrainJob", "train", "ns-a")
        b = kube.get("TrainJob", "train", "ns-b")
        if {a.status.phase, b.status.phase} == {"Succeeded"}:
            break
        clock.advance(5.1)
    assert a.status.phase == "Succeeded" and b.status.phase == "Succeeded"


def test_delete_running_job_releases_pods_and_pool(harness):
    """Regression (code review): deleting a job must remove its worker Pods
    (freeing slice capacity) and let the autoscaler retire its pool."""
    kube, clock, cloud, mgr = harness
    kube.create(make_job("v4-8", name="doomed"))
    wait_phase(kube, mgr, clock, "doomed", "Succeeded")
    kube.delete("TrainJob", "doomed")
    assert mgr.wait_idle(
        predicate=lambda: kube.try_get("TrainJob", "doomed") is None
    )
    pods = [p for p in kube.list("Pod") if p.metadata.labels.get("job") == "doomed"]
    assert pods == []
    for _ in range(10):
        mgr.wait_idle()
        pool = kube.try_get("TpuPodSlice", "autoscale-v4-8")
        if pool is not None and pool.spec.slice_count == 0:
            break
        clock.advance(5.1)
    assert kube.get("TpuPodSlice", "autoscale-v4-8").spec.slice_count == 0
