"""TpuPodSlice reconcile integration: queued-resource lifecycle, node joins
with ICI-topology labels, multislice, preemption self-healing — BASELINE
configs 2-4 on the fake Cloud TPU backend.
"""

import pytest

from k8s_gpu_tpu.api import TpuPodSlice
from k8s_gpu_tpu.cloud import FakeCloudTpu, cloudtpu_client_factory
from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.operators import TpuPodSliceReconciler
from k8s_gpu_tpu.scheduling import (
    LABEL_ACCELERATOR,
    LABEL_SLICE,
    LABEL_SLICE_INDEX,
    LABEL_TOPOLOGY,
    LABEL_WORKER_ID,
    TPU_RESOURCE,
)
from k8s_gpu_tpu.utils.clock import FakeClock


@pytest.fixture
def harness(kube: FakeKube, clock: FakeClock):
    cloud = FakeCloudTpu(clock=clock)
    mgr = Manager(kube, clock=clock)
    mgr.register(
        "TpuPodSlice", TpuPodSliceReconciler(kube, cloudtpu_client_factory(cloud))
    )
    mgr.start()
    yield kube, clock, cloud, mgr
    mgr.stop()


def make_ps(accel="v4-8", count=1, name="trainer"):
    ps = TpuPodSlice()
    ps.metadata.name = name
    ps.spec.accelerator_type = accel
    ps.spec.slice_count = count
    return ps


def phase(kube, want, name="trainer"):
    def check():
        ps = kube.try_get("TpuPodSlice", name)
        return ps is not None and ps.status.phase == want

    return check


def test_v4_8_reconciles_to_ready(harness):
    """BASELINE config 2: v4-8 single-slice 0→Ready."""
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))
    ps = kube.get("TpuPodSlice", "trainer")
    assert ps.status.ready_replicas == 1
    assert ps.status.slices[0].nodes_ready == 2  # v4-8 = 2 hosts
    nodes = kube.list("Node")
    assert len(nodes) == 2
    assert sum(n.capacity[TPU_RESOURCE] for n in nodes) == 8


def test_v5p_64_node_labels_and_device_plugin(harness):
    """BASELINE config 3: v5p-64 joins 16 nodes with ICI-topology labels and
    google.com/tpu capacity."""
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v5p-64"))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))
    nodes = kube.list("Node")
    assert len(nodes) == 16  # 64 chips / 4 per host
    for n in nodes:
        assert n.metadata.labels[LABEL_ACCELERATOR] == "v5p-64"
        assert n.metadata.labels[LABEL_TOPOLOGY] == "4x4x4"
        assert n.capacity[TPU_RESOURCE] == 4
        assert n.ready
    ids = sorted(int(n.metadata.labels[LABEL_WORKER_ID]) for n in nodes)
    assert ids == list(range(16))
    assert sum(n.capacity[TPU_RESOURCE] for n in nodes) == 64


def test_multislice_2x_v5e_256(harness):
    """BASELINE config 4: 2×v5e-256 multislice — distinct slice labels and
    slice indices for DCN-aware anti-affinity."""
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v5e-256", count=2))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"), timeout=60)
    ps = kube.get("TpuPodSlice", "trainer")
    assert ps.status.ready_replicas == 2
    nodes = kube.list("Node")
    assert len(nodes) == 64  # 2 slices × 32 hosts
    slices = {n.metadata.labels[LABEL_SLICE] for n in nodes}
    assert len(slices) == 2
    indices = {n.metadata.labels[LABEL_SLICE_INDEX] for n in nodes}
    assert indices == {"0", "1"}


def test_queued_then_provisioning_then_ready(harness):
    """The QR ladder ACCEPTED→WAITING→PROVISIONING→ACTIVE is surfaced in
    status.phase while the 5 s poll drives it forward."""
    kube, clock, cloud, mgr = harness
    cloud.accepted_delay = 10.0
    cloud.provisioning_delay = 60.0
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle()
    assert kube.get("TpuPodSlice", "trainer").status.phase == "Queued"
    for _ in range(40):
        clock.advance(5.1)
        mgr.wait_idle()
        if kube.get("TpuPodSlice", "trainer").status.phase == "Ready":
            break
    assert kube.get("TpuPodSlice", "trainer").status.phase == "Ready"


def test_stockout_holds_in_queued(harness):
    kube, clock, cloud, mgr = harness
    cloud.faults.stockout = True
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle()
    for _ in range(3):
        clock.advance(5.1)
        mgr.wait_idle()
    assert kube.get("TpuPodSlice", "trainer").status.phase == "Queued"
    cloud.faults.stockout = False
    clock.advance(5.1)
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))


def test_provisioning_failure_recreates_qr(harness):
    """FAILED queued resource → deleted and recreated (self-healing,
    SURVEY §5.3)."""
    kube, clock, cloud, mgr = harness
    cloud.faults.fail_provisioning = 1
    kube.create(make_ps("v4-8"))
    for _ in range(10):
        clock.advance(5.1)
        mgr.wait_idle()
        if kube.get("TpuPodSlice", "trainer").status.phase == "Ready":
            break
    assert kube.get("TpuPodSlice", "trainer").status.phase == "Ready"


def test_preemption_recovers(harness):
    """Spot preemption (SUSPENDED + unhealthy hosts) → recreate → Ready."""
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))
    cloud.preempt_slice("default-trainer-qr")
    clock.advance(61.0)  # resync notices
    for _ in range(10):
        clock.advance(5.1)
        mgr.wait_idle()
        if kube.get("TpuPodSlice", "trainer").status.phase == "Ready":
            break
    ps = kube.get("TpuPodSlice", "trainer")
    assert ps.status.phase == "Ready"
    assert ps.status.ready_replicas == 1


def test_accelerator_change_replaces_qr_and_nodes(harness):
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))
    ps = kube.get("TpuPodSlice", "trainer")
    ps.spec.accelerator_type = "v5p-64"
    kube.update(ps)
    assert mgr.wait_idle(
        predicate=lambda: (
            kube.get("TpuPodSlice", "trainer").status.phase == "Ready"
            and len(kube.list("Node")) == 16
        ),
        timeout=60,
    )
    for n in kube.list("Node"):
        assert n.metadata.labels[LABEL_ACCELERATOR] == "v5p-64"


def test_scale_to_zero_deletes_qr_and_nodes(harness):
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))
    ps = kube.get("TpuPodSlice", "trainer")
    ps.spec.slice_count = 0
    kube.update(ps)
    assert mgr.wait_idle(predicate=phase(kube, "Paused"))
    assert len(cloud.queued_resources) == 0
    assert len(kube.list("Node")) == 0


def test_delete_cr_finalizes_everything(harness):
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v5p-64"))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))
    kube.delete("TpuPodSlice", "trainer")
    assert mgr.wait_idle(
        predicate=lambda: kube.try_get("TpuPodSlice", "trainer") is None
    )
    assert len(cloud.queued_resources) == 0
    assert len(kube.list("Node")) == 0


def test_status_readyreplicas_parity_printer_columns(harness):
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v5e-256", count=2))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"), timeout=60)
    ps = kube.get("TpuPodSlice", "trainer")
    cols = ps.printer_columns
    assert cols["Desired"] == 2 and cols["Ready"] == 2
    assert cols["Accelerator"] == "v5e-256"


def test_runtime_version_drift_replaces_qr(harness):
    """Regression (code review): editing runtime_version/spot/reserved must
    replace the queued resource, not silently report Ready on the old one."""
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))
    ps = kube.get("TpuPodSlice", "trainer")
    ps.spec.runtime_version = "tpu-ubuntu2204-v2"
    kube.update(ps)
    assert mgr.wait_idle(
        predicate=lambda: (
            kube.get("TpuPodSlice", "trainer").status.phase == "Ready"
            and all(
                q.runtime_version == "tpu-ubuntu2204-v2"
                for q in cloud.queued_resources.values()
            )
            and len(cloud.queued_resources) == 1
        )
    )


def test_stray_qr_deletion_keeps_healthy_nodes(harness):
    """Regression (code review): cleaning up a stray tag-matched QR must not
    evict the healthy primary slice's nodes."""
    kube, clock, cloud, mgr = harness
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle(predicate=phase(kube, "Ready"))
    uids_before = {n.metadata.name: n.metadata.uid for n in kube.list("Node")}
    cloud.create_queued_resource(
        "stray", "v4-8", 1, "rt",
        {"managed-by": "tpupodslice-operator", "owner": "default-trainer"},
    )
    clock.advance(61.0)
    assert mgr.wait_idle(
        predicate=lambda: len(cloud.queued_resources) == 1
    )
    uids_after = {n.metadata.name: n.metadata.uid for n in kube.list("Node")}
    assert uids_before == uids_after  # same Node objects, never recreated


def test_same_name_pools_in_two_namespaces_do_not_fight(harness):
    """Regression (code review): ns1/trainer and ns2/trainer must own
    disjoint node sets and never prune each other's."""
    kube, clock, cloud, mgr = harness
    a = make_ps("v4-8")
    a.metadata.namespace = "ns1"
    b = make_ps("v4-8")
    b.metadata.namespace = "ns2"
    kube.create(a)
    kube.create(b)
    assert mgr.wait_idle(
        predicate=lambda: (
            (pa := kube.try_get("TpuPodSlice", "trainer", "ns1")) is not None
            and pa.status.phase == "Ready"
            and (pb := kube.try_get("TpuPodSlice", "trainer", "ns2")) is not None
            and pb.status.phase == "Ready"
        ),
        timeout=60,
    )
    nodes = kube.list("Node")
    assert len(nodes) == 4  # 2 hosts per pool
    pools = {n.metadata.labels["tpu.k8sgpu.dev/pool"] for n in nodes}
    assert pools == {"ns1.trainer", "ns2.trainer"}
    # A few resyncs later nothing has churned.
    uids = {n.metadata.name: n.metadata.uid for n in nodes}
    for _ in range(3):
        clock.advance(61.0)
        mgr.wait_idle()
    assert {n.metadata.name: n.metadata.uid for n in kube.list("Node")} == uids


def test_transient_failure_condition_clears_during_provisioning(harness):
    """Regression (code review): a transient list error must not leave
    Failed=True for the whole provisioning window."""
    kube, clock, cloud, mgr = harness
    cloud.provisioning_delay = 300.0
    cloud.faults.fail_lists = 1
    kube.create(make_ps("v4-8"))
    assert mgr.wait_idle()
    clock.advance(20.5)  # list retry fires, succeeds; QR still provisioning
    assert mgr.wait_idle()
    ps = kube.get("TpuPodSlice", "trainer")
    conds = {c.type: c.status for c in ps.status.conditions}
    assert ps.status.phase in ("Queued", "Provisioning")
    assert conds.get("Failed") == "False"


def test_malformed_topology_string_rejected(harness):
    from k8s_gpu_tpu.api import ValidationError
    import pytest as _pytest

    kube, clock, cloud, mgr = harness
    bad = make_ps("v4-8", name="bad")
    bad.spec.topology = "2x2xbanana"
    with _pytest.raises(ValidationError):
        kube.create(bad)
