"""Int8 KV cache (VERDICT r3 ask #3): per-(head, position) absmax
quantization of the pool cache — ~1.9× slot capacity at fixed HBM —
with decode-quality parity against the bf16/f32 cache."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher, InferenceEngine
from k8s_gpu_tpu.serve.engine import _empty_cache, _quantize_kv

TINY = TransformerConfig(
    vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
    d_ff=96, max_seq=64, use_flash=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(TINY)
    return model, model.init(jax.random.PRNGKey(0))


def test_quantize_roundtrip_error_bounded():
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 4, 16, 32))
    q, s = _quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (3, 4, 16)
    back = q.astype(jnp.float32) * s[..., None]
    # absmax int8: error per element <= scale/2 = amax/254
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    assert float(jnp.max(jnp.abs(back - x) / amax)) <= (1 / 254) + 1e-6


def test_cache_bytes_roughly_halved():
    import dataclasses

    bf = dataclasses.replace(TINY, dtype=jnp.bfloat16)
    dense = _empty_cache(bf, 8, 64)
    quant = _empty_cache(bf, 8, 64, kv_quant=True)
    dense_b = sum(x.nbytes for x in jax.tree.leaves(dense))
    quant_b = sum(x.nbytes for x in jax.tree.leaves(quant))
    # int8 + f32/d_head scales vs bf16: 0.5 + 2/d_head of the bytes —
    # exactly 1.5× capacity at this toy d_head=12; ~1.9× at d_head 128.
    assert quant_b < 0.7 * dense_b
    assert dense_b / quant_b >= 1.5


def _agreement(a, b):
    n = min(len(a), len(b))
    return sum(x == y for x, y in zip(a[:n], b[:n])) / max(n, 1)


def test_engine_generate_parity(setup):
    """Greedy decode with the int8 cache must track the f32-cache stream
    closely — quantization noise may flip a near-tie argmax, but the
    streams cannot diverge wholesale."""
    model, params = setup
    prompt = jnp.asarray([[5, 9, 17, 3]], jnp.int32)
    base = InferenceEngine(model).generate(
        params, prompt, max_new_tokens=16
    )
    quant = InferenceEngine(model, kv_quant=True).generate(
        params, prompt, max_new_tokens=16
    )
    a = [int(t) for t in base.tokens[0][: int(base.lengths[0])]]
    b = [int(t) for t in quant.tokens[0][: int(quant.lengths[0])]]
    assert _agreement(a, b) >= 0.8, (a, b)
    # prompt logits carry most of the signal un-quantized (only the
    # prefix K/V round-trips): they must be close
    np.testing.assert_allclose(
        np.asarray(base.prompt_logits), np.asarray(quant.prompt_logits),
        atol=0.15, rtol=0.1,
    )


def test_batcher_kv_quant_matches_engine_kv_quant(setup):
    """The int8-cache BATCHER stream equals the int8-cache one-shot
    engine's (same quantized numerics through a different write path:
    bucketed prefill + per-row scatter vs scalar geometry).  Exactness
    here mirrors the bf16 batcher-vs-engine parity contract."""
    model, params = setup
    ids = [5, 9, 17]
    eng = InferenceEngine(model, kv_quant=True)
    # left-pad to the batcher's bucket of 8 so prefill geometry matches
    pad = 8 - len(ids)
    padded = jnp.zeros((1, 8), jnp.int32).at[0, pad:].set(
        jnp.asarray(ids)
    )
    ref = eng.generate(params, padded, max_new_tokens=8, pad_left=pad)
    want = [int(t) for t in ref.tokens[0][: int(ref.lengths[0])]]
    b = ContinuousBatcher(model, params, slots=2, kv_quant=True).start()
    try:
        got = b.submit(ids, max_new_tokens=8).result()
        assert got == want, (got, want)
    finally:
        b.stop()


def test_batcher_kv_quant_interleaved_consistency(setup):
    """Two co-tenant int8-cache requests must not contaminate each
    other: each matches its own solo-run stream."""
    model, params = setup

    def solo(ids):
        b = ContinuousBatcher(model, params, slots=2, kv_quant=True).start()
        try:
            return b.submit(ids, max_new_tokens=8).result()
        finally:
            b.stop()

    ids_a, ids_b = [5, 9, 17], [2, 4, 8, 16]
    ref_a, ref_b = solo(ids_a), solo(ids_b)
    b = ContinuousBatcher(model, params, slots=2, kv_quant=True).start()
    try:
        ha = b.submit(ids_a, max_new_tokens=8)
        hb = b.submit(ids_b, max_new_tokens=8)
        assert ha.result() == ref_a
        assert hb.result() == ref_b
    finally:
        b.stop()


def test_kv_quant_composes_with_spec_and_gqa(setup):
    """int8 KV + speculative rounds + GQA in one batcher: the verify
    path's window writes quantize too, and greedy stays agreement-close
    to the quantized plain batcher (bit-exact: both run the SAME int8
    numerics)."""
    import dataclasses

    cfg = dataclasses.replace(TINY, n_kv_heads=2)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(3))
    ids = [5, 9, 17]
    plain = ContinuousBatcher(model, params, slots=2, kv_quant=True).start()
    try:
        want = plain.submit(ids, max_new_tokens=8).result()
    finally:
        plain.stop()
    spec = ContinuousBatcher(
        model, params, slots=2, kv_quant=True, draft=(model, params),
        spec_k=2,
    ).start()
    try:
        got = spec.submit(ids, max_new_tokens=8).result()
        assert got == want, (got, want)
    finally:
        spec.stop()


def test_precomputed_row_quant_mismatch_rejected(setup):
    """A disagg row prefilled without kv_quant must be rejected at
    submit (leaf mismatch), not crash the scheduler."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2, kv_quant=True).start()
    try:
        eng = InferenceEngine(model)  # dense rows
        cache, logits = eng.prefill(
            params, jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        )
        with pytest.raises(ValueError, match="kv_quant"):
            b.submit_precomputed(cache, logits, 4, 0)
    finally:
        b.stop()
