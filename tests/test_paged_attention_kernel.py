"""Fused Pallas paged-attention decode kernel — ROADMAP item 3 parity.

The kernel (`ops/paged_attention.py`) consumes block tables in-kernel,
so the one thing it must never do is read the wrong physical block.
Contract, bottom-up:

1. op PARITY: kernel output matches the gather-path oracle
   (`paged_attention_reference`, bit-identical math to the engine's
   `_paged_read` + `_attend_cached`) across MHA/GQA, single- and
   multi-query rows, f32/bf16, and ragged ``t_hi`` edges;
2. ISOLATION: poisoning the trash block and every block outside a row's
   table (another tenant's live data) changes NOTHING — a spec-decode
   overrun streams trash block 0, not a neighbor's KV;
3. int8-KV parity: the in-kernel dequant (scale applied in VMEM) agrees
   with the oracle exactly and with float attention within quant
   tolerance;
4. engine streams: a `paged_kernel` batcher is token-for-token identical
   to the gather batcher — greedy, sampled, speculative (ngram + neural
   + int8 draft), and int8-KV;
5. steady-state decode with the kernel enabled compiles ZERO new XLA
   executables (the conftest compile-telemetry guard).

Everything runs on CPU through the Pallas interpreter
(``interpret=None`` auto-selects it off-TPU) — same code path Mosaic
compiles on a real TPU, minus the tiling constraint.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
    supported,
)
from k8s_gpu_tpu.serve import ContinuousBatcher

PAGE = 8


def _setup(B, Sq, H, KH, Dh, MP, dtype, seed=0):
    """Random pool + valid page tables: row b owns blocks
    [1 + b*live, ...) (block 0 is the trash block), start mid-window."""
    rng = np.random.default_rng(seed)
    NB = 1 + B * MP
    q = jnp.asarray(rng.standard_normal((B, Sq, H, Dh)), dtype)
    k = jnp.asarray(rng.standard_normal((NB, KH, PAGE, Dh)), dtype)
    v = jnp.asarray(rng.standard_normal((NB, KH, PAGE, Dh)), dtype)
    pages = jnp.asarray(
        [[1 + b * MP + j for j in range(MP)] for b in range(B)], jnp.int32)
    return q, k, v, pages


@pytest.mark.parametrize(
    "H,KH,Sq,dtype,tol",
    [
        (2, 2, 1, jnp.float32, 2e-5),    # MHA single-token decode
        (4, 2, 1, jnp.float32, 2e-5),    # GQA
        (4, 1, 3, jnp.float32, 2e-5),    # MQA, multi-query (spec verify)
        (4, 2, 5, jnp.bfloat16, 5e-2),   # GQA wide row, low precision
    ],
)
def test_kernel_matches_oracle(H, KH, Sq, dtype, tol):
    q, k, v, pages = _setup(3, Sq, H, KH, 16, 4, dtype)
    t_hi = 3 * PAGE
    start = jnp.asarray([t_hi - Sq, PAGE + 1, 2 * PAGE - Sq], jnp.int32)
    kv_start = jnp.asarray([0, 2, PAGE], jnp.int32)
    ref = paged_attention_reference(
        q, k, v, pages, start, kv_start, page=PAGE, t_hi=t_hi)
    out = paged_attention(
        q, k, v, pages, start, kv_start, page=PAGE, t_hi=t_hi)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=tol)


@pytest.mark.parametrize("t_hi", [PAGE, 2 * PAGE, 4 * PAGE])
def test_ragged_t_hi_edges(t_hi):
    """The grid's trailing axis follows the decode bucket: one page,
    mid-table, and the full table must all agree with the oracle."""
    q, k, v, pages = _setup(2, 1, 2, 2, 16, 4, jnp.float32, seed=1)
    start = jnp.asarray([t_hi - 1, max(t_hi - PAGE, 0)], jnp.int32)
    kv_start = jnp.zeros((2,), jnp.int32)
    ref = paged_attention_reference(
        q, k, v, pages, start, kv_start, page=PAGE, t_hi=t_hi)
    out = paged_attention(
        q, k, v, pages, start, kv_start, page=PAGE, t_hi=t_hi)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5)


def test_trash_block_and_cross_tenant_isolation():
    """The regression the `_paged_read` hoist protects: rows whose table
    ends before ``p_hi`` stream trash block 0 (masked out), NEVER a high
    block index holding another tenant's live KV.  Poisoning the trash
    block and every foreign block must leave both paths bit-unchanged."""
    B, Sq, H, KH, Dh, MP = 2, 1, 2, 2, 16, 4
    q, k, v, pages = _setup(B, Sq, H, KH, Dh, MP, jnp.float32, seed=2)
    # Row tables end after 2 live pages; dead entries point at trash 0.
    pages = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
    t_hi = 4 * PAGE                       # bucket wider than either row
    start = jnp.asarray([2 * PAGE - 1, PAGE + 3], jnp.int32)
    kv_start = jnp.zeros((B,), jnp.int32)

    args = dict(page=PAGE, t_hi=t_hi)
    ref = paged_attention_reference(q, k, v, pages, start, kv_start, **args)
    out = paged_attention(q, k, v, pages, start, kv_start, **args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    # Poison trash block 0 and blocks 5.. (a third tenant's live data).
    k_p = k.at[0].set(1e4).at[5:].set(-1e4)
    v_p = v.at[0].set(1e4).at[5:].set(-1e4)
    ref_p = paged_attention_reference(
        q, k_p, v_p, pages, start, kv_start, **args)
    out_p = paged_attention(q, k_p, v_p, pages, start, kv_start, **args)
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out))
    np.testing.assert_array_equal(np.asarray(ref_p), np.asarray(ref))


def test_int8_kv_parity():
    """int8 pool + per-(block, head, slot) scales: kernel dequant-in-VMEM
    vs oracle is exact-ish (same math, different order); both stay within
    quant tolerance of float attention on the dequantized pool."""
    B, Sq, H, KH, Dh, MP = 2, 1, 4, 2, 16, 3
    qf, kf, vf, pages = _setup(B, Sq, H, KH, Dh, MP, jnp.float32, seed=3)
    t_hi = 3 * PAGE
    start = jnp.asarray([t_hi - 1, 2 * PAGE], jnp.int32)
    kv_start = jnp.zeros((B,), jnp.int32)

    def quant(x):                          # engine's _quantize_kv grain
        amax = jnp.max(jnp.abs(x), axis=-1)
        s = jnp.maximum(amax, 1e-8) / 127.0
        return (jnp.clip(jnp.round(x / s[..., None]), -127, 127)
                .astype(jnp.int8), s)

    kq, ks = quant(kf)
    vq, vs = quant(vf)
    args = dict(page=PAGE, t_hi=t_hi, k_scale=ks, v_scale=vs)
    ref = paged_attention_reference(
        qf, kq, vq, pages, start, kv_start, **args)
    out = paged_attention(qf, kq, vq, pages, start, kv_start, **args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    exact = paged_attention_reference(
        qf, kq.astype(jnp.float32) * ks[..., None],
        vq.astype(jnp.float32) * vs[..., None],
        pages, start, kv_start, page=PAGE, t_hi=t_hi)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(exact), atol=1e-4)


def test_supported_fallback_matrix():
    """Geometry gates always apply; Mosaic tiling gates only off the
    interpreter — the documented matrix in docs/platform/kv-cache.md."""
    shape = (2, 1, 4, 128)
    ok = dict(page=32, t_hi=64, max_pages=4)
    assert supported(shape, jnp.bfloat16, interpret=False, **ok)
    # Partial page / zero pages / table too narrow: never supported.
    assert not supported(shape, jnp.bfloat16, interpret=True,
                         page=32, t_hi=40, max_pages=4)
    assert not supported(shape, jnp.bfloat16, interpret=True,
                         page=32, t_hi=0, max_pages=4)
    assert not supported(shape, jnp.bfloat16, interpret=True,
                         page=32, t_hi=192, max_pages=4)
    # Tiling constraints bind on TPU only.
    assert not supported((2, 1, 4, 16), jnp.bfloat16, interpret=False, **ok)
    assert supported((2, 1, 4, 16), jnp.bfloat16, interpret=True, **ok)
    assert not supported(shape, jnp.int8, interpret=False,
                         page=16, t_hi=64, max_pages=4)
    assert supported(shape, jnp.int8, interpret=False,
                     page=32, t_hi=64, max_pages=4)


def test_fallback_result_matches_kernel():
    """An unsupported-on-TPU geometry routed through the fallback gives
    the same answer the kernel gives on the interpreter — the seam the
    engine relies on being invisible."""
    q, k, v, pages = _setup(2, 1, 2, 2, 16, 4, jnp.float32, seed=4)
    start = jnp.asarray([PAGE, 2 * PAGE + 1], jnp.int32)
    kv_start = jnp.zeros((2,), jnp.int32)
    # t_hi not a page multiple → fallback even on the interpreter.
    kw = dict(page=PAGE, t_hi=2 * PAGE, k_scale=None, v_scale=None)
    via_kernel = paged_attention(
        q, k, v, pages, start, kv_start, interpret=True, **kw)
    via_ref = paged_attention_reference(
        q, k, v, pages, start, kv_start, page=PAGE, t_hi=2 * PAGE)
    np.testing.assert_allclose(
        np.asarray(via_kernel), np.asarray(via_ref), atol=2e-5)


# -- engine-level stream parity ------------------------------------------------

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8,
    n_kv_heads=2, d_ff=64, max_seq=64, use_flash=False, dtype=jnp.float32,
)
MODEL = TransformerLM(CFG)
PARAMS = MODEL.init(jax.random.PRNGKey(0))

DRAFT_CFG = TransformerConfig(
    vocab_size=64, d_model=16, n_layers=1, n_heads=2, d_head=8,
    d_ff=32, max_seq=64, use_flash=False, dtype=jnp.float32,
)
DRAFT_MODEL = TransformerLM(DRAFT_CFG)
DRAFT_PARAMS = DRAFT_MODEL.init(jax.random.PRNGKey(1))

PROMPTS = [
    [3, 5, 7, 11, 2, 9, 3, 5, 7, 11],   # repetitive (ngram-friendly)
    [1, 2, 3, 4, 5, 6, 7, 8, 9],
    list(range(20, 45)),                 # crosses pages
]


def _run(reqs, **kw):
    kw.setdefault("paged_blocks", 24)
    kw.setdefault("page_size", 8)
    b = ContinuousBatcher(MODEL, PARAMS, slots=4, steps_per_round=4,
                          **kw).start()
    try:
        handles = [b.submit(ids, **r) for ids, r in reqs]
        return [h.result() for h in handles]
    finally:
        b.stop()


def test_engine_stream_parity_greedy_and_sampled():
    """Same batcher, kernel on/off: byte-identical token streams."""
    greedy = [(p, dict(max_new_tokens=10)) for p in PROMPTS]
    assert (_run(greedy, attn_impl="paged_kernel")
            == _run(greedy, attn_impl="gather"))
    sampled = [
        (p, dict(max_new_tokens=8, temperature=0.7 + 0.1 * i, seed=i + 1))
        for i, p in enumerate(PROMPTS)
    ]
    assert (_run(sampled, attn_impl="paged_kernel")
            == _run(sampled, attn_impl="gather"))


def test_engine_stream_parity_staggered_tables():
    """Staggered admits interleave the block allocator's assignments, so
    each slot's table is non-contiguous and neighbors' live blocks sit at
    indices just past a row's own — the cross-tenant layout the hoisted
    `_paged_read` bound and the trash-block guard both protect."""
    def staggered(attn_impl):
        b = ContinuousBatcher(MODEL, PARAMS, slots=4, paged_blocks=24,
                              page_size=8, steps_per_round=2,
                              attn_impl=attn_impl).start()
        try:
            h0 = b.submit(PROMPTS[2], max_new_tokens=14)
            h1 = b.submit(PROMPTS[0], max_new_tokens=6)
            r1 = h1.result()             # retires early: blocks recycle
            h2 = b.submit(PROMPTS[1], max_new_tokens=10)
            return [h0.result(), r1, h2.result()]
        finally:
            b.stop()

    assert staggered("paged_kernel") == staggered("gather")


def test_spec_decode_stream_parity():
    """Speculative verify reads multi-query rows through the kernel; the
    accept/reject outcome (hence the stream) must not move — ngram draft,
    neural draft, and the int8-compute draft all stay exact."""
    reqs = [(p, dict(max_new_tokens=10)) for p in PROMPTS[:2]]
    base = _run(reqs, attn_impl="gather")
    assert _run(reqs, attn_impl="paged_kernel",
                draft="ngram", spec_k=3) == base
    assert _run(reqs, attn_impl="paged_kernel",
                draft=(DRAFT_MODEL, DRAFT_PARAMS), spec_k=3) == base
    assert _run(reqs, attn_impl="paged_kernel",
                draft=(DRAFT_MODEL, DRAFT_PARAMS), spec_k=3,
                draft_int8=True) == base


def test_kv_quant_stream_parity():
    """int8 pool: both paths read the same quantized blocks, so streams
    agree even though they differ from the float streams."""
    reqs = [(p, dict(max_new_tokens=10)) for p in PROMPTS]
    assert (_run(reqs, attn_impl="paged_kernel", kv_quant=True)
            == _run(reqs, attn_impl="gather", kv_quant=True))


def test_steady_state_zero_recompile_with_kernel(xla_compiles):
    """The kernel call sits inside the decode trace — steady-state rounds
    with it enabled must compile zero new executables, same bar the
    gather path holds (test_analysis_selfcheck.py)."""
    b = ContinuousBatcher(MODEL, PARAMS, slots=2, paged_blocks=24,
                          page_size=8, attn_impl="paged_kernel").start()
    try:
        def wave():
            handles = [b.submit(p, max_new_tokens=5) for p in PROMPTS[:2]]
            return [h.result() for h in handles]

        warm = wave()
        wave()
        before = xla_compiles()
        steady1 = wave()
        steady2 = wave()
        assert xla_compiles() == before, (
            "paged kernel decode recompiled in steady state"
        )
        assert steady1 == warm and steady2 == warm
    finally:
        b.stop()
