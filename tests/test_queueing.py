"""SchedulingQueue admission: priority-then-FIFO, chip caps, closed queues
(C16 — the Volcano queue role, GPU调度平台搭建.md:273-287, 650)."""

import pytest

from k8s_gpu_tpu.api import SchedulingQueue, TrainJob
from k8s_gpu_tpu.controller import FakeKube
from k8s_gpu_tpu.controller.manager import Request
from k8s_gpu_tpu.scheduling import QueueAdmitter, QueueReconciler, job_chips


@pytest.fixture
def admitter(kube: FakeKube):
    return QueueAdmitter(kube)


def make_queue(kube, name, cap_tpu=0, closed=False):
    q = SchedulingQueue()
    q.metadata.name = name
    q.metadata.namespace = ""
    q.spec.cap_tpu = cap_tpu
    q.spec.closed = closed
    kube.create(q)


def make_job(kube, name, queue="default", priority=0, accel="v4-8",
             phase="", slices=1):
    j = TrainJob()
    j.metadata.name = name
    j.spec.queue = queue
    j.spec.priority = priority
    j.spec.accelerator_type = accel
    j.spec.slice_count = slices
    j.spec.mode = "single" if slices == 1 else "multislice"
    j.spec.num_workers = 1
    created = kube.create(j)
    if phase:
        created.status.phase = phase
        kube.update_status(created)
    return kube.get("TrainJob", name)


def test_job_chips(kube):
    j = make_job(kube, "j1", accel="v4-8")
    assert job_chips(j) == 8
    m = make_job(kube, "m1", accel="v5e-8", slices=4)
    assert job_chips(m) == 32


def test_default_queue_implicit(kube, admitter):
    j = make_job(kube, "j1")
    assert admitter.decide(j).admit


def test_unknown_queue_denied(kube, admitter):
    j = make_job(kube, "j1", queue="nope")
    d = admitter.decide(j)
    assert not d.admit and "unknown queue" in d.reason


def test_closed_queue_denied(kube, admitter):
    make_queue(kube, "drain", closed=True)
    j = make_job(kube, "j1", queue="drain")
    d = admitter.decide(j)
    assert not d.admit and "closed" in d.reason


def test_fifo_within_queue(kube, admitter):
    first = make_job(kube, "first")
    second = make_job(kube, "second")
    assert admitter.decide(first).admit
    d = admitter.decide(second)
    assert not d.admit and "behind default/first" in d.reason


def test_priority_jumps_fifo(kube, admitter):
    make_job(kube, "old", priority=0)
    vip = make_job(kube, "vip", priority=10)
    assert admitter.decide(vip).admit
    old = kube.get("TrainJob", "old")
    assert not admitter.decide(old).admit


def test_chip_cap_blocks_and_releases(kube, admitter):
    make_queue(kube, "team-q", cap_tpu=8)
    make_job(kube, "running", queue="team-q", phase="Running")
    j = make_job(kube, "j1", queue="team-q")
    d = admitter.decide(j)
    assert not d.admit and "chip cap" in d.reason
    # Completion releases the queue's share.
    done = kube.get("TrainJob", "running")
    done.status.phase = "Succeeded"
    kube.update_status(done)
    assert admitter.decide(kube.get("TrainJob", "j1")).admit


def test_oversized_job_is_fatal_not_wedging(kube, admitter):
    """A job that can never fit the queue cap is rejected fatally and does
    not head-of-line-block jobs behind it."""
    make_queue(kube, "small", cap_tpu=8)
    big = make_job(kube, "big", queue="small", accel="v4-16")  # 16 chips
    d = admitter.decide(big)
    assert not d.admit and d.fatal
    ok = make_job(kube, "ok", queue="small", accel="v4-8")
    assert admitter.decide(ok).admit


def test_queue_namespace_pinned(kube):
    from k8s_gpu_tpu.api import ValidationError

    q = SchedulingQueue()
    q.metadata.name = "q"  # ObjectMeta defaults namespace to "default"
    with pytest.raises(ValidationError, match="cluster-scoped"):
        kube.create(q)


def test_queue_timeout_applies_to_admission_block(kube, clock):
    """queue_timeout_s fires for queue-blocked jobs, not just
    capacity-blocked ones."""
    from k8s_gpu_tpu.controller import Manager
    from k8s_gpu_tpu.operators import TrainJobReconciler

    mgr = Manager(kube, clock=clock)
    mgr.register("TrainJob", TrainJobReconciler(kube), name="trainjob")
    mgr.start()
    try:
        make_queue(kube, "drain", closed=True)
        j = TrainJob()
        j.metadata.name = "j1"
        j.spec.queue = "drain"
        j.spec.accelerator_type = "v4-8"
        j.spec.num_workers = 2
        j.spec.queue_timeout_s = 0.5
        kube.create(j)
        import time as _time

        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            mgr.wait_idle()
            cur = kube.get("TrainJob", "j1")
            if cur.status.phase == "Failed":
                break
            clock.advance(5.1)
        assert cur.status.phase == "Failed"
        assert "timeout" in cur.status.message
    finally:
        mgr.stop()


def test_queue_status_reconcile(kube):
    make_queue(kube, "team-q", cap_tpu=32)
    make_job(kube, "r1", queue="team-q", phase="Running")
    make_job(kube, "p1", queue="team-q")
    make_job(kube, "s1", queue="team-q", phase="Succeeded")
    QueueReconciler(kube).reconcile(Request("", "team-q"))
    q = kube.get("SchedulingQueue", "team-q", "")
    assert (q.status.running, q.status.pending, q.status.completed) == (1, 1, 1)
    assert q.status.chips_in_use == 8


def test_reconciler_integration_fifo_order(kube, clock):
    """Through the live TrainJob reconciler: a capped queue runs jobs one
    at a time in FIFO order; the blocked job carries Admitted=False."""
    from k8s_gpu_tpu.api import TpuPodSlice
    from k8s_gpu_tpu.api.types import get_condition
    from k8s_gpu_tpu.cloud import FakeCloudTpu, cloudtpu_client_factory
    from k8s_gpu_tpu.controller import Manager
    from k8s_gpu_tpu.operators import TpuPodSliceReconciler, TrainJobReconciler
    from k8s_gpu_tpu.cloud.topology import parse_accelerator_type

    cloud = FakeCloudTpu(clock=clock)
    mgr = Manager(kube, clock=clock)
    mgr.register(
        "TpuPodSlice", TpuPodSliceReconciler(kube, cloudtpu_client_factory(cloud))
    )
    mgr.register("TrainJob", TrainJobReconciler(kube), name="trainjob")
    mgr.start()
    try:
        make_queue(kube, "team-q", cap_tpu=8)
        ps = TpuPodSlice()
        ps.metadata.name = "pool"
        ps.spec.accelerator_type = "v4-8"
        kube.create(ps)
        for name in ("first", "second"):
            j = TrainJob()
            j.metadata.name = name
            j.spec.queue = "team-q"
            j.spec.accelerator_type = "v4-8"
            j.spec.workload = "psum-smoke"
            j.spec.num_workers = parse_accelerator_type("v4-8").hosts
            kube.create(j)
        for _ in range(60):
            mgr.wait_idle()
            jobs = {n: kube.get("TrainJob", n) for n in ("first", "second")}
            if all(j.status.phase == "Succeeded" for j in jobs.values()):
                break
            clock.advance(5.1)
        else:
            raise AssertionError(
                {n: (j.status.phase, j.status.message) for n, j in jobs.items()}
            )
        first, second = jobs["first"], jobs["second"]
        assert first.status.completion_time <= second.status.start_time
        adm = get_condition(second.status.conditions, "Admitted")
        assert adm is not None and adm.status == "True"  # finally admitted
    finally:
        mgr.stop()
