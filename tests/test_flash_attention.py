"""Pallas flash-attention kernel vs the reference oracle (interpret mode on
CPU; the same kernel compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.ops import flash_attention, reference_attention


def qkv(key, b=2, h=2, s=256, d=64, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, s, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    q, k, v = qkv(jax.random.PRNGKey(0))
    want = reference_attention(q, k, v, causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_single_block():
    q, k, v = qkv(jax.random.PRNGKey(1), s=64)
    want = reference_attention(q, k, v)
    got = flash_attention(q, k, v, block_q=128, block_k=128)  # clamped to 64
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_uneven_seq_falls_back():
    q, k, v = qkv(jax.random.PRNGKey(2), s=100)  # 100 % 64 != 0
    got = flash_attention(q, k, v, block_q=64, block_k=64)
    want = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_bf16_inputs():
    q, k, v = qkv(jax.random.PRNGKey(3), s=128, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, block_q=64, block_k=64)
    want = reference_attention(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=2e-2
    )


def test_gradients_match_reference():
    q, k, v = qkv(jax.random.PRNGKey(4), b=1, h=2, s=64, d=32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=32, block_k=32) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_jit_compiles():
    q, k, v = qkv(jax.random.PRNGKey(5), s=128)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, block_q=64, block_k=64))
    out = f(q, k, v)
    assert out.shape == q.shape


@pytest.mark.parametrize("causal", [True, False])
def test_fused_backward_matches_reference_vjp(causal):
    """The Pallas backward kernels (dq + dk/dv) against the jnp VJP oracle."""
    q, k, v = qkv(jax.random.PRNGKey(6), b=2, h=2, s=128, d=64)
    g = jax.random.normal(jax.random.PRNGKey(7), q.shape, q.dtype)

    def flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)

    def ref(q, k, v):
        return reference_attention(q, k, v, causal)

    _, vjp_f = jax.vjp(flash, q, k, v)
    _, vjp_r = jax.vjp(ref, q, k, v)
    for a, b in zip(vjp_f(g), vjp_r(g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_backward_never_calls_reference():
    """Structural check: the VJP lowers to pallas_call, not to the O(S²)
    einsum chain of reference_attention (VERDICT r1 weak #2)."""
    q, k, v = qkv(jax.random.PRNGKey(8), b=1, h=1, s=128, d=64)

    def loss(q, k, v):
        return flash_attention(q, k, v, block_q=64, block_k=64).sum()

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    text = str(jaxpr)
    # Three pallas calls: fused forward (o+lse), dq kernel, dkv kernel.
    assert text.count("pallas_call") >= 3
    assert "softmax" not in text


def test_fused_backward_bf16():
    q, k, v = qkv(jax.random.PRNGKey(9), s=128, dtype=jnp.bfloat16)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=64, block_k=64) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=0.15, rtol=0.1,
        )


def test_fused_backward_rectangular_blocks():
    """block_q != block_k exercises the diagonal-start index math of the
    dkv kernel and the partial-block mask of the dq kernel."""
    q, k, v = qkv(jax.random.PRNGKey(10), b=1, h=2, s=256, d=32)
    g = jax.random.normal(jax.random.PRNGKey(11), q.shape, q.dtype)

    def flash(q, k, v):
        return flash_attention(q, k, v, block_q=32, block_k=64)

    _, vjp_f = jax.vjp(flash, q, k, v)
    _, vjp_r = jax.vjp(lambda q, k, v: reference_attention(q, k, v), q, k, v)
    for a, b in zip(vjp_f(g), vjp_r(g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_flash_lse_value_and_gradient_match_reference():
    """flash_attention_lse: the lse output and its cotangent path (used by
    ring attention's block merge) against the plain-AD oracle."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from k8s_gpu_tpu.ops.attention import (
        flash_attention_lse,
        reference_attention_lse,
    )

    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (1, 2, 64, 16), jnp.float32)
    k = jax.random.normal(ks[1], (1, 2, 64, 16), jnp.float32)
    v = jax.random.normal(ks[2], (1, 2, 64, 16), jnp.float32)

    o_f, lse_f = jax.jit(
        lambda q, k, v: flash_attention_lse(q, k, v, block_q=16, block_k=16)
    )(q, k, v)
    o_r, lse_r = reference_attention_lse(q, k, v)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_r), atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse_f), np.asarray(lse_r),
                               atol=2e-5)

    # lse cotangent alone (zero o cotangent) — the pure g_lse path.
    def f_lse_only(impl):
        def fn(q, k, v):
            return impl(q, k, v)[1].sum()
        return fn

    g_f = jax.jit(jax.grad(
        f_lse_only(lambda q, k, v: flash_attention_lse(
            q, k, v, block_q=16, block_k=16)), argnums=(0, 1, 2)
    ))(q, k, v)
    g_r = jax.grad(f_lse_only(reference_attention_lse),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_pinned_block_odd_seq_falls_back():
    """A pinned block clamped to an odd S (512→65) divides S evenly yet
    violates the TPU sublane tiling — flash must fall back to the
    reference path instead of handing Mosaic an uncompilable kernel
    (seen live: model.forward at S=65 with flash_block_q=512)."""
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (1, 2, 65, 16), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, 2, 65, 16), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, 2, 65, 16), jnp.bfloat16)
    o = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, block_q=512, block_k=512)
    )(q, k, v)
    o_r = reference_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(o_r, np.float32), atol=2e-2
    )
