"""JSON-schema constrained decoding: schema → regex → token DFA.

Contract layers:
1. the generated regex accepts exactly the canonical JSON serializations
   the schema admits (cross-checked against Python's `re` — the dialect
   overlaps for everything schema_to_regex emits);
2. the regex compiles through the existing DFA pipeline and a token walk
   accepts canonical instances;
3. end-to-end: a constrained decode over a JSON-ish vocabulary emits a
   parseable instance of the schema.
"""

import json
import re

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import compile_constraint
from k8s_gpu_tpu.serve.jsonschema import SchemaError, schema_to_regex


def canon(v) -> str:
    return json.dumps(v, separators=(",", ":"))


def accepts(schema, value) -> bool:
    return re.fullmatch(schema_to_regex(schema), canon(value)) is not None


# -- layer 1: regex semantics vs Python re ----------------------------------

def test_scalars():
    assert accepts({"type": "integer"}, 0)
    assert accepts({"type": "integer"}, -17)
    assert not accepts({"type": "integer"}, 1.5)
    assert re.fullmatch(schema_to_regex({"type": "integer"}), "007") is None
    assert accepts({"type": "number"}, -3.25)
    assert accepts({"type": "number"}, 2e10)
    assert accepts({"type": "boolean"}, True)
    assert not accepts({"type": "boolean"}, "true")
    assert accepts({"type": "null"}, None)


def test_strings_and_escapes():
    s = {"type": "string"}
    assert accepts(s, "hello world")
    assert accepts(s, 'say "hi"')       # json.dumps escapes the quotes
    assert accepts(s, "tab\there")      # \t escape
    assert not re.fullmatch(schema_to_regex(s), '"raw " quote"')


def test_string_pattern_override():
    s = {"type": "string", "pattern": "[a-z]+@[a-z]+\\.com"}
    r = schema_to_regex(s)
    assert re.fullmatch(r, '"ann@corp.com"')
    assert not re.fullmatch(r, '"not an email"')


def test_string_pattern_alternation_stays_quoted():
    # Without the wrapping group, '"yes|no"' would parse as
    # ('"yes' | 'no"') and the DFA could emit unterminated strings.
    r = schema_to_regex({"type": "string", "pattern": "yes|no"})
    assert re.fullmatch(r, '"yes"') and re.fullmatch(r, '"no"')
    assert not re.fullmatch(r, '"yes')
    assert not re.fullmatch(r, 'no"')


def test_string_pattern_dialect_guard():
    # constrain.py has no bounded reps or anchors — {n}/^/$ would match
    # LITERALLY, silently under-constraining.  Rejected loudly instead.
    for pat in ("[0-9]{3}", "^ok$", "a{1,2}"):
        with pytest.raises(SchemaError):
            schema_to_regex({"type": "string", "pattern": pat})
    # escaped braces are literal on purpose and stay allowed
    r = schema_to_regex({"type": "string", "pattern": "a\\{b\\}"})
    assert re.fullmatch(r, '"a{b}"')


def test_raw_control_chars_rejected_in_strings():
    r = schema_to_regex({"type": "string"})
    assert not re.fullmatch(r, '"\x0c"'), "form feed must need escaping"
    assert not re.fullmatch(r, '"\x00"')
    assert re.fullmatch(r, '"\\f"')  # the escape form is fine


def test_enum():
    s = {"enum": ["low", "high", 3, None]}
    for v in ["low", "high", 3, None]:
        assert accepts(s, v), v
    assert not accepts(s, "medium")


def test_array():
    s = {"type": "array", "items": {"type": "integer"}}
    for v in ([], [1], [1, -2, 30]):
        assert accepts(s, v), v
    assert not accepts(s, ["x"])
    s1 = {"type": "array", "items": {"type": "boolean"}, "minItems": 1}
    assert not accepts(s1, [])
    assert accepts(s1, [True, False])


def test_object_fixed_order_and_nullable():
    s = {"type": "object", "properties": {
        "name": {"type": "string"},
        "age": {"type": "integer", "nullable": True},
        "tags": {"type": "array", "items": {"type": "string"}},
    }}
    assert accepts(s, {"name": "ann", "age": 7, "tags": ["a", "b"]})
    assert accepts(s, {"name": "ann", "age": None, "tags": []})
    # wrong order / missing key → rejected (canonical form)
    r = schema_to_regex(s)
    assert not re.fullmatch(r, '{"age":7,"name":"ann","tags":[]}')
    assert not re.fullmatch(r, '{"name":"ann","tags":[]}')


def test_nested():
    s = {"type": "object", "properties": {
        "user": {"type": "object", "properties": {
            "id": {"type": "integer"},
            "role": {"enum": ["admin", "viewer"]},
        }},
        "scores": {"type": "array", "items": {"type": "number"},
                   "minItems": 1},
    }}
    assert accepts(s, {"user": {"id": 3, "role": "admin"},
                       "scores": [1.5, -2e3]})
    assert not accepts(s, {"user": {"id": 3, "role": "root"},
                           "scores": [1.0]})


def test_pattern_intersected_with_json_string_alphabet():
    # '.' and negated classes are narrowed so the DFA can never emit a
    # raw quote/backslash/control char — output is always valid JSON.
    r = schema_to_regex({"type": "string", "pattern": ".+"})
    assert re.fullmatch(r, '"abc"')
    assert not re.fullmatch(r, '"a"b"'), "dot must not admit a raw quote"
    assert not re.fullmatch(r, '"a\\b"'), "dot must not admit a raw backslash"
    r = schema_to_regex({"type": "string", "pattern": "[^0-9]+"})
    assert re.fullmatch(r, '"xy"')
    assert not re.fullmatch(r, '"x"y"')
    # but patterns that can ONLY emit illegal bodies are rejected loudly
    for pat in ('a"b', '\\"', "\\\\", "\\n", "\\s+", '[a-z"]', "[ -~]+"):
        with pytest.raises(SchemaError):
            schema_to_regex({"type": "string", "pattern": pat})


def test_negated_class_negation_caret_allowed():
    # '^' right after '[' is class negation (supported by constrain.py),
    # not an anchor — only anchor uses are rejected.
    r = schema_to_regex({"type": "string", "pattern": "[^abc]"})
    assert re.fullmatch(r, '"z"') and not re.fullmatch(r, '"a"')
    with pytest.raises(SchemaError):
        schema_to_regex({"type": "string", "pattern": "a^b"})


def test_negated_class_trailing_dash_cannot_leak_quote():
    """Regression (ADVICE medium): a trailing literal '-' in a negated
    class used to sit raw against the appended quote/backslash exclusions
    and form a `-"` range — `[^a-]*` compiled to a body that could emit a
    raw quote into constrained JSON output.  The dash must be escaped."""
    from k8s_gpu_tpu.serve.jsonschema import _pattern_to_string_body

    body = _pattern_to_string_body("[^a-]*")
    assert re.fullmatch(body, "xyz")
    assert not re.fullmatch(body, 'x"y'), "negated class leaked a raw quote"
    assert not re.fullmatch(body, "a")      # the named member still excluded
    assert not re.fullmatch(body, "-")      # the dash member still excluded
    # same hazard through the schema surface, and '"' must stay framed
    r = schema_to_regex({"type": "string", "pattern": "[^a-]*"})
    assert re.fullmatch(r, '"xyz"')
    assert not re.fullmatch(r, '"x"y"')
    # a dash member in a POSITIVE class keeps matching
    body = _pattern_to_string_body("[a-]+")
    assert re.fullmatch(body, "a-a-")
    assert not re.fullmatch(body, "b")
    # and the compiled DFA agrees (constrain.py resolves the \- escape)
    import numpy as np

    dfa = compile_constraint(
        _pattern_to_string_body("[^a-]*"), ["x", '"', "a", "-"]
    )
    allowed = np.asarray(dfa.allowed)[dfa.start]
    assert allowed[0]          # 'x' fine
    assert not allowed[1]      # '"' excluded by the negated-class rewrite
    assert not allowed[2]      # 'a' excluded by the author pattern
    assert not allowed[3]      # '-' excluded by the author pattern


def test_nullable_honored_at_every_level():
    # nullable is allowlisted everywhere, so it must WORK everywhere —
    # array items and top level, not just object properties.
    s = {"type": "array", "items": {"type": "integer", "nullable": True}}
    assert accepts(s, [1, None, 3])
    assert not accepts(s, ["x"])
    assert accepts({"type": "string", "nullable": True}, None)
    assert accepts({"enum": ["a", "b"], "nullable": True}, None)


def test_allowlist_rejects_unknown_keywords():
    # Allowlist, not denylist: ANY constraining keyword outside the
    # supported set must fail loudly instead of silently under-constraining.
    for bad in (
        {"type": "integer", "minimum": 0},
        {"type": "string", "maxLength": 8},
        {"type": "string", "minLength": 1},
        {"type": "number", "multipleOf": 2},
        {"type": "object", "properties": {"a": {"type": "integer"}},
         "required": ["a"]},
        {"type": "array", "items": {"type": "integer"}, "uniqueItems": True},
        {"type": "integer", "not": {"enum": [3]}},
        {"type": "integer", "if": {"enum": [3]}},
    ):
        with pytest.raises(SchemaError):
            schema_to_regex(bad)
    # annotation-only keys constrain nothing and stay tolerated
    r = schema_to_regex({"type": "integer", "title": "count",
                         "description": "a count", "default": 0})
    assert re.fullmatch(r, "12")


def test_loud_rejections():
    for bad in (
        {"$ref": "#/defs/x"},
        {"type": "array", "items": {"type": "integer"}, "maxItems": 3},
        {"type": "object", "properties": {"a": {"type": "string"}},
         "additionalProperties": False},
        {"anyOf": [{"type": "integer"}]},
        {"type": "array"},
        {"type": "object"},
        {"type": "array", "items": {"type": "integer"}, "minItems": 2},
        {"enum": []},
        {"enum": [[1, 2]]},
        {"type": "frobnicate"},
    ):
        with pytest.raises(SchemaError):
            schema_to_regex(bad)


# -- layer 2: through the DFA pipeline --------------------------------------

TOKENS = ["", "{", "}", "[", "]", '"', ":", ",", "-", "ok", "fail",
          "0", "1", "7", "12", "true", "false", "null", "a", "b", "e",
          '"status"', '"n"', '{"status":']


def _walk(c, text_tokens):
    """Token-walk the compiled tables; returns final state or -1."""
    import numpy as np
    nxt = np.asarray(c.next_state)
    state = 0
    for tok in text_tokens:
        v = TOKENS.index(tok)
        state = int(nxt[state, v])
        if state < 0:
            return -1
    return state


def test_dfa_accepts_canonical_instance():
    s = {"type": "object", "properties": {
        "status": {"enum": ["ok", "fail"]},
        "n": {"type": "integer"},
    }}
    c = compile_constraint(schema_to_regex(s), TOKENS)
    import numpy as np
    acc = np.asarray(c.accepting)
    # '{"status":' (one BPE-ish token) '"ok"' ',' '"n"' ':' '7' '}'
    end = _walk(c, ['{"status":', '"', "ok", '"', ",", '"n"', ":", "7",
                    "}"])
    assert end >= 0 and acc[end]
    assert _walk(c, ['{"status":', '"', "b", '"']) == -1  # not in enum


# -- layer 3: end-to-end constrained decode ---------------------------------

CFG = TransformerConfig(
    vocab_size=len(TOKENS), d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq=48, use_flash=False, dtype=jnp.float32,
)


def test_constrained_decode_emits_schema_instance():
    from k8s_gpu_tpu.serve import ContinuousBatcher
    from k8s_gpu_tpu.serve.constrain import ConstraintBank

    schema = {"type": "object", "properties": {
        "status": {"enum": ["ok", "fail"]},
        "n": {"type": "integer"},
    }}
    bank = ConstraintBank({"resp": schema_to_regex(schema)}, TOKENS)
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(3))
    b = ContinuousBatcher(model, params, slots=2, eos_id=0,
                          constraints=bank).start()
    try:
        toks = b.submit([18, 19], max_new_tokens=30,
                        constraint="resp").result()
        text = "".join(TOKENS[t] for t in toks)
        # The automaton guarantees prefix-validity; with this vocabulary
        # every prefix can complete, and budget 30 > the longest
        # canonical instance, so the emitted text parses.
        obj = json.loads(text)
        assert obj["status"] in ("ok", "fail")
        assert isinstance(obj["n"], int)
    finally:
        b.stop()
