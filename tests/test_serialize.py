"""YAML manifests + kubectl-style apply/get/delete (the north-star UX:
`kubectl apply -f tpupodslice.yaml`, reference README.md:287-296)."""

from pathlib import Path

import pytest

from k8s_gpu_tpu.api import TpuPodSlice, ValidationError
from k8s_gpu_tpu.api.serialize import (
    from_manifest,
    known_kinds,
    load_manifests,
    to_manifest,
    to_yaml,
)

SAMPLES = Path(__file__).resolve().parent.parent / "config" / "samples"


def test_roundtrip_tpupodslice():
    ps = TpuPodSlice()
    ps.metadata.name = "p"
    ps.spec.accelerator_type = "v5p-64"
    ps.spec.slice_count = 2
    ps.metadata.labels["team"] = "ml"
    doc = to_manifest(ps)
    assert doc["kind"] == "TpuPodSlice"
    assert doc["spec"]["acceleratorType"] == "v5p-64"
    again = from_manifest(doc)
    assert again.spec.accelerator_type == "v5p-64"
    assert again.spec.slice_count == 2
    assert again.metadata.labels == {"team": "ml"}


def test_unknown_field_rejected():
    with pytest.raises(ValidationError, match="unknown field"):
        from_manifest({
            "kind": "TpuPodSlice",
            "metadata": {"name": "x"},
            "spec": {"acceleratorTyp": "v4-8"},
        })


def test_unknown_kind_rejected():
    with pytest.raises(ValidationError, match="unknown kind"):
        from_manifest({"kind": "Nope", "metadata": {"name": "x"}})


def test_status_ignored_on_apply():
    obj = from_manifest({
        "kind": "TpuPodSlice",
        "metadata": {"name": "x"},
        "spec": {"acceleratorType": "v4-8"},
        "status": {"phase": "Ready", "readyReplicas": 99},
    })
    assert obj.status.phase == "Pending"


def test_all_sample_manifests_parse_and_roundtrip():
    for f in sorted(SAMPLES.glob("*.yaml")):
        for obj in load_manifests(f.read_text()):
            obj.validate()
            again = load_manifests(to_yaml(obj))
            assert len(again) == 1 and again[0].kind == obj.kind


def test_known_kinds_cover_platform():
    kinds = known_kinds()
    for k in ("TpuPodSlice", "AzureVmPool", "TrainJob", "DevEnv",
              "SchedulingQueue", "Node", "Pod", "Secret", "Deployment"):
        assert k in kinds


def test_nested_condition_list_roundtrip():
    from k8s_gpu_tpu.api.types import set_condition

    ps = TpuPodSlice()
    ps.metadata.name = "c"
    ps.spec.accelerator_type = "v4-8"
    set_condition(ps.status.conditions, "Ready", "True", "AsExpected", "ok")
    doc = to_manifest(ps)
    assert doc["status"]["conditions"][0]["type"] == "Ready"


def test_apply_invalid_spec_is_clean_error(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("K8SGPU_CONFIG_DIR", str(tmp_path / "cfg"))
    monkeypatch.setenv("K8SGPU_STATE_DIR", str(tmp_path / "state"))
    from k8s_gpu_tpu.cli.main import main

    main(["login", "--user", "ada"])
    capsys.readouterr()
    f = tmp_path / "bad.yaml"
    f.write_text(
        "kind: TpuPodSlice\nmetadata:\n  name: bad\n"
        "spec:\n  acceleratorType: bogus-9\n"
    )
    code = main(["apply", "-f", str(f)])
    err = capsys.readouterr().err
    assert code == 1 and "bad" in err and "Traceback" not in err


def test_tuple_and_union_decode():
    """ADVICE r1: tuple-typed fields round-trip as tuples; non-Optional
    unions try every arm, not just the first."""
    import dataclasses
    from k8s_gpu_tpu.api.serialize import _decode_value

    assert _decode_value(tuple[int, ...], [1, 2, 3], "x") == (1, 2, 3)
    assert _decode_value(tuple[int], [4], "x") == (4,)
    assert _decode_value(list[int], [1, 2], "x") == [1, 2]

    @dataclasses.dataclass
    class Inner:
        a: int = 0

    # Union whose first arm fails (dataclass wants a mapping) must fall
    # through to the list arm.
    got = _decode_value(Inner | list[int], [1, 2], "x")
    assert got == [1, 2]
    got = _decode_value(Inner | list[int], {"a": 5}, "x")
    assert got == Inner(a=5)
