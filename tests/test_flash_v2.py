"""Flash attention v2 (ISSUE 12): RoPE fused in-kernel, GQA-native K/V
streaming, and the wider q-block pipeline.

Parity discipline: every knob is proven independently and all-on, forward
AND backward, against the composition ``reference_attention ∘ rope_rotate
∘ repeat_kv`` — the exact math the v1 path runs.  The rotated-basis
gradient contract (the VJP's transpose rotation returns dq/dk in the
UNROTATED parameter basis) is proven by comparing against gradients taken
through the outside-rope composition, not by argument.  All on CPU via
the Pallas interpreter (`conftest` pins JAX_PLATFORMS=cpu).
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.ops.attention import (
    describe_train_attention,
    flash_attention,
    flash_attention_lse,
    flash_attention_v2,
    flash_attention_v2_lse,
    reference_attention,
    reference_attention_lse,
    rope_rotate,
)
from k8s_gpu_tpu.utils.metrics import global_metrics

THETA = 10000.0


def qkv(key, b=2, h=4, kh=2, s=64, d=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, h, s, d), dtype)
    k = jax.random.normal(ks[1], (b, kh, s, d), dtype)
    v = jax.random.normal(ks[2], (b, kh, s, d), dtype)
    return q, k, v


def oracle(q, k, v, *, causal=True, rope=False):
    """The v1 math: optional outside rope, broadcast K/V, einsum oracle."""
    g = q.shape[1] // k.shape[1]
    if rope:
        q, k = rope_rotate(q, THETA), rope_rotate(k, THETA)
    k, v = jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1)
    return reference_attention(q, k, v, causal)


def _tol(dtype):
    return 2e-2 if dtype == jnp.bfloat16 else 2e-5


# ---------------------------------------------------------------- forward

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("kh", [4, 2, 1])  # MHA / GQA / MQA
@pytest.mark.parametrize("causal", [True, False])
def test_fwd_gqa_parity(dtype, kh, causal):
    q, k, v = qkv(jax.random.PRNGKey(0), kh=kh, dtype=dtype)
    got = flash_attention_v2(q, k, v, causal=causal, block_q=16, block_k=16)
    want = oracle(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=_tol(dtype),
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("causal", [True, False])
def test_fwd_rope_parity(dtype, causal):
    q, k, v = qkv(jax.random.PRNGKey(1), kh=4, dtype=dtype)
    got = flash_attention_v2(
        q, k, v, causal=causal, rope_theta=THETA, block_q=16, block_k=16
    )
    want = oracle(q, k, v, causal=causal, rope=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=_tol(dtype),
    )


@pytest.mark.parametrize("pipeline", [2, 4])
def test_fwd_pipeline_parity(pipeline):
    q, k, v = qkv(jax.random.PRNGKey(2), kh=4, s=128, d=32)
    got = flash_attention_v2(
        q, k, v, causal=True, block_q=16, block_k=16, q_pipeline=pipeline
    )
    want = oracle(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fwd_all_knobs_parity(dtype):
    q, k, v = qkv(jax.random.PRNGKey(3), kh=2, s=128, dtype=dtype)
    got = flash_attention_v2(
        q, k, v, causal=True, rope_theta=THETA, block_q=16, block_k=16,
        q_pipeline=2,
    )
    want = oracle(q, k, v, rope=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=_tol(dtype),
    )


def test_lse_matches_reference():
    q, k, v = qkv(jax.random.PRNGKey(4), kh=2)
    _, lse = flash_attention_v2_lse(q, k, v, causal=True, block_q=16,
                                    block_k=16)
    g = q.shape[1] // k.shape[1]
    _, want = reference_attention_lse(
        q, jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1), True
    )
    assert lse.shape == q.shape[:3]
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want), atol=2e-5)


# --------------------------------------------------------------- backward

def test_grad_all_knobs_rotated_basis():
    """The decisive gradient check: all-knobs v2 (rope IN-kernel) vs
    gradients taken through the outside-rope oracle composition.  If the
    VJP's transpose rotation were wrong, dq/dk would come back in the
    rotated basis and diverge by O(1)."""
    q, k, v = qkv(jax.random.PRNGKey(5), kh=2, s=128)

    def loss_v2(q, k, v):
        o = flash_attention_v2(
            q, k, v, causal=True, rope_theta=THETA, block_q=16, block_k=16,
            q_pipeline=2,
        )
        return (o.astype(jnp.float32) ** 2).mean()

    def loss_ref(q, k, v):
        return (oracle(q, k, v, rope=True).astype(jnp.float32) ** 2).mean()

    g2 = jax.grad(loss_v2, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g2, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_grad_gqa_only(causal):
    q, k, v = qkv(jax.random.PRNGKey(6), kh=1)  # MQA: hardest fold

    def loss_v2(q, k, v):
        o = flash_attention_v2(q, k, v, causal=causal, block_q=16, block_k=16)
        return (o.astype(jnp.float32) ** 2).mean()

    def loss_ref(q, k, v):
        return (oracle(q, k, v, causal=causal).astype(jnp.float32) ** 2).mean()

    g2 = jax.grad(loss_v2, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g2, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_grad_rope_matches_v1_outside_rope():
    """Fused-rope gradients equal v1-kernel gradients with rope applied
    as a separate jnp pass — the exact substitution _attention makes."""
    q, k, v = qkv(jax.random.PRNGKey(7), kh=4)

    def loss_v2(q, k, v):
        o = flash_attention_v2(
            q, k, v, causal=True, rope_theta=THETA, block_q=16, block_k=16
        )
        return (o.astype(jnp.float32) ** 2).mean()

    def loss_v1(q, k, v):
        o = flash_attention(
            rope_rotate(q, THETA), rope_rotate(k, THETA), v,
            causal=True, block_q=16, block_k=16,
        )
        return (o.astype(jnp.float32) ** 2).mean()

    g2 = jax.grad(loss_v2, argnums=(0, 1, 2))(q, k, v)
    g1 = jax.grad(loss_v1, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g2, g1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_grad_lse_cotangent():
    """lse is a first-class differentiable output (ring's merge contract
    differentiates through it): a loss touching BOTH out and lse must
    match the oracle composition's gradients."""
    q, k, v = qkv(jax.random.PRNGKey(8), kh=2)

    def loss_v2(q, k, v):
        o, lse = flash_attention_v2_lse(
            q, k, v, causal=True, rope_theta=THETA, block_q=16, block_k=16
        )
        return (o.astype(jnp.float32) ** 2).mean() + 0.1 * lse.sum()

    def loss_ref(q, k, v):
        g = q.shape[1] // k.shape[1]
        o, lse = reference_attention_lse(
            rope_rotate(q, THETA),
            jnp.repeat(rope_rotate(k, THETA), g, axis=1),
            jnp.repeat(v, g, axis=1), True,
        )
        return (o.astype(jnp.float32) ** 2).mean() + 0.1 * lse.sum()

    g2 = jax.grad(loss_v2, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g2, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_backward_never_calls_reference():
    """The v2 VJP must be the fused kernels, not a silent fallback: the
    backward jaxpr contains the pallas calls and no softmax."""
    q, k, v = qkv(jax.random.PRNGKey(9), kh=2)

    def loss(q, k, v):
        o = flash_attention_v2(
            q, k, v, causal=True, rope_theta=THETA, block_q=16, block_k=16,
            q_pipeline=2,
        )
        return (o.astype(jnp.float32) ** 2).mean()

    jaxpr = str(jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v))
    assert jaxpr.count("pallas_call") >= 3  # fwd + dq + dkv
    assert "softmax" not in jaxpr


# ---------------------------------------------------- fallbacks & guards

def _minted(before, after):
    return sorted(
        ln.split("{")[1].split("}")[0]
        for ln in after.splitlines()
        if ln.startswith("flash_fallback_total")
        and ln not in before.splitlines()
    )


def test_fallback_counter_two_hop():
    """An untileable shape demotes v2 → v1 → oracle and mints the counter
    at BOTH hops, attributed per hop by the v2_ prefix."""
    q, k, v = qkv(jax.random.PRNGKey(10), kh=2, s=65, dtype=jnp.bfloat16)
    before = global_metrics.render()
    got = flash_attention_v2(q, k, v, causal=True, block_q=512, block_k=512)
    minted = _minted(before, global_metrics.render())
    assert any("v2_sublane_misaligned" in m for m in minted), minted
    assert any(
        "sublane_misaligned" in m and "v2_" not in m for m in minted
    ), minted
    want = oracle(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=2e-2
    )


def test_fallback_pipeline_indivisible_lands_on_v1():
    """A pipeline factor that doesn't divide the folded q-block count
    demotes ONE hop (to v1, which compiles fine) — one mint only."""
    q, k, v = qkv(jax.random.PRNGKey(11), kh=4, s=64)
    before = global_metrics.render()
    got = flash_attention_v2(
        q, k, v, causal=True, block_q=32, block_k=32, q_pipeline=3
    )
    minted = _minted(before, global_metrics.render())
    assert minted == ['reason="v2_pipeline_indivisible"'], minted
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(oracle(q, k, v)), atol=2e-5
    )


def test_v1_fallback_mints_counter():
    """Satellite bugfix: the v1 entry itself now mints on oracle fallback
    (the silent-einsum regression the issue names)."""
    q, k, v = qkv(jax.random.PRNGKey(12), kh=4, s=65, dtype=jnp.bfloat16)
    before = global_metrics.render()
    flash_attention_lse(q, k, v, causal=True, block_q=512, block_k=512)
    minted = _minted(before, global_metrics.render())
    assert minted == ['reason="sublane_misaligned"'], minted


def test_validation_errors():
    q, k, v = qkv(jax.random.PRNGKey(13), kh=4)
    with pytest.raises(ValueError, match="multiple of KV heads"):
        flash_attention_v2(q, k[:, :3], v[:, :3], causal=True)
    with pytest.raises(ValueError, match="k/v shape mismatch"):
        flash_attention_v2(q, k, v[:, :1], causal=True)
    with pytest.raises(ValueError, match="even head dim"):
        flash_attention_v2(q[..., :15], k[..., :15], v[..., :15],
                           causal=True, rope_theta=THETA)


def test_no_knobs_routes_to_v1():
    """KH == H, P == 1, no rope: the v2 entry must not add compile
    surface — identical jaxpr to the v1 entry."""
    import re

    q, k, v = qkv(jax.random.PRNGKey(14), kh=4)
    j1 = str(jax.make_jaxpr(
        lambda a, b, c: flash_attention_lse(a, b, c, True, 16, 16)
    )(q, k, v))
    j2 = str(jax.make_jaxpr(
        lambda a, b, c: flash_attention_v2_lse(
            a, b, c, causal=True, block_q=16, block_k=16
        )
    )(q, k, v))
    strip = lambda s: re.sub(r"0x[0-9a-f]+", "0x", s)  # closure addresses
    assert strip(j1) == strip(j2)


def test_describe_train_attention_matrix():
    class Cfg:
        use_flash = True
        max_seq = 64
        dtype = jnp.float32
        flash_block_q = 16
        flash_block_k = 16
        n_heads = 4
        kv_heads = 2
        sp_attention = "ring"
        flash_fuse_rope = True
        flash_kv_grouped = True
        flash_q_pipeline = 2

    assert describe_train_attention(Cfg()) == (
        "flash-v2[rope,gqa=2,pipeline=2] blocks 16x16"
    )
    assert describe_train_attention(Cfg(), seq_sharded=True) == (
        "sp-ring (rope outside: sp_fused_rope)"
    )

    c2 = Cfg()
    c2.flash_q_pipeline = 3  # 4 folded blocks % 3 != 0 → v1
    assert "v2 fallback: v2_pipeline_indivisible" in describe_train_attention(c2)

    c3 = Cfg()
    c3.max_seq = 65
    c3.flash_block_q = 512
    c3.flash_block_k = 512
    assert describe_train_attention(c3).startswith("reference-oracle")

    c4 = Cfg()
    c4.use_flash = False
    assert describe_train_attention(c4) == "plain-causal (use_flash off)"


# ------------------------------------------------- model & trainer wiring

def _model_cfg(**kw):
    from k8s_gpu_tpu.models import TransformerConfig

    base = dict(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_head=16, d_ff=64, max_seq=64, use_flash=True,
        flash_block_q=16, flash_block_k=16, dtype=jnp.float32,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _mesh1():
    from k8s_gpu_tpu.parallel.mesh import MeshConfig, mesh_from_devices

    return mesh_from_devices(jax.devices()[:1], MeshConfig(dp=1))


def test_train_step_all_knobs_matches_v1():
    """The acceptance bar: the all-knobs train step's losses track the
    v1-config step within dtype tolerance over several steps — identical
    init, identical data, only the attention path differs."""
    from k8s_gpu_tpu.models import TransformerLM
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, 64)
    losses = {}
    for name, cfg in (
        ("v1", _model_cfg()),
        ("v2", _model_cfg(flash_fuse_rope=True, flash_kv_grouped=True,
                          flash_q_pipeline=2)),
    ):
        tr = Trainer(TransformerLM(cfg), mesh=_mesh1(),
                     train_config=TrainConfig(warmup_steps=1))
        tr.init(jax.random.PRNGKey(0))
        losses[name] = [
            float(tr.step(toks[:, :-1], toks[:, 1:])) for _ in range(3)
        ]
    np.testing.assert_allclose(losses["v2"], losses["v1"], atol=5e-5)


def test_train_step_zero_recompile_with_v2(xla_compiles):
    """Steady-state train steps with every v2 knob on compile nothing new."""
    from k8s_gpu_tpu.models import TransformerLM
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    cfg = _model_cfg(flash_fuse_rope=True, flash_kv_grouped=True,
                     flash_q_pipeline=2)
    tr = Trainer(TransformerLM(cfg), mesh=_mesh1(),
                 train_config=TrainConfig(warmup_steps=1))
    tr.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, 64)
    tr.step(toks[:, :-1], toks[:, 1:])
    tr.step(toks[:, :-1], toks[:, 1:])
    before = xla_compiles()
    tr.step(toks[:, :-1], toks[:, 1:])
    tr.step(toks[:, :-1], toks[:, 1:])
    assert xla_compiles() == before, "v2 train step recompiled in steady state"


def test_trainer_logs_attention_path(caplog):
    from k8s_gpu_tpu.models import TransformerLM
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    cfg = _model_cfg(flash_fuse_rope=True, flash_kv_grouped=True,
                     flash_q_pipeline=2)
    tr = Trainer(TransformerLM(cfg), mesh=_mesh1(),
                 train_config=TrainConfig(warmup_steps=1))
    tr.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0, 64)
    with caplog.at_level(logging.INFO, logger="k8s_gpu_tpu.train"):
        tr.step(toks[:, :-1], toks[:, 1:])
    msgs = [r.message for r in caplog.records
            if "attention path" in r.message]
    assert msgs and "flash-v2[rope,gqa=2,pipeline=2]" in msgs[0], msgs


def test_model_sp_keeps_rope_outside_and_mints():
    """The sp-sharded path cannot fuse rope (a shard's global position
    offset is invisible to the kernel): the model rotates outside, mints
    sp_fused_rope, and still matches the unsharded forward."""
    from k8s_gpu_tpu.models import TransformerLM
    from k8s_gpu_tpu.parallel.mesh import MeshConfig, build_mesh

    cfg = _model_cfg(flash_fuse_rope=True, flash_kv_grouped=True,
                     sp_attention="ring")
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0, 64)
    want, _ = model.forward(params, toks)
    mesh = build_mesh(MeshConfig(dp=1, sp=2), n_devices=2)
    before = global_metrics.render()
    got, _ = model.forward(params, toks, mesh=mesh)
    minted = _minted(before, global_metrics.render())
    assert any("sp_fused_rope" in m for m in minted), minted
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=1e-4
    )


# --------------------------------------------------- sp grouped K/V plumbing

def test_ring_grouped_kv_parity():
    from k8s_gpu_tpu.parallel.mesh import MeshConfig, build_mesh
    from k8s_gpu_tpu.parallel.ring_attention import (
        plain_causal_attention, ring_attention,
    )

    q, k, v = qkv(jax.random.PRNGKey(15), kh=2, s=64)
    g = q.shape[1] // k.shape[1]
    want = plain_causal_attention(
        q, jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1)
    )
    mesh = build_mesh(MeshConfig(dp=1, sp=2), n_devices=2)
    got = ring_attention(q, k, v, mesh, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    # n == 1 ring (sp=1) expands grouped K/V for the plain path.
    mesh1 = build_mesh(MeshConfig(dp=2, sp=1), n_devices=2)
    got1 = ring_attention(q, k, v, mesh1, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(want), atol=2e-5)


def test_ulysses_grouped_kv_parity_and_guard():
    from k8s_gpu_tpu.parallel.mesh import MeshConfig, build_mesh
    from k8s_gpu_tpu.parallel.ring_attention import plain_causal_attention
    from k8s_gpu_tpu.parallel.ulysses import (
        ulysses_attention, ulysses_grouped_ok,
    )

    q, k, v = qkv(jax.random.PRNGKey(16), kh=2, s=64)
    g = q.shape[1] // k.shape[1]
    want = plain_causal_attention(
        q, jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1)
    )
    mesh = build_mesh(MeshConfig(dp=1, sp=2), n_devices=2)
    assert ulysses_grouped_ok(q.shape[1], k.shape[1], mesh)
    got = ulysses_attention(q, k, v, mesh, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    # sp=4 would strand queries away from their KV head: loud, not wrong.
    mesh4 = build_mesh(MeshConfig(dp=1, sp=4), n_devices=4)
    assert not ulysses_grouped_ok(q.shape[1], k.shape[1], mesh4)
    with pytest.raises(ValueError, match="grouped"):
        ulysses_attention(q, k, v, mesh4, block_q=16, block_k=16)
