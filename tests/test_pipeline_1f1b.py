"""1F1B pipeline schedule (parallel/pipeline.py:one_f_one_b).

Proof obligations (VERDICT r2 #4): gradient parity with the non-pipelined
oracle on a dp×pp×tp mesh (the pp×tp composition hole), the activation-
memory win over GPipe-through-jax.grad measured on compiled programs, and
Trainer integration."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.parallel.mesh import MeshConfig, build_mesh

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=4, n_heads=2, d_head=16,
    d_ff=64, max_seq=16, dtype=jnp.float32, use_flash=False,
    pp_microbatches=4,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, 64)
    return model, params, toks[:, :-1], toks[:, 1:]


def _tree_allclose(a, b, rtol):
    for pa, (la, lb) in zip(
        jax.tree_util.tree_flatten_with_path(a)[0],
        zip(jax.tree.leaves(a), jax.tree.leaves(b)),
    ):
        la, lb = np.asarray(la), np.asarray(lb)
        denom = np.max(np.abs(la)) + 1e-9
        err = np.max(np.abs(la - lb)) / denom
        assert err < rtol, f"{jax.tree_util.keystr(pa[0])}: rel err {err:.2e}"


def test_1f1b_grads_match_oracle_on_dp_pp_tp(setup):
    """Loss AND every gradient leaf match the sequential oracle on a
    dp=2, pp=2, tp=2 mesh — pp×tp runs in one program (the r2 hole)."""
    model, params, tokens, targets = setup
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    loss_o, grads_o = jax.value_and_grad(model.loss)(params, tokens, targets)
    mesh = build_mesh(MeshConfig(dp=2, pp=2, tp=2))
    loss_p, grads_p = jax.jit(
        lambda p, t, tg: model.pipeline_value_and_grad(p, t, tg, mesh)
    )(params, tokens, targets)
    assert abs(float(loss_o) - float(loss_p)) < 1e-4
    _tree_allclose(grads_o, grads_p, rtol=2e-4)


def test_1f1b_grads_match_oracle_many_microbatches(setup):
    """M >> P exercises the steady-state 1F1B interleave (warmup/cooldown
    validity masks, store-slot reuse)."""
    model, params, tokens, targets = setup
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    cfg = dataclasses.replace(CFG, pp_microbatches=8)
    model8 = TransformerLM(cfg)
    loss_o, grads_o = jax.value_and_grad(model8.loss)(params, tokens, targets)
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), n_devices=2)
    loss_p, grads_p = jax.jit(
        lambda p, t, tg: model8.pipeline_value_and_grad(p, t, tg, mesh)
    )(params, tokens, targets)
    assert abs(float(loss_o) - float(loss_p)) < 1e-4
    _tree_allclose(grads_o, grads_p, rtol=2e-4)


def test_1f1b_activation_memory_beats_gpipe_grad():
    """The schedule's point: compiled temp memory at M=16 microbatches is
    a multiple smaller than GPipe-through-jax.grad, because 1F1B keeps
    2P-1 stage inputs live instead of M+P-1 autodiff residuals.
    (Measured: ~207KB vs ~1385KB on this config.)"""
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    M = 16
    cfg = dataclasses.replace(
        CFG, n_layers=2, pp_microbatches=M, remat=False
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (16, 17), 0, 64)
    tokens, targets = toks[:, :-1], toks[:, 1:]
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), n_devices=2)

    f_1f1b = jax.jit(
        lambda p, t, tg: model.pipeline_value_and_grad(p, t, tg, mesh)
    )
    model_g = TransformerLM(dataclasses.replace(cfg, pp_schedule="gpipe"))
    f_gpipe = jax.jit(
        jax.value_and_grad(lambda p, t, tg: model_g.loss(p, t, tg, mesh))
    )
    temp_1f1b = f_1f1b.lower(params, tokens, targets).compile(
    ).memory_analysis().temp_size_in_bytes
    temp_gpipe = f_gpipe.lower(params, tokens, targets).compile(
    ).memory_analysis().temp_size_in_bytes
    assert temp_1f1b * 2 < temp_gpipe, (
        f"1f1b temp {temp_1f1b} should be well under gpipe {temp_gpipe}"
    )


def test_trainer_runs_1f1b_and_learns(setup):
    """Trainer picks the 1F1B step for pp>1 meshes and the loss moves."""
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    model, params, tokens, targets = setup
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = build_mesh(MeshConfig(dp=2, pp=2, tp=2))
    trainer = Trainer(model, mesh=mesh,
                      train_config=TrainConfig(warmup_steps=1))
    assert trainer._use_1f1b()
    trainer.init(jax.random.PRNGKey(0))
    first = trainer.step(tokens, targets)
    for _ in range(12):
        last = trainer.step(tokens, targets)
    assert last < first


def test_unsupported_compositions_raise_with_design_reason(setup):
    """MoE+pp and sp+pp raise messages that carry the design rationale
    (VERDICT r2 #4: 'document the design reason, not a bare raise')."""
    model, params, tokens, targets = setup
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    moe_model = TransformerLM(dataclasses.replace(CFG, num_experts=4))
    mesh = build_mesh(MeshConfig(dp=4, pp=2, tp=1))
    with pytest.raises(NotImplementedError, match="all-to-all"):
        moe_model.pipeline_value_and_grad(params, tokens, targets, mesh)
    sp_mesh = build_mesh(MeshConfig(dp=2, pp=2, sp=2))
    with pytest.raises(NotImplementedError, match="ring"):
        model.pipeline_value_and_grad(params, tokens, targets, sp_mesh)


def test_unknown_pp_schedule_fails_loudly(setup):
    """A typo'd schedule must not silently train gpipe (review finding)."""
    from k8s_gpu_tpu.train import Trainer

    model, params, tokens, targets = setup
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    bad = TransformerLM(dataclasses.replace(CFG, pp_schedule="1F1B"))
    mesh = build_mesh(MeshConfig(dp=1, pp=2, tp=1), n_devices=2)
    trainer = Trainer(bad, mesh=mesh)
    trainer.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="pp_schedule"):
        trainer.step(tokens, targets)
