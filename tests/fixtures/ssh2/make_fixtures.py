"""Independent SSH-2 wire-vector generator — run once, output committed.

The round-4 verdict's gap: with no stock ssh client in this environment,
sshwire.py was proven only self-against-self — both ends of every test
share one implementation, so a misreading of RFC 4253/4252/8731 would
cancel out.  This script is a SECOND implementation of the deterministic
wire encodings, written directly against the RFC text and deliberately
importing nothing from k8s_gpu_tpu: it builds the expected bytes for

- the ssh-ed25519 public-key blob and authorized_keys line (RFC 8709 §4),
- the KEXINIT payload for the gateway's algorithm suite (RFC 4253 §7.1),
- the curve25519-sha256 exchange hash serialization (RFC 8731 §3),
- the §7.2 key-derivation outputs for a fixed (K, H, session_id),
- the publickey USERAUTH_REQUEST signature blob (RFC 4252 §7),
- a fully encrypted-and-MACed binary packet (RFC 4253 §6) under fixed
  keys, sequence number and padding,

from fixed inputs, into vectors.json.  tests/test_ssh2_vectors.py then
checks sshwire.py's output byte-for-byte against these.  Agreement means
two independent readings of the RFCs converge — recorded-transcript
evidence, not assertion.  (AES/HMAC/Ed25519 primitives come from the
``cryptography``/hashlib libraries in both implementations; what is
independently derived here is everything SSH-specific: framing, field
order, padding math, KDF structure, signed-blob layout.)

Regenerate with:  python tests/fixtures/ssh2/make_fixtures.py
"""

import hashlib
import hmac
import json
import struct
from pathlib import Path

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
)
from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
from cryptography.hazmat.primitives.serialization import (
    Encoding,
    PublicFormat,
)


def s(b: bytes) -> bytes:  # RFC 4251 §5 'string'
    return struct.pack(">I", len(b)) + b


def u32(n: int) -> bytes:
    return struct.pack(">I", n)


def mpint(n: int) -> bytes:
    # RFC 4251 §5: two's complement, minimal length, leading zero byte
    # if the high bit would read as a sign bit.
    if n == 0:
        return s(b"")
    raw = n.to_bytes((n.bit_length() + 8) // 8, "big")
    return s(raw)


FIXED = {
    # 32 zero bytes would be a weak fixture; use a counting pattern.
    "host_seed": bytes(range(32)),
    "user_seed": bytes(range(32, 64)),
    "cookie": bytes(range(16)),
    "v_c": b"SSH-2.0-k8sgpu_gateway-client",
    "v_s": b"SSH-2.0-k8sgpu-devenv-gateway",
    "q_c": bytes(range(64, 96)),
    "q_s": bytes(range(96, 128)),
    "K": int.from_bytes(hashlib.sha256(b"shared-secret-fixture").digest(),
                        "big"),
    "session_id": hashlib.sha256(b"session-id-fixture").digest(),
    "username": "ada",
    "payload": b"\x05" + s(b"ssh-userauth"),  # SERVICE_REQUEST
    "seq": 3,
}


def ed25519_blob(seed: bytes) -> bytes:
    pub = Ed25519PrivateKey.from_private_bytes(seed).public_key()
    raw = pub.public_bytes(Encoding.Raw, PublicFormat.Raw)
    return s(b"ssh-ed25519") + s(raw)


def kexinit(cookie: bytes) -> bytes:
    # name-list fields in RFC 4253 §7.1 order; single-algorithm lists.
    lists = [b"curve25519-sha256", b"ssh-ed25519", b"aes128-ctr",
             b"aes128-ctr", b"hmac-sha2-256", b"hmac-sha2-256",
             b"none", b"none", b"", b""]
    out = b"\x14" + cookie  # SSH_MSG_KEXINIT = 20
    for item in lists:
        out += s(item)
    return out + b"\x00" + u32(0)


def exchange_hash(v_c, v_s, i_c, i_s, k_s, q_c, q_s, K) -> bytes:
    # RFC 8731 §3: strings for the version lines WITHOUT CR/LF, the two
    # KEXINIT payloads, the host key blob, both ephemeral publics, then
    # the shared secret as an mpint.
    blob = (s(v_c) + s(v_s) + s(i_c) + s(i_s) + s(k_s)
            + s(q_c) + s(q_s) + mpint(K))
    return hashlib.sha256(blob).digest()


def derive(K: int, H: bytes, session_id: bytes) -> dict:
    # RFC 4253 §7.2: K1 = HASH(K || H || X || session_id),
    # Kn = HASH(K || H || K1 || ... || K(n-1)); K encoded as mpint.
    def kdf(letter: bytes, size: int) -> bytes:
        out = hashlib.sha256(mpint(K) + H + letter + session_id).digest()
        while len(out) < size:
            out += hashlib.sha256(mpint(K) + H + out).digest()
        return out[:size]

    return {
        "iv_c2s": kdf(b"A", 16), "iv_s2c": kdf(b"B", 16),
        "key_c2s": kdf(b"C", 16), "key_s2c": kdf(b"D", 16),
        "mac_c2s": kdf(b"E", 32), "mac_s2c": kdf(b"F", 32),
    }


def userauth_blob(session_id: bytes, username: str, key_blob: bytes) -> bytes:
    # RFC 4252 §7: the exact byte layout the publickey signature covers.
    return (s(session_id) + b"\x32" + s(username.encode())
            + s(b"ssh-connection") + s(b"publickey") + b"\x01"
            + s(b"ssh-ed25519") + s(key_blob))


def packet(payload: bytes, seq: int, key: bytes, iv: bytes,
           mac_key: bytes, pad_byte: int = 0xAA) -> bytes:
    # RFC 4253 §6: packet_length covers padding_length + payload + pad;
    # total length a multiple of the cipher block (16); padding >= 4.
    # MAC = HMAC(key, seq || cleartext packet), appended UNencrypted.
    pad = 16 - ((5 + len(payload)) % 16)
    if pad < 4:
        pad += 16
    pkt = struct.pack(">IB", 1 + len(payload) + pad, pad)
    pkt += payload + bytes([pad_byte]) * pad
    mac = hmac.new(mac_key, u32(seq) + pkt, hashlib.sha256).digest()
    enc = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return enc.update(pkt) + mac


def main() -> None:
    f = FIXED
    host_blob = ed25519_blob(f["host_seed"])
    user_blob = ed25519_blob(f["user_seed"])
    i_c = kexinit(f["cookie"])
    i_s = kexinit(f["cookie"])
    H = exchange_hash(f["v_c"], f["v_s"], i_c, i_s, host_blob,
                      f["q_c"], f["q_s"], f["K"])
    keys = derive(f["K"], H, f["session_id"])
    auth = userauth_blob(f["session_id"], f["username"], user_blob)
    pkt = packet(f["payload"], f["seq"], keys["key_c2s"],
                 keys["iv_c2s"], keys["mac_c2s"])
    import base64

    authorized = "ssh-ed25519 " + base64.b64encode(user_blob).decode() + " ada@fixture"
    vectors = {
        "_note": "generated by make_fixtures.py — an independent RFC "
                 "implementation; do not regenerate from sshwire.py",
        "inputs": {
            "host_seed": f["host_seed"].hex(),
            "user_seed": f["user_seed"].hex(),
            "cookie": f["cookie"].hex(),
            "v_c": f["v_c"].decode(),
            "v_s": f["v_s"].decode(),
            "q_c": f["q_c"].hex(),
            "q_s": f["q_s"].hex(),
            "K": str(f["K"]),
            "session_id": f["session_id"].hex(),
            "username": f["username"],
            "payload": f["payload"].hex(),
            "seq": f["seq"],
            "pad_byte": 0xAA,
        },
        "expected": {
            "host_key_blob": host_blob.hex(),
            "user_key_blob": user_blob.hex(),
            "authorized_keys_line": authorized,
            "kexinit_payload": i_c.hex(),
            "exchange_hash": H.hex(),
            **{k: v.hex() for k, v in keys.items()},
            "userauth_sign_blob": auth.hex(),
            "encrypted_packet_with_mac": pkt.hex(),
        },
    }
    out = Path(__file__).parent / "vectors.json"
    out.write_text(json.dumps(vectors, indent=1))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
