"""Servable bundles: export → AssetStore → load → serve round trip.

The contract: a bundle is self-describing — loading needs only the
asset, and the loaded model decodes identically to the original
(including bf16 leaves that ride npz as raw bytes, and int8-quantized
trees that must serve as int8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
from k8s_gpu_tpu.models.transformer import TransformerConfig, TransformerLM
from k8s_gpu_tpu.platform.assets import AssetStore
from k8s_gpu_tpu.serve import (
    InferenceEngine, export_servable, load_servable, quantize_params,
)
from k8s_gpu_tpu.serve.bundle import _flatten, _unflatten


def _model(dtype=jnp.float32):
    cfg = TransformerConfig(
        vocab_size=300, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=64, dtype=dtype, use_flash=False, remat=False,
    )
    m = TransformerLM(cfg)
    return m, m.init(jax.random.PRNGKey(0))


def test_flatten_roundtrip():
    tree = {"a": 1, "b": {"c": 2, "d": {"e": 3}}}
    assert _unflatten(dict(_flatten(tree))) == tree


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_export_load_identical(tmp_path, dtype):
    store = AssetStore(tmp_path)
    model, params = _model(dtype)
    a = export_servable(store, "ml", "lm", model, params)
    assert a.kind == "model" and a.version == "v1"
    m2, p2, tok = load_servable(store, "ml", "lm")
    assert tok is None
    assert m2.cfg == model.cfg
    for (k1, v1), (k2, v2) in zip(
        sorted(_flatten(params)), sorted(_flatten(p2))
    ):
        assert k1 == k2
        assert v1.dtype == v2.dtype, k1
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))


def test_quantized_bundle_serves_int8(tmp_path):
    store = AssetStore(tmp_path)
    model, params = _model()
    qp = quantize_params(params)
    export_servable(store, "ml", "lm-int8", model, qp)
    m2, p2, _ = load_servable(store, "ml", "lm-int8")
    assert p2["blocks"]["wq"]["q"].dtype == jnp.int8
    ref = InferenceEngine(model).generate(
        qp, jnp.ones((1, 5), jnp.int32), max_new_tokens=6
    )
    got = InferenceEngine(m2).generate(
        p2, jnp.ones((1, 5), jnp.int32), max_new_tokens=6
    )
    assert jnp.array_equal(ref.tokens, got.tokens)


def test_bundle_with_tokenizer_and_versioning(tmp_path):
    store = AssetStore(tmp_path)
    model, params = _model()
    tok = BpeTokenizer.train("the quick brown fox " * 40, vocab_size=280,
                             backend="python")
    export_servable(store, "ml", "lm", model, params, tokenizer=tok)
    export_servable(store, "ml", "lm", model, params, tokenizer=tok)
    assert store.versions("ml", "model", "lm") == ["v1", "v2"]
    _, _, tok2 = load_servable(store, "ml", "lm", version="v1")
    ids = tok2.encode("the quick brown fox")
    assert tok2.decode(ids) == "the quick brown fox"


def test_non_bundle_asset_rejected(tmp_path):
    store = AssetStore(tmp_path)
    store.import_bytes("ml", "model", "raw", b"not a bundle")
    with pytest.raises(ValueError, match="not a servable bundle"):
        load_servable(store, "ml", "raw")
