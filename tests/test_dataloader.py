"""Native C++ data loader vs the bit-exact Python fallback (SURVEY §7;
the reference's in-pod DataLoader role, GPU调度平台搭建.md:584-604)."""

import numpy as np
import pytest

from k8s_gpu_tpu.data import TokenLoader, native_available, write_tokens
from k8s_gpu_tpu.data.loader import epoch_permutation

SEQ = 8
BATCH = 4


@pytest.fixture
def token_file(tmp_path):
    # 40 samples of width SEQ+1 = 360 tokens, values = their index.
    return write_tokens(tmp_path / "toks.bin", np.arange(40 * (SEQ + 1)))


def collect(loader, n):
    out = []
    for _ in range(n):
        x, y = next(loader)
        out.append((x.copy(), y.copy()))
    return out


def test_python_backend_shapes_and_shift(token_file):
    with TokenLoader(token_file, SEQ, BATCH, backend="python",
                     shuffle=False) as dl:
        x, y = next(dl)
        assert x.shape == (BATCH, SEQ) and y.shape == (BATCH, SEQ)
        # Targets are inputs shifted by one within each sample window.
        assert (y[:, :-1] == x[:, 1:]).all()
        assert x[0, 0] == 0 and x[1, 0] == SEQ + 1


def test_drop_last_and_epoch_rollover(token_file):
    # 40 samples / batch 4 = 10 batches per epoch.
    with TokenLoader(token_file, SEQ, BATCH, backend="python",
                     shuffle=False) as dl:
        assert dl.batches_per_epoch == 10
        collect(dl, 10)
        # .epoch reports the epoch of the just-returned batch (matching
        # the native dl_next_batch contract): the 10th batch still belongs
        # to epoch 0; the 11th is the first of epoch 1.
        assert dl.epoch == 0
        collect(dl, 1)
        assert dl.epoch == 1


def test_sharding_partitions_samples(token_file):
    seen = set()
    for sid in range(2):
        with TokenLoader(token_file, SEQ, BATCH, shard=(sid, 2),
                         backend="python", shuffle=False) as dl:
            assert dl.num_local == 20
            for x, _ in collect(dl, dl.batches_per_epoch):
                seen.update(int(v) for v in x[:, 0])
    # Every sample's first token appears exactly once across both shards.
    assert seen == {i * (SEQ + 1) for i in range(40)}


def test_shuffle_deterministic_and_epoch_varying():
    p0 = epoch_permutation(16, seed=7, epoch=0)
    p0b = epoch_permutation(16, seed=7, epoch=0)
    p1 = epoch_permutation(16, seed=7, epoch=1)
    q0 = epoch_permutation(16, seed=8, epoch=0)
    assert (p0 == p0b).all()
    assert not (p0 == p1).all()
    assert not (p0 == q0).all()
    assert sorted(p0.tolist()) == list(range(16))


@pytest.mark.skipif(not native_available(), reason="native lib not buildable")
def test_native_matches_python_exactly(token_file):
    n_batches = 25  # crosses 2 epoch boundaries (10 per epoch)
    with TokenLoader(token_file, SEQ, BATCH, backend="python", seed=42) as py:
        ref = collect(py, n_batches)
    with TokenLoader(token_file, SEQ, BATCH, backend="native", seed=42,
                     prefetch_depth=4, n_threads=3) as nat:
        got = collect(nat, n_batches)
    for (rx, ry), (gx, gy) in zip(ref, got):
        np.testing.assert_array_equal(rx, gx)
        np.testing.assert_array_equal(ry, gy)


@pytest.mark.skipif(not native_available(), reason="native lib not buildable")
def test_native_sharded_shuffled_parity(token_file):
    for sid in range(2):
        with TokenLoader(token_file, SEQ, BATCH, shard=(sid, 2),
                         backend="python", seed=3) as py:
            ref = collect(py, 12)
        with TokenLoader(token_file, SEQ, BATCH, shard=(sid, 2),
                         backend="native", seed=3) as nat:
            got = collect(nat, 12)
        for (rx, _), (gx, _) in zip(ref, got):
            np.testing.assert_array_equal(rx, gx)


def test_too_small_shard_raises(tmp_path):
    f = write_tokens(tmp_path / "tiny.bin", np.arange(2 * (SEQ + 1)))
    with pytest.raises(ValueError):
        TokenLoader(f, SEQ, BATCH, backend="python")
