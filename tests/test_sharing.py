"""Chip-granular sharing — the HAMi role (C17), TPU-flavored: disjoint
TPU_VISIBLE_CHIPS grants, best-fit anti-fragmentation, gang isolation, and
the DevEnv integration (a 1-chip debug env on a shared host)."""

import pytest

from k8s_gpu_tpu.api.core import Node, Pod
from k8s_gpu_tpu.api.devenv import DevEnv
from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.operators import DevEnvReconciler, TpuPodSliceReconciler
from k8s_gpu_tpu.scheduling import (
    ChipAllocator,
    PlacementError,
    place_gang,
    TPU_RESOURCE,
)
from k8s_gpu_tpu.scheduling.labels import (
    LABEL_ACCELERATOR,
    LABEL_SLICE,
    LABEL_WORKER_ID,
)


def tpu_node(name, chips=4, slice_name="s0", worker=0, accel="v4-8"):
    n = Node()
    n.metadata.name = name
    n.capacity = {TPU_RESOURCE: chips}
    n.allocatable = {TPU_RESOURCE: chips}
    n.ready = True
    n.metadata.labels = {
        LABEL_ACCELERATOR: accel,
        LABEL_SLICE: slice_name,
        LABEL_WORKER_ID: str(worker),
    }
    return n


def test_allocations_are_disjoint_and_env_shaped():
    nodes = [tpu_node("n0")]
    alloc = ChipAllocator()
    a = alloc.allocate("p1", 2, nodes)
    b = alloc.allocate("p2", 2, nodes)
    assert set(a.chip_ids) & set(b.chip_ids) == set()
    assert a.env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert b.env["TPU_VISIBLE_CHIPS"] == "2,3"
    assert nodes[0].allocatable[TPU_RESOURCE] == 0
    with pytest.raises(PlacementError):
        alloc.allocate("p3", 1, nodes)


def test_best_fit_packs_fragmented_host_first():
    n0, n1 = tpu_node("n0"), tpu_node("n1")
    alloc = ChipAllocator()
    alloc.allocate("p1", 3, [n0, n1])  # n0 now has 1 free chip
    a = alloc.allocate("p2", 1, [n0, n1])
    # 1-chip request goes to the fragmented host, keeping n1 pristine.
    assert a.node == "n0"
    assert n1.allocatable[TPU_RESOURCE] == 4


def test_release_restores_capacity():
    nodes = [tpu_node("n0")]
    alloc = ChipAllocator()
    alloc.allocate("p1", 4, nodes)
    alloc.release("p1", nodes)
    assert nodes[0].allocatable[TPU_RESOURCE] == 4
    alloc.allocate("p2", 4, nodes)  # full host available again


def test_from_pods_rebuilds_state_and_detects_double_grant():
    nodes = [tpu_node("n0")]
    p = Pod()
    p.metadata.name = "p1"
    p.node_name = "n0"
    p.env = {"TPU_VISIBLE_CHIPS": "0,1"}
    p.phase = "Running"
    alloc = ChipAllocator.from_pods([p], nodes)
    assert nodes[0].allocatable[TPU_RESOURCE] == 2
    clash = Pod()
    clash.metadata.name = "p2"
    clash.node_name = "n0"
    clash.env = {"TPU_VISIBLE_CHIPS": "1,2"}
    clash.phase = "Running"
    with pytest.raises(PlacementError):
        ChipAllocator.from_pods([p, clash], nodes)


def test_gang_placement_skips_shared_hosts():
    # Slice s0's worker 0 has a carve-out; the 2-host gang must not use s0.
    s0 = [tpu_node("a0", slice_name="s0", worker=0),
          tpu_node("a1", slice_name="s0", worker=1)]
    s1 = [tpu_node("b0", slice_name="s1", worker=0),
          tpu_node("b1", slice_name="s1", worker=1)]
    ChipAllocator().allocate("dev", 1, [s0[0]])
    pods = []
    for i in range(2):
        p = Pod()
        p.metadata.name = f"job-w-{i}"
        pods.append(p)
    placed = place_gang(pods, s0 + s1, "v4-8")
    assert set(placed.values()) == {"b0", "b1"}


def test_devenv_with_chips_end_to_end(kube: FakeKube, manager: Manager):
    from k8s_gpu_tpu.api import TpuPodSlice
    from k8s_gpu_tpu.cloud import FakeCloudTpu, cloudtpu_client_factory

    cloud = FakeCloudTpu()
    manager.register(
        "TpuPodSlice",
        TpuPodSliceReconciler(kube, cloudtpu_client_factory(cloud),
                              provision_poll=0.02),
    )
    manager.register("DevEnv", DevEnvReconciler(kube))
    manager.start()
    ps = TpuPodSlice()
    ps.metadata.name = "pool"
    ps.spec.accelerator_type = "v4-8"
    kube.create(ps)
    assert manager.wait_idle(
        timeout=20,
        predicate=lambda: kube.get("TpuPodSlice", "pool").status.phase == "Ready",
    )

    env = DevEnv()
    env.metadata.name = "dbg"
    env.spec.username = "ada"
    env.spec.ssh_public_key = "ssh-ed25519 AAAA ada"
    env.spec.tpu_chips = 1
    kube.create(env)
    assert manager.wait_idle(
        timeout=10,
        predicate=lambda: kube.get("DevEnv", "dbg").status.phase == "Ready",
    )
    pod = kube.get("Pod", "devenv-ada")
    assert pod.env["TPU_VISIBLE_CHIPS"] == "0"
    assert pod.node_name
    node = kube.get("Node", pod.node_name, "default")
    assert node.allocatable[TPU_RESOURCE] == node.capacity[TPU_RESOURCE] - 1

    # Teardown restores the chip.
    kube.delete("DevEnv", "dbg")
    assert manager.wait_idle(
        timeout=10,
        predicate=lambda: kube.try_get("Pod", "devenv-ada") is None,
    )
    node = kube.get("Node", pod.node_name, "default")
    assert node.allocatable[TPU_RESOURCE] == node.capacity[TPU_RESOURCE]


def test_devenv_pending_when_no_chips(kube: FakeKube, manager: Manager):
    manager.register("DevEnv", DevEnvReconciler(kube))
    manager.start()
    env = DevEnv()
    env.metadata.name = "dbg"
    env.spec.username = "ada"
    env.spec.ssh_public_key = "ssh-ed25519 AAAA ada"
    env.spec.tpu_chips = 2
    kube.create(env)
    assert manager.wait_idle(
        timeout=10,
        predicate=lambda: kube.get("DevEnv", "dbg").status.phase == "Pending",
    )
    cur = kube.get("DevEnv", "dbg")
    assert "free chip" in cur.status.message


def test_grant_skips_gang_occupied_hosts(kube: FakeKube, manager: Manager):
    """A host whose chips are held by a gang worker (TPU requests, no chip
    grant) must never be carved up for a devenv."""
    n_busy = tpu_node("busy0")
    n_free = tpu_node("free0", slice_name="s1")
    kube.create(n_busy)
    kube.create(n_free)
    gang = Pod()
    gang.metadata.name = "job-w-0"
    gang.node_name = "busy0"
    gang.requests = {TPU_RESOURCE: 4}
    gang.phase = "Running"
    kube.create(gang)
    manager.register("DevEnv", DevEnvReconciler(kube))
    manager.start()
    env = DevEnv()
    env.metadata.name = "dbg"
    env.spec.username = "ada"
    env.spec.ssh_public_key = "ssh-ed25519 AAAA ada"
    env.spec.tpu_chips = 1
    kube.create(env)
    assert manager.wait_idle(
        timeout=10,
        predicate=lambda: kube.get("DevEnv", "dbg").status.phase == "Ready",
    )
    assert kube.get("Pod", "devenv-ada").node_name == "free0"


def test_chip_count_drift_replaces_pod(kube: FakeKube, manager: Manager):
    kube.create(tpu_node("n0"))
    manager.register("DevEnv", DevEnvReconciler(kube))
    manager.start()
    env = DevEnv()
    env.metadata.name = "dbg"
    env.spec.username = "ada"
    env.spec.ssh_public_key = "ssh-ed25519 AAAA ada"
    env.spec.tpu_chips = 1
    kube.create(env)
    assert manager.wait_idle(
        timeout=10,
        predicate=lambda: kube.get("DevEnv", "dbg").status.phase == "Ready",
    )
    cur = kube.get("DevEnv", "dbg")
    cur.spec.tpu_chips = 3
    kube.update(cur)
    assert manager.wait_idle(
        timeout=10,
        predicate=lambda: kube.get("Pod", "devenv-ada").requests.get(
            TPU_RESOURCE) == 3,
    )
    pod = kube.get("Pod", "devenv-ada")
    assert pod.env["TPU_VISIBLE_CHIPS"] == "0,1,2"
    node = kube.get("Node", "n0", "default")
    assert node.allocatable[TPU_RESOURCE] == 1


def _tpu_pool_node(kube, name, slice_name="s0", worker=0):
    n = tpu_node(name, slice_name=slice_name, worker=worker)
    kube.create(n)
    return n


def test_shared_chip_trainjob_end_to_end(kube: FakeKube, manager: Manager):
    """A 1-chip job (the reference's 1gpu instance type) carves a chip out
    of a shared host instead of taking a whole slice."""
    from k8s_gpu_tpu.api.trainjob import TrainJob
    from k8s_gpu_tpu.operators import TrainJobReconciler
    from k8s_gpu_tpu.platform import expand_template, parse_template

    _tpu_pool_node(kube, "host0")
    manager.register("TrainJob", TrainJobReconciler(kube))
    manager.start()

    tpl = parse_template(
        "title: tiny\nworkload: psum-smoke\n"
        "spec:\n  singleInstanceType: gpu-1x-16c-32g-1gpu\n"
    )
    job = expand_template(tpl, "tiny")
    assert job.spec.shared_chips == 1 and job.spec.num_workers == 1
    kube.create(job)
    assert manager.wait_idle(
        timeout=30,
        predicate=lambda: kube.get("TrainJob", "tiny").status.phase
        in ("Succeeded", "Failed"),
    )
    done = kube.get("TrainJob", "tiny")
    assert done.status.phase == "Succeeded", done.status.message
    assert done.status.placements == {"tiny-w-0": "host0"}
    # Grant released after completion.
    node = kube.get("Node", "host0", "default")
    assert node.allocatable[TPU_RESOURCE] == 4


def test_shared_job_waits_then_runs_when_chips_free(
    kube: FakeKube, manager: Manager
):
    from k8s_gpu_tpu.api.trainjob import TrainJob
    from k8s_gpu_tpu.operators import TrainJobReconciler

    manager.register("TrainJob", TrainJobReconciler(kube))
    manager.start()
    job = TrainJob()
    job.metadata.name = "waits"
    job.spec.shared_chips = 2
    job.spec.workload = "psum-smoke"
    kube.create(job)
    assert manager.wait_idle(
        timeout=10,
        predicate=lambda: "insufficient capacity"
        in kube.get("TrainJob", "waits").status.message,
    )
    _tpu_pool_node(kube, "late-host")
    # CAPACITY_POLL is 2s on the fixture's FakeClock: advance past it so
    # the retry fires and sees the new host.
    manager.clock.advance(3)
    assert manager.wait_idle(
        timeout=30,
        predicate=lambda: kube.get("TrainJob", "waits").status.phase
        == "Succeeded",
    )


def test_shared_and_gang_jobs_coexist(kube: FakeKube, manager: Manager):
    """A shared job on slice s0 must not block a gang on pristine s1, and
    the gang's hosts must be invisible to later shared jobs."""
    from k8s_gpu_tpu.api.trainjob import TrainJob
    from k8s_gpu_tpu.operators import TrainJobReconciler

    for i, (name, sl) in enumerate([("a0", "s0"), ("a1", "s0"),
                                    ("b0", "s1"), ("b1", "s1")]):
        _tpu_pool_node(kube, name, slice_name=sl, worker=i % 2)
    manager.register("TrainJob", TrainJobReconciler(kube, run_workloads=False))
    manager.start()

    shared = TrainJob()
    shared.metadata.name = "small"
    shared.spec.shared_chips = 1
    kube.create(shared)
    gang = TrainJob()
    gang.metadata.name = "big"
    gang.spec.accelerator_type = "v4-8"
    gang.spec.num_workers = 2
    kube.create(gang)
    assert manager.wait_idle(
        timeout=20,
        predicate=lambda: kube.get("TrainJob", "big").status.phase == "Running"
        and kube.get("TrainJob", "small").status.phase == "Running",
    )
    small_node = kube.get("TrainJob", "small").status.placements["small-w-0"]
    gang_nodes = set(kube.get("TrainJob", "big").status.placements.values())
    assert small_node not in gang_nodes
