"""Wire-level KV block migration (serve/migrate.py, ISSUE 17).

The contract pinned here, at two layers:

Batcher layer (``migrate_export`` / ``migrate_import`` through the
``run_quiesced`` round-boundary barrier):

1. parity: a greedy stream on the destination after import is
   token-for-token identical to the source's, and re-exporting the
   migrated chains returns byte-identical block bodies — migration
   moves state, it never transforms it;
2. leak-freedom: 200 alternating export/import churn cycles between two
   pools leave every block allocatable on both sides, and the payload
   stabilizes byte-identically once the pools converge;
3. determinism: two fresh runs over the same request sequence export
   byte-identical wire payloads (no ambient time, no ambient ids).

Fleet layer (``BlockMigrator`` + the gateway drain):

4. degradation: seeded ``migrate.export`` faults exhaust the capped
   retries, the drain falls back to the plain wait-and-retire path, and
   the in-flight stream still completes with zero lost tokens —
   degraded, never wrong;
5. the coordinator reports a dead endpoint as ``None`` after minting
   one ``migrate_failures_total{stage=}`` per failed attempt.
"""

import http.client
import json
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.data import BpeTokenizer
from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher, FleetFrontend, LmServer
from k8s_gpu_tpu.serve.migrate import (
    BlockMigrator,
    pack,
    payload_bytes,
    unpack,
)
from k8s_gpu_tpu.utils import FakeClock, MetricsRegistry
from k8s_gpu_tpu.utils.faults import FaultPlan, global_faults

CFG = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=1, n_heads=2, d_head=16,
    d_ff=64, max_seq=128, use_flash=False, dtype=jnp.float32,
)
MODEL = TransformerLM(CFG)
PARAMS = MODEL.init(jax.random.PRNGKey(0))

PAGE = 16
PREFIX = [(i * 7 + 3) % 120 for i in range(40)]   # 2 full pages + tail


def _mk(metrics=None):
    return ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=64, page_size=PAGE,
        metrics=metrics if metrics is not None else MetricsRegistry(),
    ).start()


def _export(b, **kw):
    return b.run_quiesced(lambda: b.migrate_export(**kw))


def _import(b, parsed):
    return b.run_quiesced(lambda: b.migrate_import(parsed))


def _leakfree(b):
    assert sorted(b._pool.allocatable_blocks()) == list(
        range(1, b.paged_blocks)
    )


# -- parity ---------------------------------------------------------------


def test_export_import_greedy_parity_and_byte_exact():
    """Blocks that crossed the wire ARE the source's blocks: the
    destination's greedy stream is identical, its prefix cache hits the
    migrated pages, and re-exporting them returns the same bytes."""
    ma, mb = MetricsRegistry(), MetricsRegistry()
    a = _mk(ma)
    ids = np.asarray(PREFIX + [99, 98], np.int32)
    toks_a = a.submit(ids, max_new_tokens=10, temperature=0.0).result()
    snap = _export(a)
    a.stop()
    payload = pack(snap)
    assert payload["blocks"], "nothing registered to migrate"
    assert payload["version"] == 1

    b = _mk(mb)
    try:
        n = _import(b, unpack(payload))
        assert n == len(payload["blocks"])
        toks_b = b.submit(
            ids, max_new_tokens=10, temperature=0.0
        ).result()
        assert toks_b == toks_a
        # The migrated chain is indistinguishable from a local one: the
        # destination's FIRST admission of this prompt prefix-hits it.
        assert mb.counter("serve_prefix_cache_hits_total") >= 1
        # Byte-exactness: the same hashes name the same bytes on both
        # sides of the wire.
        back = {
            e["hash"]: e["data"] for e in pack(_export(b))["blocks"]
        }
        for ent in payload["blocks"]:
            assert back[ent["hash"]] == ent["data"]
    finally:
        b.stop()
    _leakfree(b)


def test_import_rejects_malformed_payloads():
    """The import side refuses garbage instead of splicing it into a
    live pool: wrong version, missing geometry, truncated bodies."""
    with pytest.raises(ValueError, match="version"):
        unpack({"version": 2})
    with pytest.raises(ValueError, match="geometry"):
        unpack({"version": 1, "geometry": {}})
    a = _mk()
    a.submit(
        np.asarray(PREFIX + [99], np.int32),
        max_new_tokens=4, temperature=0.0,
    ).result()
    payload = pack(_export(a))
    a.stop()
    bad = json.loads(json.dumps(payload))
    first_leaf = sorted(bad["blocks"][0]["data"])[0]
    bad["blocks"][0]["data"][first_leaf] = "AAAA"
    with pytest.raises(ValueError, match="bytes"):
        unpack(bad)


# -- churn / leak-freedom -------------------------------------------------


def test_migrate_churn_200_cycles_leak_free():
    """200 alternating export/import cycles between two live pools:
    every block stays allocatable on both sides (imports park in LRU
    exactly like local retirement), re-imports are idempotent
    (duplicate hashes skip), and the payloads stabilize byte-identical
    once the pools converge."""
    a, b = _mk(), _mk()
    try:
        for i in range(2):
            a.submit(
                np.asarray(PREFIX + [70 + i], np.int32),
                max_new_tokens=4, temperature=0.0,
            ).result()
            b.submit(
                np.asarray(list(reversed(PREFIX)) + [80 + i], np.int32),
                max_new_tokens=4, temperature=0.0,
            ).result()
        prev = None
        for cycle in range(200):
            src, dst = (a, b) if cycle % 2 == 0 else (b, a)
            payload = pack(_export(src))
            _import(dst, unpack(payload))
            _leakfree(a)
            _leakfree(b)
            if cycle >= 2:
                # Converged: the same direction's export repeats
                # byte-identically (replica name is constant here).
                cur = payload_bytes(payload)
                if prev is not None and cycle % 2 == 0:
                    assert cur == prev
                if cycle % 2 == 0:
                    prev = cur
    finally:
        a.stop()
        b.stop()
    _leakfree(a)
    _leakfree(b)


# -- determinism ----------------------------------------------------------


def test_two_run_export_byte_identical():
    """Same model, same request sequence, fresh pools: the wire payload
    is byte-identical across runs — no timestamps, no ambient ids, and
    sorted block/leaf order."""

    def run():
        b = _mk()
        try:
            for i in range(2):
                b.submit(
                    np.asarray(PREFIX + [60 + i], np.int32),
                    max_new_tokens=4, temperature=0.0,
                ).result()
            snap = _export(b)
        finally:
            b.stop()
        p = pack(snap)
        p["replica"] = "pinned-name"
        return payload_bytes(p)

    assert run() == run()


# -- coordinator degradation ----------------------------------------------


def test_migrator_dead_endpoint_degrades_to_none():
    """A victim that cannot be reached exhausts the export stage's
    capped retries: one failure metric per attempt, ``None`` result —
    the caller falls back to re-prefill, nothing raises."""
    reg = MetricsRegistry()
    m = BlockMigrator(
        clock=FakeClock(), metrics=reg, timeout_s=0.2, max_attempts=2
    )
    assert m.migrate(
        "http://127.0.0.1:9", "http://127.0.0.1:9", victim="ghost"
    ) is None
    assert reg.counter("migrate_failures_total", stage="export") == 2.0
    assert m.last() is None


# -- fleet-level: seeded fault → fallback, zero lost ----------------------


@pytest.fixture(scope="module")
def fleet_stack():
    corpus = "the cat sat on the mat. the dog sat on the log. " * 40
    tok = BpeTokenizer.train(corpus, vocab_size=300)
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, use_flash=False,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return tok, model, params


def _mk_server(stack, name):
    tok, model, params = stack
    return LmServer(
        model, params, tok, slots=4, paged_blocks=64, page_size=8,
        metrics=MetricsRegistry(), name=name,
    ).start()


def test_seeded_export_fault_degrades_to_replay_zero_lost(fleet_stack):
    """Every export attempt faults (seeded ``migrate.export``): the
    drain's migration leg gives up after the retry cap and the drain
    degrades to the plain wait — the in-flight stream finishes on the
    victim with zero lost tokens, and the failure is on the meter."""
    tok, _, _ = fleet_stack
    servers = {
        f"mf-{i}": _mk_server(fleet_stack, f"mf-{i}") for i in range(2)
    }
    fe = FleetFrontend(
        tok, page_size=8, metrics=MetricsRegistry()
    ).start()
    try:
        for name, srv in servers.items():
            fe.register_replica(
                name, f"http://127.0.0.1:{srv.port}",
                on_drain=srv.drain,
            )
        global_faults.arm(
            "migrate.export",
            FaultPlan(seed=7, rate=1.0, kinds=("error",)),
        )
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=60)
        conn.request(
            "POST", "/generate",
            json.dumps({
                "prompt": "the cat sat on the log. the dog sat on "
                          "the mat. fault drill",
                "max_new_tokens": 24, "temperature": 0.0,
                "tenant": "acme", "stream": True,
            }),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        victim = resp.getheader("x-route-replica")
        code, st, _ = urllib_post(
            fe.url, "/admin/drain", {"name": victim, "deadline_s": 30.0}
        )
        assert code == 202 and st["state"] == "draining"
        events = [json.loads(line) for line in resp if line.strip()]
        conn.close()
        summary = events[-1]
        # Zero lost, zero duplicated: the full budget arrived and the
        # terminal event says completion, not truncation.
        assert summary["done"] is True, summary
        assert summary["generated_tokens"] == 24
        assert len(events) - 1 == 24
        # The degradation is observable, not silent.
        assert fe.metrics.counter(
            "migrate_failures_total", stage="export"
        ) >= 2.0
        assert fe.metrics.counter("migrate_blocks_total") == 0.0
        deadline_t = time.time() + 15.0
        state = {}
        while time.time() < deadline_t:
            with urllib.request.urlopen(
                fe.url + "/admin/drain", timeout=10
            ) as r:
                drains = json.loads(r.read())["drains"]
            state = next(
                (d for d in drains if d["replica"] == victim), {}
            )
            if state.get("state") == "retired":
                break
            time.sleep(0.05)
        assert state.get("state") == "retired", state
        assert state["forced"] is False
        assert "migrated" not in state  # the leg never succeeded
    finally:
        global_faults.disarm()
        fe.stop()
        for srv in servers.values():
            srv.stop()


def urllib_post(base, path, payload):
    req = urllib.request.Request(
        base.rstrip("/") + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload, dict(e.headers)
