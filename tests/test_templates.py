"""Template schema + expansion parity (C26/C27: GPU调度平台搭建.md:512-552)."""

import pytest
import yaml

from k8s_gpu_tpu.platform import (
    TemplateError,
    expand_template,
    parse_template,
    render_yaml,
    resolve_instance_type,
)

TEMPLATE = """
title: fashion-mnist-cnn
description: reference workload
image: registry.example.com/train:latest
command: python train.py --epochs 5
env:
  - name: EPOCHS
    value: "5"
repository:
  - space: ml-team
    id: fashion-repo
    hash: abc123
dataset:
  - space: ml-team
    id: fashion-mnist
    versionId: v3
model: []
mode: single
workload: cnn-train
spec:
  singleInstanceType: tpu-v4-8
"""


def test_parse_and_expand():
    tpl = parse_template(TEMPLATE)
    job = expand_template(tpl, "job-1")
    assert job.spec.title == "fashion-mnist-cnn"
    assert job.spec.instance_type == "tpu-v4-8"
    assert job.spec.accelerator_type == "v4-8"
    assert job.spec.num_workers == 2  # v4-8 = 2 hosts
    assert job.spec.env[0].name == "EPOCHS"
    assert job.spec.repository[0].version == "abc123"
    assert job.spec.dataset[0].version == "v3"


def test_bare_skips_expansion():
    job = expand_template(parse_template(TEMPLATE), "job-1", bare=True)
    assert job.spec.accelerator_type == ""
    assert job.spec.num_workers == 0


def test_dry_run_renders_yaml():
    job = expand_template(parse_template(TEMPLATE), "job-1")
    doc = yaml.safe_load(render_yaml(job))
    assert doc["kind"] == "TrainJob"
    assert doc["spec"]["acceleratorType"] == "v4-8"
    assert doc["spec"]["numWorkers"] == 2


def test_unknown_field_rejected():
    with pytest.raises(TemplateError):
        parse_template("title: x\nbogus: y\n")


def test_missing_title_rejected():
    with pytest.raises(TemplateError):
        parse_template("description: no title\n")


def test_invalid_yaml_rejected():
    with pytest.raises(TemplateError):
        parse_template("title: [unclosed\n")


def test_unknown_instance_type_rejected():
    with pytest.raises(TemplateError):
        expand_template(
            parse_template("title: x\nspec:\n  singleInstanceType: warp-drive\n"),
            "j",
        )


def test_gpu_alias_resolves_to_tpu():
    """Reference-era GPU instance strings (:535) map onto TPU capacity:
    a single-GPU instance becomes a single-chip carve-out, not a slice."""
    it = resolve_instance_type("gpu-1x-16c-32g-1gpu")
    assert it.shared_chips == 1 and it.workers == 1 and it.chips == 1
    assert resolve_instance_type("gpu-8x-96c-768g-8gpu").accelerator_type == "v5p-8"


def test_bare_accelerator_type_accepted():
    it = resolve_instance_type("v5p-64")
    assert it.workers == 16


def test_template_of_job_round_trips():
    """Regression (code review): `trainjob template -s` output must be
    consumable by `trainjob create`."""
    from k8s_gpu_tpu.platform import render_template

    job = expand_template(parse_template(TEMPLATE), "orig")
    text = render_template(job)
    job2 = expand_template(parse_template(text), "copy")
    assert job2.spec.accelerator_type == job.spec.accelerator_type
    assert job2.spec.num_workers == job.spec.num_workers
    assert job2.spec.dataset[0].version == "v3"


def test_bare_with_explicit_accelerator_is_runnable():
    """Regression (code review): --bare must be able to produce a
    schedulable job via explicit spec fields."""
    text = (
        "title: expert\nworkload: psum-smoke\n"
        "spec:\n  acceleratorType: v4-8\n  numWorkers: 2\n"
    )
    job = expand_template(parse_template(text), "j", bare=True)
    assert job.spec.accelerator_type == "v4-8"
    assert job.spec.num_workers == 2
