"""envtest-style integration: AzureVmPool replicas=2 reconcile with a fake
Azure client, CPU-only — BASELINE config 1, and the retry-ladder /
finalizer / leak contracts from reference README.md:167-240.
"""

import pytest

from k8s_gpu_tpu.api import AzureVmPool, Secret
from k8s_gpu_tpu.cloud import FakeAzureCloud, azure_client_factory
from k8s_gpu_tpu.controller import FakeKube, Manager, NotFound
from k8s_gpu_tpu.operators import AzureVmPoolReconciler
from k8s_gpu_tpu.utils.clock import FakeClock

CREDS = {
    "AZURE_CLIENT_ID": "cid",
    "AZURE_CLIENT_SECRET": "sec",
    "AZURE_TENANT_ID": "tid",
    "AZURE_SUBSCRIPTION_ID": "sub",
}


@pytest.fixture
def harness(kube: FakeKube, clock: FakeClock):
    cloud = FakeAzureCloud(clock=clock)
    mgr = Manager(kube, clock=clock)
    mgr.register(
        "AzureVmPool", AzureVmPoolReconciler(kube, azure_client_factory(cloud))
    )
    mgr.start()
    secret = Secret(data=dict(CREDS))
    secret.metadata.name = "azure-creds"
    kube.create(secret)
    yield kube, clock, cloud, mgr
    mgr.stop()


def make_pool(replicas=2):
    p = AzureVmPool()
    p.metadata.name = "gpu-pool"
    p.spec.replicas = replicas
    p.spec.vm_size = "Standard_NC4as_T4_v3"
    p.spec.location = "eastus"
    p.spec.azure_credential_secret = "azure-creds"
    return p


def ready(kube, want):
    def check():
        p = kube.try_get("AzureVmPool", "gpu-pool")
        return p is not None and p.status.ready_replicas == want

    return check


def test_replicas_2_reconciles_to_ready(harness):
    """BASELINE config 1: replicas=2, 0→Ready with readyReplicas parity."""
    kube, clock, cloud, mgr = harness
    kube.create(make_pool(2))
    assert mgr.wait_idle(predicate=ready(kube, 2))
    pool = kube.get("AzureVmPool", "gpu-pool")
    assert pool.status.ready_replicas == 2
    assert [v.name for v in pool.status.vms] == ["gpu-pool-vm-0", "gpu-pool-vm-1"]
    assert all(v.provisioning_state == "Succeeded" for v in pool.status.vms)
    conds = {c.type: c.status for c in pool.status.conditions}
    assert conds["Ready"] == "True"
    assert conds["Failed"] == "False"
    assert len(cloud.vms) == 2
    # Ownership tags on every VM (reference README.md:238).
    for vm in cloud.vms.values():
        assert vm.tags["managed-by"] == "vmpool-operator"
        assert vm.tags["owner"] == "default-gpu-pool"


def test_scale_up_then_down_deletes_head_and_leaks_nothing(harness):
    kube, clock, cloud, mgr = harness
    kube.create(make_pool(1))
    assert mgr.wait_idle(predicate=ready(kube, 1))
    p = kube.get("AzureVmPool", "gpu-pool")
    p.spec.replicas = 3
    kube.update(p)
    assert mgr.wait_idle(predicate=ready(kube, 3))
    p = kube.get("AzureVmPool", "gpu-pool")
    p.spec.replicas = 1
    kube.update(p)
    assert mgr.wait_idle(predicate=ready(kube, 1))
    # NICs/disks must be deleted with their VMs (reference README.md:239).
    assert cloud.leaked_attachments == 0
    assert len(cloud.vms) == 1


def test_unmanaged_vms_are_never_touched(harness):
    """Tag isolation: the anti-foot-gun (reference README.md:238)."""
    kube, clock, cloud, mgr = harness
    cloud.create_vm("intruder", make_pool().spec, {"managed-by": "someone-else"})
    kube.create(make_pool(0))
    assert mgr.wait_idle(predicate=ready(kube, 0))
    assert "intruder" in cloud.vms  # untouched


def test_auth_error_retries_at_30s(harness):
    """Retry ladder: auth failure → requeue 30 s (reference README.md:184)."""
    kube, clock, cloud, mgr = harness
    cloud.faults.fail_auth = 1
    kube.create(make_pool(1))
    assert mgr.wait_idle()
    p = kube.get("AzureVmPool", "gpu-pool")
    conds = {c.type: (c.status, c.reason) for c in p.status.conditions}
    assert conds["Failed"] == ("True", "AuthFailed")
    assert len(cloud.vms) == 0
    clock.advance(30.5)  # the 30 s retry fires and succeeds
    assert mgr.wait_idle(predicate=ready(kube, 1))


def test_list_error_retries_at_20s(harness):
    kube, clock, cloud, mgr = harness
    cloud.faults.fail_lists = 1
    kube.create(make_pool(1))
    assert mgr.wait_idle()
    assert kube.get("AzureVmPool", "gpu-pool").status.ready_replicas == 0
    clock.advance(20.5)
    assert mgr.wait_idle(predicate=ready(kube, 1))


def test_create_error_retries_at_40s(harness):
    kube, clock, cloud, mgr = harness
    cloud.faults.fail_creates = 1
    kube.create(make_pool(2))
    assert mgr.wait_idle()
    clock.advance(40.5)
    assert mgr.wait_idle(predicate=ready(kube, 2))


def test_missing_secret_sets_failed_condition(harness):
    kube, clock, cloud, mgr = harness
    p = make_pool(1)
    p.spec.azure_credential_secret = "nope"
    kube.create(p)
    assert mgr.wait_idle()
    conds = {c.type: (c.status, c.reason)
             for c in kube.get("AzureVmPool", "gpu-pool").status.conditions}
    assert conds["Failed"] == ("True", "AuthFailed")


def test_periodic_resync_heals_out_of_band_drift(harness):
    """Level-triggered self-healing: someone deletes a VM behind our back;
    the 60 s resync (reference README.md:233-234) recreates it."""
    kube, clock, cloud, mgr = harness
    kube.create(make_pool(2))
    assert mgr.wait_idle(predicate=ready(kube, 2))
    cloud.delete_vm("gpu-pool-vm-0")  # out-of-band drift
    clock.advance(61.0)
    assert mgr.wait_idle(predicate=ready(kube, 2))
    assert len(cloud.vms) == 2


def test_finalizer_deletes_cloud_resources(harness):
    """Graceful deletion (reference README.md:309): deleting the CR tears
    down every managed VM before the object disappears."""
    kube, clock, cloud, mgr = harness
    kube.create(make_pool(2))
    assert mgr.wait_idle(predicate=ready(kube, 2))
    kube.delete("AzureVmPool", "gpu-pool")
    assert mgr.wait_idle(
        predicate=lambda: kube.try_get("AzureVmPool", "gpu-pool") is None
    )
    assert len(cloud.vms) == 0
    assert cloud.leaked_attachments == 0


def test_events_emitted_on_create_and_delete(harness):
    """K8s Events on VM lifecycle (reference README.md:311)."""
    kube, clock, cloud, mgr = harness
    kube.create(make_pool(1))
    assert mgr.wait_idle(predicate=ready(kube, 1))
    reasons = [e.reason for e in kube.list("Event")]
    assert "VmCreated" in reasons


def test_idempotent_reconcile_no_churn(harness):
    """Reconcile must converge: once Ready, further resyncs issue no
    create/delete calls (reference README.md:240)."""
    kube, clock, cloud, mgr = harness
    kube.create(make_pool(2))
    assert mgr.wait_idle(predicate=ready(kube, 2))
    before = [c for c in cloud.api_calls if c in ("create", "delete")]
    for _ in range(3):
        clock.advance(61.0)
        assert mgr.wait_idle()
    after = [c for c in cloud.api_calls if c in ("create", "delete")]
    assert before == after


def test_slow_provisioning_reaches_ready_via_fast_poll(harness):
    """VMs that take (fake) minutes to provision still converge, via the
    5 s converge-poll while not Ready."""
    kube, clock, cloud, mgr = harness
    cloud.provisioning_delay = 120.0
    kube.create(make_pool(2))
    assert mgr.wait_idle()
    assert kube.get("AzureVmPool", "gpu-pool").status.ready_replicas == 0
    for _ in range(30):
        clock.advance(5.1)
        mgr.wait_idle()
        if kube.get("AzureVmPool", "gpu-pool").status.ready_replicas == 2:
            break
    assert kube.get("AzureVmPool", "gpu-pool").status.ready_replicas == 2
