"""CLI verb parity (C26): login/context/whoami/trainjob/pool/asset flows
against an isolated state dir."""

import os

import pytest

from k8s_gpu_tpu.cli.main import main


def _no_cryptography() -> bool:
    # `devenv keygen` is the one CLI verb with a hard dependency on the
    # optional 'cryptography' package (real Ed25519 keys); skip by name
    # instead of failing where the env lacks it.
    try:
        import cryptography  # noqa: F401
        return False
    except ImportError:
        return True


@pytest.fixture(autouse=True)
def isolated_dirs(tmp_path, monkeypatch):
    monkeypatch.setenv("K8SGPU_CONFIG_DIR", str(tmp_path / "config"))
    monkeypatch.setenv("K8SGPU_STATE_DIR", str(tmp_path / "state"))
    yield tmp_path


def run(capsys, *argv):
    code = main(list(argv))
    out = capsys.readouterr()
    return code, out.out, out.err


def test_requires_login(capsys):
    code, out, err = run(capsys, "whoami")
    assert code == 2
    assert "not logged in" in err


def test_login_whoami_contexts(capsys):
    code, out, _ = run(capsys, "login", "--user", "ada", "--space", "ml")
    assert code == 0 and "logged in as ada" in out
    code, out, _ = run(capsys, "whoami")
    assert code == 0 and "user: ada" in out and "space: ml" in out
    run(capsys, "context", "new", "prod", "--space", "prod-ml", "--user", "ada")
    code, out, _ = run(capsys, "context", "list")
    assert "prod" in out and "* default" in out
    code, out, _ = run(capsys, "context", "use", "prod")
    assert code == 0
    code, out, err = run(capsys, "context", "use", "nope")
    assert code == 1 and "no such context" in err


def test_trainjob_template_skeleton(capsys):
    run(capsys, "login", "--user", "ada")
    code, out, _ = run(capsys, "trainjob", "template")
    assert code == 0 and "singleInstanceType" in out


def test_trainjob_dry_run_and_create(tmp_path, capsys):
    run(capsys, "login", "--user", "ada")
    tpl = tmp_path / "job.yaml"
    tpl.write_text(
        "title: smoke\nworkload: psum-smoke\nspec:\n  singleInstanceType: tpu-v4-8\n"
    )
    code, out, _ = run(capsys, "trainjob", "create", "-f", str(tpl), "--dry-run")
    assert code == 0 and "acceleratorType: v4-8" in out
    code, out, _ = run(
        capsys, "trainjob", "create", "-f", str(tpl), "--name", "smoke1"
    )
    assert code == 0 and "Succeeded" in out
    code, out, _ = run(capsys, "trainjob", "list")
    assert "smoke1" in out and "Succeeded" in out
    code, out, _ = run(capsys, "trainjob", "logs", "smoke1")
    assert code == 0 and "result" in out


def test_pool_apply_list_delete(capsys):
    run(capsys, "login", "--user", "ada")
    code, out, _ = run(
        capsys, "pool", "apply", "p1", "--accelerator", "v5p-64"
    )
    assert code == 0 and "Ready" in out
    code, out, _ = run(capsys, "pool", "list")
    assert "v5p-64" in out
    code, out, _ = run(capsys, "pool", "delete", "p1")
    assert code == 0


def test_pool_state_persists_across_invocations(capsys):
    run(capsys, "login", "--user", "ada")
    run(capsys, "pool", "apply", "p1", "--accelerator", "v4-8")
    # Fresh platform instance (new CLI process equivalent) still sees it.
    code, out, _ = run(capsys, "pool", "list")
    assert "p1" in out and "Ready" in out


def test_asset_import_and_list(tmp_path, capsys):
    run(capsys, "login", "--user", "ada")
    data = tmp_path / "weights.bin"
    data.write_bytes(b"w" * 128)
    code, out, _ = run(
        capsys, "asset", "import", "--kind", "model", "--id", "lm",
        "--path", str(data),
    )
    assert code == 0 and "v1" in out
    code, out, _ = run(capsys, "asset", "list")
    assert "model\tlm\tv1" in out


def test_repo_push(tmp_path, capsys):
    run(capsys, "login", "--user", "ada")
    repo = tmp_path / "code"
    repo.mkdir()
    (repo / "train.py").write_text("print('x')")
    code, out, _ = run(capsys, "repo", "push", "myrepo", "--path", str(repo))
    assert code == 0 and "pushed myrepo v1" in out


def test_bad_template_fails_cleanly(tmp_path, capsys):
    run(capsys, "login", "--user", "ada")
    tpl = tmp_path / "bad.yaml"
    tpl.write_text("nonsense_field: 1\n")
    code, _, err = run(capsys, "trainjob", "create", "-f", str(tpl))
    assert code == 1 and "error:" in err


def test_devenv_flow(tmp_path, capsys):
    run(capsys, "login", "--user", "ada")
    key = tmp_path / "id_ed25519.pub"
    key.write_text("ssh-ed25519 AAAA ada@laptop\n")
    code, out, err = run(capsys, "devenv", "create", "--pubkey", str(key))
    assert code == 0 and "Ready" in out and ":2022" in out, (out, err)
    code, out, _ = run(capsys, "devenv", "list")
    assert "env-ada" in out and "ada" in out
    code, out, _ = run(capsys, "devenv", "delete", "env-ada")
    assert code == 0 and "PVC retained" in out
    # Creating without a key for a new env is a usage error.
    code, _, err = run(capsys, "devenv", "create", "--name", "env-2")
    assert code == 2 and "pubkey" in err


def test_obs_logs_and_metrics(capsys):
    run(capsys, "login", "--user", "ada")
    # Drive the platform so reconcile logs/metrics are generated+persisted.
    code, out, _ = run(capsys, "pool", "apply", "p1", "--accelerator", "v4-8")
    assert code == 0
    code, out, _ = run(capsys, "obs", "logs", "--tail", "200")
    assert code == 0 and "p1" in out
    code, out, _ = run(capsys, "obs", "logs", "-l", "level=info")
    assert code == 0
    code, out, _ = run(capsys, "obs", "metrics")
    assert code == 0 and "reconcile_total" in out


def test_obs_traces(capsys):
    run(capsys, "login", "--user", "ada")
    code, out, _ = run(capsys, "pool", "apply", "p1", "--accelerator", "v4-8")
    assert code == 0
    # The pool apply above ran reconciles in THIS process — the in-process
    # tracer renders them as flame trees (filterable by span name).
    code, out, _ = run(capsys, "obs", "traces", "--name", "reconcile")
    assert code == 0
    assert "trace " in out and "reconcile" in out
    code, _, err = run(capsys, "obs", "traces", "--name", "no-such-span")
    assert code == 1 and "no traces" in err


def test_ci_run_and_releases(tmp_path, capsys):
    run(capsys, "login", "--user", "ada", "--space", "ml")
    repo = tmp_path / "proj"
    repo.mkdir()
    (repo / "train.py").write_text("print('hi')\n")
    (repo / "train_job.yaml").write_text(
        "title: ci\nworkload: psum-smoke\nspec:\n  singleInstanceType: tpu-v4-8\n"
    )
    code, out, _ = run(capsys, "repo", "push", "proj", "--path", str(repo))
    assert code == 0
    code, out, _ = run(capsys, "ci", "run", "--repo", "proj")
    assert code == 0 and "deploy  success" in out
    code, out, _ = run(capsys, "ci", "releases", "gohai")
    assert code == 0 and "deployed" in out
    code, out, _ = run(capsys, "ci", "run", "--repo", "proj", "--tag", "v1")
    assert code == 0 and "train   success" in out


def test_devenv_ssh_and_put_cli_client(tmp_path, capsys):
    """C24 end-to-end with the platform's OWN client (VERDICT r3 #7):
    `devenv ssh -c` and `devenv put` speak the gateway protocol over a
    live TCP socket — CLI → TCP → pubkey auth → EXEC/PUT — and a wrong
    key is denied."""
    run(capsys, "login", "--user", "ada")
    key = tmp_path / "id.pub"
    key.write_text("ssh-ed25519 AAAATESTKEY ada@laptop\n")
    code, out, _ = run(capsys, "devenv", "create", "--pubkey", str(key))
    assert code == 0, out
    from k8s_gpu_tpu.cli.platform_local import LocalPlatform
    from k8s_gpu_tpu.platform.sshgate import SshGateway

    p = LocalPlatform()
    gw = SshGateway(p.kube, port=0, namespace="default",
                    assets=p.assets).start()
    try:
        ep = f"127.0.0.1:{gw.port}"
        code, out, err = run(
            capsys, "devenv", "ssh", "--gateway", ep, "--pubkey", str(key),
            "-c", "hostname", "-c", "whoami",
        )
        assert code == 0, err
        assert "devenv-ada" in out and "ada" in out
        data = tmp_path / "weights.bin"
        data.write_bytes(b"w" * 4096)
        code, out, err = run(
            capsys, "devenv", "put", "--gateway", ep, "--pubkey", str(key),
            "model", "m1", str(data),
        )
        assert code == 0, err
        assert "OK imported model/m1" in out and "4096 bytes" in out
        # the line-protocol put warns that it is deprecated (SFTP is
        # the standard-protocol path now)
        assert "deprecated" in err
        bad = tmp_path / "bad.pub"
        bad.write_text("ssh-ed25519 WRONGKEY\n")
        code, out, err = run(
            capsys, "devenv", "ssh", "--gateway", ep, "--pubkey", str(bad),
            "-c", "hostname",
        )
        assert code == 1 and "denied" in err
    finally:
        gw.stop()
        p.close()


@pytest.mark.skipif(
    _no_cryptography(),
    reason="devenv keygen needs the optional 'cryptography' package",
)
def test_devenv_ssh2_cli_end_to_end(tmp_path, capsys):
    """The SSH-2 stretch (VERDICT r3 #7): `devenv keygen` makes a real
    Ed25519 pair, `devenv create` registers the .pub, and `devenv ssh
    --ssh2 --key` runs the full RFC-4253 transport against the live
    gateway socket."""
    run(capsys, "login", "--user", "ada")
    code, out, _ = run(capsys, "devenv", "keygen", "--out", str(tmp_path))
    assert code == 0
    assert (tmp_path / "id_ed25519").exists()
    code, out, _ = run(capsys, "devenv", "create", "--pubkey",
                       str(tmp_path / "id_ed25519.pub"))
    assert code == 0, out
    from k8s_gpu_tpu.cli.platform_local import LocalPlatform
    from k8s_gpu_tpu.platform.sshgate import SshGateway

    p = LocalPlatform()
    gw = SshGateway(p.kube, port=0, namespace="default").start()
    try:
        ep = f"127.0.0.1:{gw.port}"
        code, out, err = run(
            capsys, "devenv", "ssh", "--gateway", ep, "--ssh2",
            "--key", str(tmp_path / "id_ed25519"),
            "-c", "hostname", "-c", "whoami",
        )
        assert code == 0, err
        assert "devenv-ada" in out and "ada" in out
        # wrong private key: transport-level auth failure
        run(capsys, "devenv", "keygen", "--out", str(tmp_path / "other"))
        code, _, err = run(
            capsys, "devenv", "ssh", "--gateway", ep, "--ssh2",
            "--key", str(tmp_path / "other" / "id_ed25519"),
            "-c", "hostname",
        )
        assert code == 1 and "denied" in err
    finally:
        gw.stop()


@pytest.mark.skipif(
    _no_cryptography(),
    reason="devenv keygen needs the optional 'cryptography' package",
)
def test_devenv_put_over_sftp_cli(tmp_path, capsys):
    """`devenv put --ssh2`: bulk upload rides the standard SFTP
    subsystem end-to-end (CLI → SSH-2 transport → sftp channel →
    versioned asset store) — the lftp-mirror role with no invented
    verbs (VERDICT r4 #6)."""
    run(capsys, "login", "--user", "ada")
    code, out, _ = run(capsys, "devenv", "keygen", "--out", str(tmp_path))
    assert code == 0
    code, out, _ = run(capsys, "devenv", "create", "--pubkey",
                       str(tmp_path / "id_ed25519.pub"))
    assert code == 0, out
    from k8s_gpu_tpu.cli.platform_local import LocalPlatform
    from k8s_gpu_tpu.platform.sshgate import SshGateway

    p = LocalPlatform()
    gw = SshGateway(p.kube, port=0, namespace="default",
                    assets=p.assets).start()
    try:
        ep = f"127.0.0.1:{gw.port}"
        data = tmp_path / "weights.bin"
        data.write_bytes(b"w" * 100_000)
        code, out, err = run(
            capsys, "devenv", "put", "--gateway", ep, "--ssh2",
            "--key", str(tmp_path / "id_ed25519"), "--space", "ml",
            "model", "m-sftp", str(data),
        )
        assert code == 0, err
        assert "imported model/m-sftp v1" in out
        assert "100000 bytes" in out
        assert "deprecated" not in err  # this IS the standard path
        a = p.assets.get("ml", "model", "m-sftp")
        assert a.size == 100_000
    finally:
        gw.stop()
        p.close()


def test_ci_install_uninstall(capsys):
    """`make deploy`'s CLI analogue (reference README.md:298-302): the
    platform chart installs with the operator image ref, upgrades
    idempotently, and uninstalls."""
    run(capsys, "login", "--user", "ada")
    code, out, _ = run(capsys, "ci", "install", "gohai",
                       "--image", "reg.example/op:v9")
    assert code == 0 and "revision 1 deployed" in out
    code, out, _ = run(capsys, "get", "Deployment", "gohai-api")
    assert code == 0 and "reg.example/op:v9" in out
    # upgrade --install semantics: second install bumps the revision
    code, out, _ = run(capsys, "ci", "install", "gohai",
                       "--set", "image=reg.example/op:v10")
    assert code == 0 and "revision 2 deployed" in out
    code, out, _ = run(capsys, "ci", "uninstall", "gohai")
    assert code == 0 and "uninstalled" in out
    code, _, _ = run(capsys, "get", "Deployment", "gohai-api")
    assert code != 0


def test_apply_get_delete_manifest(tmp_path, capsys):
    run(capsys, "login", "--user", "ada")
    f = tmp_path / "slice.yaml"
    f.write_text(
        "apiVersion: tpu.k8sgpu.dev/v1alpha1\nkind: TpuPodSlice\n"
        "metadata:\n  name: demo\nspec:\n  acceleratorType: v4-8\n"
    )
    code, out, _ = run(capsys, "apply", "-f", str(f))
    assert code == 0 and "tpupodslice/demo created" in out
    code, out, _ = run(capsys, "get", "TpuPodSlice", "demo")
    assert code == 0 and "phase: Ready" in out
    # Re-apply with a spec change: configured, reconciled.
    f.write_text(f.read_text().replace("acceleratorType: v4-8",
                                       "acceleratorType: v5p-8"))
    code, out, _ = run(capsys, "apply", "-f", str(f))
    assert code == 0 and "configured" in out
    code, out, _ = run(capsys, "get", "TpuPodSlice", "demo")
    assert "v5p-8" in out
    code, out, _ = run(capsys, "delete", "TpuPodSlice", "demo")
    assert code == 0
    code, out, err = run(capsys, "get", "TpuPodSlice", "demo")
    assert code == 1 and "not found" in err


def test_apply_provisions_class_pvc(tmp_path, capsys):
    """Integration: the assembled local platform dynamically provisions a
    class-bearing PVC applied as a manifest — provisioner registered,
    pools exist, usage resynced (C13 through the CLI front door)."""
    run(capsys, "login", "--user", "ada", "--space", "ml")
    manifest = tmp_path / "pvc.yaml"
    manifest.write_text(
        "apiVersion: v1\n"
        "kind: PersistentVolumeClaim\n"
        "metadata: {name: corpus}\n"
        "capacity: 50Gi\n"
        "storageClass: ceph-fs\n"
        "accessModes: [ReadWriteMany]\n"
        "phase: Pending\n"
    )
    code, out, err = run(capsys, "apply", "-f", str(manifest), "--validate")
    assert code == 0, err
    code, out, _ = run(capsys, "get", "PersistentVolumeClaim", "corpus")
    assert "Bound" in out and "pv-ml-corpus" in out
    code, out, _ = run(capsys, "get", "PersistentVolume", "pv-ml-corpus")
    assert "Bound" in out and "ceph" in out


def test_serve_model_asset(capsys, tmp_path):
    """The export→serve journey through the CLI: bundle a model into the
    platform asset store, then `serve` loads and stands the LM server up
    (briefly, via --for-seconds)."""
    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.cli.platform_local import LocalPlatform
    from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
    from k8s_gpu_tpu.models.transformer import (
        TransformerConfig, TransformerLM,
    )
    from k8s_gpu_tpu.serve import export_servable

    run(capsys, "login", "--user", "ada", "--space", "ml")
    cfg = TransformerConfig(
        vocab_size=300, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, max_seq=64, dtype=jnp.float32, use_flash=False,
        remat=False,
    )
    model = TransformerLM(cfg)
    tok = BpeTokenizer.train("tiny corpus for serving " * 30,
                             vocab_size=280, backend="python")
    p = LocalPlatform()
    try:
        export_servable(p.assets, "ml", "srv-lm", model,
                        model.init(jax.random.PRNGKey(0)), tokenizer=tok)
    finally:
        p.close()

    code, out, err = run(capsys, "serve", "srv-lm", "--for-seconds", "0.3")
    assert code == 0, err
    assert "serving ml/model/srv-lm" in out

    code, _, err = run(capsys, "serve", "missing", "--for-seconds", "0.1")
    assert code == 1 and "no asset" in err


def test_serve_with_draft_and_kv_quant(capsys, tmp_path):
    """`serve --draft <asset> --kv-quant`: speculative rounds + int8 KV
    from the CLI — both bundles load from the asset store."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.cli.platform_local import LocalPlatform
    from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
    from k8s_gpu_tpu.models.transformer import (
        TransformerConfig, TransformerLM,
    )
    from k8s_gpu_tpu.serve import export_servable

    run(capsys, "login", "--user", "ada", "--space", "ml")
    cfg = TransformerConfig(
        vocab_size=300, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=64, dtype=jnp.float32, use_flash=False,
        remat=False,
    )
    model = TransformerLM(cfg)
    draft = TransformerLM(dataclasses.replace(cfg, n_layers=1))
    tok = BpeTokenizer.train("tiny corpus for serving " * 30,
                             vocab_size=280, backend="python")
    p = LocalPlatform()
    try:
        export_servable(p.assets, "ml", "spec-lm", model,
                        model.init(jax.random.PRNGKey(0)), tokenizer=tok)
        export_servable(p.assets, "ml", "spec-draft", draft,
                        draft.init(jax.random.PRNGKey(1)), tokenizer=tok)
    finally:
        p.close()

    code, out, err = run(
        capsys, "serve", "spec-lm", "--draft", "spec-draft", "--kv-quant",
        "--for-seconds", "0.3",
    )
    assert code == 0, err
    assert "serving ml/model/spec-lm" in out
    code, _, err = run(
        capsys, "serve", "spec-lm", "--draft", "missing-draft",
        "--for-seconds", "0.1",
    )
    assert code == 1 and "no asset" in err

    # Model-free drafting is its own flag (mirroring spec.draftMode), so
    # '--draft ngram' is an ASSET lookup — an asset named 'ngram' is not
    # shadowed by the mode name.
    code, out, err = run(
        capsys, "serve", "spec-lm", "--draft-mode", "ngram",
        "--for-seconds", "0.3",
    )
    assert code == 0, err
    code, _, err = run(
        capsys, "serve", "spec-lm", "--draft", "ngram", "--for-seconds", "0.1",
    )
    assert code == 1 and "no asset" in err
    code, _, err = run(
        capsys, "serve", "spec-lm", "--draft", "spec-draft",
        "--draft-mode", "ngram", "--for-seconds", "0.1",
    )
    assert code == 2 and "mutually exclusive" in err


def test_serve_with_constraints(capsys, tmp_path):
    """--constraint name=regex stands the server up with a compiled
    bank; malformed specs and bad patterns exit cleanly."""
    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.cli.platform_local import LocalPlatform
    from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
    from k8s_gpu_tpu.models.transformer import (
        TransformerConfig, TransformerLM,
    )
    from k8s_gpu_tpu.serve import export_servable

    run(capsys, "login", "--user", "ada", "--space", "ml")
    tok = BpeTokenizer.train("0 1 2 answer yes no " * 30, vocab_size=270,
                             backend="python")
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, dtype=jnp.float32,
        use_flash=False, remat=False,
    )
    model = TransformerLM(cfg)
    p = LocalPlatform()
    try:
        export_servable(p.assets, "ml", "c-lm", model,
                        model.init(jax.random.PRNGKey(0)), tokenizer=tok)
    finally:
        p.close()

    code, out, err = run(
        capsys, "serve", "c-lm", "--for-seconds", "0.3",
        "--constraint", "digits=[0-9 ]+", "--eos-id", "0",
    )
    assert code == 0, err
    code, _, err = run(capsys, "serve", "c-lm", "--for-seconds", "0.1",
                       "--constraint", "nope", "--eos-id", "0")
    assert code == 2 and "expected key=value" in err
    code, _, err = run(capsys, "serve", "c-lm", "--for-seconds", "0.1",
                       "--constraint", "d=[0-9]+")
    assert code == 2 and "requires --eos-id" in err
    code, _, err = run(capsys, "serve", "c-lm", "--for-seconds", "0.1",
                       "--constraint", "bad=(unclosed", "--eos-id", "0")
    assert code == 1 and "parenthesis" in err


def test_serve_with_json_constraint(capsys, tmp_path):
    """--json-constraint name=schema.json compiles the schema through
    schema_to_regex into the same constraint bank; unsupported schemas
    and unreadable files exit cleanly."""
    import json as _json

    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.cli.platform_local import LocalPlatform
    from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
    from k8s_gpu_tpu.models.transformer import (
        TransformerConfig, TransformerLM,
    )
    from k8s_gpu_tpu.serve import export_servable

    run(capsys, "login", "--user", "ada", "--space", "ml")
    tok = BpeTokenizer.train('{"status": "ok"} 0 1 2 ' * 30, vocab_size=270,
                             backend="python")
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, dtype=jnp.float32,
        use_flash=False, remat=False,
    )
    model = TransformerLM(cfg)
    p = LocalPlatform()
    try:
        export_servable(p.assets, "ml", "j-lm", model,
                        model.init(jax.random.PRNGKey(0)), tokenizer=tok)
    finally:
        p.close()

    schema = tmp_path / "resp.json"
    schema.write_text(_json.dumps({
        "type": "object",
        "properties": {"status": {"enum": ["ok", "fail"]}},
    }))
    code, out, err = run(
        capsys, "serve", "j-lm", "--for-seconds", "0.3",
        "--json-constraint", f"resp={schema}", "--eos-id", "0",
    )
    assert code == 0, err

    bad = tmp_path / "bad.json"
    bad.write_text(_json.dumps({"$ref": "#/x"}))
    code, _, err = run(capsys, "serve", "j-lm", "--for-seconds", "0.1",
                       "--json-constraint", f"b={bad}", "--eos-id", "0")
    assert code == 2 and "unsupported schema keyword" in err
    code, _, err = run(capsys, "serve", "j-lm", "--for-seconds", "0.1",
                       "--json-constraint", "b=/nope/missing.json",
                       "--eos-id", "0")
    assert code == 2
