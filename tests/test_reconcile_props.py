"""Property-based reconcile fuzzing (SURVEY §5.2, §7 hard part 2; VERDICT
r2 #6): randomized fault plans × event interleavings × concurrent spec
edits, checked against the reconcile contract's invariants.

The homegrown controller runtime is exactly where interleaving bugs live —
the reference leans on controller-runtime for all of this (SURVEY §5.2).
Scenarios drive the reconciler SYNCHRONOUSLY (no Manager threads): each
event mutates cluster/cloud/spec state, then the reconciler runs some
number of times.  After the storm, faults clear and the loop must converge:

  I1  phase reaches Ready (slice_count>0) / Paused (==0)
  I2  status.readyReplicas == spec.sliceCount (the BASELINE parity metric)
  I3  exactly one owned queued resource (no duplicates, no strays)
  I4  cluster Nodes == the active QR's hosts, topology-labeled (no orphans)
  I5  re-reconcile at steady state is a no-op (no cloud mutations, no
      object writes)
  I6  delete converges to nothing: finalizer removes the QR and all Nodes
"""

import pytest
from hypothesis import HealthCheck, given, settings, strategies as st

from k8s_gpu_tpu.api import TpuPodSlice
from k8s_gpu_tpu.cloud import FakeCloudTpu, cloudtpu_client_factory
from k8s_gpu_tpu.controller import FakeKube
from k8s_gpu_tpu.controller.kubefake import Conflict
from k8s_gpu_tpu.operators import TpuPodSliceReconciler
from k8s_gpu_tpu.operators.tpupodslice import Request

ACCELS = ["v4-8", "v5p-8", "v5p-64", "v5e-16"]
RUNTIMES = ["tpu-ubuntu2204-base", "tpu-vm-v4-base"]

# Event vocabulary: (name, needs_qr)
EVENTS = st.sampled_from([
    "reconcile",
    "reconcile_twice",
    "edit_accel",
    "edit_slice_count",
    "edit_runtime",
    "preempt",
    "fault_create",
    "fault_delete",
    "fault_list",
    "fault_auth",
    "fault_provisioning",
    "stockout_on",
    "stockout_off",
])


class Scenario:
    def __init__(self):
        self.kube = FakeKube()
        self.cloud = FakeCloudTpu()
        self.rec = TpuPodSliceReconciler(
            self.kube, cloudtpu_client_factory(self.cloud)
        )
        ps = TpuPodSlice()
        ps.metadata.name = "fuzz"
        ps.spec.accelerator_type = "v4-8"
        self.kube.create(ps)
        self.req = Request(name="fuzz", namespace="default")

    def reconcile(self):
        try:
            self.rec.reconcile(self.req)
        except Conflict:
            pass  # a requeue would retry; the loop below reconciles again

    def edit(self, fn):
        """Concurrent spec edit: read-modify-write with conflict retry."""
        for _ in range(5):
            ps = self.kube.get("TpuPodSlice", "fuzz")
            fn(ps)
            try:
                self.kube.update(ps)
                return
            except Conflict:
                continue
        raise AssertionError("spec edit failed 5 conflicts in a row")

    def apply(self, event, draw):
        if event == "reconcile":
            self.reconcile()
        elif event == "reconcile_twice":
            self.reconcile()
            self.reconcile()
        elif event == "edit_accel":
            accel = draw(st.sampled_from(ACCELS))
            self.edit(lambda ps: setattr(ps.spec, "accelerator_type", accel))
        elif event == "edit_slice_count":
            n = draw(st.integers(min_value=0, max_value=2))
            self.edit(lambda ps: setattr(ps.spec, "slice_count", n))
        elif event == "edit_runtime":
            rt = draw(st.sampled_from(RUNTIMES))
            self.edit(lambda ps: setattr(ps.spec, "runtime_version", rt))
        elif event == "preempt":
            qrs = [
                q for q in self.cloud.queued_resources.values()
                if q.state == "ACTIVE" and q.slices
            ]
            if qrs:
                self.cloud.preempt_slice(qrs[0].name, 0)
        elif event == "fault_create":
            self.cloud.faults.fail_creates += draw(
                st.integers(min_value=1, max_value=2))
        elif event == "fault_delete":
            self.cloud.faults.fail_deletes += draw(
                st.integers(min_value=1, max_value=2))
        elif event == "fault_list":
            self.cloud.faults.fail_lists += draw(
                st.integers(min_value=1, max_value=2))
        elif event == "fault_auth":
            self.cloud.faults.fail_auth += draw(
                st.integers(min_value=1, max_value=2))
        elif event == "fault_provisioning":
            self.cloud.faults.fail_provisioning += 1
        elif event == "stockout_on":
            self.cloud.faults.stockout = True
        elif event == "stockout_off":
            self.cloud.faults.stockout = False

    # -- invariants --------------------------------------------------------
    def clear_faults(self):
        f = self.cloud.faults
        f.fail_creates = f.fail_deletes = f.fail_lists = f.fail_auth = 0
        f.fail_provisioning = 0
        f.stockout = False

    def converge(self, max_iters=60):
        for _ in range(max_iters):
            self.reconcile()
            ps = self.kube.try_get("TpuPodSlice", "fuzz")
            if ps is None:
                return None
            want = "Paused" if ps.spec.slice_count == 0 else "Ready"
            if ps.status.phase == want:
                return ps
        raise AssertionError(
            f"did not converge: phase={ps.status.phase} "
            f"spec={ps.spec.slice_count}x{ps.spec.accelerator_type} "
            f"qrs={[(q.name, q.state) for q in self.cloud.queued_resources.values()]}"
        )

    def owned_qrs(self):
        return [
            q for q in self.cloud.queued_resources.values()
            if q.tags.get("owner") == "default-fuzz"
        ]

    def pool_nodes(self):
        return [
            n for n in self.kube.list("Node")
            if n.metadata.labels.get("tpu.k8sgpu.dev/pool") == "default.fuzz"
        ]

    def check_invariants(self):
        ps = self.converge()  # I1
        assert ps.status.ready_replicas == ps.spec.slice_count  # I2
        qrs = self.owned_qrs()
        if ps.spec.slice_count == 0:
            assert qrs == [], f"scaled to zero but QRs remain: {qrs}"  # I3
            assert self.pool_nodes() == []  # I4
        else:
            assert len(qrs) == 1, f"duplicate/stray QRs: {qrs}"  # I3
            qr = qrs[0]
            assert qr.state == "ACTIVE"
            assert qr.accelerator_type == ps.spec.accelerator_type
            want_hosts = {
                h.hostname for inv in qr.slices for h in inv.hosts
            }
            got_hosts = {n.metadata.name for n in self.pool_nodes()}
            assert got_hosts == want_hosts, (  # I4: no orphans, none missing
                f"nodes {got_hosts} != hosts {want_hosts}"
            )
        # I5: steady state is a no-op — no cloud mutations, no writes.
        calls_before = list(self.cloud.api_calls)
        rv_before = self.kube.get("TpuPodSlice", "fuzz").metadata.resource_version
        self.reconcile()
        new_calls = self.cloud.api_calls[len(calls_before):]
        assert all(c == "list" for c in new_calls), (
            f"steady-state reconcile mutated the cloud: {new_calls}"
        )
        assert (
            self.kube.get("TpuPodSlice", "fuzz").metadata.resource_version
            == rv_before
        ), "steady-state reconcile wrote the object"
        # I6: delete tears everything down.
        self.kube.delete("TpuPodSlice", "fuzz")
        for _ in range(20):
            self.reconcile()
            if self.kube.try_get("TpuPodSlice", "fuzz") is None:
                break
        assert self.kube.try_get("TpuPodSlice", "fuzz") is None
        assert self.owned_qrs() == [], "finalizer leaked queued resources"
        assert self.pool_nodes() == [], "finalizer leaked nodes"


@settings(
    max_examples=120,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow, HealthCheck.data_too_large],
)
@given(data=st.data())
def test_reconcile_converges_under_fault_and_edit_storms(data):
    """120 randomized storms of faults, preemptions, and concurrent spec
    edits interleaved with reconciles — every one must satisfy I1-I6."""
    sc = Scenario()
    events = data.draw(st.lists(EVENTS, min_size=3, max_size=14))
    for ev in events:
        sc.apply(ev, data.draw)
    sc.clear_faults()
    sc.check_invariants()


@settings(max_examples=60, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(
    accel=st.sampled_from(ACCELS),
    slice_count=st.integers(min_value=1, max_value=3),
    n_preempts=st.integers(min_value=0, max_value=3),
    fail_provisioning=st.integers(min_value=0, max_value=2),
)
def test_self_heal_always_recovers(accel, slice_count, n_preempts,
                                   fail_provisioning):
    """60 randomized break-fix cycles: provisioning failures then repeated
    preemptions; each cycle must self-heal back to Ready with a fresh
    ACTIVE queued resource and full node parity."""
    sc = Scenario()
    sc.edit(lambda ps: (
        setattr(ps.spec, "accelerator_type", accel),
        setattr(ps.spec, "slice_count", slice_count),
    ))
    sc.cloud.faults.fail_provisioning = fail_provisioning
    ps = sc.converge()
    assert ps.status.ready_replicas == slice_count
    for _ in range(n_preempts):
        qr = sc.owned_qrs()[0]
        sc.cloud.preempt_slice(qr.name, 0)
        ps = sc.converge()
        assert ps.status.ready_replicas == slice_count
    sc.check_invariants()


@settings(max_examples=60, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(data=st.data())
def test_chip_allocator_never_leaks_capacity(data):
    """60 randomized allocate/release interleavings on shared nodes: used
    chips always equal the sum of live allocations, never exceed capacity,
    and full release returns every chip (the HAMi-sharing leak class the
    devenv Conflict bug lived in)."""
    from k8s_gpu_tpu.api.core import Node
    from k8s_gpu_tpu.scheduling.sharing import ChipAllocator

    n_nodes = data.draw(st.integers(min_value=1, max_value=3))
    cap = data.draw(st.sampled_from([4, 8]))
    nodes = []
    for i in range(n_nodes):
        n = Node()
        n.metadata.name = f"node-{i}"
        n.capacity = {"google.com/tpu": cap}
        n.ready = True
        nodes.append(n)
    alloc = ChipAllocator()
    alloc.sync_nodes(nodes)
    live: dict[str, int] = {}
    for step in range(data.draw(st.integers(min_value=1, max_value=25))):
        do_alloc = data.draw(st.booleans()) or not live
        if do_alloc:
            want = data.draw(st.integers(min_value=1, max_value=cap))
            name = f"pod-{step}"
            from k8s_gpu_tpu.scheduling.placement import PlacementError

            total_free = n_nodes * cap - sum(live.values())
            try:
                alloc.allocate(name, want, nodes)
                live[name] = want
            except PlacementError:
                # Legal only when no single host can fit the request.
                per_host_free = [
                    cap - alloc.used_chips(n.metadata.name) for n in nodes
                ]
                assert want > max(per_host_free), (
                    f"refused {want} chips with per-host free "
                    f"{per_host_free} (total {total_free})"
                )
        else:
            name = data.draw(st.sampled_from(sorted(live)))
            alloc.release(name, nodes)
            del live[name]
        used = sum(alloc.used_chips(n.metadata.name) for n in nodes)
        assert used == sum(live.values()), "capacity leak"
        for n in nodes:
            assert alloc.used_chips(n.metadata.name) <= cap
    for name in sorted(live):
        alloc.release(name, nodes)
    assert sum(alloc.used_chips(n.metadata.name) for n in nodes) == 0
