"""Replicated gateway fleet (ISSUE 18): reconstructible routing state,
gateway failover, and per-tenant weighted-fair admission.

The robustness contract this file pins:

- Routing state is RECONSTRUCTIBLE rather than replicated: the pure
  ``merge_owner_map`` kernel is order- and tie-break-deterministic, and
  N independently started live gateways rebuild byte-identical
  chain→owner maps from replica ``/debug/chains`` scrapes alone — no
  gossip, no consensus, no shared store — then re-converge after
  replica churn.
- A gateway killed cruelly mid-stream (accepted sockets slammed, not a
  graceful drain) loses ZERO accepted tokens: the client re-issues
  ``prompt_ids = original + delivered`` with ``x-resume-from`` against
  a surviving gateway and the assembled stream is byte-identical to an
  uninterrupted greedy reference.
- The admission door is weighted-fair and deterministic: DRR equalizes
  a 10:1 flood, weights skew admitted tokens proportionally,
  interactive preempts granted-not-running batch (requeued at the
  front — delayed, never lost), quotas throttle at the door and refill
  on the injected clock, SLO burn sheds batch before interactive, and
  two scripted runs produce byte-identical schedules and snapshots.
"""

import http.client
import json
import socket
import threading
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import jax
import pytest

from k8s_gpu_tpu.data import BpeTokenizer
from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import (
    AdmissionController,
    FleetFrontend,
    LmServer,
    merge_owner_map,
    owner_map_digest,
)
from k8s_gpu_tpu.utils import FakeClock, MetricsRegistry

PAGE = 8

TENANT_PROMPTS = {
    "acme": ("the cat sat on the log. the dog sat on the mat. "
             "the mat sat on the cat."),
    "blue": ("the dog sat on the mat. the cat sat on the log. "
             "the log sat on the dog."),
}


@pytest.fixture(scope="module")
def stack():
    corpus = "the cat sat on the mat. the dog sat on the log. " * 40
    tok = BpeTokenizer.train(corpus, vocab_size=300)
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, use_flash=False,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return tok, model, params


def _mk_server(stack, name):
    tok, model, params = stack
    return LmServer(
        model, params, tok, slots=4, paged_blocks=64, page_size=PAGE,
        metrics=MetricsRegistry(), name=name,
    ).start()


def _mk_gateway(stack, servers, **kw):
    tok, _, _ = stack
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry(), **kw
    ).start()
    for name, srv in servers.items():
        fe.register_replica(name, f"http://127.0.0.1:{srv.port}")
    return fe


def _post(base, path, payload, headers=None, timeout=60.0):
    req = urllib.request.Request(
        base.rstrip("/") + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        return e.code, body


def _get(base, path, timeout=30.0):
    with urllib.request.urlopen(
        base.rstrip("/") + path, timeout=timeout
    ) as r:
        return json.loads(r.read())


def _stream(base, body, headers=None):
    """Stream /generate, return (delivered token ids, finished).  A
    transport error mid-stream returns the partial list — exactly the
    client-side failover contract."""
    host, port = base.replace("http://", "").split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=120)
    delivered, finished = [], False
    try:
        conn.request(
            "POST", "/generate", json.dumps(body),
            {"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            resp.read()
            return delivered, False
        for raw in resp:
            line = raw.strip()
            if not line:
                continue
            ev = json.loads(line)
            if "id" in ev:
                delivered.append(int(ev["id"]))
            if "done" in ev:
                finished = bool(ev["done"])
    except (OSError, http.client.HTTPException, ValueError):
        return delivered, False
    finally:
        conn.close()
    return delivered, finished


# -- the pure reconstruction kernel --------------------------------------

def test_merge_owner_map_pure_and_deterministic():
    a, b = "ab" * 16, "cd" * 16
    scrapes = {"r2": [b, a], "r1": [a]}
    m1 = merge_owner_map(scrapes)
    # Single claimant owns directly; multi-claimant tie-breaks by
    # rendezvous over the sorted claimant set — same inputs in any
    # scrape order give the same map and digest.
    assert m1[b] == "r2"
    assert m1[a] in ("r1", "r2")
    m2 = merge_owner_map({"r1": [a], "r2": [a, b]})
    assert m1 == m2
    assert owner_map_digest(m1) == owner_map_digest(m2)
    # Malformed hashes are dropped, never poison the map.
    m3 = merge_owner_map({"r1": [a, "zz-not-hex"], "r2": [a]})
    assert set(m3) == {a}


def test_owner_map_digest_is_canonical():
    m = {"aa": "r1", "bb": "r2"}
    assert owner_map_digest(m) == owner_map_digest(
        dict(reversed(list(m.items())))
    )
    assert owner_map_digest(m) != owner_map_digest({"aa": "r1"})


# -- live fleet: reconstruction, convergence, churn ----------------------

def test_gateways_converge_and_survive_churn(stack):
    """3 independently started gateways rebuild byte-identical owner
    maps from scrapes alone, the admin plane serves digest + peers,
    and replica churn re-converges (dead replica out of the map)."""
    servers = {f"ha-{i}": _mk_server(stack, f"ha-{i}") for i in range(2)}
    gws = [_mk_gateway(stack, servers) for _ in range(3)]
    try:
        # Warm chains through ONE gateway only — the other two start
        # with no routing state and must reconstruct it.
        for t in ("acme", "blue"):
            for i in range(3):
                code, _ = _post(gws[0].url, "/generate", {
                    "prompt": TENANT_PROMPTS[t] + f" q{i}",
                    "max_new_tokens": 4, "temperature": 0.0,
                    "tenant": t,
                })
                assert code == 200
        for a in gws:
            for b in gws:
                if a is not b:
                    a.add_peer(f"gw-{gws.index(b)}", b.url)
        # Two passes: everyone reconstructs, THEN everyone compares
        # digests (a peer can only agree once it has reconstructed).
        for fe in gws:
            fe.reconstruct(check_peers=False)
        snaps = []
        for fe in gws:
            code, got = _post(fe.url, "/admin/ownermap", {})
            assert code == 200
            assert all(p["agree"] for p in got["peers"]), got["peers"]
            snaps.append(_get(fe.url, "/admin/ownermap"))
        digests = {s["digest"] for s in snaps}
        assert len(digests) == 1 and None not in digests
        blobs = {
            json.dumps(s["chains"], sort_keys=True) for s in snaps
        }
        assert len(blobs) == 1
        assert snaps[0]["chains"], "no chains reconstructed"
        assert gws[0].metrics.gauge("gateway_converged") == 1.0

        # Churn: kill one replica outright.  Scrape of the dead one
        # fails (counted), the merge drops its chains, and the fleet
        # re-converges on a new identical digest.
        servers["ha-1"].stop()
        for fe in gws:
            fe.reconstruct(check_peers=False)
        digests2, maps2 = set(), set()
        for fe in gws:
            snap = fe.owner_map_snapshot()
            digests2.add(snap["digest"])
            maps2.add(json.dumps(snap["chains"], sort_keys=True))
            assert "ha-1" not in set(snap["chains"].values())
        assert len(digests2) == 1 and len(maps2) == 1
        assert digests2 != digests
        assert gws[0].metrics.counter(
            "gateway_scrape_failures_total", replica="ha-1"
        ) >= 1.0

        # Every replica dead → reconstruction refuses loudly (503 on
        # the admin plane) rather than installing an empty map.
        servers["ha-0"].stop()
        code, _ = _post(gws[0].url, "/admin/ownermap", {})
        assert code == 503
    finally:
        for fe in gws:
            fe.stop()
        for srv in servers.values():
            try:
                srv.stop()
            except Exception:
                pass


def test_gateway_kill_mid_stream_zero_lost(stack):
    """Cruel-kill one of two gateways mid-burst (accepted sockets
    slammed, not a drain).  Every cut client fails over with
    ``prompt_ids = original + delivered`` + ``x-resume-from`` to the
    survivor; the assembled stream equals an uninterrupted greedy
    reference byte for byte — zero tokens lost or duplicated."""
    tok, _, _ = stack
    n_new = 16
    servers = {f"hk-{i}": _mk_server(stack, f"hk-{i}") for i in range(2)}
    fe_a = _mk_gateway(stack, servers)
    fe_b = _mk_gateway(stack, servers)
    socks = []
    orig = fe_b._httpd.process_request_thread

    def tracking(request, client_address):
        socks.append(request)
        orig(request, client_address)

    fe_b._httpd.process_request_thread = tracking
    killed = []
    try:
        prompts = [
            TENANT_PROMPTS[t] + f" k{i}"
            for i, t in enumerate(("acme", "blue", "acme", "blue"))
        ]
        started = threading.Event()
        lock = threading.Lock()
        results = {}

        def fire(i):
            p = prompts[i]
            ids = [int(x) for x in tok.encode(p).tolist()]
            base = (fe_a, fe_b)[i % 2]
            started.set()
            got, done = _stream(base.url, {
                "prompt": p, "max_new_tokens": n_new,
                "temperature": 0.0, "stream": True,
            })
            resumed = False
            if not done:
                more, done = _stream(fe_a.url, {
                    "prompt_ids": ids + got,
                    "max_new_tokens": n_new - len(got),
                    "temperature": 0.0, "stream": True,
                }, {"x-resume-from": "gw-b"})
                got, resumed = got + more, True
            with lock:
                results[i] = (got, done, resumed)

        def killer():
            started.wait(5.0)
            while not any(
                s.batcher.inflight_requests for s in servers.values()
            ):
                import time
                time.sleep(0.01)
            for s in socks:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            fe_b.stop()
            killed.append(True)

        kt = threading.Thread(target=killer)
        with ThreadPoolExecutor(max_workers=4) as ex:
            kt.start()
            futs = [ex.submit(fire, i) for i in range(len(prompts))]
            for f in futs:
                f.result()
        kt.join()

        assert len(results) == len(prompts)
        for i, (got, done, _resumed) in results.items():
            assert done, f"stream {i} never finished"
            assert len(got) == n_new, (i, len(got))
        # Zero-loss is byte-level: the failover-assembled stream must
        # equal an uninterrupted greedy reference on the survivor.
        for i, p in enumerate(prompts):
            ref, done = _stream(fe_a.url, {
                "prompt": p, "max_new_tokens": n_new,
                "temperature": 0.0, "stream": True,
            })
            assert done and results[i][0] == ref, f"stream {i} diverged"
        # The kill actually cut someone (the drill is vacuous
        # otherwise), and the replicas minted the resumed counter.
        cut = [i for i in results if results[i][2]]
        if cut:
            resumed_total = sum(
                s.batcher.metrics.counter("serve_resumed_requests_total")
                for s in servers.values()
            )
            assert resumed_total >= 1.0
    finally:
        fe_a.stop()
        if not killed:
            fe_b.stop()
        for srv in servers.values():
            srv.stop()


# -- the admission door: deterministic, FakeClock-driven -----------------

def _drain_round(adm, backlog, admitted):
    """Service exactly the grants standing now; releases re-pump for
    the next round, keeping backlog pressure alive."""
    ready = [
        tk for t in sorted(backlog) for tk in backlog[t]
        if tk.state == "granted"
    ]
    for tk in ready:
        if adm.try_run(tk):
            admitted[tk.tenant] = admitted.get(tk.tenant, 0.0) + tk.tokens
            adm.release(tk)
    for t in backlog:
        backlog[t] = [
            tk for tk in backlog[t]
            if tk.state in ("queued", "granted")
        ]


def test_drr_equalizes_ten_to_one_flood():
    clk = FakeClock()
    adm = AdmissionController(
        slots=4, quantum_tokens=32.0, clock=clk,
        metrics=MetricsRegistry(),
    )
    adm.set_tenant("hot", weight=1.0, priority="batch")
    adm.set_tenant("cold", weight=1.0, priority="batch")
    admitted = {"hot": 0.0, "cold": 0.0}
    backlog = {"hot": [], "cold": []}
    for _ in range(50):
        for t, n in (("hot", 10), ("cold", 2)):
            for _i in range(n):
                tk = adm.offer(t, 32)
                if tk.state in ("queued", "granted"):
                    backlog[t].append(tk)
        adm.pump()
        _drain_round(adm, backlog, admitted)
        clk.advance(0.1)
    xs = [admitted["hot"], admitted["cold"]]
    jain = (sum(xs) ** 2) / (len(xs) * sum(x * x for x in xs))
    assert jain >= 0.95, (jain, admitted)
    # The cold tenant got at least its equal-weight share.
    assert admitted["cold"] >= 0.45 * sum(xs)


def test_weight_skews_admitted_ratio():
    clk = FakeClock()
    adm = AdmissionController(
        slots=4, quantum_tokens=32.0, clock=clk,
        metrics=MetricsRegistry(),
    )
    adm.set_tenant("big", weight=3.0, priority="batch")
    adm.set_tenant("small", weight=1.0, priority="batch")
    admitted = {"big": 0.0, "small": 0.0}
    backlog = {"big": [], "small": []}
    for _ in range(60):
        for t in ("big", "small"):
            for _i in range(8):  # both saturated
                tk = adm.offer(t, 32)
                if tk.state in ("queued", "granted"):
                    backlog[t].append(tk)
        adm.pump()
        _drain_round(adm, backlog, admitted)
        clk.advance(0.1)
    ratio = admitted["big"] / max(1.0, admitted["small"])
    assert 2.0 <= ratio <= 4.5, (ratio, admitted)


def test_interactive_preempts_granted_batch_never_lost():
    clk = FakeClock()
    m = MetricsRegistry()
    adm = AdmissionController(
        slots=2, quantum_tokens=64.0, clock=clk, metrics=m,
    )
    adm.set_tenant("batchy", weight=1.0, priority="batch")
    adm.set_tenant("vip", weight=1.0, priority="interactive")
    b1 = adm.offer("batchy", 8)
    b2 = adm.offer("batchy", 8)
    assert b1.state == "granted" and b2.state == "granted"
    # b1 starts running — immune; b2 stays granted — preemptible.
    assert adm.try_run(b1)
    v = adm.offer("vip", 8)
    adm.pump()
    assert v.state == "granted"
    assert b1.state == "running"
    assert b2.state == "queued" and b2.preemptions == 1
    assert m.counter(
        "admission_preemptions_total", **{"class": "batch"}
    ) == 1.0
    # The revoked ticket is delayed, never lost: free capacity and it
    # wins its next round from the FRONT of its queue.
    adm.release(b1)
    adm.release(v)
    adm.pump()
    assert b2.state == "granted"


def test_quota_throttles_at_door_and_refills_on_clock():
    clk = FakeClock()
    m = MetricsRegistry()
    adm = AdmissionController(slots=8, clock=clk, metrics=m)
    adm.set_tenant(
        "metered", quota_tokens_per_s=10.0, quota_burst=20.0,
    )
    assert adm.offer("metered", 20).state == "granted"  # burst drained
    t = adm.offer("metered", 5)
    assert t.state == "throttled" and t.shed_reason == "quota"
    assert m.counter(
        "admission_quota_throttled_total", tenant="metered"
    ) == 1.0
    clk.advance(1.0)  # refill 10 tokens
    assert adm.offer("metered", 5).state == "granted"


def test_burn_sheds_batch_before_interactive():
    clk = FakeClock()
    m = MetricsRegistry()
    burn = [0.0]
    adm = AdmissionController(
        slots=8, clock=clk, metrics=m, burn_source=lambda: burn[0],
        burn_shed_batch=10.0, burn_shed_interactive=20.0,
    )
    adm.set_tenant("b", priority="batch")
    adm.set_tenant("i", priority="interactive")
    burn[0] = 12.0  # past batch threshold, under interactive
    tb = adm.offer("b", 4)
    ti = adm.offer("i", 4)
    assert tb.state == "shed" and tb.shed_reason == "burn"
    assert ti.state == "granted"
    assert m.counter("admission_sheds_total", reason="burn") == 1.0
    burn[0] = 25.0  # past interactive too — everyone sheds
    assert adm.offer("i", 4).state == "shed"


def test_two_runs_byte_identical_schedule_and_snapshot():
    def run():
        clk = FakeClock()
        adm = AdmissionController(
            slots=2, quantum_tokens=16.0, clock=clk,
            metrics=MetricsRegistry(),
        )
        adm.set_tenant("a", weight=2.0, priority="interactive",
                       quota_tokens_per_s=100.0)
        adm.set_tenant("b", weight=1.0, priority="batch")
        trace = []
        live = []
        for step in range(12):
            for t, n in (("a", 2), ("b", 3)):
                for _i in range(n):
                    tk = adm.offer(t, 8)
                    trace.append((tk.seq, tk.tenant, tk.state))
                    if tk.state in ("queued", "granted"):
                        live.append(tk)
            adm.pump()
            for tk in list(live):
                if tk.state == "granted" and adm.try_run(tk):
                    trace.append((tk.seq, tk.tenant, "ran"))
                    adm.release(tk)
                    live.remove(tk)
            trace.append(json.dumps(adm.snapshot(), sort_keys=True))
            clk.advance(0.25)
        return trace

    assert run() == run()
