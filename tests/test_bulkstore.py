"""Replicated bulk storage (C13, the Rook-Ceph alternative,
GPU调度平台搭建.md:226-237): class-based provisioning, replication-aware
capacity accounting, degradation, reclaim policies — and the static
(classless) PVC path staying untouched."""

import pytest

from k8s_gpu_tpu.api.core import PersistentVolumeClaim
from k8s_gpu_tpu.controller import FakeKube
from k8s_gpu_tpu.controller.manager import Request
from k8s_gpu_tpu.platform.bulkstore import (
    StoragePool,
    StorageProvisioner,
    parse_quantity,
)


def make(kube, name, capacity="10Gi", storage_class="ceph-block",
         modes=("ReadWriteOnce",)):
    pvc = PersistentVolumeClaim()
    pvc.metadata.name = name
    pvc.capacity = capacity
    pvc.storage_class = storage_class
    pvc.access_modes = list(modes)
    pvc.phase = "Pending"
    kube.create(pvc)
    return pvc


@pytest.fixture()
def setup():
    kube = FakeKube()
    prov = StorageProvisioner(kube)
    ceph = prov.pools.setdefault("ceph", StoragePool("ceph"))
    for i in range(3):
        ceph.add_device(f"osd-{i}", "100Gi")
    return kube, prov, ceph


def r(prov, name):
    return prov.reconcile(Request(name=name, namespace="default"))


def test_parse_quantity():
    assert parse_quantity("200Gi") == 200 * 2**30
    assert parse_quantity("1T") == 10**12
    assert parse_quantity("512") == 512
    with pytest.raises(ValueError):
        parse_quantity("10GB")


def test_provision_bind_and_replicated_accounting(setup):
    kube, prov, ceph = setup
    make(kube, "data")
    r(prov, "data")
    pvc = kube.get("PersistentVolumeClaim", "data")
    assert pvc.phase == "Bound" and pvc.volume_name == "pv-default-data"
    pv = kube.get("PersistentVolume", "pv-default-data")
    assert pv.phase == "Bound" and pv.replicas == 3
    # 10Gi at 3x replication charges 30Gi raw (the Ceph cost model).
    assert ceph.used == 3 * parse_quantity("10Gi")


def test_exhaustion_pends_then_unblocks(setup):
    kube, prov, ceph = setup
    make(kube, "big", capacity="90Gi")   # 270Gi raw of 300Gi
    r(prov, "big")
    make(kube, "more", capacity="20Gi")  # needs 60Gi raw, only 30 free
    res = r(prov, "more")
    pvc = kube.get("PersistentVolumeClaim", "more")
    assert pvc.phase == "Pending" and res.requeue_after
    events = [e for e in kube.list("Event")
              if e.reason == "PoolExhausted"]
    assert events and "replicas" in events[0].message
    # Capacity arrives (new OSD) → the level-triggered retry binds it.
    ceph.add_device("osd-3", "100Gi")
    r(prov, "more")
    assert kube.get("PersistentVolumeClaim", "more").phase == "Bound"


def test_degraded_pool_blocks_new_but_keeps_existing(setup):
    kube, prov, ceph = setup
    make(kube, "before")
    r(prov, "before")
    ceph.fail_device("osd-0")
    ceph.fail_device("osd-1")  # 1 device up < 3 replicas: no write quorum
    make(kube, "after")
    r(prov, "after")
    assert kube.get("PersistentVolumeClaim", "before").phase == "Bound"
    assert kube.get("PersistentVolumeClaim", "after").phase == "Pending"
    assert any(e.reason == "PoolDegraded" for e in kube.list("Event"))
    ceph.restore_device("osd-0")
    ceph.restore_device("osd-1")
    r(prov, "after")
    assert kube.get("PersistentVolumeClaim", "after").phase == "Bound"


def test_reclaim_delete_frees_capacity(setup):
    kube, prov, ceph = setup
    make(kube, "temp")
    r(prov, "temp")
    used = ceph.used
    assert used > 0
    kube.delete("PersistentVolumeClaim", "temp")
    r(prov, "temp")  # claim gone → reclaim pass
    assert ceph.used == 0
    assert kube.try_get("PersistentVolume", "pv-default-temp") is None


def test_reclaim_retain_releases_pv(setup):
    kube, prov, ceph = setup
    from k8s_gpu_tpu.platform.bulkstore import StorageClass

    prov.classes["keep"] = StorageClass(
        "keep", pool="ceph", access_modes=("ReadWriteOnce",),
        replicas=2, reclaim_policy="Retain",
    )
    make(kube, "precious", storage_class="keep")
    r(prov, "precious")
    kube.delete("PersistentVolumeClaim", "precious")
    r(prov, "precious")
    pv = kube.get("PersistentVolume", "pv-default-precious")
    assert pv.phase == "Released"
    assert ceph.used == 2 * parse_quantity("10Gi")  # Retain keeps the bytes


def test_access_mode_mismatch_and_unknown_class(setup):
    kube, prov, ceph = setup
    make(kube, "rwx-on-block", modes=("ReadWriteMany",))  # block is RWO
    r(prov, "rwx-on-block")
    assert kube.get("PersistentVolumeClaim", "rwx-on-block").phase == "Pending"
    make(kube, "lost", storage_class="nope")
    r(prov, "lost")
    assert any(e.reason == "UnknownStorageClass" for e in kube.list("Event"))


def test_cephfs_rwx_and_nfs_classes(setup):
    kube, prov, ceph = setup
    nfs = prov.pools.setdefault("nfs", StoragePool("nfs"))
    nfs.add_device("nfs-server", "500Gi")
    make(kube, "shared", storage_class="ceph-fs", modes=("ReadWriteMany",))
    make(kube, "ws", storage_class="workspace-nfs", modes=("ReadWriteMany",))
    r(prov, "shared")
    r(prov, "ws")
    assert kube.get("PersistentVolumeClaim", "shared").phase == "Bound"
    assert kube.get("PersistentVolumeClaim", "ws").phase == "Bound"
    assert nfs.used == parse_quantity("10Gi")  # 1x replication on nfs


def test_classless_pvc_untouched(setup):
    kube, prov, _ = setup
    pvc = PersistentVolumeClaim()
    pvc.metadata.name = "static"
    kube.create(pvc)
    rv = kube.get("PersistentVolumeClaim", "static").metadata.resource_version
    r(prov, "static")
    cur = kube.get("PersistentVolumeClaim", "static")
    assert cur.phase == "Bound" and cur.metadata.resource_version == rv


def test_idempotent_reconcile(setup):
    kube, prov, ceph = setup
    make(kube, "once")
    r(prov, "once")
    used = ceph.used
    r(prov, "once")
    r(prov, "once")
    assert ceph.used == used  # no double-charge
    assert len(kube.list("PersistentVolume")) == 1


def test_recreated_claim_does_not_double_charge_or_steal_stale_pv(setup):
    """Review finding: delete + recreate of a same-named claim must not
    silently adopt the old PV or charge the pool twice."""
    from k8s_gpu_tpu.platform.bulkstore import StorageClass

    kube, prov, ceph = setup
    prov.classes["keep"] = StorageClass(
        "keep", pool="ceph", access_modes=("ReadWriteOnce",),
        replicas=2, reclaim_policy="Retain",
    )
    make(kube, "data", storage_class="keep")
    r(prov, "data")
    kube.delete("PersistentVolumeClaim", "data")
    r(prov, "data")  # reclaim: Retain → Released PV stays, charge stays
    used_after_release = ceph.used
    make(kube, "data", storage_class="keep")  # same name, new claim
    r(prov, "data")
    cur = kube.get("PersistentVolumeClaim", "data")
    assert cur.phase == "Pending", "must not bind to a Released PV"
    assert ceph.used == used_after_release, "no double charge"
    assert any(e.reason == "StalePersistentVolume" for e in kube.list("Event"))


def test_resync_pools_rederives_usage(setup):
    kube, prov, ceph = setup
    make(kube, "a")
    r(prov, "a")
    want = ceph.used
    ceph.used = 0  # simulate a restarted provisioner with fresh memory
    prov.resync_pools()
    assert ceph.used == want


def test_unsafe_asset_components_rejected(tmp_path):
    """Review finding: space/kind/id become directory names and now arrive
    from network clients — traversal must be rejected, not resolved."""
    from k8s_gpu_tpu.platform import AssetStore

    store = AssetStore(tmp_path / "assets")
    for bad in ("../../etc", "a/b", "..", ".hidden", ""):
        with pytest.raises(ValueError):
            store.import_bytes(bad, "model", "x", b"data")
        with pytest.raises(ValueError):
            store.import_bytes("ml", "model", bad, b"data")
    store.import_bytes("ml", "model", "ok-1.2_3", b"data")  # safe chars fine
