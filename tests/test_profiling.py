"""Profiler traces (SURVEY §5.1): capture produces TensorBoard-readable
xplane artifacts; profile_trainer excludes compile from the trace window."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_tpu.utils import profile_trainer, trace, trace_files


def test_trace_captures_xplane(tmp_path):
    with trace(tmp_path / "tb"):
        jax.jit(lambda x: (x @ x).sum())(jnp.ones((64, 64))).block_until_ready()
    assert trace_files(tmp_path / "tb"), "no .xplane.pb produced"


def test_profile_trainer(tmp_path):
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
    from k8s_gpu_tpu.parallel import MeshConfig
    from k8s_gpu_tpu.parallel.mesh import build_mesh
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    model = TransformerLM(TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, max_seq=16, use_flash=False))
    trainer = Trainer(model, mesh=build_mesh(MeshConfig(dp=2), n_devices=2),
                      train_config=TrainConfig(warmup_steps=1))
    trainer.init(jax.random.PRNGKey(0))
    toks = np.random.default_rng(0).integers(0, 64, (4, 17), dtype=np.int32)

    def it():
        while True:
            yield toks[:, :-1], toks[:, 1:]

    out = profile_trainer(trainer, it(), steps=3, log_dir=tmp_path / "prof")
    assert out["steps"] == 3 and out["mean_step_s"] > 0
    assert trace_files(out["trace_dir"])
