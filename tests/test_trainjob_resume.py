"""Elastic recovery end-to-end (SURVEY §5.3-5.4; VERDICT r1 item #3):
periodic checkpoint → slice preemption mid-training → slice self-heals →
job re-places and RESUMES from the latest checkpoint — the loss curve
continues instead of restarting from step 0.

Runs on the real clock: the workload trains in a reconciler worker thread
while the slice reconciler concurrently notices the SUSPENDED queued
resource and prunes/recreates nodes.
"""

import time

import pytest

import k8s_gpu_tpu.operators.tpupodslice as tps_mod
import k8s_gpu_tpu.operators.trainjob as tj_mod
from k8s_gpu_tpu.api import TpuPodSlice, TrainJob
from k8s_gpu_tpu.cloud import FakeCloudTpu, cloudtpu_client_factory
from k8s_gpu_tpu.cloud.topology import parse_accelerator_type
from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.operators import TpuPodSliceReconciler, TrainJobReconciler

ACCEL = "v4-8"  # one host → one worker pod

WORKLOAD_ARGS = {
    "steps": 200, "d_model": 32, "layers": 1, "d_ff": 64, "batch": 2,
    "vocab": 64,
}


@pytest.fixture
def live(monkeypatch):
    # Real-clock harness with tight polling so preemption → prune →
    # re-place all happens within the test budget.
    monkeypatch.setattr(tps_mod, "RESYNC", 0.05)
    monkeypatch.setattr(tj_mod, "CAPACITY_POLL", 0.05)
    kube = FakeKube()
    cloud = FakeCloudTpu()
    mgr = Manager(kube)
    mgr.register(
        "TpuPodSlice",
        TpuPodSliceReconciler(
            kube, cloudtpu_client_factory(cloud), provision_poll=0.01
        ),
    )
    mgr.register("TrainJob", TrainJobReconciler(kube))
    mgr.start()
    yield kube, cloud, mgr
    mgr.stop()


def _wait(cond, timeout=60.0, what="condition"):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {what}")


def _make_job(name, tmp_path, interval=5):
    job = TrainJob()
    job.metadata.name = name
    job.spec.accelerator_type = ACCEL
    job.spec.num_workers = parse_accelerator_type(ACCEL).hosts
    job.spec.workload = "lm-train-ckpt"
    job.spec.workload_args = dict(WORKLOAD_ARGS)
    job.spec.restart_policy = "OnFailure"
    job.spec.checkpoint_interval_steps = interval
    job.spec.checkpoint_dir = str(tmp_path / f"ck-{name}")
    return job


def test_preempted_job_resumes_from_checkpoint(live, tmp_path):
    kube, cloud, mgr = live
    ps = TpuPodSlice()
    ps.metadata.name = "pool"
    ps.spec.accelerator_type = ACCEL
    kube.create(ps)
    _wait(lambda: kube.get("TpuPodSlice", "pool").status.phase == "Ready",
          what="slice Ready")

    kube.create(_make_job("elastic", tmp_path))
    # Let training make some progress past the first checkpoint...
    _wait(
        lambda: kube.get("TrainJob", "elastic").status.progress_step >= 8,
        timeout=120, what="training progress",
    )
    # ...then yank the slice out from under it (spot preemption).
    cloud.preempt_slice("default-pool-qr")

    _wait(
        lambda: kube.get("TrainJob", "elastic").status.phase == "Succeeded",
        timeout=180, what="job Succeeded after preemption",
    )
    job = kube.get("TrainJob", "elastic")
    assert job.status.restarts == 1
    assert job.status.result["resumed"]  # status floats bools → 1.0
    # It resumed from a periodic checkpoint, not from scratch.
    assert job.status.result["start_step"] >= 5
    assert job.status.resumed_from_step == job.status.result["start_step"]
    assert job.status.checkpoint_step >= job.status.resumed_from_step
    assert job.status.result["steps"] == WORKLOAD_ARGS["steps"]
    # The slice healed underneath it.
    assert kube.get("TpuPodSlice", "pool").status.phase == "Ready"


def test_loss_curve_continues_not_restarts(live, tmp_path):
    """The resumed run must land where an uninterrupted run lands: per-step
    data is derived from the step index and state comes from the
    checkpoint, so the final loss matches a control job exactly."""
    kube, cloud, mgr = live
    ps = TpuPodSlice()
    ps.metadata.name = "pool"
    ps.spec.accelerator_type = ACCEL
    kube.create(ps)
    _wait(lambda: kube.get("TpuPodSlice", "pool").status.phase == "Ready",
          what="slice Ready")

    kube.create(_make_job("interrupted", tmp_path))
    _wait(
        lambda: kube.get("TrainJob", "interrupted").status.progress_step >= 8,
        timeout=120, what="training progress",
    )
    cloud.preempt_slice("default-pool-qr")
    _wait(
        lambda: kube.get("TrainJob", "interrupted").status.phase
        == "Succeeded",
        timeout=180, what="interrupted job Succeeded",
    )

    kube.create(_make_job("control", tmp_path))
    _wait(
        lambda: kube.get("TrainJob", "control").status.phase == "Succeeded",
        timeout=180, what="control job Succeeded",
    )

    a = kube.get("TrainJob", "interrupted").status
    b = kube.get("TrainJob", "control").status
    assert a.restarts == 1 and b.restarts == 0
    assert a.result["resumed"] and not b.result["resumed"]
    assert a.result["last_loss"] == pytest.approx(
        b.result["last_loss"], abs=1e-4
    )


def test_restart_policy_never_fails_on_preemption(live, tmp_path):
    """Without OnFailure the old behavior stands: preemption → Failed."""
    kube, cloud, mgr = live
    ps = TpuPodSlice()
    ps.metadata.name = "pool"
    ps.spec.accelerator_type = ACCEL
    kube.create(ps)
    _wait(lambda: kube.get("TpuPodSlice", "pool").status.phase == "Ready",
          what="slice Ready")

    job = _make_job("oneshot", tmp_path)
    job.spec.restart_policy = "Never"
    kube.create(job)
    _wait(
        lambda: kube.get("TrainJob", "oneshot").status.progress_step >= 8,
        timeout=120, what="training progress",
    )
    cloud.preempt_slice("default-pool-qr")
    _wait(
        lambda: kube.get("TrainJob", "oneshot").status.phase == "Failed",
        timeout=180, what="job Failed",
    )
    job = kube.get("TrainJob", "oneshot")
    assert job.status.restarts == 0
    assert "placement node(s) lost" in job.status.message


def test_recreated_job_starts_fresh_and_conditions_clear(live, tmp_path):
    """A completed job's derived checkpoint dir is cleaned up (a re-created
    same-name job must not silently resume its predecessor) and a recovered
    job's Interrupted condition flips back to False on success."""
    kube, cloud, mgr = live
    ps = TpuPodSlice()
    ps.metadata.name = "pool"
    ps.spec.accelerator_type = ACCEL
    kube.create(ps)
    _wait(lambda: kube.get("TpuPodSlice", "pool").status.phase == "Ready",
          what="slice Ready")

    job = _make_job("fresh", tmp_path)
    job.spec.checkpoint_dir = ""  # use the derived default dir
    job.spec.workload_args = dict(WORKLOAD_ARGS, steps=12)
    kube.create(job)
    _wait(
        lambda: kube.get("TrainJob", "fresh").status.progress_step >= 4,
        timeout=120, what="training progress",
    )
    cloud.preempt_slice("default-pool-qr")
    _wait(lambda: kube.get("TrainJob", "fresh").status.phase == "Succeeded",
          timeout=180, what="job Succeeded")
    done = kube.get("TrainJob", "fresh")
    interrupted = next(
        c for c in done.status.conditions if c.type == "Interrupted"
    )
    assert interrupted.status == "False" and interrupted.reason == "Recovered"

    # Same name, new job: must train from step 0, not resume at 12.
    kube.delete("TrainJob", "fresh")
    _wait(lambda: kube.try_get("TrainJob", "fresh") is None,
          what="job deleted")
    job2 = _make_job("fresh", tmp_path)
    job2.spec.checkpoint_dir = ""
    job2.spec.workload_args = dict(WORKLOAD_ARGS, steps=12)
    kube.create(job2)
    _wait(lambda: kube.get("TrainJob", "fresh").status.phase == "Succeeded",
          timeout=180, what="re-created job Succeeded")
    again = kube.get("TrainJob", "fresh")
    assert not again.status.result["resumed"]
    assert again.status.result["start_step"] == 0
