"""Property-based serving stress: randomized request mixes vs oracles.

SURVEY §5.2 applied to the serving scheduler: the reconcile fuzzing
(test_reconcile_props.py) covers the control plane; this covers the
batcher — random interleavings of prompts × budgets × adapters ×
prefix-cache states must all produce their model's exact greedy stream,
with no deadlock, no cross-request leakage, and clean teardown under a
racing stop().

One long-lived batcher serves every hypothesis example (program
compiles amortize across examples; the scheduler is designed for
serving many requests over its lifetime, so reuse IS the realistic
shape).
"""

import threading

import jax
import jax.numpy as jnp
import pytest
from hypothesis import HealthCheck, given, settings, strategies as st

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher
from k8s_gpu_tpu.train.lora import LoraAdapter, LoraConfig

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
    d_ff=64, max_seq=48, use_flash=False, dtype=jnp.float32,
)

_MODEL = TransformerLM(CFG)
_PARAMS = _MODEL.init(jax.random.PRNGKey(0))


def _adapter():
    cfg = LoraConfig(rank=4, targets=("wq", "wv"))
    tree = LoraAdapter(cfg).init(jax.random.PRNGKey(1), _PARAMS)
    tree["blocks"] = {
        t: {"a": ab["a"],
            "b": jax.random.normal(jax.random.PRNGKey(50 + i),
                                   ab["b"].shape) * 0.05}
        for i, (t, ab) in enumerate(tree["blocks"].items())
    }
    return {"t1": (tree, cfg)}, LoraAdapter(cfg).merge(_PARAMS, tree)


_ADAPTERS, _MERGED = _adapter()
_ORACLE_CACHE: dict = {}


@jax.jit
def _oracle_logits(params, padded):
    return _MODEL.forward(params, padded)[0]


def _oracle(ids, n, adapter):
    """Greedy reference via the FULL forward (independent of the engine's
    cached decode).  One fixed [1, max_seq] shape for every call — the
    causal mask makes right-pad garbage invisible to position len-1, and
    the growing-shape variant compiled a fresh XLA program per emitted
    token, which at full-suite scale (hundreds of eager compiles) tips
    this jaxlib's CPU compiler into a segfault (utils/compat.py)."""
    key = (tuple(ids), n, adapter)
    if key not in _ORACLE_CACHE:
        params = _MERGED if adapter else _PARAMS
        S = CFG.max_seq
        seq = list(int(t) for t in ids)
        out = []
        for _ in range(n):
            padded = jnp.zeros((1, S), jnp.int32).at[0, : len(seq)].set(
                jnp.asarray(seq, jnp.int32)
            )
            logits = _oracle_logits(params, padded)
            nxt = int(jnp.argmax(logits[0, len(seq) - 1]))
            out.append(nxt)
            seq.append(nxt)
        _ORACLE_CACHE[key] = out
    return _ORACLE_CACHE[key]


@pytest.fixture(scope="module")
def batcher():
    b = ContinuousBatcher(_MODEL, _PARAMS, slots=3,
                          adapters=_ADAPTERS).start()
    b.precache_prefix([7, 3, 11])  # some prompts will hit, some won't
    yield b
    b.stop()


req_strategy = st.fixed_dictionaries({
    # some prompts extend the precached [7, 3, 11] prefix, some miss
    "prefix_hit": st.booleans(),
    "extra": st.lists(st.integers(1, 60), min_size=1, max_size=6),
    "max_new": st.integers(1, 6),
    "adapter": st.sampled_from([None, "t1"]),
})


@settings(max_examples=20, deadline=None,
          suppress_health_check=[HealthCheck.function_scoped_fixture])
@given(reqs=st.lists(req_strategy, min_size=2, max_size=6))
def test_random_mixes_match_oracles(batcher, reqs):
    # Oracles FIRST: computing them after submit puts the main thread's
    # eager-forward compiles concurrent with the batcher thread's round
    # compiles, which segfaults this jaxlib's CPU compiler (observed
    # twice in full-suite runs — a jaxlib thread-safety bug, avoided by
    # never compiling from two threads at once).
    want = []
    for r in reqs:
        ids = ([7, 3, 11] + r["extra"]) if r["prefix_hit"] else r["extra"]
        want.append((ids, r["max_new"], r["adapter"],
                     _oracle(ids, r["max_new"], r["adapter"])))
    handles = [
        (ids, exp,
         batcher.submit(ids, max_new_tokens=n, adapter=adapter))
        for ids, n, adapter, exp in want
    ]
    for ids, exp, h in handles:
        got = h.result()
        assert not h.aborted
        assert got == exp, ids


_DRAFT_MODEL = TransformerLM(
    TransformerConfig(
        vocab_size=64, d_model=24, n_layers=1, n_heads=2, d_head=12,
        d_ff=48, max_seq=48, use_flash=False, dtype=jnp.float32,
    )
)
_DRAFT_PARAMS = _DRAFT_MODEL.init(jax.random.PRNGKey(9))


@pytest.fixture(scope="module")
def spec_batcher():
    # Random-init draft: worst-case acceptance, so every accepted token
    # REALLY had to match the target argmax (VERDICT r3 ask #2's
    # "greedy bit-exactness preserved under interleaving").
    b = ContinuousBatcher(
        _MODEL, _PARAMS, slots=3, draft=(_DRAFT_MODEL, _DRAFT_PARAMS),
        spec_k=2,
    ).start()
    b.precache_prefix([7, 3, 11])
    yield b
    b.stop()


spec_req_strategy = st.fixed_dictionaries({
    "prefix_hit": st.booleans(),
    "extra": st.lists(st.integers(1, 60), min_size=1, max_size=6),
    "max_new": st.integers(1, 6),
})


@settings(max_examples=15, deadline=None,
          suppress_health_check=[HealthCheck.function_scoped_fixture])
@given(reqs=st.lists(spec_req_strategy, min_size=2, max_size=6))
def test_spec_random_mixes_stay_greedy_exact(spec_batcher, reqs):
    """Speculative rounds under random interleavings (mixed prefix-hit /
    cold admissions, random budgets): every stream must equal the plain
    greedy oracle bit-for-bit — acceptance variance across co-tenants
    changes round shapes, never tokens."""
    want = []
    for r in reqs:  # oracles first — see test_random_mixes_match_oracles
        ids = ([7, 3, 11] + r["extra"]) if r["prefix_hit"] else r["extra"]
        want.append((ids, r["max_new"], _oracle(ids, r["max_new"], None)))
    handles = [
        (ids, exp, spec_batcher.submit(ids, max_new_tokens=n))
        for ids, n, exp in want
    ]
    for ids, exp, h in handles:
        got = h.result()
        assert not h.aborted
        assert got == exp, ids


@pytest.fixture(scope="module")
def ngram_batcher():
    # Prompt-lookup draft: proposal quality varies wildly with the
    # traffic (repetitive streams accept, fresh ones don't) — every
    # stream must STILL be oracle-exact.
    b = ContinuousBatcher(
        _MODEL, _PARAMS, slots=3, draft="ngram", spec_k=2,
    ).start()
    b.precache_prefix([7, 3, 11])
    yield b
    b.stop()


@settings(max_examples=15, deadline=None,
          suppress_health_check=[HealthCheck.function_scoped_fixture])
@given(reqs=st.lists(spec_req_strategy, min_size=2, max_size=6))
def test_ngram_random_mixes_stay_greedy_exact(ngram_batcher, reqs):
    """Prompt-lookup speculative rounds under random interleavings:
    bit-exact greedy regardless of what the history lookup proposes."""
    want = []
    for r in reqs:  # oracles first — see test_random_mixes_match_oracles
        ids = ([7, 3, 11] + r["extra"]) if r["prefix_hit"] else r["extra"]
        want.append((ids, r["max_new"], _oracle(ids, r["max_new"], None)))
    handles = [
        (ids, exp, ngram_batcher.submit(ids, max_new_tokens=n))
        for ids, n, exp in want
    ]
    for ids, exp, h in handles:
        got = h.result()
        assert not h.aborted
        assert got == exp, ids


@settings(max_examples=8, deadline=None)
@given(n_reqs=st.integers(1, 4), stop_after=st.integers(0, 3))
def test_stop_race_never_hangs(n_reqs, stop_after):
    """Submits racing stop(): every handle either completes with its
    oracle stream or is marked aborted — never a hang, never a silently
    wrong stream."""
    b = ContinuousBatcher(_MODEL, _PARAMS, slots=2).start()
    handles = []
    stopper = threading.Timer(stop_after * 0.02, b.stop)
    stopper.start()
    try:
        for i in range(n_reqs):
            try:
                handles.append(
                    (i, b.submit([5 + i, 9], max_new_tokens=4))
                )
            except RuntimeError:
                break  # stopped before this submit: acceptable
        for i, h in handles:
            got = h.result()  # must return promptly either way
            if not h.aborted:
                assert got == _oracle([5 + i, 9], 4, None)
    finally:
        stopper.join()
        b.stop()
