"""Platform REST API (VERDICT r2 #7): POST /api/v1/assets/import parity
with the reference's GoHai-api (GPU调度平台搭建.md:701-744) — direct
upload, HuggingFace/S3 pull-through (injectable fetcher), the <2 GB
limit, listing, schema export, and Bearer auth."""

import json
import urllib.error
import urllib.request

import pytest

from k8s_gpu_tpu.platform import AssetStore, PlatformApiServer


@pytest.fixture()
def server(tmp_path):
    fetched = []

    def fake_fetch(url: str) -> bytes:
        fetched.append(url)
        return f"FAKE-BYTES-FROM:{url}".encode()

    srv = PlatformApiServer(
        AssetStore(tmp_path / "assets"), url_fetch=fake_fetch,
        max_upload=1024,
    ).start()
    srv.fetched = fetched
    yield srv
    srv.stop()


def _req(srv, method, path, body=None, ctype="application/json",
         headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}{path}",
        data=body,
        headers={"Content-Type": ctype, **(headers or {})},
        method=method,
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_direct_upload_and_versioning(server):
    code, a = _req(server, "POST",
                   "/api/v1/assets/import?space=ml&kind=model&id=m1",
                   body=b"weights-v1", ctype="application/octet-stream")
    assert code == 200 and a["version"] == "v1" and a["size"] == 10
    code, a = _req(server, "POST",
                   "/api/v1/assets/import?space=ml&kind=model&id=m1",
                   body=b"weights-v2!", ctype="application/octet-stream")
    assert code == 200 and a["version"] == "v2"
    code, listing = _req(server, "GET", "/api/v1/assets?space=ml")
    assert listing["assets"] == [
        {"kind": "model", "id": "m1", "versions": ["v1", "v2"]}
    ]
    code, meta = _req(server, "GET", "/api/v1/assets/ml/model/m1")
    assert code == 200 and meta["version"] == "v2"


def test_huggingface_and_s3_import_build_exact_urls(server):
    code, a = _req(server, "POST", "/api/v1/assets/import", body=json.dumps({
        "space": "ml", "kind": "model", "id": "bert",
        "source": {"type": "huggingface", "repo": "org/bert",
                   "file": "model.safetensors"},
    }).encode())
    assert code == 200
    assert a["source_url"] == (
        "https://huggingface.co/org/bert/resolve/main/model.safetensors"
    )
    code, a = _req(server, "POST", "/api/v1/assets/import", body=json.dumps({
        "space": "ml", "kind": "dataset", "id": "d1",
        "source": {"type": "s3", "bucket": "bkt", "key": "data/train.bin"},
    }).encode())
    assert code == 200
    assert a["source_url"] == "https://s3.amazonaws.com/bkt/data/train.bin"
    assert server.fetched == [
        "https://huggingface.co/org/bert/resolve/main/model.safetensors",
        "https://s3.amazonaws.com/bkt/data/train.bin",
    ]
    # The fetched bytes actually landed as the asset payload.
    code, meta = _req(server, "GET", "/api/v1/assets/ml/model/bert")
    with open(meta["path"], "rb") as f:
        assert f.read().startswith(b"FAKE-BYTES-FROM:https://huggingface.co")


def test_upload_size_limit_is_413(server):
    code, out = _req(server, "POST",
                     "/api/v1/assets/import?space=ml&kind=model&id=big",
                     body=b"x" * 2048, ctype="application/octet-stream")
    assert code == 413 and "limit" in out["error"]


def test_bad_requests_are_400(server):
    code, out = _req(server, "POST", "/api/v1/assets/import",
                     body=b"not json")
    assert code == 400
    code, out = _req(server, "POST", "/api/v1/assets/import",
                     body=json.dumps({"space": "ml"}).encode())
    assert code == 400 and "required" in out["error"]
    code, out = _req(server, "POST", "/api/v1/assets/import", body=json.dumps({
        "space": "ml", "kind": "model", "id": "x",
        "source": {"type": "ftp"},
    }).encode())
    assert code == 400 and "unknown source type" in out["error"]
    code, out = _req(server, "POST",
                     "/api/v1/assets/import?space=ml&kind=model",
                     body=b"zz", ctype="application/octet-stream")
    assert code == 400 and "id" in out["error"]


def test_schema_endpoints(server):
    code, schemas = _req(server, "GET", "/api/v1/schemas")
    assert code == 200 and "TpuPodSlice" in schemas
    code, s = _req(server, "GET", "/api/v1/schemas/TpuPodSlice")
    assert code == 200
    assert s["properties"]["spec"]["properties"]["acceleratorType"] == {
        "type": "string"
    }
    code, _ = _req(server, "GET", "/api/v1/schemas/NopeKind")
    assert code == 404


def test_bearer_auth_when_verifier_set(tmp_path):
    def verify(tok):
        if tok != "good":
            raise ValueError("bad token")

    srv = PlatformApiServer(
        AssetStore(tmp_path / "a2"), verify_token=verify
    ).start()
    try:
        code, out = _req(srv, "GET", "/api/v1/assets?space=ml")
        assert code == 401
        code, out = _req(srv, "GET", "/api/v1/assets?space=ml",
                         headers={"Authorization": "Bearer nope"})
        assert code == 401
        code, out = _req(srv, "GET", "/api/v1/assets?space=ml",
                         headers={"Authorization": "Bearer good"})
        assert code == 200
        # /healthz stays open for probes.
        code, out = _req(srv, "GET", "/healthz")
        assert code == 200
    finally:
        srv.stop()


def test_request_metrics_recorded(server):
    """C32: every request lands in the shared metrics registry with
    route/method/code labels + a latency histogram.  Counters land in a
    finally AFTER the response is written, so poll briefly."""
    import time

    from k8s_gpu_tpu.utils.metrics import global_metrics

    _req(server, "GET", "/api/v1/schemas")
    _req(server, "POST", "/api/v1/assets/import", body=b"not json")
    _req(server, "GET", "/totally/unknown/deep/path")
    deadline = time.time() + 5
    while time.time() < deadline:
        rendered = global_metrics.render()
        if (
            'route="/api/v1/schemas"' in rendered
            and 'route="/api/v1/assets/import"' in rendered
            and 'code="400"' in rendered
            and 'route="other"' in rendered  # unknown paths collapse
        ):
            break
        time.sleep(0.02)
    assert 'http_requests_total{' in rendered
    assert 'route="/api/v1/schemas"' in rendered
    assert 'code="400"' in rendered
    assert 'route="other"' in rendered
    assert "/totally" not in rendered, "raw paths must not become labels"
    assert "http_request_seconds" in rendered


# -- web console (the GoHai-ui analogue, GPU调度平台搭建.md:889) ----------

@pytest.fixture()
def console(tmp_path):
    from k8s_gpu_tpu.api.tpupodslice import TpuPodSlice
    from k8s_gpu_tpu.api.types import ObjectMeta
    from k8s_gpu_tpu.controller.kubefake import FakeKube

    kube = FakeKube()
    ps = TpuPodSlice(metadata=ObjectMeta(name="pool-a", namespace="ml"))
    ps.spec.accelerator_type = "v5p-8"
    kube.create(ps)
    got = kube.get("TpuPodSlice", "pool-a", "ml")
    got.status.phase = "Ready"
    got.status.ready_replicas = 1
    kube.update_status(got)
    srv = PlatformApiServer(AssetStore(tmp_path / "a"), kube=kube).start()
    yield srv
    srv.stop()


def test_console_dashboard_page(console):
    req = urllib.request.Request(f"http://127.0.0.1:{console.port}/")
    with urllib.request.urlopen(req) as r:
        assert r.status == 200
        assert "text/html" in r.headers["Content-Type"]
        page = r.read().decode()
    assert "TPU Platform Console" in page
    assert "/api/v1/ui/overview" in page  # the page drives the JSON API


def test_console_overview_digest(console):
    code, data = _req(console, "GET", "/api/v1/ui/overview")
    assert code == 200
    by_kind = {k["kind"]: k for k in data["kinds"]}
    sec = by_kind["TpuPodSlice"]
    assert sec["count"] == 1
    obj = sec["objects"][0]
    assert obj["name"] == "pool-a" and obj["namespace"] == "ml"
    assert obj["summary"]["phase"] == "Ready"
    assert obj["summary"]["readyReplicas"] == 1


def test_console_object_browser(console):
    code, data = _req(console, "GET", "/api/v1/objects?kind=TpuPodSlice")
    assert code == 200 and len(data["items"]) == 1
    man = data["items"][0]
    assert man["spec"]["acceleratorType"] == "v5p-8"
    code, err = _req(console, "GET", "/api/v1/objects?kind=Bogus")
    assert code == 400


def test_console_absent_without_kube(server):
    req = urllib.request.Request(f"http://127.0.0.1:{server.port}/")
    try:
        with urllib.request.urlopen(req) as r:
            assert False, "should 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
    code, err = _req(server, "GET", "/api/v1/ui/overview")
    assert code == 404


def test_console_page_public_but_data_authed(tmp_path):
    """With auth on, the static page still serves (it holds no data and
    carries a token box), while the overview JSON requires a Bearer."""
    from k8s_gpu_tpu.controller.kubefake import FakeKube

    def verify(token):
        if token != "good":
            raise ValueError("bad token")

    srv = PlatformApiServer(
        AssetStore(tmp_path / "a"), kube=FakeKube(), verify_token=verify,
    ).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/ui"
        ) as r:
            page = r.read().decode()
        assert "Authorization" in page  # the page can attach a token
        code, _ = _req(srv, "GET", "/api/v1/ui/overview")
        assert code == 401
        code, data = _req(srv, "GET", "/api/v1/ui/overview",
                          headers={"Authorization": "Bearer good"})
        assert code == 200 and "kinds" in data
    finally:
        srv.stop()
