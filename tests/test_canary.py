"""Black-box canary probing, the replica health FSM, and the SLO
error-budget plane (ISSUE 14).  Scripted-target tests run under
FakeClock (two-run byte-identity); the integration tests drive real
tiny batchers.  Named test_canary so it sorts early inside the tier-1
870 s window."""

import json
import urllib.request

import pytest

from k8s_gpu_tpu.serve.canary import (
    DEGRADED,
    HEALTHY,
    UNHEALTHY,
    CanaryProber,
)
from k8s_gpu_tpu.serve.journal import PROBE_TENANT
from k8s_gpu_tpu.utils.alerts import (
    RuleEvaluator,
    SloObjective,
    default_rule_pack,
    slo_rule_pack,
)
from k8s_gpu_tpu.utils.clock import FakeClock
from k8s_gpu_tpu.utils.metrics import MetricsRegistry

TINY_KW = dict(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8,
    d_ff=64, max_seq=48, use_flash=False,
)


@pytest.fixture(scope="module")
def tiny_lm():
    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(dtype=jnp.float32, **TINY_KW)
    model = TransformerLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


# -- scripted probe targets ----------------------------------------------------

class _Handle:
    """The prober-visible slice of a RequestHandle."""

    def __init__(self, toks, expired=False, aborted=False):
        self._toks = list(toks)
        self.deadline_expired = expired
        self.aborted = aborted

    def __iter__(self):
        return iter(self._toks)


class ScriptedReplica:
    """A submit-shaped callable replaying a scripted outcome list.

    Script entries: ("ok", tokens) | ("error",) | ("deadline",) |
    ("aborted",) | ("slow", ttft_s, tokens).  The last entry repeats
    once the script is exhausted.
    """

    def __init__(self, script, clock=None):
        self.script = list(script)
        self.clock = clock
        self.i = 0
        self.calls = []

    def __call__(self, ids, **kw):
        self.calls.append(kw)
        step = self.script[min(self.i, len(self.script) - 1)]
        self.i += 1
        kind = step[0]
        if kind == "error":
            raise RuntimeError("injected")
        if kind == "deadline":
            return _Handle([], expired=True)
        if kind == "aborted":
            return _Handle([1], aborted=True)
        if kind == "slow":
            # Advance fake time so the prober measures a big TTFT but
            # stays inside the deadline.
            self.clock.advance(step[1])
            return _Handle(step[2])
        return _Handle(step[1])


GOOD = [7, 11, 13, 17]


def _prober(targets, clock, reg, **kw):
    kw.setdefault("interval", 10.0)
    kw.setdefault("deadline_s", 5.0)
    kw.setdefault("window_n", 4)
    kw.setdefault("fail_k", 2)
    kw.setdefault("recover_k", 2)
    return CanaryProber(targets, clock=clock, metrics=reg, **kw)


# -- the FSM -------------------------------------------------------------------

def test_fsm_walks_degraded_unhealthy_and_recovers():
    """healthy -> degraded on the first hard failure, -> unhealthy at
    fail_k-of-window_n, -> healthy after recover_k consecutive ok; the
    state gauge tracks 1.0 / 0.5 / 0.0 and failures count by reason."""
    clock = FakeClock()
    reg = MetricsRegistry()
    rep = ScriptedReplica(
        [("ok", GOOD), ("error",), ("deadline",),
         ("ok", GOOD), ("ok", GOOD)]
    )
    p = _prober({"r0": rep}, clock, reg)

    def state():
        return p.snapshot()["replicas"]["r0"]["state"]

    assert state() == HEALTHY
    assert reg.gauge("probe_replica_healthy", replica="r0") == 1.0
    p.probe_once()
    assert state() == HEALTHY
    p.probe_once()                      # error
    assert state() == DEGRADED
    assert reg.gauge("probe_replica_healthy", replica="r0") == 0.5
    p.probe_once()                      # deadline -> 2 fails in window
    assert state() == UNHEALTHY
    assert reg.gauge("probe_replica_healthy", replica="r0") == 0.0
    p.probe_once()                      # ok streak 1
    assert state() == UNHEALTHY
    p.probe_once()                      # ok streak 2 = recover_k
    assert state() == HEALTHY
    assert reg.gauge("probe_replica_healthy", replica="r0") == 1.0
    assert reg.counter("probe_failures_total", replica="r0",
                       reason="error") == 1.0
    assert reg.counter("probe_failures_total", replica="r0",
                       reason="deadline") == 1.0
    assert reg.counter("probe_requests_total", replica="r0") == 5.0
    # The transition history carries the whole walk.
    trans = p.snapshot()["replicas"]["r0"]["transitions"]
    assert [(t["from"], t["to"]) for t in trans] == [
        (HEALTHY, DEGRADED), (DEGRADED, UNHEALTHY), (UNHEALTHY, HEALTHY),
    ]


def test_golden_drift_is_corrupt():
    """The golden hash records on first healthy contact; a replica
    answering DIFFERENT tokens later is corrupt — a hard failure."""
    clock = FakeClock()
    reg = MetricsRegistry()
    good = ScriptedReplica([("ok", GOOD)])
    bad = ScriptedReplica([("ok", GOOD), ("ok", [9, 9, 9, 9])])
    # Sorted probe order: a-good probes first and pins the golden.
    p = _prober({"a-good": good, "b-drift": bad}, clock, reg)
    p.probe_once()
    assert p.snapshot()["golden"] != ""
    p.probe_once()
    snap = p.snapshot()["replicas"]
    assert snap["a-good"]["state"] == HEALTHY
    assert snap["b-drift"]["state"] == DEGRADED
    assert snap["b-drift"]["last"]["reason"] == "corrupt"
    assert reg.counter("probe_failures_total", replica="b-drift",
                       reason="corrupt") == 1.0


def test_slow_is_budget_event_not_fsm_failure():
    """A correct-but-slow probe mints reason="slow" (the latency SLO's
    bad event) but does NOT walk the FSM — quarantining slow replicas
    would shed capacity exactly when the fleet is saturated."""
    clock = FakeClock()
    reg = MetricsRegistry()
    rep = ScriptedReplica([("slow", 1.5, GOOD)] * 3, clock=clock)
    p = _prober({"r0": rep}, clock, reg, ttft_slo_s=0.5)
    for _ in range(3):
        p.probe_once()
    snap = p.snapshot()["replicas"]["r0"]
    assert snap["state"] == HEALTHY
    assert snap["window"] == [1, 1, 1]
    assert reg.counter("probe_failures_total", replica="r0",
                       reason="slow") == 3.0
    # The measured outside-in TTFT landed in the probe histogram.
    assert reg.histogram("probe_ttft_seconds", replica="r0").n == 3
    assert reg.percentile("probe_ttft_seconds", 0.5,
                          replica="r0") == pytest.approx(1.5)


def test_two_run_snapshots_byte_identical():
    """The acceptance bar: two scripted FakeClock runs produce
    byte-identical /debug/probes bodies."""

    def run():
        clock = FakeClock()
        reg = MetricsRegistry()
        p = _prober(
            {
                "r0": ScriptedReplica([("ok", GOOD)]),
                "r1": ScriptedReplica(
                    [("ok", GOOD), ("error",), ("deadline",),
                     ("ok", GOOD), ("ok", GOOD)]
                ),
            },
            clock, reg,
        )
        for _ in range(6):
            p.probe_once()
            clock.advance(10.0)
        return json.dumps(p.snapshot(), sort_keys=True)

    assert run() == run()


def test_router_quarantine_and_readmission():
    """An unhealthy verdict quarantines the replica in the router (no
    NEW traffic — same eligibility effect as a drain); recovery
    re-admits it."""
    from k8s_gpu_tpu.serve.router import FleetRouter

    clock = FakeClock()
    reg = MetricsRegistry()
    router = FleetRouter(page_size=4, metrics=reg)
    for n in ("r0", "r1"):
        router.add_replica(n)
    rep = ScriptedReplica(
        [("error",), ("error",), ("ok", GOOD), ("ok", GOOD)]
    )
    p = _prober({"r1": rep}, clock, reg, router=router)
    p.probe_once()
    p.probe_once()                      # 2 hard failures -> unhealthy
    assert p.snapshot()["replicas"]["r1"]["state"] == UNHEALTHY
    assert reg.counter("serve_router_quarantines_total") == 1.0
    assert reg.gauge("serve_router_replicas_unhealthy") == 1.0
    row = [r for r in router.snapshot()["replicas"]
           if r["replica"] == "r1"][0]
    assert row["unhealthy"] is True
    # Zero NEW requests route to the quarantined replica.
    assert all(
        router.route([i, i + 1, i + 2]).replica == "r0"
        for i in range(1, 20)
    )
    p.probe_once()
    p.probe_once()                      # recover_k streak -> healthy
    assert p.snapshot()["replicas"]["r1"]["state"] == HEALTHY
    assert reg.gauge("serve_router_replicas_unhealthy") == 0.0
    # Full-page prompts rendezvous-hash across the fleet again.
    assert any(
        router.route([i, i + 1, i + 2, i + 3, i + 4]).replica == "r1"
        for i in range(1, 40)
    )


# -- the SLO error-budget plane ------------------------------------------------

def test_slo_budget_math_and_multiwindow_burn():
    """slo_budget_remaining_ratio is the cumulative clamp of
    1 - (bad/total)/(1-target); the burn rates are windowed; and
    SloBudgetBurn pages only while BOTH windows breach, resolving once
    the bad events age out of the fast window."""
    reg = MetricsRegistry()
    clock = FakeClock()
    obj = SloObjective(
        "probe-availability", 0.999,
        total="probe_requests_total", bad="probe_failures_total",
        bad_where={"reason": lambda r: r != "slow"},
    )
    ev = RuleEvaluator(
        slo_rule_pack([obj], fast_window=300.0, slow_window=900.0),
        clock=clock, registry=reg, interval=10.0,
    )
    # Tick 0: seeds the rate watches; a 1-in-2000 failure history gives
    # remaining = 1 - (1/2000)/0.001 = 0.5 (cumulative, not windowed).
    reg.inc("probe_requests_total", 2000.0, replica="r0")
    reg.inc("probe_failures_total", 1.0, replica="r0", reason="error")
    ev.evaluate_once()
    assert reg.gauge("slo_budget_remaining_ratio",
                     slo="probe-availability") == pytest.approx(0.5)
    assert reg.gauge("slo_burn_rate_fast",
                     slo="probe-availability") == 0.0
    # A burst of failures: 5 of 10 probes bad over 10 s -> burn 500x in
    # both windows -> SloBudgetBurn walks pending -> firing after for_s.
    for _ in range(8):
        clock.advance(10.0)
        reg.inc("probe_requests_total", 10.0, replica="r0")
        reg.inc("probe_failures_total", 5.0, replica="r0",
                reason="deadline")
        ev.evaluate_once()
    assert reg.gauge("slo_burn_rate_fast",
                     slo="probe-availability") > 14.4
    assert reg.gauge("slo_burn_rate_slow",
                     slo="probe-availability") > 14.4
    assert reg.gauge("alerts_firing", alertname="SloBudgetBurn") == 1.0
    # Budget spent stays visible (cumulative): far below the pre-burst
    # remaining ratio.
    assert reg.gauge("slo_budget_remaining_ratio",
                     slo="probe-availability") == 0.0
    # Healthy traffic + the fast window scrolling past the burst ->
    # fast burn decays -> min(fast, slow) clears -> resolved.
    for _ in range(8):
        clock.advance(50.0)
        reg.inc("probe_requests_total", 10.0, replica="r0")
        ev.evaluate_once()
    assert reg.gauge("slo_burn_rate_fast",
                     slo="probe-availability") < 14.4
    assert reg.gauge("alerts_firing", alertname="SloBudgetBurn") == 0.0
    assert any(
        t["alert"] == "SloBudgetBurn" and t["to"] == "resolved"
        for t in ev.timeline
    )


def test_default_pack_canary_rules_and_reserved_tenant_exclusion():
    """CanaryFailing warns at degraded (< 0.75), ReplicaUnhealthy pages
    at unhealthy (< 0.25) with zero hold, and the tenant burn-rate rule
    skips the reserved "_" tenants wholesale."""
    reg = MetricsRegistry()
    clock = FakeClock()
    ev = RuleEvaluator(
        default_rule_pack(), clock=clock, registry=reg, interval=10.0,
    )
    reg.set_gauge("probe_replica_healthy", 1.0, replica="r0")
    reg.inc("serve_tenant_tokens_total", 100.0, tenant="acme")
    reg.inc("serve_tenant_goodput_tokens_total", 100.0, tenant="acme")
    reg.inc("serve_tenant_tokens_total", 50.0, tenant=PROBE_TENANT)
    ev.evaluate_once()
    # The reserved tenant minted NO burn-rate series.
    burns = {
        dict(lbls).get("tenant")
        for lbls in reg.series("tenant_slo_burn_rate")
    }
    assert burns == {"acme"}
    # Degraded -> CanaryFailing pending, fires after its 30 s hold;
    # ReplicaUnhealthy stays quiet above 0.25.
    reg.set_gauge("probe_replica_healthy", 0.5, replica="r0")
    clock.advance(10.0)
    ev.evaluate_once()
    clock.advance(30.0)
    ev.evaluate_once()
    assert reg.gauge("alerts_firing", alertname="CanaryFailing") == 1.0
    assert reg.gauge("alerts_firing", alertname="ReplicaUnhealthy") == 0.0
    # Unhealthy -> ReplicaUnhealthy pages in ONE tick (for_s=0: the
    # K-of-N probe window is the hold).
    reg.set_gauge("probe_replica_healthy", 0.0, replica="r0")
    clock.advance(10.0)
    ev.evaluate_once()
    assert reg.gauge("alerts_firing", alertname="ReplicaUnhealthy") == 1.0
    # Recovery resolves both.
    reg.set_gauge("probe_replica_healthy", 1.0, replica="r0")
    clock.advance(10.0)
    ev.evaluate_once()
    assert reg.gauge("alerts_firing", alertname="CanaryFailing") == 0.0
    assert reg.gauge("alerts_firing", alertname="ReplicaUnhealthy") == 0.0


def test_fleet_aggregation_policy_for_probe_and_slo_gauges():
    """Federation stores min for probe_replica_healthy and
    slo_budget_remaining_ratio (the fleet is its sickest member /
    tightest budget) and max for the burn rates."""
    from k8s_gpu_tpu.utils.federation import FleetCollector

    # Two probers watching the SAME replica disagree: the fleet view
    # must keep the pessimistic verdict.
    regs = {"p0": MetricsRegistry(), "p1": MetricsRegistry()}
    regs["p0"].set_gauge("probe_replica_healthy", 1.0, replica="shared")
    regs["p0"].set_gauge("slo_budget_remaining_ratio", 0.9, slo="avail")
    regs["p0"].set_gauge("slo_burn_rate_fast", 0.1, slo="avail")
    regs["p1"].set_gauge("probe_replica_healthy", 0.0, replica="shared")
    regs["p1"].set_gauge("slo_budget_remaining_ratio", 0.4, slo="avail")
    regs["p1"].set_gauge("slo_burn_rate_fast", 20.0, slo="avail")
    fc = FleetCollector(
        {n: (lambda r=r: r.render()) for n, r in regs.items()},
        clock=FakeClock(),
    )
    fc.scrape_once()
    agg = fc.registry
    assert agg.gauge("probe_replica_healthy", replica="shared") == 0.0
    assert agg.gauge("slo_budget_remaining_ratio", slo="avail") == 0.4
    assert agg.gauge("slo_burn_rate_fast", slo="avail") == 20.0  # max


# -- surfaces ------------------------------------------------------------------

def test_debug_probes_endpoint_and_renderers():
    """/debug/probes serves the sort_keys snapshot; render_probes and
    render_slo draw the tables."""
    from k8s_gpu_tpu.utils.obs import (
        MetricsServer,
        render_probes,
        render_slo,
    )

    clock = FakeClock()
    reg = MetricsRegistry()
    p = _prober(
        {"r0": ScriptedReplica([("ok", GOOD), ("error",)])}, clock, reg
    )
    p.probe_once()
    p.probe_once()
    srv = MetricsServer(registry=reg, probes=p).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/probes"
        ) as r:
            body = r.read()
        assert body == json.dumps(p.snapshot(), sort_keys=True).encode()
        out = render_probes(json.loads(body))
        assert "r0" in out and "degraded" in out and "error=1" in out
    finally:
        srv.stop()
    reg.set_gauge("slo_budget_remaining_ratio", 0.25, slo="avail")
    reg.set_gauge("slo_burn_rate_fast", 2.0, slo="avail")
    from k8s_gpu_tpu.utils.metrics import parse_exposition

    out = render_slo(parse_exposition(reg.render()))
    assert "avail" in out and "25.00%" in out and "2.00x" in out
    # A prober-less server 404s the route.
    srv = MetricsServer(registry=reg).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/probes"
            )
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_evaluator_attach_paces_probes_by_interval():
    """attach() probes as an evaluator collector, gated by the probe
    interval — a fast alert cadence doesn't turn into probe spam."""
    clock = FakeClock()
    reg = MetricsRegistry()
    rep = ScriptedReplica([("ok", GOOD)])
    p = _prober({"r0": rep}, clock, reg, interval=30.0)
    ev = RuleEvaluator([], clock=clock, registry=reg, interval=10.0)
    p.attach(ev)
    ev.evaluate_once()                  # first tick probes
    assert len(rep.calls) == 1
    clock.advance(10.0)
    ev.evaluate_once()                  # 10 s < interval: no probe
    assert len(rep.calls) == 1
    clock.advance(25.0)
    ev.evaluate_once()
    assert len(rep.calls) == 2


# -- the serve-plane integration ----------------------------------------------

def test_batcher_self_pollution_guard(tiny_lm):
    """Canary traffic must not move user-facing SLO series: no tenant
    token counters, no latency histogram observations — but the journal
    records it (probe=true) and snapshot(probes=False) filters it."""
    from k8s_gpu_tpu.serve import ContinuousBatcher

    model, params = tiny_lm
    reg = MetricsRegistry()
    b = ContinuousBatcher(model, params, slots=2, metrics=reg).start()
    try:
        h = b.submit([1, 2, 3], max_new_tokens=4, tenant="acme")
        assert len(h.result()) == 4
        p = _prober({"r0": b.submit}, FakeClock(), reg, deadline_s=60.0)
        # RealClock prober would also work; FakeClock keeps the probe
        # deadline far in the batcher's past, so disable it instead.
        p.deadline_s = float("inf")
        assert p.probe_once() == {"r0": "ok"}
        # Tenant accounting: acme only — no _canary series anywhere.
        tenants = {
            dict(lbls).get("tenant")
            for lbls in reg.series("serve_tenant_tokens_total")
        }
        assert tenants == {"acme"}
        assert reg.histogram("serve_ttft_seconds").n == 1
        assert reg.histogram("serve_ttft_seconds",
                             tenant=PROBE_TENANT) is None
        # The probe DID count as real work.
        assert reg.counter("probe_requests_total", replica="r0") == 1.0
        recs = b.journal.snapshot()
        probe_recs = [r for r in recs if r.get("extra", {}).get("probe")]
        assert len(probe_recs) == 1
        assert probe_recs[0]["tenant"] == PROBE_TENANT
        # The --no-probes filter drops exactly the probe record.
        assert len(b.journal.snapshot(probes=False)) == len(recs) - 1
    finally:
        b.stop()


def test_lm_server_health_contract(tiny_lm):
    """/healthz is pure liveness (always 200); /readyz gates on
    scheduler-alive AND warmed AND not draining, with the failing leg
    named in the body; drain()/undrain() flip it."""
    from k8s_gpu_tpu.data import BpeTokenizer
    from k8s_gpu_tpu.serve import LmServer

    model, params = tiny_lm
    tok = BpeTokenizer.train("aa bb cc dd " * 30, vocab_size=80)
    srv = LmServer(model, params, tok, metrics=MetricsRegistry())
    srv._thread.start()                 # HTTP only; batcher not started

    def get(path):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}{path}"
            ) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        assert get("/healthz")[0] == 200
        code, body = get("/readyz")
        assert code == 503 and body["scheduler_alive"] is False
        srv.batcher.start()
        code, body = get("/readyz")
        assert code == 503 and body["warmed"] is False
        # First emitted token warms the readiness latch.
        h = srv.batcher.submit([1, 2, 3], max_new_tokens=2)
        assert len(h.result()) == 2
        code, body = get("/readyz")
        assert code == 200 and body["ready"] is True
        # Drain: NotReady without stopping work; liveness unaffected.
        srv.drain()
        code, body = get("/readyz")
        assert code == 503 and body["draining"] is True
        assert get("/healthz")[0] == 200
        srv.undrain()
        assert get("/readyz")[0] == 200
    finally:
        srv.stop()


def test_router_drain_hook_flips_replica_readiness():
    """FleetRouter.drain() announces scale-down through the replica's
    on_drain hook — the LmServer.drain seam, tested with a recorder."""
    from k8s_gpu_tpu.serve.router import FleetRouter

    drained = []
    r = FleetRouter(page_size=4, metrics=MetricsRegistry())
    r.add_replica("r0", on_drain=lambda: drained.append("r0"))
    r.add_replica("r1")
    r.drain("r0")
    assert drained == ["r0"]
    r.drain("r1")                       # hook-less drain still works
    assert drained == ["r0"]


def test_chaos_canary_acceptance(tiny_lm):
    """The acceptance drill: a 3-replica fleet of real batchers, seeded
    serve.submit faults plus one corrupted-output replica.  The FSM
    walks the corrupt replica to unhealthy, ReplicaUnhealthy fires, the
    router sends it zero NEW requests; the fault lifts, probes recover,
    the replica re-admits, the alert resolves — and the spent error
    budget stays visible."""
    from k8s_gpu_tpu.serve import ContinuousBatcher
    from k8s_gpu_tpu.serve.router import FleetRouter
    from k8s_gpu_tpu.utils.faults import FaultPlan, global_faults

    model, params = tiny_lm
    reg = MetricsRegistry()
    reps = {
        n: ContinuousBatcher(
            model, params, slots=2, metrics=MetricsRegistry()
        ).start()
        for n in ("r0", "r1", "r2")
    }

    class CorruptingTarget:
        """Wraps a replica's submit: while armed, every emitted token
        is rewritten — the answers-garbage failure mode self-reported
        health can never see."""

        def __init__(self, submit):
            self.submit = submit
            self.armed = True

        def __call__(self, ids, **kw):
            h = self.submit(ids, **kw)
            if not self.armed:
                return h
            toks = [(int(t) + 1) % 64 for t in h]
            return _Handle(
                toks,
                expired=bool(getattr(h, "deadline_expired", False)),
                aborted=bool(getattr(h, "aborted", False)),
            )

    corrupt = CorruptingTarget(reps["r1"].submit)
    router = FleetRouter(page_size=4, metrics=reg)
    for n, b in reps.items():
        router.add_replica(n, b.submit)
    # Probes run on the real clock (the batcher's deadline domain);
    # alert evaluation runs on its own FakeClock over the same registry.
    prober = CanaryProber(
        {"r0": reps["r0"].submit, "r1": corrupt, "r2": reps["r2"].submit},
        metrics=reg, router=router, deadline_s=60.0,
        window_n=4, fail_k=2, recover_k=2, max_new_tokens=4,
    )
    clock = FakeClock()
    ev = RuleEvaluator(
        default_rule_pack(), clock=clock, registry=reg, interval=10.0,
    )

    def tick():
        clock.advance(10.0)
        ev.evaluate_once()

    try:
        # Round 1 under seeded faults: the first two probe submits (r0,
        # r1 in sorted order) die injected; r2's clean probe pins the
        # golden.
        global_faults.arm(
            "serve.submit", FaultPlan(flaky=2, kinds=("error",))
        )
        try:
            out = prober.probe_once()
        finally:
            global_faults.disarm("serve.submit")
        assert out == {"r0": "error", "r1": "error", "r2": "ok"}
        assert prober.snapshot()["golden"] != ""
        ev.evaluate_once()
        # Round 2: faults healed; r1 now answers corrupted tokens —
        # second hard failure in its window walks it to unhealthy.
        out = prober.probe_once()
        assert out == {"r0": "ok", "r1": "corrupt", "r2": "ok"}
        states = {
            n: d["state"]
            for n, d in prober.snapshot()["replicas"].items()
        }
        assert states["r1"] == UNHEALTHY
        assert states["r2"] == HEALTHY
        tick()
        assert reg.gauge("alerts_firing",
                         alertname="ReplicaUnhealthy") == 1.0
        # Zero NEW requests reach the quarantined replica (full-page
        # prompts so placement rendezvous-hashes across the fleet).
        decisions = [
            router.route([i, i + 1, i + 2, i + 3, i + 4])
            for i in range(1, 33)
        ]
        assert all(d.replica != "r1" for d in decisions)
        assert {d.replica for d in decisions} == {"r0", "r2"}
        # Budget spend is visible and cumulative: 3 hard failures in 6
        # probes burned the 99.9% availability budget flat.
        assert reg.gauge("slo_budget_remaining_ratio",
                         slo="probe-availability") == 0.0
        # Fault lifted: recover_k clean probes re-admit and resolve.
        corrupt.armed = False
        for _ in range(3):
            prober.probe_once()
        assert (
            prober.snapshot()["replicas"]["r1"]["state"] == HEALTHY
        )
        tick()
        assert reg.gauge("alerts_firing",
                         alertname="ReplicaUnhealthy") == 0.0
        assert any(
            t["alert"] == "ReplicaUnhealthy" and t["to"] == "resolved"
            for t in ev.timeline
        )
        row = [r for r in router.snapshot()["replicas"]
               if r["replica"] == "r1"][0]
        assert row["unhealthy"] is False
        # The drill's cost stays on the books after recovery.
        assert reg.gauge("slo_budget_remaining_ratio",
                         slo="probe-availability") == 0.0
        assert reg.counter("serve_router_quarantines_total") == 1.0
    finally:
        global_faults.disarm("serve.submit")
        for b in reps.values():
            b.stop()
