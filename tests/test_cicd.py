"""Image registry (C10), Helm-role releases (C33), and CI/CD pipeline (C31):
the reference's build→push→deploy on main, build→push→train on tags
(GPU调度平台搭建.md:748-794)."""

import pytest

from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.platform import (
    AssetStore,
    Chart,
    DeploymentReconciler,
    ImageRegistry,
    ImmutableTagError,
    PipelineRunner,
    Ref,
    ReleaseError,
    ReleaseManager,
    ScanPolicyError,
    gohai_platform_chart,
)

# -- registry ---------------------------------------------------------------

def test_registry_push_pull_roundtrip():
    reg = ImageRegistry()
    m = reg.push("ml", "train", "v1", b"layer-data")
    assert m.digest.startswith("sha256:") and m.scan_status == "Passed"
    assert reg.pull("ml/train:v1") == b"layer-data"
    assert reg.pull(f"ml/train@{m.digest}") == b"layer-data"
    assert [t.tag for t in reg.list_tags("ml", "train")] == ["v1"]
    assert reg.list_repositories("ml") == ["train"]


def test_registry_scan_policy_blocks_pull():
    reg = ImageRegistry()
    m = reg.push("ml", "train", "bad", b"contains CVE-2026-0001 marker")
    assert m.scan_status == "Failed"
    with pytest.raises(ScanPolicyError):
        reg.pull("ml/train:bad")


def test_registry_immutable_tags():
    reg = ImageRegistry(immutable_tags=True)
    reg.push("ml", "train", "v1", b"a")
    reg.push("ml", "train", "v1", b"a")  # same digest: idempotent
    with pytest.raises(ImmutableTagError):
        reg.push("ml", "train", "v1", b"b")


def test_registry_blob_gc():
    reg = ImageRegistry()
    reg.push("ml", "train", "v1", b"a")
    reg.push("ml", "train", "v2", b"b")
    reg.delete_tag("ml", "train", "v1")
    assert reg.gc_blobs() == 1
    assert reg.pull("ml/train:v2") == b"b"


# -- releases ---------------------------------------------------------------

def test_release_install_upgrade_prune_and_history(kube: FakeKube):
    rm = ReleaseManager(kube)
    chart = gohai_platform_chart()
    rel = rm.install(chart, "gohai", "default", {"image": "ml/train:v1"})
    assert rel.revision == 1
    deps = kube.list("Deployment")
    assert {d.metadata.name for d in deps} == {
        "gohai-api", "gohai-controller", "gohai-devenv-controller"
    }
    assert all(d.spec.image == "ml/train:v1" for d in deps)
    assert kube.get("Deployment", "gohai-api").spec.replicas == 2
    # Role selection: one image, GOHAI_ROLE per Deployment (the operator
    # image contract — images/operator/Dockerfile + platform/entrypoint).
    assert {d.spec.env["GOHAI_ROLE"] for d in deps} == {
        "api", "controller", "devenv-controller"
    }

    rel2 = rm.upgrade(chart, "gohai", "default",
                      {"image": "ml/train:v2", "api": {"replicas": 3}})
    assert rel2.revision == 2
    api = kube.get("Deployment", "gohai-api")
    assert api.spec.image == "ml/train:v2" and api.spec.replicas == 3
    hist = rm.history("gohai")
    assert [r.revision for r in hist] == [1, 2]
    assert hist[0].status == "superseded" and hist[1].status == "deployed"


def test_release_upgrade_prunes_vanished_objects(kube: FakeKube):
    from k8s_gpu_tpu.api.core import Deployment

    def render_two(v, name, ns):
        a, b = Deployment(), Deployment()
        a.metadata.name, b.metadata.name = f"{name}-a", f"{name}-b"
        return [a, b] if v.get("both", True) else [a]

    chart = Chart("two", "0.1", {"both": True}, render_two)
    rm = ReleaseManager(kube)
    rm.install(chart, "r1")
    assert kube.try_get("Deployment", "r1-b") is not None
    rm.upgrade(chart, "r1", values={"both": False})
    assert kube.try_get("Deployment", "r1-b") is None
    assert kube.try_get("Deployment", "r1-a") is not None


def test_release_rollback_and_uninstall(kube: FakeKube):
    rm = ReleaseManager(kube)
    chart = gohai_platform_chart()
    rm.install(chart, "gohai", values={"image": "ml/train:v1"})
    rm.upgrade(chart, "gohai", values={"image": "ml/train:v2"})
    rel3 = rm.rollback(chart, "gohai")
    assert rel3.revision == 3
    assert kube.get("Deployment", "gohai-api").spec.image == "ml/train:v1"
    rm.uninstall("gohai")
    assert kube.list("Deployment") == []
    assert rm.history("gohai") == []
    with pytest.raises(ReleaseError):
        rm.uninstall("gohai")


def test_release_refuses_foreign_objects(kube: FakeKube):
    rm = ReleaseManager(kube)
    chart = gohai_platform_chart()
    rm.install(chart, "gohai")
    with pytest.raises(ReleaseError):
        rm.install(chart, "gohai")  # exists
    # A second release rendering colliding names is refused.
    other = Chart(
        "evil", "0.1", {},
        lambda v, n, ns: gohai_platform_chart().render(
            gohai_platform_chart().values, "gohai", ns
        ),
    )
    with pytest.raises(ReleaseError):
        rm.install(other, "intruder")


def test_deployment_reconciler_materializes_pods(kube: FakeKube, manager: Manager):
    manager.register("Deployment", DeploymentReconciler(kube))
    manager.start()
    rm = ReleaseManager(kube)
    rm.install(gohai_platform_chart(), "gohai")
    assert manager.wait_idle(timeout=10)
    api = kube.get("Deployment", "gohai-api")
    assert api.status.ready_replicas == 2
    pods = [p for p in kube.list("Pod")
            if p.metadata.labels.get("deployment") == "gohai-api"]
    assert len(pods) == 2 and all(p.phase == "Running" for p in pods)


# -- pipeline ---------------------------------------------------------------

@pytest.fixture
def pipeline(kube, tmp_path):
    assets = AssetStore(tmp_path / "assets")
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "train.py").write_text("print('train')\n")
    (repo / "train_job.yaml").write_text(
        "title: ci-train\nworkload: psum-smoke\n"
        "spec:\n  singleInstanceType: tpu-v4-8\n"
    )
    assets.import_path("ml", "repository", "demo", repo)
    reg = ImageRegistry()
    runner = PipelineRunner(
        kube, reg, ReleaseManager(kube), assets,
        platform_chart=gohai_platform_chart(),
    )
    return runner, reg, repo, assets


def test_pipeline_main_branch_deploys(pipeline, kube):
    runner, reg, _, _ = pipeline
    run = runner.run("ml", "demo", Ref("main"))
    assert run.status == "success"
    assert [s.status for s in run.stages] == [
        "success", "success", "success", "skipped"
    ]
    assert kube.get("Deployment", "gohai-api").spec.image == "ml/demo:main-latest"
    assert reg.resolve("ml/demo:main-latest").scan_status == "Passed"


def test_pipeline_tag_trains(pipeline, kube):
    runner, _, _, _ = pipeline
    run = runner.run("ml", "demo", Ref("v1.0", is_tag=True))
    assert run.status == "success"
    assert run.stage("deploy").status == "skipped"
    assert run.stage("train").status == "success"
    job = kube.get("TrainJob", "ci-demo-v1-0")
    assert job.spec.image == "ml/demo:v1.0"
    assert job.spec.accelerator_type == "v4-8"


def test_pipeline_feature_branch_builds_only(pipeline):
    runner, _, _, _ = pipeline
    run = runner.run("ml", "demo", Ref("feature-x"))
    assert [s.status for s in run.stages] == [
        "success", "success", "skipped", "skipped"
    ]


def test_pipeline_scan_failure_stops_before_deploy(pipeline, kube):
    runner, _, repo, assets = pipeline
    (repo / "deps.txt").write_text("libfoo CVE-2026-1234\n")
    assets.import_path("ml", "repository", "demo", repo)
    run = runner.run("ml", "demo", Ref("main"))
    assert run.status == "failed"
    assert run.stage("push").status == "failed"
    assert run.stage("deploy").status == "skipped"
    assert kube.try_get("Deployment", "gohai-api") is None


def test_pipeline_rebuild_is_deterministic(pipeline):
    runner, reg, _, _ = pipeline
    runner.run("ml", "demo", Ref("main"))
    d1 = reg.resolve("ml/demo:main-latest").digest
    runner.run("ml", "demo", Ref("main"))
    assert reg.resolve("ml/demo:main-latest").digest == d1


def test_upgrade_rolls_pods_in_same_session(kube: FakeKube, manager: Manager):
    """Spec subobject regression: the upgrade's MODIFIED event must pass the
    generation predicate so pods roll without waiting for resync."""
    manager.register("Deployment", DeploymentReconciler(kube))
    manager.start()
    rm = ReleaseManager(kube)
    chart = gohai_platform_chart()
    rm.install(chart, "gohai", values={"image": "ml/t:v1"})
    assert manager.wait_idle(timeout=10)
    rm.upgrade(chart, "gohai", values={"image": "ml/t:v2"})
    assert manager.wait_idle(timeout=10)
    pods = [p for p in kube.list("Pod")
            if p.metadata.labels.get("deployment") == "gohai-api"]
    assert pods and all(p.image == "ml/t:v2" for p in pods)


def test_pipeline_tag_rerun_upserts(pipeline, kube):
    runner, _, _, _ = pipeline
    assert runner.run("ml", "demo", Ref("v1", is_tag=True)).status == "success"
    run2 = runner.run("ml", "demo", Ref("v1", is_tag=True))
    assert run2.status == "success"
    assert "configured" in run2.stage("train").log[0]


def test_release_names_do_not_cross_contaminate(kube: FakeKube):
    """history('app') must not absorb release 'app.v2''s records."""
    rm = ReleaseManager(kube)
    chart = gohai_platform_chart()
    rm.install(chart, "app")
    rm.install(chart2 := Chart("other", "0.1", {}, lambda v, n, ns: []),
               "app.v2")
    rm.upgrade(chart2, "app.v2")
    assert [r.revision for r in rm.history("app")] == [1]
    assert [r.revision for r in rm.history("app.v2")] == [1, 2]


def test_deployment_env_propagates_and_rolls(kube: FakeKube, manager: Manager):
    from k8s_gpu_tpu.api.core import Deployment

    def render(v, name, ns):
        d = Deployment()
        d.metadata.name = f"{name}-svc"
        d.spec.image = "img:1"
        d.spec.env = dict(v.get("env", {}))
        return [d]

    chart = Chart("envd", "0.1", {"env": {"A": "1"}}, render)
    manager.register("Deployment", DeploymentReconciler(kube))
    manager.start()
    rm = ReleaseManager(kube)
    rm.install(chart, "r")
    assert manager.wait_idle(timeout=10)
    pods = [p for p in kube.list("Pod")
            if p.metadata.labels.get("deployment") == "r-svc"]
    assert pods and pods[0].env == {"A": "1"}
    rm.upgrade(chart, "r", values={"env": {"A": "2"}})
    assert manager.wait_idle(timeout=10)
    pods = [p for p in kube.list("Pod")
            if p.metadata.labels.get("deployment") == "r-svc"]
    assert pods and all(p.env == {"A": "2"} for p in pods)
