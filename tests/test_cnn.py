"""Reference-workload parity: the Fashion-MNIST-class CNN trains
(GPU调度平台搭建.md:557-636) — here on synthetic data, data-parallel."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_tpu.models import SmallCnn
from k8s_gpu_tpu.parallel import MeshConfig, build_mesh
from k8s_gpu_tpu.train import TrainConfig, Trainer
from jax.sharding import PartitionSpec as P


def synthetic_batch(key, b=16):
    ki, kl = jax.random.split(key)
    labels = jax.random.randint(kl, (b,), 0, 10)
    # Make images weakly label-dependent so the loss can actually drop.
    images = (
        jax.random.normal(ki, (b, 28, 28, 1)) * 0.1
        + labels[:, None, None, None] / 10.0
    )
    return images, labels


def test_forward_shape():
    model = SmallCnn()
    params = model.init(jax.random.PRNGKey(0))
    images, _ = synthetic_batch(jax.random.PRNGKey(1))
    logits = model.forward(params, images)
    assert logits.shape == (16, 10)


def test_training_loss_decreases_dp():
    model = SmallCnn()
    mesh = build_mesh(MeshConfig(dp=8))
    trainer = Trainer(
        model, mesh=mesh, batch_specs=(P("dp"), P("dp")),
        train_config=TrainConfig(learning_rate=1e-3, warmup_steps=1),
    )
    trainer.init(jax.random.PRNGKey(0))
    images, labels = synthetic_batch(jax.random.PRNGKey(1))
    losses = [trainer.step(images, labels) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_default_batch_specs_handle_mixed_ranks():
    """Regression (code review): Trainer's default batch sharding must cope
    with rank-1 labels and rank-4 images without explicit batch_specs."""
    model = SmallCnn()
    mesh = build_mesh(MeshConfig(dp=8))
    trainer = Trainer(
        model, mesh=mesh,
        train_config=TrainConfig(learning_rate=1e-3, warmup_steps=1),
    )
    trainer.init(jax.random.PRNGKey(0))
    images, labels = synthetic_batch(jax.random.PRNGKey(1))
    loss = trainer.step(images, labels)
    assert np.isfinite(loss)
