"""DevEnv SSH gateway (VERDICT r2 #8): a socket test exercises the full
C24 flow — DevEnv reconciled → key Secret stored → TCP connect →
authenticate against the Secret → session banner + commands.  Key
rotation and teardown must take effect on the very next connection."""

import socket

import pytest

from k8s_gpu_tpu.api.devenv import DevEnv
from k8s_gpu_tpu.controller import FakeKube
from k8s_gpu_tpu.controller.manager import Request
from k8s_gpu_tpu.operators import DevEnvReconciler
from k8s_gpu_tpu.platform.sshgate import SshGateway

KEY = "ssh-ed25519 AAAAC3NzaC1lZDI1NTE5AAAAIFake ada@laptop"


class Client:
    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        self.f = self.sock.makefile("rwb")

    def line(self) -> str:
        return self.f.readline().decode().rstrip("\r\n")

    def send(self, text: str) -> None:
        self.f.write(text.encode() + b"\n")
        self.f.flush()

    def close(self):
        self.sock.close()


@pytest.fixture()
def cluster():
    kube = FakeKube()
    rec = DevEnvReconciler(kube)
    env = DevEnv()
    env.metadata.name = "ada-env"
    env.spec.username = "ada"
    env.spec.ssh_public_key = KEY
    kube.create(env)
    rec.reconcile(Request(name="ada-env", namespace="default"))
    gw = SshGateway(kube).start()
    yield kube, rec, gw
    gw.stop()


def test_connect_authenticate_session(cluster):
    kube, rec, gw = cluster
    c = Client(gw.port)
    assert c.line().startswith("SSH-2.0-k8sgpu-devenv-gateway")
    c.send("SSH-2.0-testclient")
    c.send(f"AUTH ada {KEY}")
    assert c.line().startswith("OK session opened for ada on devenv-ada")
    assert "Welcome to the TPU devenv" in c.line()
    c.send("EXEC hostname")
    assert c.line() == "devenv-ada"
    c.send("EXEC whoami")
    assert c.line() == "ada"
    c.send("EXIT")
    assert c.line() == "BYE"
    c.close()


def test_wrong_key_denied(cluster):
    kube, rec, gw = cluster
    c = Client(gw.port)
    c.line()
    c.send("SSH-2.0-testclient")
    c.send("AUTH ada ssh-ed25519 WRONGKEY mallory@evil")
    assert c.line().startswith("DENIED public key rejected")
    c.close()


def test_unknown_user_denied(cluster):
    kube, rec, gw = cluster
    c = Client(gw.port)
    c.line()
    c.send("SSH-2.0-testclient")
    c.send(f"AUTH bob {KEY}")
    assert "no running devenv for 'bob'" in c.line()
    c.close()


def test_non_ssh_client_denied(cluster):
    kube, rec, gw = cluster
    c = Client(gw.port)
    c.line()
    c.send("GET / HTTP/1.1")
    assert c.line().startswith("DENIED protocol mismatch")
    c.close()


def test_key_rotation_takes_effect_immediately(cluster):
    kube, rec, gw = cluster
    new_key = "ssh-ed25519 AAAANEWKEY ada@new-laptop"
    env = kube.get("DevEnv", "ada-env")
    env.spec.ssh_public_key = new_key
    kube.update(env)
    rec.reconcile(Request(name="ada-env", namespace="default"))
    # Old key now denied, new key accepted — auth reads the live Secret.
    c = Client(gw.port)
    c.line(); c.send("SSH-2.0-x"); c.send(f"AUTH ada {KEY}")
    assert c.line().startswith("DENIED")
    c.close()
    c = Client(gw.port)
    c.line(); c.send("SSH-2.0-x"); c.send(f"AUTH ada {new_key}")
    assert c.line().startswith("OK")
    c.close()


def test_teardown_stops_accepting(cluster):
    kube, rec, gw = cluster
    kube.delete("DevEnv", "ada-env")
    rec.reconcile(Request(name="ada-env", namespace="default"))
    c = Client(gw.port)
    c.line(); c.send("SSH-2.0-x"); c.send(f"AUTH ada {KEY}")
    assert "no running devenv" in c.line()
    c.close()


def test_sftp_style_bulk_upload(tmp_path, cluster):
    """C29's SFTP half: a big payload rides the authenticated ssh channel
    into the versioned asset store — no web-upload size cap on this path
    (GPU调度平台搭建.md:707-734)."""
    from k8s_gpu_tpu.platform import AssetStore

    kube, rec, gw = cluster
    gw.stop()
    store = AssetStore(tmp_path / "assets")
    gw2 = SshGateway(kube, assets=store).start()
    try:
        c = Client(gw2.port)
        c.line()
        c.send("SSH-2.0-testclient")
        c.send(f"AUTH ada {KEY}")
        assert c.line().startswith("OK")
        c.line()  # welcome
        payload = b"model-bytes " * 500_000  # ~6 MB, one shot
        c.send(f"PUT ml model big-model {len(payload)}")
        assert c.line() == "GO"  # header accepted before any body byte
        c.f.write(payload)
        c.f.flush()
        reply = c.line()
        assert reply.startswith("OK imported model/big-model v1")
        a = store.get("ml", "model", "big-model")
        assert a.size == len(payload)
        with open(a.path, "rb") as f:
            assert f.read() == payload
        # Second upload versions.
        c.send("PUT ml model big-model 3")
        assert c.line() == "GO"
        c.f.write(b"xyz")
        c.f.flush()
        assert "v2" in c.line()
        c.send("EXIT")
        c.close()
    finally:
        gw2.stop()


def test_put_without_store_or_bad_args(cluster):
    kube, rec, gw = cluster
    c = Client(gw.port)
    c.line(); c.send("SSH-2.0-x"); c.send(f"AUTH ada {KEY}")
    assert c.line().startswith("OK")
    c.line()
    c.send("PUT ml model x 10")
    assert "uploads disabled" in c.line()
    c.close()


def test_put_traversal_rejected(tmp_path, cluster):
    """Review finding: PUT must not resolve '..' into filesystem paths."""
    from k8s_gpu_tpu.platform import AssetStore

    kube, rec, gw = cluster
    gw.stop()
    gw2 = SshGateway(kube, assets=AssetStore(tmp_path / "assets")).start()
    try:
        c = Client(gw2.port)
        c.line(); c.send("SSH-2.0-x"); c.send(f"AUTH ada {KEY}")
        assert c.line().startswith("OK")
        c.line()
        c.send("PUT ../../evil model x 4")
        # Refused at the HEADER — no GO, so the body is never sent and
        # a rejected transfer costs one round trip.
        assert c.line().startswith("ERR unsafe path component")
        assert not (tmp_path / "evil").exists()
    finally:
        gw2.stop()
