"""Generated CRD schemas (VERDICT r2 #7 second half): per-kind schema
export from the dataclass codec and schema-derived apply --validate
rejection — the ``make manifests generate`` analogue (README.md:157-160)."""

import pytest

from k8s_gpu_tpu.api.schema import (
    all_schemas,
    schema_for_kind,
    validate_manifest,
)
from k8s_gpu_tpu.api.serialize import known_kinds


def test_every_registered_kind_has_a_schema():
    schemas = all_schemas()
    assert set(schemas) == set(known_kinds())
    for kind, s in schemas.items():
        assert s["type"] == "object"
        assert s["properties"]["kind"]["enum"] == [kind]
        assert s["additionalProperties"] is False


def test_tpupodslice_schema_shape():
    s = schema_for_kind("TpuPodSlice")
    spec = s["properties"]["spec"]
    assert spec["properties"]["acceleratorType"] == {"type": "string"}
    assert spec["properties"]["sliceCount"] == {"type": "integer"}
    assert spec["properties"]["spot"] == {"type": "boolean"}
    assert spec["additionalProperties"] is False


def test_validate_accepts_good_manifest():
    doc = {
        "apiVersion": "tpu.k8sgpu.dev/v1alpha1",
        "kind": "TpuPodSlice",
        "metadata": {"name": "demo"},
        "spec": {"acceleratorType": "v5p-64", "sliceCount": 1},
    }
    assert validate_manifest(doc) == []


def test_validate_reports_unknown_field_with_path():
    doc = {
        "apiVersion": "v1", "kind": "TpuPodSlice",
        "metadata": {"name": "demo"},
        "spec": {"acceleratorTpye": "v5p-64"},  # typo
    }
    errs = validate_manifest(doc)
    assert any(".spec.acceleratorTpye: unknown field" in e for e in errs)
    assert any("acceleratorType" in e for e in errs)  # names the allowed set


def test_validate_reports_type_errors_with_path():
    doc = {
        "apiVersion": "v1", "kind": "TpuPodSlice",
        "metadata": {"name": "demo"},
        "spec": {"sliceCount": "three", "spot": 1},
    }
    errs = validate_manifest(doc)
    assert any(".spec.sliceCount: expected integer" in e for e in errs)
    assert any(".spec.spot: expected boolean" in e for e in errs)


def test_validate_unknown_kind():
    errs = validate_manifest({"kind": "Zorp", "metadata": {}})
    assert errs and "unknown kind" in errs[0]


def test_status_ignored_on_validate():
    doc = {
        "apiVersion": "v1", "kind": "TpuPodSlice",
        "metadata": {"name": "demo"},
        "status": {"whatever": "controller-owned"},
    }
    assert validate_manifest(doc) == []


# -- CLI integration --------------------------------------------------------

@pytest.fixture(autouse=True)
def isolated_dirs(tmp_path, monkeypatch):
    monkeypatch.setenv("K8SGPU_CONFIG_DIR", str(tmp_path / "config"))
    monkeypatch.setenv("K8SGPU_STATE_DIR", str(tmp_path / "state"))
    yield tmp_path


def _run(capsys, *argv):
    from k8s_gpu_tpu.cli.main import main

    code = main(list(argv))
    out = capsys.readouterr()
    return code, out.out, out.err


def test_cli_apply_validate_rejects_bad_manifest(tmp_path, capsys):
    _run(capsys, "login", "--user", "ada", "--space", "ml")
    bad = tmp_path / "bad.yaml"
    bad.write_text(
        "apiVersion: tpu.k8sgpu.dev/v1alpha1\n"
        "kind: TpuPodSlice\n"
        "metadata: {name: demo}\n"
        "spec: {acceleratorTpye: v5p-64, sliceCount: one}\n"
    )
    code, out, err = _run(capsys, "apply", "-f", str(bad), "--validate")
    assert code == 1
    assert ".spec.acceleratorTpye: unknown field" in err
    assert ".spec.sliceCount: expected integer" in err

    good = tmp_path / "good.yaml"
    good.write_text(
        "apiVersion: tpu.k8sgpu.dev/v1alpha1\n"
        "kind: TpuPodSlice\n"
        "metadata: {name: demo}\n"
        "spec: {acceleratorType: v4-8, sliceCount: 1}\n"
    )
    code, out, err = _run(capsys, "apply", "-f", str(good), "--validate",
                          "--no-wait")
    assert code == 0 and "created" in out


def test_cli_schema_export(tmp_path, capsys):
    code, out, _ = _run(capsys, "schema", "TpuPodSlice")
    assert code == 0 and '"acceleratorType"' in out
    code, out, _ = _run(capsys, "schema", "-o", str(tmp_path / "crds"))
    assert code == 0
    files = sorted(p.name for p in (tmp_path / "crds").iterdir())
    assert "TpuPodSlice.json" in files and "TrainJob.json" in files
    code, _, err = _run(capsys, "schema", "Zorp")
    assert code == 1 and "unknown kind" in err


def test_cli_apply_handles_malformed_yaml_and_scalar_docs(tmp_path, capsys):
    """Review findings: broken YAML and non-mapping documents must produce
    clean errors, not tracebacks or garbled concatenation."""
    _run(capsys, "login", "--user", "ada", "--space", "ml")
    broken = tmp_path / "broken.yaml"
    broken.write_text("foo: [")
    code, out, err = _run(capsys, "apply", "-f", str(broken), "--validate")
    assert code == 1 and "error:" in err
    scalar = tmp_path / "scalar.yaml"
    scalar.write_text("hello")
    code, out, err = _run(capsys, "apply", "-f", str(scalar), "--validate")
    assert code == 1
    assert "document 0: manifest must be a mapping" in err
