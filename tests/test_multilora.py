"""Multi-LoRA serving: one decode program, per-row adapters.

Contracts:
- base rows (adapter index 0) are BITWISE identical to a bank-less
  batcher — the zero adapter contributes exactly 0 to every projection;
- an adapter row decodes like an engine running the merged
  ``W + scale·A@B`` weights (tolerance: the low-rank path sums in a
  different order than the merged matmul);
- mixed batches serve different adapters in the same rounds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher, InferenceEngine
from k8s_gpu_tpu.serve.lora_bank import AdapterBank, SERVABLE_TARGETS
from k8s_gpu_tpu.train.lora import LoraAdapter, LoraConfig

CFG = TransformerConfig(
    vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
    d_ff=96, max_seq=64, use_flash=False, dtype=jnp.float32,
)


def _randomized_adapter(model, params, cfg: LoraConfig, seed: int):
    """LoraAdapter.init gives B=0 (delta 0); randomize B so the adapter
    actually changes the model."""
    tree = LoraAdapter(cfg).init(jax.random.PRNGKey(seed), params)
    keys = iter(jax.random.split(jax.random.PRNGKey(seed + 100), 16))
    tree["blocks"] = {
        t: {"a": ab["a"],
            "b": jax.random.normal(next(keys), ab["b"].shape) * 0.05}
        for t, ab in tree["blocks"].items()
    }
    return tree


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0))
    c1 = LoraConfig(rank=4, targets=("wq", "wv"))
    c2 = LoraConfig(rank=8, targets=("wq", "wk", "wv", "wo"))
    a1 = _randomized_adapter(model, params, c1, seed=1)
    a2 = _randomized_adapter(model, params, c2, seed=2)
    return model, params, {"tenant-a": (a1, c1), "tenant-b": (a2, c2)}


def _oracle(model, params, ids, n):
    seq = jnp.asarray(ids, jnp.int32)[None, :]
    out = []
    for _ in range(n):
        logits, _ = model.forward(params, seq)
        nxt = int(jnp.argmax(logits[:, -1], axis=-1)[0])
        out.append(nxt)
        seq = jnp.concatenate([seq, jnp.asarray([[nxt]], jnp.int32)], axis=1)
    return out


def test_bank_shapes_and_zero_row(setup):
    model, params, adapters = setup
    bank = AdapterBank(adapters)
    assert bank.names == ["__base__", "tenant-a", "tenant-b"]
    wq = bank.banked["wq"]
    L, K, fin, R = wq["a"].shape
    assert (L, K, R) == (CFG.n_layers, 3, 8)  # rank-padded to max
    assert float(jnp.abs(wq["a"][:, 0]).max()) == 0.0  # base row is zeros
    # tenant-a (rank 4) pads ranks 4..7 with zeros
    ia = bank.names.index("tenant-a")
    assert float(jnp.abs(wq["a"][:, ia, :, 4:]).max()) == 0.0
    assert bank.index(None) == 0
    with pytest.raises(KeyError, match="unknown adapter"):
        bank.index("nope")


def test_bank_rejects_unsupported_targets(setup):
    model, params, _ = setup
    cfg = LoraConfig(rank=4, targets=("wq", "wi_gate"))
    tree = LoraAdapter(cfg).init(jax.random.PRNGKey(3), params)
    with pytest.raises(ValueError, match="wi_gate"):
        AdapterBank({"bad": (tree, cfg)})


def test_base_rows_bitwise_unchanged(setup):
    """The zero adapter is EXACTLY zero: a banked batcher must produce
    the same stream as a bank-less one for base requests."""
    model, params, adapters = setup
    plain = ContinuousBatcher(model, params, slots=2).start()
    banked = ContinuousBatcher(model, params, slots=2,
                               adapters=adapters).start()
    try:
        ids = [5, 9, 17]
        a = plain.submit(ids, max_new_tokens=8).result()
        b = banked.submit(ids, max_new_tokens=8).result()
        assert a == b == _oracle(model, params, ids, 8)
    finally:
        plain.stop()
        banked.stop()


@pytest.mark.parametrize("name", ["tenant-a", "tenant-b"])
def test_adapter_row_matches_merged_oracle(setup, name):
    model, params, adapters = setup
    tree, cfg = adapters[name]
    merged = LoraAdapter(cfg).merge(params, tree)
    b = ContinuousBatcher(model, params, slots=2,
                          adapters=adapters).start()
    try:
        ids = [7, 3, 11, 19]
        got = b.submit(ids, max_new_tokens=8, adapter=name).result()
        assert got == _oracle(model, merged, ids, 8)
    finally:
        b.stop()


def test_mixed_batch_each_matches_its_model(setup):
    model, params, adapters = setup
    tree, cfg = adapters["tenant-b"]
    merged = LoraAdapter(cfg).merge(params, tree)
    b = ContinuousBatcher(model, params, slots=4,
                          adapters=adapters).start()
    try:
        base_ids, ad_ids = [2, 4, 6], [8, 10, 12]
        h1 = b.submit(base_ids, max_new_tokens=8)
        h2 = b.submit(ad_ids, max_new_tokens=8, adapter="tenant-b")
        assert h1.result() == _oracle(model, params, base_ids, 8)
        assert h2.result() == _oracle(model, merged, ad_ids, 8)
    finally:
        b.stop()


def test_unknown_adapter_rejected_at_submit(setup):
    model, params, adapters = setup
    b = ContinuousBatcher(model, params, slots=2, adapters=adapters)
    with pytest.raises(KeyError, match="unknown adapter"):
        b.submit([1, 2, 3], adapter="nope")


def test_adapter_requests_skip_prefix_cache(setup):
    """Cached prefixes hold base-model K/V; an adapter request must
    cold-prefill and still match its merged oracle."""
    model, params, adapters = setup
    tree, cfg = adapters["tenant-a"]
    merged = LoraAdapter(cfg).merge(params, tree)
    b = ContinuousBatcher(model, params, slots=2,
                          adapters=adapters).start()
    try:
        prefix = [7, 3, 11]
        b.precache_prefix(prefix)
        ids = prefix + [19, 23]
        got = b.submit(ids, max_new_tokens=8, adapter="tenant-a").result()
        assert got == _oracle(model, merged, ids, 8)
        # and the base path still uses the cache + stays correct
        got_base = b.submit(ids, max_new_tokens=8).result()
        assert got_base == _oracle(model, params, ids, 8)
    finally:
        b.stop()


def test_lm_server_adapter_param(setup):
    """HTTP surface: {"adapter": name} routes to the adapter; unknown
    names are a clean 400."""
    import json
    import urllib.error
    import urllib.request

    from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
    from k8s_gpu_tpu.serve import LmServer

    model, params, adapters = setup
    tok = BpeTokenizer.train("serve many tenants well " * 30,
                             vocab_size=CFG.vocab_size, backend="python")
    srv = LmServer(model, params, tok, adapters=adapters).start()
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, base = post({"prompt": "serve many", "max_new_tokens": 5})
        code2, ad = post({"prompt": "serve many", "max_new_tokens": 5,
                          "adapter": "tenant-b"})
        assert code == 200 and code2 == 200
        assert base["ids"] != ad["ids"]  # the adapter changed the model
        code3, err = post({"prompt": "x", "adapter": "nope"})
        assert code3 == 400 and "unknown adapter" in err["error"]
    finally:
        srv.stop()
