"""Paged KV cache: block-table pool parity + allocator behavior.

VERDICT r4 ask #3: the dense slots×max_seq pool reserves the full
window per slot whether a request uses 40 tokens or 4,000; the paged
pool (engine._empty_cache_paged + the batcher's block allocator) scales
a slot's cache bytes with ceil(used/page).  Contract:

1. decode PARITY: paged streams are token-for-token identical to the
   dense batcher and the one-shot oracle (greedy and sampled);
2. composes with int8 KV (paged int8 blocks, same parity bar);
3. the allocator backpressures (defers under block exhaustion, resumes
   on retirement, frees everything at the end) instead of corrupting.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher

CFG = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq=128, use_flash=False, dtype=jnp.float32,
)
MODEL = TransformerLM(CFG)
PARAMS = MODEL.init(jax.random.PRNGKey(0))

PROMPTS = [
    [3, 5, 7],                           # short
    list(range(2, 24)),                  # crosses a 16-token page
    [11, 13],                            # tiny
    list(range(40, 75)),                 # multi-page
]


def _run(batcher_kwargs, reqs):
    b = ContinuousBatcher(MODEL, PARAMS, slots=4, **batcher_kwargs).start()
    try:
        handles = [b.submit(ids, **kw) for ids, kw in reqs]
        return [h.result() for h in handles]
    finally:
        b.stop()


def test_paged_matches_dense_greedy():
    reqs = [(p, dict(max_new_tokens=12)) for p in PROMPTS]
    dense = _run({}, reqs)
    paged = _run({"paged_blocks": 64, "page_size": 16}, reqs)
    assert paged == dense


def test_paged_matches_dense_sampled():
    reqs = [
        (p, dict(max_new_tokens=10, temperature=0.8, seed=41 + i))
        for i, p in enumerate(PROMPTS)
    ]
    dense = _run({}, reqs)
    paged = _run({"paged_blocks": 64, "page_size": 16}, reqs)
    assert paged == dense


def test_paged_composes_with_int8_kv():
    reqs = [(p, dict(max_new_tokens=12)) for p in PROMPTS]
    dense_q = _run({"kv_quant": True}, reqs)
    paged_q = _run(
        {"kv_quant": True, "paged_blocks": 64, "page_size": 16}, reqs
    )
    assert paged_q == dense_q


def test_paged_matches_oracle():
    from k8s_gpu_tpu.serve.engine import InferenceEngine

    eng = InferenceEngine(MODEL)

    def oracle(ids, n):
        out = eng.generate(
            PARAMS, jnp.asarray(ids, jnp.int32)[None], max_new_tokens=n
        )
        return [int(t) for t in out.tokens[0][: int(out.lengths[0])]]

    got = _run(
        {"paged_blocks": 64, "page_size": 16},
        [(p, dict(max_new_tokens=12)) for p in PROMPTS],
    )
    for p, toks in zip(PROMPTS, got):
        assert toks == oracle(p, 12), p


def test_allocator_backpressure_and_reclaim():
    """More requests than blocks: later admissions defer until earlier
    retirements free blocks; every stream still completes exactly; all
    blocks return to the free list."""
    # 12 blocks of 16 = 192 positions; each request needs
    # ceil((8 + 24)/16) = 2 blocks, so at most 5 concurrent (plus
    # trash); submit 8 with 4 slots.
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=12, page_size=16
    ).start()
    try:
        handles = [
            b.submit([3, 5, 7, 11 + i], max_new_tokens=24)
            for i in range(8)
        ]
        outs = [h.result() for h in handles]
        assert all(len(o) == 24 for o in outs)
        # prompts differ only in the last token → streams may differ;
        # equal prompts must produce equal streams through the paging
        same = [
            b.submit([3, 5, 7, 11], max_new_tokens=24) for _ in range(2)
        ]
        s0, s1 = same[0].result(), same[1].result()
        assert s0 == s1 == outs[0]
    finally:
        b.stop()
    assert sorted(b._free_blocks) == list(range(1, 12))
    assert (b._pages == 0).all()


def test_block_pressure_deferrals_stay_fifo():
    """Regression (ADVICE): the block-pressure retry path must not rotate
    the deferred queue — a popped-and-refused request goes back to the
    FRONT (appendleft), so deferrals admit in submission order even while
    the allocator repeatedly refuses the head."""
    # 10 blocks of 16 (9 usable).  The holder takes 5 (ceil((40+30)/16)
    # — unpadded allocation since the block-prefix-cache rework), leaving
    # 4 — the two big deferrals need 5 each, so both sit in _overflow
    # through many scheduler passes (each pass pops the head, fails,
    # re-queues: the rotation site) until the holder retires, then must
    # admit b2 BEFORE b3.
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=10, page_size=16
    ).start()
    try:
        holder = b.submit(list(range(2, 42)), max_new_tokens=30)
        big2 = b.submit(list(range(3, 43)), max_new_tokens=30)
        big3 = b.submit(list(range(4, 44)), max_new_tokens=30)
        outs = [h.result() for h in (holder, big2, big3)]
        assert all(len(o) == 30 for o in outs)
        assert not any(h.aborted for h in (holder, big2, big3))
        # admission order == submission order (t_admit is stamped once,
        # at the admit dispatch)
        assert holder._req.t_admit < big2._req.t_admit < big3._req.t_admit
    finally:
        b.stop()
    assert sorted(b._free_blocks) == list(range(1, 10))


def test_pool_floor_guarantees_progress():
    """paged_blocks must cover trash + one max-length request — below
    that, a long request could deadlock the allocator, so the
    constructor refuses."""
    with pytest.raises(ValueError, match="trash"):
        ContinuousBatcher(
            MODEL, PARAMS, slots=2, paged_blocks=8, page_size=16
        )
    # exactly at the floor: a worst-case request still serves
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=2, paged_blocks=9, page_size=16
    ).start()
    try:
        ok = b.submit(list(range(2, 60)), max_new_tokens=56).result()
        assert len(ok) == 56
    finally:
        b.stop()


def test_lm_server_paged_passthrough():
    """The HTTP server serves off a paged pool end-to-end and frees
    every block at retirement."""
    import json
    import urllib.request

    from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
    from k8s_gpu_tpu.serve import LmServer

    tok = BpeTokenizer.train("tiny corpus for serving " * 40,
                             vocab_size=120, backend="python")
    import dataclasses

    cfg = dataclasses.replace(CFG, vocab_size=tok.vocab_size)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    srv = LmServer(model, params, tok, port=0, slots=4,
                   paged_blocks=64, page_size=16).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate",
            data=json.dumps({"prompt": "tiny corpus",
                             "max_new_tokens": 12}).encode(),
            headers={"Content-Type": "application/json"},
        )
        out = json.loads(urllib.request.urlopen(req, timeout=90).read())
        assert out["generated_tokens"] == 12
    finally:
        srv.stop()
    assert sorted(srv.batcher._free_blocks) == list(range(1, 64))


def test_inferenceservice_paged_spec_validation():
    from k8s_gpu_tpu.api.inferenceservice import InferenceService
    from k8s_gpu_tpu.api.types import ValidationError

    svc = InferenceService()
    svc.metadata.name = "paged-svc"
    svc.spec.model.id = "m"
    svc.spec.paged_blocks = 128
    svc.validate()  # paged alone is fine
    svc.spec.draft_mode = "ngram"
    svc.validate()  # paged + speculative drafting composes now
    svc.spec.draft_mode = ""
    svc.spec.paged_blocks = -1
    with pytest.raises(ValidationError, match=">= 0"):
        svc.validate()


def test_paged_composes_with_spec_and_prefix():
    """The r5 restrictions are lifted: paged + ngram drafting constructs
    (greedy parity lives in test_block_prefix_cache.py), and paged
    precache_prefix warms the BLOCK cache instead of raising."""
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=2, draft="ngram",
        paged_blocks=32, page_size=16,
    ).start()
    try:
        got = b.submit(PROMPTS[1], max_new_tokens=8).result()
        assert len(got) == 8
    finally:
        b.stop()
    assert sorted(b._free_blocks) == list(range(1, 32))
    with pytest.raises(ValueError, match="max_seq"):
        ContinuousBatcher(
            MODEL, PARAMS, slots=2, paged_blocks=32, page_size=48
        )
