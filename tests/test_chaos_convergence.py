"""Chaos suite: the control and serving planes must CONVERGE under
seeded fault schedules, not merely pass when the fake cloud is polite.

Named to sort early in the alphabetically-truncated tier-1 window.
Everything is driven by FakeClock + seeded FaultPlans (utils/faults.py),
so minutes of retry/requeue/breaker cadence replay in milliseconds and
every run injects the identical schedule.  Invariants pinned here:

- AzureVmPool and TpuPodSlice converge to spec under a 30% injected
  cloud-fault rate within a bounded number of reconcile passes, with
  zero leaked cloud resources (strays / orphaned NICs+disks), and tear
  down cleanly while the faults keep firing;
- an open circuit breaker caps outbound call attempts while the endpoint
  is down (short-circuits never reach the cloud) and heals through the
  half-open probe;
- the workqueue failure ladder resets: a successful reconcile forgets
  the key, so a later transient error starts at base_delay again;
- a hung transport surfaces as CloudError within the timeout bound
  instead of blocking a reconcile worker forever;
- the serve plane sheds (429/Overloaded, expired deadlines) instead of
  queueing or computing work nobody is waiting for.
"""

import json
import socket
import threading
import time
import urllib.request

import pytest

from k8s_gpu_tpu.api import AzureVmPool, Secret, TpuPodSlice
from k8s_gpu_tpu.cloud import (
    AuthError,
    CircuitOpenError,
    CloudError,
    CloudTpuClient,
    FakeAzureCloud,
    FakeCloudTpu,
    MetadataIdentity,
    RetryPolicy,
    azure_client_factory,
    cloudtpu_client_factory,
    make_urllib_transport,
    resilient_factory,
)
from k8s_gpu_tpu.cloud.resilience import CircuitBreaker
from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.controller.manager import Reconciler, Result
from k8s_gpu_tpu.controller.workqueue import RateLimitingQueue
from k8s_gpu_tpu.operators import AzureVmPoolReconciler, TpuPodSliceReconciler
from k8s_gpu_tpu.utils.clock import FakeClock
from k8s_gpu_tpu.utils.faults import FaultInjector, FaultPlan, global_faults

# Zero-delay retries: under FakeClock a non-zero backoff would park the
# worker until the test advances time — determinism is already covered by
# the dedicated jitter test below.
FAST_RETRY = RetryPolicy(max_attempts=3, budget=6, base_delay=0.0)

FAULT_RATE = 0.30


@pytest.fixture
def faults():
    """The global injector, disarmed before and after — sites in the
    workqueue/manager/serve planes read global_faults directly."""
    global_faults.disarm()
    yield global_faults
    global_faults.disarm()


def drive(mgr, clock, predicate, passes=30, step=41.0):
    """Advance fake time one error-ladder rung at a time (41 s clears the
    worst rung, MUTATE_RETRY=40) until *predicate* holds.  Returns the
    number of advances spent — the suite's 'bounded reconcile passes'
    measure."""
    for i in range(passes):
        if predicate():
            return i
        clock.advance(step)
        mgr.wait_idle(timeout=0.5)
    assert predicate(), "did not converge within the pass bound"
    return passes


# -- pool convergence under a 30% fault rate --------------------------------

def test_tpu_pool_converges_under_30pct_faults(kube, clock, faults):
    for site, seed in (
        ("cloudtpu.create", 11), ("cloudtpu.list", 12),
        ("cloudtpu.delete", 13),
    ):
        faults.arm(site, FaultPlan(seed=seed, rate=FAULT_RATE))
    # Control-plane sites too: delayed watch delivery and reconciler
    # panics must also be survivable (events delayed, never lost).
    faults.arm(
        "workqueue.add",
        FaultPlan(seed=14, rate=0.2, kinds=("slow",), slow_s=2.0),
    )
    faults.arm("reconcile.TpuPodSlice", FaultPlan(seed=15, rate=0.1))

    cloud = FakeCloudTpu(clock=clock)
    mgr = Manager(kube, clock=clock)
    factory = resilient_factory(
        cloudtpu_client_factory(cloud), policy=FAST_RETRY, clock=clock,
        name="cloudtpu",
    )
    mgr.register("TpuPodSlice", TpuPodSliceReconciler(kube, factory))
    mgr.start()
    try:
        ps = TpuPodSlice()
        ps.metadata.name = "chaos"
        ps.spec.accelerator_type = "v4-8"
        kube.create(ps)

        def ready():
            cur = kube.try_get("TpuPodSlice", "chaos")
            return cur is not None and cur.status.phase == "Ready"

        drive(mgr, clock, ready)
        # Faults really fired — a green run with zero injections would be
        # a broken harness, not a robust system.
        assert sum(
            s["injected"] for s in faults.sites().values()
        ) > 0
        # Zero leaked cloud resources: exactly the one owned QR, ACTIVE.
        assert list(cloud.queued_resources) == ["default-chaos-qr"]
        assert cloud.queued_resources["default-chaos-qr"].state == "ACTIVE"
        assert len(kube.list("Node")) == 2  # v4-8 = 2 hosts

        # Teardown must also converge while the faults keep firing.
        kube.delete("TpuPodSlice", "chaos")
        drive(
            mgr, clock,
            lambda: not cloud.queued_resources
            and kube.try_get("TpuPodSlice", "chaos") is None,
        )
        assert kube.list("Node") == []
    finally:
        mgr.stop()


def test_azure_pool_converges_and_scales_without_leaks(kube, clock, faults):
    for site, seed in (
        ("azure.create", 21), ("azure.list", 22), ("azure.delete", 23),
    ):
        faults.arm(site, FaultPlan(seed=seed, rate=FAULT_RATE))

    cloud = FakeAzureCloud(clock=clock)
    mgr = Manager(kube, clock=clock)
    factory = resilient_factory(
        azure_client_factory(cloud), policy=FAST_RETRY, clock=clock,
        name="azure",
    )
    mgr.register("AzureVmPool", AzureVmPoolReconciler(kube, factory))
    mgr.start()
    secret = Secret(data={
        "AZURE_CLIENT_ID": "cid", "AZURE_CLIENT_SECRET": "sec",
        "AZURE_TENANT_ID": "tid", "AZURE_SUBSCRIPTION_ID": "sub",
    })
    secret.metadata.name = "azure-creds"
    kube.create(secret)
    try:
        pool = AzureVmPool()
        pool.metadata.name = "chaos-pool"
        pool.spec.replicas = 3
        pool.spec.vm_size = "Standard_NC4as_T4_v3"
        pool.spec.azure_credential_secret = "azure-creds"
        kube.create(pool)

        def ready(n):
            def check():
                p = kube.try_get("AzureVmPool", "chaos-pool")
                return (
                    p is not None and p.status.ready_replicas == n
                    and len(cloud.vms) == n
                )
            return check

        drive(mgr, clock, ready(3))
        assert cloud.leaked_attachments == 0

        # Scale down under the same fault schedule: the cost-leak rule
        # (NIC + disk go with the VM) must hold on every retried delete.
        p = kube.get("AzureVmPool", "chaos-pool")
        p.spec.replicas = 1
        kube.update(p)
        drive(mgr, clock, ready(1))
        assert cloud.leaked_attachments == 0
        assert faults.injected("azure.delete") + faults.injected(
            "azure.create") + faults.injected("azure.list") > 0
    finally:
        mgr.stop()


# -- circuit breaker --------------------------------------------------------

def test_breaker_caps_attempts_while_endpoint_down(clock, faults):
    inj = FaultInjector()
    cloud = FakeCloudTpu(clock=clock, injector=inj)
    inj.arm("cloudtpu.list", FaultPlan(rate=1.0))  # endpoint hard-down
    factory = resilient_factory(
        cloudtpu_client_factory(cloud), policy=FAST_RETRY, clock=clock,
        failure_threshold=3, reset_timeout=30.0, name="tpu",
    )
    # Each factory() call = one reconcile pass's client (fresh retry
    # budget, shared breakers).
    opens = 0
    for _ in range(10):
        try:
            factory("wi").list_resources({})
        except CircuitOpenError:
            opens += 1
        except CloudError:
            pass
    calls_while_down = len(cloud.api_calls)
    assert factory.breakers.states() == {"list": "open"}
    assert opens > 0
    # The cap: 10 passes x up to 3 attempts = 30 potential calls; the
    # breaker must have stopped all outbound traffic at its threshold.
    assert calls_while_down == 3
    # More passes while open: ZERO additional outbound calls.
    for _ in range(5):
        with pytest.raises(CircuitOpenError):
            factory("wi").list_resources({})
    assert len(cloud.api_calls) == calls_while_down

    # Half-open probe after the reset window: endpoint still down → one
    # probe call, straight back to open.
    clock.advance(30.1)
    with pytest.raises(CloudError):
        factory("wi").list_resources({})
    assert len(cloud.api_calls) == calls_while_down + 1
    assert factory.breakers.states() == {"list": "open"}

    # Endpoint heals → next probe closes the breaker and traffic flows.
    inj.disarm()
    clock.advance(30.1)
    assert factory("wi").list_resources({}) == []
    assert factory.breakers.states() == {"list": "closed"}


def test_breaker_state_transitions_deterministic(clock):
    br = CircuitBreaker(
        "ep", clock=clock, failure_threshold=2, reset_timeout=10.0
    )
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clock.advance(9.9)
    assert not br.allow()  # still inside the reset window
    clock.advance(0.2)
    assert br.allow() and br.state == "half_open"
    assert not br.allow()  # a single probe at a time
    br.record_failure()    # probe failed → re-open, timer restarts
    assert br.state == "open" and not br.allow()
    clock.advance(10.1)
    assert br.allow() and br.state == "half_open"
    br.record_success()    # probe succeeded → closed, failures reset
    assert br.state == "closed"
    br.record_failure()
    assert br.state == "closed"  # the count restarted from zero


def test_half_open_probe_claim_released_on_non_cloud_outcomes(clock):
    """An AuthError (or a bug in the backend) during the half-open probe
    must hand the claim back — a stranded claim would wedge the breaker
    half-open forever, short-circuiting every future call."""
    from k8s_gpu_tpu.cloud.resilience import BreakerBank, ResilientBackend

    class Backend:
        def __init__(self):
            self.mode = CloudError

        def list_resources(self, tags):
            if self.mode is None:
                return []
            raise self.mode("scripted")

        def is_ready(self, r):
            return True

    bank = BreakerBank(clock=clock, failure_threshold=1, reset_timeout=5.0)
    inner = Backend()
    rb = ResilientBackend(
        inner, bank, policy=RetryPolicy(max_attempts=1), clock=clock
    )
    with pytest.raises(CloudError):
        rb.list_resources({})          # threshold 1 → open
    assert bank.states() == {"list": "open"}
    clock.advance(5.1)
    inner.mode = AuthError             # probe hits a credential problem
    with pytest.raises(AuthError):
        rb.list_resources({})
    # The claim came back: the breaker still admits a (real) probe...
    inner.mode = TypeError             # ...which explodes non-cloudly...
    with pytest.raises(TypeError):
        rb.list_resources({})
    inner.mode = None                  # ...and the NEXT probe still runs.
    assert rb.list_resources({}) == []
    assert bank.states() == {"list": "closed"}


def test_retry_backoff_deterministic_and_capped():
    p = RetryPolicy(base_delay=0.1, max_delay=2.0, jitter=0.5)
    for attempt in range(1, 8):
        a = p.delay(attempt, key="queuedResources")
        b = p.delay(attempt, key="queuedResources")
        assert a == b  # same (key, attempt) → same jitter, every run
        assert 0.0 < a <= 2.0
    # Different keys de-synchronize (full-jitter's herd spread).
    assert p.delay(3, key="list") != p.delay(3, key="create")
    # The exponential rises until the cap.
    assert p.delay(1, key="k") < p.delay(4, key="k") <= 2.0


# -- workqueue failure ladder ----------------------------------------------

def test_workqueue_forget_resets_backoff_ladder(clock):
    q = RateLimitingQueue(clock=clock, base_delay=1.0, max_delay=100.0)
    # Two failures climb the ladder to a 2 s delay...
    q.add_rate_limited("k")
    clock.advance(1.1)
    assert q.get(block=False) == "k"
    q.done("k")
    q.add_rate_limited("k")
    clock.advance(1.1)
    assert q.get(block=False) is None  # second rung: 2 s, not 1 s
    clock.advance(1.0)
    assert q.get(block=False) == "k"
    q.done("k")
    # ... a successful reconcile forgets the key ...
    q.forget("k")
    # ... so the NEXT transient error starts back at base_delay.
    q.add_rate_limited("k")
    clock.advance(1.1)
    assert q.get(block=False) == "k"
    q.done("k")


def test_manager_forgets_backoff_after_successful_reconcile(kube, clock):
    """The contract the workqueue test pins, proven at the manager level:
    reconcile failures climb the per-key ladder, ONE success resets it."""

    class Flaky(Reconciler):
        def __init__(self):
            self.calls = 0

        def reconcile(self, req):
            self.calls += 1
            if self.calls <= 2:
                raise RuntimeError("transient")
            return Result()

    rec = Flaky()
    mgr = Manager(kube, clock=clock)
    mgr.register("TpuPodSlice", rec)
    mgr.start()
    try:
        ps = TpuPodSlice()
        ps.metadata.name = "flaky"
        ps.spec.accelerator_type = "v4-8"
        kube.create(ps)
        q = mgr._controllers["TpuPodSlice"].queue
        deadline = time.monotonic() + 10.0
        while rec.calls < 3 and time.monotonic() < deadline:
            clock.advance(0.05)  # clears any backoff rung (base 5 ms)
            time.sleep(0.002)
        assert rec.calls >= 3
        mgr.wait_idle(timeout=5.0)
        from k8s_gpu_tpu.controller.manager import Request

        # forget() ran on success: the failure memory is gone and a
        # future transient error restarts at base_delay.
        assert q.num_requeues(Request("default", "flaky")) == 0
    finally:
        mgr.stop()


def test_workqueue_slow_site_delays_but_never_loses_events(clock, faults):
    faults.arm(
        "workqueue.add",
        FaultPlan(rate=1.0, kinds=("slow",), slow_s=5.0),
    )
    q = RateLimitingQueue(clock=clock)
    q.add("k")
    assert q.get(block=False) is None  # delivery delayed, not dropped
    clock.advance(5.1)
    assert q.get(block=False) == "k"
    # An error-kind plan at this site is IGNORED: losing an event would
    # violate at-least-once delivery, which no real fault mode does.
    faults.arm("workqueue.add", FaultPlan(rate=1.0, kinds=("error",)))
    q.done("k")
    q.add("k2")
    assert q.get(block=False) == "k2"
    assert faults.injected("workqueue.add") == 0


# -- transport timeouts -----------------------------------------------------

def test_hung_transport_surfaces_as_clouderror_not_a_hang():
    """Regression for the un-timed urllib call: a server that accepts and
    never responds must fail the call within the timeout bound instead of
    wedging a reconcile worker forever."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    accepted = []
    t = threading.Thread(
        target=lambda: accepted.append(srv.accept()), daemon=True
    )
    t.start()
    transport = make_urllib_transport(
        connect_timeout=0.3, read_timeout=0.3
    )
    t0 = time.monotonic()
    try:
        with pytest.raises(CloudError, match="timeout"):
            transport("GET", f"http://127.0.0.1:{port}/v2/x", {}, None)
        assert time.monotonic() - t0 < 5.0
    finally:
        srv.close()
        for conn, _ in accepted:
            conn.close()


class ScriptedTransport:
    """(status, body, headers) responses in order; records calls."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def __call__(self, method, url, headers, body):
        self.calls += 1
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        return item


def _client(script, retry):
    ident = MetadataIdentity(
        "sa",
        transport=ScriptedTransport(
            [(200, json.dumps(
                {"access_token": "tok", "expires_in": 3600}).encode(), {})]
        ),
    )
    api = ScriptedTransport(script)
    return CloudTpuClient(
        "p", "z", ident, transport=api, retry=retry, clock=FakeClock()
    ), api


def test_cloudtpu_client_retries_5xx_and_honors_retry_after():
    ok = json.dumps({"queuedResources": []}).encode()
    client, api = _client(
        [
            (503, b"{}", {"Retry-After": "0"}),
            (429, b"{}", {}),
            (200, ok, {}),
        ],
        retry=RetryPolicy(max_attempts=3, base_delay=0.0),
    )
    assert client.list_resources({}) == []
    assert api.calls == 3  # 503 and 429 retried, 200 ended the ladder


def test_retry_after_is_capped_not_a_wedge():
    """A hostile 'Retry-After: 86400' must not outsleep the requeue
    ladder: the honored floor clamps at RETRY_AFTER_CAP (30 s)."""
    client, _ = _client([], retry=RetryPolicy(max_attempts=3, base_delay=0.0))
    done = threading.Event()

    def run():
        client._sleep_before_retry(1, "p", {"retry-after": "86400"})
        done.set()

    threading.Thread(target=run, daemon=True).start()
    client._clock.advance(30.1)  # > the cap, << the hostile hint
    assert done.wait(2.0), "sleep exceeded RETRY_AFTER_CAP"


def test_cloudtpu_client_4xx_is_permanent_and_auth_maps():
    client, api = _client(
        [(403, json.dumps({"error": {"status": "PERMISSION_DENIED"}}
                          ).encode(), {})],
        retry=RetryPolicy(max_attempts=3, base_delay=0.0),
    )
    with pytest.raises(AuthError):
        client.list_resources({})
    assert api.calls == 1  # permanent: never retried

    client, api = _client(
        [(404, b"{}", {})],
        retry=RetryPolicy(max_attempts=3, base_delay=0.0),
    )
    client.delete_resource("gone")  # idempotent 404, single attempt
    assert api.calls == 1


def test_cloudtpu_rest_fault_site_heals_through_retry(faults):
    """flaky-2-then-succeed at the transport site: the client's retry
    ladder absorbs both injected faults inside ONE _call."""
    faults.arm("cloudtpu.rest", FaultPlan(flaky=2))
    ok = json.dumps({"queuedResources": []}).encode()
    client, api = _client(
        [(200, ok, {})], retry=RetryPolicy(max_attempts=3, base_delay=0.0),
    )
    assert client.list_resources({}) == []
    assert faults.injected("cloudtpu.rest") == 2
    assert api.calls == 1  # the two faults fired before the transport


# -- serve-plane admission control ------------------------------------------

@pytest.fixture(scope="module")
def tiny_lm():
    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, max_seq=64, use_flash=False, dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def test_batcher_sheds_at_max_pending(tiny_lm):
    from k8s_gpu_tpu.serve import ContinuousBatcher, Overloaded

    model, params = tiny_lm
    b = ContinuousBatcher(model, params, slots=2, max_pending=1)
    # Scheduler not started: the first submit parks in _pending, the
    # second must be refused at the door (no unbounded queue).
    b.submit([1, 2, 3], max_new_tokens=4)
    with pytest.raises(Overloaded, match="queue full"):
        b.submit([4, 5, 6], max_new_tokens=4)


def test_batcher_drops_expired_work_without_computing(tiny_lm):
    from k8s_gpu_tpu.serve import ContinuousBatcher

    model, params = tiny_lm
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        h = b.submit(
            [1, 2, 3], max_new_tokens=8,
            deadline=time.monotonic() - 0.001,  # already expired
        )
        assert h.result() == []
        assert h.deadline_expired and h.aborted
        # Dropped, not computed: no admit or decode round was dispatched.
        assert b.steps_taken == 0
    finally:
        b.stop()


def test_server_maps_sheds_to_429_503_504_with_retry_after(tiny_lm):
    from k8s_gpu_tpu.data import BpeTokenizer
    from k8s_gpu_tpu.serve import LmServer, Overloaded

    model, params = tiny_lm
    tok = BpeTokenizer.train("aa bb cc dd " * 30, vocab_size=80)
    srv = LmServer(model, params, tok, max_pending=4)
    # HTTP surface only — the batcher scheduler never starts, so no
    # device program compiles in this test.
    srv._thread.start()
    try:
        def post(payload, headers=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json",
                         **(headers or {})},
            )
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, dict(r.headers), json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), json.loads(e.read())

        # Queue full → 429 + Retry-After.
        real_submit = srv.batcher.submit
        srv.batcher.submit = lambda *a, **k: (_ for _ in ()).throw(
            Overloaded("pending queue full (4 requests); retry later")
        )
        code, hdrs, body = post({"prompt": "aa", "max_new_tokens": 2})
        assert code == 429 and hdrs.get("Retry-After") == "1"
        assert "queue full" in body["error"]
        srv.batcher.submit = real_submit

        # Expired-before-submit budget → 504.
        code, _, body = post(
            {"prompt": "aa"}, headers={"x-request-deadline-ms": "0"}
        )
        assert code == 504 and body["error"] == "deadline exceeded"
        code, _, _ = post(
            {"prompt": "aa"}, headers={"x-request-deadline-ms": "nan?"}
        )
        assert code == 400

        # Dead scheduler → 503 + Retry-After (clients back off instead
        # of tight-looping on a server that cannot recover by itself).
        srv.batcher._dead = True
        code, hdrs, _ = post({"prompt": "aa", "max_new_tokens": 2})
        assert code == 503 and hdrs.get("Retry-After") == "1"
    finally:
        srv._httpd.shutdown()
        srv._httpd.server_close()
