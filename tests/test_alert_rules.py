"""Rules engine (ISSUE 4): recording/alerting rules evaluate
deterministically under FakeClock, the default pack fires and resolves on
synthetic registry state, /alerts serves the engine's view, and the
metrics-registry hardening (percentile snapshot, cardinality cap) holds
under concurrency.  Named test_alert_rules so it sorts early inside the
tier-1 870 s window."""

import json
import threading
import urllib.request

import pytest

from k8s_gpu_tpu.utils.alerts import (
    AlertingRule,
    RecordingRule,
    RuleEvaluator,
    default_rule_pack,
)
from k8s_gpu_tpu.utils.clock import FakeClock
from k8s_gpu_tpu.utils.metrics import MetricsRegistry, parse_exposition
from k8s_gpu_tpu.utils.obs import MetricsServer, render_top


def _tick(ev, clock, dt=0.0):
    if dt:
        clock.advance(dt)
    ev.evaluate_once()


def _states(ev):
    return {
        (a["alertname"], tuple(sorted(a["labels"].items()))): a["state"]
        for a in ev.active_alerts()
    }


def _fingerprint(ev):
    return [
        (t["t"], t["alert"], tuple(sorted(t["labels"].items())),
         t["from"], t["to"])
        for t in ev.timeline
    ]


# -- registry hardening -----------------------------------------------------

def test_percentile_hammer_under_concurrent_observe():
    """registry.percentile snapshots under the registry lock — concurrent
    observe() appends must never blow up the sort (the deque-mutated-
    during-iteration race) and the result stays within observed range."""
    reg = MetricsRegistry()
    stop = threading.Event()

    def hammer(tid):
        i = 0
        while not stop.is_set():
            reg.observe("lat", (i % 100) / 100.0, worker=str(tid))
            reg.observe("lat", (i % 100) / 100.0)
            i += 1

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(2000):
            p = reg.percentile("lat", 0.95)
            assert 0.0 <= p <= 1.0
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


def test_histogram_direct_percentile_retries_on_mutation():
    """The bare Histogram path stays usable too (bench holds direct
    handles): a hammered direct percentile never raises."""
    reg = MetricsRegistry()
    reg.observe("h", 0.5)
    h = reg.histogram("h")
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            reg.observe("h", (i % 100) / 100.0)
            i += 1

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(2000):
            assert 0.0 <= h.percentile(0.5) <= 1.0
    finally:
        stop.set()
        t.join(timeout=5)


def test_label_cardinality_guard_collapses_overflow():
    reg = MetricsRegistry(max_series_per_name=4)
    for i in range(10):
        reg.inc("req_total", route=f"/r{i}")
    series = reg.series("req_total")
    # 4 real series + the single collapsed overflow series.
    assert len(series) == 5
    assert reg.counter("req_total", other="true") == 6.0
    assert reg.counter(
        "metrics_series_dropped_total", metric="req_total"
    ) == 6.0
    # Existing series keep incrementing normally past the cap.
    reg.inc("req_total", route="/r0")
    assert reg.counter("req_total", route="/r0") == 2.0
    # Gauges and histograms ride the same guard.
    for i in range(10):
        reg.set_gauge("g", float(i), src=f"s{i}")
        reg.observe("h", 0.1, src=f"s{i}")
    assert reg.gauge("g", other="true") is not None
    assert reg.histogram("h", other="true") is not None
    # Unlabeled series never count against the cap.
    reg.inc("req_total")
    assert reg.counter("req_total") == 1.0


def test_remove_gauge_frees_cardinality_slot():
    """Object churn (create/delete pools forever) must not ratchet
    toward the cap: removing a gauge frees its slot unless a counter or
    histogram still holds the same series — otherwise the N+1th pool's
    gauges would collapse into the overflow series, which nothing can
    ever clear."""
    reg = MetricsRegistry(max_series_per_name=4)
    for i in range(20):
        reg.set_gauge("pool_ready_ratio", 0.5, pool=f"p{i}")
        reg.remove_gauge("pool_ready_ratio", pool=f"p{i}")
    # Every write landed on a real series, never the overflow.
    assert reg.counter(
        "metrics_series_dropped_total", metric="pool_ready_ratio"
    ) == 0.0
    assert reg.series("pool_ready_ratio") == {}
    # A counter sharing the series pins the slot (counters never evict).
    reg.inc("shared", pool="x")
    reg.set_gauge("shared", 1.0, pool="x")
    reg.remove_gauge("shared", pool="x")
    reg.set_gauge("shared", 2.0, pool="x")
    assert reg.gauge("shared", pool="x") == 2.0


def test_snapshot_limit_zero_returns_no_transitions():
    clock, reg = FakeClock(), MetricsRegistry()
    rule = AlertingRule("Hot", lambda ctx: ctx.gauge("t"), above=1.0)
    ev = RuleEvaluator([rule], clock=clock, registry=reg)
    reg.set_gauge("t", 5.0)
    ev.evaluate_once()
    assert len(ev.snapshot(limit=100)["transitions"]) == 2
    assert ev.snapshot(limit=0)["transitions"] == []
    assert len(ev.snapshot(limit=1)["transitions"]) == 1


def test_parse_exposition_roundtrip():
    reg = MetricsRegistry()
    reg.inc("c_total", 3.0, kind="A")
    reg.set_gauge("g", 0.5, pool="p", kind="B")
    reg.observe("lat", 0.02)
    fam = parse_exposition(reg.render())
    assert fam["c_total"][(("kind", "A"),)] == 3.0
    assert fam["g"][(("kind", "B"), ("pool", "p"))] == 0.5
    assert fam["lat_count"][()] == 1.0
    assert any(k for k in fam if k == "lat_bucket")


# -- rules engine core ------------------------------------------------------

def test_recording_rule_writes_gauge_visible_to_later_rules():
    clock, reg = FakeClock(), MetricsRegistry()
    reg.inc("widgets_total", 8.0, kind="a")
    reg.inc("widgets_total", 2.0, kind="b")
    rules = [
        RecordingRule(
            "widget_a_ratio",
            lambda ctx: ctx.ratio(
                ctx.sum("widgets_total", kind="a"),
                ctx.sum("widgets_total"),
            ),
        ),
        AlertingRule(
            "WidgetSkew", lambda ctx: ctx.gauge("widget_a_ratio"),
            above=0.5, for_s=0.0,
        ),
    ]
    ev = RuleEvaluator(rules, clock=clock, registry=reg)
    ev.evaluate_once()
    assert reg.gauge("widget_a_ratio") == pytest.approx(0.8)
    # Pack order: the recorded gauge fed the alert in the SAME tick.
    assert _states(ev) == {("WidgetSkew", ()): "firing"}


def test_alert_fsm_hold_duration_and_transitions():
    clock, reg = FakeClock(), MetricsRegistry()
    rule = AlertingRule(
        "Hot", lambda ctx: ctx.gauge("temp"), above=100.0, for_s=30.0
    )
    ev = RuleEvaluator([rule], clock=clock, registry=reg)
    reg.set_gauge("temp", 50.0)
    _tick(ev, clock)
    assert _states(ev) == {}
    reg.set_gauge("temp", 150.0)
    _tick(ev, clock, 10.0)          # breach starts → pending
    assert _states(ev) == {("Hot", ()): "pending"}
    _tick(ev, clock, 10.0)          # held 10 s < 30 s → still pending
    assert _states(ev) == {("Hot", ()): "pending"}
    assert reg.gauge("alerts_firing", alertname="Hot") == 0.0
    _tick(ev, clock, 25.0)          # held 35 s ≥ 30 s → firing
    assert _states(ev) == {("Hot", ()): "firing"}
    assert reg.gauge("alerts_firing", alertname="Hot") == 1.0
    reg.set_gauge("temp", 20.0)
    _tick(ev, clock, 5.0)           # clears → resolved (then inactive)
    assert _states(ev) == {}
    assert reg.gauge("alerts_firing", alertname="Hot") == 0.0
    assert [(t["from"], t["to"]) for t in ev.timeline] == [
        ("inactive", "pending"), ("pending", "firing"),
        ("firing", "resolved"),
    ]
    assert reg.counter(
        "alert_transitions_total", alertname="Hot", to="firing"
    ) == 1.0


def test_pending_deflickers_without_firing():
    """A breach shorter than for_s never fires (and never notifies)."""
    clock, reg = FakeClock(), MetricsRegistry()
    fired = []
    rule = AlertingRule(
        "Flap", lambda ctx: ctx.gauge("v"), above=1.0, for_s=60.0
    )
    ev = RuleEvaluator(
        [rule], clock=clock, registry=reg,
        notify=lambda *a: fired.append(a),
    )
    reg.set_gauge("v", 5.0)
    _tick(ev, clock)
    reg.set_gauge("v", 0.0)
    _tick(ev, clock, 10.0)
    assert _states(ev) == {}
    assert fired == []
    assert [(t["from"], t["to"]) for t in ev.timeline] == [
        ("inactive", "pending"), ("pending", "inactive"),
    ]


def test_per_labelset_fsm_is_independent():
    clock, reg = FakeClock(), MetricsRegistry()
    rule = AlertingRule(
        "Deep", lambda ctx: ctx.series("depth"), above=5.0, for_s=0.0
    )
    ev = RuleEvaluator([rule], clock=clock, registry=reg)
    reg.set_gauge("depth", 10.0, queue="a")
    reg.set_gauge("depth", 1.0, queue="b")
    _tick(ev, clock)
    st = _states(ev)
    assert st[("Deep", (("queue", "a"),))] == "firing"
    assert ("Deep", (("queue", "b"),)) not in st
    assert reg.gauge("alerts_firing", alertname="Deep") == 1.0
    reg.set_gauge("depth", 9.0, queue="b")
    _tick(ev, clock, 1.0)
    assert reg.gauge("alerts_firing", alertname="Deep") == 2.0


def test_counter_rate_and_burn_rate():
    clock, reg = FakeClock(), MetricsRegistry()
    pack = default_rule_pack(slo=0.99, burn_window=300.0,
                             burn_threshold=14.4)
    ev = RuleEvaluator(pack, clock=clock, registry=reg)
    # 100 req/tick, 30% 5xx → error ratio 0.3 → burn 0.3/0.01 = 30 > 14.4.
    for _ in range(8):
        reg.inc("http_requests_total", 70.0, code="200", server="lm")
        reg.inc("http_requests_total", 30.0, code="503", server="lm")
        _tick(ev, clock, 10.0)
    assert reg.gauge("http_error_ratio") == pytest.approx(0.3)
    assert reg.gauge("slo_burn_rate") == pytest.approx(30.0)
    st = _states(ev)
    assert st.get(("HighErrorBurnRate", ())) in ("pending", "firing")
    # Hold 60 s from when the burn first breached; keep burning.
    for _ in range(6):
        reg.inc("http_requests_total", 70.0, code="200", server="lm")
        reg.inc("http_requests_total", 30.0, code="503", server="lm")
        _tick(ev, clock, 10.0)
    assert _states(ev)[("HighErrorBurnRate", ())] == "firing"
    # Traffic goes clean → ratio decays inside the window → resolves.
    for _ in range(40):
        reg.inc("http_requests_total", 100.0, code="200", server="lm")
        _tick(ev, clock, 10.0)
    assert ("HighErrorBurnRate", ()) not in _states(ev)
    assert reg.counter(
        "alert_transitions_total", alertname="HighErrorBurnRate",
        to="resolved",
    ) == 1.0


# -- the default pack, rule by rule ----------------------------------------

@pytest.mark.parametrize(
    "alert,gauge,labels,bad,good",
    [
        ("QueueBacklog", "workqueue_depth", {"queue": "TpuPodSlice"},
         50.0, 1.0),
        ("KVCacheSaturation", "serve_kv_occupancy_ratio", {}, 0.97, 0.2),
        ("BreakerOpen", "circuit_breaker_state",
         {"endpoint": "cloudtpu.list"}, 2.0, 0.0),
        ("PoolDegraded", "pool_ready_ratio",
         {"kind": "TpuPodSlice", "pool": "demo"}, 0.5, 1.0),
    ],
)
def test_default_pack_fires_and_resolves(alert, gauge, labels, bad, good):
    clock, reg = FakeClock(), MetricsRegistry()
    ev = RuleEvaluator(default_rule_pack(), clock=clock, registry=reg)
    key = (alert, tuple(sorted(labels.items())))
    reg.set_gauge(gauge, bad, **labels)
    _tick(ev, clock)
    assert _states(ev)[key] == "pending"
    _tick(ev, clock, 120.0)  # past every rule's hold duration
    assert _states(ev)[key] == "firing"
    assert reg.gauge("alerts_firing", alertname=alert) == 1.0
    reg.set_gauge(gauge, good, **labels)
    _tick(ev, clock, 10.0)
    assert key not in _states(ev)
    assert reg.gauge("alerts_firing", alertname=alert) == 0.0
    path = [(t["from"], t["to"]) for t in ev.timeline
            if t["alert"] == alert]
    assert path == [("inactive", "pending"), ("pending", "firing"),
                    ("firing", "resolved")]


def test_fleet_replica_down_fsm_lifecycle():
    """FleetReplicaDown fires the tick the collector drops
    fleet_replica_up to 0 (the M-consecutive-failures hold lives in the
    collector's down_after, so for_s defaults to 0 and the FSM walks
    inactive→pending→firing in ONE tick) and resolves on recovery."""
    clock, reg = FakeClock(), MetricsRegistry()
    ev = RuleEvaluator(default_rule_pack(), clock=clock, registry=reg)
    reg.set_gauge("fleet_replica_up", 1.0, replica="r0")
    reg.set_gauge("fleet_replica_up", 1.0, replica="r1")
    _tick(ev, clock)
    assert _states(ev) == {}
    reg.set_gauge("fleet_replica_up", 0.0, replica="r1")
    _tick(ev, clock, 10.0)
    key = ("FleetReplicaDown", (("replica", "r1"),))
    assert _states(ev)[key] == "firing"
    assert ("FleetReplicaDown", (("replica", "r0"),)) not in _states(ev)
    assert reg.gauge("alerts_firing",
                     alertname="FleetReplicaDown") == 1.0
    reg.set_gauge("fleet_replica_up", 1.0, replica="r1")
    _tick(ev, clock, 10.0)
    assert key not in _states(ev)
    path = [(t["from"], t["to"]) for t in ev.timeline
            if t["alert"] == "FleetReplicaDown"]
    assert path == [("inactive", "pending"), ("pending", "firing"),
                    ("firing", "resolved")]


def test_tenant_slo_burn_rate_fsm_lifecycle():
    """TenantSloBurnRate: the recorded per-tenant goodput burn (from
    counter rates — needs history across ticks) breaches for the hot
    tenant only, fires after its 60 s hold, and resolves once goodput
    recovers inside the rate window."""
    clock, reg = FakeClock(), MetricsRegistry()
    ev = RuleEvaluator(default_rule_pack(), clock=clock, registry=reg)
    key = ("TenantSloBurnRate", (("tenant", "hot"),))
    # hot: 50% of tokens miss the deadline → burn 50/1% = 50x > 14.4;
    # cool: full goodput → burn 0.
    for _ in range(8):
        reg.inc("serve_tenant_tokens_total", 100.0, tenant="hot")
        reg.inc("serve_tenant_goodput_tokens_total", 50.0, tenant="hot")
        reg.inc("serve_tenant_tokens_total", 100.0, tenant="cool")
        reg.inc("serve_tenant_goodput_tokens_total", 100.0,
                tenant="cool")
        _tick(ev, clock, 10.0)
    assert reg.gauge("tenant_slo_burn_rate",
                     tenant="hot") == pytest.approx(50.0)
    assert reg.gauge("tenant_slo_burn_rate", tenant="cool") == 0.0
    assert _states(ev).get(key) in ("pending", "firing")
    assert ("TenantSloBurnRate", (("tenant", "cool"),)) not in _states(ev)
    for _ in range(6):
        reg.inc("serve_tenant_tokens_total", 100.0, tenant="hot")
        reg.inc("serve_tenant_goodput_tokens_total", 50.0, tenant="hot")
        _tick(ev, clock, 10.0)
    assert _states(ev)[key] == "firing"
    # Recovery: goodput == total until the bad rate ages out of the
    # 300 s window.
    for _ in range(40):
        reg.inc("serve_tenant_tokens_total", 100.0, tenant="hot")
        reg.inc("serve_tenant_goodput_tokens_total", 100.0, tenant="hot")
        _tick(ev, clock, 10.0)
    assert key not in _states(ev)
    path = [(t["from"], t["to"]) for t in ev.timeline
            if t["alert"] == "TenantSloBurnRate"]
    assert path == [("inactive", "pending"), ("pending", "firing"),
                    ("firing", "resolved")]


def test_two_runs_identical_timelines():
    """Determinism: the same scripted registry mutations under FakeClock
    produce bit-identical transition timelines."""

    def run():
        clock, reg = FakeClock(), MetricsRegistry()
        ev = RuleEvaluator(default_rule_pack(), clock=clock, registry=reg)
        reg.set_gauge("circuit_breaker_state", 2.0, endpoint="e1")
        reg.set_gauge("pool_ready_ratio", 0.0, kind="TpuPodSlice",
                      pool="p")
        _tick(ev, clock)
        _tick(ev, clock, 15.0)
        _tick(ev, clock, 20.0)
        reg.set_gauge("circuit_breaker_state", 0.0, endpoint="e1")
        reg.set_gauge("pool_ready_ratio", 1.0, kind="TpuPodSlice",
                      pool="p")
        _tick(ev, clock, 10.0)
        return _fingerprint(ev)

    a, b = run(), run()
    assert a == b and len(a) >= 6


def test_vanished_series_resolves():
    """A label-set that disappears from the registry (restarted process,
    replaced endpoint) resolves instead of firing forever."""
    clock = FakeClock()
    values = {"x": {(("q", "a"),): 10.0}}
    rule = AlertingRule("Gone", lambda ctx: values["x"], above=1.0,
                        for_s=0.0)
    ev = RuleEvaluator([rule], clock=clock, registry=MetricsRegistry())
    _tick(ev, clock)
    assert len(_states(ev)) == 1
    values["x"] = {}
    _tick(ev, clock, 1.0)
    assert _states(ev) == {}
    assert ev.timeline[-1]["to"] == "resolved"


# -- workqueue + notifier + manager wiring ---------------------------------

def test_workqueue_exports_depth_and_oldest_age(clock):
    from k8s_gpu_tpu.controller.workqueue import RateLimitingQueue

    reg = MetricsRegistry()
    q = RateLimitingQueue(clock=clock, name="demo", registry=reg)
    q.add("a")
    clock.advance(5.0)
    q.add("b")
    q.export_gauges()
    assert reg.gauge("workqueue_depth", queue="demo") == 2.0
    assert reg.gauge(
        "workqueue_oldest_age_seconds", queue="demo"
    ) == pytest.approx(5.0)
    assert q.get(block=False) == "a"
    q.export_gauges()
    assert reg.gauge("workqueue_depth", queue="demo") == 1.0
    # b was enqueued at t=5 → age 0 now.
    assert reg.gauge(
        "workqueue_oldest_age_seconds", queue="demo"
    ) == pytest.approx(0.0)
    q.done("a")
    assert q.get(block=False) == "b"
    q.export_gauges()
    assert reg.gauge("workqueue_depth", queue="demo") == 0.0
    assert reg.gauge(
        "workqueue_oldest_age_seconds", queue="demo"
    ) == pytest.approx(0.0)


def test_workqueue_scheduled_future_items_are_not_backlog(clock):
    """Steady-state resyncs parked on add_after deadlines must NOT count
    as depth (QueueBacklog would fire forever on a healthy idle fleet);
    they join the backlog the tick they come due."""
    from k8s_gpu_tpu.controller.workqueue import RateLimitingQueue

    reg = MetricsRegistry()
    q = RateLimitingQueue(clock=clock, name="demo", registry=reg)
    for i in range(15):
        q.add_after(f"resync-{i}", 60.0)
    q.export_gauges()
    assert reg.gauge("workqueue_depth", queue="demo") == 0.0
    assert reg.gauge(
        "workqueue_oldest_age_seconds", queue="demo"
    ) == 0.0
    clock.advance(70.0)
    q.export_gauges()
    assert reg.gauge("workqueue_depth", queue="demo") == 15.0
    # Due at t=60, now t=70 → the oldest has waited 10 s past deadline.
    assert reg.gauge(
        "workqueue_oldest_age_seconds", queue="demo"
    ) == pytest.approx(10.0)


def test_pool_gauges_cleared_on_deletion(kube):
    """A deleted pool's gauges are retired — a stale ratio would keep
    PoolDegraded firing against an object that no longer exists."""
    from k8s_gpu_tpu.controller.manager import Request
    from k8s_gpu_tpu.operators.pool_gauges import export_pool_gauges
    from k8s_gpu_tpu.operators.azurevmpool import AzureVmPoolReconciler

    reg = MetricsRegistry()
    export_pool_gauges(reg, "AzureVmPool", "default", "gone",
                       ready=1, desired=2)
    clock2, ev = FakeClock(), None
    rule_ev = RuleEvaluator(default_rule_pack(pool_for_s=0.0),
                            clock=clock2, registry=reg)
    rule_ev.evaluate_once()
    assert [a["alertname"] for a in rule_ev.active_alerts()] == [
        "PoolDegraded"
    ]
    rec = AzureVmPoolReconciler(kube, client_factory=None, metrics=reg)
    rec.reconcile(Request("default", "gone"))  # object absent → clear
    assert reg.gauge("pool_ready_ratio", kind="AzureVmPool",
                     namespace="default", pool="gone") is None
    clock2.advance(1.0)
    rule_ev.evaluate_once()  # vanished series resolves the alert
    assert rule_ev.active_alerts() == []
    assert rule_ev.timeline[-1]["to"] == "resolved"


def test_alert_event_notifier_records_warning_event(kube):
    from k8s_gpu_tpu.api import TpuPodSlice
    from k8s_gpu_tpu.controller.alerting import AlertEventNotifier

    ps = TpuPodSlice()
    ps.metadata.name = "demo"
    kube.create(ps)
    rule = AlertingRule(
        "PoolDegraded", lambda ctx: 0.0, below=1.0,
        annotation="pool {pool} at {value:.0%}",
    )
    notifier = AlertEventNotifier(kube)
    notifier(rule, {"kind": "TpuPodSlice", "pool": "demo"}, "firing", 0.5)
    evs = [e for e in kube.list("Event") if e.reason == "PoolDegraded"]
    assert len(evs) == 1
    assert evs[0].type == "Warning"
    assert evs[0].involved_name == "demo"
    notifier(rule, {"kind": "TpuPodSlice", "pool": "demo"}, "resolved", 1.0)
    evs = [e for e in kube.list("Event") if e.reason == "PoolDegraded"]
    assert {e.type for e in evs} == {"Warning", "Normal"}
    # No object reference → logged, never raises.
    notifier(rule, {"endpoint": "cloudtpu.list"}, "firing", 2.0)


def test_manager_owns_evaluator_and_queue_collector(kube, clock):
    from k8s_gpu_tpu.controller import Manager, Reconciler, Request, Result

    class Nop(Reconciler):
        def reconcile(self, req):
            return Result()

    reg = MetricsRegistry()
    ev = RuleEvaluator(default_rule_pack(), clock=clock, registry=reg)
    mgr = Manager(kube, clock=clock, metrics=reg, alerts=ev)
    mgr.register("TpuPodSlice", Nop())
    try:
        mgr.start()
        assert ev._thread is not None and ev._thread.is_alive()
        # The collector refreshes queue gauges on evaluation.
        mgr._controllers["TpuPodSlice"].queue.add(Request("default", "x"))
        ev.evaluate_once()
        assert reg.gauge("workqueue_depth", queue="TpuPodSlice") is not None
    finally:
        mgr.stop()
    assert ev._thread is None


# -- /alerts endpoint + chaos e2e ------------------------------------------

def _get_json(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, json.loads(r.read())


def test_alerts_endpoint_shows_breaker_open_under_chaos(clock):
    """End-to-end: an injected cloud outage opens the breaker; /alerts
    shows BreakerOpen firing with its transition history."""
    from k8s_gpu_tpu.cloud.base import CloudError
    from k8s_gpu_tpu.cloud.resilience import (
        BreakerBank, ResilientBackend, RetryPolicy,
    )

    reg = MetricsRegistry()

    class Broken:
        def list_resources(self, tags):
            raise CloudError("chaos: injected outage")

        def is_ready(self, r):
            return True

    bank = BreakerBank(clock=clock, name="cloudtpu",
                       failure_threshold=3, registry=reg)
    backend = ResilientBackend(
        Broken(), bank, policy=RetryPolicy(max_attempts=1, budget=0),
        clock=clock, registry=reg,
    )
    ev = RuleEvaluator(default_rule_pack(breaker_for_s=10.0),
                       clock=clock, registry=reg)
    for _ in range(3):
        with pytest.raises(CloudError):
            backend.list_resources({})
    assert reg.gauge(
        "circuit_breaker_state", endpoint="cloudtpu.list"
    ) == 2.0
    ev.evaluate_once()
    clock.advance(15.0)
    ev.evaluate_once()
    srv = MetricsServer(reg, alerts=ev).start()
    try:
        code, body = _get_json(srv.port, "/alerts")
        assert code == 200
        firing = [a for a in body["alerts"] if a["state"] == "firing"]
        assert [a["alertname"] for a in firing] == ["BreakerOpen"]
        assert firing[0]["labels"] == {"endpoint": "cloudtpu.list"}
        tos = [t["to"] for t in body["transitions"]
               if t["alert"] == "BreakerOpen"]
        assert tos == ["pending", "firing"]
        # state filter
        code, body = _get_json(srv.port, "/alerts?state=pending")
        assert body["alerts"] == []
    finally:
        srv.stop()


def test_alerts_endpoint_without_engine_404s():
    srv = MetricsServer(MetricsRegistry()).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(srv.port, "/alerts")
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_metrics_server_self_instrumentation():
    """The obs server's own handler rides RequestMetricsMixin: scrapes
    show up in http_requests_total{server=obs} with route collapse."""
    from k8s_gpu_tpu.utils.metrics import global_metrics

    srv = MetricsServer(MetricsRegistry()).start()
    base = global_metrics.counter(
        "http_requests_total", server="obs", method="GET",
        route="/metrics", code="200",
    )
    other = global_metrics.counter(
        "http_requests_total", server="obs", method="GET",
        route="other", code="404",
    )
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics"
        ) as r:
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/../../etc/passwd"
            )
    finally:
        srv.stop()
    assert global_metrics.counter(
        "http_requests_total", server="obs", method="GET",
        route="/metrics", code="200",
    ) == base + 1
    # Unknown paths collapse to the fixed "other" label.
    assert global_metrics.counter(
        "http_requests_total", server="obs", method="GET",
        route="other", code="404",
    ) == other + 1


# -- obs top ----------------------------------------------------------------

def test_render_top_from_one_scrape():
    reg = MetricsRegistry()
    reg.set_gauge("serve_kv_occupancy_ratio", 0.42)
    reg.set_gauge("serve_slot_fill_ratio", 0.5)
    reg.set_gauge("serve_slots_active", 4.0)
    reg.set_gauge("workqueue_depth", 3.0, queue="TpuPodSlice")
    reg.set_gauge("workqueue_oldest_age_seconds", 7.5, queue="TpuPodSlice")
    reg.set_gauge("pool_ready_replicas", 1.0, kind="TpuPodSlice",
                  pool="demo")
    reg.set_gauge("pool_desired_replicas", 2.0, kind="TpuPodSlice",
                  pool="demo")
    reg.set_gauge("pool_ready_ratio", 0.5, kind="TpuPodSlice", pool="demo")
    reg.set_gauge("alerts_firing", 1.0, alertname="PoolDegraded")
    out = render_top(reg.render())
    assert "42.0%" in out          # kv occupancy
    assert "50.0%" in out          # batch fill + pool ratio
    assert "TpuPodSlice" in out and "7.5" in out
    assert "demo" in out
    assert "PoolDegraded" in out


def test_render_top_tolerates_sparse_snapshot():
    out = render_top(MetricsRegistry().render())
    assert "no workqueue gauges" in out
    assert "no pool gauges" in out


def test_pool_gauges_namespaced_no_cross_talk():
    """Same-named pools in different namespaces are distinct series;
    clearing one must not wipe the other's gauges."""
    from k8s_gpu_tpu.operators.pool_gauges import (
        clear_pool_gauges, export_pool_gauges,
    )

    reg = MetricsRegistry()
    export_pool_gauges(reg, "AzureVmPool", "ns-a", "demo", 0, 3)
    export_pool_gauges(reg, "AzureVmPool", "ns-b", "demo", 3, 3)
    assert reg.gauge("pool_ready_ratio", kind="AzureVmPool",
                     namespace="ns-a", pool="demo") == 0.0
    assert reg.gauge("pool_ready_ratio", kind="AzureVmPool",
                     namespace="ns-b", pool="demo") == 1.0
    clear_pool_gauges(reg, "AzureVmPool", "ns-a", "demo")
    assert reg.gauge("pool_ready_ratio", kind="AzureVmPool",
                     namespace="ns-a", pool="demo") is None
    assert reg.gauge("pool_ready_ratio", kind="AzureVmPool",
                     namespace="ns-b", pool="demo") == 1.0


def test_pool_gauges_cover_degraded_states(kube):
    """The reconciler exports ready/desired/ratio on every status
    projection — a provisioning pool reads degraded, not stale."""
    from k8s_gpu_tpu.cloud.fake_cloudtpu import (
        FakeCloudTpu, cloudtpu_client_factory,
    )
    from k8s_gpu_tpu.api import TpuPodSlice
    from k8s_gpu_tpu.controller.manager import Request
    from k8s_gpu_tpu.operators import TpuPodSliceReconciler
    from k8s_gpu_tpu.utils.clock import FakeClock

    clock = FakeClock()
    reg = MetricsRegistry()
    cloud = FakeCloudTpu(clock=clock, accepted_delay=100.0)
    rec = TpuPodSliceReconciler(
        kube, cloudtpu_client_factory(cloud), metrics=reg
    )
    ps = TpuPodSlice()
    ps.metadata.name = "p1"
    ps.spec.accelerator_type = "v4-8"
    kube.create(ps)
    rec.reconcile(Request("default", "p1"))
    labels = {"kind": "TpuPodSlice", "namespace": "default", "pool": "p1"}
    assert reg.gauge("pool_ready_ratio", **labels) == 0.0
    assert reg.gauge("pool_desired_replicas", **labels) == 1.0
    clock.advance(200.0)
    rec.reconcile(Request("default", "p1"))
    assert reg.gauge("pool_ready_ratio", **labels) == 1.0
    assert reg.gauge("pool_ready_replicas", **labels) == 1.0
