"""graftcheck rule fixtures: each invariant catches its known-violation
snippet and stays quiet on the sanctioned form (ISSUE 8 satellite).

These tests drive the passes over synthetic mini-repos (tmp_path trees
mirroring the real layout), so they pin the RULES; the self-check test
(test_analysis_selfcheck.py) pins the REPO.  Named to sort early in the
alphabetical tier-1 window.
"""

import textwrap
import threading

from k8s_gpu_tpu.analysis import (
    format_report, run_all, run_report, save_baseline,
)
from k8s_gpu_tpu.utils.faults import (
    InstrumentedLock, LockViolation, guard_declared, guard_object,
)


def make_repo(tmp_path, files: dict, doc: str | None = None):
    for relpath, src in files.items():
        p = tmp_path / relpath
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    if doc is not None:
        d = tmp_path / "docs" / "platform" / "observability.md"
        d.parent.mkdir(parents=True, exist_ok=True)
        d.write_text(textwrap.dedent(doc))
    return tmp_path


def rules_of(findings):
    return {f.rule for f in findings}


# -- pass 1: determinism -------------------------------------------------------

def test_wallclock_in_router_plane_is_flagged(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/router.py": """
            import time

            def route():
                return time.time() + time.monotonic()
        """,
    })
    fs = run_all(root)
    assert [f.rule for f in fs] == ["det-wallclock", "det-wallclock"]
    # Both calls share a line; findings sort by detail within it.
    assert fs[0].detail == "time.monotonic in route"
    assert fs[1].detail == "time.time in route"
    assert fs[0].line == 5


def test_wallclock_outside_planes_is_not_flagged(tmp_path):
    # serve/batcher.py is the real-time plane — deliberately NOT in the
    # deterministic set; its latency measurements ARE wall clock.
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/batcher.py": """
            import time

            def measure():
                return time.time()
        """,
    })
    assert run_all(root) == []


def test_unseeded_random_flagged_seeded_allowed(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/cloud/resilience.py": """
            import random

            GOOD = random.Random(7).random()
            GOOD2 = random.Random("endpoint:3")

            def jitter():
                return random.random() + random.randrange(3)
        """,
    })
    fs = run_all(root)
    assert [f.rule for f in fs] == ["det-random", "det-random"]
    assert {f.detail for f in fs} == {
        "random.random in jitter", "random.randrange in jitter",
    }


def test_from_import_forms_are_caught(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/journal.py": """
            from random import random
            from time import monotonic

            def stamp():
                return monotonic() + random()
        """,
    })
    assert rules_of(run_all(root)) == {"det-wallclock", "det-random"}


def test_from_import_seeded_random_stays_sanctioned(tmp_path):
    # `from random import Random` keeps the seeded-form sanction: only
    # the seedless constructor is ambient randomness.
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/journal.py": """
            from random import Random, choice

            def draw(seq):
                rng = Random(7)          # sanctioned
                bad = Random()           # seedless → flagged
                return rng.random(), choice(seq)   # choice → flagged
        """,
    })
    fs = run_all(root)
    assert sorted(f.detail for f in fs) == [
        "random.Random() in draw", "random.choice in draw",
    ]


def test_datetime_now_is_flagged(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/operators/gc.py": """
            import datetime
            from datetime import datetime as dt

            def when():
                return datetime.datetime.now(), dt.utcnow()
        """,
    })
    fs = run_all(root)
    assert [f.rule for f in fs] == ["det-datetime", "det-datetime"]


def test_set_iteration_flagged_sorted_allowed(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/controller/events.py": """
            def emit(pods):
                out = []
                for p in set(pods):          # flagged
                    out.append(p)
                for p in sorted(set(pods)):  # sanctioned
                    out.append(p)
                for p in {1, 2, 3}:          # flagged (literal)
                    out.append(p)
                seen = {x for x in pods}     # building a set is fine
                return out, seen
        """,
    })
    fs = run_all(root)
    assert [f.rule for f in fs] == ["det-set-iter", "det-set-iter"]
    assert [f.line for f in fs] == [4, 8]


def test_pragma_suppresses_one_rule(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/router.py": """
            import time

            def a():
                return time.time()  # graftcheck: ignore[det-wallclock]

            def b():
                return time.time()  # graftcheck: ignore[det-random]
        """,
    })
    fs = run_all(root)
    # a()'s pragma names the rule and suppresses; b()'s names another
    # rule and does not.
    assert [f.detail for f in fs] == ["time.time in b"]


# -- pass 2: metrics contract --------------------------------------------------

def test_label_set_mismatch_flagged_unlabeled_aggregate_allowed(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/metrics_site.py": """
            def record(m, v):
                m.observe("ttft_seconds", v)                  # unlabeled OK
                m.observe("ttft_seconds", v, tenant="t")      # canonical
                m.observe("ttft_seconds", v, tenant="t")
                m.observe("ttft_seconds", v, queue="q")       # mismatch
        """,
    })
    fs = run_all(root)
    assert [f.rule for f in fs] == ["met-label-mismatch"]
    assert fs[0].line == 6
    assert "queue" in fs[0].detail


def test_counter_set_as_gauge_and_suffixes(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/metrics_site.py": """
            def record(m):
                m.inc("requests_total")
                m.set_gauge("requests_total", 3.0)   # kind conflict
                m.inc("shed_count")                  # counter sans _total
                m.set_gauge("drops_total", 1.0)      # gauge with _total
        """,
    })
    fs = run_all(root)
    # requests_total fires BOTH rules at the set_gauge site: the kind
    # conflict and the gauge-with-_total suffix breach.
    assert sorted(f.rule for f in fs) == [
        "met-counter-suffix", "met-counter-suffix",
        "met-counter-suffix", "met-kind-conflict",
    ]


def test_reserved_labels_scoped_to_fleet_plane(tmp_path):
    src = """
        def record(m):
            m.set_gauge("pool_fill_ratio", 1.0, replica="r0")
    """
    # Outside the fleet plane: flagged.
    root = make_repo(tmp_path / "a", {
        "k8s_gpu_tpu/serve/metrics_site.py": src,
    })
    assert rules_of(run_all(root)) == {"met-reserved-label"}
    # utils/federation.py owns the replica label: allowed.
    root2 = make_repo(tmp_path / "b", {
        "k8s_gpu_tpu/utils/federation.py": src,
    })
    assert run_all(root2) == []


def test_doc_drift_both_directions(tmp_path):
    root = make_repo(
        tmp_path,
        {
            "k8s_gpu_tpu/serve/metrics_site.py": """
                def record(m):
                    m.inc("serve_widgets_total")
                    m.inc("serve_undocumented_total")
            """,
        },
        doc="""
            | metric | meaning |
            |---|---|
            | `serve_widgets_total` | widgets |
            | `serve_ghost_total` | documented but minted nowhere |
        """,
    )
    fs = run_all(root)
    assert {(f.rule, f.detail.split()[0]) for f in fs} == {
        ("met-undocumented", "serve_undocumented_total"),
        ("met-doc-stale", "serve_ghost_total"),
    }


def test_recording_rule_counts_as_mint(tmp_path):
    root = make_repo(
        tmp_path,
        {
            "k8s_gpu_tpu/serve/rules_site.py": """
                def pack():
                    return [RecordingRule("serve_burn_rate", None)]
            """,
        },
        doc="`serve_burn_rate` is recorded each tick.\n",
    )
    assert run_all(root) == []


# -- pass 3: lock discipline ---------------------------------------------------

def test_inferred_guard_flags_unlocked_access(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/shared.py": """
            import threading

            class Table:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._rows = {}

                def put(self, k, v):
                    with self._lock:
                        self._rows[k] = v

                def drop(self, k):
                    with self._lock:
                        self._rows.pop(k, None)

                def racy_len(self):
                    return len(self._rows)
        """,
    })
    fs = run_all(root)
    assert [f.rule for f in fs] == ["lock-guard"]
    assert fs[0].detail == "Table._rows read in racy_len"


def test_single_owner_state_not_poisoned_by_shutdown_lock(tmp_path):
    # The batcher pattern: scheduler-private state touched under an
    # unrelated lifecycle lock exactly once (the drain) must not turn
    # every scheduler access into a finding — the majority filter.
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/shared.py": """
            import threading

            class Loop:
                def __init__(self):
                    self._lifecycle = threading.Lock()
                    self._overflow = []

                def step(self):
                    self._overflow.append(1)
                    if self._overflow:
                        self._overflow.pop()

                def tail(self):
                    return list(self._overflow)

                def drain(self):
                    with self._lifecycle:
                        self._overflow.clear()
        """,
    })
    assert run_all(root) == []


def test_locked_suffix_and_docstring_exemptions(tmp_path):
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/shared.py": """
            import threading

            class Table:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._rows = {}

                def put(self, k, v):
                    with self._lock:
                        self._rows[k] = v
                        self._size_locked()

                def _size_locked(self):
                    return len(self._rows)

                def _export(self):
                    \"\"\"Lock held by caller.\"\"\"
                    return dict(self._rows)
        """,
    })
    assert run_all(root) == []


def test_declared_contract_beats_majority(tmp_path):
    # With _GUARDED_BY declared, even a single unlocked write is a
    # finding — no majority vote.
    root = make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/shared.py": """
            import threading

            class Flag:
                _GUARDED_BY = {"_lock": ("_dead",)}

                def __init__(self):
                    self._lock = threading.Lock()
                    self._dead = False

                def kill(self):
                    self._dead = True
        """,
    })
    fs = run_all(root)
    assert [f.detail for f in fs] == ["Flag._dead write in kill"]


# -- baseline + determinism of the report --------------------------------------

def _violating_repo(tmp_path):
    return make_repo(tmp_path, {
        "k8s_gpu_tpu/serve/router.py": """
            import time

            def route():
                return time.time()
        """,
    })


def test_baseline_suppresses_pinned_debt(tmp_path):
    root = _violating_repo(tmp_path)
    baseline = root / "config" / "analysis_baseline.json"
    baseline.parent.mkdir(parents=True)
    save_baseline(baseline, run_all(root))
    report = run_report(root)
    assert report["ok"] and report["suppressed"] == 1 and not report["new"]


def test_stale_baseline_entry_fails(tmp_path):
    root = _violating_repo(tmp_path)
    baseline = root / "config" / "analysis_baseline.json"
    baseline.parent.mkdir(parents=True)
    save_baseline(baseline, run_all(root))
    # Fix the violation; the pinned entry now matches nothing — the
    # baseline must shrink, so the check fails until it does.
    (root / "k8s_gpu_tpu" / "serve" / "router.py").write_text(
        "def route():\n    return 0.0\n"
    )
    report = run_report(root)
    assert not report["ok"]
    assert report["stale"] == [(
        "k8s_gpu_tpu/serve/router.py", "det-wallclock",
        "time.time in route",
    )]
    assert "baseline-stale" in format_report(report)


def test_baseline_keys_survive_line_drift(tmp_path):
    root = _violating_repo(tmp_path)
    baseline = root / "config" / "analysis_baseline.json"
    baseline.parent.mkdir(parents=True)
    save_baseline(baseline, run_all(root))
    # Prepend unrelated lines: the finding's line number moves, the
    # (path, rule, detail) key does not.
    p = root / "k8s_gpu_tpu" / "serve" / "router.py"
    p.write_text("# comment\n# comment\n" + p.read_text())
    assert run_report(root)["ok"]


def test_report_is_byte_identical_across_runs(tmp_path):
    root = _violating_repo(tmp_path)
    a = format_report(run_report(root, baseline_path=None))
    b = format_report(run_report(root, baseline_path=None))
    assert a == b
    assert a.encode() == b.encode()


# -- runtime half: the instrumented lock ---------------------------------------

def test_guard_object_records_unlocked_access():
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)

    b = Box()
    v = guard_object(b, {"_lock": ("_items",)})
    b.add(1)
    assert v == []
    b._items.append(2)  # unguarded container mutation, seen as access
    assert len(v) == 1
    assert v[0].field == "_items" and v[0].mode == "access"
    assert isinstance(v[0], LockViolation) and "_lock" in str(v[0])


def test_guard_declared_reads_class_contract():
    class Flag:
        _GUARDED_BY = {"_lock": ("_dead",)}

        def __init__(self):
            self._lock = threading.Lock()
            self._dead = False

        def kill(self):
            with self._lock:
                self._dead = True

    f = Flag()
    v = guard_declared(f)
    f.kill()
    assert v == []
    f._dead = True  # the seeded unguarded write
    assert [x.mode for x in v] == ["write"]


def test_instrumented_rlock_reentrancy():
    lk = InstrumentedLock(threading.RLock())
    assert not lk.held_by_me
    with lk:
        with lk:
            assert lk.held_by_me
        assert lk.held_by_me
    assert not lk.held_by_me


def test_guard_concurrent_clean_hammer():
    class Box:
        _GUARDED_BY = {"_lock": ("_items",)}

        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)

    b = Box()
    v = guard_declared(b)
    threads = [
        threading.Thread(target=lambda: [b.add(i) for i in range(300)])
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert v == []
    with b._lock:
        assert len(b._items) == 1200
