"""Versioned asset store (C11/C29/C30 parity)."""

import pytest

from k8s_gpu_tpu.platform import AssetStore


@pytest.fixture
def store(tmp_path):
    return AssetStore(tmp_path / "assets")


def test_import_versions_monotonic(store):
    a1 = store.import_bytes("ml", "model", "lm", b"weights-v1")
    a2 = store.import_bytes("ml", "model", "lm", b"weights-v2")
    assert (a1.version, a2.version) == ("v1", "v2")
    assert store.versions("ml", "model", "lm") == ["v1", "v2"]


def test_get_latest_and_pinned(store):
    store.import_bytes("ml", "model", "lm", b"one")
    store.import_bytes("ml", "model", "lm", b"two")
    latest = store.get("ml", "model", "lm")  # "" = latest (:525 semantics)
    assert latest.version == "v2"
    pinned = store.get("ml", "model", "lm", "v1")
    assert open(pinned.path, "rb").read() == b"one"


def test_export_roundtrip(store, tmp_path):
    store.import_bytes("ml", "dataset", "d", b"data")
    out = store.export(store.get("ml", "dataset", "d"), tmp_path / "out.bin")
    assert out.read_bytes() == b"data"


def test_import_directory(store, tmp_path):
    src = tmp_path / "repo"
    (src / "sub").mkdir(parents=True)
    (src / "train.py").write_text("print('hi')")
    (src / "sub" / "util.py").write_text("x = 1")
    a = store.import_path("ml", "repository", "code", src)
    assert a.size > 0
    dest = tmp_path / "checkout"
    store.export(a, dest)
    assert (dest / "sub" / "util.py").read_text() == "x = 1"


def test_missing_asset_raises(store):
    with pytest.raises(KeyError):
        store.get("ml", "model", "nope")
    store.import_bytes("ml", "model", "m", b"x")
    with pytest.raises(KeyError):
        store.get("ml", "model", "m", "v9")


def test_latest_version_numeric_after_v10(store):
    """Regression (code review): v10 must be newer than v9."""
    for i in range(11):
        store.import_bytes("ml", "model", "big", f"w{i}".encode())
    assert store.versions("ml", "model", "big")[-1] == "v11"
    latest = store.get("ml", "model", "big")
    assert latest.version == "v11"
