"""Speculative decoding: greedy exactness, acceptance accounting, EOS.

The load-bearing property is *bit-exactness*: for any draft model — even
one with random weights that disagrees with the target almost always —
the emitted stream must equal ``InferenceEngine.generate`` on the target
alone.  Speculation may only change latency, never output.
"""

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.models.transformer import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve.engine import InferenceEngine, SamplingConfig
from k8s_gpu_tpu.serve.speculative import SpeculativeDecoder


def _make(vocab=64, d_model=32, n_layers=2, n_heads=2, seed=0, max_seq=96):
    cfg = TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, d_head=d_model // n_heads, d_ff=64,
        max_seq=max_seq, dtype=jnp.float32, use_flash=False, remat=False,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(seed))
    return model, params


@pytest.fixture(scope="module")
def target():
    return _make(n_layers=3, seed=0)


@pytest.fixture(scope="module")
def draft():
    return _make(n_layers=1, seed=7)


def _engines(target, draft, k):
    tm, tp = target
    dm, dp = draft
    te = InferenceEngine(tm)
    de = InferenceEngine(dm)
    return SpeculativeDecoder(te, de, k=k), te, tp, dp


@pytest.mark.parametrize("k", [1, 3, 5])
def test_greedy_exactness_random_draft(target, draft, k):
    """A disagreeing draft must still yield the target's exact stream."""
    spec, te, tp, dp = _engines(target, draft, k)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 7), 1, 60)
    ref = te.generate(tp, prompt, max_new_tokens=24)
    out = spec.generate(tp, dp, prompt, max_new_tokens=24)
    assert jnp.array_equal(out.tokens, ref.tokens), (
        out.tokens, ref.tokens)
    assert jnp.array_equal(out.lengths, ref.lengths)


def test_self_draft_accepts_everything(target):
    """Draft == target → every round accepts all k drafts, so the round
    count collapses to ceil(max_new / (k+1))."""
    tm, tp = target
    te = InferenceEngine(tm)
    spec = SpeculativeDecoder(te, InferenceEngine(tm), k=4)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (1, 6), 1, 60)
    out = spec.generate(tp, tp, prompt, max_new_tokens=25)
    ref = te.generate(tp, prompt, max_new_tokens=25)
    assert jnp.array_equal(out.tokens, ref.tokens)
    # first token comes from prefill; remaining 24 arrive 5 per round
    assert out.rounds == 5
    assert spec.stats.acceptance_rate == 1.0


def test_eos_parity(target, draft):
    """Pick the EOS id from the reference stream's interior so the spec
    path must cut emission at the same position."""
    spec, te, tp, dp = _engines(target, draft, 3)
    prompt = jax.random.randint(jax.random.PRNGKey(11), (2, 5), 1, 60)
    base = te.generate(tp, prompt, max_new_tokens=20)
    eos = int(base.tokens[0, 8])  # a token the greedy stream really emits
    samp = SamplingConfig(eos_id=eos)
    ref = te.generate(tp, prompt, max_new_tokens=20, sampling=samp)
    out = spec.generate(tp, dp, prompt, max_new_tokens=20, sampling=samp)
    assert jnp.array_equal(out.tokens, ref.tokens)
    assert jnp.array_equal(out.lengths, ref.lengths)


def test_pad_left_bucketed_prompts(target, draft):
    """Left-padded (bucketed) prompts decode identically to unpadded."""
    spec, te, tp, dp = _engines(target, draft, 3)
    prompt = jax.random.randint(jax.random.PRNGKey(13), (2, 6), 1, 60)
    padded = jnp.concatenate(
        [jnp.zeros((2, 4), prompt.dtype), prompt], axis=1
    )
    ref = te.generate(tp, prompt, max_new_tokens=16)
    out = spec.generate(tp, dp, padded, max_new_tokens=16, pad_left=4)
    assert jnp.array_equal(out.tokens, ref.tokens)


def test_budget_never_overshoots(target, draft):
    """Emission stops exactly at max_new even when a round could emit
    past it (k+1 > remaining budget)."""
    spec, te, tp, dp = _engines(target, draft, 5)
    prompt = jax.random.randint(jax.random.PRNGKey(17), (1, 4), 1, 60)
    ref = te.generate(tp, prompt, max_new_tokens=7)
    out = spec.generate(tp, dp, prompt, max_new_tokens=7)
    assert out.tokens.shape == (1, 7)
    assert jnp.array_equal(out.tokens, ref.tokens)


def test_rejection_sample_distribution_exact():
    """The math core: for fixed p/q, the first emitted token's empirical
    distribution must equal p (Leviathan Thm 1), for a draft that
    disagrees with the target badly."""
    from k8s_gpu_tpu.serve.speculative import rejection_sample

    V, K, N = 4, 2, 40000
    p1 = jnp.array([0.5, 0.25, 0.15, 0.10])
    q1 = jnp.array([0.05, 0.05, 0.45, 0.45])  # adversarial draft
    p = jnp.tile(p1, (1, K + 1, 1))
    q = jnp.tile(q1, (1, K, 1))

    def one(key):
        kg, kr = jax.random.split(key)
        # draft tokens drawn from q, as the algorithm requires
        g = jax.random.categorical(kg, jnp.log(q[0] + 1e-30), axis=-1)[None]
        a, x = rejection_sample(kr, p, q, g)
        return jnp.where(a[0] > 0, g[0, 0], x[0])  # first emitted token

    first = jax.vmap(one)(jax.random.split(jax.random.PRNGKey(0), N))
    emp = jnp.bincount(first, length=V) / N
    assert float(jnp.abs(emp - p1).max()) < 0.015, emp


def test_sampled_self_draft_accepts_everything(target):
    """p == q → accept ratio 1 → every draft accepted."""
    tm, tp = target
    te = InferenceEngine(tm)
    spec = SpeculativeDecoder(te, InferenceEngine(tm), k=4)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 6), 1, 60)
    out = spec.generate(
        tp, tp, prompt, max_new_tokens=20,
        sampling=SamplingConfig(temperature=0.8, top_k=8),
        key=jax.random.PRNGKey(7),
    )
    assert spec.stats.acceptance_rate >= 0.99, spec.stats.acceptance_rate
    assert bool((out.lengths == 20).all())


def test_sampled_stream_plausible(target, draft):
    """Sampled speculation with a disagreeing draft: correct shapes,
    in-vocab tokens, budget respected, and different keys → different
    streams (it really samples)."""
    spec, te, tp, dp = _engines(target, draft, 3)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 7), 1, 60)
    samp = SamplingConfig(temperature=1.0, top_k=0)
    o1 = spec.generate(tp, dp, prompt, max_new_tokens=16, sampling=samp,
                       key=jax.random.PRNGKey(1))
    o2 = spec.generate(tp, dp, prompt, max_new_tokens=16, sampling=samp,
                       key=jax.random.PRNGKey(2))
    assert o1.tokens.shape == (2, 16)
    assert int(o1.tokens.max()) < 64 and int(o1.tokens.min()) >= 0
    assert bool((o1.lengths == 16).all())
    assert not jnp.array_equal(o1.tokens, o2.tokens)


def test_max_seq_guard(target, draft):
    spec, te, tp, dp = _engines(target, draft, 4)
    prompt = jnp.ones((1, 90), jnp.int32)
    with pytest.raises(ValueError):
        spec.generate(tp, dp, prompt, max_new_tokens=8)


def test_moe_target_exactness():
    """MoE targets: the W-wide verify must route experts with full
    capacity (like the width-1 decode it stands in for) — a capped
    dispatch would drop tokens and break exactness (code-review r3)."""
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=96, dtype=jnp.float32, use_flash=False,
        remat=False, num_experts=4,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(1))
    te = InferenceEngine(model)
    spec = SpeculativeDecoder(te, InferenceEngine(model), k=4)  # self-draft
    prompt = jax.random.randint(jax.random.PRNGKey(21), (2, 6), 1, 60)
    ref = te.generate(params, prompt, max_new_tokens=20)
    out = spec.generate(params, params, prompt, max_new_tokens=20)
    assert jnp.array_equal(out.tokens, ref.tokens)
    # Not 1.0: the Switch gate's argmax routing amplifies shape-dependent
    # GEMM rounding (the draft's width-1 steps vs the width-(k+1) verify),
    # so a ~1e-7 gate-logit difference occasionally flips an expert and
    # rejects a draft.  The correction token keeps the OUTPUT exact (the
    # assert above); near-1 acceptance is the MoE self-draft contract.
    assert spec.stats.acceptance_rate >= 0.9, spec.stats.acceptance_rate


def test_short_draft_max_seq_rejected(target):
    """A draft whose cache can't hold the stream must error loudly, not
    silently reject every proposal (code-review r3)."""
    tm, tp = target
    short, _ = _make(n_layers=1, seed=7, max_seq=32)
    spec = SpeculativeDecoder(InferenceEngine(tm), InferenceEngine(short),
                              k=4)
    prompt = jnp.ones((1, 20), jnp.int32)
    with pytest.raises(ValueError, match="draft 32"):
        spec.generate(tp, tp, prompt, max_new_tokens=20)
