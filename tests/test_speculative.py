"""Speculative decoding math core + batcher-surface properties.

The one spec code path is the continuous batcher's shared rounds
(tests/test_batcher_spec.py holds its exactness/interleaving suite);
this file pins the MATH those rounds ride on — Leviathan rejection
sampling exactness for any draft — and the distribution-level
properties that used to be asserted through the (retired) one-shot
SpeculativeDecoder: self-draft full acceptance under sampling and under
the shared top-p warp.
"""

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.models.transformer import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher
from k8s_gpu_tpu.serve.speculative import (
    reject_row,
    rejection_sample,
    warped_probs,
)


def test_rejection_sample_distribution_exact():
    """The math core: for fixed p/q, the first emitted token's empirical
    distribution must equal p (Leviathan Thm 1), for a draft that
    disagrees with the target badly."""
    V, K, N = 4, 2, 40000
    p1 = jnp.array([0.5, 0.25, 0.15, 0.10])
    q1 = jnp.array([0.05, 0.05, 0.45, 0.45])  # adversarial draft
    p = jnp.tile(p1, (1, K + 1, 1))
    q = jnp.tile(q1, (1, K, 1))

    def one(key):
        kg, kr = jax.random.split(key)
        # draft tokens drawn from q, as the algorithm requires
        g = jax.random.categorical(kg, jnp.log(q[0] + 1e-30), axis=-1)[None]
        a, x = rejection_sample(kr, p, q, g)
        return jnp.where(a[0] > 0, g[0, 0], x[0])  # first emitted token

    first = jax.vmap(one)(jax.random.split(jax.random.PRNGKey(0), N))
    emp = jnp.bincount(first, length=V) / N
    assert float(jnp.abs(emp - p1).max()) < 0.015, emp


def test_reject_row_identical_pq_accepts_all():
    """p == q → the accept ratio is 1 everywhere: every draft accepted."""
    V, K = 8, 4
    key = jax.random.PRNGKey(3)
    probs = jax.nn.softmax(jax.random.normal(key, (K + 1, V)))
    g = jnp.arange(K, dtype=jnp.int32) % V
    a, _ = reject_row(jax.random.PRNGKey(1), probs, probs[:K], g)
    assert int(a) == K


def test_reject_row_disjoint_support_rejects_first():
    """q puts all mass where p has none → ratio 0 → reject at 0 and the
    correction comes from p's support."""
    V, K = 4, 3
    p = jnp.tile(jnp.array([[0.5, 0.5, 0.0, 0.0]]), (K + 1, 1))
    q = jnp.tile(jnp.array([[0.0, 0.0, 0.5, 0.5]]), (K, 1))
    g = jnp.full((K,), 2, jnp.int32)  # drafts from q's support
    a, x = reject_row(jax.random.PRNGKey(5), p, q, g)
    assert int(a) == 0 and int(x) in (0, 1)


def _tiny():
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=96, dtype=jnp.float32, use_flash=False,
        remat=False,
    )
    model = TransformerLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _acceptance(b, reqs):
    for h in reqs:
        h.result()
    return b.spec_stats["acceptance"]


def test_batcher_sampled_self_draft_accepts_everything():
    """p == q per position (draft IS the target) → rejection sampling
    accepts ~every proposal even at temperature > 0."""
    model, params = _tiny()
    b = ContinuousBatcher(
        model, params, slots=2, draft=(model, params), spec_k=4
    ).start()
    try:
        hs = [
            b.submit([3, 5, 7], max_new_tokens=20, temperature=0.8,
                     seed=i)
            for i in range(2)
        ]
        acc = _acceptance(b, hs)
    finally:
        b.stop()
    assert acc >= 0.99, acc


def test_batcher_top_p_spec_self_draft():
    """warped_probs shares warp_logits, so the spec accept math sees the
    SAME nucleus the plain sampler draws from — self-draft still
    accepts everything under top-p."""
    model, params = _tiny()
    b = ContinuousBatcher(
        model, params, slots=2, draft=(model, params), spec_k=4
    ).start()
    try:
        hs = [
            b.submit([3, 5, 7], max_new_tokens=16, temperature=0.9,
                     top_p=0.8, seed=i)
            for i in range(2)
        ]
        acc = _acceptance(b, hs)
    finally:
        b.stop()
    assert acc >= 0.99, acc


def test_warped_probs_matches_sample_distribution():
    """warped_probs must be the softmax of exactly the logits transform
    _sample draws from (temperature + top_k)."""
    from k8s_gpu_tpu.serve.engine import InferenceEngine, SamplingConfig

    logits = jax.random.normal(jax.random.PRNGKey(2), (3, 16))
    s = SamplingConfig(temperature=0.7, top_k=5)
    p = warped_probs(logits, s)
    w = jax.nn.softmax(InferenceEngine.warp_logits(logits, s), axis=-1)
    assert jnp.allclose(p, w)
    # top_k really zeroes the tail
    assert int((p > 0).sum(axis=-1).max()) <= 5


def test_adaptive_k_moves_with_acceptance():
    """The adaptive-K policy: high measured acceptance earns a deeper
    draft window for a CHEAP draft, low acceptance shrinks it, and an
    expensive draft caps the depth even at high acceptance (pure host
    logic — drive the rolling window directly)."""
    model, params = _tiny()

    def batcher(ratio):
        b = ContinuousBatcher(
            model, params, slots=2, draft=(model, params), spec_k=4
        )
        b._draft_ratio = ratio  # model a draft of this relative cost
        return b

    # cheap draft (5% of target) + high acceptance → deeper window pays
    b = batcher(0.05)
    b._spec_recent.extend([(64, 60)] * 8)
    assert b._adaptive_k() == 8
    # cheap draft + low acceptance → shallow window
    b = batcher(0.05)
    b._spec_recent.extend([(64, 2)] * 8)
    assert b._adaptive_k() == 2
    # SELF-draft (ratio 1.0): every draft step costs a full target step,
    # so even near-perfect acceptance caps the window shallow
    b = batcher(1.0)
    b._spec_recent.extend([(64, 60)] * 8)
    assert b._adaptive_k() == 2
    # too little evidence → keep the configured K
    b = batcher(0.05)
    b._spec_recent.append((32, 30))
    assert b._adaptive_k() == 4
