"""CRD schema + validation parity tests (reference README.md:83-156)."""

import pytest

from k8s_gpu_tpu.api import (
    AzureVmPool,
    Condition,
    TpuPodSlice,
    ValidationError,
    set_condition,
    get_condition,
)


def make_pool(replicas=2) -> AzureVmPool:
    p = AzureVmPool()
    p.metadata.name = "gpu-pool"
    p.spec.replicas = replicas
    p.spec.resource_group_name = "rg"
    p.spec.location = "eastus"
    p.spec.vm_size = "Standard_NC4as_T4_v3"
    p.spec.azure_credential_secret = "azure-creds"
    return p


def test_azurevmpool_spec_fields_parity():
    # Every spec field from reference README.md:92-118 must exist.
    p = make_pool()
    assert p.spec.replicas == 2
    assert p.spec.vnet_name == ""
    assert p.spec.subnet_name == ""
    assert p.spec.image_reference.publisher == "Canonical"
    assert p.spec.image_reference.sku == "22_04-lts-gen2"
    assert p.api_version == "compute.my.domain/v1alpha1"


def test_replicas_minimum_zero_validation():
    # kubebuilder:validation:Minimum=0 (reference README.md:94).
    p = make_pool(replicas=-1)
    with pytest.raises(ValidationError):
        p.validate()
    make_pool(replicas=0).validate()


def test_printer_columns():
    # Desired/Ready printcolumns (reference README.md:132-133).
    p = make_pool(3)
    p.status.ready_replicas = 1
    assert p.printer_columns == {"Desired": 3, "Ready": 1}


def test_condition_transition_time_only_changes_on_flip():
    conds: list[Condition] = []
    set_condition(conds, "Ready", "False", "Scaling", "", now=1.0)
    set_condition(conds, "Ready", "False", "Scaling", "", now=2.0)
    assert get_condition(conds, "Ready").last_transition_time == 1.0
    set_condition(conds, "Ready", "True", "AsExpected", "", now=3.0)
    assert get_condition(conds, "Ready").last_transition_time == 3.0


def make_podslice(accel="v4-8", count=1) -> TpuPodSlice:
    ps = TpuPodSlice()
    ps.metadata.name = "trainer"
    ps.spec.accelerator_type = accel
    ps.spec.slice_count = count
    return ps


def test_tpupodslice_validation():
    make_podslice().validate()
    with pytest.raises(ValidationError):
        make_podslice("v99-8").validate()
    with pytest.raises(ValidationError):
        make_podslice("v4-banana").validate()
    bad = make_podslice()
    bad.spec.slice_count = -1
    with pytest.raises(ValidationError):
        bad.validate()


def test_tpupodslice_topology_consistency():
    ps = make_podslice("v5p-64")
    ps.spec.topology = "4x4x4"
    ps.validate()
    ps.spec.topology = "2x2x2"  # 8 chips != 64
    with pytest.raises(ValidationError):
        ps.validate()
