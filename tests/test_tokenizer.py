"""Byte-level BPE tokenizer: native/Python parity (merge tables AND
encodings must be bit-identical), roundtrip, persistence, and the
text->tokens->loader pipeline."""

import numpy as np
import pytest

from k8s_gpu_tpu.data import BpeTokenizer, TokenLoader, write_tokens
from k8s_gpu_tpu.data.loader import native_available

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "the quicker brown foxes jump over lazier dogs. "
    "pack my box with five dozen liquor jugs. "
) * 20


def test_python_train_encode_decode_roundtrip():
    tok = BpeTokenizer.train(CORPUS, vocab_size=300, backend="python")
    assert 256 < tok.vocab_size <= 300
    ids = tok.encode("the quick brown fox")
    assert ids.dtype == np.int32
    assert len(ids) < len("the quick brown fox")  # compression happened
    assert tok.decode(ids) == "the quick brown fox"


def test_unicode_roundtrip():
    text = "héllo wörld — 中文分词测试 🙂 " * 10
    tok = BpeTokenizer.train(text, vocab_size=280, backend="python")
    assert tok.decode(tok.encode(text)) == text


@pytest.mark.skipif(not native_available(), reason="native lib not buildable")
def test_native_matches_python():
    py = BpeTokenizer.train(CORPUS, vocab_size=300, backend="python")
    nat = BpeTokenizer.train(CORPUS, vocab_size=300, backend="native")
    assert py.merges == nat.merges, "training diverged"
    for text in ("the quick brown fox", "zebra!", CORPUS[:200], ""):
        np.testing.assert_array_equal(py.encode(text), nat.encode(text))
    ids = nat.encode(CORPUS[:500])
    assert nat.decode(ids) == CORPUS[:500]
    assert py.decode(ids) == CORPUS[:500]


def test_save_load(tmp_path):
    tok = BpeTokenizer.train(CORPUS, vocab_size=280, backend="python")
    tok.save(tmp_path / "vocab.json")
    again = BpeTokenizer.load(tmp_path / "vocab.json", backend="python")
    assert again.merges == tok.merges
    np.testing.assert_array_equal(again.encode("the dog"), tok.encode("the dog"))


def test_invalid_ids_rejected():
    tok = BpeTokenizer.train("abcabc", vocab_size=258, backend="python")
    with pytest.raises(ValueError):
        tok.decode([tok.vocab_size + 5])
    with pytest.raises(ValueError):
        tok.decode([-1])


def test_text_to_loader_pipeline(tmp_path):
    """text -> BPE tokens -> token file -> batched loader."""
    tok = BpeTokenizer.train(CORPUS, vocab_size=300)
    ids = tok.encode(CORPUS)
    path = write_tokens(tmp_path / "corpus.bin", ids)
    with TokenLoader(path, seq_len=16, batch_size=4, shuffle=False) as dl:
        x, y = next(dl)
        assert x.shape == (4, 16)
        # The loader's windows decode back to real corpus text.
        assert tok.decode(x[0]) in CORPUS


@pytest.mark.skipif(not native_available(), reason="native lib not buildable")
def test_native_encode_parity_random_bytes():
    """Heavy parity: random byte soup stresses overlapping/nested merges."""
    rng = np.random.default_rng(7)
    data = bytes(rng.integers(97, 105, size=4000, dtype=np.uint8))
    py = BpeTokenizer.train(data, vocab_size=320, backend="python")
    nat = BpeTokenizer(py.merges, backend="native")
    for seed in range(5):
        probe = bytes(np.random.default_rng(seed).integers(
            97, 105, size=700, dtype=np.uint8))
        np.testing.assert_array_equal(py.encode(probe), nat.encode(probe))
        assert nat.decode(nat.encode(probe)) == probe.decode()


@pytest.mark.skipif(not native_available(), reason="native lib not buildable")
def test_strided_view_decodes_correctly():
    tok = BpeTokenizer.train(CORPUS, vocab_size=300)
    ids = tok.encode("the quick brown fox the quick brown fox")
    # A strided view must decode its OWN elements, not adjacent memory.
    assert tok.decode(ids[::2]) == BpeTokenizer(
        tok.merges, backend="python").decode(np.ascontiguousarray(ids[::2]))


def test_corrupt_merge_table_rejected():
    with pytest.raises(ValueError, match="invalid merge table"):
        BpeTokenizer([(256, 256)], backend="python")
    with pytest.raises(ValueError, match="invalid merge table"):
        BpeTokenizer([(97, 98), (300, 97)], backend="python")
