"""Prompt-lookup ("ngram") speculative decoding in the continuous batcher.

The draft is the row's own token history (serve/batcher.py:ngram_propose)
— no draft model, no draft KV pool, one K+1-wide verify per sub-round.
Contract mirrors test_batcher_spec.py:
1. greedy streams are BIT-exact vs the plain oracle for ANY proposal
   quality — lookup affects throughput only;
2. on self-repeating streams (greedy decode of a small model settles
   into a cycle) measured acceptance is high — the honest, measured
   number the bench reports;
3. interleaving, EOS, budget, prefix-cache, and seeded-sampling
   behavior are unchanged from the plain/neural paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher
from k8s_gpu_tpu.serve.batcher import ngram_propose

TINY = TransformerConfig(
    vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
    d_ff=96, max_seq=64, use_flash=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _reference_greedy(model, params, ids, n):
    seq = jnp.asarray(ids, jnp.int32)[None, :]
    out = []
    for _ in range(n):
        logits, _ = model.forward(params, seq)
        nxt = int(jnp.argmax(logits[:, -1], axis=-1)[0])
        out.append(nxt)
        seq = jnp.concatenate([seq, jnp.asarray([[nxt]], jnp.int32)], axis=1)
    return out


# -- ngram_propose unit behavior --------------------------------------------

def _hist(tokens, size=32):
    h = np.full(size, -1, np.int32)
    h[: len(tokens)] = tokens
    return jnp.asarray(h)


def test_propose_continues_most_recent_match():
    # stream: 1 2 3 9 1 2 3 | current gram ends at pos=6 (token 3);
    # the trigram (1,2,3) ending at position 2 matched → continue 9 1 2.
    h = _hist([1, 2, 3, 9, 1, 2, 3])
    g = ngram_propose(h, jnp.int32(3), jnp.int32(6), 3)
    assert list(np.asarray(g)) == [9, 1, 2]


def test_propose_prefers_longest_then_most_recent():
    # Two candidate continuations of "...7": position 1 (7→4, unigram)
    # and position 4 (2 7→5, bigram via 2 at pos 3).  Current suffix is
    # (2, 7): the bigram match must win over the more... the unigram.
    h = _hist([7, 4, 8, 2, 7, 5, 2, 7])
    g = ngram_propose(h, jnp.int32(7), jnp.int32(7), 2)
    assert list(np.asarray(g)) == [5, 2]


def test_propose_no_match_repeats_token():
    h = _hist([1, 2, 3, 4, 5])
    g = ngram_propose(h, jnp.int32(5), jnp.int32(4), 3)
    assert list(np.asarray(g)) == [5, 5, 5]


def test_propose_never_reads_unwritten_history():
    # The match candidate right at the frontier would slice into -1
    # fill; those proposals must degrade to the repeat fallback, never
    # emit a negative token id.
    h = _hist([6, 6, 6])
    g = ngram_propose(h, jnp.int32(6), jnp.int32(2), 4)
    got = list(np.asarray(g))
    assert all(t >= 0 for t in got), got


# -- batcher behavior -------------------------------------------------------

def test_greedy_exact_vs_oracle(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2, draft="ngram",
                          spec_k=3).start()
    try:
        for ids in ([5, 9, 17], [3, 1, 4, 1, 5]):
            got = b.submit(ids, max_new_tokens=10).result()
            assert got == _reference_greedy(model, params, ids, 10)
    finally:
        b.stop()


def test_acceptance_on_repetitive_stream(setup):
    """Small-model greedy decode settles into (near-)cycles — ties can
    flip once in a while, so the stream is repetitive rather than
    exactly periodic.  Once the repetition is in history, lookup
    predicts it: measured acceptance must be real (the number the
    bench reports on TPU).  The prompt is picked by a repetition
    detector so a jax-version change in the trajectory skips honestly
    instead of flaking."""
    model, params = setup

    # Oracle via the PLAIN batcher (bit-exact greedy, bucketed compiles
    # — the unjitted forward loop would compile 40 growing shapes).
    candidates = ([13, 26, 39], [99, 1, 3])
    refs = {}
    plain = ContinuousBatcher(model, params, slots=2).start()
    try:
        for ids in candidates:
            refs[tuple(ids)] = plain.submit(ids, max_new_tokens=40).result()
    finally:
        plain.stop()
    best = 0.0
    for ids in candidates:
        b = ContinuousBatcher(model, params, slots=2, draft="ngram",
                              spec_k=3).start()
        # This measures RAW drafting acceptance.  The adaptive gate
        # would freeze the stat mid-decode: early proposals (before the
        # cycle is in history) accept ~nothing, tripping the floor, and
        # on the CPU toy the timed-round comparison correctly prefers
        # plain — both turn late rounds plain, so the rolling acceptance
        # never sees the warmed-up regime the assertion is about.
        b.ngram_breakeven = 0.0
        b._ngram_next_meas = {"plain": float("inf"),
                              "spec": float("inf")}
        try:
            got = b.submit(ids, max_new_tokens=40).result()
            assert got == refs[tuple(ids)]
            best = max(best, b.spec_stats["acceptance"])
        finally:
            b.stop()
    # Measured on these near-cyclic trajectories: 0.26 / 0.49 — a
    # changed jax trace can shift the cycle, but self-repetition of a
    # tiny model's greedy decode is robust, so demand a real rate.
    assert best > 0.2, best


def test_concurrent_requests_interleave_and_match(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=4, draft="ngram",
                          spec_k=2).start()
    try:
        ids_a, ids_b = [5, 9, 17], [2, 4, 8]
        ref_a = _reference_greedy(model, params, ids_a, 8)
        ref_b = _reference_greedy(model, params, ids_b, 8)
        ha = b.submit(ids_a, max_new_tokens=8)
        hb = b.submit(ids_b, max_new_tokens=8)
        assert ha.result() == ref_a
        assert hb.result() == ref_b
        rounds = {}
        for rnd, slot in b.interleave_log:
            rounds.setdefault(rnd, set()).add(slot)
        assert any(len(s) > 1 for s in rounds.values())
    finally:
        b.stop()


def test_eos_and_budget(setup):
    model, params = setup
    ids = [5, 9, 17]
    ref = _reference_greedy(model, params, ids, 12)
    eos = ref[4]
    want = ref[: ref.index(eos)]
    b = ContinuousBatcher(model, params, slots=2, eos_id=eos,
                          draft="ngram", spec_k=3).start()
    try:
        assert b.submit(ids, max_new_tokens=12).result() == want
        assert b.submit(ids, max_new_tokens=2).result() == want[:2]
    finally:
        b.stop()


def test_prefix_cache_admission_carries_history(setup):
    """Prefix-cached admission seats the FULL prompt history (prefix
    tokens are known host-side) — the stream stays oracle-exact."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2, draft="ngram",
                          spec_k=2).start()
    try:
        prefix = [7, 3, 11, 2, 9, 1, 8, 4]
        b.precache_prefix(prefix)
        ids = prefix + [5, 6]
        got = b.submit(ids, max_new_tokens=6).result()
        assert got == _reference_greedy(model, params, ids, 6)
        # exact-prefix hit too
        got2 = b.submit(prefix, max_new_tokens=6).result()
        assert got2 == _reference_greedy(model, params, prefix, 6)
    finally:
        b.stop()


def test_seeded_sampled_stream_co_tenant_independent(setup):
    model, params = setup

    def run(with_neighbor):
        b = ContinuousBatcher(model, params, slots=3, draft="ngram",
                              spec_k=2).start()
        try:
            h = b.submit([5, 9, 17], max_new_tokens=6, temperature=0.8,
                         seed=42)
            if with_neighbor:
                b.submit([2, 4, 8], max_new_tokens=6)
            return h.result()
        finally:
            b.stop()

    assert run(False) == run(True)


def test_unknown_draft_mode_rejected(setup):
    model, params = setup
    with pytest.raises(ValueError, match="unknown draft mode"):
        ContinuousBatcher(model, params, slots=2, draft="lookahead")


def test_constraints_plus_ngram_rejected(setup):
    model, params = setup
    from k8s_gpu_tpu.serve.constrain import ConstraintBank

    bank = ConstraintBank({"d": "[0-9]+"}, ["x"] * TINY.vocab_size)
    with pytest.raises(ValueError, match="speculative"):
        ContinuousBatcher(model, params, slots=2, eos_id=0,
                          constraints=bank, draft="ngram")


def test_tp_sharded_ngram_matches_unsharded(setup):
    """ngram speculative rounds compose with tp sharding: the verify's
    extend_multi runs tp-parallel while hist/proposals stay replicated
    row state — the stream must equal the unsharded greedy oracle."""
    import jax as _jax

    from k8s_gpu_tpu.parallel.mesh import MeshConfig, build_mesh
    from k8s_gpu_tpu.parallel.sharding import shard_params

    model, params = setup
    if _jax.device_count() < 4:
        pytest.skip("needs the 8-device CPU mesh (conftest sets it)")
    mesh = build_mesh(MeshConfig(dp=1, tp=4), n_devices=4)
    sharded = shard_params(params, model.logical_axes(), mesh)
    b = ContinuousBatcher(model, sharded, slots=2, mesh=mesh,
                          draft="ngram", spec_k=3).start()
    try:
        ids = [13, 26, 39]
        got = b.submit(ids, max_new_tokens=12).result()
        assert got == _reference_greedy(model, params, ids, 12)
    finally:
        b.stop()
