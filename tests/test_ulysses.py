"""Ulysses all-to-all sequence parallelism: exact parity with the
single-device oracle, the divisibility guard, and a sharded train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.parallel import MeshConfig, ulysses_attention
from k8s_gpu_tpu.parallel.mesh import build_mesh
from k8s_gpu_tpu.parallel.ring_attention import plain_causal_attention


def _qkv(key, B=2, H=4, S=32, D=8):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, H, S, D), jnp.float32) for k in ks)


def test_matches_plain_attention_sp_only():
    mesh = build_mesh(MeshConfig(dp=1, sp=4, tp=1, ep=1, pp=1), n_devices=4)
    q, k, v = _qkv(jax.random.PRNGKey(0))
    want = plain_causal_attention(q, k, v)
    got = ulysses_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_matches_plain_attention_dp_sp_tp():
    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2, ep=1, pp=1))
    q, k, v = _qkv(jax.random.PRNGKey(1), B=4, H=4, S=16, D=8)
    want = plain_causal_attention(q, k, v)
    got = ulysses_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_head_divisibility_guard():
    mesh = build_mesh(MeshConfig(dp=1, sp=4, tp=1, ep=1, pp=1), n_devices=4)
    q, k, v = _qkv(jax.random.PRNGKey(2), H=2)  # 2 heads, sp=4
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh)


def test_train_step_with_ulysses():
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8,
        d_ff=64, max_seq=32, sp_attention="ulysses", use_flash=False,
    )
    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2, ep=1, pp=1))
    trainer = Trainer(TransformerLM(cfg), mesh=mesh,
                      train_config=TrainConfig(warmup_steps=1))
    trainer.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, 64)
    loss = trainer.step(toks[:, :-1], toks[:, 1:])
    assert np.isfinite(loss)


def test_unknown_sp_attention_raises():
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_head=8,
        d_ff=64, max_seq=32, sp_attention="ulyses", use_flash=False,
    )
    mesh = build_mesh(MeshConfig(dp=4, sp=2, tp=1, ep=1, pp=1))
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.zeros((2, 16), jnp.int32)
    with pytest.raises(ValueError, match="sp_attention"):
        model.forward(params, toks, mesh=mesh)


def test_ulysses_local_attend_is_kernelized_when_tileable():
    """The post-all-to-all attend rides the Pallas flash kernel at
    tileable shapes (same composition as ring attention), with identical
    numerics and gradients."""
    sp = 2
    mesh = build_mesh(MeshConfig(dp=1, sp=sp, tp=1), n_devices=sp)
    key = jax.random.PRNGKey(9)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (1, 4, 64, 16)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    fn = lambda q, k, v: ulysses_attention(q, k, v, mesh, block_q=16,
                                           block_k=16)
    assert "pallas_call" in str(jax.make_jaxpr(fn)(q, k, v))
    got = jax.jit(fn)(q, k, v)
    want = plain_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def loss_u(q, k, v):
        return (fn(q, k, v).astype(jnp.float32) ** 2).mean()

    def loss_p(q, k, v):
        return (plain_causal_attention(q, k, v).astype(jnp.float32) ** 2).mean()

    gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)
