"""Fin-Agent-Suite equivalent: ingest idempotency, on-device vector search
exactness, router/agent behavior, and the HTTP acceptance flow — mirroring
the reference's curl test plan (智能风控解决方案.md:500-520) and its
re-runnable data-init fixture (:47-52, 117-158).
"""

import json
import urllib.request

import numpy as np
import pytest

from k8s_gpu_tpu.finagent import (
    FinAgentApp, QueryRequest, SqlStore, TemplateLM, TextEmbedder,
    VectorStore, ingest, recursive_split,
)
from k8s_gpu_tpu.finagent.agents import COMPLAINT_AGENT, MARKETING_AGENT
from k8s_gpu_tpu.finagent.server import serve_background

KB_DOCS = {
    "products/gold.md": (
        "# 贵金属产品\n\n我们的贵金属产品包括黄金积存和白银账户。"
        "黄金积存支持每日定投，起投金额为1克。\n\n"
        "White-gold savings products support daily automatic investment."
    ),
    "products/loans.md": (
        "# 贷款产品\n\n个人消费贷款年利率低至3.4%，最高额度50万元。\n\n"
        "Personal loans have annual rates from 3.4 percent."
    ),
    "faq.md": "# 常见问题\n\n如何重置密码？请前往设置页面点击重置。",
}


@pytest.fixture
def kb(tmp_path):
    for rel, text in KB_DOCS.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text, encoding="utf-8")
    return tmp_path


@pytest.fixture(scope="module")
def embedder():
    return TextEmbedder(dim=64, n_features=1024)  # small for test speed


@pytest.fixture
def app(kb, embedder):
    vectors = VectorStore()
    sql = SqlStore()
    ingest(kb, vectors, sql, embedder=embedder)
    return FinAgentApp(embedder=embedder, vectors=vectors, sql=sql,
                       llm=TemplateLM())


# -- embedder ---------------------------------------------------------------

def test_embedder_deterministic_and_normalized(embedder):
    a = embedder.encode("黄金积存产品")
    b = embedder.encode("黄金积存产品")
    np.testing.assert_allclose(a, b)
    assert a.shape == (64,)
    assert abs(np.linalg.norm(a) - 1.0) < 1e-5


def test_embedder_ranks_related_text_closer(embedder):
    q = embedder.encode("贵金属 黄金")
    gold = embedder.encode("贵金属产品包括黄金积存")
    loan = embedder.encode("个人消费贷款年利率")
    assert float(q @ gold) > float(q @ loan)


# -- vector store -----------------------------------------------------------

def test_vectorstore_l2_search_is_exact(embedder):
    rng = np.random.default_rng(0)
    emb = rng.normal(size=(50, 64)).astype(np.float32)
    vs = VectorStore()
    coll = vs.create_collection("c", dim=64)
    coll.insert([f"t{i}" for i in range(50)], emb)
    coll.flush()
    q = rng.normal(size=(64,)).astype(np.float32)
    hits = coll.search(q, limit=5, metric="L2")
    ref = np.argsort(((emb - q) ** 2).sum(-1))[:5]
    assert [h.id for h in hits] == ref.tolist()
    # distances are real L2 and ascending
    d = [h.distance for h in hits]
    assert d == sorted(d)
    np.testing.assert_allclose(
        d[0], np.linalg.norm(emb[ref[0]] - q), rtol=1e-4
    )


def test_vectorstore_drop_if_exists_idempotency():
    vs = VectorStore()
    vs.create_collection("k", dim=8)
    assert vs.has_collection("k")
    vs.drop_collection("k")
    assert not vs.has_collection("k")
    vs.drop_collection("k")  # dropping absent collection is fine
    c = vs.create_collection("k", dim=8)
    assert c.num_entities == 0


# -- splitter ---------------------------------------------------------------

def test_recursive_split_sizes_and_coverage():
    text = "\n\n".join(
        f"Paragraph {i}: " + "word " * 60 for i in range(8)
    )
    chunks = recursive_split(text, chunk_size=200, chunk_overlap=30)
    assert len(chunks) > 1
    assert all(len(c) <= 200 + 30 for c in chunks)
    for i in range(8):  # no paragraph lost
        assert any(f"Paragraph {i}:" in c for c in chunks)


# -- sql store --------------------------------------------------------------

def test_sqlstore_seed_and_idempotent_setup():
    sql = SqlStore()
    ev = sql.latest_failed_event("user_123")
    assert ev is not None and "Face ID" in ev.details
    sql.insert_complaint("user_123", "无法登录")
    assert len(sql.complaints("user_123")) == 1
    sql.setup()  # drop-and-recreate wipes complaints, keeps the seed
    assert sql.complaints("user_123") == []
    assert sql.latest_failed_event("user_123") is not None


# -- ingest -----------------------------------------------------------------

def test_ingest_idempotent_rerun(kb, embedder):
    vectors, sql = VectorStore(), SqlStore()
    r1 = ingest(kb, vectors, sql, embedder=embedder)
    n1 = vectors.collection("financial_knowledge").num_entities
    r2 = ingest(kb, vectors, sql, embedder=embedder)
    n2 = vectors.collection("financial_knowledge").num_entities
    assert r1["num_chunks"] == r2["num_chunks"] == n1 == n2 > 0


# -- agents / router --------------------------------------------------------

def test_router_complaint_path_records_and_verifies(app):
    resp = app.chat(QueryRequest(query="我无法登录，人脸识别失败了，我要投诉"))
    assert resp.agent == COMPLAINT_AGENT
    # The complaint was recorded and the verified log fact reached the LLM.
    assert len(app.sql.complaints("user_123")) == 1
    prompt = app.llm.calls[-1]
    assert "Face ID" in prompt and "2025-05-04" in prompt


def test_router_marketing_path_uses_rag_context(app):
    resp = app.chat(QueryRequest(query="介绍一下你们的贵金属黄金产品"))
    assert resp.agent == MARKETING_AGENT
    prompt = app.llm.calls[-1]
    assert "背景知识" in prompt and "黄金积存" in prompt
    assert app.sql.complaints() == []  # marketing path writes nothing


def test_unknown_user_complaint_still_recorded(app):
    resp = app.chat(QueryRequest(query="transfer failed twice", user_id="u9"))
    assert resp.agent == COMPLAINT_AGENT
    assert len(app.sql.complaints("u9")) == 1
    assert "未查询到相关用户行为日志" in app.llm.calls[-1]


def test_extension_contract_new_agent(app):
    app.extra_routes["余额"] = (
        "查询专员", lambda req: f"balance for {req.user_id}"
    )
    resp = app.chat(QueryRequest(query="查询余额", user_id="u1"))
    assert resp.agent == "查询专员"
    assert resp.response == "balance for u1"


def test_tpu_lm_client_generates_through_decode_path(app):
    """The real LLM seam: byte tokenizer → InferenceEngine → bytes.
    Random params, so only the mechanics are asserted."""
    import dataclasses

    from k8s_gpu_tpu.finagent.llm import TpuLMClient
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM

    model = TransformerLM(TransformerConfig(
        vocab_size=259, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=128, use_flash=False,
    ))
    lm = TpuLMClient(model=model, max_new_tokens=8)
    out = lm.chat("你好")
    assert isinstance(out, str)
    app2 = dataclasses.replace(app, llm=lm)
    resp = app2.chat(QueryRequest(query="介绍产品"))
    assert resp.agent == MARKETING_AGENT


# -- HTTP acceptance (reference curl plan :500-520) -------------------------

def _post(port, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/chat",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def test_http_acceptance_flow(app):
    srv, port = serve_background(app)
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
            assert json.loads(r.read())["status"] == "Fin-Agent-Suite is running."
        code, body = _post(port, {"query": "介绍贵金属产品"})
        assert code == 200 and body["agent"] == MARKETING_AGENT
        code, body = _post(port, {"query": "登录失败，我要投诉",
                                  "user_id": "user_123"})
        assert code == 200 and body["agent"] == COMPLAINT_AGENT
        # 422 on missing query (FastAPI parity)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/chat", data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req)
            assert False, "expected 422"
        except urllib.error.HTTPError as e:
            assert e.code == 422
        # valid JSON but not an object → 422 too (FastAPI parity)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/chat", data=b'"query string"',
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req)
            assert False, "expected 422"
        except urllib.error.HTTPError as e:
            assert e.code == 422
    finally:
        srv.shutdown()


def test_http_llm_client_serves_agents(embedder, kb):
    """The full reference topology on one box: the platform's LM server
    hosts the model, the agent suite consumes it over HTTP — both
    routing branches produce a reply through the real socket."""
    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
    from k8s_gpu_tpu.finagent import HttpLMClient
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
    from k8s_gpu_tpu.serve import LmServer

    corpus = "黄金积存产品 收益 咨询 投诉 转账 " * 20 + "gold yield help " * 20
    tok = BpeTokenizer.train(corpus, vocab_size=300, backend="python")
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=2048, use_flash=False,
        dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    srv = LmServer(model, model.init(jax.random.PRNGKey(0)), tok,
                   max_new_tokens_cap=16).start()
    try:
        vectors, sql = VectorStore(), SqlStore()
        ingest(kb, vectors, sql, embedder=embedder)
        app = FinAgentApp(
            embedder=embedder, vectors=vectors, sql=sql,
            llm=HttpLMClient(f"http://127.0.0.1:{srv.port}",
                             max_new_tokens=8, temperature=0.0),
        )
        r1 = app.chat(QueryRequest(query="黄金积存产品怎么样", user_id="u1"))
        assert r1.agent == "营销专员" and isinstance(r1.response, str)
        r2 = app.chat(QueryRequest(query="我要投诉转账问题", user_id="user_123"))
        assert r2.agent == "投诉专员" and isinstance(r2.response, str)
    finally:
        srv.stop()


def test_http_llm_client_error_paths():
    from k8s_gpu_tpu.finagent import HttpLMClient

    c = HttpLMClient("http://127.0.0.1:1", timeout=2)
    with pytest.raises(RuntimeError, match="unreachable"):
        c.chat("hi")
