"""Test configuration: force JAX onto a virtual 8-device CPU mesh so every
multi-chip sharding path is exercised without TPU hardware (SURVEY §4 item 3;
the driver separately dry-runs multichip via __graft_entry__.dryrun_multichip).

Must run before the first ``import jax`` anywhere in the test process.
"""

import os
import sys

# Force CPU even when the ambient environment pins a real accelerator
# (JAX_PLATFORMS=axon on the bench host): tests are CPU-only by design.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The bench host's sitecustomize registers a TPU PJRT plugin AND sets
# jax.config jax_platforms programmatically (which beats the env var), so
# override the config itself before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# This jaxlib's CPU compiler is not thread-safe: a main-thread compile
# racing a batcher-thread compile segfaults the process (seen thrice in
# full-suite runs, always inside backend_compile_and_load).  Serialize
# compiles for the whole test process.
from k8s_gpu_tpu.utils.compat import serialize_xla_compiles  # noqa: E402

serialize_xla_compiles()

import gc  # noqa: E402

import pytest  # noqa: E402

from k8s_gpu_tpu.controller import FakeKube, Manager  # noqa: E402
from k8s_gpu_tpu.utils.clock import FakeClock  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _bound_compile_accumulation():
    """Drop compiled executables between test modules.

    Beyond the two crash modes serialize_xla_compiles/large_thread_stack
    cover, this jaxlib segfaults a third way: a single main-thread compile
    after several hundred compiles have accumulated in-process (seen at
    ~70% of a 611-test run).  Clearing JAX's caches per module bounds the
    number of live executables so a single-process run stays under the
    threshold; tools/run_tests.py (``make test``) additionally chunks the
    suite into subprocesses.  Cross-module cache reuse is negligible, so
    this costs little.
    """
    yield
    jax.clear_caches()
    gc.collect()


class _CompileCounter:
    """Counts real XLA compilations via jax.monitoring duration events
    (``/jax/core/compile/backend_compile_duration`` fires once per
    backend compile; executable-cache hits fire nothing).  One listener
    for the whole process — jax.monitoring has no per-listener
    unregister, and a dead counter costs one string compare per event."""

    def __init__(self):
        self.n = 0

    def _on_event(self, event, duration, **kw):
        if event == "/jax/core/compile/backend_compile_duration":
            self.n += 1


_xla_compile_counter = None


@pytest.fixture
def xla_compiles():
    """The recompile guard (ISSUE 8 satellite): ``snap = fx(); ...;
    assert fx() == snap`` pins a code path as compiling ZERO new
    executables — the steady-state continuous-batching contract that
    silent static-shape regressions (ROADMAP item 3's kernel work)
    would break first."""
    global _xla_compile_counter
    if _xla_compile_counter is None:
        _xla_compile_counter = _CompileCounter()
        jax.monitoring.register_event_duration_secs_listener(
            _xla_compile_counter._on_event
        )
    counter = _xla_compile_counter
    return lambda: counter.n


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def kube():
    return FakeKube()


@pytest.fixture
def manager(kube, clock):
    m = Manager(kube, clock=clock)
    yield m
    m.stop()
