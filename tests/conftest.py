"""Test configuration: force JAX onto a virtual 8-device CPU mesh so every
multi-chip sharding path is exercised without TPU hardware (SURVEY §4 item 3;
the driver separately dry-runs multichip via __graft_entry__.dryrun_multichip).

Must run before the first ``import jax`` anywhere in the test process.
"""

import os
import sys

# Force CPU even when the ambient environment pins a real accelerator
# (JAX_PLATFORMS=axon on the bench host): tests are CPU-only by design.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The bench host's sitecustomize registers a TPU PJRT plugin AND sets
# jax.config jax_platforms programmatically (which beats the env var), so
# override the config itself before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# This jaxlib's CPU compiler is not thread-safe: a main-thread compile
# racing a batcher-thread compile segfaults the process (seen thrice in
# full-suite runs, always inside backend_compile_and_load).  Serialize
# compiles for the whole test process.
from k8s_gpu_tpu.utils.compat import serialize_xla_compiles  # noqa: E402

serialize_xla_compiles()

import gc  # noqa: E402

import pytest  # noqa: E402

from k8s_gpu_tpu.controller import FakeKube, Manager  # noqa: E402
from k8s_gpu_tpu.utils.clock import FakeClock  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _bound_compile_accumulation():
    """Drop compiled executables between test modules.

    Beyond the two crash modes serialize_xla_compiles/large_thread_stack
    cover, this jaxlib segfaults a third way: a single main-thread compile
    after several hundred compiles have accumulated in-process (seen at
    ~70% of a 611-test run).  Clearing JAX's caches per module bounds the
    number of live executables so a single-process run stays under the
    threshold; tools/run_tests.py (``make test``) additionally chunks the
    suite into subprocesses.  Cross-module cache reuse is negligible, so
    this costs little.
    """
    yield
    jax.clear_caches()
    gc.collect()


@pytest.fixture
def xla_compiles():
    """The recompile guard (ISSUE 8 satellite): ``snap = fx(); ...;
    assert fx() == snap`` pins a code path as compiling ZERO new
    executables — the steady-state continuous-batching contract that
    silent static-shape regressions (ROADMAP item 3's kernel work)
    would break first.

    Since ISSUE 9 the listener is the RUNTIME compile telemetry
    (``utils.compat.install_compile_telemetry``: every backend compile
    bumps ``xla_compiles_total`` / ``xla_compile_seconds`` — the same
    counter the ``CompileStorm`` alerting rule pages on), so CI and
    production watch one instrumentation path."""
    from k8s_gpu_tpu.utils.compat import (
        install_compile_telemetry, xla_compile_count,
    )

    install_compile_telemetry()
    return xla_compile_count


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def kube():
    return FakeKube()


@pytest.fixture
def manager(kube, clock):
    m = Manager(kube, clock=clock)
    yield m
    m.stop()
