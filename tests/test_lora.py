"""LoRA fine-tuning (the reference's 模型微调最佳实践.md:19-33 capability):
zero-delta init, adapter-only training under a sharded mesh, and merge."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.parallel import MeshConfig
from k8s_gpu_tpu.parallel.mesh import build_mesh
from k8s_gpu_tpu.train import (
    LoraConfig,
    LoraModel,
    TrainConfig,
    Trainer,
    num_params,
)


@pytest.fixture(scope="module")
def base():
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_head=8,
        d_ff=64, max_seq=32, use_flash=False,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def test_zero_delta_init_preserves_base(base):
    model, params = base
    lm = LoraModel(model, params, LoraConfig(rank=4))
    lora = lm.init(jax.random.PRNGKey(1))
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 128)
    base_logits, _ = model.forward(params, toks)
    merged_logits, _ = model.forward(lm.merged_params(lora), toks)
    np.testing.assert_allclose(
        np.asarray(base_logits), np.asarray(merged_logits), atol=1e-5
    )


def test_adapter_is_small(base):
    model, params = base
    lm = LoraModel(model, params, LoraConfig(rank=4))
    lora = lm.init(jax.random.PRNGKey(1))
    assert num_params(lora) < 0.1 * num_params(params)
    # Only the attention projections by default.
    assert set(lora["blocks"]) == {"wq", "wk", "wv", "wo"}


def test_lora_train_moves_only_adapters(base):
    model, params = base
    lm = LoraModel(model, params, LoraConfig(rank=4))
    mesh = build_mesh(MeshConfig(dp=4, tp=2, sp=1, ep=1, pp=1))
    trainer = Trainer(lm, mesh=mesh, train_config=TrainConfig(
        warmup_steps=1, learning_rate=5e-3))
    trainer.init(jax.random.PRNGKey(1))
    toks = np.tile(np.arange(17), (8, 1)) % 128
    losses = [
        trainer.step(jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:]))
        for _ in range(10)
    ]
    assert losses[-1] < losses[0]
    # Base params untouched (frozen); only adapters trained.
    b0 = lm.base_params["blocks"]["wq"]
    np.testing.assert_array_equal(np.asarray(b0), np.asarray(params["blocks"]["wq"]))
    assert float(jnp.abs(trainer.params["blocks"]["wq"]["b"]).max()) > 0


def test_extended_targets_and_head(base):
    model, params = base
    cfg = LoraConfig(rank=2, targets=("wq", "wi_gate", "head"))
    lm = LoraModel(model, params, cfg)
    lora = lm.init(jax.random.PRNGKey(1))
    assert set(lora["blocks"]) == {"wq", "wi_gate"}
    assert "head" in lora
    axes = lm.logical_axes()
    assert axes["head"]["a"] == ("embed", "lora")
    assert axes["head"]["b"] == ("lora", "vocab")
    assert axes["blocks"]["wq"]["a"] == ("stages", "embed", "lora")
    # Merge shape parity.
    merged = lm.merged_params(lora)
    for name in ("embed", "head"):
        assert merged[name].shape == params[name].shape
    for name, w in params["blocks"].items():
        assert merged["blocks"][name].shape == w.shape


def test_bad_targets_raise(base):
    model, params = base
    with pytest.raises(ValueError):
        LoraModel(model, params, LoraConfig(targets=("nope",))).init(
            jax.random.PRNGKey(0)
        )


def test_lora_workload_registered():
    from k8s_gpu_tpu.train.registry import get_workload

    class Spec:
        workload_args = {"steps": 2, "rank": 4}

    out = get_workload("lora-finetune")(Spec(), None)
    assert out["adapter_params"] < out["base_params"]
    assert out["steps"] == 2
