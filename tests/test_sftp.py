"""SFTP subsystem + shell/PTY channels over the SSH-2 gateway.

The session-layer completion of C24/C29 (GPU调度平台搭建.md:408-419 — an
interactive shell is what `ssh -p 2022` and VSCode Remote-SSH's
bootstrap need; :707-734 — sftp/lftp mirror semantics for bulk assets).
Three layers:

1. SftpServer unit: the filexfer-02 state machine against the asset
   store, including byte-fragmented feeds and unsupported ops;
2. end-to-end over real sockets: Ssh2Client.sftp() put/get/stat/listdir
   through kex + auth + subsystem channel;
3. shell: pty-req + shell gives a scriptable line-discipline session.
"""

import os
import struct
from pathlib import Path

import pytest

# The SSH-2 suite signs with real ed25519 keys; without the optional
# 'cryptography' package the whole module skips by name instead of
# failing collection.
pytest.importorskip(
    "cryptography",
    reason="ssh gateway tests need the optional 'cryptography' package",
)
from cryptography.hazmat.primitives.asymmetric.ed25519 import (  # noqa: E402
    Ed25519PrivateKey,
)

from k8s_gpu_tpu.api.core import Pod, Secret
from k8s_gpu_tpu.controller.kubefake import FakeKube
from k8s_gpu_tpu.platform import sftp as fx
from k8s_gpu_tpu.platform.assets import AssetStore
from k8s_gpu_tpu.platform.sftp import SftpError, SftpServer
from k8s_gpu_tpu.platform.sshgate import SshGateway
from k8s_gpu_tpu.platform.sshwire import (
    Reader,
    Ssh2Client,
    SshError,
    authorized_key_line,
    sb,
    su32,
)

KEY = Ed25519PrivateKey.generate()


@pytest.fixture()
def cluster(tmp_path):
    kube = FakeKube()
    pod = Pod()
    pod.metadata.name = "devenv-ada"
    pod.phase = "Running"
    pod.env["TPU_VISIBLE_CHIPS"] = "0,1"
    kube.create(pod)
    sec = Secret()
    sec.metadata.name = "user-ssh-ada"
    sec.data["authorized_keys"] = authorized_key_line(KEY, "ada@laptop")
    kube.create(sec)
    assets = AssetStore(tmp_path / "assets")
    gw = SshGateway(kube, assets=assets).start()
    yield kube, gw, assets
    gw.stop()


# -- layer 1: SftpServer unit ------------------------------------------------

def _unit_server(tmp_path):
    return SftpServer(AssetStore(tmp_path / "a"), "ada")


def _req(ptype, rid, body):
    return fx.pack(ptype, su32(rid) + body)


def _parse(resp):
    (plen,) = struct.unpack(">I", resp[:4])
    pkt = resp[4:4 + plen]
    return pkt[0], pkt[1:], resp[4 + plen:]


def test_init_version_negotiation(tmp_path):
    s = _unit_server(tmp_path)
    out = s.feed(fx.pack(fx.FXP_INIT, su32(3)))
    ptype, body, rest = _parse(out)
    assert ptype == fx.FXP_VERSION and Reader(body).u32() == 3 and not rest


def test_fragmented_feed_reassembles(tmp_path):
    """Channel data arrives in arbitrary fragments; one byte at a time
    must still parse into whole SFTP packets."""
    s = _unit_server(tmp_path)
    wire = fx.pack(fx.FXP_INIT, su32(3)) + _req(
        fx.FXP_REALPATH, 7, sb(b"ml/../ml/./dataset")
    )
    out = b""
    for i in range(len(wire)):
        out += s.feed(wire[i:i + 1])
    ptype, body, rest = _parse(out)
    assert ptype == fx.FXP_VERSION
    ptype, body, _ = _parse(rest)
    assert ptype == fx.FXP_NAME
    r = Reader(body)
    assert r.u32() == 7 and r.u32() == 1
    # ".." is not special-cased away: _split_path keeps it and the asset
    # store's component check would reject it on open; realpath just
    # normalizes slashes and dots.
    assert r.string() == b"/ml/../ml/dataset"


def test_unsupported_ops_fail_loudly(tmp_path):
    s = _unit_server(tmp_path)
    s.feed(fx.pack(fx.FXP_INIT, su32(3)))
    for ptype, body in (
        (fx.FXP_REMOVE, sb(b"/ml/dataset/corpus")),
        (fx.FXP_RENAME, sb(b"/a/b/c") + sb(b"/a/b/d")),
        (fx.FXP_MKDIR, sb(b"/newspace") + fx.attrs_bytes()),
        (fx.FXP_SETSTAT, sb(b"/a/b/c") + fx.attrs_bytes()),
    ):
        out = s.feed(_req(ptype, 3, body))
        t, rbody, _ = _parse(out)
        assert t == fx.FXP_STATUS
        r = Reader(rbody)
        assert r.u32() == 3 and r.u32() == fx.FX_OP_UNSUPPORTED


def test_write_commits_version_on_close(tmp_path):
    s = _unit_server(tmp_path)
    s.feed(fx.pack(fx.FXP_INIT, su32(3)))
    out = s.feed(_req(
        fx.FXP_OPEN, 1,
        sb(b"/ml/dataset/corpus")
        + su32(fx.FXF_WRITE | fx.FXF_CREAT | fx.FXF_TRUNC)
        + fx.attrs_bytes(),
    ))
    t, body, _ = _parse(out)
    assert t == fx.FXP_HANDLE
    r = Reader(body)
    assert r.u32() == 1
    handle = r.string()
    # out-of-order offsets are fine (seek-based writes)
    s.feed(_req(fx.FXP_WRITE, 2,
                sb(handle) + struct.pack(">Q", 5) + sb(b"world")))
    s.feed(_req(fx.FXP_WRITE, 3,
                sb(handle) + struct.pack(">Q", 0) + sb(b"hello")))
    # nothing committed until CLOSE
    assert s.assets.versions("ml", "dataset", "corpus") == []
    out = s.feed(_req(fx.FXP_CLOSE, 4, sb(handle)))
    t, body, _ = _parse(out)
    r = Reader(body)
    assert t == fx.FXP_STATUS and r.u32() == 4 and r.u32() == fx.FX_OK
    assert "v1" in r.string().decode()
    a = s.assets.get("ml", "dataset", "corpus")
    assert open(a.path, "rb").read() == b"helloworld"


# -- layers 2+3: end-to-end over the gateway ---------------------------------

def test_sftp_put_get_stat_listdir_end_to_end(cluster, tmp_path):
    kube, gw, assets = cluster
    payload = os.urandom(300 * 1024)  # multi-chunk (32 KiB write size)
    local = tmp_path / "blob.bin"
    local.write_bytes(payload)
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as c:
        s = c.sftp()
        msg = s.put(local, "/ml/dataset/corpus")
        assert "v1" in msg and "sha256" in msg
        # a second upload is a NEW version, not a mutation
        msg2 = s.put(local, "/ml/dataset/corpus")
        assert "v2" in msg2
        st = s.stat("/ml/dataset/corpus")
        assert st["size"] == len(payload)
        assert st["mtime"] > 0
        assert [n for n, _ in s.listdir("/")] == ["ml"]
        assert [n for n, _ in s.listdir("/ml")] == ["dataset"]
        names = [n for n, _ in s.listdir("/ml/dataset")]
        assert names == ["corpus"]
        back = tmp_path / "back.bin"
        n = s.get("/ml/dataset/corpus", back)
        assert n == len(payload) and back.read_bytes() == payload
    # the store agrees (same import discipline as the web path)
    assert assets.versions("ml", "dataset", "corpus") == ["v1", "v2"]
    a = assets.get("ml", "dataset", "corpus")
    import hashlib

    assert a.sha256 == hashlib.sha256(payload).hexdigest()


def test_sftp_errors_surface(cluster, tmp_path):
    kube, gw, assets = cluster
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as c:
        s = c.sftp()
        with pytest.raises(SftpError, match="missing"):
            s.stat("/ml/dataset/missing")
        with pytest.raises(SftpError):
            s.listdir("/nope")
        with pytest.raises(SftpError):
            s.get("/ml/dataset/missing", tmp_path / "x")
        # unsafe asset id is refused by the shared component check
        bad = tmp_path / "b"
        bad.write_bytes(b"x")
        with pytest.raises(SftpError, match="unsafe|component"):
            s.put(bad, "/ml/dataset/..evil")


def test_sftp_paths_cannot_escape_the_asset_root(cluster, tmp_path):
    """'..' (or any unsafe component) must never reach a filesystem op:
    listing/stating outside the store root is an information leak."""
    kube, gw, assets = cluster
    # a sibling of the asset root that must stay invisible
    (Path(assets.root).parent / "secrets-top").mkdir()
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as c:
        s = c.sftp()
        for bad in ("/..", "/../", "/ml/..", "/../secrets-top",
                    "/.hidden", "/ml/../../x"):
            with pytest.raises(SftpError):
                s.listdir(bad)
            with pytest.raises(SftpError):
                s.stat(bad)


def test_sftp_subsystem_refused_without_assets():
    """A gateway with no asset store refuses the subsystem instead of
    accepting and failing every op."""
    kube = FakeKube()
    pod = Pod()
    pod.metadata.name = "devenv-ada"
    pod.phase = "Running"
    kube.create(pod)
    sec = Secret()
    sec.metadata.name = "user-ssh-ada"
    sec.data["authorized_keys"] = authorized_key_line(KEY)
    kube.create(sec)
    gw = SshGateway(kube, assets=None).start()
    try:
        with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as c:
            with pytest.raises(SshError, match="refused"):
                c.sftp()
    finally:
        gw.stop()


def test_shell_session_line_discipline(cluster):
    """pty-req + shell: banner, prompt-delimited command/response, clean
    exit — the scripted form of an interactive `ssh -p 2022` session."""
    kube, gw, assets = cluster
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as c:
        sh = c.shell()
        assert "Welcome to the TPU devenv" in sh.banner
        assert sh.run("whoami").strip() == "ada"
        assert sh.run("hostname").strip() == "devenv-ada"
        assert sh.run("chips").strip() == "0,1"
        assert "unsupported" in sh.run("sudo reboot")
        sh.close()
        # the connection survives the shell: exec still works after
        out, status = c.exec("whoami")
        assert out.strip() == "ada" and status == 0


def test_shell_and_sftp_interleave_on_one_connection(cluster, tmp_path):
    """Two channels on one authenticated transport — the multiplexing
    RFC 4254 is for (what scp/sftp-over-ssh does)."""
    kube, gw, assets = cluster
    local = tmp_path / "f.bin"
    local.write_bytes(b"payload bytes")
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as c:
        with c.shell() as sh:
            assert sh.run("whoami").strip() == "ada"
        s = c.sftp()
        assert "v1" in s.put(local, "/ml/dataset/f")
        assert s.stat("/ml/dataset/f")["size"] == 13
