"""ArgoCD-style GitOps (operators/gitops.py): pull-based sync of an
Application's repo manifests — apply on drift, prune on removal, manual
mode — the reference's optional pull alternative to its push-mode CI
deploy (GPU调度平台搭建.md:792-794)."""

import time

import pytest

from k8s_gpu_tpu.api.gitops import Application
from k8s_gpu_tpu.api.types import ValidationError
from k8s_gpu_tpu.controller.kubefake import FakeKube
from k8s_gpu_tpu.controller.manager import Manager
from k8s_gpu_tpu.operators.gitops import APP_LABEL, GitOpsReconciler
from k8s_gpu_tpu.platform.assets import AssetStore

SECRET = """\
apiVersion: v1
kind: Secret
metadata:
  name: app-config
data:
  mode: fast
"""

# Cluster-scoped kind: proves the validation-driven namespace fallback.
QUEUE = """\
apiVersion: scheduling.tpu.k8sgpu.dev/v1alpha1
kind: SchedulingQueue
metadata:
  name: team-queue
spec:
  capTpu: 8
"""


def _repo(tmp_path, files: dict) -> str:
    src = tmp_path / f"src-{time.monotonic_ns()}"
    (src / "manifests").mkdir(parents=True)
    for name, text in files.items():
        (src / "manifests" / name).write_text(text)
    return str(src)


@pytest.fixture()
def rig(tmp_path):
    kube = FakeKube()
    store = AssetStore(tmp_path / "assets")
    rec = GitOpsReconciler(kube, store, poll_s=0.05)
    mgr = Manager(kube)
    mgr.register("Application", rec)
    mgr.start()
    yield kube, store, rec, tmp_path
    mgr.stop()


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _app(name="demo", **spec_kw) -> Application:
    app = Application()
    app.metadata.name = name
    app.spec.repo = spec_kw.pop("repo", "cfg")
    for k, v in spec_kw.items():
        setattr(app.spec, k, v)
    return app


def test_sync_applies_and_tracks_revision(rig):
    kube, store, rec, tmp = rig
    store.import_path("default", "repository", "cfg",
                      _repo(tmp, {"a.yaml": SECRET, "b.yaml": QUEUE}))
    kube.create(_app())
    assert _wait(lambda: kube.try_get("Secret", "app-config") is not None)
    assert _wait(lambda: kube.try_get("SchedulingQueue", "team-queue", "")
                 is not None)
    sec = kube.get("Secret", "app-config")
    assert sec.metadata.labels[APP_LABEL] == "demo"
    assert sec.data["mode"] == "fast"
    assert _wait(
        lambda: kube.get("Application", "demo").status.phase == "Synced"
    )
    assert kube.get("Application", "demo").status.revision == "v1"


def test_drift_is_reverted(rig):
    """A hand-edited managed object converges back to git (the GitOps
    self-heal contract)."""
    kube, store, rec, tmp = rig
    store.import_path("default", "repository", "cfg",
                      _repo(tmp, {"a.yaml": SECRET}))
    kube.create(_app())
    assert _wait(lambda: kube.try_get("Secret", "app-config") is not None)
    sec = kube.get("Secret", "app-config")
    sec.data["mode"] = "slow"  # kubectl edit
    kube.update(sec)
    assert _wait(
        lambda: kube.get("Secret", "app-config").data["mode"] == "fast"
    )


def test_git_update_rolls_forward_and_prunes(rig):
    """A new repo revision changes one object and drops another: the
    change applies, the orphan prunes (ownership = tracking label)."""
    kube, store, rec, tmp = rig
    store.import_path("default", "repository", "cfg",
                      _repo(tmp, {"a.yaml": SECRET, "b.yaml": QUEUE}))
    kube.create(_app())
    assert _wait(lambda: kube.try_get("SchedulingQueue", "team-queue", "")
                 is not None)
    store.import_path(
        "default", "repository", "cfg",
        _repo(tmp, {"a.yaml": SECRET.replace("fast", "careful")}),
    )
    assert _wait(
        lambda: kube.get("Secret", "app-config").data["mode"] == "careful"
    )
    assert _wait(
        lambda: kube.try_get("SchedulingQueue", "team-queue", "") is None
    )
    app = kube.get("Application", "demo")
    assert app.status.synced_revision == "v2"


def test_unmanaged_objects_never_pruned(rig):
    """Prune only touches app-labeled objects — a foreign Secret in the
    same namespace is invisible to the app."""
    from k8s_gpu_tpu.api.core import Secret

    kube, store, rec, tmp = rig
    foreign = Secret()
    foreign.metadata.name = "unrelated"
    kube.create(foreign)
    store.import_path("default", "repository", "cfg",
                      _repo(tmp, {"a.yaml": SECRET}))
    kube.create(_app())
    assert _wait(
        lambda: kube.get("Application", "demo").status.phase == "Synced"
    )
    assert kube.try_get("Secret", "unrelated") is not None


def test_manual_mode_reports_then_sync_now_applies(rig):
    kube, store, rec, tmp = rig
    store.import_path("default", "repository", "cfg",
                      _repo(tmp, {"a.yaml": SECRET}))
    kube.create(_app(auto_sync=False))
    assert _wait(
        lambda: kube.get("Application", "demo").status.phase == "OutOfSync"
    )
    assert kube.try_get("Secret", "app-config") is None
    assert "Secret/app-config" in kube.get(
        "Application", "demo"
    ).status.drifted
    out = rec.sync_now("demo")
    assert out["applied"] == 1 and out["revision"] == "v1"
    assert kube.try_get("Secret", "app-config") is not None
    assert _wait(
        lambda: kube.get("Application", "demo").status.phase == "Synced"
    )


def test_missing_repo_reports_error(rig):
    kube, store, rec, tmp = rig
    kube.create(_app(repo="nope"))
    assert _wait(
        lambda: kube.get("Application", "demo").status.phase == "Error"
    )


def test_application_validation():
    with pytest.raises(ValidationError, match="spec.repo"):
        FakeKube().create(_app(repo=""))
    with pytest.raises(ValidationError, match="relative"):
        FakeKube().create(_app(path="../escape"))
