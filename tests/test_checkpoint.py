"""Checkpoint/resume (SURVEY §5.4): save, restore onto a sharded mesh,
retention, asset export."""

import jax
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.parallel import MeshConfig, build_mesh
from k8s_gpu_tpu.platform import AssetStore
from k8s_gpu_tpu.train import TrainConfig, Trainer
from k8s_gpu_tpu.train.checkpoint import CheckpointManager, attach_to_trainer

TINY = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_head=16, d_ff=64
)
TC = TrainConfig(learning_rate=1e-3, warmup_steps=1)


def batch(key):
    toks = jax.random.randint(key, (2, 17), 0, 128)
    return toks[:, :-1], toks[:, 1:]


def test_save_restore_roundtrip(tmp_path):
    trainer = Trainer(
        TransformerLM(TINY),
        mesh=build_mesh(MeshConfig(dp=1), n_devices=1),
        train_config=TC,
    )
    trainer.init(jax.random.PRNGKey(0))
    toks, tgts = batch(jax.random.PRNGKey(1))
    trainer.step(toks, tgts)
    ckpt, save, resume = attach_to_trainer(trainer, tmp_path / "ckpt")
    save(1)
    want = jax.tree.map(np.asarray, trainer.params)
    # Train further, then resume: params must return to the step-1 state.
    trainer.step(toks, tgts)
    step = resume()
    assert step == 1
    got = jax.tree.map(np.asarray, trainer.params)
    jax.tree.map(np.testing.assert_array_equal, want, got)
    ckpt.close()


def test_restore_onto_sharded_mesh(tmp_path):
    """Save from single-device, resume onto a dp2/tp2 mesh — re-sharding on
    restore is the multislice-resume path."""
    t1 = Trainer(
        TransformerLM(TINY),
        mesh=build_mesh(MeshConfig(dp=1), n_devices=1),
        train_config=TC,
    )
    t1.init(jax.random.PRNGKey(0))
    toks, tgts = batch(jax.random.PRNGKey(1))
    t1.step(toks, tgts)
    ckpt = CheckpointManager(tmp_path / "ckpt")
    ckpt.save(5, t1.params, t1.opt_state)
    want_loss = t1.step(toks, tgts)
    ckpt.close()

    t2 = Trainer(
        TransformerLM(TINY),
        mesh=build_mesh(MeshConfig(dp=2, tp=2), n_devices=4),
        train_config=TC,
    )
    t2.init(jax.random.PRNGKey(42))  # different init, will be overwritten
    ckpt2 = CheckpointManager(tmp_path / "ckpt")
    params, opt_state, step = ckpt2.restore(t2.params, t2.opt_state)
    t2.params, t2.opt_state = params, opt_state
    assert step == 5
    got_loss = t2.step(toks, tgts)
    assert abs(got_loss - want_loss) < 2e-2, (got_loss, want_loss)
    ckpt2.close()


def test_retention_keeps_last_n(tmp_path):
    trainer = Trainer(
        TransformerLM(TINY),
        mesh=build_mesh(MeshConfig(dp=1), n_devices=1),
        train_config=TC,
    )
    trainer.init(jax.random.PRNGKey(0))
    ckpt = CheckpointManager(tmp_path / "ckpt", max_to_keep=2)
    for s in (1, 2, 3):
        ckpt.save(s, trainer.params, trainer.opt_state)
    assert ckpt.latest_step() == 3
    steps = sorted(int(p.name) for p in (tmp_path / "ckpt").iterdir() if p.name.isdigit())
    assert steps == [2, 3]
    ckpt.close()


def test_export_to_asset_store(tmp_path):
    trainer = Trainer(
        TransformerLM(TINY),
        mesh=build_mesh(MeshConfig(dp=1), n_devices=1),
        train_config=TC,
    )
    trainer.init(jax.random.PRNGKey(0))
    ckpt = CheckpointManager(tmp_path / "ckpt")
    ckpt.save(7, trainer.params, trainer.opt_state)
    store = AssetStore(tmp_path / "assets")
    asset = ckpt.export_to_assets(store, "ml", "flagship")
    assert asset.version == "v1"
    assert store.get("ml", "model", "flagship").size > 0
    ckpt.close()


def test_restore_missing_raises(tmp_path):
    ckpt = CheckpointManager(tmp_path / "empty")
    with pytest.raises(FileNotFoundError):
        ckpt.restore(None, None)
    ckpt.close()
