"""Real Cloud TPU client against recorded wire fixtures (VERDICT r2 #3).

Three layers of proof:
1. the client emits the EXACT queuedResources REST calls (method, URL,
   query, body) and parses recorded responses into the shared inventory
   types;
2. errors map onto the reconciler's retry ladder (AuthError / CloudError /
   idempotent 404-delete and 409-create);
3. the TpuPodSliceReconciler runs UNMODIFIED against the real client
   wired to an HTTP-level fake — same wire schema, same reconcile result
   as with FakeCloudTpu.
"""

import json
import re
import urllib.parse
from pathlib import Path

import pytest

from k8s_gpu_tpu.api import TpuPodSlice
from k8s_gpu_tpu.cloud import (
    AuthError,
    CloudError,
    CloudTpuClient,
    FakeCloudTpu,
    MetadataIdentity,
    real_cloudtpu_client_factory,
)
from k8s_gpu_tpu.cloud import wire

FIXTURES = Path(__file__).parent / "fixtures" / "cloudtpu"


def fx(name: str) -> dict:
    return json.loads((FIXTURES / name).read_text())


def fx_bytes(name: str) -> bytes:
    return (FIXTURES / name).read_bytes()


class ReplayTransport:
    """Scripted (method, url-regex) → (status, fixture) responses; records
    every call for assertions."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = []

    def __call__(self, method, url, headers, body):
        self.calls.append(
            {"method": method, "url": url, "headers": dict(headers),
             "body": json.loads(body) if body else None}
        )
        for i, (m, pattern, status, payload) in enumerate(self.script):
            if m == method and re.search(pattern, url):
                self.script.pop(i)
                return status, payload
        raise AssertionError(f"unexpected call: {method} {url}")


def token_transport():
    return ReplayTransport(
        [("GET", "metadata.google.internal", 200, fx_bytes("token.json"))] * 1
    )


def make_client(script):
    tt = ReplayTransport(
        [("GET", "metadata.google.internal", 200, fx_bytes("token.json"))]
    )
    api = ReplayTransport(script)
    ident = MetadataIdentity("tpu-provisioner", transport=tt)
    return CloudTpuClient("proj-1", "us-east5-a", ident, transport=api), api, tt


class Spec:
    accelerator_type = "v5p-64"
    slice_count = 1
    runtime_version = "tpu-ubuntu2204-base"
    network = "default"
    spot = False
    reserved = False


TAGS = {"managed-by": "tpupodslice-operator", "owner": "default-demo"}


def test_create_emits_exact_wire_payload():
    client, api, tt = make_client([
        ("POST", r"/queuedResources\?", 200, b"{}"),
        ("GET", r"/queuedResources/default-demo-qr$", 200,
         fx_bytes("qr_accepted.json")),
    ])
    qr = client.create_resource("default-demo-qr", Spec(), TAGS)
    post = api.calls[0]
    assert post["method"] == "POST"
    url = urllib.parse.urlparse(post["url"])
    assert url.path.endswith(
        "/v2/projects/proj-1/locations/us-east5-a/queuedResources"
    )
    assert urllib.parse.parse_qs(url.query) == {
        "queuedResourceId": ["default-demo-qr"]
    }
    # The body must be byte-for-byte the recorded create schema.
    assert post["body"] == {
        "tpu": {"nodeSpec": [{
            "parent": "projects/proj-1/locations/us-east5-a",
            "nodeId": "default-demo-qr-slice-0",
            "node": {
                "acceleratorType": "v5p-64",
                "runtimeVersion": "tpu-ubuntu2204-base",
                "labels": TAGS,
                "networkConfig": {"network": "default",
                                  "enableExternalIps": False},
            },
        }]},
    }
    assert post["headers"]["Authorization"] == "Bearer ya29.FIXTURE-TOKEN"
    assert qr.state == "ACCEPTED" and qr.accelerator_type == "v5p-64"


def test_token_exchange_uses_metadata_flavor_and_caches():
    client, api, tt = make_client([
        ("GET", r"/queuedResources$", 200, fx_bytes("qr_list.json")),
        ("GET", r"/nodes/default-demo-qr-slice-0$", 200,
         fx_bytes("node_active.json")),
        ("GET", r"/queuedResources$", 200, fx_bytes("qr_list.json")),
        ("GET", r"/nodes/default-demo-qr-slice-0$", 200,
         fx_bytes("node_active.json")),
    ])
    client.list_resources(TAGS)
    client.list_resources(TAGS)
    # One token call serves both API calls (cached until expiry).
    assert len(tt.calls) == 1
    assert tt.calls[0]["headers"] == {"Metadata-Flavor": "Google"}


def test_list_filters_by_tags_and_attaches_inventory():
    client, api, _ = make_client([
        ("GET", r"/queuedResources$", 200, fx_bytes("qr_list.json")),
        ("GET", r"/nodes/default-demo-qr-slice-0$", 200,
         fx_bytes("node_active.json")),
    ])
    qrs = client.list_resources(TAGS)
    assert len(qrs) == 1  # the foreign-owner QR is filtered out
    qr = qrs[0]
    assert qr.name == "default-demo-qr" and qr.state == "ACTIVE"
    assert len(qr.slices) == 1
    inv = qr.slices[0]
    assert inv.topology == "4x4x4" and inv.state == "ACTIVE"
    assert len(inv.hosts) == 16  # v5p-64: 16 hosts x 4 chips
    assert sum(h.chips for h in inv.hosts) == 64
    assert inv.hosts[0].internal_ip == "10.164.0.2"
    assert all(h.healthy for h in inv.hosts)


def test_list_pagination():
    client, api, _ = make_client([
        ("GET", r"/queuedResources$", 200, fx_bytes("qr_list_page1.json")),
        ("GET", r"pageToken=page-2-token", 200, fx_bytes("qr_list_page2.json")),
        ("GET", r"/nodes/default-demo-qr-slice-0$", 200,
         fx_bytes("node_active.json")),
    ])
    qrs = client.list_resources(TAGS)
    assert [q.name for q in qrs] == ["default-demo-qr"]
    assert len(api.calls) == 3


def test_failed_state_carries_error_message():
    client, _, _ = make_client([
        ("GET", r"/queuedResources/default-demo-qr$", 200,
         fx_bytes("qr_failed.json")),
    ])
    qr = client._get("default-demo-qr")
    assert qr.state == "FAILED"
    assert "no more capacity" in qr.error


def test_auth_errors_map_to_autherror():
    client, _, _ = make_client([
        ("GET", r"/queuedResources$", 403, fx_bytes("error_403.json")),
    ])
    with pytest.raises(AuthError, match="PERMISSION_DENIED"):
        client.list_resources(TAGS)


def test_metadata_failure_is_autherror():
    tt = ReplayTransport([("GET", "metadata.google.internal", 404, b"")])
    ident = MetadataIdentity("sa", transport=tt)
    with pytest.raises(AuthError, match="token exchange failed"):
        ident.token()


def test_delete_404_is_idempotent_but_500_raises():
    client, api, _ = make_client([
        ("DELETE", r"/queuedResources/gone\?", 200, fx_bytes("error_404.json")),
    ])
    client.delete_resource("gone")  # no raise
    url = urllib.parse.urlparse(api.calls[0]["url"])
    assert urllib.parse.parse_qs(url.query) == {"force": ["true"]}

    client2, _, _ = make_client([
        ("DELETE", r"/queuedResources/x\?", 500, fx_bytes("error_500.json")),
    ])
    with pytest.raises(CloudError, match="INTERNAL"):
        client2.delete_resource("x")


def test_create_409_returns_existing():
    client, _, _ = make_client([
        ("POST", r"/queuedResources\?", 409, fx_bytes("error_409.json")),
        ("GET", r"/queuedResources/default-demo-qr$", 200,
         fx_bytes("qr_active.json")),
        ("GET", r"/nodes/default-demo-qr-slice-0$", 200,
         fx_bytes("node_active.json")),
    ])
    qr = client.create_resource("default-demo-qr", Spec(), TAGS)
    assert qr.state == "ACTIVE"


def test_fake_shares_wire_schema():
    """FakeCloudTpu constructs its QRs through wire.build/validate/parse —
    the exact schema the real client puts on the wire.  A payload the
    validator rejects must be rejected by the fake too."""
    fake = FakeCloudTpu()
    qr = fake.create_queued_resource(
        "default-demo-qr", "v5p-64", 1, "tpu-ubuntu2204-base", TAGS
    )
    # Same parse result as the real client reading the recorded fixture.
    real = wire.parse_queued_resource(fx("qr_accepted.json"))
    assert (qr.name, qr.accelerator_type, qr.slice_count,
            qr.runtime_version, qr.tags) == (
        real.name, real.accelerator_type, real.slice_count,
        real.runtime_version, real.tags)
    with pytest.raises(ValueError, match="63"):
        fake.create_queued_resource(
            "bad", "v5p-8", 1, "tpu-ubuntu2204-base", {"owner": "x" * 64}
        )


class RestFakeCloudTpu:
    """HTTP-level fake: implements the queuedResources/nodes REST semantics
    as a Transport, so the REAL client (URL building, auth, parsing, error
    mapping) is exercised end-to-end by the reconciler."""

    def __init__(self):
        self.qrs = {}
        self.polls = {}

    def __call__(self, method, url, headers, body):
        assert headers.get("Authorization", "").startswith("Bearer ")
        u = urllib.parse.urlparse(url)
        q = urllib.parse.parse_qs(u.query)
        parts = u.path.split("/")
        if method == "POST" and parts[-1] == "queuedResources":
            name = q["queuedResourceId"][0]
            payload = json.loads(body)
            wire.validate_create_payload(payload)
            self.qrs[name] = wire.build_qr_resource(
                project="proj-1", zone="us-east5-a", name=name,
                payload=payload, state="ACCEPTED",
            )
            return 200, b"{}"
        if method == "GET" and "queuedResources" in parts and parts[-1] != "queuedResources":
            name = parts[-1]
            if name not in self.qrs:
                return 404, fx_bytes("error_404.json")
            self._advance(name)
            return 200, json.dumps(self.qrs[name]).encode()
        if method == "GET" and parts[-1] == "queuedResources":
            for name in list(self.qrs):
                self._advance(name)
            return 200, json.dumps(
                {"queuedResources": list(self.qrs.values())}
            ).encode()
        if method == "GET" and "nodes" in parts:
            node_id = parts[-1]
            qr_name = node_id.rsplit("-slice-", 1)[0]
            if qr_name not in self.qrs:
                return 404, fx_bytes("error_404.json")
            spec0 = self.qrs[qr_name]["tpu"]["nodeSpec"][0]["node"]
            accel = spec0["acceleratorType"]
            from k8s_gpu_tpu.cloud import parse_accelerator_type

            topo = parse_accelerator_type(accel)
            return 200, json.dumps({
                "name": f"projects/proj-1/locations/us-east5-a/nodes/{node_id}",
                "acceleratorType": accel,
                "acceleratorConfig": {"topology": topo.topology_str},
                "state": "READY",
                "health": "HEALTHY",
                "networkEndpoints": [
                    {"ipAddress": f"10.0.0.{w+1}", "port": 8470}
                    for w in range(topo.hosts)
                ],
            }).encode()
        if method == "DELETE":
            self.qrs.pop(parts[-1], None)
            return 200, b"{}"
        return 404, fx_bytes("error_404.json")

    def _advance(self, name):
        """ACCEPTED → PROVISIONING → ACTIVE, one step per poll."""
        n = self.polls.get(name, 0) + 1
        self.polls[name] = n
        ladder = ["ACCEPTED", "PROVISIONING", "ACTIVE"]
        self.qrs[name]["state"]["state"] = ladder[min(n, len(ladder) - 1)]


def test_reconciler_runs_unmodified_against_real_client():
    """The end-to-end proof: FakeKube + TpuPodSliceReconciler wired to the
    REAL CloudTpuClient over an HTTP-level fake reaches Ready with full
    node inventory — no reconciler changes, just a different factory."""
    import time

    from k8s_gpu_tpu.controller import FakeKube, Manager
    from k8s_gpu_tpu.operators import TpuPodSliceReconciler

    rest = RestFakeCloudTpu()
    tt = ReplayTransport(
        [("GET", "metadata.google.internal", 200, fx_bytes("token.json"))] * 50
    )
    factory = real_cloudtpu_client_factory(
        "proj-1", "us-east5-a", transport=rest, token_transport=tt
    )
    kube = FakeKube()
    mgr = Manager(kube)
    mgr.register(
        "TpuPodSlice",
        TpuPodSliceReconciler(kube, factory, provision_poll=0.02),
    )
    mgr.start()
    try:
        ps = TpuPodSlice()
        ps.metadata.name = "demo"
        ps.spec.accelerator_type = "v5p-64"
        kube.create(ps)
        deadline = time.time() + 20
        cur = None
        while time.time() < deadline:
            cur = kube.get("TpuPodSlice", "demo")
            if cur.status.phase == "Ready":
                break
            time.sleep(0.01)
        assert cur.status.phase == "Ready"
        nodes = kube.list("Node")
        assert len(nodes) == 16
        assert sum(int(n.capacity["google.com/tpu"]) for n in nodes) == 64
        # Finalizer path: delete tears down the QR through the real client.
        kube.delete("TpuPodSlice", "demo")
        deadline = time.time() + 10
        while time.time() < deadline and rest.qrs:
            time.sleep(0.01)
        assert not rest.qrs, "delete must remove the queued resource"
    finally:
        mgr.stop()


# -- multislice queued resources (BASELINE config 4's provisioning half) ----

class MsSpec:
    accelerator_type = "v5p-32"
    slice_count = 2
    runtime_version = "tpu-ubuntu2204-base"
    network = "default"
    spot = False
    reserved = False


MS_TAGS = {"managed-by": "tpupodslice-operator", "owner": "default-msdemo"}


def test_multislice_create_emits_one_nodespec_per_slice():
    """slice_count=2 → the explicit multislice create form: TWO nodeSpec
    entries under one queued resource, ids suffixed -slice-{i} (recorded
    wire shape qr_ms_accepted.json)."""
    client, api, _ = make_client([
        ("POST", r"/queuedResources\?", 200, b"{}"),
        ("GET", r"/queuedResources/ms-demo-qr$", 200,
         fx_bytes("qr_ms_accepted.json")),
    ])
    qr = client.create_resource("ms-demo-qr", MsSpec(), MS_TAGS)
    specs = api.calls[0]["body"]["tpu"]["nodeSpec"]
    assert [ns["nodeId"] for ns in specs] == [
        "ms-demo-qr-slice-0", "ms-demo-qr-slice-1"
    ]
    assert all(ns["node"]["acceleratorType"] == "v5p-32" for ns in specs)
    # the POSTed body parses back to the same shape the fixture records
    assert api.calls[0]["body"]["tpu"] == fx("qr_ms_accepted.json")["tpu"]
    assert qr.state == "ACCEPTED" and qr.slice_count == 2


def test_multislice_active_attaches_per_slice_inventory():
    """An ACTIVE 2-slice QR does one nodes.get PER SLICE and carries two
    disjoint host inventories — the DCN-connected slice pair the mesh
    layer's multislice_mesh consumes."""
    client, api, _ = make_client([
        ("GET", r"/queuedResources/ms-demo-qr$", 200,
         fx_bytes("qr_ms_active.json")),
        ("GET", r"/nodes/ms-demo-qr-slice-0$", 200,
         fx_bytes("node_ms_slice0.json")),
        ("GET", r"/nodes/ms-demo-qr-slice-1$", 200,
         fx_bytes("node_ms_slice1.json")),
    ])
    qr = client._get("ms-demo-qr")
    assert qr.state == "ACTIVE" and qr.slice_count == 2
    assert len(qr.slices) == 2
    for i, inv in enumerate(qr.slices):
        assert inv.name == f"ms-demo-qr-slice-{i}"
        assert inv.topology == "2x4x4" and len(inv.hosts) == 8
        assert sum(h.chips for h in inv.hosts) == 32
    ips0 = {h.internal_ip for h in qr.slices[0].hosts}
    ips1 = {h.internal_ip for h in qr.slices[1].hosts}
    assert not ips0 & ips1, "slices must be distinct host sets"


def test_multislice_partial_failure_is_atomic_and_names_the_node():
    """One slice hitting capacity exhaustion fails the WHOLE queued
    resource (atomicity is the point of QRs vs N independent creates);
    the error names the failing node so operators can see which half
    died.  The reconciler's self-heal ladder keys off state=FAILED."""
    client, _, _ = make_client([
        ("GET", r"/queuedResources/ms-demo-qr$", 200,
         fx_bytes("qr_ms_partial_failed.json")),
    ])
    qr = client._get("ms-demo-qr")
    assert qr.state == "FAILED"
    assert "ms-demo-qr-slice-1" in qr.error
    assert "rolled back" in qr.error
    assert not client.is_ready(qr)
    # no nodes.get calls for a FAILED QR — inventory only attaches to ACTIVE


def test_multislice_spot_preemption_mid_provision():
    """Spot reclamation while provisioning drops the QR to SUSPENDED with
    the spot tier marker intact; SUSPENDED is in the reconciler's broken
    set (delete + recreate), so parse must surface it as-is."""
    client, _, _ = make_client([
        ("GET", r"/queuedResources/ms-demo-qr$", 200,
         fx_bytes("qr_ms_preempted.json")),
    ])
    qr = client._get("ms-demo-qr")
    assert qr.state == "SUSPENDED" and qr.spot
    assert not client.is_ready(qr)
    from k8s_gpu_tpu.operators.tpupodslice import TpuPodSliceReconciler  # noqa: F401
    # the state the self-heal branch keys on (operators/tpupodslice.py:121)
    assert qr.state in ("FAILED", "SUSPENDED")


def test_multislice_reconciler_end_to_end_against_real_client():
    """A slice_count=2 TpuPodSlice reaches Ready through the REAL client
    over the HTTP-level fake: 2 slices × 8 hosts of nodes, 64 chips."""
    import time

    from k8s_gpu_tpu.controller import FakeKube, Manager
    from k8s_gpu_tpu.operators import TpuPodSliceReconciler

    rest = RestFakeCloudTpu()
    tt = ReplayTransport(
        [("GET", "metadata.google.internal", 200, fx_bytes("token.json"))] * 50
    )
    factory = real_cloudtpu_client_factory(
        "proj-1", "us-east5-a", transport=rest, token_transport=tt
    )
    kube = FakeKube()
    mgr = Manager(kube)
    mgr.register(
        "TpuPodSlice",
        TpuPodSliceReconciler(kube, factory, provision_poll=0.02),
    )
    mgr.start()
    try:
        ps = TpuPodSlice()
        ps.metadata.name = "msdemo"
        ps.spec.accelerator_type = "v5p-32"
        ps.spec.slice_count = 2
        kube.create(ps)
        deadline = time.time() + 20
        cur = None
        while time.time() < deadline:
            cur = kube.get("TpuPodSlice", "msdemo")
            if cur.status.phase == "Ready":
                break
            time.sleep(0.01)
        assert cur.status.phase == "Ready"
        nodes = kube.list("Node")
        assert len(nodes) == 16  # 2 slices x 8 hosts
        assert sum(int(n.capacity["google.com/tpu"]) for n in nodes) == 64
    finally:
        mgr.stop()


def test_spot_and_reserved_mutually_exclusive():
    """Silently dropping one tier would round-trip as drift and make the
    reconciler delete/recreate forever — both layers must reject it."""
    with pytest.raises(ValueError, match="mutually exclusive"):
        wire.build_create_payload(
            project="p", zone="z", name="n", accelerator_type="v5p-8",
            slice_count=1, runtime_version="r", labels={}, spot=True,
            reserved=True,
        )
    from k8s_gpu_tpu.api.types import ValidationError

    ps = TpuPodSlice()
    ps.metadata.name = "x"
    ps.spec.spot = True
    ps.spec.reserved = True
    with pytest.raises(ValidationError, match="mutually exclusive"):
        ps.validate()
