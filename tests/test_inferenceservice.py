"""InferenceService: serving as a reconciled workload (the reference
runs its LM as a hand-managed Ollama container, 智能风控解决方案.md:368-419
— here serving gets the TrainJob treatment: placement, self-heal,
autoscale, real endpoints)."""

import json
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.api import InferenceService, Node, ValidationError
from k8s_gpu_tpu.api.trainjob import AssetRef
from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.controller.kubefake import NotFound
from k8s_gpu_tpu.controller.manager import Request
from k8s_gpu_tpu.data.tokenizer import BpeTokenizer
from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.operators import InferenceServiceReconciler
from k8s_gpu_tpu.platform.assets import AssetStore
from k8s_gpu_tpu.scheduling.labels import TPU_RESOURCE
from k8s_gpu_tpu.serve.bundle import export_servable

TINY = TransformerConfig(
    vocab_size=256, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq=64, use_flash=False, dtype=jnp.float32,
)


def _tpu_node(name: str, chips: int = 8) -> Node:
    n = Node()
    n.metadata.name = name
    n.capacity = {TPU_RESOURCE: chips}
    n.allocatable = {TPU_RESOURCE: chips}
    n.ready = True
    return n


@pytest.fixture(scope="module")
def bundle_store(tmp_path_factory):
    """AssetStore with one servable TINY bundle (and a tokenizer)."""
    root = tmp_path_factory.mktemp("assets")
    store = AssetStore(root)
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    tok = BpeTokenizer.train(
        "the quick brown fox jumps over the lazy dog " * 4,
        vocab_size=TINY.vocab_size,
    )
    export_servable(store, "default", "tiny-lm", model, params, tok)
    return store


def _cluster(run_servers: bool, store=None, nodes: int = 2):
    kube = FakeKube()
    for i in range(nodes):
        kube.create(_tpu_node(f"tpu-{i}"))
    rec = InferenceServiceReconciler(
        kube, store=store, run_servers=run_servers
    )
    return kube, rec


def _svc(name="chat", replicas=1, chips=2, **spec) -> InferenceService:
    svc = InferenceService()
    svc.metadata.name = name
    svc.spec.model = AssetRef(space="default", id="tiny-lm")
    svc.spec.replicas = replicas
    svc.spec.chips = chips
    for k, v in spec.items():
        setattr(svc.spec, k, v)
    return svc


def _reconcile(kube, rec, name="chat"):
    return rec.reconcile(Request(namespace="default", name=name))


def test_validation():
    svc = InferenceService()
    svc.metadata.name = "x"
    with pytest.raises(ValidationError, match="model.id"):
        svc.validate()
    svc.spec.model.id = "m"
    svc.spec.replicas = 0
    with pytest.raises(ValidationError, match="replicas"):
        svc.validate()
    svc.spec.replicas = 1
    svc.spec.max_replicas = 2
    with pytest.raises(ValidationError, match="minReplicas"):
        svc.validate()


def test_placement_only_reconcile_to_ready():
    """run_servers=False: pods placed on chip carve-outs, endpoints are
    service DNS, status Ready — pure control-plane semantics."""
    kube, rec = _cluster(run_servers=False)
    kube.create(_svc(replicas=3, chips=2))
    _reconcile(kube, rec)
    svc = kube.get("InferenceService", "chat")
    assert svc.status.phase == "Ready", svc.status
    assert svc.status.ready_replicas == 3
    assert len(svc.status.endpoints) == 3
    pods = [p for p in kube.list("Pod")
            if p.metadata.labels.get("inferenceservice") == "chat"]
    assert len(pods) == 3
    for p in pods:
        assert p.requests[TPU_RESOURCE] == 2
        assert p.env.get("TPU_VISIBLE_CHIPS"), "no chip grant"
    # carve-outs visible in allocatable: 3 replicas x 2 chips from 16
    free = sum(n.allocatable.get(TPU_RESOURCE, 0)
               for n in kube.list("Node"))
    assert free == 16 - 6, free


def test_self_heal_replaces_dead_pod():
    kube, rec = _cluster(run_servers=False)
    kube.create(_svc(replicas=2))
    _reconcile(kube, rec)
    kube.delete("Pod", "chat-r-0")
    _reconcile(kube, rec)
    assert kube.get("Pod", "chat-r-0") is not None
    svc = kube.get("InferenceService", "chat")
    assert svc.status.ready_replicas == 2


def test_scale_down_frees_chips():
    kube, rec = _cluster(run_servers=False)
    kube.create(_svc(replicas=3, chips=2))
    _reconcile(kube, rec)
    svc = kube.get("InferenceService", "chat")
    svc.spec.replicas = 1
    kube.update(svc)
    _reconcile(kube, rec)
    pods = [p for p in kube.list("Pod")
            if p.metadata.labels.get("inferenceservice") == "chat"]
    assert len(pods) == 1
    free = sum(n.allocatable.get(TPU_RESOURCE, 0)
               for n in kube.list("Node"))
    assert free == 16 - 2, free


def test_no_capacity_pending_then_ready():
    """More chips than the cluster has → Pending with NoCapacity; a new
    node unblocks the next reconcile (level-triggered)."""
    kube, rec = _cluster(run_servers=False, nodes=1)
    kube.create(_svc(replicas=3, chips=8))  # 24 chips vs 8 available
    res = _reconcile(kube, rec)
    svc = kube.get("InferenceService", "chat")
    assert svc.status.phase in ("Pending", "Degraded")
    assert res.requeue_after is not None
    kube.create(_tpu_node("tpu-9", 16))
    _reconcile(kube, rec)
    assert kube.get("InferenceService", "chat").status.phase == "Ready"


def test_finalizer_teardown_frees_everything():
    kube, rec = _cluster(run_servers=False)
    kube.create(_svc(replicas=2, chips=4))
    _reconcile(kube, rec)
    kube.delete("InferenceService", "chat")
    _reconcile(kube, rec)
    with pytest.raises(NotFound):
        kube.get("InferenceService", "chat")
    assert not [p for p in kube.list("Pod")
                if p.metadata.labels.get("inferenceservice")]
    free = sum(n.allocatable.get(TPU_RESOURCE, 0)
               for n in kube.list("Node"))
    assert free == 16, free


def test_real_servers_serve_http(bundle_store):
    """run_servers=True: endpoints are LIVE LmServers loaded from the
    asset store — /generate round-trips through the continuous batcher."""
    kube, rec = _cluster(run_servers=True, store=bundle_store)
    kube.create(_svc(replicas=2, slots=2))
    try:
        _reconcile(kube, rec)
        svc = kube.get("InferenceService", "chat")
        assert svc.status.phase == "Ready", svc.status
        assert len(svc.status.endpoints) == 2
        for ep in svc.status.endpoints:
            body = json.dumps(
                {"prompt": "the quick", "max_new_tokens": 4}
            ).encode()
            req = urllib.request.Request(
                f"http://{ep}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                out = json.loads(r.read())
            assert "text" in out or "ids" in out, out
    finally:
        svc = kube.get("InferenceService", "chat")
        kube.delete("InferenceService", "chat")
        _reconcile(kube, rec)
    assert not rec._servers, "servers leaked after teardown"


def test_autoscale_grows_and_shrinks_on_queue_depth(bundle_store,
                                                    monkeypatch):
    """The telemetry-driven policy (serve/router.py): a sustained
    backlog fires FleetQueueBacklog and scales up SIZED by pending /
    target; sustained low fill scales down one step per cooldown
    window back to the floor — all deterministic under FakeClock."""
    from k8s_gpu_tpu.utils.clock import FakeClock

    clk = FakeClock()
    kube = FakeKube()
    for i in range(2):
        kube.create(_tpu_node(f"tpu-{i}"))
    rec = InferenceServiceReconciler(
        kube, store=bundle_store, run_servers=True, clock=clk,
        autoscale_params={"cooldown_s": 5.0, "max_step": 2},
    )
    kube.create(_svc(replicas=1, slots=2, min_replicas=1, max_replicas=3,
                     target_pending_per_replica=2))
    try:
        res = _reconcile(kube, rec)
        assert res.requeue_after is not None  # keeps watching the queue
        assert kube.get("InferenceService", "chat").status.replicas == 1
        # 5 queued at target 2/replica: backlog breaches, holds for
        # backlog_for_s (= AUTOSCALE_POLL), then fires → ceil(5/2) = 3.
        monkeypatch.setattr(rec, "_pending", lambda svc: 5)
        _reconcile(kube, rec)                 # alert goes pending
        assert kube.get("InferenceService", "chat").status.replicas == 1
        clk.advance(5.0)
        _reconcile(kube, rec)                 # hold elapsed → firing
        svc = kube.get("InferenceService", "chat")
        assert svc.status.replicas == 3, svc.status
        assert svc.status.ready_replicas == 3
        # Queue drains, fill stays 0: FleetLowFill fires after its
        # sustained hold, then one step down per cooldown window.
        monkeypatch.setattr(rec, "_pending", lambda svc: 0)
        for _ in range(8):
            clk.advance(10.0)
            _reconcile(kube, rec)
        assert kube.get("InferenceService", "chat").status.replicas == 1
    finally:
        kube.delete("InferenceService", "chat")
        _reconcile(kube, rec)


def test_prefix_aware_scale_down_retires_fewest_chains(bundle_store):
    """With a FleetRouter attached (replica names = pod names), a
    scale-down retires the replica owning the FEWEST warm prefix
    chains — not the highest index — announces the drain, and the
    survivors keep their (non-contiguous) indices."""
    from k8s_gpu_tpu.serve.router import FleetRouter
    from k8s_gpu_tpu.utils.metrics import MetricsRegistry

    router = FleetRouter(page_size=8, metrics=MetricsRegistry())
    kube = FakeKube()
    kube.create(_tpu_node("tpu-0"))
    rec = InferenceServiceReconciler(kube, run_servers=False,
                                     router=router)
    kube.create(_svc(replicas=3, chips=1))
    _reconcile(kube, rec)
    pods = sorted(
        p.metadata.name for p in kube.list("Pod", namespace="default")
    )
    assert pods == ["chat-r-0", "chat-r-1", "chat-r-2"]
    for p in pods:
        router.add_replica(p)
    # Warm chains: r-0 owns two tenants' chains, r-2 owns one, r-1 none.
    prefix_a, prefix_b, prefix_c = (
        list(range(1, 9)), list(range(10, 18)), list(range(20, 28))
    )
    for ids in (prefix_a, prefix_b, prefix_c):
        router.route(ids + [40])
    # Rendezvous spread is hash-determined; pin the expectation from
    # the observed ownership: the victim must be the minimum owner.
    owned = {p: router.chains_owned(p) for p in pods}
    expect_victim = min(pods, key=lambda p: (owned[p], p))
    svc = kube.get("InferenceService", "chat")
    svc.spec.replicas = 2
    kube.update(svc)
    _reconcile(kube, rec)
    left = sorted(
        p.metadata.name for p in kube.list("Pod", namespace="default")
    )
    assert expect_victim not in left and len(left) == 2, (owned, left)
    assert expect_victim not in router.replica_names()
    events = [e for e in kube.list("Event", namespace="default")
              if e.reason == "ReplicaDraining"]
    assert events and expect_victim in events[-1].message
    # Status stays coherent over the non-contiguous index set.
    svc = kube.get("InferenceService", "chat")
    assert svc.status.replicas == 2 and svc.status.ready_replicas == 2
    assert len(svc.status.endpoints) == 2


def test_manager_integration_real_clock(bundle_store):
    """The production path: Manager + watch, CR applied → Ready, spec
    change → scaled, delete → gone (the verify-skill drive shape)."""
    import time

    kube = FakeKube()
    kube.create(_tpu_node("tpu-0"))
    rec = InferenceServiceReconciler(kube, store=bundle_store,
                                     run_servers=False)
    mgr = Manager(kube)
    mgr.register("InferenceService", rec)
    mgr.start()
    try:
        kube.create(_svc(replicas=2))
        t0 = time.time()
        while time.time() - t0 < 8:
            svc = kube.get("InferenceService", "chat")
            if svc.status.phase == "Ready":
                break
            time.sleep(0.1)
        assert svc.status.phase == "Ready", svc.status
        kube.delete("InferenceService", "chat")
        t0 = time.time()
        while time.time() - t0 < 8:
            try:
                kube.get("InferenceService", "chat")
            except NotFound:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("finalizer never released the CR")
    finally:
        mgr.stop()


def test_schema_and_apply_validate():
    from k8s_gpu_tpu.api.schema import schema_for_kind, validate_manifest

    s = schema_for_kind("InferenceService")
    assert "spec" in s["properties"]
    doc = {
        "apiVersion": "tpu.k8sgpu.dev/v1alpha1",
        "kind": "InferenceService",
        "metadata": {"name": "chat"},
        "spec": {"model": {"space": "default", "id": "tiny-lm"},
                 "replicas": 2},
    }
    assert validate_manifest(doc) == []
    doc["spec"]["replicas"] = "two"
    assert validate_manifest(doc), "type error not caught"


def test_sample_manifest_validates():
    import yaml

    from k8s_gpu_tpu.api.schema import validate_manifest
    from k8s_gpu_tpu.api.serialize import from_manifest

    doc = yaml.safe_load(
        open("config/samples/inferenceservice.yaml")
    )
    assert validate_manifest(doc) == []
    svc = from_manifest(doc)
    assert svc.spec.max_replicas == 4
    svc.validate()


def test_bad_bundle_fails_cleanly(bundle_store):
    """A missing/unusable bundle is a spec problem: Failed phase with a
    message, no chips held, no endless retry."""
    kube, rec = _cluster(run_servers=True, store=bundle_store)
    svc = _svc(replicas=2)
    svc.spec.model.id = "no-such-model"
    kube.create(svc)
    res = _reconcile(kube, rec)
    svc = kube.get("InferenceService", "chat")
    assert svc.status.phase == "Failed"
    assert "no-such-model" in svc.status.message or "bundle" in svc.status.message
    assert res.requeue_after is None and not res.requeue
    free = sum(n.allocatable.get(TPU_RESOURCE, 0)
               for n in kube.list("Node"))
    assert free == 16, "chips leaked on Failed service"


def test_autoscale_first_reconcile_uses_spec_replicas(bundle_store):
    """With autoscaling on, the FIRST reconcile sizes to spec.replicas
    (the declared initial size) — not to min_replicas."""
    kube, rec = _cluster(run_servers=True, store=bundle_store)
    kube.create(_svc(replicas=2, slots=2, min_replicas=1, max_replicas=4))
    try:
        _reconcile(kube, rec)
        svc = kube.get("InferenceService", "chat")
        assert svc.status.replicas == 2, svc.status
        assert svc.status.ready_replicas == 2
    finally:
        kube.delete("InferenceService", "chat")
        _reconcile(kube, rec)
    assert not rec._bundles, "bundle cache not evicted at zero refs"


def test_draft_mode_validation():
    svc = _svc()
    svc.spec.draft_mode = "lookahead"
    with pytest.raises(ValidationError, match="draftMode"):
        svc.validate()
    svc.spec.draft_mode = "ngram"
    svc.spec.draft = AssetRef(space="default", id="tiny-draft")
    with pytest.raises(ValidationError, match="mutually exclusive"):
        svc.validate()
    svc.spec.draft = AssetRef()
    svc.validate()  # ngram alone is fine


def test_ngram_draft_mode_serves(bundle_store):
    """spec.draftMode='ngram' reaches the batcher (prompt-lookup
    speculative rounds) and the endpoint still serves correctly."""
    kube, rec = _cluster(run_servers=True, store=bundle_store)
    kube.create(_svc(replicas=1, slots=2, draft_mode="ngram"))
    try:
        _reconcile(kube, rec)
        svc = kube.get("InferenceService", "chat")
        assert svc.status.phase == "Ready", svc.status
        (key,) = list(rec._servers)
        assert rec._servers[key].batcher.spec_mode == "ngram"
        ep = svc.status.endpoints[0]
        body = json.dumps(
            {"prompt": "the quick", "max_new_tokens": 4}
        ).encode()
        req = urllib.request.Request(
            f"http://{ep}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert "text" in out or "ids" in out, out
    finally:
        kube.delete("InferenceService", "chat")
        _reconcile(kube, rec)
    assert not rec._servers
