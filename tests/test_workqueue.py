"""Work-queue semantics: dedup, delayed adds, backoff, dirty re-add
(SURVEY §7 hard part 2)."""

import threading

import pytest

from k8s_gpu_tpu.controller.workqueue import RateLimitingQueue, ShutDown
from k8s_gpu_tpu.utils.clock import FakeClock


def test_fifo_and_dedup():
    q = RateLimitingQueue(clock=FakeClock())
    q.add("a")
    q.add("b")
    q.add("a")  # coalesced
    assert q.get() == "a"
    assert q.get() == "b"
    assert q.get(block=False) is None


def test_add_while_processing_marks_dirty():
    q = RateLimitingQueue(clock=FakeClock())
    q.add("a")
    key = q.get()
    q.add("a")  # event arrives mid-reconcile
    assert q.get(block=False) is None  # not concurrently deliverable
    q.done(key)
    assert q.get(block=False) == "a"  # redelivered after done()


def test_delayed_add_fires_after_clock_advance():
    clock = FakeClock()
    q = RateLimitingQueue(clock=clock)
    q.add_after("a", 30.0)
    assert q.get(block=False) is None
    clock.advance(29.0)
    assert q.get(block=False) is None
    clock.advance(1.1)
    assert q.get(block=False) == "a"


def test_earlier_deadline_wins():
    clock = FakeClock()
    q = RateLimitingQueue(clock=clock)
    q.add_after("a", 60.0)
    q.add_after("a", 5.0)
    clock.advance(6.0)
    assert q.get(block=False) == "a"
    q.done("a")
    clock.advance(60.0)
    assert q.get(block=False) is None  # the 60s entry was coalesced away


def test_rate_limited_backoff_grows_and_forget_resets():
    clock = FakeClock()
    q = RateLimitingQueue(clock=clock, base_delay=1.0, max_delay=100.0)
    for expected in (1.0, 2.0, 4.0):
        q.add_rate_limited("a")
        assert q.get(block=False) is None
        clock.advance(expected * 0.9)
        assert q.get(block=False) is None
        clock.advance(expected * 0.2)
        assert q.get(block=False) == "a"
        q.done("a")
    q.forget("a")
    q.add_rate_limited("a")
    clock.advance(1.1)
    assert q.get(block=False) == "a"


def test_blocking_get_wakes_on_add():
    q = RateLimitingQueue(clock=FakeClock())
    got = []
    t = threading.Thread(target=lambda: got.append(q.get()))
    t.start()
    q.add("x")
    t.join(timeout=5)
    assert got == ["x"]


def test_shutdown_raises():
    q = RateLimitingQueue(clock=FakeClock())
    q.shutdown()
    with pytest.raises(ShutDown):
        q.get()
