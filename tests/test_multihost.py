"""Multi-host distributed simulation (SURVEY §4 item 3): spawned-process
coordinator on localhost — real jax.distributed rendezvous, global device
count spanning processes, cross-process collectives, and a coherent
dp-sharded train step.  The platform side (env injection) is the same
contract the trainjob controller renders into worker pods."""

import pytest

from k8s_gpu_tpu.parallel.multihost import (
    ENV_COORDINATOR,
    ENV_PROCESS_COUNT,
    ENV_PROCESS_ID,
    rendezvous_env,
    spawn_local_cluster,
    workload_device_report,
    workload_global_psum,
    workload_train_step,
)


def test_rendezvous_env_shape():
    envs = rendezvous_env(4, port=9999)
    assert [e.process_id for e in envs] == [0, 1, 2, 3]
    assert all(e.coordinator_address == "localhost:9999" for e in envs)
    e = envs[2].as_env()
    assert e[ENV_COORDINATOR] == "localhost:9999"
    assert e[ENV_PROCESS_ID] == "2" and e[ENV_PROCESS_COUNT] == "4"


@pytest.mark.slow
def test_two_process_cluster_global_devices():
    reports = spawn_local_cluster(
        workload_device_report, num_processes=2, devices_per_host=4
    )
    assert [r["process_index"] for r in reports] == [0, 1]
    assert all(r["process_count"] == 2 for r in reports)
    assert all(r["global_devices"] == 8 for r in reports)
    assert all(r["local_devices"] == 4 for r in reports)


@pytest.mark.slow
def test_cross_process_psum():
    out = spawn_local_cluster(
        workload_global_psum, num_processes=2, devices_per_host=4
    )
    # 4 devices × 1.0 (proc 0) + 4 × 2.0 (proc 1) = 12
    assert all(r["sum"] == 12.0 for r in out)
    assert all(r["global_devices"] == 8 for r in out)


@pytest.mark.slow
def test_multihost_train_step_coherent():
    out = spawn_local_cluster(
        workload_train_step, num_processes=2, devices_per_host=2,
        timeout=300,
    )
    losses = [r["loss"] for r in out]
    # Gradient all-reduce crossed processes: both saw the same update.
    assert losses[0] == pytest.approx(losses[1], rel=1e-5)
    assert all(r["global_devices"] == 4 for r in out)


def test_trainjob_workers_get_rendezvous_env(kube):
    from k8s_gpu_tpu.api.trainjob import TrainJob
    from k8s_gpu_tpu.operators.trainjob import TrainJobReconciler

    job = TrainJob()
    job.metadata.name = "dist"
    job.spec.accelerator_type = "v5p-16"
    job.spec.num_workers = 4
    kube.create(job)
    rec = TrainJobReconciler(kube, run_workloads=False)
    pods = rec._worker_pods(kube.get("TrainJob", "dist"))
    assert len(pods) == 4
    addrs = {p.env[ENV_COORDINATOR] for p in pods}
    assert addrs == {"dist-w-0.default:8476"}
    assert [p.env[ENV_PROCESS_ID] for p in pods] == ["0", "1", "2", "3"]
    assert all(p.env[ENV_PROCESS_COUNT] == "4" for p in pods)
