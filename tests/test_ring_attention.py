"""Ring attention == plain causal attention, numerically, on a CPU mesh.

The correctness oracle (SURVEY §4 item 3: JAX's native distributed-sim
story replaces 'fake NCCL')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.parallel import MeshConfig, build_mesh
from k8s_gpu_tpu.parallel.ring_attention import (
    plain_causal_attention,
    ring_attention,
)


def make_qkv(key, b=2, h=4, s=32, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, h, s, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_plain(sp):
    mesh = build_mesh(MeshConfig(dp=1, sp=sp, tp=1), n_devices=sp)
    q, k, v = make_qkv(jax.random.PRNGKey(0))
    want = plain_causal_attention(q, k, v)
    got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_with_dp_and_tp():
    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2))
    q, k, v = make_qkv(jax.random.PRNGKey(1), b=4, h=4, s=16, d=8)
    want = plain_causal_attention(q, k, v)
    got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_is_differentiable():
    mesh = build_mesh(MeshConfig(dp=1, sp=4, tp=1), n_devices=4)
    q, k, v = make_qkv(jax.random.PRNGKey(2), b=1, h=2, s=16, d=8)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh).sum()

    def loss_plain(q, k, v):
        return plain_causal_attention(q, k, v).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    for gr, gp in zip(g_ring, g_plain):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gp), atol=3e-5)


def test_causality_no_future_leak():
    """Perturbing a future token must not change past outputs."""
    mesh = build_mesh(MeshConfig(dp=1, sp=4, tp=1), n_devices=4)
    q, k, v = make_qkv(jax.random.PRNGKey(3), b=1, h=1, s=16, d=8)
    base = np.asarray(jax.jit(lambda *a: ring_attention(*a, mesh))(q, k, v))
    k2 = k.at[:, :, -1, :].add(100.0)
    v2 = v.at[:, :, -1, :].add(100.0)
    pert = np.asarray(jax.jit(lambda *a: ring_attention(*a, mesh))(q, k2, v2))
    np.testing.assert_allclose(base[:, :, :-1], pert[:, :, :-1], atol=1e-5)
    assert not np.allclose(base[:, :, -1], pert[:, :, -1])
