"""Ring attention == plain causal attention, numerically, on a CPU mesh.

The correctness oracle (SURVEY §4 item 3: JAX's native distributed-sim
story replaces 'fake NCCL')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.parallel import MeshConfig, build_mesh
from k8s_gpu_tpu.parallel.ring_attention import (
    plain_causal_attention,
    ring_attention,
)


def make_qkv(key, b=2, h=4, s=32, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, h, s, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_plain(sp):
    mesh = build_mesh(MeshConfig(dp=1, sp=sp, tp=1), n_devices=sp)
    q, k, v = make_qkv(jax.random.PRNGKey(0))
    want = plain_causal_attention(q, k, v)
    got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_with_dp_and_tp():
    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2))
    q, k, v = make_qkv(jax.random.PRNGKey(1), b=4, h=4, s=16, d=8)
    want = plain_causal_attention(q, k, v)
    got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_is_differentiable():
    mesh = build_mesh(MeshConfig(dp=1, sp=4, tp=1), n_devices=4)
    q, k, v = make_qkv(jax.random.PRNGKey(2), b=1, h=2, s=16, d=8)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh).sum()

    def loss_plain(q, k, v):
        return plain_causal_attention(q, k, v).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    for gr, gp in zip(g_ring, g_plain):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gp), atol=3e-5)


def test_causality_no_future_leak():
    """Perturbing a future token must not change past outputs."""
    mesh = build_mesh(MeshConfig(dp=1, sp=4, tp=1), n_devices=4)
    q, k, v = make_qkv(jax.random.PRNGKey(3), b=1, h=1, s=16, d=8)
    base = np.asarray(jax.jit(lambda *a: ring_attention(*a, mesh))(q, k, v))
    k2 = k.at[:, :, -1, :].add(100.0)
    v2 = v.at[:, :, -1, :].add(100.0)
    pert = np.asarray(jax.jit(lambda *a: ring_attention(*a, mesh))(q, k2, v2))
    np.testing.assert_allclose(base[:, :, :-1], pert[:, :, :-1], atol=1e-5)
    assert not np.allclose(base[:, :, -1], pert[:, :, -1])


def _naive_ring(q, k, v, mesh, axis_name="sp"):
    """The r1 implementation: every hop computes the full block einsum and
    masks afterwards — the FLOP baseline the zigzag schedule halves."""
    import functools
    from jax.sharding import PartitionSpec as P
    n = mesh.shape[axis_name]
    scale = q.shape[-1] ** -0.5

    def body(q, k, v):
        b, h, sq, d = q.shape
        o = jnp.zeros((b, h, sq, d), jnp.float32)
        m = jnp.full((b, h, sq), -1e30, jnp.float32)
        l = jnp.zeros((b, h, sq), jnp.float32)
        my = jax.lax.axis_index(axis_name)

        def step(carry, t):
            o, m, l, k, v = carry
            src = (my - t) % n
            q_pos = my * sq + jnp.arange(sq)
            k_pos = src * sq + jnp.arange(sq)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) * scale
            s = jnp.where(mask[None, None], s, -1e30)
            m_new = jnp.maximum(m, s.max(-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])
            l = l * alpha + p.sum(-1)
            o = o * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
            perm = [(i, (i + 1) % n) for i in range(n)]
            return (o, m_new, l, jax.lax.ppermute(k, axis_name, perm),
                    jax.lax.ppermute(v, axis_name, perm)), None

        (o, m, l, _, _), _ = jax.lax.scan(step, (o, m, l, k, v), jnp.arange(n))
        return (o / l[..., None]).astype(q.dtype)

    spec = P(("dp",), ("tp",), axis_name, None)
    from k8s_gpu_tpu.parallel.collectives import shard_map_compat

    return shard_map_compat(body, mesh=mesh, in_specs=(spec,) * 3,
                            out_specs=spec, check_vma=False)(q, k, v)


def _matmul_flops(jaxpr, mult=1):
    """Count dot_general FLOPs in a jaxpr, multiplying scan bodies by their
    trip count (XLA's cost_analysis counts loop bodies once, which would
    hide the per-hop saving)."""
    import math
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            lhs = eqn.invars[0].aval.shape
            rhs = eqn.invars[1].aval.shape
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            batch = math.prod(lhs[i] for i in lb)
            kdim = math.prod(lhs[i] for i in lc)
            m = math.prod(
                lhs[i] for i in range(len(lhs)) if i not in lc and i not in lb
            )
            n = math.prod(
                rhs[i] for i in range(len(rhs)) if i not in rc and i not in rb
            )
            total += 2 * batch * m * n * kdim * mult
        inner_mult = (
            mult * eqn.params["length"]
            if eqn.primitive.name == "scan"
            else mult
        )
        for p in eqn.params.values():
            inner = p if hasattr(p, "eqns") else getattr(p, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                total += _matmul_flops(inner, inner_mult)
    return total


def test_zigzag_halves_flops_at_sp8():
    """VERDICT r1 item #4 'done' criterion: per-step FLOPs ~halved at sp=8
    vs the mask-after-full-einsum ring."""
    sp = 8
    mesh = build_mesh(MeshConfig(dp=1, sp=sp, tp=1), n_devices=sp)
    q, k, v = make_qkv(jax.random.PRNGKey(5), b=1, h=2, s=512, d=64)

    zig = lambda q, k, v: ring_attention(q, k, v, mesh)
    naive = lambda q, k, v: _naive_ring(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(jax.jit(zig)(q, k, v)),
        np.asarray(jax.jit(naive)(q, k, v)),
        atol=2e-5,
    )

    fz = _matmul_flops(jax.make_jaxpr(zig)(q, k, v).jaxpr)
    fn = _matmul_flops(jax.make_jaxpr(naive)(q, k, v).jaxpr)
    # Exact accounting: naive does n full block pairs per device; zigzag
    # does the local causal prologue (1 full pair) + 2 half-pairs on each
    # of the n-1 hops = (n+1)/2 full-pair equivalents → ratio 9/16 at n=8.
    assert fz < 0.6 * fn, f"zigzag flops {fz} not ~half of naive {fn}"


def test_ring_uses_flash_kernel_when_blocks_tile():
    """VERDICT r2 weak #5: at kernel-tileable shapes the per-hop block
    attend must be the Pallas flash kernel (pallas_call in the jaxpr), not
    a materialized (C/2)^2 score einsum."""
    sp = 2
    mesh = build_mesh(MeshConfig(dp=1, sp=sp, tp=1), n_devices=sp)
    q, k, v = make_qkv(jax.random.PRNGKey(7), b=1, h=2, s=64, d=16)
    fn = lambda q, k, v: ring_attention(q, k, v, mesh, block_q=16, block_k=16)
    jaxpr = str(jax.make_jaxpr(fn)(q, k, v))
    assert "pallas_call" in jaxpr, "ring hop attends must be kernelized"
    want = plain_causal_attention(q, k, v)
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_flash_gradients_match_plain():
    """Gradients through the kernelized ring: the lse outputs participate
    in the online-softmax merge, so this exercises the flash kernel's lse
    cotangent path (ops/attention.py:_flash_bwd) end to end."""
    sp = 2
    mesh = build_mesh(MeshConfig(dp=1, sp=sp, tp=1), n_devices=sp)
    q, k, v = make_qkv(jax.random.PRNGKey(8), b=1, h=2, s=64, d=16)

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, block_q=16, block_k=16)
        return (o.astype(jnp.float32) ** 2).mean()

    def loss_plain(q, k, v):
        return (plain_causal_attention(q, k, v).astype(jnp.float32) ** 2).mean()

    assert "pallas_call" in str(jax.make_jaxpr(loss_ring)(q, k, v))
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    for gr, gp in zip(g_ring, g_plain):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gp), atol=5e-5)
