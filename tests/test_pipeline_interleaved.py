"""Interleaved (virtual-stage) 1F1B (VERDICT r3 ask #6,
parallel/pipeline.py:interleaved_1f1b): gradient parity with the
sequential oracle, bubble-tick accounting vs classic 1F1B, and the
bf16-vs-f32 pipeline parity the dryrun's f32 pin left unproven."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.parallel.mesh import MeshConfig, build_mesh
from k8s_gpu_tpu.parallel.pipeline import (
    classic_ticks_fine,
    interleaved_ticks,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=8, n_heads=2, d_head=16,
    d_ff=64, max_seq=16, dtype=jnp.float32, use_flash=False,
    pp_microbatches=8, pp_virtual_stages=2,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, 64)
    return model, params, toks[:, :-1], toks[:, 1:]


def _tree_allclose(a, b, rtol):
    for pa, (la, lb) in zip(
        jax.tree_util.tree_flatten_with_path(a)[0],
        zip(jax.tree.leaves(a), jax.tree.leaves(b)),
    ):
        la, lb = np.asarray(la), np.asarray(lb)
        denom = np.max(np.abs(la)) + 1e-9
        err = np.max(np.abs(la - lb)) / denom
        assert err < rtol, f"{jax.tree_util.keystr(pa[0])}: rel err {err:.2e}"


def test_interleaved_grads_match_oracle_pp4_v2(setup):
    """pp=4, v=2: 8 virtual stages over 4 devices; every gradient leaf
    matches the sequential oracle — chunk wraparound hops, the decode
    bijection, and the enlarged store ring are all load-bearing here."""
    model, params, tokens, targets = setup
    if jax.device_count() < 4:
        pytest.skip("needs >= 4 devices")
    loss_o, grads_o = jax.value_and_grad(model.loss)(params, tokens, targets)
    mesh = build_mesh(MeshConfig(dp=1, pp=4), n_devices=4)
    loss_p, grads_p = jax.jit(
        lambda p, t, tg: model.pipeline_value_and_grad(p, t, tg, mesh)
    )(params, tokens, targets)
    assert abs(float(loss_o) - float(loss_p)) < 1e-4
    _tree_allclose(grads_o, grads_p, rtol=2e-4)


def test_interleaved_composes_with_dp(setup):
    """dp=2 × pp=4: batch axes stay manual inside the schedule and the
    dp gradient psum still lands (the one_f_one_b composition rules)."""
    model, params, tokens, targets = setup
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = dataclasses.replace(CFG, pp_microbatches=4)
    model4 = TransformerLM(cfg)
    loss_o, grads_o = jax.value_and_grad(model4.loss)(
        params, tokens, targets
    )
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    loss_p, grads_p = jax.jit(
        lambda p, t, tg: model4.pipeline_value_and_grad(p, t, tg, mesh)
    )(params, tokens, targets)
    assert abs(float(loss_o) - float(loss_p)) < 1e-4
    _tree_allclose(grads_o, grads_p, rtol=2e-4)


def test_bubble_accounting():
    """The schedule's reason to exist, in ticks.  Fine tick = one chunk
    (1/v of a classic tick), so classic 1F1B costs v·(M + 2P - 2) fine
    ticks and interleaved M·v + Pv + P - 2:

    - pp >= 4: interleaved strictly cheaper, bubble (P-1)(1+1/v) coarse
      vs classic 2(P-1), approaching HALF as v grows (the lockstep-SPMD
      bound; Megatron's (P-1)/v needs per-device asynchrony);
    - pp = 2: exactly equal — the docstring's 'win needs pp >= 4'."""
    for M, P, v in [(8, 4, 2), (16, 4, 4), (8, 8, 2), (32, 8, 4)]:
        fine_interleaved = interleaved_ticks(M, P, v)
        fine_classic = v * classic_ticks_fine(M, P)
        assert fine_interleaved < fine_classic, (M, P, v)
        # busy time is identical (M·v fine ticks); the delta is bubble
        bubble_i = fine_interleaved - M * v
        bubble_c = fine_classic - M * v
        assert bubble_i == (P - 1) * (v + 1) + (v - 1)
        assert bubble_c == 2 * (P - 1) * v
        # v→∞ limit: bubble ratio → (v+1+...)/(2v) → 1/2, not 1/v
        assert bubble_i / bubble_c > 0.5
    # pp=2: no win under lockstep — documented equality
    assert interleaved_ticks(8, 2, 4) == 4 * classic_ticks_fine(8, 2)


def test_v_must_divide_layers():
    from k8s_gpu_tpu.parallel.pipeline import interleaved_1f1b

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    mesh = build_mesh(MeshConfig(dp=1, pp=2), n_devices=2)
    params = {"w": jnp.zeros((6, 3))}
    with pytest.raises(ValueError, match="divisible"):
        interleaved_1f1b(
            lambda p, x: x, params, (), lambda t, y, tg: y.sum(),
            jnp.zeros((4, 3)), jnp.zeros((4, 3), jnp.int32), mesh, v=4,
        )


def test_bf16_pipeline_matches_f32(setup):
    """VERDICT r3 weak #6: pp in the flagship dtype (bf16) has never
    executed anywhere — the CPU dryruns pin f32 around a jaxlib CPU
    crash in bf16 all-reduce promotion.  The pipeline's OWN psums are
    f32-wrapped, so the schedule itself runs bf16 on CPU: prove it and
    pin loss/grad parity against the f32 pipeline."""
    model, params, tokens, targets = setup
    if jax.device_count() < 4:
        pytest.skip("needs >= 4 devices")
    mesh = build_mesh(MeshConfig(dp=1, pp=4), n_devices=4)
    cfg16 = dataclasses.replace(CFG, dtype=jnp.bfloat16)
    model16 = TransformerLM(cfg16)
    loss32, grads32 = jax.jit(
        lambda p, t, tg: model.pipeline_value_and_grad(p, t, tg, mesh)
    )(params, tokens, targets)
    loss16, grads16 = jax.jit(
        lambda p, t, tg: model16.pipeline_value_and_grad(p, t, tg, mesh)
    )(params, tokens, targets)
    # bf16 rounding: loose but bounded parity
    assert abs(float(loss32) - float(loss16)) < 5e-2
    for l32, l16 in zip(jax.tree.leaves(grads32), jax.tree.leaves(grads16)):
        a, b = np.asarray(l32, np.float32), np.asarray(l16, np.float32)
        denom = np.max(np.abs(a)) + 1e-6
        assert np.max(np.abs(a - b)) / denom < 0.15
