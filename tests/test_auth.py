"""Identity: directory bind, OIDC code flow, token verify (C14)."""

import time

import pytest

from k8s_gpu_tpu.auth import AuthError, TokenIssuer, UserDirectory


@pytest.fixture
def directory():
    d = UserDirectory()
    d.add_user("alice", "s3cret", groups=["ml-team"])
    d.add_user("bob", "hunter2")
    return d


@pytest.fixture
def issuer(directory):
    return TokenIssuer(directory)


def test_directory_bind(directory):
    u = directory.authenticate("alice", "s3cret")
    assert u.groups == ["ml-team"]
    with pytest.raises(AuthError):
        directory.authenticate("alice", "wrong")
    with pytest.raises(AuthError):
        directory.authenticate("nobody", "x")


def test_group_membership(directory):
    directory.add_to_group("bob", "ml-team")
    directory.add_to_group("bob", "ml-team")  # idempotent
    assert directory.get("bob").groups == ["ml-team"]


def test_code_flow_roundtrip(issuer):
    code = issuer.authorize("alice", "s3cret", "tpu-cli")
    token = issuer.exchange_code(code, "tpu-cli")
    claims = issuer.verify(token)
    assert claims["sub"] == "alice"
    assert claims["groups"] == ["ml-team"]
    assert claims["aud"] == "tpu-cli"


def test_code_single_use(issuer):
    code = issuer.authorize("alice", "s3cret", "tpu-cli")
    issuer.exchange_code(code, "tpu-cli")
    with pytest.raises(AuthError):
        issuer.exchange_code(code, "tpu-cli")


def test_code_client_binding(issuer):
    code = issuer.authorize("alice", "s3cret", "tpu-portal")
    with pytest.raises(AuthError):
        issuer.exchange_code(code, "tpu-cli")


def test_unknown_client_rejected(issuer):
    with pytest.raises(AuthError):
        issuer.authorize("alice", "s3cret", "evil-client")


def test_token_tamper_rejected(issuer, directory):
    token = issuer.issue(directory.get("alice"), "tpu-cli")
    head, _, sig = token.rpartition(".")
    with pytest.raises(AuthError):
        issuer.verify(head + ".AAAA")
    # Payload swap without re-signing must fail too.
    parts = token.split(".")
    forged = ".".join([parts[0], parts[1][:-2] + "xx", parts[2]])
    with pytest.raises(AuthError):
        issuer.verify(forged)


def test_token_expiry(issuer, directory):
    token = issuer.issue(directory.get("alice"), "tpu-cli", ttl=0.05)
    issuer.verify(token)
    time.sleep(0.06)
    with pytest.raises(AuthError):
        issuer.verify(token)


def test_audience_checked_at_verify(issuer, directory):
    token = issuer.issue(directory.get("alice"), "tpu-portal")
    issuer.verify(token)  # no expected audience: any client's token
    issuer.verify(token, audience="tpu-portal")
    with pytest.raises(AuthError, match="audience"):
        issuer.verify(token, audience="tpu-cli")


def test_cross_issuer_rejected(directory):
    a = TokenIssuer(directory)
    b = TokenIssuer(directory)  # different secret
    token = a.issue(directory.get("alice"), "tpu-cli")
    with pytest.raises(AuthError):
        b.verify(token)
