"""Grouped-query attention: KV cache shrinks by the group factor while
training and serving stay oracle-consistent.

Contract: wk/wv carry n_kv_heads; the cache is [L, B, KH, T, Dh]; the
engine's grouped attend never materializes a repeated cache; decode
matches the teacher-forced forward's greedy stream (the same oracle the
batcher tests use)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher, InferenceEngine, quantize_params


def _cfg(kv=2, heads=8):
    return TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=heads, d_head=8,
        n_kv_heads=kv, d_ff=96, max_seq=48, use_flash=False,
        dtype=jnp.float32, remat=False,
    )


def _oracle(model, params, ids, n):
    seq = jnp.asarray(ids, jnp.int32)[None, :]
    out = []
    for _ in range(n):
        logits, _ = model.forward(params, seq)
        nxt = int(jnp.argmax(logits[:, -1], axis=-1)[0])
        out.append(nxt)
        seq = jnp.concatenate([seq, jnp.asarray([[nxt]], jnp.int32)], axis=1)
    return out


def test_param_and_cache_shapes():
    model = TransformerLM(_cfg(kv=2))
    params = model.init(jax.random.PRNGKey(0))
    assert params["blocks"]["wk"].shape == (2, 64, 2, 8)
    assert params["blocks"]["wv"].shape == (2, 64, 2, 8)
    assert params["blocks"]["wq"].shape == (2, 64, 8, 8)
    from k8s_gpu_tpu.serve.engine import _empty_cache

    cache = _empty_cache(model.cfg, 3, 48)
    assert cache["k"].shape == (2, 3, 2, 48, 8)  # KH=2, 4x smaller than MHA


def test_invalid_group_rejected():
    with pytest.raises(ValueError, match="multiple"):
        TransformerLM(_cfg(kv=3, heads=8)).init(jax.random.PRNGKey(0))


def test_kv_tp_mismatch_rejected_early():
    """tp > n_kv_heads must fail with a config-level message, not an
    opaque device_put divisibility error (code-review r3)."""
    from k8s_gpu_tpu.parallel.mesh import MeshConfig, mesh_from_devices
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    model = TransformerLM(_cfg(kv=2, heads=8))
    mesh = mesh_from_devices(jax.devices()[:4], MeshConfig(dp=1, tp=4))
    tr = Trainer(model, mesh=mesh, train_config=TrainConfig(warmup_steps=1))
    with pytest.raises(ValueError, match="n_kv_heads"):
        tr.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="n_kv_heads"):
        InferenceEngine(model, mesh=mesh)


@pytest.mark.parametrize("kv", [1, 2, 4])
def test_decode_matches_forward_oracle(kv):
    """The engine's grouped cache attend and the training forward (which
    repeats K/V) are the same function: greedy streams agree."""
    model = TransformerLM(_cfg(kv=kv))
    params = model.init(jax.random.PRNGKey(1))
    eng = InferenceEngine(model)
    ids = [5, 9, 17, 3]
    out = eng.generate(params, jnp.asarray([ids]), max_new_tokens=8)
    assert [int(t) for t in out.tokens[0]] == _oracle(model, params, ids, 8)


def test_training_step_backprops():
    from k8s_gpu_tpu.parallel.mesh import MeshConfig, mesh_from_devices
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    model = TransformerLM(_cfg(kv=2))
    tr = Trainer(
        model, mesh=mesh_from_devices(jax.devices()[:1], MeshConfig(dp=1)),
        train_config=TrainConfig(warmup_steps=1),
    )
    tr.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(2), (4, 17), 0, 128)
    losses = [tr.step(toks[:, :-1], toks[:, 1:]) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # it learns the batch


def test_gqa_quantized_decode():
    model = TransformerLM(_cfg(kv=2))
    params = model.init(jax.random.PRNGKey(3))
    qp = quantize_params(params)
    assert qp["blocks"]["wk"]["q"].shape == (2, 64, 2, 8)
    eng = InferenceEngine(model)
    out = eng.generate(qp, jnp.ones((1, 5), jnp.int32), max_new_tokens=6)
    assert out.tokens.shape == (1, 6)


def test_gqa_continuous_batching():
    model = TransformerLM(_cfg(kv=2))
    params = model.init(jax.random.PRNGKey(4))
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        ids = [7, 3, 11]
        got = b.submit(ids, max_new_tokens=6).result()
        assert got == _oracle(model, params, ids, 6)
    finally:
        b.stop()


def test_gqa_sp_training_runs():
    """GQA composes with ring-attention sequence parallelism (K/V are
    repeated to full heads before the ring, so any KH works)."""
    from k8s_gpu_tpu.parallel.mesh import MeshConfig, mesh_from_devices
    from k8s_gpu_tpu.train import TrainConfig, Trainer

    model = TransformerLM(_cfg(kv=2))
    mesh = mesh_from_devices(jax.devices()[:4], MeshConfig(dp=2, sp=2))
    tr = Trainer(model, mesh=mesh, train_config=TrainConfig(warmup_steps=1))
    tr.init(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(5), (4, 17), 0, 128)
    assert np.isfinite(tr.step(toks[:, :-1], toks[:, 1:]))
