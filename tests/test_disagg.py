"""Disaggregated prefill/decode: prefill workers feed the decode batcher
precomputed K/V rows; admission is splice+sample only.

Contract: callers can't tell — greedy streams are oracle-exact, adapters
ride through, shutdown drains."""

import threading

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher, DisaggregatedLm
from k8s_gpu_tpu.train.lora import LoraAdapter, LoraConfig

CFG = TransformerConfig(
    vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
    d_ff=96, max_seq=64, use_flash=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _oracle(model, params, ids, n):
    seq = jnp.asarray(ids, jnp.int32)[None, :]
    out = []
    for _ in range(n):
        logits, _ = model.forward(params, seq)
        nxt = int(jnp.argmax(logits[:, -1], axis=-1)[0])
        out.append(nxt)
        seq = jnp.concatenate([seq, jnp.asarray([[nxt]], jnp.int32)], axis=1)
    return out


def test_disagg_matches_oracle(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=4).start()
    d = DisaggregatedLm(model, params, batcher=b).start()
    try:
        ids = [5, 9, 17, 3]
        got = d.submit(ids, max_new_tokens=8).result()
        assert got == _oracle(model, params, ids, 8)
    finally:
        d.stop()
        b.stop()


def test_disagg_concurrent_requests(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=4).start()
    d = DisaggregatedLm(model, params, batcher=b, prefill_workers=2).start()
    try:
        prompts = [[5, 9], [7, 3, 11], [2, 4, 6, 8], [13]]
        results = [None] * len(prompts)

        def run(i):
            results[i] = d.submit(prompts[i], max_new_tokens=6).result()

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for i, ids in enumerate(prompts):
            assert results[i] == _oracle(model, params, ids, 6), i
    finally:
        d.stop()
        b.stop()


def test_disagg_adapter_rides_through(setup):
    model, params = setup
    cfg = LoraConfig(rank=4, targets=("wq", "wv"))
    tree = LoraAdapter(cfg).init(jax.random.PRNGKey(1), params)
    keys = iter(jax.random.split(jax.random.PRNGKey(9), 8))
    tree["blocks"] = {
        t: {"a": ab["a"],
            "b": jax.random.normal(next(keys), ab["b"].shape) * 0.05}
        for t, ab in tree["blocks"].items()
    }
    adapters = {"t1": (tree, cfg)}
    merged = LoraAdapter(cfg).merge(params, tree)
    b = ContinuousBatcher(model, params, slots=2, adapters=adapters).start()
    d = DisaggregatedLm(model, params, batcher=b).start()
    try:
        ids = [7, 3, 11, 19]
        got = d.submit(ids, max_new_tokens=6, adapter="t1").result()
        assert got == _oracle(model, merged, ids, 6)
        with pytest.raises(KeyError, match="unknown adapter"):
            d.submit(ids, adapter="nope")
    finally:
        d.stop()
        b.stop()


def test_disagg_stop_then_submit_raises(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    d = DisaggregatedLm(model, params, batcher=b).start()
    d.stop()
    try:
        with pytest.raises(RuntimeError, match="stopped"):
            d.submit([1, 2, 3])
    finally:
        b.stop()


def test_disagg_prompt_too_long(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    d = DisaggregatedLm(model, params, batcher=b).start()
    try:
        with pytest.raises(ValueError, match="too long"):
            d.submit(list(range(60)))
    finally:
        d.stop()
        b.stop()


def test_disagg_backpressure_bounds_inflight(setup):
    """Prefill never runs more than inflight_cap rows ahead of decode:
    with cap=1 and a stalled batcher (not started), the second submit's
    prefill must wait until the first row is seated."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2)  # NOT started: no admits
    d = DisaggregatedLm(model, params, batcher=b, inflight_cap=1).start()
    try:
        done = []

        def run(i):
            h = d.submit([3 + i, 5, 7], max_new_tokens=2)
            done.append(i)
            h.result()

        t1 = threading.Thread(target=run, args=(0,), daemon=True)
        t2 = threading.Thread(target=run, args=(1,), daemon=True)
        t1.start()
        import time
        time.sleep(3)  # t1's prefill completes and enqueues its row
        t2.start()
        time.sleep(3)
        # cap=1: the second prefill is blocked until a seat frees
        assert done == [0], done
        b.start()  # decode begins: seats free, second request proceeds
        t1.join(timeout=60)
        t2.join(timeout=60)
        assert sorted(done) == [0, 1]
    finally:
        d.stop()
        b.stop()


def test_submit_precomputed_validates_shapes(setup):
    import jax.numpy as jnp

    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        bad_cache = {"k": jnp.zeros((2, 1, 4, 32, 12)),  # wrong max_seq
                     "v": jnp.zeros((2, 1, 4, 32, 12))}
        with pytest.raises(ValueError, match="row_cache\\['k'\\] shape"):
            b.submit_precomputed(bad_cache, jnp.zeros((1, 128)), 8, 0)
        good_cache = {"k": jnp.zeros((2, 1, 4, 64, 12)),
                      "v": jnp.zeros((2, 1, 4, 64, 12))}
        with pytest.raises(ValueError, match="last_logits shape"):
            b.submit_precomputed(good_cache, jnp.zeros((128,)), 8, 0)
    finally:
        b.stop()


# -- chunked prefill ---------------------------------------------------------

@pytest.mark.parametrize("n_prompt", [3, 8, 9, 16, 21])
def test_chunked_prefill_matches_oracle(setup, n_prompt):
    """Chunked prefill is the same computation re-chunked: greedy streams
    match the teacher-forced oracle at every chunk-boundary shape
    (n < C, n == C, n = kC, n = kC + r)."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    d = DisaggregatedLm(model, params, batcher=b, chunk_tokens=8).start()
    try:
        ids = [(i * 7) % 120 + 1 for i in range(n_prompt)]
        got = d.submit(ids, max_new_tokens=5).result()
        assert got == _oracle(model, params, ids, 5), n_prompt
    finally:
        d.stop()
        b.stop()


def test_chunked_prefill_with_adapter(setup):
    model, params = setup
    cfg = LoraConfig(rank=4, targets=("wq", "wv"))
    tree = LoraAdapter(cfg).init(jax.random.PRNGKey(1), params)
    keys = iter(jax.random.split(jax.random.PRNGKey(9), 8))
    tree["blocks"] = {
        t: {"a": ab["a"],
            "b": jax.random.normal(next(keys), ab["b"].shape) * 0.05}
        for t, ab in tree["blocks"].items()
    }
    adapters = {"t1": (tree, cfg)}
    merged = LoraAdapter(cfg).merge(params, tree)
    b = ContinuousBatcher(model, params, slots=2, adapters=adapters).start()
    d = DisaggregatedLm(model, params, batcher=b, chunk_tokens=8).start()
    try:
        ids = [7, 3, 11, 19, 2, 4, 6, 8, 10, 12]  # crosses a chunk boundary
        got = d.submit(ids, max_new_tokens=5, adapter="t1").result()
        assert got == _oracle(model, merged, ids, 5)
    finally:
        d.stop()
        b.stop()


def test_chunk_tokens_validation(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2)
    with pytest.raises(ValueError, match="multiple of 8"):
        DisaggregatedLm(model, params, batcher=b, chunk_tokens=10)


def test_chunk_and_prompt_validation(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2)
    with pytest.raises(ValueError, match="multiple of 8"):
        DisaggregatedLm(model, params, batcher=b, chunk_tokens=-8)
    d = DisaggregatedLm(model, params, batcher=b, chunk_tokens=8)
    with pytest.raises(ValueError, match="empty prompt"):
        d.submit([])
