"""Block-level shared prefix caching on the paged KV pool (ISSUE 5).

The paged pool's prefix cache is refcounted and block-granular
(serve/kv_blocks.py): page-aligned prompt chunks are chain-hashed to
physical block ids, so requests sharing a system prompt map their page
tables to the SAME blocks; a partial tail block is recomputed into a
private block (copy-on-write); eviction is LRU over refcount-0 blocks.
Contract pinned here:

1. decode equivalence: paged shared-prefix streams are token-for-token
   identical to the dense batcher (greedy and sampled), and greedy
   stays exact when speculative decode rides the SAME paged pool — the
   composability the r5 constructor still refused;
2. sharing is physical: two admissions with a common prefix hold the
   same block ids, refcounted, counted ONCE by occupancy;
3. no leaks: 200 admit/retire churn cycles return every block to the
   allocatable set;
4. eviction under pressure never takes a referenced block.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import ContinuousBatcher, DisaggregatedLm
from k8s_gpu_tpu.serve.batcher import _Request
from k8s_gpu_tpu.utils.metrics import global_metrics

CFG = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq=128, use_flash=False, dtype=jnp.float32,
)
MODEL = TransformerLM(CFG)
PARAMS = MODEL.init(jax.random.PRNGKey(0))

PAGE = 16
PREFIX = [(i * 7 + 3) % 120 for i in range(40)]   # 2 full pages + tail


def _dense(reqs, **bkw):
    b = ContinuousBatcher(MODEL, PARAMS, slots=4, **bkw).start()
    try:
        hs = [b.submit(ids, **kw) for ids, kw in reqs]
        return [h.result() for h in hs]
    finally:
        b.stop()


def _paged(reqs, paged_blocks=64, **bkw):
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=paged_blocks,
        page_size=PAGE, **bkw,
    ).start()
    try:
        hs = [b.submit(ids, **kw) for ids, kw in reqs]
        outs = [h.result() for h in hs]
    finally:
        b.stop()
    # every test doubles as a leak check: all blocks allocatable again
    assert sorted(b._free_blocks) == list(range(1, b.paged_blocks))
    return outs, b


def test_shared_prefix_greedy_bitexact_vs_dense():
    reqs = [(PREFIX + [60 + i, 61 + i], dict(max_new_tokens=10))
            for i in range(4)]
    dense = _dense(reqs)
    paged, b = _paged(reqs)
    assert paged == dense
    # requests 2..4 matched the pages request 1 registered
    assert global_metrics.counter("serve_prefix_cache_hits_total") >= 3


def test_shared_prefix_sampled_bitexact_vs_dense():
    reqs = [
        (PREFIX + [50 + i], dict(max_new_tokens=8, temperature=0.9,
                                 seed=13 + i))
        for i in range(3)
    ]
    dense = _dense(reqs)
    paged, _ = _paged(reqs)
    assert paged == dense


def test_sharing_is_physical_and_counted_once():
    """Two planned admissions with a common prefix reference the SAME
    physical blocks; occupancy counts them once (the KVCacheSaturation
    fix — per-request lists would double-count)."""
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=64, page_size=PAGE
    )
    ids = np.asarray(PREFIX + [99, 98], np.int32)
    r1 = _Request(ids=ids, max_new=8, temperature=0.0, top_p=0.0, seed=0)
    r2 = _Request(ids=ids, max_new=8, temperature=0.0, top_p=0.0, seed=1)
    assert b._paged_plan(r1) and b._paged_plan(r2)
    assert r1.prefix_tokens == 0 and r2.prefix_tokens == 2 * PAGE
    assert r2.blocks[:2] == r1.blocks[:2]       # same physical blocks
    assert set(r2.blocks[2:]).isdisjoint(r1.blocks)  # private tails
    assert b._pool.shared_count == 2
    assert b._pool.refcount(r1.blocks[0]) == 2
    # physical accounting: pinned < sum of per-request holdings
    assert b._pool.pinned_count == len(r1.blocks) + len(r2.blocks) - 2
    b._update_util_gauges()
    assert global_metrics.gauge("serve_kv_blocks_used") == (
        b._pool.pinned_count
    )
    assert global_metrics.gauge("serve_kv_blocks_shared") == 2.0
    for r in (r1, r2):
        for blk in r.blocks:
            b._pool.release(blk)
    assert b._pool.pinned_count == 0


def test_refcount_churn_returns_pool_to_all_free():
    """200 admit/retire cycles over rotating prompts (sharing, misses,
    and LRU eviction all exercised) leave zero pinned blocks."""
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=16, page_size=PAGE
    ).start()
    try:
        for i in range(200):
            # leading token varies -> a distinct hash chain per cycle,
            # so registrations accumulate and the LRU really evicts;
            # revisited chains (i wraps at 120) hit the cache if they
            # survived or recompute if evicted — both must be clean
            ids = [i % 120] + PREFIX[:32] + [i % 64]
            assert len(b.submit(ids, max_new_tokens=2).result()) == 2
    finally:
        b.stop()
    assert b._pool.pinned_count == 0
    assert sorted(b._free_blocks) == list(range(1, 16))
    assert b._pool.evictions > 0  # pressure really evicted cached blocks


def test_eviction_keeps_referenced_blocks_pinned():
    """A live request's blocks survive heavy churn that evicts every
    refcount-0 cached block around them — its stream still matches the
    dense path."""
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=16, page_size=PAGE
    ).start()
    try:
        long_ids = PREFIX + [77]
        slow = b.submit(long_ids, max_new_tokens=24)
        for i in range(12):  # distinct prompts -> register + evict churn
            b.submit([(i * 11 + 5) % 120 for _ in range(20)],
                     max_new_tokens=2).result()
        got = slow.result()
    finally:
        b.stop()
    assert got == _dense([(long_ids, dict(max_new_tokens=24))])[0]
    assert sorted(b._free_blocks) == list(range(1, 16))


def test_paged_ngram_spec_greedy_bitexact_vs_dense():
    """paged KV + speculative decode + shared prefix in ONE batcher —
    the composability r5 refused.  Greedy spec is verify-gated, so the
    stream must equal the dense plain batcher's bit-for-bit."""
    reqs = [(PREFIX + [30 + i], dict(max_new_tokens=12)) for i in range(3)]
    reqs += [(list(range(2, 24)), dict(max_new_tokens=12))]  # cold, no share
    dense = _dense(reqs)
    paged, b = _paged(reqs, draft="ngram", spec_k=4)
    assert paged == dense
    assert b.spec_stats["drafted"] > 0  # spec rounds really ran


def test_paged_neural_spec_greedy_bitexact_vs_dense():
    """Neural draft on the paged pool (target-as-draft: the machinery
    ceiling) — greedy parity with the dense plain path."""
    reqs = [(PREFIX + [41 + i], dict(max_new_tokens=10)) for i in range(2)]
    dense = _dense(reqs)
    paged, _ = _paged(reqs, draft=(MODEL, PARAMS), spec_k=2)
    assert paged == dense


def test_precache_prefix_warms_block_cache():
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=64, page_size=PAGE
    ).start()
    try:
        b.precache_prefix(PREFIX)
        assert b._pool.cached_count >= 2  # full pages parked at refcount 0
        h0 = global_metrics.counter("serve_prefix_cache_hits_total")
        got = b.submit(PREFIX + [88], max_new_tokens=8).result()
        assert global_metrics.counter("serve_prefix_cache_hits_total") == (
            h0 + 1
        )
    finally:
        b.stop()
    assert got == _dense([(PREFIX + [88], dict(max_new_tokens=8))])[0]


def test_disagg_over_paged_pool_matches_dense():
    """Disaggregated prefill hands page-aligned rows to a paged decode
    batcher; streams match the dense batcher and blocks free."""
    ids = PREFIX + [12, 13]
    dense = _dense([(ids, dict(max_new_tokens=10))])[0]
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=4, paged_blocks=64, page_size=PAGE
    ).start()
    d = DisaggregatedLm(MODEL, PARAMS, batcher=b).start()
    try:
        got = d.submit(ids, max_new_tokens=10).result()
    finally:
        d.stop()
        b.stop()
    assert got == dense
    assert sorted(b._free_blocks) == list(range(1, 64))


def test_ngram_gate_falls_back_below_breakeven():
    """Sampled traffic on a random-init model accepts almost nothing —
    the adaptive gate must stop paying for ngram rounds (plain-round
    fallback), which is what keeps ngram never-slower-than-plain."""
    b = ContinuousBatcher(
        MODEL, PARAMS, slots=2, draft="ngram", spec_k=4,
    ).start()
    b.ngram_min_obs = 8
    b.ngram_probe_s = 1000.0
    try:
        out = b.submit(
            list(range(2, 22)), max_new_tokens=48, temperature=1.0, seed=3
        ).result()
        assert len(out) == 48
        st = b.spec_stats
    finally:
        b.stop()
    assert st["fallback_rounds"] > 0
    assert st["drafted"] > 0  # it measured before gating


def test_moe_paged_skips_sharing_but_serves():
    """MoE on the paged pool: no block sharing (chunked prefill would
    diverge from the one-shot oracle) but paged serving still works via
    the dense-splice path, and precache refuses loudly."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=128, use_flash=False, dtype=jnp.float32,
        num_experts=4,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(1))
    b = ContinuousBatcher(
        model, params, slots=2, paged_blocks=32, page_size=PAGE
    ).start()
    try:
        with pytest.raises(ValueError, match="MoE"):
            b.precache_prefix(PREFIX)
        got = b.submit(PREFIX + [9], max_new_tokens=6).result()
        assert len(got) == 6
        assert b._pool.cached_count == 0  # nothing registered
    finally:
        b.stop()
    assert sorted(b._free_blocks) == list(range(1, 32))
