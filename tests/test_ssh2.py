"""SSH-2 transport on the devenv gateway (platform/sshwire.py — RFC
4253/4252/4254 with the restricted suite curve25519-sha256 /
ssh-ed25519 / aes128-ctr / hmac-sha2-256): real key exchange, encrypted
packets, publickey auth and exec channels against live cluster state —
C24's standard-protocol half (GPU调度平台搭建.md:408-419)."""

import pytest

# The SSH-2 suite signs with real ed25519 keys; without the optional
# 'cryptography' package the whole module skips by name instead of
# failing collection.
pytest.importorskip(
    "cryptography",
    reason="ssh gateway tests need the optional 'cryptography' package",
)
from cryptography.hazmat.primitives.asymmetric.ed25519 import (  # noqa: E402
    Ed25519PrivateKey,
)

from k8s_gpu_tpu.api.core import Pod, Secret
from k8s_gpu_tpu.controller.kubefake import FakeKube
from k8s_gpu_tpu.platform.sshgate import SshGateway
from k8s_gpu_tpu.platform.sshwire import (
    Ssh2Client,
    SshError,
    authorized_key_line,
    parse_authorized_key,
)

KEY = Ed25519PrivateKey.generate()


@pytest.fixture()
def cluster():
    kube = FakeKube()
    pod = Pod()
    pod.metadata.name = "devenv-ada"
    pod.phase = "Running"
    pod.env["TPU_VISIBLE_CHIPS"] = "0,1"
    kube.create(pod)
    sec = Secret()
    sec.metadata.name = "user-ssh-ada"
    sec.data["authorized_keys"] = authorized_key_line(KEY, "ada@laptop")
    kube.create(sec)
    gw = SshGateway(kube).start()
    yield kube, gw
    gw.stop()


def test_handshake_auth_exec(cluster):
    kube, gw = cluster
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as c:
        out, status = c.exec("hostname")
        assert out.strip() == "devenv-ada" and status == 0
        out, status = c.exec("whoami")
        assert out.strip() == "ada" and status == 0
        out, status = c.exec("chips")
        assert out.strip() == "0,1"
        # unsupported command maps to a nonzero exit status
        out, status = c.exec("rm -rf /")
        assert status == 1 and "unsupported" in out


def test_wrong_key_rejected(cluster):
    kube, gw = cluster
    with pytest.raises(SshError, match="authentication failed"):
        Ssh2Client("127.0.0.1", gw.port, "ada",
                   Ed25519PrivateKey.generate())


def test_no_devenv_rejected(cluster):
    kube, gw = cluster
    with pytest.raises(SshError, match="authentication failed"):
        Ssh2Client("127.0.0.1", gw.port, "mallory", KEY)


def test_key_rotation_takes_effect_immediately(cluster):
    """Auth reads live cluster state per connection: rotating the
    Secret's key flips which private key gets in, no restart."""
    kube, gw = cluster
    new_key = Ed25519PrivateKey.generate()
    sec = kube.get("Secret", "user-ssh-ada")
    sec.data["authorized_keys"] = authorized_key_line(new_key)
    kube.update(sec)
    with pytest.raises(SshError):
        Ssh2Client("127.0.0.1", gw.port, "ada", KEY)
    with Ssh2Client("127.0.0.1", gw.port, "ada", new_key) as c:
        assert c.exec("whoami")[0].strip() == "ada"


def test_host_key_is_stable_across_connections(cluster):
    """The host key persists in a Secret — the known_hosts contract:
    two connections see the same identity."""
    kube, gw = cluster
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as a:
        blob_a = a.host_key_blob
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as b:
        assert b.host_key_blob == blob_a
    assert kube.try_get("Secret", "ssh-gateway-hostkey") is not None


def test_packet_tampering_detected(cluster):
    """Flipping one ciphertext byte must fail the HMAC, not decode."""
    kube, gw = cluster
    c = Ssh2Client("127.0.0.1", gw.port, "ada", KEY)
    try:
        # Corrupt the next outgoing packet's MAC key so the server's
        # verification fails: emulate by sending a valid-length packet
        # with a garbage MAC directly.
        import os

        c.conn.w.write(os.urandom(16 + 32))
        c.conn.w.flush()
        with pytest.raises(SshError):
            # server drops the connection; our next exec dies on read
            c.exec("hostname")
    finally:
        c.close()


def test_legacy_line_protocol_still_served_on_same_port(cluster):
    """Dual protocol: the line client (GatewayClient) and SSH-2 share
    one port — the first post-version byte routes."""
    from k8s_gpu_tpu.platform.sshgate import GatewayClient, GatewayError

    kube, gw = cluster
    line = kube.get("Secret", "user-ssh-ada").data["authorized_keys"]
    with GatewayClient("127.0.0.1", gw.port, "ada", line) as c:
        assert c.exec("whoami") == "ada"
    with Ssh2Client("127.0.0.1", gw.port, "ada", KEY) as c:
        assert c.exec("whoami")[0].strip() == "ada"


def test_authorized_key_roundtrip():
    line = authorized_key_line(KEY, "comment here")
    blob = parse_authorized_key(line)
    assert blob is not None
    assert parse_authorized_key("ssh-rsa AAAA nope") is None
    assert parse_authorized_key("garbage") is None
