"""Workload flight recorder & deterministic replay (ISSUE 19).

Synthetic-journal tests pin the wire format (two captures
byte-identical) and the diff gate under FakeClock; the integration
tests drive real tiny batchers — every terminal path must emit a
replayable journal record, and a greedy capture must replay byte-exact
through a fresh batcher and over live HTTP.  Named test_replay so it
lands inside the tier-1 window alongside the other serve-plane suites.
"""

import json
import urllib.request

import numpy as np
import pytest

from k8s_gpu_tpu.serve.journal import RequestJournal, RequestRecord, golden_hash
from k8s_gpu_tpu.serve.replay import (
    ReplayState,
    WorkloadRecorder,
    WorkloadReplayer,
    diff_bytes,
    diff_reports,
    export_gauges,
    load_workload,
    request_key,
    workload_bytes,
    workload_report,
)
from k8s_gpu_tpu.utils.alerts import RuleEvaluator, replay_rule_pack
from k8s_gpu_tpu.utils.clock import FakeClock
from k8s_gpu_tpu.utils.metrics import MetricsRegistry

TINY_KW = dict(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8,
    d_ff=64, max_seq=48, use_flash=False,
)


@pytest.fixture(scope="module")
def tiny_lm():
    import jax
    import jax.numpy as jnp

    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(dtype=jnp.float32, **TINY_KW)
    model = TransformerLM(cfg)
    return model, model.init(jax.random.PRNGKey(0))


# -- synthetic journal helpers -------------------------------------------------


def _rec(prompt, t_submit, t_done, *, toks=(5, 6, 7), reason="budget",
         tenant="default", seed=0, max_new=4, extra=None):
    """A fully-populated terminal record at a FIXED monotonic instant —
    the journal stamps seq/arrival_offset_s on append."""
    return RequestRecord(
        tenant=tenant, reason=reason, path="direct",
        prompt_ids=[int(t) for t in prompt], max_new=max_new,
        temperature=0.0, top_p=0.0, seed=seed, deadline_s=0.0,
        golden_hash=golden_hash(list(toks)), prompt_tokens=len(prompt),
        tokens=len(toks), queue_wait_s=0.002, ttft_s=0.01, tpot_s=0.001,
        t_submit=t_submit, t_done=t_done, extra=dict(extra or {}),
    )


def test_capture_two_runs_byte_identical():
    """Same journal contents -> byte-identical .workload, regardless of
    how many scrape passes assembled it; probe traffic is excluded and
    a second journal's offsets re-base onto the earliest origin."""
    j1, j2 = RequestJournal(), RequestJournal()
    j1.append(_rec([1, 2, 3], 100.0, 100.1))
    j1.append(_rec([1, 2, 3], 100.2, 100.3))          # occurrence 1
    j1.append(_rec([4, 5], 100.4, 100.5, tenant="chat", seed=7))
    j1.append(_rec([9], 100.6, 100.7, tenant="_canary",
                   extra={"probe": True}))            # dropped by default
    j2.append(_rec([8, 8], 100.5, 100.9, tenant="batch"))

    targets = {"a": j1, "b": j2}
    r1 = WorkloadRecorder(targets)
    r1.scrape_once()
    r2 = WorkloadRecorder(targets)
    r2.scrape_once()
    r2.scrape_once()  # overlap pass: (target, seq) dedup absorbs it

    b1, b2 = r1.workload_bytes(), r2.workload_bytes()
    assert b1 == b2
    w = load_workload(b1)
    reqs = w["requests"]
    assert len(reqs) == 4  # probe record excluded
    offs = [r["arrival_offset_s"] for r in reqs]
    assert offs == sorted(offs) and offs[0] == 0.0
    # j2's origin is 0.5s after j1's: its record keeps fleet-relative time.
    (b_entry,) = [r for r in reqs if r["tenant"] == "batch"]
    assert b_entry["arrival_offset_s"] == pytest.approx(0.5)
    # Two submissions of one reproduction tuple are occurrences 0 and 1.
    occ = sorted(r["occurrence"] for r in reqs if r["prompt_ids"] == [1, 2, 3])
    assert occ == [0, 1]
    # Everything here is greedy + completed + hashed -> verifiable.
    assert all(r["verify"] for r in reqs)
    # Round-trip: re-encoding the parsed object reproduces the bytes.
    assert workload_bytes(w) == b1


def test_recorder_seeded_cursor_excludes_warmup():
    """cursors= seeds the capture window: records at-or-before the
    seeded cursor never enter the workload."""
    j = RequestJournal()
    j.append(_rec([1], 10.0, 10.1))
    j.append(_rec([2], 10.2, 10.3))
    window = {"j": j.cursor}
    j.append(_rec([3], 10.4, 10.5))
    j.append(_rec([4], 10.6, 10.7))
    rec = WorkloadRecorder({"j": j}, cursors=window)
    rec.scrape_once()
    rec.scrape_once()
    got = sorted(r["prompt_ids"][0] for r in rec.workload()["requests"])
    assert got == [3, 4]


def test_load_workload_rejects_malformed():
    with pytest.raises(ValueError):
        load_workload(b"not json")
    with pytest.raises(ValueError):
        load_workload(b'{"version": 99, "requests": []}\n')
    bad = {"version": 1, "requests": [{"prompt_ids": [], "max_new": 1}]}
    with pytest.raises(ValueError):
        load_workload(json.dumps(bad).encode())


# -- /debug/requests?since= (the cursor contract) ------------------------------


def test_debug_requests_since_cursor_http():
    """Cursor rides in the body BEFORE-read semantics: resuming from
    the returned cursor yields exactly the later appends — no gaps, no
    leftovers — and equal state reads are byte-identical."""
    from k8s_gpu_tpu.utils.obs import MetricsServer

    j = RequestJournal()
    j.append(_rec([1], 1.0, 1.1))
    j.append(_rec([2], 1.2, 1.3))
    srv = MetricsServer(registry=MetricsRegistry(), journal=j)
    srv.start()
    try:
        def get(q=""):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/requests{q}"
            ) as r:
                return r.read()

        a, b = get(), get()
        assert a == b  # same journal state -> same bytes
        body = json.loads(a)
        assert body["cursor"] == j.cursor == 2
        assert len(body["requests"]) == 2
        cur = body["cursor"]
        # Nothing new yet: the delta from the cursor is empty.
        empty = json.loads(get(f"?since={cur}"))
        assert empty["requests"] == [] and empty["cursor"] == cur
        j.append(_rec([3], 1.4, 1.5))
        delta = json.loads(get(f"?since={cur}"))
        assert [r["prompt_ids"] for r in delta["requests"]] == [[3]]
        assert delta["cursor"] == cur + 1
        # Resuming from the NEW cursor again yields nothing — no dups.
        assert json.loads(get(f"?since={delta['cursor']}"))["requests"] == []
    finally:
        srv.stop()


# -- every terminal path is replayable (the flight-recorder guarantee) ---------

_REPLAY_FIELDS = (
    "prompt_ids", "max_new", "temperature", "top_p", "seed",
    "arrival_offset_s", "deadline_s",
)


def test_every_terminal_path_emits_replayable_record(tiny_lm):
    """budget / deadline / queue_full / aborted / eos: each terminal
    reason lands a journal record carrying the full reproduction tuple,
    and the recorder classifies verifiability correctly."""
    from k8s_gpu_tpu.serve import ContinuousBatcher
    from k8s_gpu_tpu.serve.batcher import Overloaded

    model, params = tiny_lm
    ja = RequestJournal()
    # Paged + single-step rounds: admission is unfused and stop() is
    # checked after every emitted token, so the abort below provably
    # cuts mid-stream — an idle DENSE batcher fuses admission with a
    # multi-token round whose fetch can deliver the whole budget in one
    # burst, racing both the Overloaded window and the abort on a
    # loaded 1-core box.
    a = ContinuousBatcher(
        model, params, slots=1, max_pending=1, paged_blocks=64,
        page_size=8, steps_per_round=1, metrics=MetricsRegistry(),
        journal=ja,
    ).start()
    p_long, p2 = [1, 2, 3, 4], [7, 8, 9]
    try:
        # budget (and the greedy stream the eos batcher below replays)
        h_long = a.submit(p_long, max_new_tokens=12)
        next(iter(h_long))  # seated: slot 0 is provably occupied
        h_pend = a.submit(p2, max_new_tokens=6)        # pending (slots=1)
        with pytest.raises(Overloaded):
            a.submit([5, 5], max_new_tokens=2)         # queue_full shed
        long_toks = [int(t) for t in h_long.result()]
        eos_toks = [int(t) for t in h_pend.result()]
        assert len(long_toks) == 12 and len(eos_toks) == 6
        # deadline: an already-expired absolute budget sheds at admission
        h_dead = a.submit(p_long, max_new_tokens=4, deadline=1e-9)
        assert h_dead.result() == [] and h_dead.deadline_expired
        # aborted: stop() cuts a live stream mid-decode
        h_ab = a.submit(p_long, max_new_tokens=40)
        next(iter(h_ab))
        a.stop()
        assert h_ab.aborted and 0 < len(h_ab.result()) < 40
    finally:
        a.stop()

    # eos: pick the first token of p2's greedy stream that hasn't
    # appeared before it — a batcher with that eos_id retires the same
    # prompt early with reason "eos" and a non-empty delivered prefix.
    cut = next(
        (i for i in range(1, len(eos_toks)) if eos_toks[i] not in eos_toks[:i]),
        None,
    )
    assert cut is not None, f"degenerate greedy stream {eos_toks}"
    jb = RequestJournal()
    b = ContinuousBatcher(
        model, params, slots=1, eos_id=eos_toks[cut],
        metrics=MetricsRegistry(), journal=jb,
    ).start()
    try:
        h_eos = b.submit(p2, max_new_tokens=6)
        assert [int(t) for t in h_eos.result()] == eos_toks[:cut]
    finally:
        b.stop()

    recs = ja.snapshot(limit=100) + jb.snapshot(limit=100)
    reasons = sorted(r["reason"] for r in recs)
    assert reasons == sorted(
        ["budget", "budget", "queue_full", "deadline", "aborted", "eos"]
    )
    for r in recs:
        for f in _REPLAY_FIELDS:
            assert f in r, f"reason={r['reason']} missing {f}"
        assert r["prompt_ids"] and r["max_new"] > 0
        assert isinstance(r["seed"], int)
    # The capture classifies them: completed greedy streams verify,
    # sheds/aborts ride along as load but are never hash-checked.
    w = WorkloadRecorder({"a": ja, "b": jb})
    w.scrape_once()
    by_reason = {r["reason"]: r for r in w.workload()["requests"]}
    assert by_reason["budget"]["verify"] and by_reason["eos"]["verify"]
    assert by_reason["eos"]["golden_hash"] == golden_hash(eos_toks[:cut])
    for shed in ("deadline", "queue_full", "aborted"):
        assert not by_reason[shed]["verify"]


# -- byte-exact replay through a real batcher ----------------------------------


def test_greedy_replay_byte_exact_and_mismatch_detection(tiny_lm):
    from k8s_gpu_tpu.serve import ContinuousBatcher

    model, params = tiny_lm
    jc = RequestJournal()
    c = ContinuousBatcher(
        model, params, slots=2, metrics=MetricsRegistry(), journal=jc,
    ).start()
    try:
        handles = [
            c.submit([1, 2, 3], max_new_tokens=5, tenant="search"),
            c.submit([1, 2, 3], max_new_tokens=5, tenant="search"),
            c.submit([4, 5], max_new_tokens=5, tenant="chat"),
            c.submit([6, 7, 8, 9], max_new_tokens=5, tenant="chat"),
        ]
        for h in handles:
            assert len(h.result()) == 5
    finally:
        c.stop()
    rec = WorkloadRecorder({"c": jc})
    rec.scrape_once()
    workload = rec.workload()
    assert len(workload["requests"]) == 4
    assert all(r["verify"] for r in workload["requests"])

    jd = RequestJournal()
    d = ContinuousBatcher(
        model, params, slots=2, metrics=MetricsRegistry(), journal=jd,
    ).start()
    reg = MetricsRegistry()
    try:
        rep = WorkloadReplayer(registry=reg, time_scale=0.0).run(
            workload, batcher=d,
        )
        t = rep["totals"]
        assert (t["requests"], t["verified"], t["matched"]) == (4, 4, 4)
        assert t["mismatches"] == 0 and t["errors"] == 0
        assert reg.counter("replay_requests_total") == 4.0
        assert reg.counter("replay_mismatch_total") == 0.0
        # Segment attribution came from the replay journal, not zeros.
        assert any(e["segments"]["prefill"] > 0 for e in rep["requests"])

        # Corrupt one golden: the replay must notice — wrong bytes gate.
        bad = json.loads(workload_bytes(workload).decode())
        bad["requests"][0]["golden_hash"] = "0" * 16
        rep2 = WorkloadReplayer(registry=reg, time_scale=0.0).run(
            bad, batcher=d,
        )
        assert rep2["totals"]["mismatches"] == 1
        assert reg.counter("replay_mismatch_total") == 1.0
        flagged = [e for e in rep2["requests"] if e["match"] is False]
        assert len(flagged) == 1 and flagged[0]["replay_hash"] != "0" * 16
        diff = diff_reports(workload_report(bad), rep2,
                            rel_threshold=10.0, abs_floor_s=10.0)
        assert diff["regression"] and diff["mismatches"] == 1
    finally:
        d.stop()


# -- arrival pacing ------------------------------------------------------------


class _AutoClock(FakeClock):
    """FakeClock whose sleep() advances itself — single-threaded
    deterministic pacing (nobody else drives the clock)."""

    def sleep(self, seconds: float) -> None:
        self.advance(max(0.0, seconds))


class _FakeHandle:
    def __init__(self, toks):
        self._toks = list(toks)

    def result(self):
        return list(self._toks)


class _FakeBatcher:
    """submit-shaped recorder: logs (fake-clock instant, prompt)."""

    def __init__(self, clock):
        self.clock = clock
        self.journal = RequestJournal()
        self.submits = []

    def submit(self, ids, **kw):
        self.submits.append((self.clock.now(), tuple(int(t) for t in ids)))
        return _FakeHandle([1, 2])


def test_time_scaled_arrivals_preserve_ordering():
    clock = _AutoClock()
    fb = _FakeBatcher(clock)
    prompts = [[1], [2], [3]]
    offsets = [0.0, 0.1, 0.3]
    workload = {"version": 1, "requests": [
        {
            "key": request_key(p, 4, 0.0, 0.0, 0, "default"),
            "occurrence": 0, "arrival_offset_s": off, "prompt_ids": p,
            "max_new": 4, "temperature": 0.0, "top_p": 0.0, "seed": 0,
            "tenant": "default", "deadline_s": 0.0, "verify": False,
            "golden_hash": "", "ttft_s": 0.0, "tpot_s": 0.0, "e2e_s": 0.0,
        }
        for p, off in zip(prompts, offsets)
    ]}
    rep = WorkloadReplayer(
        clock=clock, registry=MetricsRegistry(), time_scale=2.0,
    ).run(workload, batcher=fb)
    assert rep["totals"]["requests"] == 3
    assert [p for _, p in fb.submits] == [(1,), (2,), (3,)]
    # Inter-arrival gaps stretched exactly 2x on the injected clock.
    times = [t for t, _ in fb.submits]
    assert times == pytest.approx([0.0, 0.2, 0.6])


# -- live-fleet HTTP replay + the obs replay CLI -------------------------------


def test_http_replay_and_cli_roundtrip(tiny_lm, tmp_path):
    """record (scrape over HTTP) -> run (re-inject over /generate) ->
    diff: the full CLI loop, exit codes as the CI contract — then a
    corrupted golden flips `run` non-zero."""
    from k8s_gpu_tpu.cli.main import main
    from k8s_gpu_tpu.data import BpeTokenizer
    from k8s_gpu_tpu.serve import LmServer
    from k8s_gpu_tpu.utils.obs import MetricsServer

    model, params = tiny_lm
    tok = BpeTokenizer.train("aa bb cc dd " * 30, vocab_size=80)
    srv_rec = LmServer(model, params, tok, metrics=MetricsRegistry())
    srv_rec._thread.start()
    srv_rec.batcher.start()
    obs_rec = MetricsServer(
        registry=MetricsRegistry(), journal=srv_rec.journal,
    ).start()
    srv_play = LmServer(model, params, tok, metrics=MetricsRegistry())
    srv_play._thread.start()
    srv_play.batcher.start()
    obs_play = MetricsServer(
        registry=MetricsRegistry(), journal=srv_play.journal,
    ).start()

    def post(port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    wl = tmp_path / "capture.workload"
    run = tmp_path / "run.json"
    try:
        for ids in ([1, 2, 3], [4, 5], [1, 2, 3]):
            out = post(srv_rec.port, {
                "prompt_ids": ids, "max_new_tokens": 4, "temperature": 0.0,
            })
            assert len(out["ids"]) == 4
        # Journal-before-close: the records exist once /generate answered.
        rc = main([
            "obs", "replay", "record",
            "--url", f"rec=http://127.0.0.1:{obs_rec.port}",
            "--out", str(wl),
        ])
        assert rc == 0
        w = load_workload(wl.read_bytes())
        assert len(w["requests"]) == 3
        assert all(r["verify"] for r in w["requests"])

        rc = main([
            "obs", "replay", "run", "--workload", str(wl),
            "--url", f"http://127.0.0.1:{srv_play.port}",
            "--journal-url", f"http://127.0.0.1:{obs_play.port}",
            "--time-scale", "0", "--out", str(run),
        ])
        assert rc == 0  # every golden matched over live HTTP
        rep = json.loads(run.read_bytes())
        t = rep["totals"]
        assert (t["verified"], t["matched"], t["mismatches"]) == (3, 3, 0)
        # Client-observed surplus is attributed to the fleet plane.
        assert all("gateway_route" in e["segments"] for e in rep["requests"])

        # diff capture-vs-run: thresholds wide open -> no regression.
        rc = main([
            "obs", "replay", "diff", "--baseline", str(wl),
            "--candidate", str(run),
            "--threshold", "1000", "--floor-ms", "100000",
        ])
        assert rc == 0

        # Corrupt a golden in the capture: the run gate flips non-zero.
        w["requests"][0]["golden_hash"] = "0" * 16
        wl.write_bytes(workload_bytes(w))
        rc = main([
            "obs", "replay", "run", "--workload", str(wl),
            "--url", f"http://127.0.0.1:{srv_play.port}",
            "--time-scale", "0",
        ])
        assert rc == 1

        # obs requests --since: the cursor-delta view renders cleanly.
        cur = srv_rec.journal.cursor
        assert main([
            "obs", "requests",
            "--url", f"http://127.0.0.1:{obs_rec.port}",
            "--since", str(max(0, cur - 1)),
        ]) == 0
    finally:
        obs_rec.stop()
        obs_play.stop()
        srv_rec.stop()
        srv_play.stop()


# -- diff gate -----------------------------------------------------------------


def _entry(key, occ, *, ttft, e2e, segs, match=None):
    return {
        "key": key, "occurrence": occ, "tenant": "default",
        "reason": "budget", "tokens": 4, "verify": match is not None,
        "match": match, "golden_hash": "", "replay_hash": "", "error": "",
        "ttft_s": ttft, "tpot_s": 0.001, "e2e_s": e2e, "segments": segs,
    }


def _report(entries):
    return {
        "version": 1, "source": "replay", "target": "batcher",
        "time_scale": 1.0, "requests": entries, "totals": {},
    }


def test_diff_double_gate_and_byte_identity():
    """A segment stars only past BOTH gates (abs floor + relative
    threshold); sub-floor jitter never regresses; equal inputs produce
    byte-identical diff bytes."""
    keys = [request_key([i], 4, 0.0, 0.0, 0, "default") for i in range(3)]
    base = _report([
        _entry(k, 0, ttft=0.010, e2e=0.05, segs={
            "queue_wait": 0.002, "prefill": 0.008, "decode": 0.040,
            "unattributed": 0.0,
        })
        for k in keys
    ])
    # prefill doubles (+8ms/request, past floor+threshold); decode
    # wobbles +0.1ms/request (sub-floor jitter).
    cand = _report([
        _entry(k, 0, ttft=0.018, e2e=0.0581, segs={
            "queue_wait": 0.002, "prefill": 0.016, "decode": 0.0401,
            "unattributed": 0.0,
        })
        for k in keys
    ])
    d = diff_reports(base, cand, rel_threshold=0.10, abs_floor_s=0.005)
    assert d["matched"] == 3 and d["regression"]
    assert d["regressed_segments"] == ["prefill"]
    assert d["segments"]["prefill"]["ratio"] == pytest.approx(2.0)
    assert not d["segments"]["decode"]["regressed"]
    assert d["ttft"]["ratio"] == pytest.approx(1.8)
    assert diff_bytes(d) == diff_bytes(
        diff_reports(base, cand, rel_threshold=0.10, abs_floor_s=0.005)
    )
    # A mismatch gates even with zero latency movement.
    cand_bad = _report([
        _entry(keys[0], 0, ttft=0.010, e2e=0.05, segs={
            "queue_wait": 0.002, "prefill": 0.008, "decode": 0.040,
            "unattributed": 0.0,
        }, match=False),
    ])
    d2 = diff_reports(base, cand_bad, rel_threshold=10.0, abs_floor_s=10.0)
    assert d2["regression"] and d2["mismatches"] == 1
    assert d2["regressed_segments"] == []


# -- /debug/replay + the alert gate --------------------------------------------


def test_replay_state_endpoint_byte_stable():
    from k8s_gpu_tpu.utils.obs import MetricsServer

    keys = [request_key([1], 4, 0.0, 0.0, 0, "default")]
    base = _report([_entry(keys[0], 0, ttft=0.01, e2e=0.05, segs={
        "queue_wait": 0.0, "prefill": 0.01, "decode": 0.04,
        "unattributed": 0.0,
    })])
    state = ReplayState()
    state.publish_report(base)
    state.publish_diff(diff_reports(base, base))
    srv = MetricsServer(registry=MetricsRegistry(), replay=state)
    srv.start()
    try:
        def get():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/replay"
            ) as r:
                return r.read()

        a, b = get(), get()
        assert a == b
        body = json.loads(a)
        assert body["report"]["totals"] is not None
        assert body["diff"]["regression"] is False
    finally:
        srv.stop()


def test_replay_regression_rule_fires_and_resolves():
    """export_gauges feeds the alert plane: a >1.2x TTFT diff fires
    ReplayRegression; a healthy diff resolves it.  A mismatch-counter
    bump fires ReplayMismatch (page)."""
    reg = MetricsRegistry()
    clock = FakeClock()
    ev = RuleEvaluator(
        replay_rule_pack(regression_x=1.2), clock=clock, registry=reg,
    )
    keys = [request_key([1], 4, 0.0, 0.0, 0, "default")]
    segs = {"queue_wait": 0.0, "prefill": 0.01, "decode": 0.04,
            "unattributed": 0.0}
    base = _report([_entry(keys[0], 0, ttft=0.010, e2e=0.05, segs=segs)])
    slow = _report([_entry(keys[0], 0, ttft=0.050, e2e=0.09, segs=segs)])

    export_gauges(diff_reports(base, slow), reg)
    assert reg.gauge("replay_ttft_regression_x") == pytest.approx(5.0)
    ev.evaluate_once()
    clock.advance(30)
    ev.evaluate_once()
    names = {a["alertname"] for a in ev.active_alerts()}
    assert "ReplayRegression" in names

    export_gauges(diff_reports(base, base), reg)
    clock.advance(30)
    ev.evaluate_once()
    names = {a["alertname"] for a in ev.active_alerts()}
    assert "ReplayRegression" not in names

    reg.inc("replay_mismatch_total")
    clock.advance(30)
    ev.evaluate_once()
    assert any(
        a["alertname"] == "ReplayMismatch" and a["severity"] == "page"
        for a in ev.active_alerts()
    )
