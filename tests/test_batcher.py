"""Continuous batching: parity with the one-shot generate path, true
interleaving of concurrent requests, and tp-sharded serving.

The reference serves requests strictly sequentially through Ollama
(智能风控解决方案.md:250-266); the batcher is the TPU-native upgrade —
VERDICT r2 weak #2's done-criteria live here."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.parallel.mesh import MeshConfig, build_mesh
from k8s_gpu_tpu.parallel.sharding import shard_params
from k8s_gpu_tpu.serve import ContinuousBatcher, InferenceEngine

TINY = TransformerConfig(
    vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
    d_ff=96, max_seq=64, use_flash=False, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def setup():
    model = TransformerLM(TINY)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def _reference_greedy(model, params, ids, n):
    """Oracle: step-by-step full forward, argmax each step."""
    seq = jnp.asarray(ids, jnp.int32)[None, :]
    out = []
    for _ in range(n):
        logits, _ = model.forward(params, seq)
        nxt = int(jnp.argmax(logits[:, -1], axis=-1)[0])
        out.append(nxt)
        seq = jnp.concatenate([seq, jnp.asarray([[nxt]], jnp.int32)], axis=1)
    return out


def test_single_request_matches_oracle(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        ids = [5, 9, 17]
        got = b.submit(ids, max_new_tokens=6).result()
        assert got == _reference_greedy(model, params, ids, 6)
    finally:
        b.stop()


def test_concurrent_requests_match_oracle_and_interleave(setup):
    """Two requests submitted together must (a) both match the sequential
    oracle — slots don't contaminate each other — and (b) share decode
    steps: the interleave log must show both slots emitting within the
    same step window (the continuous-batching property)."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=4).start()
    try:
        ids_a = [3, 7, 11, 19, 4]
        ids_b = [2, 2, 8]
        ha = b.submit(ids_a, max_new_tokens=12)
        hb = b.submit(ids_b, max_new_tokens=12)
        got_a = ha.result()
        got_b = hb.result()
        assert got_a == _reference_greedy(model, params, ids_a, 12)
        assert got_b == _reference_greedy(model, params, ids_b, 12)
        log = b.interleave_log
        slots = {s for _, s in log}
        assert len(slots) == 2
        # Steps where each slot emitted; they must overlap in time.
        steps = {s: {st for st, sl in log if sl == s} for s in slots}
        s1, s2 = list(steps.values())
        assert s1 & s2, f"no shared decode steps: {steps}"
    finally:
        b.stop()


def test_late_admission_interleaves(setup):
    """A request submitted mid-decode joins the running batch instead of
    waiting for the first to finish: its emit steps must start before the
    first request's last step."""
    model, params = setup
    # Small rounds → many scheduler rounds for A, so B demonstrably joins
    # while A is still decoding even with the pipelined dispatcher.  Solo
    # amortization is pinned off (bucket == steps_per_round): with it on,
    # a 40-token A's whole budget is legitimately in flight before B
    # arrives (budget-aware tail-sizing) and the rounds can't be shared —
    # the solo path has its own test below.
    b = ContinuousBatcher(model, params, slots=4, steps_per_round=2)
    b.solo_buckets = [2]
    b.start()
    try:
        ha = b.submit([1, 2, 3], max_new_tokens=40)
        # Wait until A is demonstrably mid-decode.
        it = iter(ha)
        first_a = [next(it) for _ in range(3)]
        hb = b.submit([9, 9], max_new_tokens=4)
        got_b = hb.result()
        rest_a = list(it)
        got_a = first_a + rest_a
        assert got_a == _reference_greedy(model, params, [1, 2, 3], 40)
        assert got_b == _reference_greedy(model, params, [9, 9], 4)
        log = b.interleave_log
        a_slot = log[0][1]
        b_steps = [st for st, sl in log if sl != a_slot]
        a_steps = [st for st, sl in log if sl == a_slot]
        assert b_steps, "B never emitted"
        assert min(b_steps) < max(a_steps), "B waited for A to finish"
    finally:
        b.stop()


def test_eos_retires_slot(setup):
    model, params = setup
    ids = [1, 2, 3]  # greedy continuation is non-repeating for this prompt
    oracle = _reference_greedy(model, params, ids, 8)
    assert oracle[3] not in oracle[:3], "test needs a distinct 4th token"
    eos = oracle[3]  # force an early stop on the 4th token
    b = ContinuousBatcher(model, params, slots=2, eos_id=eos).start()
    try:
        got = b.submit(ids, max_new_tokens=8).result()
        assert got == oracle[:3]  # EOS itself not emitted
    finally:
        b.stop()


def test_budget_and_slot_reuse(setup):
    """More requests than slots: all complete, all correct (slots recycle)."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        prompts = [[i + 1, i + 2] for i in range(5)]
        handles = [b.submit(p, max_new_tokens=4) for p in prompts]
        for p, h in zip(prompts, handles):
            assert h.result() == _reference_greedy(model, params, p, 4)
    finally:
        b.stop()


def test_sampled_requests_are_seeded(setup):
    """temperature>0: same seed → same stream; the point is per-request
    PRNG isolation inside the shared batch."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        a = b.submit([4, 5], max_new_tokens=6, temperature=0.9, seed=7).result()
        c = b.submit([4, 5], max_new_tokens=6, temperature=0.9, seed=7).result()
        assert a == c
        assert len(a) == 6
    finally:
        b.stop()


def test_tp_sharded_serving_matches_unsharded(setup):
    """dp×tp mesh: tp-sharded projections + sharded KV cache produce the
    same greedy tokens as the unsharded engine (VERDICT r2 weak #2)."""
    model, params = setup
    n = jax.device_count()
    if n < 4:
        pytest.skip("needs the 8-device CPU mesh (conftest sets it)")
    mesh = build_mesh(MeshConfig(dp=1, tp=4), n_devices=4)
    sharded = shard_params(params, model.logical_axes(), mesh)
    b = ContinuousBatcher(model, sharded, slots=2, mesh=mesh).start()
    try:
        ids = [5, 9, 17, 23]
        got = b.submit(ids, max_new_tokens=6).result()
        assert got == _reference_greedy(model, params, ids, 6)
    finally:
        b.stop()


def test_engine_mesh_generate_matches_unsharded(setup):
    """The plain generate path also runs tp-sharded (engine mesh arg)."""
    model, params = setup
    n = jax.device_count()
    if n < 4:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = build_mesh(MeshConfig(dp=1, tp=4), n_devices=4)
    sharded = shard_params(params, model.logical_axes(), mesh)
    eng_s = InferenceEngine(model, mesh=mesh)
    eng_u = InferenceEngine(model)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 7), 0, 128)
    a = eng_s.generate(sharded, prompt, max_new_tokens=5)
    c = eng_u.generate(params, prompt, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(a.tokens), np.asarray(c.tokens))


def test_submit_after_stop_raises_and_inflight_marked_aborted(setup):
    """Lifecycle: stop() drains waiting requests with aborted=True, and a
    later submit fails fast instead of deadlocking the caller."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    h = b.submit([5, 9], max_new_tokens=50)
    b.stop()
    h.result()  # must return (possibly truncated), never hang
    with pytest.raises(RuntimeError, match="stopped"):
        b.submit([1], max_new_tokens=2)


def test_handle_reiteration_replays(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        h = b.submit([5, 9, 17], max_new_tokens=4)
        first = list(h)
        again = h.result()
        assert first == again and len(first) == 4
        assert h.aborted is False
    finally:
        b.stop()


# -- prefix caching ---------------------------------------------------------

def test_prefix_hit_matches_oracle(setup):
    """A prompt extending a cached prefix decodes exactly like the
    uncached path (the suffix-extension admission is just a re-chunked
    prefill)."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        prefix = [7, 3, 11, 19, 2]
        b.precache_prefix(prefix)
        ids = prefix + [23, 29]
        got = b.submit(ids, max_new_tokens=6).result()
        assert got == _reference_greedy(model, params, ids, 6)
    finally:
        b.stop()


def test_exact_prefix_admits_without_forward(setup):
    """A prompt that IS a cached prefix must admit via splice+sample —
    no prefill and no extend run on the admit path."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        prefix = [5, 9, 17, 4]
        b.precache_prefix(prefix)
        calls = []
        orig_prefill = b.engine.prefill
        orig_extend = b.engine.extend_multi
        b.engine.prefill = lambda *a, **k: (
            calls.append("prefill") or orig_prefill(*a, **k)
        )
        b.engine.extend_multi = lambda *a, **k: (
            calls.append("extend") or orig_extend(*a, **k)
        )
        got = b.submit(prefix, max_new_tokens=5).result()
        assert got == _reference_greedy(model, params, prefix, 5)
        assert calls == [], calls  # admission was splice-only
    finally:
        b.stop()


def test_prefix_lru_eviction_and_miss(setup):
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        b._prefix_cap = 2
        b.precache_prefix([1, 2, 3])
        b.precache_prefix([4, 5, 6])
        b.precache_prefix([7, 8, 9])  # evicts [1,2,3]
        assert len(b._prefix) == 2
        # evicted prefix now misses → plain path still correct
        ids = [1, 2, 3, 30]
        got = b.submit(ids, max_new_tokens=4).result()
        assert got == _reference_greedy(model, params, ids, 4)
        # longest-prefix wins: precache a longer overlapping prefix
        b.precache_prefix([7, 8])
        ids2 = [7, 8, 9, 40]
        got2 = b.submit(ids2, max_new_tokens=4).result()
        assert got2 == _reference_greedy(model, params, ids2, 4)
    finally:
        b.stop()


def test_prefix_and_plain_requests_interleave(setup):
    """Mixed traffic: prefix-hit and cold requests share decode rounds
    and each matches its oracle."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=4).start()
    try:
        prefix = [2, 4, 6, 8]
        b.precache_prefix(prefix)
        warm_ids = prefix + [10]
        cold_ids = [9, 7, 5]
        h1 = b.submit(warm_ids, max_new_tokens=6)
        h2 = b.submit(cold_ids, max_new_tokens=6)
        assert h1.result() == _reference_greedy(model, params, warm_ids, 6)
        assert h2.result() == _reference_greedy(model, params, cold_ids, 6)
    finally:
        b.stop()


def test_prefix_cache_refused_for_moe():
    """Capacity-capped MoE dispatch couples tokens across the dispatch
    group — chunked prefill can't match the one-shot oracle, so the
    batcher refuses rather than serve silently diverging streams."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=48, n_layers=2, n_heads=4, d_head=12,
        d_ff=96, max_seq=64, use_flash=False, dtype=jnp.float32,
        num_experts=4,
    )
    model = TransformerLM(cfg)
    b = ContinuousBatcher(model, model.init(jax.random.PRNGKey(0)), slots=2)
    with pytest.raises(ValueError, match="MoE"):
        b.precache_prefix([1, 2, 3])


def test_serving_metrics_recorded(setup):
    """C32 for the serving stack: admissions by path, slot gauge, and
    completion counters land in the shared registry."""
    from k8s_gpu_tpu.utils.metrics import global_metrics

    model, params = setup
    b = ContinuousBatcher(model, params, slots=2).start()
    try:
        b.precache_prefix([5, 9, 17])
        cold0 = sum(
            global_metrics.counter("serve_admissions_total", path=p)
            for p in ("cold", "cold_fused")
        )
        b.submit([5, 9, 17, 4], max_new_tokens=3).result()   # prefix_suffix
        b.submit([5, 9, 17], max_new_tokens=3).result()      # prefix_exact
        b.submit([8, 6], max_new_tokens=3).result()          # cold (fused
        # when the batcher happens to be idle at admit — either path)
        rendered = global_metrics.render()
        for path in ("prefix_suffix", "prefix_exact"):
            assert f'serve_admissions_total{{path="{path}"}}' in rendered, path
        cold1 = sum(
            global_metrics.counter("serve_admissions_total", path=p)
            for p in ("cold", "cold_fused")
        )
        assert cold1 == cold0 + 1, (cold0, cold1)
        assert "serve_completions_total" in rendered
        assert global_metrics.gauge("serve_slots_active") == 0.0
        # Latency budget surface: queue wait, TTFT, inter-token gap.
        for h in ("serve_queue_wait_seconds", "serve_ttft_seconds",
                  "serve_inter_token_seconds"):
            hist = global_metrics.histogram(h)
            assert hist is not None and hist.n >= 1, h
            assert hist.mean >= 0.0, h
    finally:
        b.stop()


def test_logprobs_parallel_and_correct(setup):
    """handle.logprobs aligns with result() and each value equals the
    oracle's log-softmax at the emitted token (greedy)."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2, logprobs=True).start()
    try:
        ids = [5, 9, 17]
        h = b.submit(ids, max_new_tokens=5)
        toks = h.result()
        lps = h.logprobs
        assert len(lps) == len(toks) == 5
        seq = jnp.asarray(ids, jnp.int32)[None, :]
        for tok, lp in zip(toks, lps):
            logits, _ = model.forward(params, seq)
            ref = float(jax.nn.log_softmax(
                logits[0, -1].astype(jnp.float32))[tok])
            assert abs(lp - ref) < 1e-4, (tok, lp, ref)
            seq = jnp.concatenate(
                [seq, jnp.asarray([[tok]], jnp.int32)], axis=1
            )
    finally:
        b.stop()


def test_solo_rounds_amortize_dispatches(setup):
    """A single live request runs LONG round variants (solo_buckets,
    up to 8x steps_per_round): same oracle-exact stream, far fewer
    dispatches — the single-stream-overhead fix (VERDICT r3 weak
    #2/ask #4)."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2, steps_per_round=2).start()
    try:
        ids = [5, 9, 17]
        got = b.submit(ids, max_new_tokens=33).result()
        assert got == _reference_greedy(model, params, ids, 33)
        # 32 post-admit tokens at 8/solo-round = 4 rounds (+ inflight
        # slack); the short variant alone would need 16.
        assert b.steps_taken <= 8, b.steps_taken
    finally:
        b.stop()
    # Two co-tenants: back to the short variant, still oracle-exact.
    b = ContinuousBatcher(model, params, slots=2, steps_per_round=2).start()
    try:
        ha = b.submit([5, 9, 17], max_new_tokens=8)
        hb = b.submit([2, 4, 8], max_new_tokens=8)
        assert ha.result() == _reference_greedy(model, params, [5, 9, 17], 8)
        assert hb.result() == _reference_greedy(model, params, [2, 4, 8], 8)
    finally:
        b.stop()


def test_budget_gate_no_garbage_rounds(setup):
    """The scheduler never dispatches a round past every live row's
    remaining budget: a 5-token solo request is one admit + ONE tail-
    sized round (bucket 4 covers rem=4), not a pipeline of full-width
    garbage rounds that no stream can consume."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=4, steps_per_round=4).start()
    try:
        got = b.submit([5, 9, 17], max_new_tokens=5).result()
        assert got == _reference_greedy(model, params, [5, 9, 17], 5)
        # Give the scheduler a beat to (wrongly) dispatch extra rounds.
        time.sleep(0.2)
        assert b.steps_taken == 1, b.steps_taken
    finally:
        b.stop()


def test_solo_tail_round_sized_to_budget(setup):
    """Tail-sizing picks the smallest solo bucket covering the remaining
    budget.  A cold solo 14-token request at steps_per_round=2 runs the
    fused admit(+2-step) dispatch, leaving 11 tokens — covered by ONE
    12-step tail round (ladder 2/4/6/8/12/16), not 8+8 or 4x bigger:
    exactly 2 dispatches total."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=2, steps_per_round=2).start()
    try:
        got = b.submit([5, 9, 17], max_new_tokens=14).result()
        assert got == _reference_greedy(model, params, [5, 9, 17], 14)
        time.sleep(0.2)
        assert b.steps_taken == 2, b.steps_taken
    finally:
        b.stop()


def test_nucleus_mask_identity_when_off():
    """Rows with top_p off pass through nucleus_mask BIT-identical —
    float cumsum can hit 1.0 before the tail, so `before < 1.0` alone
    would clip it for a top_p-off row sharing a round with a top-p
    request (co-tenant-dependent streams)."""
    from k8s_gpu_tpu.serve.engine import nucleus_mask

    # One dominant logit: softmax ≈ [1, 0, 0, ...] and the cumsum
    # reaches 1.0 at position 1 in float32.
    scaled = jnp.asarray([[40.0, 0.0, -1.0, -2.0],
                          [40.0, 0.0, -1.0, -2.0]], jnp.float32)
    out = nucleus_mask(scaled, jnp.asarray([0.0, 0.0]))
    assert np.array_equal(np.asarray(out), np.asarray(scaled))
    # Mixed rows: row 0 masks to its nucleus, row 1 stays identical.
    out = nucleus_mask(scaled, jnp.asarray([0.5, 0.0]))
    assert np.isneginf(np.asarray(out)[0, 1:]).all()
    assert np.array_equal(np.asarray(out)[1], np.asarray(scaled)[1])


def test_top_p_requests_sample_from_nucleus(setup):
    """Per-request nucleus: a top_p row's emissions come only from the
    top of its per-step distribution, while a greedy row in the same
    rounds is untouched (oracle-exact)."""
    model, params = setup
    b = ContinuousBatcher(model, params, slots=3).start()
    try:
        ids = [5, 9, 17]
        greedy_ref = _reference_greedy(model, params, ids, 6)
        h_greedy = b.submit(ids, max_new_tokens=6)
        h_p = b.submit(ids, max_new_tokens=6, temperature=1.0, top_p=0.5,
                       seed=3)
        assert h_greedy.result() == greedy_ref
        toks = h_p.result()
        # every sampled token lies in that step's 0.5-nucleus
        seq = jnp.asarray(ids, jnp.int32)[None, :]
        for tok in toks:
            logits, _ = model.forward(params, seq)
            p = np.asarray(jax.nn.softmax(logits[0, -1].astype(jnp.float32)))
            order = np.argsort(p)[::-1]
            before = np.cumsum(p[order]) - p[order]
            nucleus = set(order[before < 0.5].tolist())
            assert tok in nucleus, (tok, sorted(nucleus))
            seq = jnp.concatenate(
                [seq, jnp.asarray([[tok]], jnp.int32)], axis=1
            )
    finally:
        b.stop()


def test_fused_cold_solo_admission(setup):
    """An idle batcher admits a cold solo request through the fused
    admit+round dispatch (ONE device program — the single-stream latency
    story, VERDICT r3 ask #4) and the stream is oracle-exact; subsequent
    concurrent admissions take the normal path and still match."""
    from k8s_gpu_tpu.utils.metrics import global_metrics

    model, params = setup
    b = ContinuousBatcher(model, params, slots=3).start()
    try:
        ids = [5, 9, 17]
        # Counter DELTA, not substring presence: global_metrics is a
        # process singleton earlier tests already populate.
        before = global_metrics.counter(
            "serve_admissions_total", path="cold_fused"
        )
        got = b.submit(ids, max_new_tokens=9).result()
        assert got == _reference_greedy(model, params, ids, 9)
        after = global_metrics.counter(
            "serve_admissions_total", path="cold_fused"
        )
        assert after == before + 1, (before, after)
        # Concurrent pair: neither is alone, so both go unfused — and
        # every stream still matches the oracle.
        ha = b.submit(ids, max_new_tokens=6)
        hb = b.submit([2, 4, 8], max_new_tokens=6)
        assert ha.result() == _reference_greedy(model, params, ids, 6)
        assert hb.result() == _reference_greedy(model, params, [2, 4, 8], 6)
    finally:
        b.stop()


def test_fused_solo_eos_and_budget(setup):
    """EOS in the fused round's tokens retires mid-window; max_new=1
    (admit covers the budget) skips the fused path entirely."""
    model, params = setup
    ids = [5, 9, 17]
    ref = _reference_greedy(model, params, ids, 12)
    eos = ref[4]
    b = ContinuousBatcher(model, params, slots=2, eos_id=eos).start()
    try:
        got = b.submit(ids, max_new_tokens=12).result()
        assert got == ref[: ref.index(eos)]
        assert b.submit(ids, max_new_tokens=1).result() == ref[:1]
    finally:
        b.stop()


def test_fused_solo_seeded_sampling_matches_unfused(setup):
    """The fused path consumes PRNG exactly like admit+round: a seeded
    sampled request must produce the same stream fused (alone) and
    unfused (with a queued neighbor at submit time)."""
    model, params = setup

    def run(neighbor):
        b = ContinuousBatcher(model, params, slots=3).start()
        try:
            if neighbor:
                # Queue a neighbor FIRST so the target admit is unfused.
                b.submit([2, 4, 8], max_new_tokens=8)
            h = b.submit([5, 9, 17], max_new_tokens=8, temperature=0.7,
                         seed=11)
            return h.result()
        finally:
            b.stop()

    assert run(False) == run(True)
