"""Slice-correct placement: gang semantics + multislice anti-affinity
(SURVEY §2.7; BASELINE configs 3-4), incl. review-found regressions."""

import pytest

from k8s_gpu_tpu.api.core import Node, Pod
from k8s_gpu_tpu.cloud.topology import parse_accelerator_type
from k8s_gpu_tpu.scheduling import (
    LABEL_WORKER_ID,
    PlacementError,
    TPU_RESOURCE,
    multislice_spread,
    place_gang,
    validate_slice_nodes,
)
from k8s_gpu_tpu.scheduling.labels import node_labels_for_host
from k8s_gpu_tpu.cloud.fake_cloudtpu import TpuHost


def make_slice_nodes(accel: str, slice_name: str, slice_index=0, pool="p"):
    topo = parse_accelerator_type(accel)
    nodes = []
    for w in range(topo.hosts):
        host = TpuHost(
            hostname=f"{slice_name}-w{w}",
            slice_name=slice_name,
            worker_id=w,
            chips=min(topo.generation.chips_per_host, topo.chips),
        )
        n = Node()
        n.metadata.name = host.hostname
        n.metadata.labels = node_labels_for_host(host, topo, pool, slice_index)
        n.capacity = {TPU_RESOURCE: host.chips}
        n.allocatable = {TPU_RESOURCE: host.chips}
        n.ready = True
        nodes.append(n)
    return nodes


def make_pods(n, prefix="job-w"):
    pods = []
    for i in range(n):
        p = Pod()
        p.metadata.name = f"{prefix}-{i}"
        p.requests = {TPU_RESOURCE: 4}
        pods.append(p)
    return pods


def test_validate_complete_slice():
    validate_slice_nodes(make_slice_nodes("v5p-64", "s0"), "v5p-64")


def test_validate_rejects_missing_host():
    nodes = make_slice_nodes("v5p-64", "s0")[:-1]
    with pytest.raises(PlacementError):
        validate_slice_nodes(nodes, "v5p-64")


def test_validate_rejects_mixed_slices():
    nodes = make_slice_nodes("v4-8", "s0") + make_slice_nodes("v4-8", "s1")
    with pytest.raises(PlacementError):
        validate_slice_nodes(nodes, "v4-8")


def test_gang_places_one_worker_per_host():
    nodes = make_slice_nodes("v4-8", "s0")
    pods = make_pods(2)
    placement = place_gang(pods, nodes, "v4-8")
    assert len(placement) == 2
    assert set(placement.values()) == {n.metadata.name for n in nodes}


def test_gang_worker_ordinals_align_numerically():
    """Regression (code review): 16-worker gang must map pod ordinal i to
    worker-id i — lexicographic name sort would send job-w-10 to host w2."""
    nodes = make_slice_nodes("v5p-64", "s0")
    pods = make_pods(16)
    placement = place_gang(pods, nodes, "v5p-64")
    node_by_name = {n.metadata.name: n for n in nodes}
    for i in range(16):
        assigned = node_by_name[placement[f"job-w-{i}"]]
        assert int(assigned.metadata.labels[LABEL_WORKER_ID]) == i


def test_gang_is_all_or_nothing():
    nodes = make_slice_nodes("v5p-64", "s0")[:10]  # incomplete slice
    with pytest.raises(PlacementError):
        place_gang(make_pods(16), nodes, "v5p-64")


def test_gang_wrong_worker_count_rejected():
    nodes = make_slice_nodes("v4-8", "s0")
    with pytest.raises(PlacementError):
        place_gang(make_pods(3), nodes, "v4-8")


def test_gang_skips_busy_slice():
    busy = make_slice_nodes("v4-8", "s0")
    for n in busy:
        n.allocatable[TPU_RESOURCE] = 0
    free = make_slice_nodes("v4-8", "s1")
    placement = place_gang(make_pods(2), busy + free, "v4-8")
    assert all(v.startswith("s1-") for v in placement.values())


def test_multislice_spread_distinct_slices():
    """BASELINE config 4: two worker groups land on two distinct slices."""
    nodes = make_slice_nodes("v5e-256", "s0", 0) + make_slice_nodes(
        "v5e-256", "s1", 1
    )
    groups = [make_pods(32, "g0-w"), make_pods(32, "g1-w")]
    placement = multislice_spread(groups, nodes, "v5e-256")
    node_by_name = {n.metadata.name: n for n in nodes}
    g0_slices = {
        node_by_name[placement[p.metadata.name]].metadata.labels["tpu.k8sgpu.dev/slice"]
        for p in groups[0]
    }
    g1_slices = {
        node_by_name[placement[p.metadata.name]].metadata.labels["tpu.k8sgpu.dev/slice"]
        for p in groups[1]
    }
    assert len(g0_slices) == 1 and len(g1_slices) == 1
    assert g0_slices != g1_slices


def test_multislice_insufficient_slices_rejected():
    nodes = make_slice_nodes("v4-8", "s0")
    with pytest.raises(PlacementError):
        multislice_spread([make_pods(2, "a"), make_pods(2, "b")], nodes, "v4-8")


def test_host_bounds_v5e_is_2x4():
    """Regression (code review): 8-chip hosts own a 2x4 subgrid, not 2x2."""
    t = parse_accelerator_type("v5e-16")
    assert t.host_bounds() == (2, 4)
    assert parse_accelerator_type("v5p-64").host_bounds() == (2, 2, 1)
