"""End-to-end span tracing: tracer mechanics (nesting, thread isolation,
ring bounds), W3C traceparent round-trips, the control-plane journey
(apiserver create → workqueue wait → reconcile → fake cloud call → Event),
the serving-plane journey (request → admission wait → batcher rounds), and
/debug/traces filtering.

(Named test_distributed_tracing, not test_tracing: the single-process
tier-1 run truncates alphabetically at its time budget, and this file
must sort inside the executed window to keep the tracing path exercised
there.)"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from k8s_gpu_tpu.api import TpuPodSlice
from k8s_gpu_tpu.cloud import FakeCloudTpu, cloudtpu_client_factory
from k8s_gpu_tpu.controller import FakeKube, Manager
from k8s_gpu_tpu.controller.manager import Request
from k8s_gpu_tpu.controller.workqueue import RateLimitingQueue
from k8s_gpu_tpu.operators import TpuPodSliceReconciler
from k8s_gpu_tpu.utils import MetricsRegistry, MetricsServer
from k8s_gpu_tpu.utils.tracing import (
    SpanContext,
    Tracer,
    format_traceparent,
    global_tracer,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    render_trace,
)


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    global_tracer.clear()
    yield
    global_tracer.clear()


def _ctx() -> SpanContext:
    return SpanContext(new_trace_id(), new_span_id())


def _names(node, out=None):
    out = [] if out is None else out
    out.append(node["name"])
    for c in node.get("children", ()):
        _names(c, out)
    return out


def _all_names(trace):
    out = []
    for root in trace["tree"]:
        _names(root, out)
    return out


# -- tracer mechanics -------------------------------------------------------

def test_span_nesting_and_assembly():
    tr = Tracer(registry=MetricsRegistry())
    with tr.span("root", who="test") as root:
        with tr.span("child-a"):
            with tr.span("leaf"):
                pass
        with tr.span("child-b"):
            pass
    t = tr.get_trace(root.trace_id)
    assert t["span_count"] == 4
    assert len(t["tree"]) == 1
    top = t["tree"][0]
    assert top["name"] == "root" and top["attributes"]["who"] == "test"
    assert [c["name"] for c in top["children"]] == ["child-a", "child-b"]
    assert top["children"][0]["children"][0]["name"] == "leaf"
    # durations nest: the parent covers its children
    assert top["duration_ms"] >= top["children"][0]["duration_ms"]


def test_span_error_status_propagates_and_reraises():
    tr = Tracer(registry=MetricsRegistry())
    with pytest.raises(ValueError):
        with tr.span("outer") as sp:
            raise ValueError("boom")
    t = tr.get_trace(sp.trace_id)
    assert t["tree"][0]["status"] == "error"
    assert "boom" in t["tree"][0]["attributes"]["error"]


def test_thread_local_isolation():
    """Concurrent threads must never cross-parent each other's spans."""
    tr = Tracer(registry=MetricsRegistry())
    ids = {}
    barrier = threading.Barrier(2)

    def work(tag):
        with tr.span(f"root-{tag}") as root:
            barrier.wait()  # both roots open simultaneously
            with tr.span(f"inner-{tag}"):
                time.sleep(0.01)
            ids[tag] = root.trace_id

    threads = [threading.Thread(target=work, args=(t,)) for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ids["a"] != ids["b"]
    for tag in ("a", "b"):
        trace = tr.get_trace(ids[tag])
        assert trace["span_count"] == 2
        assert _all_names(trace) == [f"root-{tag}", f"inner-{tag}"]


def test_explicit_propagation_use_and_add_span():
    tr = Tracer(registry=MetricsRegistry())
    ctx = _ctx()
    with tr.use(ctx):
        assert tr.current() == ctx
        with tr.span("child"):
            pass
    assert tr.current() is None
    tr.add_span("late", parent=ctx, start=1.0, end=2.5)
    t = tr.get_trace(ctx.trace_id)
    names = _all_names(t)
    assert "child" in names and "late" in names
    late = next(n for r in t["tree"] for n in [r] if n["name"] == "late")
    assert late["duration_ms"] == pytest.approx(1500.0)


def test_ring_buffer_eviction_and_span_cap_under_churn():
    reg = MetricsRegistry()
    tr = Tracer(max_traces=4, max_spans_per_trace=3, registry=reg)
    for _ in range(10):
        with tr.span("churn"):
            pass
    assert len(tr.traces(limit=100)) == 4
    assert reg.counter("tracing_dropped_total", kind="trace") == 6
    # Per-trace span cap: bounded, but a capped trace keeps its ORIGIN
    # plus the most RECENT spans (drops the middle) — a lifecycle trace
    # that requeues forever must not go dark after its first seconds.
    ctx = _ctx()
    for i in range(5):
        tr.add_span(f"s{i}", parent=ctx)
    t = tr.get_trace(ctx.trace_id)
    assert t["span_count"] == 3
    kept = {n["name"] for n in t["tree"]}
    assert kept == {"s0", "s3", "s4"}  # origin + rolling tail
    assert reg.counter("tracing_dropped_total", kind="span") == 2
    assert reg.counter("tracing_spans_total") == 10 + 5


# -- traceparent ------------------------------------------------------------

def test_traceparent_round_trip():
    ctx = _ctx()
    assert parse_traceparent(format_traceparent(ctx)) == ctx


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-abc-def-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",   # forbidden version
    "00-" + "g" * 32 + "-" + "b" * 16 + "-01",   # non-hex
])
def test_traceparent_rejects_malformed(bad):
    assert parse_traceparent(bad) is None


# -- workqueue carry --------------------------------------------------------

def test_workqueue_carries_trace_context():
    q = RateLimitingQueue()
    ctx = _ctx()
    with global_tracer.use(ctx):
        q.add(Request("default", "x"))
    key = q.get(block=False)
    assert key == Request("default", "x")
    carried, t_enq = q.pop_trace(key)
    assert carried == ctx and t_enq > 0
    # collected once; done() leaves nothing stale behind
    assert q.pop_trace(key) is None
    q.done(key)
    q.add(Request("default", "x"))  # untraced re-add
    key = q.get(block=False)
    assert q.pop_trace(key) is None


# -- control plane end-to-end ----------------------------------------------

@pytest.fixture
def control_plane(tmp_path):
    from k8s_gpu_tpu.platform.apiserver import PlatformApiServer
    from k8s_gpu_tpu.platform.assets import AssetStore

    kube = FakeKube()
    cloud = FakeCloudTpu()
    mgr = Manager(kube)
    mgr.register(
        "TpuPodSlice",
        TpuPodSliceReconciler(kube, cloudtpu_client_factory(cloud)),
    )
    mgr.start()
    api = PlatformApiServer(AssetStore(tmp_path), kube=kube).start()
    obs = MetricsServer().start()
    yield kube, mgr, api, obs
    obs.stop()
    api.stop()
    mgr.stop()


def _debug_traces(obs, **params):
    from urllib.parse import urlencode

    url = f"http://127.0.0.1:{obs.port}/debug/traces"
    if params:
        url += "?" + urlencode(params)
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())["traces"]


def test_create_request_links_queue_reconcile_cloud_and_event(control_plane):
    """The acceptance journey: ONE trace_id observably links the apiserver
    create to its workqueue wait, reconcile passes, cloud-call child
    spans, and the recorded Events — queried through /debug/traces."""
    kube, mgr, api, obs = control_plane
    ctx = _ctx()
    manifest = {
        "kind": "TpuPodSlice",
        "metadata": {"name": "traced", "namespace": "default"},
        "spec": {"acceleratorType": "v4-8", "sliceCount": 1},
    }
    req = urllib.request.Request(
        f"http://127.0.0.1:{api.port}/api/v1/objects",
        data=json.dumps(manifest).encode(),
        headers={"Content-Type": "application/json",
                 "traceparent": format_traceparent(ctx)},
    )
    with urllib.request.urlopen(req) as r:
        assert r.status == 201
        created = json.loads(r.read())
    # the server continued OUR trace rather than minting its own
    assert created["trace_id"] == ctx.trace_id

    assert mgr.wait_idle(
        timeout=30.0,
        predicate=lambda: (
            (ps := kube.try_get("TpuPodSlice", "traced")) is not None
            and ps.status.phase == "Ready"
        ),
    )
    # The http span closes AFTER the response bytes go out (same beat as
    # the RequestMetricsMixin counter note) and the zero-delay fake can
    # reach Ready first — poll briefly for the root to land.
    deadline = time.monotonic() + 5.0
    names, traces = [], []
    while time.monotonic() < deadline:
        traces = _debug_traces(obs, trace_id=ctx.trace_id)
        names = _all_names(traces[0]) if traces else []
        if any("http POST /api/v1/objects" in n for n in names):
            break
        time.sleep(0.02)
    assert len(traces) == 1
    assert any("http POST /api/v1/objects" in n for n in names), names
    assert "queue.wait" in names
    assert names.count("reconcile") >= 1
    assert "cloud.create" in names

    # cloud spans are CHILDREN of a reconcile span (tree, not a flat bag)
    def find(node, name):
        if node["name"] == name:
            return node
        for c in node.get("children", ()):
            got = find(c, name)
            if got:
                return got
        return None

    rec = next(
        (n for r in traces[0]["tree"] for n in [find(r, "reconcile")] if n),
        None,
    )
    assert rec is not None and rec["attributes"]["kind"] == "TpuPodSlice"
    assert any(
        find(r, "cloud.create") for r in traces[0]["tree"]
    )

    # the recorded Events carry the same trace id
    stamped = [
        e for e in kube.list("Event")
        if e.metadata.labels.get("trace-id") == ctx.trace_id
    ]
    assert stamped, "no Event stamped with the originating trace id"

    # and the whole thing renders without blowing up
    art = render_trace(traces[0])
    assert "reconcile" in art and ctx.trace_id in art


def test_untraced_create_roots_trace_at_first_reconcile(control_plane):
    kube, mgr, api, obs = control_plane
    ps = TpuPodSlice()
    ps.metadata.name = "plain"
    ps.spec.accelerator_type = "v4-8"
    ps.spec.slice_count = 1
    kube.create(ps)
    assert mgr.wait_idle(
        timeout=30.0,
        predicate=lambda: (
            (cur := kube.try_get("TpuPodSlice", "plain")) is not None
            and cur.status.phase == "Ready"
        ),
    )
    traces = _debug_traces(obs, name="cloud.create")
    assert traces, "reconcile lifecycle did not assemble into a trace"
    names = _all_names(traces[0])
    assert "reconcile" in names and "cloud.create" in names


def test_tracing_counters_registered(control_plane):
    kube, mgr, api, obs = control_plane
    with urllib.request.urlopen(
        f"http://127.0.0.1:{api.port}/healthz"
    ) as r:
        assert r.status == 200
    from k8s_gpu_tpu.utils.metrics import global_metrics

    with global_tracer.span("probe"):
        pass
    assert global_metrics.counter("tracing_spans_total") >= 1
    body = global_metrics.render()
    assert "tracing_spans_total" in body


# -- /debug/traces filtering ------------------------------------------------

def test_debug_traces_filtering():
    obs = MetricsServer().start()
    try:
        slow = _ctx()
        global_tracer.add_span("slow.op", parent=slow, start=0.0, end=1.0)
        fast = _ctx()
        global_tracer.add_span("fast.op", parent=fast, start=0.0, end=0.001)

        assert len(_debug_traces(obs)) == 2
        only_slow = _debug_traces(obs, min_ms=500)
        assert [t["trace_id"] for t in only_slow] == [slow.trace_id]
        by_name = _debug_traces(obs, name="fast")
        assert [t["trace_id"] for t in by_name] == [fast.trace_id]
        by_id = _debug_traces(obs, trace_id=slow.trace_id)
        assert len(by_id) == 1 and by_id[0]["span_count"] == 1
        assert _debug_traces(obs, name="nomatch") == []
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{obs.port}/debug/traces?min_ms=banana"
            )
    finally:
        obs.stop()


# -- serving plane ----------------------------------------------------------

@pytest.fixture(scope="module")
def lm_server():
    import jax

    from k8s_gpu_tpu.data import BpeTokenizer
    from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
    from k8s_gpu_tpu.serve import LmServer

    corpus = "the cat sat on the mat. the dog sat on the log. " * 40
    tok = BpeTokenizer.train(corpus, vocab_size=300)
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, use_flash=False,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    srv = LmServer(model, params, tok).start()
    yield srv
    srv.stop()


def test_serve_request_trace_has_admission_wait_and_rounds(lm_server):
    """Acceptance: one serve request's trace shows admission wait plus
    ≥1 batcher-round span, queried via /debug/traces."""
    obs = MetricsServer().start()
    try:
        ctx = _ctx()
        req = urllib.request.Request(
            f"http://127.0.0.1:{lm_server.port}/generate",
            data=json.dumps(
                {"prompt": "the cat", "max_new_tokens": 24}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": format_traceparent(ctx)},
        )
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert out["generated_tokens"] >= 1
        assert out["trace_id"] == ctx.trace_id

        # Round spans land when the scheduler processes results — give
        # the pipeline a beat to drain after the response returned.
        deadline = time.monotonic() + 5.0
        names = []
        while time.monotonic() < deadline:
            traces = _debug_traces(obs, trace_id=ctx.trace_id)
            names = _all_names(traces[0]) if traces else []
            if "serve.round" in names and "serve.queue_wait" in names:
                break
            time.sleep(0.05)
        assert "serve.queue_wait" in names, names
        assert "serve.prefill" in names, names
        assert names.count("serve.round") >= 1, names
        # round spans carry token counts; their sum covers the stream
        # minus the first (prefill-emitted) token
        traces = _debug_traces(obs, trace_id=ctx.trace_id)
        rounds = [
            n for r in traces[0]["tree"] for n in _flatten(r)
            if n["name"] == "serve.round"
        ]
        assert sum(n["attributes"]["tokens"] for n in rounds) >= (
            out["generated_tokens"] - 1
        )
    finally:
        obs.stop()


def _flatten(node):
    yield node
    for c in node.get("children", ()):
        yield from _flatten(c)


def test_untraced_serve_request_records_no_request_spans(lm_server):
    """No traceparent, no server span context leak: direct batcher
    submits stay span-free (the bench/hot-path zero-overhead contract)."""
    import numpy as np

    global_tracer.clear()
    h = lm_server.batcher.submit(
        np.asarray([1, 2, 3], np.int32), max_new_tokens=4
    )
    h.result()
    assert all(
        "serve." not in n
        for t in global_tracer.traces(limit=100)
        for n in _all_names(t)
    )
