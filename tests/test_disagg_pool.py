"""Cross-process disaggregated prefill/decode over the migration wire
(ISSUE 20), end to end over real sockets.

The gateway classifies by prompt length: long prompts prefill on a
dedicated prefill worker, the page-aligned KV chain ships over the
migration wire to the routed decode owner's /admin/import, and the
normal dispatch then decodes against the warm chain.  Contract: the
handed-over stream is byte-identical to the fused path; the prefill
worker never runs a decode round; every seeded handover/classify fault
degrades to fused re-prefill with zero lost requests; the handover is
journaled (prefill_replica, handover) and attributed (the waterfall's
``kv_handover`` segment); and the ratio controller reassigns workers as
the traffic mix flips — two-run byte-identical under FakeClock.
"""

import json
import time
import urllib.error
import urllib.request

import jax
import pytest

from k8s_gpu_tpu.data import BpeTokenizer
from k8s_gpu_tpu.models import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve import FleetFrontend, LmServer, RatioController
from k8s_gpu_tpu.utils import FakeClock, MetricsRegistry
from k8s_gpu_tpu.utils.faults import FaultPlan, global_faults
from k8s_gpu_tpu.utils.tracing import global_tracer
from k8s_gpu_tpu.utils.waterfall import (
    FleetTraceAssembler,
    split_by_process,
)

PAGE = 8

# > threshold and page-aligned headroom inside max_seq=64 with budget.
LONG_IDS = list(range(2, 28))          # 26 tokens: 3 shareable pages
SHORT_IDS = [3, 5, 7]


@pytest.fixture(scope="module")
def stack():
    corpus = "the cat sat on the mat. the dog sat on the log. " * 40
    tok = BpeTokenizer.train(corpus, vocab_size=300)
    cfg = TransformerConfig(
        vocab_size=tok.vocab_size, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq=64, use_flash=False,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return tok, model, params


def _mk_server(stack, name, role="both"):
    tok, model, params = stack
    return LmServer(
        model, params, tok, slots=4, paged_blocks=64, page_size=PAGE,
        metrics=MetricsRegistry(), name=name, role=role,
    ).start()


def _post(base, path, payload, headers=None, timeout=60.0):
    req = urllib.request.Request(
        base.rstrip("/") + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload, dict(e.headers)


@pytest.fixture(scope="module")
def fleet(stack):
    """1 prefill worker + 2 decode workers behind one disagg-enabled
    gateway; shared by the non-destructive tests."""
    servers = {
        "pf-0": _mk_server(stack, "pf-0", role="prefill"),
        "dc-0": _mk_server(stack, "dc-0"),
        "dc-1": _mk_server(stack, "dc-1"),
    }
    tok, _, _ = stack
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry(),
        disagg_threshold=16,
    ).start()
    for name, srv in servers.items():
        fe.register_replica(
            name, f"http://127.0.0.1:{srv.port}",
            role="prefill" if name == "pf-0" else "decode",
        )
    yield fe, servers
    fe.stop()
    for srv in servers.values():
        srv.stop()


def _fused_reference(servers, ids, n):
    """The fused-path greedy stream, straight from one decode worker."""
    code, out, _ = _post(
        f"http://127.0.0.1:{servers['dc-0'].port}", "/generate",
        {"prompt_ids": ids, "max_new_tokens": n, "temperature": 0.0},
    )
    assert code == 200, out
    return out["ids"]


# -- handover correctness -----------------------------------------------------

def test_handover_stream_byte_identical(fleet):
    fe, servers = fleet
    ref = _fused_reference(servers, LONG_IDS, 8)
    code, out, hdrs = _post(fe.url, "/generate", {
        "prompt_ids": LONG_IDS, "max_new_tokens": 8, "temperature": 0.0,
    })
    assert code == 200, out
    assert out["ids"] == ref
    assert hdrs["x-route-replica"] in ("dc-0", "dc-1")
    assert fe.metrics.counter("disagg_requests_total", path="disagg") >= 1
    # The decode owner acquired the imported chain instead of
    # re-prefilling: its batcher saw a shared-prefix paged admission.
    dest = servers[hdrs["x-route-replica"]]
    assert dest.batcher.metrics.counter(
        "serve_prefix_cache_hits_total"
    ) >= 1.0
    # Journaled: the record names the prefill worker and the wire time.
    rec = next(
        r for r in fe.journal.snapshot(limit=10)
        if r.get("prefill_replica")
    )
    assert rec["prefill_replica"] == "pf-0"
    assert rec["handover"] > 0.0
    # The prefill worker admitted (prefill) but never ran a decode
    # round — the role contract, observed cross-process.
    assert servers["pf-0"].batcher.steps_taken == 0


def test_short_prompt_keeps_fused_path(fleet):
    fe, servers = fleet
    before = fe.metrics.counter("disagg_requests_total", path="disagg")
    code, out, _ = _post(fe.url, "/generate", {
        "prompt_ids": SHORT_IDS, "max_new_tokens": 4, "temperature": 0.0,
    })
    assert code == 200, out
    assert out["ids"] == _fused_reference(servers, SHORT_IDS, 4)
    assert (
        fe.metrics.counter("disagg_requests_total", path="disagg")
        == before
    )


def test_handover_waterfall_kv_segment(fleet):
    """A handed-over request's stitched waterfall attributes the
    handover window to ``kv_handover`` instead of letting
    ``gateway_route`` swallow it.

    Retries with a fresh trace id when the handover legitimately
    degrades to fused under host load (never wrong, never lost — but
    then there is no handover to attribute).
    """
    fe, _ = fleet
    wf = None
    for attempt in range(3):
        tid = f"{'ab' * 15}{attempt:02x}".rjust(32, "0")
        code, _, _ = _post(
            fe.url, "/generate",
            {"prompt_ids": LONG_IDS, "max_new_tokens": 6,
             "temperature": 0.0},
            headers={"traceparent": f"00-{tid}-{'cd' * 8}-01"},
        )
        assert code == 200
        rec = next(
            (r for r in fe.journal.snapshot(limit=20)
             if r.get("trace_id") == tid), None,
        )
        if not (rec and rec.get("prefill_replica")):
            continue
        deadline = time.time() + 30.0
        captured = []
        while time.time() < deadline:
            captured = global_tracer.traces(trace_id=tid, limit=1)
            if captured and '"gateway.handover"' in json.dumps(captured[0]):
                break
            time.sleep(0.05)
        assert captured, "trace never landed"
        frags = split_by_process(captured)
        targets = {p: (lambda p=p: {"traces": frags[p]}) for p in frags}
        a = FleetTraceAssembler(
            targets=targets, registry=MetricsRegistry(), clock=FakeClock()
        )
        a.scrape_once()
        wf = a.waterfall(tid)
        break
    assert wf is not None, "handover degraded to fused on every attempt"
    assert wf["stitched"], wf
    assert wf["segments"]["kv_handover"]["seconds"] > 0.0, wf["segments"]


# -- chaos: seeded fault sites ------------------------------------------------

def test_handover_fault_degrades_to_fused(fleet):
    fe, servers = fleet
    ref = _fused_reference(servers, LONG_IDS, 8)
    try:
        global_faults.arm(
            "disagg.handover",
            FaultPlan(seed=7, rate=1.0, kinds=("error",)),
        )
        code, out, _ = _post(fe.url, "/generate", {
            "prompt_ids": LONG_IDS, "max_new_tokens": 8,
            "temperature": 0.0,
        })
    finally:
        global_faults.disarm()
    # Never wrong, never lost: the fused path re-prefills and the
    # stream is the same bytes.
    assert code == 200, out
    assert out["ids"] == ref
    assert fe.metrics.counter(
        "disagg_handover_failures_total", stage="prefill"
    ) >= 1.0
    assert fe.metrics.counter(
        "disagg_requests_total", path="fused_fallback"
    ) >= 1.0


def test_classify_fault_degrades_to_fused(fleet):
    fe, servers = fleet
    ref = _fused_reference(servers, LONG_IDS, 6)
    before = fe.metrics.counter("disagg_requests_total", path="disagg")
    try:
        global_faults.arm(
            "disagg.classify",
            FaultPlan(seed=11, rate=1.0, kinds=("error",)),
        )
        code, out, _ = _post(fe.url, "/generate", {
            "prompt_ids": LONG_IDS, "max_new_tokens": 6,
            "temperature": 0.0,
        })
    finally:
        global_faults.disarm()
    assert code == 200, out
    assert out["ids"] == ref
    assert fe.metrics.counter(
        "disagg_handover_failures_total", stage="classify"
    ) >= 1.0
    # A classify fault means the request was never classified long —
    # no disagg attempt, no handover.
    assert (
        fe.metrics.counter("disagg_requests_total", path="disagg")
        == before
    )


# -- ratio controller FSM -----------------------------------------------------

def _script(ctl, clock):
    """A fixed decide() script; returns the decision tuple sequence."""
    out = []
    steps = [
        # (advance_s, prefill, decode, prefill_tps, decode_tps)
        (0.0, 1, 3, 100.0, 300.0),   # share 0.25 == current: hold
        (1.0, 1, 3, 900.0, 100.0),   # prefill-heavy: grow
        (1.0, 2, 2, 900.0, 100.0),   # inside cooldown: hold
        (30.0, 2, 2, 900.0, 100.0),  # cooldown over: grow again
        (1.0, 3, 1, 900.0, 100.0),   # min_decode floor: hold
        (30.0, 3, 1, 0.0, 0.0),      # no traffic: idle
        (1.0, 3, 1, 50.0, 950.0),    # decode-heavy: shrink
    ]
    for dt, p, d, ptps, dtps in steps:
        clock.advance(dt)
        dec = ctl.decide(
            prefill_workers=p, decode_workers=d,
            prefill_tps=ptps, decode_tps=dtps,
        )
        out.append((dec.target_prefill, dec.reason, dec.direction))
    return out


def test_ratio_controller_two_run_byte_identical():
    runs = []
    for _ in range(2):
        clock = FakeClock()
        ctl = RatioController(
            clock=clock, cooldown_s=10.0, deadband=0.1,
            metrics=MetricsRegistry(),
        )
        runs.append(_script(ctl, clock))
    assert runs[0] == runs[1]
    assert runs[0] == [
        (1, "hold", 0),
        (2, "mix_shift", 1),
        (2, "cooldown", 0),
        (3, "mix_shift", 1),
        (3, "hold", 0),       # desired clamps to total - min_decode
        (3, "idle", 0),
        (2, "mix_shift", -1),
    ]


def test_ratio_controller_deadband_and_metrics():
    clock = FakeClock()
    reg = MetricsRegistry()
    ctl = RatioController(
        clock=clock, cooldown_s=0.0, deadband=0.2, metrics=reg
    )
    # |0.4 - 0.25| = 0.15 <= deadband: hysteresis holds.
    d = ctl.decide(
        prefill_workers=1, decode_workers=3,
        prefill_tps=40.0, decode_tps=60.0,
    )
    assert (d.reason, d.direction) == ("hold", 0)
    d = ctl.decide(
        prefill_workers=1, decode_workers=3,
        prefill_tps=90.0, decode_tps=10.0,
    )
    assert (d.target_prefill, d.direction) == (2, 1)
    assert reg.counter(
        "disagg_ratio_actions_total", direction="grow"
    ) == 1.0
    assert reg.gauge("disagg_ratio_target_prefill") == 2.0


# -- ratio tick drives live reassignment --------------------------------------

def test_traffic_flip_reassigns_worker(stack):
    """Mix flip → ratio controller → role flip on a live worker: a
    long-prompt-heavy window converts a decode worker to prefill (out
    of the router, batcher clamped); the decode-heavy window converts
    it back (router re-joined only after the worker confirms)."""
    tok, _, _ = stack
    servers = {f"rt-{i}": _mk_server(stack, f"rt-{i}") for i in range(3)}
    fe = FleetFrontend(
        tok, page_size=PAGE, metrics=MetricsRegistry(),
        disagg_threshold=16,
        ratio=RatioController(
            cooldown_s=0.0, deadband=0.05, metrics=MetricsRegistry()
        ),
    ).start()
    try:
        for name, srv in servers.items():
            fe.register_replica(name, f"http://127.0.0.1:{srv.port}")
        # Prefill-heavy window: long prompts with tiny decode budgets.
        for _ in range(4):
            code, _, _ = _post(fe.url, "/generate", {
                "prompt_ids": LONG_IDS, "max_new_tokens": 1,
                "temperature": 0.0,
            })
            assert code == 200
        got = fe.ratio_tick()
        assert got["direction"] == 1, got
        victim = got["reassigned"]
        assert victim in servers
        assert servers[victim].batcher.role == "prefill"
        states = {s["replica"]: s for s in fe.replica_states()}
        assert states[victim]["role"] == "prefill"
        assert fe.prefill_pool() == [victim]
        # Long prompts now actually hand over through the new worker.
        code, out, _ = _post(fe.url, "/generate", {
            "prompt_ids": LONG_IDS, "max_new_tokens": 6,
            "temperature": 0.0,
        })
        assert code == 200
        assert (
            fe.metrics.counter("disagg_requests_total", path="disagg")
            >= 1
        )
        # Decode-heavy window flips it back.  The handover request
        # above left its prefill tokens in this window too, so the
        # decode flow must dominate it decisively.
        for _ in range(8):
            code, _, _ = _post(fe.url, "/generate", {
                "prompt_ids": SHORT_IDS, "max_new_tokens": 32,
                "temperature": 0.0,
            })
            assert code == 200
        got = fe.ratio_tick()
        assert got["direction"] == -1, got
        assert got["reassigned"] == victim
        assert servers[victim].batcher.role == "decode"
        states = {s["replica"]: s for s in fe.replica_states()}
        assert states[victim]["role"] == "decode"
        assert fe.prefill_pool() == []
    finally:
        fe.stop()
        for srv in servers.values():
            srv.stop()
