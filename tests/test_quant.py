"""Int8 weight-only quantization: error bounds, engine parity, bytes.

The contract is *relative* fidelity, not bit-exactness: per-channel
scales bound the round-trip error of every weight element by s/2, and
greedy decode over a trained-scale random model should agree with bf16
on the large majority of steps (argmax flips at near-ties are expected
and correct behavior for a quantized model).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_tpu.models.transformer import TransformerConfig, TransformerLM
from k8s_gpu_tpu.serve.engine import InferenceEngine
from k8s_gpu_tpu.serve.quant import quantize_params, quantized_bytes


def _make(moe=False, seed=0):
    cfg = TransformerConfig(
        vocab_size=96, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq=64, dtype=jnp.float32, use_flash=False,
        remat=False, num_experts=4 if moe else 0,
    )
    model = TransformerLM(cfg)
    return model, model.init(jax.random.PRNGKey(seed))


def test_roundtrip_error_bound():
    """|dequant - w| <= s/2 elementwise for every quantized leaf."""
    _, params = _make()
    qp = quantize_params(params)
    for name in ("wq", "wo", "wi_gate", "wo_mlp"):
        w = params["blocks"][name]
        leaf = qp["blocks"][name]
        deq = leaf["q"].astype(jnp.float32) * leaf["s"]
        err = jnp.abs(deq - w)
        assert bool((err <= leaf["s"] / 2 + 1e-7).all()), name
    assert qp["blocks"]["wq"]["q"].dtype == jnp.int8


def test_moe_experts_quantized():
    _, params = _make(moe=True)
    qp = quantize_params(params)
    for name in ("e_wi_gate", "e_wi_up", "e_wo"):
        assert qp["blocks"][name]["q"].dtype == jnp.int8
    # router stays float — top-1 dispatch is precision-sensitive
    assert not isinstance(qp["blocks"]["gate"], dict)
    assert not isinstance(qp["blocks"]["ln1"], dict)


def test_logits_close_to_float():
    """Prompt logits from quantized weights track the float model."""
    model, params = _make()
    eng = InferenceEngine(model)
    qp = quantize_params(params)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 10), 1, 90)
    _, ref = jax.jit(eng.prefill)(params, prompt)
    _, got = jax.jit(eng.prefill)(qp, prompt)
    denom = jnp.abs(ref).mean()
    assert float(jnp.abs(got - ref).mean() / denom) < 0.12


@pytest.mark.parametrize("moe", [False, True])
def test_teacher_forced_next_token_agreement(moe):
    """>=90% next-token argmax agreement under teacher forcing.

    (Free-running streams are the wrong metric: one near-tie flip makes
    every later position differ by construction.  Teacher forcing scores
    each position independently against the same prefix.)"""
    model, params = _make(moe=moe)
    qp = quantize_params(params)
    toks = jax.random.randint(jax.random.PRNGKey(5), (4, 40), 1, 90)
    ref, _ = jax.jit(model.forward)(params, toks)
    got, _ = jax.jit(model.forward)(qp, toks)
    agree = float((ref.argmax(-1) == got.argmax(-1)).mean())
    assert agree >= 0.9, agree


@pytest.mark.parametrize("moe", [False, True])
def test_quantized_engine_decodes(moe):
    """The engine's scan/cache path consumes the quantized tree end to
    end (prefill + decode, not just teacher forcing)."""
    model, params = _make(moe=moe)
    eng = InferenceEngine(model)
    qp = quantize_params(params)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 1, 90)
    out = eng.generate(qp, prompt, max_new_tokens=12)
    assert out.tokens.shape == (2, 12)
    assert bool((out.lengths > 0).all())


def test_forward_path_also_quant_aware():
    """The training forward (used for eval/perplexity) consumes the same
    quantized tree."""
    model, params = _make()
    qp = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 12), 1, 90)
    ref, _ = jax.jit(model.forward)(params, tokens)
    got, _ = jax.jit(model.forward)(qp, tokens)
    denom = jnp.abs(ref).mean()
    assert float(jnp.abs(got - ref).mean() / denom) < 0.12


def test_bytes_halved():
    _, params = _make()
    qp = quantize_params(params)
    qb, fb = quantized_bytes(qp)
    # int8 + scales must land well under the bf16-equivalent footprint
    assert qb < 0.62 * fb, (qb, fb)
