// Native byte-level BPE tokenizer — the text→tokens front of the data
// pipeline (feeds the token files native/dataloader.cc consumes).
//
// The reference's workloads take pre-tokenized torchvision datasets
// (GPU调度平台搭建.md:584-604); an LM platform needs its own tokenizer, and
// BPE training/encoding is a byte-crunching loop that belongs in native
// code. C ABI for ctypes; k8s_gpu_tpu/data/tokenizer.py mirrors the exact
// algorithm in Python (tests assert merge-table and encoding parity).
//
// Algorithm (deterministic on purpose, so both implementations agree):
// - byte-level: base vocabulary is the 256 byte values;
// - training: repeatedly count adjacent pairs, merge the most frequent
//   (ties -> smallest (left, right) pair), left-to-right greedy apply;
// - encoding: repeatedly merge the present pair with the lowest rank
//   until no mergeable pair remains.

#include <cstdint>
#include <cstring>
#include <map>
#include <queue>
#include <utility>
#include <vector>

namespace {

using Pair = std::pair<int32_t, int32_t>;

struct Tokenizer {
  // merges[i] = the pair merged into token id (256 + i).
  std::vector<Pair> merges;
  std::map<Pair, int32_t> rank;  // pair -> merge index (lower = earlier)

  void index() {
    rank.clear();
    for (size_t i = 0; i < merges.size(); ++i)
      rank[merges[i]] = static_cast<int32_t>(i);
  }
};

// Left-to-right greedy application of one merge.
void apply_merge(std::vector<int32_t>& toks, Pair p, int32_t new_id) {
  size_t w = 0;
  for (size_t i = 0; i < toks.size();) {
    if (i + 1 < toks.size() && toks[i] == p.first && toks[i + 1] == p.second) {
      toks[w++] = new_id;
      i += 2;
    } else {
      toks[w++] = toks[i++];
    }
  }
  toks.resize(w);
}

}  // namespace

extern "C" {

// Train on a UTF-8/byte buffer; returns a handle. vocab_size includes the
// 256 byte tokens (so vocab_size - 256 merges at most). Training stops
// early when no pair occurs twice.
void* tok_train(const uint8_t* text, uint64_t len, uint64_t vocab_size) {
  auto* T = new Tokenizer();
  std::vector<int32_t> toks(text, text + len);
  int32_t next_id = 256;
  while (static_cast<uint64_t>(next_id) < vocab_size) {
    std::map<Pair, uint64_t> counts;  // ordered: deterministic ties
    for (size_t i = 0; i + 1 < toks.size(); ++i)
      counts[{toks[i], toks[i + 1]}]++;
    Pair best{-1, -1};
    uint64_t best_n = 1;  // require >= 2 occurrences
    for (const auto& [p, n] : counts) {
      if (n > best_n) {  // strict >: first (smallest) pair wins ties
        best = p;
        best_n = n;
      }
    }
    if (best.first < 0) break;
    T->merges.push_back(best);
    apply_merge(toks, best, next_id);
    ++next_id;
  }
  T->index();
  return T;
}

uint64_t tok_num_merges(void* h) {
  return static_cast<Tokenizer*>(h)->merges.size();
}

// Copies merges as flat (left, right) int32 pairs.
void tok_merges(void* h, int32_t* out) {
  auto* T = static_cast<Tokenizer*>(h);
  for (size_t i = 0; i < T->merges.size(); ++i) {
    out[2 * i] = T->merges[i].first;
    out[2 * i + 1] = T->merges[i].second;
  }
}

void* tok_from_merges(const int32_t* pairs, uint64_t n) {
  auto* T = new Tokenizer();
  T->merges.reserve(n);
  for (uint64_t i = 0; i < n; ++i)
    T->merges.emplace_back(pairs[2 * i], pairs[2 * i + 1]);
  T->index();
  return T;
}

// Encode bytes -> tokens. Returns token count (<= len). out must hold
// at least len entries.
//
// O(n log n): doubly-linked token list + min-heap of (rank, position)
// candidates with lazy invalidation. Popping in (rank, pos) order
// reproduces the reference sweep semantics exactly: ranks are unique per
// pair, occurrences of the winning pair merge left-to-right, and pairs
// created by a merge only compete under their own (later-found) rank.
int64_t tok_encode(void* h, const uint8_t* text, uint64_t len, int32_t* out) {
  auto* T = static_cast<Tokenizer*>(h);
  if (len == 0) return 0;
  const size_t n = len;
  std::vector<int32_t> tok(text, text + len);
  std::vector<int64_t> prev(n), next(n);
  for (size_t i = 0; i < n; ++i) {
    prev[i] = static_cast<int64_t>(i) - 1;
    next[i] = (i + 1 < n) ? static_cast<int64_t>(i + 1) : -1;
  }
  std::vector<char> alive(n, 1);

  using Entry = std::pair<int32_t, int64_t>;  // (rank, left position)
  std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> heap;
  auto push_pair = [&](int64_t i) {
    if (i < 0 || next[i] < 0) return;
    auto it = T->rank.find({tok[i], tok[next[i]]});
    if (it != T->rank.end()) heap.emplace(it->second, i);
  };
  for (size_t i = 0; i + 1 < n; ++i) push_pair(static_cast<int64_t>(i));

  while (!heap.empty()) {
    auto [rank, i] = heap.top();
    heap.pop();
    // Lazy validation: the entry may refer to consumed nodes or a pair
    // that changed since it was pushed.
    if (!alive[i]) continue;
    int64_t j = next[i];
    if (j < 0 || !alive[j]) continue;
    const Pair& p = T->merges[rank];
    if (tok[i] != p.first || tok[j] != p.second) continue;
    tok[i] = 256 + rank;
    alive[j] = 0;
    next[i] = next[j];
    if (next[j] >= 0) prev[next[j]] = i;
    push_pair(prev[i]);
    push_pair(i);
  }

  int64_t w = 0;
  for (int64_t i = 0; i >= 0; i = next[i])
    if (alive[i]) out[w++] = tok[i];
  return w;
}

// Decode tokens -> bytes. Returns byte count, or -1 if out_cap is too
// small (call again with a bigger buffer) or a token id is invalid.
int64_t tok_decode(void* h, const int32_t* toks, uint64_t n, uint8_t* out,
                   uint64_t out_cap) {
  auto* T = static_cast<Tokenizer*>(h);
  std::vector<int32_t> stack;
  size_t w = 0;
  for (uint64_t i = 0; i < n; ++i) {
    stack.push_back(toks[i]);
    while (!stack.empty()) {
      int32_t t = stack.back();
      stack.pop_back();
      if (t < 256) {
        if (t < 0 || w >= out_cap) return -1;
        out[w++] = static_cast<uint8_t>(t);
      } else {
        size_t m = static_cast<size_t>(t - 256);
        if (m >= T->merges.size()) return -1;
        stack.push_back(T->merges[m].second);  // LIFO: left pops first
        stack.push_back(T->merges[m].first);
      }
    }
  }
  return static_cast<int64_t>(w);
}

void tok_free(void* h) { delete static_cast<Tokenizer*>(h); }

}  // extern "C"
