// Native tokenized-batch loader for the training runner.
//
// The reference's data path is torchvision's FashionMNIST DataLoader inside
// the training pod (reference GPU调度平台搭建.md:584-604) — host-side, Python,
// per-worker. The TPU-native equivalent keeps the host CPU out of the step
// path: an mmapped flat token file, per-host sharding (each JAX process loads
// only its data-parallel shard), deterministic epoch shuffling, and
// background producer threads that keep a bounded ring of ready batches so
// the device never waits on Python.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment). The
// Python fallback in k8s_gpu_tpu/data/loader.py mirrors the splitmix64 +
// Fisher-Yates stream bit-for-bit; tests assert batch parity.
//
// File format: little-endian int32 tokens, no header. Sample i is the
// half-open token window [i*(seq_len+1), (i+1)*(seq_len+1)); the trailing
// partial window is dropped. Host `shard_id` of `num_shards` owns samples
// with index % num_shards == shard_id.

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

inline uint64_t splitmix64(uint64_t* state) {
  *state += 0x9E3779B97F4A7C15ULL;
  uint64_t z = *state;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Deterministic permutation of [0, n) for (seed, epoch). The Python
// fallback reimplements exactly this.
void epoch_perm(std::vector<uint64_t>& perm, uint64_t n, uint64_t seed,
                uint64_t epoch) {
  perm.resize(n);
  for (uint64_t i = 0; i < n; ++i) perm[i] = i;
  uint64_t state = seed ^ (epoch * 0xD1B54A32D192ED03ULL + 1);
  for (uint64_t i = n - 1; i >= 1; --i) {
    uint64_t j = splitmix64(&state) % (i + 1);
    std::swap(perm[i], perm[j]);
  }
}

enum SlotState : int { kEmpty = 0, kFilling = 1, kFull = 2 };

struct Slot {
  std::vector<int32_t> data;
  int state = kEmpty;
  uint64_t batch_index = 0;
};

struct Loader {
  // Immutable after open.
  int fd = -1;
  const int32_t* tokens = nullptr;
  size_t map_bytes = 0;
  uint64_t seq_len = 0;       // sample width is seq_len + 1
  uint64_t batch = 0;
  uint64_t num_local = 0;     // samples owned by this shard
  uint64_t shard_id = 0, num_shards = 1;
  uint64_t seed = 0;
  bool shuffle = true;
  uint64_t batches_per_epoch = 0;

  // Prefetch machinery.
  std::vector<Slot> ring;
  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  uint64_t next_to_claim = 0;    // producers claim batch indices from here
  uint64_t next_to_consume = 0;  // consumer reads in index order
  bool stopping = false;
  std::vector<std::thread> workers;

  // Permutations are epoch-keyed and shared_ptr-held: a producer still
  // filling from epoch e must keep its perm alive while faster producers
  // have already moved the cache on to e+1, e+2, ...
  std::mutex perm_mu;
  std::map<uint64_t, std::shared_ptr<const std::vector<uint64_t>>> perm_cache;

  std::shared_ptr<const std::vector<uint64_t>> perm_for(uint64_t epoch) {
    std::lock_guard<std::mutex> lk(perm_mu);
    auto it = perm_cache.find(epoch);
    if (it != perm_cache.end()) return it->second;
    auto p = std::make_shared<std::vector<uint64_t>>();
    epoch_perm(*p, num_local, seed, epoch);
    perm_cache[epoch] = p;
    while (perm_cache.size() > 4) perm_cache.erase(perm_cache.begin());
    return perm_cache[epoch];
  }

  // sample -> global index in the token file
  inline uint64_t global_sample(uint64_t local_idx) const {
    return local_idx * num_shards + shard_id;
  }

  void fill_batch(uint64_t batch_index, int32_t* out) {
    const uint64_t epoch = batch_index / batches_per_epoch;
    const uint64_t b = batch_index % batches_per_epoch;
    const uint64_t width = seq_len + 1;
    std::shared_ptr<const std::vector<uint64_t>> perm;
    if (shuffle) perm = perm_for(epoch);
    for (uint64_t r = 0; r < batch; ++r) {
      uint64_t local = b * batch + r;
      if (shuffle) local = (*perm)[local];
      const uint64_t g = global_sample(local);
      std::memcpy(out + r * width, tokens + g * width,
                  width * sizeof(int32_t));
    }
  }

  void worker() {
    for (;;) {
      uint64_t idx = 0;
      Slot* slot = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu);
        // idx/slot must be re-read on every wake: another producer may
        // have claimed the index this thread was waiting on.
        cv_produce.wait(lk, [&] {
          if (stopping) return true;
          idx = next_to_claim;
          slot = &ring[idx % ring.size()];
          return slot->state == kEmpty;
        });
        if (stopping) return;
        slot->state = kFilling;
        slot->batch_index = idx;
        next_to_claim++;
      }
      fill_batch(idx, slot->data.data());
      {
        std::lock_guard<std::mutex> lk(mu);
        slot->state = kFull;
      }
      cv_consume.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// Returns a handle, or null on error. Errors: unopenable file, or fewer
// local samples than one batch.
void* dl_open(const char* path, uint64_t seq_len, uint64_t batch,
              uint64_t shard_id, uint64_t num_shards, uint64_t seed,
              int shuffle, uint64_t prefetch_depth, uint64_t n_threads) {
  if (seq_len == 0 || batch == 0 || num_shards == 0 || shard_id >= num_shards)
    return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    ::close(fd);
    return nullptr;
  }
  auto* L = new Loader();
  L->fd = fd;
  L->map_bytes = static_cast<size_t>(st.st_size);
  void* m = mmap(nullptr, L->map_bytes, PROT_READ, MAP_PRIVATE, fd, 0);
  if (m == MAP_FAILED) {
    ::close(fd);
    delete L;
    return nullptr;
  }
  madvise(m, L->map_bytes, MADV_WILLNEED);
  L->tokens = static_cast<const int32_t*>(m);
  L->seq_len = seq_len;
  L->batch = batch;
  L->shard_id = shard_id;
  L->num_shards = num_shards;
  L->seed = seed;
  L->shuffle = shuffle != 0;

  const uint64_t n_tokens = L->map_bytes / sizeof(int32_t);
  const uint64_t n_samples = n_tokens / (seq_len + 1);
  // Shard s owns ceil((n_samples - s) / num_shards) samples.
  L->num_local =
      n_samples > shard_id ? (n_samples - shard_id + num_shards - 1) / num_shards
                           : 0;
  L->batches_per_epoch = L->num_local / batch;  // drop-last
  if (L->batches_per_epoch == 0) {
    munmap(const_cast<int32_t*>(L->tokens), L->map_bytes);
    ::close(fd);
    delete L;
    return nullptr;
  }

  if (prefetch_depth == 0) prefetch_depth = 4;
  if (n_threads == 0) n_threads = 2;
  if (n_threads > prefetch_depth) n_threads = prefetch_depth;
  L->ring.resize(prefetch_depth);
  for (auto& s : L->ring) s.data.resize(batch * (seq_len + 1));
  for (uint64_t t = 0; t < n_threads; ++t)
    L->workers.emplace_back(&Loader::worker, L);
  return L;
}

uint64_t dl_num_local_samples(void* h) {
  return static_cast<Loader*>(h)->num_local;
}

uint64_t dl_batches_per_epoch(void* h) {
  return static_cast<Loader*>(h)->batches_per_epoch;
}

// Blocks until the next batch is ready, copies batch*(seq_len+1) int32s
// into `out`, and returns the epoch the batch belongs to.
int64_t dl_next_batch(void* h, int32_t* out) {
  auto* L = static_cast<Loader*>(h);
  const uint64_t idx = L->next_to_consume;
  Slot* slot = &L->ring[idx % L->ring.size()];
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_consume.wait(lk, [&] {
      return L->stopping ||
             (slot->state == kFull && slot->batch_index == idx);
    });
    if (L->stopping) return -1;
  }
  std::memcpy(out, slot->data.data(),
              slot->data.size() * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    slot->state = kEmpty;
    L->next_to_consume++;
  }
  L->cv_produce.notify_all();
  return static_cast<int64_t>(idx / L->batches_per_epoch);
}

void dl_close(void* h) {
  auto* L = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stopping = true;
  }
  L->cv_produce.notify_all();
  L->cv_consume.notify_all();
  for (auto& t : L->workers) t.join();
  munmap(const_cast<int32_t*>(L->tokens), L->map_bytes);
  ::close(L->fd);
  delete L;
}

}  // extern "C"
