"""CLI config file with contexts — C26 parity.

The reference CLI keeps ``current-context`` + named contexts
({host, token, space, user}) in ``~/.config/GoHai-cli/config.yaml``
(GPU调度平台搭建.md:461-472).  Same schema here; the location honors
``K8SGPU_CONFIG_DIR`` so tests and multi-env setups don't collide.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path

import yaml


def config_dir() -> Path:
    return Path(
        os.environ.get(
            "K8SGPU_CONFIG_DIR", os.path.expanduser("~/.config/k8sgpu-cli")
        )
    )


@dataclass
class Context:
    name: str
    host: str = "local"
    token: str = ""
    space: str = "default"
    user: str = ""


@dataclass
class CliConfig:
    current_context: str = ""
    contexts: dict[str, Context] = field(default_factory=dict)

    @classmethod
    def load(cls) -> "CliConfig":
        path = config_dir() / "config.yaml"
        if not path.exists():
            return cls()
        data = yaml.safe_load(path.read_text()) or {}
        cfg = cls(current_context=data.get("current-context", ""))
        for c in data.get("contexts", []):
            ctx = Context(
                name=c.get("name", ""),
                host=c.get("host", "local"),
                token=c.get("token", ""),
                space=c.get("space", "default"),
                user=c.get("user", ""),
            )
            cfg.contexts[ctx.name] = ctx
        return cfg

    def save(self) -> None:
        d = config_dir()
        d.mkdir(parents=True, exist_ok=True)
        doc = {
            "current-context": self.current_context,
            "contexts": [
                {
                    "name": c.name,
                    "host": c.host,
                    "token": c.token,
                    "space": c.space,
                    "user": c.user,
                }
                for c in self.contexts.values()
            ],
        }
        (d / "config.yaml").write_text(yaml.safe_dump(doc, sort_keys=False))

    def current(self) -> Context | None:
        return self.contexts.get(self.current_context)

    def use(self, name: str) -> Context:
        if name not in self.contexts:
            raise KeyError(f"no such context {name!r}; have {sorted(self.contexts)}")
        self.current_context = name
        return self.contexts[name]
