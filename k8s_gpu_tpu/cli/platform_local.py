"""Local platform backend the CLI talks to.

The reference CLI talks to GoHai-api over HTTPS (GPU调度平台搭建.md:474-552);
this framework's control plane is in-process, so the CLI binds the same
verbs to a locally persisted platform: FakeKube state pickled under a state
dir, controllers (TpuPodSlice + TrainJob + autoscaler) spun up per
invocation and drained to quiescence before state is saved.  Result: every
CLI command behaves like a short-lived API server session with durable
cluster state — and no network surface to secure for a single-user dev box.
"""

from __future__ import annotations

import os
import pickle
import time
from pathlib import Path

from ..api.trainjob import TrainJob
from ..cloud.fake_cloudtpu import FakeCloudTpu
from ..controller.kubefake import FakeKube
from ..platform.assets import AssetStore


def state_dir() -> Path:
    return Path(
        os.environ.get(
            "K8SGPU_STATE_DIR", os.path.expanduser("~/.local/state/k8sgpu")
        )
    )


def _auth_key() -> bytes:
    """Persistent signing key (the Keycloak-realm-key role,
    GPU调度平台搭建.md:241-270).  Standalone so token mint/verify — pure
    HMAC — never boots the platform or takes its exclusive lock."""
    root = state_dir()
    root.mkdir(parents=True, exist_ok=True)
    keyfile = root / "auth.key"
    if not keyfile.exists():
        keyfile.write_bytes(os.urandom(32))
        keyfile.chmod(0o600)
    return keyfile.read_bytes()


def issue_token(username: str, groups: list[str] | None = None) -> str:
    """Dev login: the local box IS the identity (no password prompt), but
    the token is a real signed credential verify_token checks."""
    from ..auth.directory import User
    from ..auth.oidc import TokenIssuer

    issuer = TokenIssuer(directory=None, secret=_auth_key())
    return issuer.issue(User(username=username, groups=list(groups or [])), "tpu-cli")


def verify_token(token: str) -> dict:
    from ..auth.oidc import TokenIssuer

    issuer = TokenIssuer(directory=None, secret=_auth_key())
    return issuer.verify(token, audience="tpu-cli")


_log_handler_attached = False
_logs_persisted_until = 0.0


def _attach_log_shipping() -> None:
    """Ship all framework logs into the global LogStore (the Fluent Bit →
    Loki role, GPU调度平台搭建.md:798-800) exactly once per process."""
    global _log_handler_attached
    if _log_handler_attached:
        return
    import logging

    from ..utils import LogStoreHandler, global_logstore

    lg = logging.getLogger("k8s_gpu_tpu")
    lg.addHandler(LogStoreHandler(global_logstore, {"component": "platform"}))
    # INFO-level reconcile activity must reach the store even when the
    # process's root logger stays at the default WARNING.
    if lg.level == logging.NOTSET:
        lg.setLevel(logging.INFO)
    _log_handler_attached = True


class LocalPlatform:
    def __init__(self):
        _attach_log_shipping()
        self.root = state_dir()
        self.root.mkdir(parents=True, exist_ok=True)
        # Exclusive lock for the whole invocation: the state files are a
        # read-modify-write cycle, and concurrent CLI processes would
        # otherwise clobber each other last-writer-wins.
        import fcntl

        self._lockfile = open(self.root / ".lock", "w")
        fcntl.flock(self._lockfile, fcntl.LOCK_EX)
        self.kube = FakeKube()
        self._load()
        self.cloud = self._load_cloud()
        self.assets = AssetStore(self.root / "assets")
        self.registry = self._load_registry()
        from ..platform.entrypoint import controller_manager
        from ..platform.release import ReleaseManager

        self.releases = ReleaseManager(self.kube)
        # THE controller wiring, shared with the in-cluster operator
        # image (platform/entrypoint.py) — one place, no drift.
        self.mgr, storage = controller_manager(
            self.kube, self.cloud, provision_poll=0.05, devenv=True,
            assets=self.assets,
        )
        # Dynamic storage (C13): dev-box pools sized generously — capacity
        # enforcement matters, exact numbers don't.  Usage is re-derived
        # from live PVs (the pickled cluster state), not persisted.
        from ..platform.bulkstore import StoragePool

        ceph = storage.pools.setdefault("ceph", StoragePool("ceph"))
        nfs = storage.pools.setdefault("nfs", StoragePool("nfs"))
        for i in range(3):
            ceph.add_device(f"osd-{i}", "500Gi")
        nfs.add_device("nfs-server", "1Ti")
        storage.resync_pools()
        self.mgr.start()

    # -- persistence -------------------------------------------------------
    def _load(self) -> None:
        f = self.root / "kube.pkl"
        if f.exists():
            self.kube.load(pickle.loads(f.read_bytes()))

    def _load_cloud(self) -> FakeCloudTpu:
        f = self.root / "cloud.pkl"
        cloud = FakeCloudTpu()
        if f.exists():
            snap = pickle.loads(f.read_bytes())
            cloud.queued_resources = snap
        return cloud

    def _load_registry(self):
        from ..platform.registry import ImageRegistry

        reg = ImageRegistry()
        f = self.root / "registry.pkl"
        if f.exists():
            reg.load(pickle.loads(f.read_bytes()))
        return reg

    def pipeline_runner(self):
        from ..platform.cicd import PipelineRunner
        from ..platform.release import gohai_platform_chart

        return PipelineRunner(
            self.kube, self.registry, self.releases, self.assets,
            platform_chart=gohai_platform_chart(),
        )

    def close(self, wait: bool = True) -> None:
        """Persist state and release the lock.  ``wait=False`` skips the
        drain (fire-and-forget submits): in-flight work is abandoned in
        this process, and the level-triggered reconcilers resume it from
        the persisted CR state on the next invocation."""
        if wait:
            self.mgr.wait_idle(timeout=30)
        self.mgr.stop()
        (self.root / "kube.pkl").write_bytes(pickle.dumps(self.kube.dump()))
        (self.root / "cloud.pkl").write_bytes(
            pickle.dumps(self.cloud.queued_resources)
        )
        (self.root / "registry.pkl").write_bytes(
            pickle.dumps(self.registry.dump())
        )
        self._persist_observability()
        import fcntl

        fcntl.flock(self._lockfile, fcntl.LOCK_UN)
        self._lockfile.close()

    MAX_PERSISTED_LOG_LINES = 10_000

    def _persist_observability(self) -> None:
        """Durable half of the Loki/Prometheus role (C32): each invocation
        appends its shipped logs to logs.jsonl (bounded) and snapshots the
        metrics exposition, so `obs logs` / `obs metrics` can query the
        platform's history from a later process."""
        import json

        from ..utils import global_logstore
        from ..utils.metrics import global_metrics

        logfile = self.root / "logs.jsonl"
        lines = []
        if logfile.exists():
            lines = logfile.read_text().splitlines()
        # High-water mark so multiple platform sessions in one process
        # (tests) don't re-append the same entries.  Strictly-greater
        # filter: adding an epsilon to a time.time()-magnitude float is a
        # no-op (ulp ≈ 2.4e-7), which would re-persist the last entry.
        global _logs_persisted_until
        entries = [
            e
            for e in global_logstore.query(limit=self.MAX_PERSISTED_LOG_LINES)
            if e.ts > _logs_persisted_until
        ]
        for e in entries:
            lines.append(
                json.dumps({"ts": e.ts, "line": e.line, "labels": dict(e.labels)})
            )
        if entries:
            _logs_persisted_until = entries[-1].ts
        logfile.write_text(
            "\n".join(lines[-self.MAX_PERSISTED_LOG_LINES:]) + "\n"
            if lines else ""
        )
        (self.root / "metrics.prom").write_text(global_metrics.render())

    # -- verbs -------------------------------------------------------------
    def settle(self, predicate=None, timeout: float = 60.0) -> bool:
        return self.mgr.wait_idle(timeout=timeout, predicate=predicate)

    def submit_job(self, job: TrainJob, wait: bool = True, timeout: float = 300.0):
        self.kube.create(job)
        if not wait:
            return self.kube.get("TrainJob", job.metadata.name, job.metadata.namespace)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            cur = self.kube.get(
                "TrainJob", job.metadata.name, job.metadata.namespace
            )
            if cur.status.phase in ("Succeeded", "Failed"):
                return cur
            time.sleep(0.05)
        return self.kube.get("TrainJob", job.metadata.name, job.metadata.namespace)
