"""The platform CLI — C26 verb parity (GPU调度平台搭建.md:447-552).

Verbs: login, whoami, context list/new/use, repo init/push,
trainjob template/create/list/logs (with --dry-run/--bare/-s),
plus TPU-native extras: pool list/apply/delete, asset list/import.

Run as ``python -m k8s_gpu_tpu.cli ...``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from .config import CliConfig, Context
from .platform_local import LocalPlatform

TEMPLATE_SKELETON = """\
title: my-train-job
description: ""
image: registry.example.com/train:latest
command: python train.py
env: []
repository: []
dataset: []
model: []
mode: single
workload: lm-train
spec:
  singleInstanceType: tpu-v5e-8
"""


def _require_login(cfg: CliConfig) -> Context:
    ctx = cfg.current()
    if ctx is None or not ctx.token:
        print("not logged in; run: login --user <you>", file=sys.stderr)
        raise SystemExit(2)
    return ctx


# -- verb implementations --------------------------------------------------

def _parse_kv(specs, what: str) -> dict | None:
    """Parse repeated KEY=VALUE args into a dict; None + message on a
    malformed spec (shared by obs selectors and serve constraints)."""
    out = {}
    for spec in specs or []:
        if "=" not in spec:
            print(f"bad {what} {spec!r}: expected key=value",
                  file=sys.stderr)
            return None
        k, v = spec.split("=", 1)
        out[k] = v
    return out


def cmd_login(args) -> int:
    cfg = CliConfig.load()
    name = args.context or "default"
    ctx = cfg.contexts.get(name) or Context(name=name)
    ctx.user = args.user
    ctx.space = args.space or ctx.space
    # The reference does an OIDC browser code flow (:474-479); the local
    # platform is its own IdP, so mint a signed session token directly.
    from .platform_local import issue_token

    ctx.token = issue_token(args.user)
    cfg.contexts[name] = ctx
    cfg.current_context = name
    cfg.save()
    print(f"logged in as {ctx.user} (context {name}, space {ctx.space})")
    return 0


def cmd_whoami(args) -> int:
    ctx = _require_login(CliConfig.load())
    from ..auth.directory import AuthError
    from .platform_local import verify_token

    try:
        claims = verify_token(ctx.token)
        verified = f"verified (expires in {claims['exp'] - time.time():.0f}s)"
    except AuthError as e:
        verified = f"INVALID token: {e}"
    print(
        f"user: {ctx.user}\nspace: {ctx.space}\ncontext: {ctx.name}\n"
        f"host: {ctx.host}\ntoken: {verified}"
    )
    return 0


def cmd_context(args) -> int:
    cfg = CliConfig.load()
    if args.context_cmd == "list":
        for name, c in sorted(cfg.contexts.items()):
            marker = "*" if name == cfg.current_context else " "
            print(f"{marker} {name}\thost={c.host}\tspace={c.space}\tuser={c.user}")
        return 0
    if args.context_cmd == "new":
        cfg.contexts[args.name] = Context(
            name=args.name, host=args.host, space=args.space, user=args.user
        )
        cfg.save()
        print(f"context {args.name} created")
        return 0
    if args.context_cmd == "use":
        try:
            cfg.use(args.name)
        except KeyError as e:
            print(str(e), file=sys.stderr)
            return 1
        cfg.save()
        print(f"switched to context {args.name}")
        return 0
    return 1


def cmd_repo(args) -> int:
    ctx = _require_login(CliConfig.load())
    p = LocalPlatform()
    try:
        if args.repo_cmd == "init":
            print(f"repo {args.name} ready in space {ctx.space} (push to upload)")
            return 0
        if args.repo_cmd == "push":
            src = Path(args.path or ".")
            asset = p.assets.import_path(ctx.space, "repository", args.name, src)
            print(f"pushed {args.name} {asset.version} ({asset.size} bytes)")
            return 0
        return 1
    finally:
        p.close()


def cmd_trainjob(args) -> int:
    from ..platform.templates import (
        TemplateError,
        expand_template,
        parse_template,
        render_template,
        render_yaml,
    )

    ctx = _require_login(CliConfig.load())
    if args.trainjob_cmd == "template":
        if args.source:
            p = LocalPlatform()
            try:
                job = p.kube.try_get("TrainJob", args.source, ctx.space)
                if job is None:
                    print(f"no such job {args.source}", file=sys.stderr)
                    return 1
                print(render_template(job), end="")
                return 0
            finally:
                p.close()
        print(TEMPLATE_SKELETON, end="")
        return 0

    if args.trainjob_cmd == "create":
        from ..api.types import ValidationError

        try:
            tpl = parse_template(Path(args.file).read_text())
            name = args.name or f"job-{int(time.time())}"
            job = expand_template(tpl, name, namespace=ctx.space, bare=args.bare)
        except (TemplateError, ValidationError, FileNotFoundError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if args.dry_run:
            print(render_yaml(job), end="")
            return 0
        from ..controller.kubefake import Conflict

        p = LocalPlatform()
        try:
            done = p.submit_job(job, wait=not args.no_wait)
            print(f"{name}\t{done.status.phase}\t{done.status.message}")
            return 0 if done.status.phase != "Failed" else 1
        except Conflict as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        finally:
            p.close(wait=not args.no_wait)

    p = LocalPlatform()
    try:
        if args.trainjob_cmd == "list":
            print("NAME\tPHASE\tACCEL\tWORKERS\tMESSAGE")
            for j in p.kube.list("TrainJob", namespace=ctx.space):
                print(
                    f"{j.metadata.name}\t{j.status.phase}\t"
                    f"{j.spec.accelerator_type}\t{j.spec.num_workers}\t"
                    f"{j.status.message}"
                )
            return 0
        if args.trainjob_cmd == "logs":
            j = p.kube.try_get("TrainJob", args.job_id, ctx.space)
            if j is None:
                print(f"no such job {args.job_id}", file=sys.stderr)
                return 1
            for line in j.status.logs:
                print(line)
            if j.status.result:
                print(f"result: {j.status.result}")
            return 0
        return 1
    finally:
        p.close()


def cmd_pool(args) -> int:
    from ..api.tpupodslice import TpuPodSlice

    ctx = _require_login(CliConfig.load())
    p = LocalPlatform()
    try:
        if args.pool_cmd == "list":
            print("NAME\tACCEL\tDESIRED\tREADY\tPHASE")
            for ps in p.kube.list("TpuPodSlice", namespace=ctx.space):
                c = ps.printer_columns
                print(
                    f"{ps.metadata.name}\t{c['Accelerator']}\t{c['Desired']}\t"
                    f"{c['Ready']}\t{c['Phase']}"
                )
            return 0
        if args.pool_cmd == "apply":
            existing = p.kube.try_get("TpuPodSlice", args.name, ctx.space)
            if existing is None:
                ps = TpuPodSlice()
                ps.metadata.name = args.name
                ps.metadata.namespace = ctx.space
                ps.spec.accelerator_type = args.accelerator
                ps.spec.slice_count = args.slices
                p.kube.create(ps)
            else:
                existing.spec.accelerator_type = args.accelerator
                existing.spec.slice_count = args.slices
                p.kube.update(existing)
            deadline = time.monotonic() + args.timeout
            while time.monotonic() < deadline:
                cur = p.kube.get("TpuPodSlice", args.name, ctx.space)
                if cur.status.phase in ("Ready", "Paused"):
                    break
                time.sleep(0.05)
            cur = p.kube.get("TpuPodSlice", args.name, ctx.space)
            print(f"{args.name}\t{cur.status.phase}\tready={cur.status.ready_replicas}")
            return 0 if cur.status.phase in ("Ready", "Paused") else 1
        if args.pool_cmd == "delete":
            from ..controller.kubefake import NotFound

            try:
                p.kube.delete("TpuPodSlice", args.name, ctx.space)
            except NotFound:
                print(f"no such pool {args.name}", file=sys.stderr)
                return 1
            p.settle()
            print(f"{args.name} deleted")
            return 0
        return 1
    finally:
        p.close()


def cmd_asset(args) -> int:
    ctx = _require_login(CliConfig.load())
    p = LocalPlatform()
    try:
        if args.asset_cmd == "list":
            for kind, id in p.assets.list_assets(ctx.space, args.kind):
                versions = p.assets.versions(ctx.space, kind, id)
                print(f"{kind}\t{id}\t{','.join(versions)}")
            return 0
        if args.asset_cmd == "import":
            a = p.assets.import_path(ctx.space, args.kind, args.id, args.path)
            print(f"imported {args.kind}/{args.id} {a.version} ({a.size} bytes)")
            return 0
        return 1
    finally:
        p.close()


def cmd_devenv(args) -> int:
    from ..api.devenv import DevEnv
    from ..controller.kubefake import NotFound

    if args.devenv_cmd == "keygen":
        # Pure local key generation — no platform state, no lock (a
        # keygen must work while a gateway holds the platform open),
        # and no login either: a fresh machine keygens FIRST.
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            NoEncryption,
            PrivateFormat,
        )

        from ..platform.sshwire import authorized_key_line

        key = Ed25519PrivateKey.generate()
        out = Path(args.out or ".")
        out.mkdir(parents=True, exist_ok=True)
        priv = out / "id_ed25519"
        priv.write_bytes(key.private_bytes(
            Encoding.PEM, PrivateFormat.OpenSSH, NoEncryption()
        ))
        priv.chmod(0o600)
        cfg = CliConfig.load()
        cur = cfg.current()
        user = args.user or (cur.user if cur else "") or "dev"
        (out / "id_ed25519.pub").write_text(
            authorized_key_line(key, f"{user}@k8sgpu") + "\n"
        )
        print(f"wrote {priv} and {priv}.pub")
        return 0
    ctx = _require_login(CliConfig.load())
    p = LocalPlatform()
    try:
        if args.devenv_cmd == "create":
            try:
                pubkey = (
                    Path(args.pubkey).read_text().strip() if args.pubkey else ""
                )
            except OSError as e:
                print(f"error: cannot read pubkey: {e}", file=sys.stderr)
                return 1
            name = args.name or f"env-{args.user or ctx.user}"
            env = p.kube.try_get("DevEnv", name, ctx.space)
            if env is None and not pubkey:
                print("--pubkey is required to create a devenv", file=sys.stderr)
                return 2
            if env is None:
                env = DevEnv()
                env.metadata.name = name
                env.metadata.namespace = ctx.space
                env.spec.username = args.user or ctx.user
                env.spec.ssh_public_key = pubkey
                env.spec.tpu_chips = args.chips or 0
                p.kube.create(env)
            else:
                env.spec.ssh_public_key = pubkey or env.spec.ssh_public_key
                if args.chips is not None:  # --chips 0 releases the grant
                    env.spec.tpu_chips = args.chips
                p.kube.update(env)
            p.settle()
            cur = p.kube.get("DevEnv", name, ctx.space)
            chips = ""
            if cur.status.phase == "Ready" and cur.spec.tpu_chips:
                pod = p.kube.try_get("Pod", cur.status.pod_name, ctx.space)
                if pod is not None and pod.env.get("TPU_VISIBLE_CHIPS"):
                    chips = (f"\tchips: {pod.env['TPU_VISIBLE_CHIPS']} "
                             f"on {pod.node_name}")
            print(f"{name}\t{cur.status.phase}\t"
                  f"ssh: {cur.status.ssh_endpoint}{chips}")
            if cur.status.phase != "Ready":
                if cur.status.message:
                    print(f"error: {cur.status.message}", file=sys.stderr)
                return 1
            return 0
        if args.devenv_cmd == "list":
            print("NAME\tUSER\tPHASE\tSSH")
            for e in p.kube.list("DevEnv", namespace=ctx.space):
                print(f"{e.metadata.name}\t{e.spec.username}\t"
                      f"{e.status.phase}\t{e.status.ssh_endpoint}")
            return 0
        if args.devenv_cmd == "delete":
            try:
                p.kube.delete("DevEnv", args.name, ctx.space)
            except NotFound:
                print(f"no such devenv {args.name}", file=sys.stderr)
                return 1
            p.settle()
            print(f"{args.name} deleted (workspace PVC retained)")
            return 0
        if args.devenv_cmd == "gateway":
            # Serve the SSH gateway off the live platform state — the
            # ingress the reference exposes on :2022 (GPU调度平台搭建.md:418).
            from ..platform.sshgate import SshGateway

            gw = SshGateway(
                p.kube, port=args.port, namespace=ctx.space or "default",
                assets=p.assets,
            ).start()
            print(f"gateway listening on 127.0.0.1:{gw.port} "
                  f"(namespace {ctx.space or 'default'})", flush=True)
            try:
                import time as _time

                if args.for_seconds > 0:
                    _time.sleep(args.for_seconds)
                else:
                    while True:
                        _time.sleep(3600)
            except KeyboardInterrupt:
                pass
            finally:
                gw.stop()
            return 0
        return 1
    finally:
        p.close()


def cmd_devenv_client(args) -> int:
    """`devenv ssh` / `devenv put`: the gateway CLIENT — pure socket,
    no platform lock, so it runs against a live `devenv gateway` (same
    or another process) exactly like ssh/sftp against the reference's
    ingress (GPU调度平台搭建.md:408-419, :707-734)."""
    from ..platform.sshgate import GatewayClient, GatewayError

    ctx = _require_login(CliConfig.load())
    try:
        host, port_s = args.gateway.rsplit(":", 1)
        port = int(port_s)
    except ValueError:
        print(f"bad --gateway {args.gateway!r}: expected host:port",
              file=sys.stderr)
        return 2
    user = args.user or ctx.user
    if getattr(args, "ssh2", False):
        # Real SSH-2 transport (platform/sshwire.py): curve25519-sha256
        # kex, ssh-ed25519 keys, aes128-ctr + hmac-sha2-256.
        from cryptography.hazmat.primitives.serialization import (
            load_ssh_private_key,
        )

        from ..platform.sshwire import Ssh2Client, SshError

        if not args.key:
            print("--key <private key> is required with --ssh2",
                  file=sys.stderr)
            return 2
        try:
            key = load_ssh_private_key(
                Path(args.key).read_bytes(), password=None
            )
        except (OSError, ValueError) as e:
            print(f"error: cannot load key: {e}", file=sys.stderr)
            return 1
        try:
            with Ssh2Client(host, port, user, key) as c:
                if args.devenv_cmd == "put":
                    # Standard-protocol bulk upload: the SFTP subsystem
                    # commits a new asset version on close (platform/
                    # sftp.py) — the lftp-mirror path, no invented verbs.
                    space = args.space or ctx.space or "default"
                    msg = c.sftp().put(
                        args.file, f"/{space}/{args.kind}/{args.id}"
                    )
                    print(msg or "OK")
                    return 0
                rc = 0
                for cmd in (args.command or []):
                    out, status = c.exec(cmd)
                    print(out, end="" if out.endswith("\n") else "\n")
                    rc = rc or status
                if not args.command:
                    # Interactive: a real pty-req+shell session, one
                    # command per stdin line (scripted ssh).
                    with c.shell() as sh:
                        print(sh.banner, end="", flush=True)
                        for line in sys.stdin:
                            line = line.strip()
                            if not line:
                                continue
                            if line in ("exit", "logout"):
                                break
                            print(sh.run(line), end="", flush=True)
                return rc
        except SshError as e:
            print(f"denied: {e}", file=sys.stderr)
            return 1
        except OSError as e:
            print(f"error: cannot reach gateway: {e}", file=sys.stderr)
            return 1
    if args.devenv_cmd == "put":
        print("note: the line-protocol PUT is deprecated; prefer "
              "--ssh2 --key <private-key> (standard SFTP subsystem)",
              file=sys.stderr)
    if not args.pubkey:
        print("--pubkey is required for the line-protocol client "
              "(or pass --ssh2 --key for the SSH-2 transport)",
              file=sys.stderr)
        return 2
    try:
        pubkey = Path(args.pubkey).read_text().strip()
    except OSError as e:
        print(f"error: cannot read pubkey: {e}", file=sys.stderr)
        return 1
    try:
        with GatewayClient(host, port, user, pubkey) as c:
            if args.devenv_cmd == "put":
                print(c.put(args.space or ctx.space or "default",
                            args.kind, args.id, args.file))
                return 0
            if args.command:
                print(c.banner)
                for cmd in args.command:
                    print(c.exec(cmd))
                return 0
            # Interactive: one command per stdin line (scripted ssh).
            print(c.banner)
            for line in sys.stdin:
                line = line.strip()
                if not line or line == "exit":
                    break
                try:
                    print(c.exec(line), flush=True)
                except GatewayError as e:
                    print(f"error: {e}", file=sys.stderr)
            return 0
    except GatewayError as e:
        print(f"denied: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"error: cannot reach gateway: {e}", file=sys.stderr)
        return 1


def cmd_apply(args) -> int:
    """kubectl-style manifest verbs: apply -f (create-or-update), get,
    delete — the reference's core UX (README.md:287-289: `kubectl apply`
    the sample CR, observe with `kubectl get azurevmpool`)."""
    from ..api.serialize import known_kinds, to_yaml
    from ..api.types import ValidationError
    from ..controller.kubefake import Conflict, NotFound

    ctx = _require_login(CliConfig.load())
    p = LocalPlatform()
    try:
        if args.file_cmd == "apply":
            import yaml as _yaml

            from ..api.serialize import from_manifest

            try:
                text = Path(args.file).read_text()
                docs = [d for d in _yaml.safe_load_all(text) if d is not None]
            except (OSError, _yaml.YAMLError) as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
            if getattr(args, "validate", False):
                # Schema validation BEFORE decode: every violation in every
                # document reported with its field path (the generated-CRD
                # validation the reference gets from `make manifests`,
                # README.md:157-160).
                from ..api.schema import validate_manifest

                failed = False
                for i, doc in enumerate(docs):
                    for err in validate_manifest(doc):
                        sep = "" if err.startswith(".") else ": "
                        print(f"error: document {i}{sep}{err}",
                              file=sys.stderr)
                        failed = True
                if failed:
                    return 1
            try:
                objs = [from_manifest(d) for d in docs]
            except ValidationError as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
            for obj in objs:
                if not obj.metadata.namespace or obj.metadata.namespace == "default":
                    obj.metadata.namespace = ctx.space or "default"
                # Retry on Conflict: background reconcilers may bump the
                # resourceVersion between read and write.
                for attempt in range(5):
                    cur = p.kube.try_get(
                        obj.kind, obj.metadata.name, obj.metadata.namespace
                    )
                    try:
                        if cur is None:
                            p.kube.create(obj)
                            print(f"{obj.kind.lower()}/{obj.metadata.name} "
                                  "created")
                        else:
                            obj.metadata.resource_version = (
                                cur.metadata.resource_version
                            )
                            obj.metadata.creation_timestamp = (
                                cur.metadata.creation_timestamp
                            )
                            obj.metadata.finalizers = list(
                                cur.metadata.finalizers
                            )
                            p.kube.update(obj)
                            print(f"{obj.kind.lower()}/{obj.metadata.name} "
                                  "configured")
                        break
                    except ValidationError as e:
                        print(f"error: {obj.kind}/{obj.metadata.name}: {e}",
                              file=sys.stderr)
                        return 1
                    except Conflict:
                        if attempt == 4:
                            print(f"error: {obj.kind}/{obj.metadata.name}: "
                                  "conflict persisted after retries",
                                  file=sys.stderr)
                            return 1
            if args.wait:
                p.settle()
            return 0
        if args.file_cmd == "get":
            kind = args.kind
            if kind not in known_kinds():
                print(f"unknown kind {kind!r}; known: {known_kinds()}",
                      file=sys.stderr)
                return 1
            ns = ctx.space or "default"
            if args.name:
                obj = p.kube.try_get(kind, args.name, ns) or p.kube.try_get(
                    kind, args.name, "default"
                )
                if obj is None:
                    print(f"{kind} {args.name!r} not found", file=sys.stderr)
                    return 1
                print(to_yaml(obj), end="")
                return 0
            objs = p.kube.list(kind, namespace=None)
            print("NAMESPACE\tNAME\tPHASE")
            for o in objs:
                phase = getattr(getattr(o, "status", None), "phase", "-")
                print(f"{o.metadata.namespace}\t{o.metadata.name}\t{phase}")
            return 0
        if args.file_cmd == "delete":
            ns = ctx.space or "default"
            try:
                p.kube.delete(args.kind, args.name, ns)
            except NotFound:
                try:
                    p.kube.delete(args.kind, args.name, "default")
                except NotFound:
                    print(f"{args.kind} {args.name!r} not found", file=sys.stderr)
                    return 1
            p.settle()
            print(f"{args.kind.lower()}/{args.name} deleted")
            return 0
        return 1
    finally:
        p.close()


def cmd_schema(args) -> int:
    """Export per-kind schemas generated from the dataclass codec — the
    ``make manifests generate`` analogue (reference README.md:157-160)."""
    import json as _json

    from ..api.schema import all_schemas, schema_for_kind

    try:
        schemas = (
            {args.kind: schema_for_kind(args.kind)} if args.kind
            else all_schemas()
        )
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 1
    if args.out_dir:
        out = Path(args.out_dir)
        out.mkdir(parents=True, exist_ok=True)
        for kind, schema in schemas.items():
            (out / f"{kind}.json").write_text(_json.dumps(schema, indent=2))
            print(f"wrote {out / f'{kind}.json'}")
        return 0
    for kind, schema in schemas.items():
        print(_json.dumps(schema, indent=2))
    return 0


def cmd_ci(args) -> int:
    """CI/CD verbs (C31): run the build/push/deploy|train pipeline on a
    pushed repo asset, and inspect release history."""
    ctx = _require_login(CliConfig.load())
    p = LocalPlatform()
    try:
        if args.ci_cmd == "run":
            from ..platform.cicd import Ref

            ref = (
                Ref(args.tag, is_tag=True) if args.tag else Ref(args.branch)
            )
            run = p.pipeline_runner().run(ctx.space or "default", args.repo,
                                          ref, namespace=ctx.space or "default")
            print(f"pipeline {run.repo} @ "
                  f"{'tag ' if ref.is_tag else ''}{ref.name}: {run.status}")
            for s in run.stages:
                print(f"  {s.stage:7s} {s.status}")
                for line in s.log:
                    print(f"          {line}")
            if run.status == "success":
                p.settle()
            return 0 if run.status == "success" else 1
        if args.ci_cmd == "install":
            # helm upgrade --install semantics (the Makefile's `make
            # deploy` analogue of reference README.md:298-302): render
            # the platform chart onto the cluster and let the
            # Deployment controller materialize pods.
            from ..platform.release import gohai_platform_chart

            flat = _parse_kv(args.set or [], "--set")
            if flat is None:
                return 2
            # helm --set semantics: dotted keys nest (api.replicas=3),
            # digit values coerce to int (replicas is an int field).
            values: dict = {}
            for k, v in flat.items():
                cur = values
                *path, leaf = k.split(".")
                for part in path:
                    cur = cur.setdefault(part, {})
                cur[leaf] = int(v) if v.isdigit() else v
            if args.image:
                values["image"] = args.image
            rel = p.releases.upgrade(
                gohai_platform_chart(), args.name,
                args.namespace or ctx.space or "default", values,
            )
            p.settle()
            print(f"release {args.name} revision {rel.revision} deployed")
            return 0
        if args.ci_cmd == "uninstall":
            from ..platform.release import ReleaseError

            try:
                p.releases.uninstall(
                    args.name, args.namespace or ctx.space or "default"
                )
            except ReleaseError as e:
                print(str(e), file=sys.stderr)
                return 1
            print(f"release {args.name} uninstalled")
            return 0
        if args.ci_cmd == "releases":
            hist = p.releases.history(args.name, ctx.space or "default")
            if not hist:
                print(f"no release {args.name}", file=sys.stderr)
                return 1
            print("REVISION\tCHART\tSTATUS\tVALUES")
            for r in hist:
                print(f"{r.revision}\t{r.chart}-{r.chart_version}\t"
                      f"{r.status}\t{r.values}")
            return 0
        return 1
    finally:
        p.close()


def _obs_fetch(url: str, path: str) -> str | None:
    """GET ``url+path`` from a running metrics server; None (with the
    error printed) on failure.  OSError covers unreachable hosts;
    ValueError covers a scheme-less --url (urlopen's "unknown url type")
    and a non-UTF-8 body (UnicodeDecodeError)."""
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"{url.rstrip('/')}{path}", timeout=10
        ) as r:
            return r.read().decode()
    except (OSError, ValueError) as e:
        print(f"fetch failed: {e}", file=sys.stderr)
        return None


def _parse_scrape_targets(urls) -> dict:
    """Shared ``--scrape-url`` parsing for `obs fleet`/`obs serve`:
    ``NAME=URL`` keeps the explicit name; a bare URL is named by its
    host:port (the replica label must not carry a scheme)."""
    from urllib.parse import urlparse

    targets = {}
    for u in urls or []:
        name, sep, rest = u.partition("=")
        if not sep:
            parsed = urlparse(u if "//" in u else f"//{u}")
            name, rest = parsed.netloc or u, u
        targets[name] = rest
    return targets


def _obs_snapshot() -> str | None:
    """The last platform invocation's persisted exposition, or None
    (with the hint printed) when no run has happened yet."""
    from .platform_local import state_dir

    prom = state_dir() / "metrics.prom"
    if not prom.exists():
        print("no metrics snapshot yet", file=sys.stderr)
        return None
    return prom.read_text()


def cmd_obs(args) -> int:
    """Observability surface (C32): query persisted platform logs (the
    Loki role), dump the last metrics exposition, render span traces, or
    serve /metrics."""
    import json

    from .platform_local import state_dir

    if args.obs_cmd == "lint":
        # Static analysis over the working tree: no platform state, no
        # login — the same passes `make check` and the tier-1
        # self-check run (docs/platform/invariants.md).
        from pathlib import Path

        from ..analysis import report_to_json, run_report
        from ..utils.obs import render_lint

        root = Path(args.root) if args.root else Path(__file__).resolve().parents[2]
        if not (root / "k8s_gpu_tpu").is_dir():
            print(f"obs lint: no k8s_gpu_tpu package under {root} — "
                  "pass --root <repo checkout>", file=sys.stderr)
            return 2
        baseline = root / "config" / "analysis_baseline.json"
        if not baseline.exists():
            # An installed tree ships no baseline/config; without it the
            # pinned debt reads as new findings, which would be a lie.
            print(f"obs lint: no baseline at {baseline}; findings are "
                  "reported unsuppressed (run from a repo checkout or "
                  "pass --root)", file=sys.stderr)
        report = run_report(root)
        if args.json:
            print(report_to_json(report), end="")
        else:
            print(render_lint(report))
        return 0 if report["ok"] else 1
    _require_login(CliConfig.load())
    if args.obs_cmd == "logs":
        logfile = state_dir() / "logs.jsonl"
        if not logfile.exists():
            print("no logs persisted yet", file=sys.stderr)
            return 1
        selector = _parse_kv(args.selector, "selector")
        if selector is None:
            return 2
        # Hydrate a LogStore so selector/contains/tail semantics are the
        # single implementation in utils/logstore.py.
        from ..utils import LogStore

        store = LogStore()
        for raw in logfile.read_text().splitlines():
            e = json.loads(raw)
            store.push(e.get("labels", {}), e["line"], ts=e["ts"])
        if args.tail <= 0:
            return 0
        for e in store.query(selector, contains=args.contains, limit=args.tail):
            lvl = dict(e.labels).get("level", "?")
            print(f"{time.strftime('%H:%M:%S', time.localtime(e.ts))} "
                  f"[{lvl}] {e.line}")
        return 0
    if args.obs_cmd == "metrics":
        text = _obs_snapshot()
        if text is None:
            return 1
        print(text, end="")
        return 0
    if args.obs_cmd == "resilience":
        # The resilience slice of the exposition: retries, breaker
        # state/transitions, load sheds, injected faults — the counters
        # docs/platform/resilience.md defines.
        text = _obs_snapshot()
        if text is None:
            return 1
        families = (
            "faults_injected_total", "circuit_breaker_",
            "cloud_retry_attempts_total",
            "cloud_breaker_short_circuits_total", "serve_shed_total",
        )
        lines = [
            ln for ln in text.splitlines() if ln.startswith(families)
        ]
        if not lines:
            print("no resilience metrics recorded (no retries, sheds, "
                  "or injected faults in the last run)")
            return 0
        print("\n".join(lines))
        return 0
    if args.obs_cmd == "top":
        # Fleet-utilization snapshot.  One source (a --url scrape or the
        # persisted metrics.prom) renders the classic single-process
        # view; REPEATED --url scrapes every replica through the
        # federation collector's relabel/aggregate path and renders one
        # column per replica plus the fleet-aggregate column.
        from ..utils.obs import render_top, render_top_columns

        urls = args.url or []
        if len(urls) > 1:
            from ..utils.federation import FleetCollector

            texts = {}
            for name, u in _parse_scrape_targets(urls).items():
                text = _obs_fetch(u, "/metrics")
                if text is None:
                    return 1
                texts[name] = text
            fc = FleetCollector(
                {name: (lambda t=t: t) for name, t in texts.items()}
            )
            fc.scrape_once()
            print(render_top_columns(fc.snapshot()))
            return 0
        text = (
            _obs_fetch(urls[0], "/metrics") if urls
            else _obs_snapshot()
        )
        if text is None:
            return 1
        print(render_top(text))
        return 0
    if args.obs_cmd == "fleet":
        # The federated fleet view: --url fetches a running obs server's
        # /fleet snapshot (a FleetCollector lives there); repeated
        # --scrape-url builds a one-shot local collector over raw
        # /metrics endpoints instead.
        from ..utils.obs import render_fleet

        if args.url:
            body = _obs_fetch(args.url, "/fleet?refresh=1")
            if body is None:
                return 1
            try:
                snap = json.loads(body)
                snap["replicas"]
            except (ValueError, KeyError, TypeError) as e:
                print(f"fetch failed: {e}", file=sys.stderr)
                return 1
        elif args.scrape_url:
            from ..utils.federation import FleetCollector

            fc = FleetCollector(_parse_scrape_targets(args.scrape_url))
            up = fc.scrape_once()
            snap = fc.snapshot()
            if not any(up.values()):
                print("no replica scrape succeeded", file=sys.stderr)
                print(render_fleet(snap))
                return 1
        else:
            print("obs fleet needs --url (a /fleet server) or repeated "
                  "--scrape-url NAME=URL", file=sys.stderr)
            return 2
        print(render_fleet(snap))
        return 0
    if args.obs_cmd == "gateways":
        # The gateway-fleet view: per-gateway owner-map digest (do the
        # independently reconstructed maps agree?) plus the admission
        # plane's per-tenant quota/WFQ table.
        from ..utils.obs import render_gateways

        if not args.url:
            print("obs gateways needs repeated --url NAME=URL (or bare "
                  "URL) of each gateway", file=sys.stderr)
            return 2
        snaps = []
        for name, u in _parse_scrape_targets(args.url).items():
            om = _obs_fetch(u, "/admin/ownermap?chains=0")
            adm = _obs_fetch(u, "/admin/admission")
            try:
                snaps.append({
                    "name": name,
                    "ownermap": json.loads(om) if om else None,
                    "admission": json.loads(adm) if adm else None,
                })
            except ValueError:
                snaps.append(
                    {"name": name, "ownermap": None, "admission": None}
                )
        print(render_gateways(snaps))
        digests = {
            (s["ownermap"] or {}).get("digest")
            for s in snaps if s["ownermap"]
        }
        return 0 if len(digests) <= 1 else 1
    if args.obs_cmd == "requests":
        # The per-request journal: what /debug/requests serves, with
        # the trace id column cross-linking into `obs traces --trace`.
        from urllib.parse import urlencode

        from ..utils.obs import render_requests

        if not args.url:
            print("obs requests needs --url of a metrics server with a "
                  "journal attached (/debug/requests)", file=sys.stderr)
            return 2
        params = {
            k: v for k, v in (
                ("tenant", args.tenant), ("reason", args.reason),
                ("trace_id", args.trace), ("limit", args.limit),
                ("since", args.since or ""),
                # probes=0 drops canary records (synthetic traffic).
                ("probes", "0" if args.no_probes else ""),
            ) if v
        }
        body = _obs_fetch(args.url, f"/debug/requests?{urlencode(params)}")
        if body is None:
            return 1
        try:
            parsed = json.loads(body)
            # Cursor BEFORE records (the /debug/traces discipline): a
            # scraper that resumes from this cursor double-ships the
            # overlap instead of gapping it.
            cursor = int(parsed.get("cursor", 0))
            recs = parsed["requests"]
            if not isinstance(recs, list):
                raise ValueError("'requests' is not a list")
        except (ValueError, KeyError, TypeError) as e:
            print(f"fetch failed: {e}", file=sys.stderr)
            return 1
        print(render_requests(recs))
        if any(r.get("trace_id") for r in recs):
            print("\n(follow a request: obs traces --url "
                  f"{args.url} --trace <TRACE>)")
        print(f"\n(resume from here: obs requests --url {args.url} "
              f"--since {cursor})")
        return 0
    if args.obs_cmd == "replay":
        # Workload flight recorder: capture journals to a .workload
        # file, re-inject it against a live fleet, diff two runs with
        # segment attribution (serve/replay.py).
        from pathlib import Path

        from ..serve import replay as rp
        from ..utils.clock import RealClock
        from ..utils.obs import render_replay

        def _load_report(path: str):
            """A run report, or a .workload viewed as the recorded
            baseline — so `obs replay diff` compares capture-vs-run
            or run-vs-run with one flag shape."""
            data = Path(path).read_bytes()
            obj = json.loads(data.decode())
            if isinstance(obj, dict) and "source" in obj:
                return obj
            return rp.workload_report(rp.load_workload(data))

        if args.replay_cmd == "record":
            targets = _parse_scrape_targets(args.url)
            if not targets:
                print("obs replay record needs --url NAME=URL of "
                      "metrics servers with journals attached",
                      file=sys.stderr)
                return 2
            rec = rp.WorkloadRecorder(targets, probes=args.probes)
            clock = RealClock()
            t_end = clock.now() + max(0.0, args.duration)
            n = rec.scrape_once()
            while clock.now() < t_end:
                clock.sleep(max(0.1, args.poll))
                n += rec.scrape_once()
            w = rec.workload()
            Path(args.out).write_bytes(rp.workload_bytes(w))
            print(f"captured {len(w['requests'])} requests "
                  f"({n} journal records) from {len(targets)} "
                  f"targets -> {args.out}")
            if rec.scrape_errors:
                print(f"warning: {rec.scrape_errors} scrape errors "
                      "(dead targets are skipped; their requests "
                      "survive in resuming replicas' journals)",
                      file=sys.stderr)
            return 0 if w["requests"] else 1
        if args.replay_cmd == "run":
            try:
                w = rp.load_workload(Path(args.workload).read_bytes())
            except (OSError, ValueError) as e:
                print(f"bad workload: {e}", file=sys.stderr)
                return 2
            if not args.url:
                print("obs replay run needs --url of a replica or "
                      "gateway /generate endpoint", file=sys.stderr)
                return 2
            rep = rp.WorkloadReplayer(
                time_scale=args.time_scale,
                arm_deadlines=args.arm_deadlines,
            ).run(w, url=args.url, journal_url=args.journal_url or "")
            if args.out:
                Path(args.out).write_bytes(rp.report_bytes(rep))
            t = rep["totals"]
            print(f"replayed {t['requests']} requests against "
                  f"{args.url}: {t['matched']}/{t['verified']} golden "
                  f"matches, {t['mismatches']} mismatches, "
                  f"{t['errors']} errors"
                  + (f" -> {args.out}" if args.out else ""))
            # Wrong bytes (or failed sends) gate: non-zero exit is the
            # CI contract.
            return 1 if t["mismatches"] or t["errors"] else 0
        if args.replay_cmd == "diff":
            try:
                baseline = _load_report(args.baseline)
                candidate = _load_report(args.candidate)
            except (OSError, ValueError) as e:
                print(f"bad report: {e}", file=sys.stderr)
                return 2
            d = rp.diff_reports(
                baseline, candidate,
                rel_threshold=args.threshold,
                abs_floor_s=args.floor_ms / 1000.0,
            )
            if args.out:
                Path(args.out).write_bytes(rp.diff_bytes(d))
            if args.json:
                print(json.dumps(d, sort_keys=True, indent=2))
            else:
                print(render_replay(d))
            # The threshold gate: regression (or mismatch) exits 1.
            return 1 if d["regression"] else 0
        print("obs replay: record|run|diff required", file=sys.stderr)
        return 2
    if args.obs_cmd == "profile":
        # Continuous performance attribution: the /debug/profile view
        # (per-phase p50/p95/share, compile telemetry, per-axis
        # collective bandwidth), plus the Chrome/Perfetto export of the
        # span ring + phase samples.
        from ..utils.obs import render_profile

        if args.url:
            body = _obs_fetch(args.url, "/debug/profile")
            if body is None:
                return 1
            try:
                snap = json.loads(body)
                snap["phases"]
            except (ValueError, KeyError, TypeError) as e:
                print(f"fetch failed: {e}", file=sys.stderr)
                return 1
            if args.chrome_trace:
                from pathlib import Path

                from ..utils.profiler import chrome_trace

                tr_body = _obs_fetch(
                    args.url, f"/debug/traces?limit={args.limit}"
                )
                try:
                    traces = (
                        json.loads(tr_body)["traces"] if tr_body else []
                    )
                except (ValueError, KeyError, TypeError):
                    traces = []
                data = chrome_trace(traces, snap)
                Path(args.chrome_trace).write_text(json.dumps(data))
                print(
                    f"chrome trace written to {args.chrome_trace} "
                    f"({len(data['traceEvents'])} events) — load it at "
                    "ui.perfetto.dev or chrome://tracing"
                )
            print(render_profile(snap))
            return 0
        if args.chrome_trace:
            print("--chrome-trace needs --url (the live span ring and "
                  "phase samples live in the serving process)",
                  file=sys.stderr)
            return 2
        # Offline: reconstruct the attribution view from the persisted
        # exposition (share gauges + histogram buckets).
        from ..utils.profiler import snapshot_from_exposition

        text = _obs_snapshot()
        if text is None:
            return 1
        print(render_profile(snapshot_from_exposition(text)))
        return 0
    if args.obs_cmd == "goodput":
        # Training goodput: the /debug/goodput view — the wall-clock
        # partition (where did the time go), the windowed goodput
        # ratio, checkpoint save/restore percentiles, straggler
        # attribution, and the incident flight recorder.
        from ..utils.obs import render_goodput

        if args.url:
            body = _obs_fetch(args.url, "/debug/goodput")
            if body is None:
                return 1
            try:
                snap = json.loads(body)
                snap["segments"]
            except (ValueError, KeyError, TypeError) as e:
                print(f"fetch failed: {e}", file=sys.stderr)
                return 1
            print(render_goodput(snap))
            return 0
        # Offline: reconstruct the goodput view from the persisted
        # exposition (nonproductive counters, step-time histogram sum,
        # ratio/skew gauges, checkpoint buckets, incident counters).
        from ..utils.goodput import goodput_snapshot_from_exposition

        text = _obs_snapshot()
        if text is None:
            return 1
        print(render_goodput(goodput_snapshot_from_exposition(text)))
        return 0
    if args.obs_cmd == "probes":
        # Black-box canary view: the /debug/probes snapshot — per-replica
        # health FSM, K-of-N windows, failure tally, recent transitions.
        from ..utils.obs import render_probes

        if not args.url:
            print("obs probes needs --url of a metrics server with a "
                  "canary prober attached (/debug/probes)",
                  file=sys.stderr)
            return 2
        body = _obs_fetch(args.url, "/debug/probes")
        if body is None:
            return 1
        try:
            snap = json.loads(body)
            snap["replicas"]
        except (ValueError, KeyError, TypeError) as e:
            print(f"fetch failed: {e}", file=sys.stderr)
            return 1
        print(render_probes(snap))
        return 0
    if args.obs_cmd == "slo":
        # The error-budget plane: per-objective budget remaining and
        # fast/slow burn (the slo_* recording rules) plus per-replica
        # probe health, read straight off /metrics — so it also works
        # offline against a persisted exposition snapshot.
        from ..utils.metrics import parse_exposition
        from ..utils.obs import render_slo

        text = (
            _obs_fetch(args.url, "/metrics") if args.url
            else _obs_snapshot()
        )
        if text is None:
            return 1
        print(render_slo(parse_exposition(text)))
        return 0
    if args.obs_cmd == "route":
        # Routing explain: which replica the prefix-affinity router
        # would pick for a prompt, and what every candidate scored.
        # --scrape-url replicas bring live load through the federation
        # collector; --replica names route on pure affinity.
        from ..serve.router import FleetRouter
        from ..utils.metrics import MetricsRegistry
        from ..utils.obs import render_route

        try:
            ids = [int(x) for x in args.ids.replace(",", " ").split()]
        except ValueError:
            print("--ids must be token ids: --ids 1,2,3", file=sys.stderr)
            return 2
        if not ids:
            print("--ids must carry at least one token id",
                  file=sys.stderr)
            return 2
        collector = None
        if args.scrape_url:
            from ..utils.federation import FleetCollector

            targets = _parse_scrape_targets(args.scrape_url)
            collector = FleetCollector(targets)
            up = collector.scrape_once()
            if not any(up.values()):
                print("no replica scrape succeeded", file=sys.stderr)
                return 1
            names = sorted(targets)
        elif args.replica:
            names = sorted(args.replica)
        else:
            print("obs route needs replicas: repeated --scrape-url "
                  "NAME=URL (live load) or --replica NAME (affinity "
                  "only)", file=sys.stderr)
            return 2
        router = FleetRouter(
            page_size=args.page_size, collector=collector,
            metrics=MetricsRegistry(),
        )
        for n in names:
            router.add_replica(n)
        dec = router.route(ids)
        print(render_route(dec, router.snapshot()))
        return 0
    if args.obs_cmd == "alerts":
        if args.url:
            # A running MetricsServer's /alerts — the rules engine's live
            # pending/firing set and transition timeline.
            body = _obs_fetch(args.url, f"/alerts?limit={args.limit}")
            if body is None:
                return 1
            try:
                snap = json.loads(body)
                alerts = snap["alerts"]
                transitions = snap.get("transitions", [])
            except (ValueError, KeyError, TypeError) as e:
                # A 200 that isn't the /alerts JSON shape (wrong --url).
                print(f"fetch failed: {e}", file=sys.stderr)
                return 1
        else:
            # Instant evaluation over the last snapshot: rebuild a
            # registry from metrics.prom and run the default pack with
            # hold durations collapsed (one snapshot carries no history,
            # so `for:` windows and counter rates cannot apply).
            from ..utils.alerts import (
                AlertingRule, RuleEvaluator, default_rule_pack,
            )
            from ..utils.metrics import MetricsRegistry, parse_exposition

            text = _obs_snapshot()
            if text is None:
                return 1
            reg = MetricsRegistry()
            for name, series in parse_exposition(text).items():
                if name.endswith(("_bucket", "_sum", "_count")):
                    continue
                for lbls, v in series.items():
                    reg.set_gauge_series(name, v, dict(lbls))
            rules = default_rule_pack()
            for r in rules:
                if isinstance(r, AlertingRule):
                    r.for_s = 0.0
            ev = RuleEvaluator(rules, registry=reg)
            ev.evaluate_once()
            alerts = ev.active_alerts()
            transitions = []
            print("(instant evaluation of the last snapshot; hold "
                  "durations and rate windows not applied — use --url "
                  "against a live server for the real state)\n")
        if not alerts:
            print("no alerts pending or firing")
        else:
            print(f"{'ALERT':<22} {'STATE':<8} {'ACTIVE(S)':>9} "
                  f"{'VALUE':>9}  LABELS")
            for a in alerts:
                lbls = ",".join(
                    f"{k}={v}" for k, v in sorted(a["labels"].items())
                )
                print(f"{a['alertname']:<22} {a['state']:<8} "
                      f"{a['active_s']:>9.1f} {a['value']:>9.3g}  {lbls}")
                if a.get("annotation"):
                    print(f"  ↳ {a['annotation']}")
        if transitions and args.limit > 0:
            # limit<=0 means none — a bare [-0:] slice would show ALL.
            print("\nrecent transitions:")
            for t in transitions[-args.limit:]:
                lbls = ",".join(
                    f"{k}={v}" for k, v in sorted(t["labels"].items())
                )
                print(f"  t={t['t']:<10.1f} {t['alert']:<22} "
                      f"{t['from']:>8} → {t['to']:<8} {lbls}")
        return 0
    if args.obs_cmd == "traces":
        from ..utils.tracing import global_tracer, render_trace

        if args.url:
            # A running MetricsServer's /debug/traces — same assembled
            # JSON shape the in-process tracer produces.
            import urllib.parse
            import urllib.request

            params = {
                k: v for k, v in (
                    ("trace_id", args.trace), ("name", args.name),
                    ("min_ms", args.min_ms or ""),
                    ("limit", args.limit),
                ) if v
            }
            url = (f"{args.url.rstrip('/')}/debug/traces?"
                   + urllib.parse.urlencode(params))
            try:
                with urllib.request.urlopen(url, timeout=10) as r:
                    traces = json.loads(r.read())["traces"]
                if not isinstance(traces, list):
                    raise ValueError("'traces' is not a list")
            except (OSError, ValueError, KeyError, TypeError) as e:
                # Covers unreachable hosts AND a 200 that isn't the
                # /debug/traces JSON shape (wrong --url, proxy page).
                print(f"fetch failed: {e}", file=sys.stderr)
                return 1
        else:
            # Boot the platform so its reconcile passes run (and trace)
            # in THIS process, then read the in-process tracer.
            p = LocalPlatform()
            p.settle()
            p.close()
            traces = global_tracer.traces(
                trace_id=args.trace or None, min_ms=args.min_ms,
                name=args.name, limit=args.limit,
            )
        if not traces:
            print("no traces recorded", file=sys.stderr)
            return 1
        for t in traces:
            print(render_trace(t))
            print()
        return 0
    if args.obs_cmd == "waterfall":
        # The fleet waterfall: stitched cross-process request traces
        # with the per-segment critical-path decomposition — the
        # "where did THIS request's 900ms go" view (/debug/waterfall).
        from ..utils.obs import render_waterfall

        if not args.url:
            print("obs waterfall needs --url (the trace assembler "
                  "lives in the serving process)", file=sys.stderr)
            return 2
        params = f"?limit={args.limit}"
        if args.trace:
            params = f"?trace_id={args.trace}"
        body = _obs_fetch(args.url, f"/debug/waterfall{params}")
        if body is None:
            return 1
        try:
            snap = json.loads(body)
            if not isinstance(snap, dict) or "error" in snap:
                raise ValueError(
                    (snap or {}).get("error", "not a waterfall snapshot")
                )
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            print(f"fetch failed: {e}", file=sys.stderr)
            return 1
        if args.chrome_trace:
            from pathlib import Path

            if not args.trace:
                print("--chrome-trace needs --trace (one stitched "
                      "trace per Perfetto export)", file=sys.stderr)
                return 2
            ch_body = _obs_fetch(
                args.url,
                f"/debug/waterfall?trace_id={args.trace}&chrome=1",
            )
            if ch_body is None:
                return 1
            try:
                data = json.loads(ch_body)
                data["traceEvents"]
            except (ValueError, KeyError, TypeError) as e:
                print(f"fetch failed: {e}", file=sys.stderr)
                return 1
            Path(args.chrome_trace).write_text(json.dumps(data))
            print(
                f"chrome trace written to {args.chrome_trace} "
                f"({len(data['traceEvents'])} events, one pid per "
                "process) — load it at ui.perfetto.dev or "
                "chrome://tracing"
            )
        print(render_waterfall(snap))
        return 0
    if args.obs_cmd == "serve":
        from ..utils.obs import MetricsServer

        # Boot the platform to refresh state/metrics, then RELEASE it before
        # serving: holding its exclusive lock for the serve duration would
        # block every other CLI invocation.  The endpoint serves this
        # process's metrics registry (a snapshot after close).
        p = LocalPlatform()
        p.settle()
        p.close()
        # The manager's rules engine rides along so /alerts serves the
        # session's final pending/firing set and timeline; --scrape-url
        # targets federate into /fleet on demand.
        fleet = None
        if args.scrape_url:
            from ..utils.federation import FleetCollector

            fleet = FleetCollector(_parse_scrape_targets(args.scrape_url))
        srv = MetricsServer(
            port=args.port, alerts=getattr(p.mgr, "alerts", None),
            fleet=fleet,
        ).start()
        print(f"serving /metrics /alerts /fleet /healthz /readyz on "
              f":{srv.port}")
        return _serve_until(srv, args.for_seconds)
    return 1


def _serve_until(srv, for_seconds: float) -> int:
    """Block until the deadline (0 = forever) or Ctrl-C, then stop."""
    deadline = time.monotonic() + for_seconds if for_seconds else None
    try:
        while deadline is None or time.monotonic() < deadline:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    srv.stop()
    return 0


def cmd_serve(args) -> int:
    """Serve a model asset over HTTP — the end of the export→serve
    journey (train → checkpoint → versioned model asset → serving
    workload; the role the reference's platform schedules for the
    Fin-Agent service, 智能风控解决方案.md:368-419)."""
    ctx = _require_login(CliConfig.load())
    if args.draft and args.draft_mode:
        # Mirrors api/inferenceservice.py: spec.draft and spec.draftMode
        # are mutually exclusive — and keeping them separate flags means
        # an asset literally named 'ngram' stays loadable via --draft.
        print("--draft and --draft-mode are mutually exclusive: a draft "
              "is either a model asset or a model-free mode",
              file=sys.stderr)
        return 2
    if args.constraint and (args.draft or args.draft_mode):
        # Knowable from flags alone — fail as a usage error BEFORE
        # loading two bundles and compiling a vocab-wide DFA bank
        # (batcher.__init__ documents why the combination can't exist).
        print("--constraint and --draft cannot be combined: the DFA "
              "advances through the accepted prefix, which only exists "
              "after the speculative verify", file=sys.stderr)
        return 2
    p = LocalPlatform()
    draft = None
    try:
        from ..serve.bundle import load_servable

        model, params, tok = load_servable(
            p.assets, ctx.space, args.model, args.version
        )
        if args.draft_mode == "ngram":
            # Prompt-lookup drafting: proposals from each row's own
            # token history — no draft bundle to load, no draft
            # forward at serve time (batcher.ngram_propose).
            draft = "ngram"
        elif args.draft:
            # Speculative serving: the draft is its own servable bundle
            # (typically distill_draft's output exported beside the
            # target); vocab compatibility is checked by the batcher.
            dmodel, dparams, _ = load_servable(
                p.assets, ctx.space, args.draft, ""
            )
            draft = (dmodel, dparams)
    except (KeyError, ValueError) as e:
        # KeyError str() wraps the message in repr quotes; args[0] is clean.
        print(e.args[0] if e.args else str(e), file=sys.stderr)
        return 1
    finally:
        # Release the platform lock before serving — params are already
        # materialized on device, and holding the exclusive lock for the
        # serve duration would block every other CLI invocation.
        p.close()
    if tok is None:
        print(
            f"asset {args.model} bundles no tokenizer; re-export with "
            "export_servable(..., tokenizer=...)",
            file=sys.stderr,
        )
        return 1
    from ..serve import LmServer

    constraints = _parse_kv(args.constraint, "--constraint")
    if constraints is None:
        return 2
    schemas = _parse_kv(args.json_constraint, "--json-constraint")
    if schemas is None:
        return 2
    if schemas:
        # NAME=schema.json → regex over canonical JSON; requests opt in
        # with {"constraint": NAME} exactly like plain-regex patterns.
        from ..serve.jsonschema import SchemaError, schema_to_regex
    for name, path in (schemas or {}).items():
        if name in constraints:
            print(f"--json-constraint {name} collides with --constraint "
                  f"{name}: pick distinct names", file=sys.stderr)
            return 2
        try:
            with open(path) as f:
                constraints[name] = schema_to_regex(json.load(f))
        except (OSError, json.JSONDecodeError, SchemaError) as e:
            print(f"--json-constraint {name}: {e}", file=sys.stderr)
            return 2
    if constraints and args.eos_id < 0:
        # A dead-ended constrained row retires by emitting EOS; without
        # one it would stream token 0 as if it were generated content.
        print("--constraint requires --eos-id (dead-ended rows retire "
              "by emitting EOS)", file=sys.stderr)
        return 2
    try:
        srv = LmServer(
            model, params, tok, port=args.port, slots=args.slots,
            constraints=constraints or None,
            eos_id=args.eos_id,
            draft=draft, kv_quant=args.kv_quant,
            paged_blocks=args.paged_blocks,
        ).start()
    except ValueError as e:  # bad regex / vocab mismatch: clean exit
        print(str(e), file=sys.stderr)
        return 1
    print(
        f"serving {ctx.space}/model/{args.model} on "
        f"http://127.0.0.1:{srv.port}/generate"
    )
    return _serve_until(srv, args.for_seconds)


def cmd_frontend(args) -> int:
    """Run the fleet front door: an HTTP gateway owning the
    prefix-affinity router over live LmServer replicas.  The model
    asset supplies ONLY the tokenizer — the gateway holds no params;
    it tokenizes each prompt to compute the same page-aligned chain
    hashes the replicas' batchers register, routes, and relays."""
    ctx = _require_login(CliConfig.load())
    p = LocalPlatform()
    try:
        from ..serve.bundle import load_servable

        _, _, tok = load_servable(
            p.assets, ctx.space, args.model, args.version
        )
    except (KeyError, ValueError) as e:
        print(e.args[0] if e.args else str(e), file=sys.stderr)
        return 1
    finally:
        p.close()
    if tok is None:
        print(
            f"asset {args.model} bundles no tokenizer; the gateway "
            "needs it to compute routing chain hashes",
            file=sys.stderr,
        )
        return 1
    replicas = _parse_kv(args.replica, "--replica")
    if replicas is None:
        return 2
    from ..serve import FleetFrontend

    fe = FleetFrontend(
        tok, port=args.port, page_size=args.page_size
    ).start()
    for name, url in replicas.items():
        try:
            fe.register_replica(name, url)
            print(f"replica {name} -> {url}")
        except (RuntimeError, OSError) as e:
            # Late replicas join via POST /admin/replicas.
            print(f"replica {name} not registered: {e}", file=sys.stderr)
    print(
        f"fleet frontend on {fe.url}/generate "
        f"({len(fe.replica_names())} replicas; "
        "POST /admin/replicas to add more)"
    )
    return _serve_until(fe, args.for_seconds)


# -- parser ----------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="k8sgpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_login = sub.add_parser("login", help="authenticate and store a context token")
    p_login.add_argument("--user", required=True)
    p_login.add_argument("--space", default="")
    p_login.add_argument("--context", default="")
    p_login.set_defaults(fn=cmd_login)

    sub.add_parser("whoami", help="show current identity").set_defaults(fn=cmd_whoami)

    p_ctx = sub.add_parser("context", help="manage contexts")
    ctx_sub = p_ctx.add_subparsers(dest="context_cmd", required=True)
    ctx_sub.add_parser("list")
    p_new = ctx_sub.add_parser("new")
    p_new.add_argument("name")
    p_new.add_argument("--host", default="local")
    p_new.add_argument("--space", default="default")
    p_new.add_argument("--user", default="")
    p_use = ctx_sub.add_parser("use")
    p_use.add_argument("name")
    p_ctx.set_defaults(fn=cmd_context)

    p_env = sub.add_parser("devenv", help="persistent dev environments")
    env_sub = p_env.add_subparsers(dest="devenv_cmd", required=True)
    p_ec = env_sub.add_parser("create")
    p_ec.add_argument("--name", default="")
    p_ec.add_argument("--user", default="")
    p_ec.add_argument("--pubkey", default="", help="path to SSH public key")
    p_ec.add_argument("--chips", type=int, default=None,
                      help="TPU chips to carve out of a shared host "
                           "(0 releases an existing grant)")
    env_sub.add_parser("list")
    env_sub.add_parser("delete").add_argument("name")
    p_gw = env_sub.add_parser(
        "gateway", help="serve the devenv SSH gateway (port 2022 role)"
    )
    p_gw.add_argument("--port", type=int, default=0)
    p_gw.add_argument("--for-seconds", type=float, default=0.0,
                      help="exit after N seconds (0 = until interrupted)")
    p_kg = env_sub.add_parser(
        "keygen", help="generate an Ed25519 keypair (ssh-keygen analogue)"
    )
    p_kg.add_argument("--out", default="", help="output dir (default .)")
    p_kg.add_argument("--user", default="", help="key comment user")
    p_ssh = env_sub.add_parser(
        "ssh", help="open a session through the gateway (EXEC channel)"
    )
    p_put = env_sub.add_parser(
        "put", help="bulk-upload a file through the gateway (SFTP role)"
    )
    for sp in (p_ssh, p_put):
        sp.add_argument("--gateway", required=True, help="host:port")
        sp.add_argument("--pubkey", default="",
                        help="path to the SSH public key the devenv holds "
                             "(legacy line protocol only)")
        sp.add_argument("--user", default="")
        sp.add_argument("--ssh2", action="store_true",
                        help="real SSH-2 transport (curve25519/ed25519/"
                             "aes128-ctr; platform/sshwire.py); for put, "
                             "uploads ride the standard SFTP subsystem")
        sp.add_argument("--key", default="",
                        help="OpenSSH Ed25519 private key (with --ssh2)")
    p_ssh.add_argument("-c", "--command", action="append",
                       help="run command(s) and exit (else read stdin)")
    p_ssh.set_defaults(fn=cmd_devenv_client)
    p_put.add_argument("--space", default="")
    p_put.add_argument("kind")
    p_put.add_argument("id")
    p_put.add_argument("file")
    p_put.set_defaults(fn=cmd_devenv_client)
    p_env.set_defaults(fn=cmd_devenv)

    p_repo = sub.add_parser("repo", help="code repositories")
    repo_sub = p_repo.add_subparsers(dest="repo_cmd", required=True)
    repo_sub.add_parser("init").add_argument("name")
    p_push = repo_sub.add_parser("push")
    p_push.add_argument("name")
    p_push.add_argument("--path", default=".")
    p_repo.set_defaults(fn=cmd_repo)

    p_tj = sub.add_parser("trainjob", help="training jobs")
    tj_sub = p_tj.add_subparsers(dest="trainjob_cmd", required=True)
    p_tpl = tj_sub.add_parser("template")
    p_tpl.add_argument("-s", "--source", default="", help="render template of existing job")
    p_create = tj_sub.add_parser("create")
    p_create.add_argument("-f", "--file", required=True)
    p_create.add_argument("--name", default="")
    p_create.add_argument("--dry-run", action="store_true")
    p_create.add_argument("--bare", action="store_true")
    p_create.add_argument("--no-wait", action="store_true")
    tj_sub.add_parser("list")
    p_logs = tj_sub.add_parser("logs")
    p_logs.add_argument("job_id")
    p_tj.set_defaults(fn=cmd_trainjob)

    p_pool = sub.add_parser("pool", help="TPU pod-slice pools")
    pool_sub = p_pool.add_subparsers(dest="pool_cmd", required=True)
    pool_sub.add_parser("list")
    p_apply = pool_sub.add_parser("apply")
    p_apply.add_argument("name")
    p_apply.add_argument("--accelerator", required=True)
    p_apply.add_argument("--slices", type=int, default=1)
    p_apply.add_argument("--timeout", type=float, default=60.0)
    p_del = pool_sub.add_parser("delete")
    p_del.add_argument("name")
    p_pool.set_defaults(fn=cmd_pool)

    p_asset = sub.add_parser("asset", help="datasets/models/repos")
    asset_sub = p_asset.add_subparsers(dest="asset_cmd", required=True)
    p_al = asset_sub.add_parser("list")
    p_al.add_argument("--kind", default=None)
    p_ai = asset_sub.add_parser("import")
    p_ai.add_argument("--kind", required=True, choices=["dataset", "model", "repository"])
    p_ai.add_argument("--id", required=True)
    p_ai.add_argument("--path", required=True)
    p_asset.set_defaults(fn=cmd_asset)

    p_apply = sub.add_parser("apply", help="apply a YAML manifest (kubectl-style)")
    p_apply.add_argument("-f", "--file", required=True)
    p_apply.add_argument("--no-wait", dest="wait", action="store_false")
    p_apply.add_argument(
        "--validate", action="store_true",
        help="schema-validate the manifest before applying",
    )
    p_apply.set_defaults(fn=cmd_apply, file_cmd="apply")

    p_schema = sub.add_parser(
        "schema", help="export generated CRD schemas (make-manifests analogue)"
    )
    p_schema.add_argument("kind", nargs="?", help="one kind; omit for all")
    p_schema.add_argument("-o", "--out-dir", help="write <Kind>.json files")
    p_schema.set_defaults(fn=cmd_schema)

    p_get = sub.add_parser("get", help="get resources by kind")
    p_get.add_argument("kind")
    p_get.add_argument("name", nargs="?", default="")
    p_get.set_defaults(fn=cmd_apply, file_cmd="get")

    p_del = sub.add_parser("delete", help="delete a resource")
    p_del.add_argument("kind")
    p_del.add_argument("name")
    p_del.set_defaults(fn=cmd_apply, file_cmd="delete")

    p_ci = sub.add_parser("ci", help="CI/CD pipelines and releases")
    ci_sub = p_ci.add_subparsers(dest="ci_cmd", required=True)
    p_run = ci_sub.add_parser("run")
    p_run.add_argument("--repo", required=True)
    ref_group = p_run.add_mutually_exclusive_group()
    ref_group.add_argument("--branch", default="main")
    ref_group.add_argument("--tag", default="")
    p_rel = ci_sub.add_parser("releases")
    p_rel.add_argument("name")
    p_inst = ci_sub.add_parser(
        "install", help="install/upgrade the platform chart (make deploy)"
    )
    p_inst.add_argument("name")
    p_inst.add_argument("--set", action="append",
                        help="chart value key=value (repeatable)")
    p_inst.add_argument("--image", default="",
                        help="operator image ref override")
    p_inst.add_argument("--namespace", default="")
    p_uninst = ci_sub.add_parser("uninstall")
    p_uninst.add_argument("name")
    p_uninst.add_argument("--namespace", default="")
    p_ci.set_defaults(fn=cmd_ci)

    p_obs = sub.add_parser("obs", help="platform logs and metrics")
    obs_sub = p_obs.add_subparsers(dest="obs_cmd", required=True)
    p_ol = obs_sub.add_parser("logs")
    p_ol.add_argument("--tail", type=int, default=50)
    p_ol.add_argument("--contains", default="")
    p_ol.add_argument("-l", "--selector", action="append",
                      help="label filter key=value (repeatable)")
    obs_sub.add_parser("metrics")
    obs_sub.add_parser(
        "resilience",
        help="retry/breaker/shed/fault-injection counters from the last "
             "metrics snapshot",
    )
    p_oa = obs_sub.add_parser(
        "alerts",
        help="pending/firing alerts + transition timeline from the "
             "rules engine (--url) or an instant view of the last "
             "metrics snapshot",
    )
    p_oa.add_argument("--url", default="",
                      help="base URL of a running metrics server "
                           "(/alerts); default: instant evaluation of "
                           "the persisted metrics.prom")
    p_oa.add_argument("--limit", type=int, default=20,
                      help="max transitions to show")
    p_otop = obs_sub.add_parser(
        "top",
        help="fleet-utilization snapshot (KV occupancy, batch fill, "
             "queue depths, pool ready-ratios) from one /metrics scrape "
             "— repeat --url to federate N replicas into per-replica "
             "columns plus a fleet-aggregate column",
    )
    p_otop.add_argument("--url", action="append", default=None,
                        help="base URL of a running metrics server "
                             "(/metrics); repeatable — one column per "
                             "replica; default: the persisted "
                             "metrics.prom")
    p_ofleet = obs_sub.add_parser(
        "fleet",
        help="federated fleet view: per-replica liveness + key gauges "
             "and the per-tenant SLO table, from a /fleet server "
             "(--url) or direct replica scrapes (--scrape-url)",
    )
    p_ofleet.add_argument("--url", default="",
                          help="base URL of a metrics server with a "
                               "fleet collector attached (/fleet)")
    p_ofleet.add_argument("--scrape-url", action="append", default=None,
                          help="NAME=URL (or bare URL) of one replica's "
                               "metrics server; repeatable")
    p_ogw = obs_sub.add_parser(
        "gateways",
        help="gateway-fleet view: per-gateway owner-map digest + "
             "agreement verdict (/admin/ownermap) and the per-tenant "
             "admission quota/WFQ table (/admin/admission); exits 1 "
             "when digests diverge",
    )
    p_ogw.add_argument("--url", action="append", default=None,
                       help="NAME=URL (or bare URL) of one gateway; "
                            "repeatable")
    p_oreq = obs_sub.add_parser(
        "requests",
        help="per-request journal (lifecycle, latency, prefix/spec "
             "evidence, trace cross-link) from /debug/requests",
    )
    p_oreq.add_argument("--url", default="",
                        help="base URL of a metrics server with a "
                             "request journal attached")
    p_oreq.add_argument("--tenant", default="", help="filter by tenant")
    p_oreq.add_argument("--reason", default="",
                        help="filter by finish reason (eos|budget|"
                             "deadline|queue_full|no_capacity|aborted)")
    p_oreq.add_argument("--trace", default="",
                        help="exact trace id filter")
    p_oreq.add_argument("--limit", type=int, default=30)
    p_oreq.add_argument("--since", type=int, default=0,
                        help="completion-index cursor from a previous "
                             "call: only records appended after it")
    p_oreq.add_argument("--no-probes", action="store_true",
                        help="drop synthetic canary-probe records "
                             "(tenant _canary)")
    p_orp = obs_sub.add_parser(
        "replay",
        help="workload flight recorder: capture journals to a "
             ".workload file, re-inject it byte-exact against a live "
             "fleet, diff two runs with segment attribution",
    )
    orp_sub = p_orp.add_subparsers(dest="replay_cmd", required=True)
    p_orpr = orp_sub.add_parser(
        "record",
        help="scrape /debug/requests journals (cursor-delta) into a "
             "deterministic .workload file",
    )
    p_orpr.add_argument("--url", action="append", default=None,
                        help="NAME=URL (or bare URL) of a metrics "
                             "server with a journal attached; "
                             "repeatable")
    p_orpr.add_argument("--out", default="capture.workload",
                        help="output .workload path")
    p_orpr.add_argument("--duration", type=float, default=0.0,
                        help="keep scraping this many seconds "
                             "(default: one pass)")
    p_orpr.add_argument("--poll", type=float, default=1.0,
                        help="scrape interval during --duration")
    p_orpr.add_argument("--probes", action="store_true",
                        help="include synthetic canary-probe records")
    p_orpu = orp_sub.add_parser(
        "run",
        help="re-inject a .workload at recorded (or time-scaled) "
             "arrivals against a /generate endpoint; verifies greedy "
             "golden hashes, exits non-zero on mismatch",
    )
    p_orpu.add_argument("--workload", required=True,
                        help=".workload file from `obs replay record`")
    p_orpu.add_argument("--url", default="",
                        help="replica or gateway base URL (/generate)")
    p_orpu.add_argument("--journal-url", default="",
                        help="metrics server of the target's journal "
                             "(/debug/requests) — enables segment "
                             "attribution in the report")
    p_orpu.add_argument("--time-scale", type=float, default=1.0,
                        help="stretch (>1) / compress (<1) arrival "
                             "gaps; 0 = fire immediately")
    p_orpu.add_argument("--arm-deadlines", action="store_true",
                        help="re-arm recorded latency budgets (off by "
                             "default: byte-exactness first)")
    p_orpu.add_argument("--out", default="",
                        help="write the run report JSON here")
    p_orpd = orp_sub.add_parser(
        "diff",
        help="baseline-vs-candidate diff with waterfall-segment "
             "attribution; regressed segments starred; exits non-zero "
             "on regression or mismatch",
    )
    p_orpd.add_argument("--baseline", required=True,
                        help="run report JSON, or a .workload (the "
                             "recorded timings become the baseline)")
    p_orpd.add_argument("--candidate", required=True,
                        help="run report JSON to compare")
    p_orpd.add_argument("--threshold", type=float, default=0.10,
                        help="relative regression threshold per "
                             "segment (0.10 = +10%%)")
    p_orpd.add_argument("--floor-ms", type=float, default=5.0,
                        help="absolute per-segment delta floor (ms) "
                             "below which jitter never regresses")
    p_orpd.add_argument("--out", default="",
                        help="write the diff report JSON here")
    p_orpd.add_argument("--json", action="store_true",
                        help="print the diff as JSON instead of the "
                             "table")
    p_oprof = obs_sub.add_parser(
        "profile",
        help="continuous performance attribution: per-phase p50/p95/"
             "share for the live batcher/trainer, XLA compile "
             "telemetry, per-axis collective bandwidth (/debug/profile)",
    )
    p_oprof.add_argument("--url", default="",
                         help="base URL of a metrics server with a "
                              "phase profiler attached "
                              "(/debug/profile); default: reconstruct "
                              "from the persisted metrics.prom")
    p_oprof.add_argument("--chrome-trace", default="",
                         help="write a Chrome/Perfetto trace JSON "
                              "(span ring + phase samples) to PATH; "
                              "requires --url")
    p_oprof.add_argument("--limit", type=int, default=200,
                         help="max traces pulled for the chrome export")
    p_ogp = obs_sub.add_parser(
        "goodput",
        help="training goodput ledger: wall-clock attribution by "
             "segment, windowed goodput ratio, checkpoint percentiles, "
             "straggler attribution and the incident flight recorder "
             "(/debug/goodput)",
    )
    p_ogp.add_argument("--url", default="",
                       help="base URL of a metrics server with a "
                            "goodput ledger attached (/debug/goodput); "
                            "default: reconstruct from the persisted "
                            "metrics.prom")
    p_oprb = obs_sub.add_parser(
        "probes",
        help="black-box canary probes: per-replica health FSM state, "
             "K-of-N windows, failure tally by reason, recent "
             "transitions (/debug/probes)",
    )
    p_oprb.add_argument("--url", default="",
                        help="base URL of a metrics server with a "
                             "canary prober attached (/debug/probes)")
    p_oslo = obs_sub.add_parser(
        "slo",
        help="the error-budget plane: per-objective budget remaining "
             "and fast/slow burn plus per-replica probe health, read "
             "off /metrics",
    )
    p_oslo.add_argument("--url", default="",
                        help="base URL of a metrics server; default: "
                             "the persisted metrics.prom")
    p_orte = obs_sub.add_parser(
        "route",
        help="explain a routing decision: which replica the "
             "prefix-affinity router picks for a prompt's token ids, "
             "with every candidate's score",
    )
    p_orte.add_argument("--ids", required=True,
                        help="prompt token ids, comma- or "
                             "space-separated (obs route --ids 1,2,3)")
    p_orte.add_argument("--scrape-url", action="append", default=None,
                        help="NAME=URL of one replica's metrics server; "
                             "repeatable — live load enters the score")
    p_orte.add_argument("--replica", action="append", default=None,
                        help="replica NAME without a metrics endpoint "
                             "(affinity-only routing); repeatable")
    p_orte.add_argument("--page-size", type=int, default=64,
                        help="paged-KV page size the replicas run "
                             "(chain hashes must chunk identically)")
    p_olint = obs_sub.add_parser(
        "lint",
        help="graftcheck: AST invariant linter over the working tree "
             "(determinism planes, metrics contract, lock discipline) "
             "against config/analysis_baseline.json",
    )
    p_olint.add_argument("--json", action="store_true",
                         help="machine-readable report")
    p_olint.add_argument("--root", default="",
                         help="repo root (default: the installed tree)")
    p_ot = obs_sub.add_parser(
        "traces", help="render recorded spans as flame-style trees"
    )
    p_ot.add_argument("--url", default="",
                      help="base URL of a running metrics server "
                           "(/debug/traces); default: boot the local "
                           "platform and read its in-process tracer")
    p_ot.add_argument("--trace", default="", help="exact trace id filter")
    p_ot.add_argument("--name", default="",
                      help="substring filter on any span name")
    p_ot.add_argument("--min-ms", type=float, default=0.0,
                      help="only traces at least this long end-to-end")
    p_ot.add_argument("--limit", type=int, default=20)
    p_owf = obs_sub.add_parser(
        "waterfall",
        help="fleet waterfall: stitched cross-process request traces "
             "with the critical-path segment decomposition (gateway/"
             "retry/network/queue/prefill/decode) off /debug/waterfall",
    )
    p_owf.add_argument("--url", required=True,
                       help="base URL of a metrics server with a "
                            "FleetTraceAssembler attached "
                            "(/debug/waterfall)")
    p_owf.add_argument("--trace", default="",
                       help="exact trace id: render ONE request's full "
                            "waterfall instead of the listing")
    p_owf.add_argument("--chrome-trace", default="",
                       help="write the multi-process Chrome/Perfetto "
                            "trace JSON to PATH; requires --trace")
    p_owf.add_argument("--limit", type=int, default=20)
    p_os = obs_sub.add_parser("serve")
    p_os.add_argument("--port", type=int, default=0)
    p_os.add_argument("--for-seconds", type=float, default=0.0,
                      help="exit after N seconds (0 = until interrupted)")
    p_os.add_argument("--scrape-url", action="append", default=None,
                      help="NAME=URL of a replica /metrics endpoint to "
                           "federate; repeatable — serves /fleet")
    p_obs.set_defaults(fn=cmd_obs)

    p_srv = sub.add_parser(
        "serve", help="serve a model asset over HTTP (LM server)"
    )
    p_srv.add_argument("model", help="model asset id in the current space")
    p_srv.add_argument("--version", default="", help="'' = latest")
    p_srv.add_argument("--port", type=int, default=0)
    p_srv.add_argument("--slots", type=int, default=4,
                       help="concurrent decode slots")
    p_srv.add_argument("--constraint", action="append", metavar="NAME=REGEX",
                       help="named decoding constraint (repeatable); "
                            "requests opt in with {'constraint': NAME}")
    p_srv.add_argument("--json-constraint", action="append",
                       metavar="NAME=SCHEMA.json",
                       help="named JSON-schema constraint (repeatable): "
                            "the schema file compiles to a canonical-JSON "
                            "regex; requests opt in with "
                            "{'constraint': NAME}")
    p_srv.add_argument("--eos-id", type=int, default=-1,
                       help="EOS token id (set when using constraints)")
    p_srv.add_argument("--draft", default="",
                       help="speculative decoding in the batcher's shared "
                            "rounds: a draft model asset id (always treated "
                            "as an asset id — use --draft-mode for "
                            "model-free drafting)")
    p_srv.add_argument("--draft-mode", default="", choices=["", "ngram"],
                       help="model-free drafting mode, mirroring "
                            "spec.draftMode: 'ngram' = prompt-lookup "
                            "proposals from each row's own history; "
                            "mutually exclusive with --draft")
    p_srv.add_argument("--kv-quant", action="store_true",
                       help="int8 KV cache (~1.9x slot capacity)")
    p_srv.add_argument("--paged-blocks", type=int, default=0,
                       help="paged KV pool: N physical blocks of 64 "
                            "positions shared by all slots (cache bytes "
                            "scale with used tokens); 0 = dense pool")
    p_srv.add_argument("--for-seconds", type=float, default=0.0,
                       help="exit after N seconds (0 = until interrupted)")
    p_srv.set_defaults(fn=cmd_serve)

    p_fe = sub.add_parser(
        "frontend",
        help="fleet HTTP gateway: prefix-affinity routing, retry/rehash "
             "on replica failure, in-flight-aware drain",
    )
    p_fe.add_argument("model",
                      help="model asset id whose bundled tokenizer the "
                           "gateway uses for chain hashing (no params "
                           "are loaded)")
    p_fe.add_argument("--version", default="", help="'' = latest")
    p_fe.add_argument("--port", type=int, default=0)
    p_fe.add_argument("--page-size", type=int, default=64,
                      help="chain-hash page size; MUST match the "
                           "replicas' paged page size or affinity "
                           "routing degrades to load-only")
    p_fe.add_argument("--replica", action="append", metavar="NAME=URL",
                      help="replica to register at boot (repeatable); "
                           "more join later via POST /admin/replicas")
    p_fe.add_argument("--for-seconds", type=float, default=0.0,
                      help="exit after N seconds (0 = until interrupted)")
    p_fe.set_defaults(fn=cmd_frontend)

    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except SystemExit as e:  # _require_login short-circuit
        return int(e.code or 0)


if __name__ == "__main__":
    sys.exit(main())
