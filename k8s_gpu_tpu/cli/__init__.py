from .config import CliConfig, Context
from .platform_local import LocalPlatform

__all__ = ["CliConfig", "Context", "LocalPlatform"]
