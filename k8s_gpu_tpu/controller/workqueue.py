"""Rate-limited, deduplicating, delayed work queue.

The concurrency heart of the controller runtime (SURVEY §7 hard part 2:
"watch/requeue correctness — coalescing, idempotency under concurrent
events").  Semantics match controller-runtime's workqueue:

- **Dedup/coalesce**: a key add()ed while already queued (or due later) is
  collapsed; a key add()ed while *being processed* is marked dirty and
  re-queued when ``done()`` is called, so no event is ever lost and no key
  runs concurrently with itself.
- **Delayed adds**: ``add_after(key, d)`` schedules; an earlier deadline
  wins over a later one.
- **Rate-limited adds**: per-key exponential backoff for error retries.
- **Clock-driven**: blocking ``get()`` waits on the Clock abstraction, so
  FakeClock tests replay minutes of requeue cadence instantly.
"""

from __future__ import annotations

import heapq
import itertools
import threading

from ..utils.clock import Clock, RealClock
from ..utils.faults import global_faults
from ..utils.tracing import global_tracer


class ShutDown(Exception):
    pass


class RateLimitingQueue:
    def __init__(
        self,
        clock: Clock | None = None,
        base_delay: float = 0.005,
        max_delay: float = 1000.0,
        name: str | None = None,
        registry=None,
    ):
        self.clock = clock or RealClock()
        self.base_delay = base_delay
        self.max_delay = max_delay
        # Fleet telemetry (ISSUE 4): a NAMED queue exports
        # workqueue_depth{name} and workqueue_oldest_age_seconds{name} —
        # the per-queue backlog gauges the QueueBacklog alert evaluates.
        # Unnamed queues (direct embedders, tests) export nothing.
        self.name = name
        if registry is None and name is not None:
            from ..utils.metrics import global_metrics as registry
        self.registry = registry
        self._cond = threading.Condition()
        self._heap: list = []  # (ready_time, seq, key)
        self._seq = itertools.count()
        self._queued: dict = {}  # key -> ready_time currently scheduled
        self._processing: set = set()
        self._dirty: set = set()  # re-add requested while processing
        self._failures: dict = {}
        # key → (SpanContext, enqueue clock time): the originating trace
        # rides with the queued key so the consumer can attribute queue
        # wait as a span.  Entries exist only while a key is queued/dirty
        # AND only when the producer had an active trace — untraced adds
        # cost one thread-local read, nothing more.  On coalesce the
        # FIRST context wins (its enqueue time is the true wait start).
        self._trace: dict = {}
        # key → entry moved aside by get() until the consumer collects it
        # via pop_trace() (or done() discards it).
        self._popped_trace: dict = {}
        self._shutdown = False

    # -- producers ---------------------------------------------------------
    def add(self, key) -> None:
        self.add_after(key, 0.0)

    def add_after(self, key, delay: float) -> None:
        # Chaos site: a "slow" plan models delayed watch delivery / a
        # congested informer.  The returned delay folds into the entry's
        # deadline (never a sleep — producers are watch handlers), and
        # only slow is honored: an injected *error* here would lose an
        # event, which no real fault mode does (at-least-once delivery is
        # the queue's contract).
        delay = max(0.0, delay) + global_faults.fire(
            "workqueue.add", only=("slow",)
        )
        ready = self.clock.now() + delay
        ctx = global_tracer.current()
        with self._cond:
            if self._shutdown:
                return
            if ctx is not None and key not in self._trace:
                self._trace[key] = (ctx, self.clock.now())
            if key in self._processing:
                self._dirty.add(key)
                return
            cur = self._queued.get(key)
            if cur is not None and cur <= ready:
                return  # already due sooner — coalesce
            self._queued[key] = ready
            heapq.heappush(self._heap, (ready, next(self._seq), key))
            self._cond.notify_all()

    def add_rate_limited(self, key) -> None:
        with self._cond:
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
        delay = min(self.base_delay * (2 ** min(n, 30)), self.max_delay)
        self.add_after(key, delay)

    def forget(self, key) -> None:
        with self._cond:
            self._failures.pop(key, None)

    def num_requeues(self, key) -> int:
        with self._cond:
            return self._failures.get(key, 0)

    # -- consumers ---------------------------------------------------------
    def get(self, block: bool = True):
        """Pop the next due key (marking it processing); raises ShutDown."""
        with self._cond:
            while True:
                if self._shutdown:
                    raise ShutDown
                now = self.clock.now()
                # Drop stale heap entries (coalesced keys).
                while self._heap and (
                    self._heap[0][2] not in self._queued
                    or self._queued[self._heap[0][2]] != self._heap[0][0]
                ):
                    heapq.heappop(self._heap)
                if self._heap and self._heap[0][0] <= now:
                    _, _, key = heapq.heappop(self._heap)
                    del self._queued[key]
                    self._processing.add(key)
                    entry = self._trace.pop(key, None)
                    if entry is not None:
                        self._popped_trace[key] = entry
                    return key
                if not block:
                    return None
                if self._heap:
                    timeout = self._heap[0][0] - now
                    self.clock.wait(self._cond, timeout)
                else:
                    self.clock.wait(self._cond, None)

    def pop_trace(self, key):
        """Collect the (SpanContext, enqueue_time) that rode with *key*
        through the queue — valid between ``get(key)`` and ``done(key)``;
        None when the producer was untraced.  The enqueue time is in the
        queue's Clock domain and includes any scheduled ``add_after``
        delay: for requeues the "wait" span IS the retry/poll cadence,
        which is exactly the attribution the 0→Ready story needs."""
        with self._cond:
            entry = self._popped_trace.pop(key, None)
        return entry

    def done(self, key) -> None:
        with self._cond:
            self._processing.discard(key)
            self._popped_trace.pop(key, None)
            if key in self._dirty:
                self._dirty.discard(key)
                ready = self.clock.now()
                self._queued[key] = ready
                heapq.heappush(self._heap, (ready, next(self._seq), key))
                self._cond.notify_all()

    # -- introspection -----------------------------------------------------
    def export_gauges(self) -> None:
        """Refresh the depth/age gauges for a named queue NOW — called
        by the rule evaluator's collector before each tick and by the
        manager on shutdown (before the metrics snapshot persists), NOT
        on the add/get/done hot path: the due-now scan is O(queued) and
        would make a watch-burst drain quadratic under the condition
        lock.  Only keys DUE NOW count: items parked on a future
        ``add_after`` deadline (steady-state resyncs, retry rungs) are
        scheduled work, not backlog — counting them would make the
        QueueBacklog alert fire forever on a healthy idle fleet.  Age is
        the oldest due key's wait SINCE its deadline (now - ready_time);
        for immediate adds that IS time-since-enqueue.  Lock order is
        queue-cond → registry-lock, and the registry never calls back
        into the queue, so this cannot deadlock."""
        if self.registry is None or self.name is None:
            return
        with self._cond:
            now = self.clock.now()
            due = [t for t in self._queued.values() if t <= now]
            self.registry.set_gauge(
                "workqueue_depth", float(len(due)), queue=self.name
            )
            age = (now - min(due)) if due else 0.0
            self.registry.set_gauge(
                "workqueue_oldest_age_seconds", age, queue=self.name
            )

    def empty(self) -> bool:
        with self._cond:
            return not self._queued and not self._processing and not self._dirty

    def idle_no_backlog(self) -> bool:
        """True when nothing is processing and nothing is due now (pending
        future requeues are allowed) — the test-harness quiescence check."""
        with self._cond:
            if self._processing or self._dirty:
                return False
            now = self.clock.now()
            return all(t > now for t in self._queued.values())

    def next_deadline(self) -> float | None:
        with self._cond:
            return min(self._queued.values()) if self._queued else None

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
