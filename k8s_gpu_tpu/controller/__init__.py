from .kubefake import FakeKube, WatchEvent, Conflict, NotFound
from .workqueue import RateLimitingQueue
from .manager import Manager, Reconciler, Request, Result
from .events import EventRecorder
from .alerting import AlertEventNotifier

__all__ = [
    "FakeKube",
    "WatchEvent",
    "Conflict",
    "NotFound",
    "RateLimitingQueue",
    "Manager",
    "Reconciler",
    "Request",
    "Result",
    "EventRecorder",
    "AlertEventNotifier",
]
