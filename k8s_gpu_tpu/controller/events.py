"""Event recorder — K8s Events on resource create/delete (the hardening item
the reference lists at README.md:311)."""

from __future__ import annotations

import uuid

from ..api.core import Event
from ..api.types import CustomResource
from .kubefake import FakeKube


class EventRecorder:
    def __init__(self, kube: FakeKube, component: str):
        self.kube = kube
        self.component = component

    def event(
        self, obj: CustomResource, etype: str, reason: str, message: str
    ) -> None:
        ev = Event(
            involved_kind=obj.kind,
            involved_name=obj.metadata.name,
            involved_namespace=obj.metadata.namespace,
            type=etype,
            reason=reason,
            message=message,
        )
        ev.metadata.name = f"{obj.metadata.name}.{uuid.uuid4().hex[:10]}"
        ev.metadata.namespace = obj.metadata.namespace
        ev.metadata.labels["component"] = self.component
        self.kube.create(ev)

    def events_for(self, obj: CustomResource) -> list[Event]:
        return [
            e
            for e in self.kube.list("Event", namespace=obj.metadata.namespace)
            if e.involved_kind == obj.kind and e.involved_name == obj.metadata.name
        ]
