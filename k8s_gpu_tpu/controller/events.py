"""Event recorder — K8s Events on resource create/delete (the hardening item
the reference lists at README.md:311).  Every event is also a structured
log line (the reference's log-every-reconcile-step contract,
README.md:171-232), so the log pipeline (utils/logstore.py, `obs logs`)
carries the same operator-activity stream `kubectl describe` would show."""

from __future__ import annotations

import logging
import uuid

from ..api.core import Event
from ..api.types import CustomResource
from ..utils.tracing import global_tracer
from .kubefake import FakeKube

log = logging.getLogger("k8s_gpu_tpu.controller.events")


class EventRecorder:
    def __init__(self, kube: FakeKube, component: str):
        self.kube = kube
        self.component = component

    def event(
        self, obj: CustomResource, etype: str, reason: str, message: str
    ) -> None:
        (log.warning if etype == "Warning" else log.info)(
            "%s %s/%s %s: %s", obj.kind, obj.metadata.namespace,
            obj.metadata.name, reason, message,
        )
        ev = Event(
            involved_kind=obj.kind,
            involved_name=obj.metadata.name,
            involved_namespace=obj.metadata.namespace,
            type=etype,
            reason=reason,
            message=message,
        )
        ev.metadata.name = f"{obj.metadata.name}.{uuid.uuid4().hex[:10]}"
        ev.metadata.namespace = obj.metadata.namespace
        ev.metadata.labels["component"] = self.component
        # Stamp the active trace so `kubectl describe`-style output links
        # straight back to the reconcile pass that emitted the event.
        ctx = global_tracer.current()
        if ctx is not None:
            ev.metadata.labels["trace-id"] = ctx.trace_id
        self.kube.create(ev)

    def events_for(self, obj: CustomResource) -> list[Event]:
        return [
            e
            for e in self.kube.list("Event", namespace=obj.metadata.namespace)
            if e.involved_kind == obj.kind and e.involved_name == obj.metadata.name
        ]
