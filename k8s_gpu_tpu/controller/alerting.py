"""Alert transitions → K8s Warning Events on the affected objects.

The rules engine (utils/alerts.py) is deliberately kube-free; this is the
controller-plane adapter that gives alerts the `kubectl describe`
surface: when a rule whose labels name an object (``kind`` plus
``pool``/``name``) transitions to firing, the object gets a Warning
Event with the alert name as reason — resolved transitions get a Normal
Event.  Alerts without an object reference (QueueBacklog, burn rate) are
logged only; the ``/alerts`` endpoint and timeline remain their surface.
"""

from __future__ import annotations

import logging

from .events import EventRecorder
from .kubefake import FakeKube

log = logging.getLogger("k8s_gpu_tpu.controller.alerting")


class AlertEventNotifier:
    """``notify`` hook for ``RuleEvaluator``: maps alert labels to a
    cluster object and records an Event on it."""

    def __init__(self, kube: FakeKube, component: str = "alerts-engine"):
        self.kube = kube
        self.recorder = EventRecorder(kube, component)

    def __call__(self, rule, labels: dict, transition: str,
                 value: float) -> None:
        obj = self._resolve(labels)
        message = (
            f"{transition}: {rule.annotate(tuple(sorted(labels.items())), value) or rule.name} "
            f"(value={value:.4g}, severity={rule.severity})"
        )
        if obj is None:
            log.warning("alert %s %s %s: no object to attach (labels=%s)",
                        rule.name, transition, message, labels)
            return
        self.recorder.event(
            obj,
            "Warning" if transition == "firing" else "Normal",
            rule.name,
            message,
        )

    def _resolve(self, labels: dict):
        kind = labels.get("kind")
        name = labels.get("pool") or labels.get("name")
        if not kind or not name:
            return None
        try:
            # Namespace-scoped when the series carries one: two
            # same-named pools in different namespaces must not receive
            # each other's alert Events.
            objs = self.kube.list(kind, namespace=labels.get("namespace"))
        except Exception:
            return None
        for o in objs:
            if o.metadata.name == name:
                return o
        return None
