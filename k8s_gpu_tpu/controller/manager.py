"""Controller manager: watches → work queues → reconciler workers.

The controller-runtime role in the reference's stack (reference
README.md:162-236): each registered controller watches its kind, enqueues
(namespace, name) keys, and worker threads invoke ``Reconciler.reconcile``
with level-triggered semantics.  Results carry ``requeue_after`` — the
reference's retry ladder (30 s auth / 20 s list / 40 s mutate errors,
60 s steady-state resync; README.md:184,192,207,219,233-234) maps directly
onto it.  Unhandled exceptions get per-key exponential backoff.

``wait_idle`` gives tests (and bench.py) a deterministic quiescence point:
all queues drained to "nothing due before the next scheduled resync".
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from .kubefake import FakeKube, WatchEvent
from .workqueue import RateLimitingQueue, ShutDown
from ..utils.clock import Clock, RealClock
from ..utils.faults import global_faults
from ..utils.metrics import MetricsRegistry, global_metrics
from ..utils.tracing import global_tracer

log = logging.getLogger("k8s_gpu_tpu.controller")


@dataclass(frozen=True)
class Request:
    namespace: str
    name: str


@dataclass
class Result:
    requeue_after: float | None = None
    requeue: bool = False


class Reconciler:
    """Protocol: subclasses implement reconcile(request) -> Result."""

    def reconcile(self, req: Request) -> Result:  # pragma: no cover - interface
        raise NotImplementedError


@dataclass
class _Controller:
    name: str
    kind: str
    reconciler: Reconciler
    queue: RateLimitingQueue
    workers: int = 1
    threads: list = field(default_factory=list)


class Manager:
    def __init__(
        self,
        kube: FakeKube,
        clock: Clock | None = None,
        metrics: MetricsRegistry | None = None,
        alerts=None,
    ):
        """``alerts``: a ``utils.alerts.RuleEvaluator`` the manager owns —
        its tick loop starts/stops with the manager, and a collector is
        registered that refreshes every controller queue's depth/age
        gauges before each evaluation (oldest-item age grows with the
        clock, so event-driven updates alone would go stale).  Construct
        it with the SAME clock as the manager so alert hold durations and
        requeue cadence live in one time domain."""
        self.kube = kube
        self.clock = clock or RealClock()
        self.metrics = metrics or global_metrics
        self.alerts = alerts
        if alerts is not None:
            alerts.collectors.append(self._collect_queue_gauges)
        self._controllers: dict[str, _Controller] = {}
        self._started = False
        self._stop = threading.Event()

    def _collect_queue_gauges(self) -> None:
        for ctl in self._controllers.values():
            ctl.queue.export_gauges()

    def register(
        self,
        kind: str,
        reconciler: Reconciler,
        workers: int = 1,
        name: str | None = None,
    ) -> None:
        """Register a controller watching *kind*.  ``name`` distinguishes
        multiple controllers on the same kind (e.g. the TrainJob reconciler
        and the autoscaler both watch TrainJob)."""
        if self._started:
            raise RuntimeError("register before start()")
        name = name or kind
        if name in self._controllers:
            raise ValueError(f"controller {name!r} already registered")
        q = RateLimitingQueue(
            clock=self.clock, name=name, registry=self.metrics
        )
        self._controllers[name] = _Controller(name, kind, reconciler, q, workers)

    def start(self) -> None:
        self._started = True
        for ctl in self._controllers.values():
            # Watch feeds the queue.  A generation-changed predicate filters
            # status-only MODIFIED events (which our own status writes
            # produce) so reconciles are driven by *meaningful* changes —
            # controller-runtime's GenerationChangedPredicate; without it
            # every status write would immediately re-trigger reconcile and
            # defeat the retry ladder's timing.
            def make_handler(queue: RateLimitingQueue):
                seen: dict[Request, tuple] = {}

                def signature(ev: WatchEvent) -> tuple:
                    # Generation (spec) + deletionTimestamp only — finalizer,
                    # label and status writes (our own included) don't
                    # re-trigger; the periodic resync covers everything else.
                    m = ev.obj.metadata
                    return (m.generation, m.deletion_timestamp)

                def handle(ev: WatchEvent) -> None:
                    req = Request(ev.obj.metadata.namespace, ev.obj.metadata.name)
                    if ev.type == "DELETED":
                        seen.pop(req, None)
                        queue.add(req)
                        return
                    sig = signature(ev)
                    if ev.type == "MODIFIED" and seen.get(req) == sig:
                        return  # status-only change; skip
                    seen[req] = sig
                    queue.add(req)

                return handle

            self.kube.watch(ctl.kind, make_handler(ctl.queue))
            for i in range(ctl.workers):
                t = threading.Thread(
                    target=self._worker, args=(ctl,), name=f"{ctl.kind}-worker-{i}",
                    daemon=True,
                )
                ctl.threads.append(t)
                t.start()
        if self.alerts is not None:
            self.alerts.start()

    def _worker(self, ctl: _Controller) -> None:
        while not self._stop.is_set():
            try:
                req = ctl.queue.get()
            except ShutDown:
                return
            # Trace plumbing: a context that rode in with the key (an
            # apiserver create, or a previous reconcile's requeue) parents
            # this pass — the queue wait becomes a span, the reconcile a
            # child, and the requeue below re-attaches the SAME root so
            # one object's whole 0→Ready lifecycle assembles as one
            # trace.  An untraced key roots a fresh trace at its first
            # reconcile and propagates from there.
            entry = ctl.queue.pop_trace(req)
            parent = entry[0] if entry else None
            if parent is not None:
                # The wait DURATION is measured in the queue's Clock
                # domain (FakeClock replays minutes instantly), but the
                # span is anchored in the tracer's monotonic domain so it
                # assembles consistently with every other span — mixing
                # domains made trace durations nonsense under FakeClock.
                wait_s = max(0.0, self.clock.now() - entry[1])
                now = time.monotonic()  # graftcheck: ignore[det-wallclock]
                global_tracer.add_span(
                    "queue.wait", parent=parent,
                    start=now - wait_s, end=now,
                    kind=ctl.kind, controller=ctl.name,
                )
            # Real-duration measurement of the pass itself (the graded
            # baseline metric's source) — intentionally wall-clock.
            t0 = time.perf_counter()  # graftcheck: ignore[det-wallclock]
            rctx = None
            try:
                # Chaos site: an injected error here is an unhandled
                # reconcile exception — the per-key rate-limited backoff
                # path, exactly what a panicking reconciler produces.
                # The clock makes "slow" plans real (a stalled pass),
                # deterministic under FakeClock.
                global_faults.fire(f"reconcile.{ctl.kind}", clock=self.clock)
                with global_tracer.span(
                    "reconcile", parent=parent, kind=ctl.kind,
                    controller=ctl.name, namespace=req.namespace,
                    name=req.name,
                ) as sp:
                    rctx = sp.context
                    res = ctl.reconciler.reconcile(req) or Result()
                    if res.requeue_after is not None:
                        sp.attributes["requeue_after"] = res.requeue_after
                with global_tracer.use(parent or rctx):
                    ctl.queue.forget(req)
                    ctl.queue.done(req)
                    if res.requeue_after is not None:
                        ctl.queue.add_after(req, res.requeue_after)
                    elif res.requeue:
                        ctl.queue.add(req)
                self.metrics.inc("reconcile_total", kind=ctl.kind, result="ok")
            except Exception:
                log.exception("reconcile %s %s failed", ctl.kind, req)
                with global_tracer.use(parent or rctx):
                    ctl.queue.done(req)
                    ctl.queue.add_rate_limited(req)
                self.metrics.inc("reconcile_total", kind=ctl.kind, result="error")
            finally:
                self.metrics.observe(
                    "reconcile_duration_seconds",
                    time.perf_counter() - t0,  # graftcheck: ignore[det-wallclock]
                    kind=ctl.kind,
                )

    def stop(self) -> None:
        self._stop.set()
        if self.alerts is not None:
            self.alerts.stop()
        # Final gauge refresh so a metrics snapshot persisted after stop
        # (platform_local) carries current queue depths — live freshness
        # comes from the evaluator's collector, not the queue hot path.
        self._collect_queue_gauges()
        for ctl in self._controllers.values():
            ctl.queue.shutdown()
        for ctl in self._controllers.values():
            for t in ctl.threads:
                t.join(timeout=2)

    # -- test/bench helpers ------------------------------------------------
    def wait_idle(
        self,
        timeout: float = 30.0,
        min_future_delay: float = 1.0,
        predicate=None,
    ) -> bool:
        """Block (real time) until every queue is quiescent: nothing
        processing and nothing scheduled within *min_future_delay* clock
        seconds — i.e. only periodic resyncs remain.  Optionally also until
        *predicate()* is true.  Returns False on timeout."""
        deadline = time.monotonic() + timeout  # graftcheck: ignore[det-wallclock]
        while time.monotonic() < deadline:  # graftcheck: ignore[det-wallclock]
            quiet = all(
                c.queue.idle_no_backlog()
                and (
                    (d := c.queue.next_deadline()) is None
                    or d - self.clock.now() >= min_future_delay
                )
                for c in self._controllers.values()
            )
            if quiet and (predicate is None or predicate()):
                return True
            time.sleep(0.002)
        return False
